"""Deterministic fault injection for the robustness test suite.

Every fault-tolerance behavior in the runtime — NaN-step skip (executor
anomaly guard), checkpoint CRC fallback, reader retry-then-degrade,
preemption-safe Trainer shutdown — is TESTED through this harness rather
than asserted in prose. All randomness flows from one seeded RandomState,
so a failing fault drill reproduces bit-for-bit from its seed.

The injectors deliberately operate at the host boundary (file bytes,
Python callables, OS signals, feed batches): the compiled XLA step stays
byte-identical with and without the harness, so the tests exercise the
SAME code paths production hits.
"""
import os
import signal

import numpy as np

__all__ = ['FaultInjector', 'send_preemption']


def send_preemption(sig=signal.SIGTERM, pid=None):
    """Deliver a preemption signal to this process (default SIGTERM — what
    a TPU-VM maintenance event or k8s eviction sends). The Trainer's
    preemption handler finishes the in-flight step, flushes an emergency
    checkpoint, and returns from train() cleanly."""
    os.kill(os.getpid() if pid is None else pid, sig)


class FaultInjector(object):
    """Seeded source of faults. One instance per test; every choice
    (which byte to flip, which call to fail, where to poison) derives from
    `seed`, so drills are reproducible."""

    def __init__(self, seed=0):
        self.seed = int(seed)
        self.rng = np.random.RandomState(self.seed)

    # -- callable faults ---------------------------------------------------

    def flaky(self, fn, fail_times=1, exc_factory=None):
        """Wrap fn to raise on its first `fail_times` calls, then succeed.
        Models transient I/O: the retry layer should absorb exactly
        `fail_times` failures."""
        if exc_factory is None:
            exc_factory = lambda i: IOError('injected transient failure #%d'
                                            % (i + 1))
        state = {'calls': 0}

        def wrapper(*args, **kwargs):
            i = state['calls']
            state['calls'] += 1
            if i < fail_times:
                raise exc_factory(i)
            return fn(*args, **kwargs)

        wrapper.calls = lambda: state['calls']
        return wrapper

    def flaky_reader(self, reader, fail_at, fail_times=1, exc_factory=None):
        """Decorate a paddle-style reader creator: each of the first
        `fail_times` iterations raises just before yielding sample index
        `fail_at`. With paddle_tpu.reader.fault_tolerant around it, the
        stream should heal without duplicating or dropping samples (until
        retries are exhausted, when it degrades to skip-with-warning)."""
        if exc_factory is None:
            exc_factory = lambda i: IOError('injected reader failure #%d'
                                            % (i + 1))
        state = {'iters': 0}

        def creator():
            it = state['iters']
            state['iters'] += 1
            def gen():
                for i, sample in enumerate(reader()):
                    if it < fail_times and i == fail_at:
                        raise exc_factory(it)
                    yield sample
            return gen()

        return creator

    # -- numeric faults ----------------------------------------------------

    def poison_nan(self, batch, rate=1.0):
        """Return a copy of a feed batch (ndarray, or nested list/tuple/
        dict of ndarrays) with a seeded fraction of float entries replaced
        by NaN — the canonical way to force an unhealthy training step
        through the REAL compiled path (the NaN propagates into loss and
        gradients; the anomaly guard must skip the step)."""
        if isinstance(batch, dict):
            return {k: self.poison_nan(v, rate) for k, v in batch.items()}
        if isinstance(batch, (list, tuple)):
            return type(batch)(self.poison_nan(v, rate) for v in batch)
        arr = np.array(batch, copy=True)
        if not np.issubdtype(arr.dtype, np.floating):
            return arr
        mask = self.rng.rand(*arr.shape) < rate if arr.shape else \
            np.asarray(self.rng.rand() < rate)
        flat = arr.reshape(-1)
        flat[np.asarray(mask).reshape(-1)] = np.nan
        return flat.reshape(arr.shape)

    # -- file faults -------------------------------------------------------

    def truncate_file(self, path, keep_fraction=None, keep_bytes=None):
        """Truncate a file in place (a torn write / crashed writer). By
        default keeps a seeded fraction in [0.25, 0.75) of the bytes."""
        size = os.path.getsize(path)
        if keep_bytes is None:
            frac = (0.25 + 0.5 * self.rng.rand()) if keep_fraction is None \
                else keep_fraction
            keep_bytes = int(size * frac)
        keep_bytes = max(0, min(size - 1, keep_bytes))
        with open(path, 'r+b') as f:
            f.truncate(keep_bytes)
        return keep_bytes

    def corrupt_file(self, path, n_bytes=4):
        """Flip `n_bytes` seeded bytes in place WITHOUT changing the file
        size — the case only a content checksum (manifest CRC32) catches;
        a size check alone passes."""
        size = os.path.getsize(path)
        offsets = self.rng.randint(0, size, size=n_bytes)
        with open(path, 'r+b') as f:
            for off in offsets:
                f.seek(int(off))
                b = f.read(1)
                f.seek(int(off))
                f.write(bytes([b[0] ^ 0xFF]))
        return sorted(int(o) for o in offsets)

    def pick_file(self, directory, suffix='.npy'):
        """Seeded choice of one file (sorted listing, so the same seed
        picks the same shard on every run)."""
        names = sorted(n for n in os.listdir(directory)
                       if n.endswith(suffix))
        if not names:
            raise ValueError('no %r files under %r' % (suffix, directory))
        return os.path.join(directory, names[self.rng.randint(len(names))])

    # -- checkpoint faults -------------------------------------------------

    def torn_checkpoint(self, ckpt_dir, what=None):
        """Tear a sharded checkpoint dir the way a crash mid-save (or
        bit rot after it) would, for the elastic drills:

          'drop_manifest'     — delete manifest.json (+ its .sum): the
                                serial can never verify;
          'truncate_manifest' — cut the manifest short (a torn write the
                                .sum sidecar exposes as a typed failure);
          'corrupt_manifest'  — same-size bit rot in the manifest (only
                                the sidecar CRC catches it);
          'drop_shard'        — delete one seeded shard file;
          'truncate_shard'    — truncate one seeded shard file.

        Default: a seeded choice among all five. Returns (what, path)."""
        modes = ('drop_manifest', 'truncate_manifest', 'corrupt_manifest',
                 'drop_shard', 'truncate_shard')
        if what is None:
            what = modes[self.rng.randint(len(modes))]
        if what not in modes:
            raise ValueError('unknown torn_checkpoint mode %r (one of %s)'
                             % (what, modes))
        if what.endswith('_manifest'):
            path = os.path.join(ckpt_dir, 'manifest.json')
            if what == 'drop_manifest':
                os.remove(path)
                for side in (path + '.sum',):
                    if os.path.exists(side):
                        os.remove(side)
            elif what == 'truncate_manifest':
                self.truncate_file(path)
            else:
                self.corrupt_file(path)
            return what, path
        path = self.pick_file(ckpt_dir, suffix='.npy')
        if what == 'drop_shard':
            os.remove(path)
        else:
            self.truncate_file(path)
        return what, path

    # -- process faults ----------------------------------------------------

    def preempt(self, sig=signal.SIGTERM):
        """Simulated preemption of THIS process (see send_preemption)."""
        send_preemption(sig)

    def kill_process(self, proc, sig=signal.SIGKILL):
        """SIGKILL a child process mid-step — the host-failure fault: no
        handlers run, no flush happens, beats stop. `proc` is a
        subprocess.Popen (or anything with .pid) or a raw pid. Returns
        the pid killed."""
        pid = int(getattr(proc, 'pid', proc))
        if pid == os.getpid():
            raise ValueError(
                'kill_process targets a CHILD (SIGKILL to self would '
                'take the test runner down); use preempt() for '
                'self-delivered signals')
        os.kill(pid, sig)
        return pid
