"""Deterministic fault injection for the robustness test suite.

Every fault-tolerance behavior in the runtime — NaN-step skip (executor
anomaly guard), checkpoint CRC fallback, reader retry-then-degrade,
preemption-safe Trainer shutdown — is TESTED through this harness rather
than asserted in prose. All randomness flows from one seeded RandomState,
so a failing fault drill reproduces bit-for-bit from its seed.

The injectors deliberately operate at the host boundary (file bytes,
Python callables, OS signals, feed batches): the compiled XLA step stays
byte-identical with and without the harness, so the tests exercise the
SAME code paths production hits.
"""
import os
import signal

import numpy as np

__all__ = ['FaultInjector', 'send_preemption']


def send_preemption(sig=signal.SIGTERM, pid=None):
    """Deliver a preemption signal to this process (default SIGTERM — what
    a TPU-VM maintenance event or k8s eviction sends). The Trainer's
    preemption handler finishes the in-flight step, flushes an emergency
    checkpoint, and returns from train() cleanly."""
    os.kill(os.getpid() if pid is None else pid, sig)


class FaultInjector(object):
    """Seeded source of faults. One instance per test; every choice
    (which byte to flip, which call to fail, where to poison) derives from
    `seed`, so drills are reproducible."""

    def __init__(self, seed=0):
        self.seed = int(seed)
        self.rng = np.random.RandomState(self.seed)

    # -- callable faults ---------------------------------------------------

    def flaky(self, fn, fail_times=1, exc_factory=None):
        """Wrap fn to raise on its first `fail_times` calls, then succeed.
        Models transient I/O: the retry layer should absorb exactly
        `fail_times` failures."""
        if exc_factory is None:
            exc_factory = lambda i: IOError('injected transient failure #%d'
                                            % (i + 1))
        state = {'calls': 0}

        def wrapper(*args, **kwargs):
            i = state['calls']
            state['calls'] += 1
            if i < fail_times:
                raise exc_factory(i)
            return fn(*args, **kwargs)

        wrapper.calls = lambda: state['calls']
        return wrapper

    def flaky_reader(self, reader, fail_at, fail_times=1, exc_factory=None):
        """Decorate a paddle-style reader creator: each of the first
        `fail_times` iterations raises just before yielding sample index
        `fail_at`. With paddle_tpu.reader.fault_tolerant around it, the
        stream should heal without duplicating or dropping samples (until
        retries are exhausted, when it degrades to skip-with-warning)."""
        if exc_factory is None:
            exc_factory = lambda i: IOError('injected reader failure #%d'
                                            % (i + 1))
        state = {'iters': 0}

        def creator():
            it = state['iters']
            state['iters'] += 1
            def gen():
                for i, sample in enumerate(reader()):
                    if it < fail_times and i == fail_at:
                        raise exc_factory(it)
                    yield sample
            return gen()

        return creator

    # -- numeric faults ----------------------------------------------------

    def poison_nan(self, batch, rate=1.0):
        """Return a copy of a feed batch (ndarray, or nested list/tuple/
        dict of ndarrays) with a seeded fraction of float entries replaced
        by NaN — the canonical way to force an unhealthy training step
        through the REAL compiled path (the NaN propagates into loss and
        gradients; the anomaly guard must skip the step)."""
        if isinstance(batch, dict):
            return {k: self.poison_nan(v, rate) for k, v in batch.items()}
        if isinstance(batch, (list, tuple)):
            return type(batch)(self.poison_nan(v, rate) for v in batch)
        arr = np.array(batch, copy=True)
        if not np.issubdtype(arr.dtype, np.floating):
            return arr
        mask = self.rng.rand(*arr.shape) < rate if arr.shape else \
            np.asarray(self.rng.rand() < rate)
        flat = arr.reshape(-1)
        flat[np.asarray(mask).reshape(-1)] = np.nan
        return flat.reshape(arr.shape)

    # -- file faults -------------------------------------------------------

    def truncate_file(self, path, keep_fraction=None, keep_bytes=None):
        """Truncate a file in place (a torn write / crashed writer). By
        default keeps a seeded fraction in [0.25, 0.75) of the bytes."""
        size = os.path.getsize(path)
        if keep_bytes is None:
            frac = (0.25 + 0.5 * self.rng.rand()) if keep_fraction is None \
                else keep_fraction
            keep_bytes = int(size * frac)
        keep_bytes = max(0, min(size - 1, keep_bytes))
        with open(path, 'r+b') as f:
            f.truncate(keep_bytes)
        return keep_bytes

    def corrupt_file(self, path, n_bytes=4):
        """Flip `n_bytes` seeded bytes in place WITHOUT changing the file
        size — the case only a content checksum (manifest CRC32) catches;
        a size check alone passes."""
        size = os.path.getsize(path)
        offsets = self.rng.randint(0, size, size=n_bytes)
        with open(path, 'r+b') as f:
            for off in offsets:
                f.seek(int(off))
                b = f.read(1)
                f.seek(int(off))
                f.write(bytes([b[0] ^ 0xFF]))
        return sorted(int(o) for o in offsets)

    def pick_file(self, directory, suffix='.npy'):
        """Seeded choice of one file (sorted listing, so the same seed
        picks the same shard on every run)."""
        names = sorted(n for n in os.listdir(directory)
                       if n.endswith(suffix))
        if not names:
            raise ValueError('no %r files under %r' % (suffix, directory))
        return os.path.join(directory, names[self.rng.randint(len(names))])

    # -- process faults ----------------------------------------------------

    def preempt(self, sig=signal.SIGTERM):
        """Simulated preemption of THIS process (see send_preemption)."""
        send_preemption(sig)
