"""ctypes bindings for the C++ runtime (paddle_tpu/csrc).

The native library owns the host data path: mmap'd recordio scanning, a
streaming record writer, and a background-thread prefetcher (the
reference's double_buffer reader thread, reference paddle/fluid/operators/
reader/create_double_buffer_reader_op.cc, lives in C++ there too).

Built lazily with `make -C paddle_tpu/csrc` on first use; everything
degrades to the pure-python implementations in reader/recordio.py when no
toolchain is available.
"""
import ctypes
import os
import subprocess

_LIB = None
_TRIED = False


def _csrc_dir():
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.join(here, 'csrc')


def _lib_path():
    return os.path.join(_csrc_dir(), 'libpaddle_tpu_native.so')


def ensure_built():
    """(Re)build the shared library if a toolchain is present. Best-effort.

    make is invoked even when the .so exists — it no-ops when up to date
    and rebuilds a stale library after a csrc update. The Makefile
    publishes via atomic rename, so concurrent builders are safe.
    """
    try:
        subprocess.run(['make', '-C', _csrc_dir()], check=True,
                       stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
                       timeout=120)
    except Exception:
        pass  # fall through: a pre-built .so may still exist
    return os.path.exists(_lib_path())


def _load():
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    if not ensure_built():
        return None
    try:
        lib = ctypes.CDLL(_lib_path())
        _bind(lib)
    except (OSError, AttributeError):
        # missing file or a stale .so lacking newer symbols: degrade
        return None
    _LIB = lib
    return _LIB


def _bind(lib):
    lib.ptrio_open.restype = ctypes.c_void_p
    lib.ptrio_open.argtypes = [ctypes.c_char_p]
    lib.ptrio_next.restype = ctypes.c_ssize_t
    lib.ptrio_next.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_char_p)]
    lib.ptrio_close.argtypes = [ctypes.c_void_p]
    lib.ptrio_writer_open.restype = ctypes.c_void_p
    lib.ptrio_writer_open.argtypes = [ctypes.c_char_p]
    lib.ptrio_writer_write.restype = ctypes.c_int
    lib.ptrio_writer_write.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                       ctypes.c_uint64]
    lib.ptrio_writer_close.argtypes = [ctypes.c_void_p]
    lib.ptrio_prefetch_open.restype = ctypes.c_void_p
    lib.ptrio_prefetch_open.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
    lib.ptrio_prefetch_next.restype = ctypes.c_ssize_t
    lib.ptrio_prefetch_next.argtypes = [ctypes.c_void_p,
                                        ctypes.POINTER(ctypes.c_char_p)]
    lib.ptrio_prefetch_close.argtypes = [ctypes.c_void_p]
    lib.ptim_transform_batch.restype = ctypes.c_int
    lib.ptim_transform_batch.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_void_p, ctypes.c_int, ctypes.c_uint64, ctypes.c_void_p]


def available():
    return _load() is not None


def image_transform_batch(images, resize_size, crop_size, is_train,
                          mean=None, seed=0):
    """Multithreaded C++ simple_transform over a same-sized uint8 HWC batch
    (csrc/image_aug.cpp). Returns [n, c, crop, crop] float32, or None when
    the native library is unavailable (caller falls back to numpy)."""
    import numpy as np
    lib = _load()
    if lib is None:
        return None
    images = np.ascontiguousarray(images, dtype=np.uint8)
    if images.ndim != 4:
        raise ValueError("expected [n, h, w, c] uint8 batch, got %s"
                         % (images.shape,))
    n, h, w, c = images.shape
    out = np.empty((n, c, crop_size, crop_size), np.float32)
    mean_arr, mean_len = None, 0
    if mean is not None:
        mean_arr = np.ascontiguousarray(mean, dtype=np.float32).reshape(-1)
        mean_len = mean_arr.shape[0]
        if mean_len not in (1, c, c * crop_size * crop_size):
            return None  # shape the kernel can't apply: numpy fallback
    rc = lib.ptim_transform_batch(
        images.ctypes.data_as(ctypes.c_void_p), n, h, w, c,
        int(resize_size), int(crop_size), int(bool(is_train)),
        mean_arr.ctypes.data_as(ctypes.c_void_p) if mean_len else None,
        mean_len, int(seed) & 0xFFFFFFFFFFFFFFFF,
        out.ctypes.data_as(ctypes.c_void_p))
    if rc != 0:
        raise ValueError("ptim_transform_batch rejected arguments "
                         "(resize %d < crop %d?)" % (resize_size, crop_size))
    return out


def recordio_iter(path):
    """Iterate raw record payloads via the mmap'd C++ chunk parser."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native library not built")
    h = lib.ptrio_open(path.encode())
    if not h:
        raise IOError("cannot open %s" % path)
    try:
        while True:
            buf = ctypes.c_char_p()
            n = lib.ptrio_next(h, ctypes.byref(buf))
            if n == -2:
                raise IOError("corrupt record file (checksum mismatch or truncation) in %s" % path)
            if n < 0:
                break
            yield ctypes.string_at(buf, n)
    finally:
        lib.ptrio_close(h)


def recordio_prefetch_iter(path, depth=4):
    """Iterate record payloads staged by the C++ background thread."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native library not built")
    h = lib.ptrio_prefetch_open(path.encode(), depth)
    if not h:
        raise IOError("cannot open %s" % path)
    try:
        while True:
            buf = ctypes.c_char_p()
            n = lib.ptrio_prefetch_next(h, ctypes.byref(buf))
            if n == -2:
                raise IOError("corrupt record file (checksum mismatch or truncation) in %s" % path)
            if n < 0:
                break
            yield ctypes.string_at(buf, n)
    finally:
        lib.ptrio_prefetch_close(h)


class NativeRecordWriter(object):
    """Streaming writer through the C ABI (crc computed in C++)."""

    def __init__(self, path):
        lib = _load()
        if lib is None:
            raise RuntimeError("native library not built")
        self._lib = lib
        self._h = lib.ptrio_writer_open(path.encode())
        if not self._h:
            raise IOError("cannot open %s for writing" % path)

    def write(self, payload):
        if self._lib.ptrio_writer_write(self._h, payload, len(payload)) != 0:
            raise IOError("short write")

    def close(self):
        if self._h:
            self._lib.ptrio_writer_close(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()
