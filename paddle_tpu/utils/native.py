"""ctypes bindings for the C++ runtime (paddle_tpu/csrc).

Gracefully degrades to pure-python when the shared library is not built;
build with `make -C paddle_tpu/csrc`.
"""
import ctypes
import os

_LIB = None
_TRIED = False


def _lib_path():
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.join(here, 'csrc', 'libpaddle_tpu_native.so')


def _load():
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    p = _lib_path()
    if os.path.exists(p):
        try:
            _LIB = ctypes.CDLL(p)
        except OSError:
            _LIB = None
    return _LIB


def available():
    return _load() is not None


def recordio_iter(path):
    """Iterate raw record payloads via the C++ chunk parser."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native library not built")
    lib.ptrio_open.restype = ctypes.c_void_p
    lib.ptrio_open.argtypes = [ctypes.c_char_p]
    lib.ptrio_next.restype = ctypes.c_ssize_t
    lib.ptrio_next.argtypes = [ctypes.c_void_p,
                               ctypes.POINTER(ctypes.c_char_p)]
    lib.ptrio_close.argtypes = [ctypes.c_void_p]
    h = lib.ptrio_open(path.encode())
    if not h:
        raise IOError("cannot open %s" % path)
    try:
        while True:
            buf = ctypes.c_char_p()
            n = lib.ptrio_next(h, ctypes.byref(buf))
            if n < 0:
                break
            yield ctypes.string_at(buf, n)
    finally:
        lib.ptrio_close(h)
