"""On-chip timing helper shared by bench.py and tools/tune_flash.py.

Through the axon TPU tunnel `jax.block_until_ready` returns before the
computation has actually finished, and a per-step host sync adds a fixed
round-trip that drowns small per-candidate deltas — so honest kernel
timing chains the steps ON DEVICE (each step's input depends on the
previous step's gradient) and round-trips ONE scalar whose value depends
on the final result.
"""
import time

__all__ = ['time_fwd_bwd_chained']


def time_fwd_bwd_chained(loss_fn, q, k, v, iters, warmup=1):
    """Seconds per fwd+bwd step of loss_fn(q, k, v) -> scalar, measured as
    `iters` chained steps inside one jit with a single scalar pulled to
    the host at the end. ALL THREE inputs advance by their gradients —
    dq and (dk, dv) come from separate pallas calls in the flash backward,
    so a chain that consumed only dq would let XLA dead-code-eliminate
    the dk/dv kernel and time half a backward."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    grad = jax.grad(loss_fn, argnums=(0, 1, 2))

    @jax.jit
    def run(q, k, v):
        def body(_, qkv):
            qq, kk, vv = qkv
            dq, dk, dv = grad(qq, kk, vv)
            return (qq + 1e-6 * dq, kk + 1e-6 * dk, vv + 1e-6 * dv)
        qn, kn, vn = jax.lax.fori_loop(0, iters, body, (q, k, v))
        return jnp.sum((qn[0, 0, 0, :8] + kn[0, 0, 0, :8]
                        + vn[0, 0, 0, :8]).astype(jnp.float32))

    for _ in range(warmup):
        s = float(run(q, k, v))     # compile + warm; host sync
        assert np.isfinite(s), s
    t0 = time.time()
    s = float(run(q, k, v))         # host round-trip = completion
    assert np.isfinite(s), s
    return (time.time() - t0) / iters
