"""Sharded (multi-host-safe) checkpointing for mesh-placed train state.

Parity: the reference persists per-var files through C++ save/load ops and
the trainer checkpoint dirs (reference python/paddle/fluid/io.py:468-690,
trainer.py:641 save_checkpoint). TPU-first redesign: arrays live sharded
over a jax.sharding.Mesh; gathering them to one host to .npz them would
need full-model host RAM and a cross-host transfer. Instead every process
writes only ITS addressable shards (replica 0 of each), with a manifest
recording shape/dtype/PartitionSpec per array; restore rebuilds each
jax.Array shard-by-shard via make_array_from_callback, so no host ever
materializes the full array and shardings round-trip exactly.

Format:
  <dir>/manifest.json                  process 0's view: {step, arrays}
  <dir>/manifest.p<i>.json             per-process shard listings (i > 0)
  <dir>/<escaped-name>.p<i>.shard<k>.npy   one file per distinct shard
Every process writes its own files (no filename collisions); the loader
merges all per-process manifests, so shards owned by other hosts are found
without any cross-host coordination at save time.
"""
import json
import os
import re
import threading
import zlib

import numpy as np

from .. import obs

__all__ = ['save_sharded', 'save_sharded_async', 'load_sharded',
           'load_latest_verified', 'verify_sharded', 'latest_step',
           'AsyncSave']

# transient-IO retry shape shared by shard reads/writes (utils.retry):
# 2 extra attempts, short base delay — a genuinely corrupt file fails all
# attempts identically and surfaces as the CRC/size RuntimeError below
_IO_RETRIES = 2
_IO_BASE_DELAY = 0.05


def _crc32_file(path, chunk=1 << 20):
    """CRC32 of a file's bytes, streamed (never loads a shard whole)."""
    crc = 0
    with open(path, 'rb') as f:
        for block in iter(lambda: f.read(chunk), b''):
            crc = zlib.crc32(block, crc)
    return crc & 0xFFFFFFFF

_MANIFEST = 'manifest.json'
# dirs with an async save in flight: overlapping saves to one dir would
# interleave identically-named shard files, so the second save raises
_INFLIGHT_DIRS = set()
_INFLIGHT_LOCK = threading.Lock()


def _escape(name):
    return re.sub(r'[^A-Za-z0-9_.@-]', '_', name)


def _spec_to_json(spec):
    out = []
    for e in tuple(spec):
        if e is None:
            out.append(None)
        elif isinstance(e, (tuple, list)):
            out.append(list(e))
        else:
            out.append(str(e))
    return out


def _spec_from_json(js):
    from jax.sharding import PartitionSpec as P
    return P(*[tuple(e) if isinstance(e, list) else e for e in js])


def _index_key(index, shape):
    """Normalize a tuple-of-slices shard index to a hashable start/stop list."""
    out = []
    for sl, n in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = n if sl.stop is None else int(sl.stop)
        out.append((start, stop))
    return tuple(out)


def _collect_shards(arrays, step, extra_meta, sink=None):
    """Walk replica-0 shards, build the manifest skeleton, and hand each
    shard to `sink(fname, host_array, shard_entry)`. With the default
    deferred sink, every shard is COPIED to host memory (copy=True — on
    the CPU backend np.asarray can be a zero-copy view of the device
    buffer, which a donating next step would clobber under the writer
    thread) and returned in `writes` for a background writer. A
    direct-write sink (the sync path) streams each shard to disk
    immediately instead, so peak host memory stays one shard, not the
    whole checkpoint."""
    import jax
    from jax.sharding import NamedSharding

    proc = jax.process_index()
    manifest = {'step': int(step), 'format': 'paddle_tpu-sharded-v1',
                'process': proc, 'extra': extra_meta or {}, 'arrays': {}}
    writes = []
    if sink is None:
        def sink(fname, shard_data, sh):
            writes.append((fname, np.array(shard_data, copy=True), sh))
    for name, arr in arrays.items():
        arr = arr if isinstance(arr, jax.Array) else jax.numpy.asarray(arr)
        sharding = arr.sharding
        entry = {'shape': list(arr.shape), 'dtype': str(arr.dtype),
                 'shards': []}
        if isinstance(sharding, NamedSharding):
            entry['mesh_axes'] = [str(a) for a in sharding.mesh.axis_names]
            entry['mesh_shape'] = [int(s) for s in sharding.mesh.devices.shape]
            entry['spec'] = _spec_to_json(sharding.spec)
        seen = set()
        base = _escape(name)
        for shard in arr.addressable_shards:
            if shard.replica_id != 0:
                continue  # some other shard/host owns this piece
            key = _index_key(shard.index, arr.shape)
            if key in seen:
                continue
            seen.add(key)
            fname = '%s.p%d.shard%d.npy' % (base, proc, len(entry['shards']))
            sh = {'file': fname, 'bytes': None,
                  'start': [k[0] for k in key],
                  'stop': [k[1] for k in key]}
            sink(fname, shard.data, sh)
            entry['shards'].append(sh)
        manifest['arrays'][name] = entry
    return manifest, writes


def _write_manifest(ckpt_dir, manifest):
    """ATOMICALLY LAST — a crash mid-save leaves either no manifest (save
    never happened) or byte counts that expose any truncated shard to
    _load_shard's corruption check."""
    proc = manifest['process']
    fname = _MANIFEST if proc == 0 else 'manifest.p%d.json' % proc
    tmp = os.path.join(ckpt_dir, fname + '.tmp')
    with open(tmp, 'w') as f:
        json.dump(manifest, f)
    os.replace(tmp, os.path.join(ckpt_dir, fname))
    return ckpt_dir


def _write_shard(fpath, data, sh):
    """Write one shard (retried on transient IO errors) and record its
    integrity triple — byte size AND content CRC32 — in the manifest
    entry. The CRC catches what the size check cannot: a same-length
    bit-rotted or overwritten file."""
    from .retry import retry_call
    retry_call(np.save, args=(fpath, data), retries=_IO_RETRIES,
               base_delay=_IO_BASE_DELAY,
               describe='write shard %r' % fpath,
               site='checkpoint.write_shard')
    sh['bytes'] = os.path.getsize(fpath)
    sh['crc32'] = _crc32_file(fpath)
    obs.counter('checkpoint.shard.writes').inc()
    obs.counter('checkpoint.shard.bytes').inc(sh['bytes'])


def _write_all(ckpt_dir, manifest, writes):
    """Deferred writer (async path): shard files first, manifest last."""
    os.makedirs(ckpt_dir, exist_ok=True)
    for fname, data, sh in writes:
        _write_shard(os.path.join(ckpt_dir, fname), data, sh)
    return _write_manifest(ckpt_dir, manifest)


def save_sharded(ckpt_dir, arrays, step=0, extra_meta=None):
    """Save {name: jax.Array} without gathering: each process writes the
    replica-0 shards it can address (filenames carry the process index, so
    hosts never collide) and its own manifest listing exactly those shards;
    the loader merges all manifests. Shards stream to disk one at a time
    (no whole-checkpoint host copy); the manifest commits last."""
    key = os.path.abspath(ckpt_dir)
    with _INFLIGHT_LOCK:
        if key in _INFLIGHT_DIRS:
            raise RuntimeError(
                'a save to %r is still in flight — overlapping saves '
                'would interleave identically-named shard files; wait() '
                'on the async handle (or let the sync save finish) first'
                % ckpt_dir)
        _INFLIGHT_DIRS.add(key)
    try:
        os.makedirs(ckpt_dir, exist_ok=True)

        def sink(fname, shard_data, sh):
            _write_shard(os.path.join(ckpt_dir, fname),
                         np.asarray(shard_data), sh)

        with obs.span('checkpoint.save_sharded', step=step,
                      dir=os.path.basename(ckpt_dir), arrays=len(arrays)):
            manifest, _ = _collect_shards(arrays, step, extra_meta,
                                          sink=sink)
            return _write_manifest(ckpt_dir, manifest)
    finally:
        with _INFLIGHT_LOCK:
            _INFLIGHT_DIRS.discard(key)


def _warn_unobserved_failure(state):
    """Warn that a background save failed with nobody left to observe it.
    Called from the AsyncSave finalizer (handle GC'd / interpreter exit)
    AND from the future's done-callback — whichever learns LAST that the
    handle is dead and the write failed; `state['lock']`+`'warned'` make
    the warning fire exactly once. `state` is a plain dict (never the
    handle itself, which a finalizer must not keep alive)."""
    with state['lock']:
        if state['observed'] or state['exc'] is None or state['warned']:
            return
        if not state['dead']:
            return  # the handle is alive: the caller can still wait()
        state['warned'] = True
    import warnings
    warnings.warn(
        'async sharded checkpoint to %r FAILED in the background (%r) '
        'and its handle was never wait()ed — the checkpoint is missing '
        'or partial' % (state['ckpt_dir'], state['exc']), RuntimeWarning)


class AsyncSave(object):
    """Handle for an in-flight save_sharded_async, wrapping the writer
    Future: wait() blocks and re-raises any IO error with its original
    traceback; done() polls.

    A caller that never observes the handle must still learn the
    checkpoint is missing/partial — but a caller that WILL wait() must
    not be pre-warned from the pool thread the moment the write fails
    (round-5 ADVICE: the old done-callback warned eagerly even when
    wait() followed and re-raised). The warning is therefore deferred to
    handle finalization (GC/atexit via weakref.finalize), the first point
    where "never observed" is actually decided."""

    def __init__(self, future, ckpt_dir):
        import weakref
        self._future = future
        self.ckpt_dir = ckpt_dir
        self._state = {'observed': False, 'exc': None, 'dead': False,
                       'warned': False, 'lock': threading.Lock(),
                       'ckpt_dir': ckpt_dir}
        state = self._state  # the callbacks must not capture self

        def record(fut):
            # runs in the pool thread when the write finishes; if the
            # handle was ALREADY dropped (GC'd before the write failed),
            # this is the last chance to surface the failure
            state['exc'] = fut.exception()
            _warn_unobserved_failure(state)
        future.add_done_callback(record)

        def finalize():
            state['dead'] = True
            _warn_unobserved_failure(state)
        self._finalizer = weakref.finalize(self, finalize)

    def done(self):
        return self._future.done()

    def wait(self, timeout=None):
        import concurrent.futures
        self._state['observed'] = True
        try:
            return self._future.result(timeout=timeout)
        except (TimeoutError, concurrent.futures.TimeoutError):
            # futures.TimeoutError is NOT builtins.TimeoutError before
            # Python 3.11 — catch both or a timed-out wait() would leave
            # observed=True and suppress the unobserved-failure warning
            self._state['observed'] = False  # the write is still in flight
            raise


def save_sharded_async(ckpt_dir, arrays, step=0, extra_meta=None):
    """save_sharded with the file IO off the critical path: device->host
    shard COPIES happen synchronously (so the caller may immediately
    donate/overwrite the device buffers — the next train step overlaps
    the disk write), then a background thread writes files and commits
    the manifest last. Returns an AsyncSave handle; call .wait() before
    relying on the checkpoint, and before issuing another save to the
    SAME directory (overlapping saves to one dir would interleave
    identically-named files — nothing serializes them for you). No orbax
    dependency — the format is identical to save_sharded's, so
    load_sharded reads both."""
    from concurrent.futures import ThreadPoolExecutor

    key = os.path.abspath(ckpt_dir)
    with _INFLIGHT_LOCK:
        if key in _INFLIGHT_DIRS:
            raise RuntimeError(
                'an async save to %r is still in flight — overlapping '
                'saves to one directory would interleave identically-'
                'named shard files; wait() on the previous handle first'
                % ckpt_dir)
        _INFLIGHT_DIRS.add(key)

    try:
        manifest, writes = _collect_shards(arrays, step, extra_meta)
        pool = ThreadPoolExecutor(max_workers=1,
                                  thread_name_prefix='paddle-tpu-async-ckpt')
        future = pool.submit(_write_all, ckpt_dir, manifest, writes)
    except BaseException:
        with _INFLIGHT_LOCK:
            _INFLIGHT_DIRS.discard(key)
        raise
    pool.shutdown(wait=False)  # lets the worker finish; nothing else queues

    def _clear_inflight(_):
        with _INFLIGHT_LOCK:
            _INFLIGHT_DIRS.discard(key)
    future.add_done_callback(_clear_inflight)
    return AsyncSave(future, ckpt_dir)


def _shard_meta_check(path, meta):
    """Existence/size gate against a manifest shard entry — the SINGLE
    implementation shared by _load_shard and verify_sharded so the two
    can never diverge on what counts as corrupt. Raises RuntimeError;
    returns the manifest CRC32 (or None when the manifest predates it).
    Missing/truncated verdicts count into checkpoint.crc_verify{fail}
    alongside CRC mismatches — the counter tracks the whole integrity
    gate, not only the hash compare."""
    if not os.path.exists(path):
        obs.counter('checkpoint.crc_verify', outcome='fail').inc()
        raise RuntimeError(
            'sharded checkpoint shard %r is missing (deleted or never '
            'fully written)' % path)
    want = meta.get('bytes')
    if want is not None and os.path.getsize(path) != want:
        obs.counter('checkpoint.crc_verify', outcome='fail').inc()
        raise RuntimeError(
            'sharded checkpoint shard %r is corrupt: %d bytes on disk, '
            'manifest recorded %d (truncated write?)'
            % (path, os.path.getsize(path), want))
    return meta.get('crc32')


def _crc_check(path, got_crc, want_crc):
    """Shared CRC comparison (same wording from every checker). Every
    verdict lands in the checkpoint.crc_verify counter, labeled by
    outcome, so an operator can see integrity checks happening (and
    failing) without scraping warnings."""
    if want_crc is None:
        return
    if got_crc != want_crc:
        obs.counter('checkpoint.crc_verify', outcome='fail').inc()
        obs.event('checkpoint.crc_fail', file=os.path.basename(path),
                  got='%08x' % got_crc, want='%08x' % want_crc)
        raise RuntimeError(
            'sharded checkpoint shard %r is corrupt: content CRC32 '
            '%08x does not match the manifest record %08x (bit rot or '
            'a partially-overwritten file)' % (path, got_crc, want_crc))
    obs.counter('checkpoint.crc_verify', outcome='ok').inc()


def _load_shard(ckpt_dir, sh, verify_crc=True):
    """np.load with corruption detection: a missing, size-mismatched
    (truncated / partially-written), or CRC-mismatched (bit-rotted /
    overwritten) shard file raises a RuntimeError naming the file instead
    of a cryptic numpy parse error or — worse — silently wrong values
    (reference io.py's load_persistables raises per-var on missing files
    the same way). The file is read from disk exactly ONCE: the CRC runs
    over the in-memory bytes np.load then parses. Reads are retried on
    transient IO errors first, so only a persistent mismatch reaches the
    corruption verdict."""
    import io as _io
    path = os.path.join(ckpt_dir, sh['file'] if isinstance(sh, dict) else sh)
    meta = sh if isinstance(sh, dict) else {}
    want_crc = _shard_meta_check(path, meta)
    from .retry import RetryError, retry_call

    def read():
        with open(path, 'rb') as f:
            return f.read()

    try:
        buf = retry_call(read, retries=_IO_RETRIES,
                         base_delay=_IO_BASE_DELAY,
                         describe='read shard %r' % path,
                         site='checkpoint.read_shard')
    except RetryError as e:
        raise RuntimeError(
            'sharded checkpoint shard %r is unreadable: %r'
            % (path, e.last_exception))
    if verify_crc:
        _crc_check(path, zlib.crc32(buf) & 0xFFFFFFFF, want_crc)
    try:
        return np.load(_io.BytesIO(buf))
    except Exception as e:
        raise RuntimeError(
            'sharded checkpoint shard %r is unreadable: %r' % (path, e))


def _merged_manifest(ckpt_dir):
    """Process 0's manifest with every other host's shard listings merged
    into the arrays table."""
    with open(os.path.join(ckpt_dir, _MANIFEST)) as f:
        manifest = json.load(f)
    for d in sorted(os.listdir(ckpt_dir)):
        if re.fullmatch(r'manifest\.p\d+\.json', d):
            with open(os.path.join(ckpt_dir, d)) as f:
                part = json.load(f)
            for name, entry in part.get('arrays', {}).items():
                if name in manifest['arrays']:
                    manifest['arrays'][name]['shards'].extend(entry['shards'])
                else:
                    manifest['arrays'][name] = entry
    return manifest


def verify_sharded(ckpt_dir):
    """Integrity-check every shard of a sharded checkpoint against its
    manifest records (existence, byte size, content CRC32) WITHOUT loading
    the arrays. Returns a list of human-readable problems — empty means
    the checkpoint is bit-exact as written. Used by load_latest_verified
    to decide whether a serial is safe to restore from."""
    problems = []
    with obs.span('checkpoint.verify', dir=os.path.basename(ckpt_dir)) \
            as sp:
        try:
            manifest = _merged_manifest(ckpt_dir)
        except (OSError, ValueError, KeyError) as e:
            sp.fields['problems'] = 1
            return ['manifest unreadable in %r: %r' % (ckpt_dir, e)]
        for name, entry in manifest.get('arrays', {}).items():
            for sh in entry.get('shards', []):
                try:
                    path = os.path.join(ckpt_dir, sh['file'])
                    want_crc = _shard_meta_check(path, sh)
                    if want_crc is not None:
                        _crc_check(path, _crc32_file(path), want_crc)
                except (RuntimeError, OSError, KeyError, TypeError) as e:
                    problems.append('%s: %s' % (name, e))
        sp.fields['problems'] = len(problems)
    return problems


def load_latest_verified(base_dir, prefix='sharded_', mesh=None):
    """Restore the NEWEST intact serial under base_dir/<prefix><step>.

    Serials are tried newest-first; one that fails integrity verification
    (torn write, truncated or bit-rotted shard, missing manifest) is
    skipped with a LOUD warning and the previous serial is tried — losing
    a few steps of progress is recoverable, silently training from
    corrupted weights is not. Raises RuntimeError when no intact serial
    remains. Returns (arrays, meta) like load_sharded."""
    import warnings
    steps = []
    if os.path.isdir(base_dir):
        for d in os.listdir(base_dir):
            if d.startswith(prefix):
                try:
                    steps.append(int(d[len(prefix):]))
                except ValueError:
                    continue
    if not steps:
        raise RuntimeError('no %r serials under %r' % (prefix, base_dir))
    tried = []
    for step in sorted(steps, reverse=True):
        ckpt_dir = os.path.join(base_dir, '%s%d' % (prefix, step))
        problems = verify_sharded(ckpt_dir)
        if not problems:
            try:
                # verify_sharded just hashed every shard; don't re-CRC
                # each file during the load (size/readability still check)
                return load_sharded(ckpt_dir, mesh=mesh, verify_crc=False)
            except (RuntimeError, OSError, ValueError, KeyError,
                    TypeError) as e:
                # a structurally-torn manifest (missing 'shape'/'spec'
                # fields) raises Key/Type/ValueError past verify_sharded's
                # integrity checks — still fall back, loudly, like the
                # Trainer's serial loop does
                problems = ['%s: %s' % (type(e).__name__, e)]
        tried.append((step, problems))
        obs.counter('checkpoint.serial_fallbacks').inc()
        obs.event('checkpoint.serial_fallback', serial=step,
                  problems=len(problems), first=str(problems[0])[:200])
        warnings.warn(
            'sharded checkpoint serial %d at %r FAILED verification '
            '(%s) — falling back to the previous serial'
            % (step, ckpt_dir, '; '.join(problems[:3])), RuntimeWarning)
    raise RuntimeError(
        'no intact sharded checkpoint under %r: %s'
        % (base_dir, '; '.join('serial %d: %s' % (s, p[0])
                               for s, p in tried)))


def load_sharded(ckpt_dir, mesh=None, verify_crc=True):
    """Restore {name: jax.Array} with the saved shardings.

    mesh: the Mesh to restore onto; None re-creates one per-array from the
    manifest's (mesh_axes, mesh_shape) over jax.devices(). Returns
    (arrays, meta) where meta has 'step' and 'extra'. verify_crc=False
    skips the per-shard content CRC (size/readability still checked) —
    for callers that just ran verify_sharded over the same dir.
    """
    with obs.span('checkpoint.load_sharded',
                  dir=os.path.basename(ckpt_dir)):
        return _load_sharded_impl(ckpt_dir, mesh, verify_crc)


def _load_sharded_impl(ckpt_dir, mesh, verify_crc):
    import jax
    from jax.sharding import Mesh, NamedSharding

    manifest = _merged_manifest(ckpt_dir)

    mesh_cache = {}

    def get_mesh(axes, shape):
        if mesh is not None:
            return mesh
        key = (tuple(axes), tuple(shape))
        if key not in mesh_cache:
            n = int(np.prod(shape)) if shape else 1
            devs = np.asarray(jax.devices()[:n]).reshape(shape)
            mesh_cache[key] = Mesh(devs, tuple(axes))
        return mesh_cache[key]

    out = {}
    for name, entry in manifest['arrays'].items():
        shape = tuple(entry['shape'])
        dtype = entry['dtype']
        shard_map = {}
        for sh in entry['shards']:
            key = tuple((s, t) for s, t in zip(sh['start'], sh['stop']))
            shard_map[key] = sh

        def cb(index, _shape=shape, _smap=shard_map, _dtype=dtype):
            key = _index_key(index, _shape)
            if key in _smap:
                return _load_shard(ckpt_dir, _smap[key],
                                   verify_crc=verify_crc).astype(_dtype)
            # Restoring onto a different mesh/spec: assemble the requested
            # region from the overlapping saved shards (elastic restore).
            region = np.empty([t - s for s, t in key], dtype=_dtype)
            covered = np.zeros(region.shape, dtype=bool)
            for skey, sh in _smap.items():
                lo = [max(a[0], b[0]) for a, b in zip(key, skey)]
                hi = [min(a[1], b[1]) for a, b in zip(key, skey)]
                if any(l >= h for l, h in zip(lo, hi)):
                    continue
                data = _load_shard(ckpt_dir, sh, verify_crc=verify_crc)
                src = tuple(slice(l - b[0], h - b[0])
                            for l, h, b in zip(lo, hi, skey))
                dst = tuple(slice(l - a[0], h - a[0])
                            for l, h, a in zip(lo, hi, key))
                region[dst] = data[src]
                covered[dst] = True
            if not covered.all():
                raise RuntimeError(
                    "sharded checkpoint %s: saved shards do not cover "
                    "region %s of %r (missing/overwritten shard file?)"
                    % (ckpt_dir, key, _shape))
            return region.astype(_dtype)

        if 'spec' in entry:
            m = get_mesh(entry['mesh_axes'], entry['mesh_shape'])
            sharding = NamedSharding(m, _spec_from_json(entry['spec']))
        else:
            sharding = jax.sharding.SingleDeviceSharding(jax.devices()[0])
        if shape == ():
            # scalars: trivial single shard
            out[name] = jax.device_put(cb(()), sharding)
        else:
            out[name] = jax.make_array_from_callback(shape, sharding, cb)
    return out, {'step': manifest['step'], 'extra': manifest.get('extra', {})}


def latest_step(base_dir, prefix='sharded_'):
    """Largest <prefix><step> subdir with a manifest, or None."""
    if not os.path.isdir(base_dir):
        return None
    best = None
    for d in os.listdir(base_dir):
        if not d.startswith(prefix):
            continue
        try:
            step = int(d[len(prefix):])
        except ValueError:
            continue
        if os.path.exists(os.path.join(base_dir, d, _MANIFEST)):
            best = step if best is None else max(best, step)
    return best
