"""Sharded (multi-host-safe) checkpointing for mesh-placed train state.

Parity: the reference persists per-var files through C++ save/load ops and
the trainer checkpoint dirs (reference python/paddle/fluid/io.py:468-690,
trainer.py:641 save_checkpoint). TPU-first redesign: arrays live sharded
over a jax.sharding.Mesh; gathering them to one host to .npz them would
need full-model host RAM and a cross-host transfer. Instead every process
writes only ITS addressable shards (replica 0 of each), with a manifest
recording shape/dtype/PartitionSpec per array; restore rebuilds each
jax.Array shard-by-shard via make_array_from_callback, so no host ever
materializes the full array and shardings round-trip exactly.

Format:
  <dir>/manifest.json                  process 0's view: {step, arrays}
  <dir>/manifest.json.sum              size+CRC32 of the manifest itself
  <dir>/manifest.p<i>.json[.sum]       per-process shard listings (i > 0)
  <dir>/<escaped-name>.p<i>.shard<k>.npy   one file per distinct shard
Every process writes its own files (no filename collisions); the loader
merges all per-process manifests, so shards owned by other hosts are found
without any cross-host coordination at save time.

Atomic commit protocol (docs/robustness.md#elastic): everything above is
staged into `<dir>.tmp` — shard files first, each process's manifest LAST
— and process 0 COMMITS by renaming the staging dir to `<dir>` (after
waiting for every peer's manifest on multi-process meshes). A SIGKILL at
any point mid-save leaves only the `.tmp` dir, which `latest_step` /
`load_latest_verified` never select, so a torn write can never look like
the latest checkpoint — the loader falls back to the previous committed
serial without depending on a CRC check happening to fail.
"""
import json
import os
import shutil
import threading
import time
import re
import zlib

import numpy as np

from .. import obs

__all__ = ['save_sharded', 'save_sharded_async', 'load_sharded',
           'load_latest_verified', 'verify_sharded', 'latest_step',
           'restorable', 'AsyncSave', 'CommitTimeout']

# transient-IO retry shape shared by shard reads/writes (utils.retry):
# 2 extra attempts, short base delay — a genuinely corrupt file fails all
# attempts identically and surfaces as the CRC/size RuntimeError below
_IO_RETRIES = 2
_IO_BASE_DELAY = 0.05


def _crc32_file(path, chunk=1 << 20):
    """CRC32 of a file's bytes, streamed (never loads a shard whole)."""
    crc = 0
    with open(path, 'rb') as f:
        for block in iter(lambda: f.read(chunk), b''):
            crc = zlib.crc32(block, crc)
    return crc & 0xFFFFFFFF

_MANIFEST = 'manifest.json'
# dirs with an async save in flight: overlapping saves to one dir would
# interleave identically-named shard files, so the second save raises
_INFLIGHT_DIRS = set()
_INFLIGHT_LOCK = threading.Lock()


def _escape(name):
    return re.sub(r'[^A-Za-z0-9_.@-]', '_', name)


def _spec_to_json(spec):
    out = []
    for e in tuple(spec):
        if e is None:
            out.append(None)
        elif isinstance(e, (tuple, list)):
            out.append(list(e))
        else:
            out.append(str(e))
    return out


def _spec_from_json(js):
    from jax.sharding import PartitionSpec as P
    return P(*[tuple(e) if isinstance(e, list) else e for e in js])


def _index_key(index, shape):
    """Normalize a tuple-of-slices shard index to a hashable start/stop list."""
    out = []
    for sl, n in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = n if sl.stop is None else int(sl.stop)
        out.append((start, stop))
    return tuple(out)


def _collect_shards(arrays, step, extra_meta, sink=None):
    """Walk replica-0 shards, build the manifest skeleton, and hand each
    shard to `sink(fname, host_array, shard_entry)`. With the default
    deferred sink, every shard is COPIED to host memory (copy=True — on
    the CPU backend np.asarray can be a zero-copy view of the device
    buffer, which a donating next step would clobber under the writer
    thread) and returned in `writes` for a background writer. A
    direct-write sink (the sync path) streams each shard to disk
    immediately instead, so peak host memory stays one shard, not the
    whole checkpoint."""
    import jax
    from jax.sharding import NamedSharding

    proc = jax.process_index()
    manifest = {'step': int(step), 'format': 'paddle_tpu-sharded-v1',
                'process': proc, 'extra': extra_meta or {}, 'arrays': {}}
    writes = []
    if sink is None:
        def sink(fname, shard_data, sh):
            writes.append((fname, np.array(shard_data, copy=True), sh))
    for name, arr in arrays.items():
        arr = arr if isinstance(arr, jax.Array) else jax.numpy.asarray(arr)
        sharding = arr.sharding
        entry = {'shape': list(arr.shape), 'dtype': str(arr.dtype),
                 'shards': []}
        if isinstance(sharding, NamedSharding):
            entry['mesh_axes'] = [str(a) for a in sharding.mesh.axis_names]
            entry['mesh_shape'] = [int(s) for s in sharding.mesh.devices.shape]
            entry['spec'] = _spec_to_json(sharding.spec)
        seen = set()
        base = _escape(name)
        for shard in arr.addressable_shards:
            if shard.replica_id != 0:
                continue  # some other shard/host owns this piece
            key = _index_key(shard.index, arr.shape)
            if key in seen:
                continue
            seen.add(key)
            fname = '%s.p%d.shard%d.npy' % (base, proc, len(entry['shards']))
            sh = {'file': fname, 'bytes': None,
                  'start': [k[0] for k in key],
                  'stop': [k[1] for k in key]}
            sink(fname, shard.data, sh)
            entry['shards'].append(sh)
        manifest['arrays'][name] = entry
    return manifest, writes


def _write_manifest(ckpt_dir, manifest):
    """ATOMICALLY LAST — a crash mid-save leaves either no manifest (save
    never happened) or byte counts that expose any truncated shard to
    _load_shard's corruption check. A `.sum` sidecar (size + content
    CRC32 of the manifest file itself) commits right after, so a
    bit-rotted manifest fails verification with a typed error instead of
    a raw JSON/KeyError; old checkpoints without the sidecar still
    load."""
    proc = manifest['process']
    fname = _MANIFEST if proc == 0 else 'manifest.p%d.json' % proc
    path = os.path.join(ckpt_dir, fname)
    tmp = path + '.tmp'
    with open(tmp, 'w') as f:
        json.dump(manifest, f)
    # sidecar FIRST (computed over the staged bytes), manifest second:
    # the manifest's appearance is what commit/peers key on, so by the
    # time anyone can see it, its integrity record already exists — the
    # reverse order would let process 0 rename the staging dir out from
    # under a peer still writing its sidecar. An orphaned sidecar from
    # a crash in between is harmless (loaders key on the manifest).
    sum_tmp = path + '.sum.tmp'
    with open(sum_tmp, 'w') as f:
        json.dump({'file': fname, 'bytes': os.path.getsize(tmp),
                   'crc32': _crc32_file(tmp)}, f)
    os.replace(sum_tmp, path + '.sum')
    os.replace(tmp, path)
    return ckpt_dir


def _read_manifest_file(path):
    """Parse one manifest file, integrity-gated: when its `.sum` sidecar
    exists (every checkpoint written since the commit protocol), the
    manifest's size and content CRC32 are verified FIRST, so bit rot or
    truncation surfaces as a typed RuntimeError the fallback machinery
    understands — never a raw json/KeyError from half-parsed garbage.
    Checkpoints predating the sidecar parse unverified (compat)."""
    sum_path = path + '.sum'
    if os.path.exists(sum_path):
        try:
            with open(sum_path) as f:
                rec = json.load(f)
            want_bytes, want_crc = rec.get('bytes'), rec.get('crc32')
        except (OSError, ValueError) as e:
            obs.counter('checkpoint.crc_verify', outcome='fail').inc()
            raise RuntimeError(
                'sharded checkpoint manifest sidecar %r is unreadable '
                '(%r) — the manifest cannot be verified' % (sum_path, e))
        if want_bytes is not None and os.path.getsize(path) != want_bytes:
            obs.counter('checkpoint.crc_verify', outcome='fail').inc()
            raise RuntimeError(
                'sharded checkpoint manifest %r is corrupt: %d bytes on '
                'disk, sidecar recorded %d (truncated write?)'
                % (path, os.path.getsize(path), want_bytes))
        got = _crc32_file(path)
        if want_crc is not None and got != want_crc:
            obs.counter('checkpoint.crc_verify', outcome='fail').inc()
            raise RuntimeError(
                'sharded checkpoint manifest %r is corrupt: content '
                'CRC32 %08x does not match the sidecar record %08x '
                '(bit rot or a partially-overwritten file)'
                % (path, got, want_crc))
        obs.counter('checkpoint.crc_verify', outcome='ok').inc()
    try:
        with open(path) as f:
            return json.load(f)
    except ValueError as e:
        raise RuntimeError(
            'sharded checkpoint manifest %r is unreadable (%r) — torn '
            'write or corruption the size/CRC sidecar did not cover'
            % (path, e))


# -- atomic commit protocol -------------------------------------------------

_STAGING_SUFFIX = '.tmp'
_OLD_SUFFIX = '.old'
_COMMIT_TIMEOUT = 60.0
_COMMIT_POLL = 0.05


class CommitTimeout(RuntimeError):
    """The commit wait for peer manifests expired — the save stays
    loudly UNCOMMITTED (staging dir left in place; load_latest_verified
    skips it). The previous committed serial carries the resume, so
    callers with that fallback (the Trainer's periodic saves) may treat
    this as a missed checkpoint rather than a fatal error."""


def _staging_dir(ckpt_dir):
    return ckpt_dir.rstrip('/' + os.sep) + _STAGING_SUFFIX


def _prepare_staging(staging):
    """Create the staging dir. Single-process, stale manifests left by a
    previous crashed save to the same serial are cleared (no peer can be
    writing); multi-process they are left alone — a peer may legitimately
    already be staging this very save — and the commit wait instead
    validates each peer manifest's step before counting it."""
    import jax
    os.makedirs(staging, exist_ok=True)
    if jax.process_count() == 1:
        for f in os.listdir(staging):
            if re.fullmatch(r'manifest(\.p\d+)?\.json(\.sum)?', f):
                try:
                    os.remove(os.path.join(staging, f))
                except OSError:
                    pass
    return staging


def _peer_manifest_step(staging, proc):
    """The 'step' a peer's staged manifest records, or None when absent /
    unparseable / unverifiable (still being written, or stale garbage)."""
    try:
        man = _read_manifest_file(
            os.path.join(staging, 'manifest.p%d.json' % proc))
        return int(man.get('step', -1))
    except (RuntimeError, OSError, ValueError, TypeError):
        return None


def _commit(staging, ckpt_dir, manifest, commit_timeout):
    """Commit a fully-staged checkpoint: process 0 waits until every
    peer's manifest (matching this save's step) is present in the staging
    dir, then atomically renames it to the final name. Non-zero processes
    only stage — the rename is process 0's, so on them this RETURNS
    WITHOUT COMMITTING (the final dir exists only once process 0
    renames; a caller that must know checks os.path.isdir on the final
    name). A SIGKILL anywhere before the rename leaves `<dir>.tmp`,
    which no loader ever selects; a commit TIMEOUT (a peer died
    mid-save) raises CommitTimeout, leaving the checkpoint loudly
    uncommitted."""
    import jax
    proc = int(manifest['process'])
    nproc = jax.process_count()
    step = int(manifest['step'])
    with obs.span('checkpoint.commit', dir=os.path.basename(ckpt_dir),
                  step=step, process=proc, processes=nproc) as sp:
        if nproc > 1 and proc != 0:
            sp.fields['role'] = 'staged'
            return ckpt_dir
        if nproc > 1:
            deadline = time.monotonic() + float(commit_timeout)
            while True:
                missing = [i for i in range(1, nproc)
                           if _peer_manifest_step(staging, i) != step]
                if not missing:
                    break
                if time.monotonic() > deadline:
                    obs.counter('checkpoint.commit.timeouts').inc()
                    obs.event('checkpoint.commit.timeout', step=step,
                              dir=os.path.basename(ckpt_dir),
                              missing=missing)
                    raise CommitTimeout(
                        'sharded checkpoint commit of %r timed out after '
                        '%.1fs waiting for peer manifest(s) from '
                        'process(es) %s — the save stays UNCOMMITTED at '
                        '%r and load_latest_verified will skip it'
                        % (ckpt_dir, float(commit_timeout), missing,
                           staging))
                time.sleep(_COMMIT_POLL)
        old = ckpt_dir.rstrip('/' + os.sep) + _OLD_SUFFIX
        if os.path.isdir(old):
            shutil.rmtree(old)   # garbage from a crashed earlier swap
        if os.path.isdir(ckpt_dir):
            # overwrite semantics of the pre-protocol writer, done as an
            # atomic SWAP: the committed data is never deleted before
            # its replacement is in place — a SIGKILL between the two
            # renames demotes the old serial to `.old` (unselectable but
            # intact on disk) instead of destroying it
            os.rename(ckpt_dir, old)
            os.rename(staging, ckpt_dir)
            shutil.rmtree(old, ignore_errors=True)
        else:
            os.rename(staging, ckpt_dir)
        obs.event('checkpoint.committed', step=step,
                  dir=os.path.basename(ckpt_dir))
    return ckpt_dir


def _write_shard(fpath, data, sh):
    """Write one shard (retried on transient IO errors) and record its
    integrity triple — byte size AND content CRC32 — in the manifest
    entry. The CRC catches what the size check cannot: a same-length
    bit-rotted or overwritten file."""
    from .retry import retry_call
    retry_call(np.save, args=(fpath, data), retries=_IO_RETRIES,
               base_delay=_IO_BASE_DELAY,
               describe='write shard %r' % fpath,
               site='checkpoint.write_shard')
    sh['bytes'] = os.path.getsize(fpath)
    sh['crc32'] = _crc32_file(fpath)
    obs.counter('checkpoint.shard.writes').inc()
    obs.counter('checkpoint.shard.bytes').inc(sh['bytes'])


def _write_all(ckpt_dir, manifest, writes, commit_timeout=_COMMIT_TIMEOUT):
    """Deferred writer (async path): stage shard files first, the
    manifest last, then commit (rename) the staging dir."""
    staging = _prepare_staging(_staging_dir(ckpt_dir))
    for fname, data, sh in writes:
        _write_shard(os.path.join(staging, fname), data, sh)
    _write_manifest(staging, manifest)
    return _commit(staging, ckpt_dir, manifest, commit_timeout)


def save_sharded(ckpt_dir, arrays, step=0, extra_meta=None,
                 commit_timeout=_COMMIT_TIMEOUT):
    """Save {name: jax.Array} without gathering: each process writes the
    replica-0 shards it can address (filenames carry the process index, so
    hosts never collide) and its own manifest listing exactly those shards;
    the loader merges all manifests. Shards stream to disk one at a time
    (no whole-checkpoint host copy); everything stages into `<dir>.tmp`,
    each process's manifest commits last within the staging dir, and
    process 0 atomically renames it to `<dir>` once every peer's manifest
    for this step is present (`commit_timeout` bounds that wait — a peer
    that died mid-save raises here, leaving the save loudly uncommitted
    instead of latest-looking and torn)."""
    key = os.path.abspath(ckpt_dir)
    with _INFLIGHT_LOCK:
        if key in _INFLIGHT_DIRS:
            raise RuntimeError(
                'a save to %r is still in flight — overlapping saves '
                'would interleave identically-named shard files; wait() '
                'on the async handle (or let the sync save finish) first'
                % ckpt_dir)
        _INFLIGHT_DIRS.add(key)
    try:
        staging = _prepare_staging(_staging_dir(ckpt_dir))

        def sink(fname, shard_data, sh):
            _write_shard(os.path.join(staging, fname),
                         np.asarray(shard_data), sh)

        with obs.span('checkpoint.save_sharded', step=step,
                      dir=os.path.basename(ckpt_dir), arrays=len(arrays)):
            manifest, _ = _collect_shards(arrays, step, extra_meta,
                                          sink=sink)
            _write_manifest(staging, manifest)
            return _commit(staging, ckpt_dir, manifest, commit_timeout)
    finally:
        with _INFLIGHT_LOCK:
            _INFLIGHT_DIRS.discard(key)


def _warn_unobserved_failure(state):
    """Warn that a background save failed with nobody left to observe it.
    Called from the AsyncSave finalizer (handle GC'd / interpreter exit)
    AND from the future's done-callback — whichever learns LAST that the
    handle is dead and the write failed; `state['lock']`+`'warned'` make
    the warning fire exactly once. `state` is a plain dict (never the
    handle itself, which a finalizer must not keep alive)."""
    with state['lock']:
        if state['observed'] or state['exc'] is None or state['warned']:
            return
        if not state['dead']:
            return  # the handle is alive: the caller can still wait()
        state['warned'] = True
    import warnings
    warnings.warn(
        'async sharded checkpoint to %r FAILED in the background (%r) '
        'and its handle was never wait()ed — the checkpoint is missing '
        'or partial' % (state['ckpt_dir'], state['exc']), RuntimeWarning)


class AsyncSave(object):
    """Handle for an in-flight save_sharded_async, wrapping the writer
    Future: wait() blocks and re-raises any IO error with its original
    traceback; done() polls.

    A caller that never observes the handle must still learn the
    checkpoint is missing/partial — but a caller that WILL wait() must
    not be pre-warned from the pool thread the moment the write fails
    (round-5 ADVICE: the old done-callback warned eagerly even when
    wait() followed and re-raised). The warning is therefore deferred to
    handle finalization (GC/atexit via weakref.finalize), the first point
    where "never observed" is actually decided."""

    def __init__(self, future, ckpt_dir):
        import weakref
        self._future = future
        self.ckpt_dir = ckpt_dir
        self._state = {'observed': False, 'exc': None, 'dead': False,
                       'warned': False, 'lock': threading.Lock(),
                       'ckpt_dir': ckpt_dir}
        state = self._state  # the callbacks must not capture self

        def record(fut):
            # runs in the pool thread when the write finishes; if the
            # handle was ALREADY dropped (GC'd before the write failed),
            # this is the last chance to surface the failure
            state['exc'] = fut.exception()
            _warn_unobserved_failure(state)
        future.add_done_callback(record)

        def finalize():
            state['dead'] = True
            _warn_unobserved_failure(state)
        self._finalizer = weakref.finalize(self, finalize)

    def done(self):
        return self._future.done()

    def wait(self, timeout=None):
        import concurrent.futures
        self._state['observed'] = True
        try:
            return self._future.result(timeout=timeout)
        except (TimeoutError, concurrent.futures.TimeoutError):
            # futures.TimeoutError is NOT builtins.TimeoutError before
            # Python 3.11 — catch both or a timed-out wait() would leave
            # observed=True and suppress the unobserved-failure warning
            self._state['observed'] = False  # the write is still in flight
            raise


def save_sharded_async(ckpt_dir, arrays, step=0, extra_meta=None,
                       commit_timeout=_COMMIT_TIMEOUT):
    """save_sharded with the file IO off the critical path: device->host
    shard COPIES happen synchronously (so the caller may immediately
    donate/overwrite the device buffers — the next train step overlaps
    the disk write), then a background thread writes files and commits
    the manifest last. Returns an AsyncSave handle; call .wait() before
    relying on the checkpoint, and before issuing another save to the
    SAME directory (overlapping saves to one dir would interleave
    identically-named files — nothing serializes them for you). No orbax
    dependency — the format is identical to save_sharded's, so
    load_sharded reads both."""
    from concurrent.futures import ThreadPoolExecutor

    key = os.path.abspath(ckpt_dir)
    with _INFLIGHT_LOCK:
        if key in _INFLIGHT_DIRS:
            raise RuntimeError(
                'an async save to %r is still in flight — overlapping '
                'saves to one directory would interleave identically-'
                'named shard files; wait() on the previous handle first'
                % ckpt_dir)
        _INFLIGHT_DIRS.add(key)

    try:
        # the buffer snapshot IS the caller's whole step-boundary cost on
        # the async path (docs/perf.md#overlap): device->host copies of
        # every addressable shard, taken synchronously so the next step
        # may donate the device buffers. The span is what obs_report's
        # step-artifact section reports as snapshot latency.
        with obs.span('checkpoint.snapshot', step=step,
                      dir=os.path.basename(ckpt_dir),
                      arrays=len(arrays)) as snap_sp:
            manifest, writes = _collect_shards(arrays, step, extra_meta)
            snap_sp.fields['bytes'] = int(
                sum(w[1].nbytes for w in writes))
        pool = ThreadPoolExecutor(max_workers=1,
                                  thread_name_prefix='paddle-tpu-async-ckpt')
        future = pool.submit(_write_all, ckpt_dir, manifest, writes,
                             commit_timeout)
    except BaseException:
        with _INFLIGHT_LOCK:
            _INFLIGHT_DIRS.discard(key)
        raise
    pool.shutdown(wait=False)  # lets the worker finish; nothing else queues

    def _clear_inflight(_):
        with _INFLIGHT_LOCK:
            _INFLIGHT_DIRS.discard(key)
    future.add_done_callback(_clear_inflight)
    return AsyncSave(future, ckpt_dir)


def _shard_meta_check(path, meta):
    """Existence/size gate against a manifest shard entry — the SINGLE
    implementation shared by _load_shard and verify_sharded so the two
    can never diverge on what counts as corrupt. Raises RuntimeError;
    returns the manifest CRC32 (or None when the manifest predates it).
    Missing/truncated verdicts count into checkpoint.crc_verify{fail}
    alongside CRC mismatches — the counter tracks the whole integrity
    gate, not only the hash compare."""
    if not os.path.exists(path):
        obs.counter('checkpoint.crc_verify', outcome='fail').inc()
        raise RuntimeError(
            'sharded checkpoint shard %r is missing (deleted or never '
            'fully written)' % path)
    want = meta.get('bytes')
    if want is not None and os.path.getsize(path) != want:
        obs.counter('checkpoint.crc_verify', outcome='fail').inc()
        raise RuntimeError(
            'sharded checkpoint shard %r is corrupt: %d bytes on disk, '
            'manifest recorded %d (truncated write?)'
            % (path, os.path.getsize(path), want))
    return meta.get('crc32')


def _crc_check(path, got_crc, want_crc):
    """Shared CRC comparison (same wording from every checker). Every
    verdict lands in the checkpoint.crc_verify counter, labeled by
    outcome, so an operator can see integrity checks happening (and
    failing) without scraping warnings."""
    if want_crc is None:
        return
    if got_crc != want_crc:
        obs.counter('checkpoint.crc_verify', outcome='fail').inc()
        obs.event('checkpoint.crc_fail', file=os.path.basename(path),
                  got='%08x' % got_crc, want='%08x' % want_crc)
        raise RuntimeError(
            'sharded checkpoint shard %r is corrupt: content CRC32 '
            '%08x does not match the manifest record %08x (bit rot or '
            'a partially-overwritten file)' % (path, got_crc, want_crc))
    obs.counter('checkpoint.crc_verify', outcome='ok').inc()


def _load_shard(ckpt_dir, sh, verify_crc=True):
    """np.load with corruption detection: a missing, size-mismatched
    (truncated / partially-written), or CRC-mismatched (bit-rotted /
    overwritten) shard file raises a RuntimeError naming the file instead
    of a cryptic numpy parse error or — worse — silently wrong values
    (reference io.py's load_persistables raises per-var on missing files
    the same way). The file is read from disk exactly ONCE: the CRC runs
    over the in-memory bytes np.load then parses. Reads are retried on
    transient IO errors first, so only a persistent mismatch reaches the
    corruption verdict."""
    import io as _io
    path = os.path.join(ckpt_dir, sh['file'] if isinstance(sh, dict) else sh)
    meta = sh if isinstance(sh, dict) else {}
    want_crc = _shard_meta_check(path, meta)
    from .retry import RetryError, retry_call

    def read():
        with open(path, 'rb') as f:
            return f.read()

    try:
        buf = retry_call(read, retries=_IO_RETRIES,
                         base_delay=_IO_BASE_DELAY,
                         describe='read shard %r' % path,
                         site='checkpoint.read_shard')
    except RetryError as e:
        raise RuntimeError(
            'sharded checkpoint shard %r is unreadable: %r'
            % (path, e.last_exception))
    if verify_crc:
        _crc_check(path, zlib.crc32(buf) & 0xFFFFFFFF, want_crc)
    try:
        return np.load(_io.BytesIO(buf))
    except Exception as e:
        raise RuntimeError(
            'sharded checkpoint shard %r is unreadable: %r' % (path, e))


def _merged_manifest(ckpt_dir):
    """Process 0's manifest with every other host's shard listings merged
    into the arrays table. Every manifest file is size/CRC-verified
    against its `.sum` sidecar first (when present — old checkpoints
    predate it), so a bit-rotted manifest is a typed verification
    failure, not a raw parse error."""
    manifest = _read_manifest_file(os.path.join(ckpt_dir, _MANIFEST))
    for d in sorted(os.listdir(ckpt_dir)):
        if re.fullmatch(r'manifest\.p\d+\.json', d):
            part = _read_manifest_file(os.path.join(ckpt_dir, d))
            for name, entry in part.get('arrays', {}).items():
                if name in manifest['arrays']:
                    manifest['arrays'][name]['shards'].extend(entry['shards'])
                else:
                    manifest['arrays'][name] = entry
    return manifest


def verify_sharded(ckpt_dir):
    """Integrity-check every shard of a sharded checkpoint against its
    manifest records (existence, byte size, content CRC32) WITHOUT loading
    the arrays. Returns a list of human-readable problems — empty means
    the checkpoint is bit-exact as written. Used by load_latest_verified
    to decide whether a serial is safe to restore from."""
    problems = []
    with obs.span('checkpoint.verify', dir=os.path.basename(ckpt_dir)) \
            as sp:
        try:
            manifest = _merged_manifest(ckpt_dir)
        except (RuntimeError, OSError, ValueError, KeyError) as e:
            sp.fields['problems'] = 1
            return ['manifest unreadable in %r: %s' % (ckpt_dir, e)]
        for name, entry in manifest.get('arrays', {}).items():
            for sh in entry.get('shards', []):
                try:
                    path = os.path.join(ckpt_dir, sh['file'])
                    want_crc = _shard_meta_check(path, sh)
                    if want_crc is not None:
                        _crc_check(path, _crc32_file(path), want_crc)
                except (RuntimeError, OSError, KeyError, TypeError) as e:
                    problems.append('%s: %s' % (name, e))
        sp.fields['problems'] = len(problems)
    return problems


def load_latest_verified(base_dir, prefix='sharded_', mesh=None):
    """Restore the NEWEST intact serial under base_dir/<prefix><step>.

    Serials are tried newest-first; one that fails integrity verification
    (torn write, truncated or bit-rotted shard, missing manifest) is
    skipped with a LOUD warning and the previous serial is tried — losing
    a few steps of progress is recoverable, silently training from
    corrupted weights is not. Raises RuntimeError when no intact serial
    remains. Returns (arrays, meta) like load_sharded."""
    import warnings
    steps = []
    uncommitted = []
    if os.path.isdir(base_dir):
        for d in os.listdir(base_dir):
            if not d.startswith(prefix):
                continue
            if re.fullmatch(r'\d+' + re.escape(_STAGING_SUFFIX),
                            d[len(prefix):]):
                uncommitted.append(d)
                continue
            try:
                steps.append(int(d[len(prefix):]))
            except ValueError:
                continue
    if uncommitted:
        # a save that never committed (SIGKILL / peer death mid-write):
        # by construction it is not a candidate — say so out loud rather
        # than silently ignoring what an operator will see on disk
        obs.event('checkpoint.uncommitted_skipped',
                  dirs=sorted(uncommitted))
        warnings.warn(
            'skipping uncommitted (torn) sharded checkpoint staging '
            'dir(s) %s under %r — a save was killed before its commit '
            'rename; restoring from the newest COMMITTED serial'
            % (sorted(uncommitted), base_dir), RuntimeWarning)
    if not steps:
        raise RuntimeError('no committed %r serials under %r%s'
                           % (prefix, base_dir,
                              ' (only uncommitted staging dirs %s)'
                              % sorted(uncommitted) if uncommitted else ''))
    tried = []
    for step in sorted(steps, reverse=True):
        ckpt_dir = os.path.join(base_dir, '%s%d' % (prefix, step))
        problems = verify_sharded(ckpt_dir)
        if not problems:
            try:
                # verify_sharded just hashed every shard; don't re-CRC
                # each file during the load (size/readability still check)
                return load_sharded(ckpt_dir, mesh=mesh, verify_crc=False)
            except (RuntimeError, OSError, ValueError, KeyError,
                    TypeError) as e:
                # a structurally-torn manifest (missing 'shape'/'spec'
                # fields) raises Key/Type/ValueError past verify_sharded's
                # integrity checks — still fall back, loudly, like the
                # Trainer's serial loop does
                problems = ['%s: %s' % (type(e).__name__, e)]
        tried.append((step, problems))
        obs.counter('checkpoint.serial_fallbacks').inc()
        obs.event('checkpoint.serial_fallback', serial=step,
                  problems=len(problems), first=str(problems[0])[:200])
        warnings.warn(
            'sharded checkpoint serial %d at %r FAILED verification '
            '(%s) — falling back to the previous serial'
            % (step, ckpt_dir, '; '.join(problems[:3])), RuntimeWarning)
    raise RuntimeError(
        'no intact sharded checkpoint under %r: %s'
        % (base_dir, '; '.join('serial %d: %s' % (s, p[0])
                               for s, p in tried)))


def load_sharded(ckpt_dir, mesh=None, verify_crc=True):
    """Restore {name: jax.Array} with the saved shardings.

    mesh: the Mesh to restore onto; None re-creates one per-array from the
    manifest's (mesh_axes, mesh_shape) over jax.devices(). Returns
    (arrays, meta) where meta has 'step' and 'extra'. verify_crc=False
    skips the per-shard content CRC (size/readability still checked) —
    for callers that just ran verify_sharded over the same dir.

    Reshard-on-restore (docs/robustness.md#elastic): when `mesh` differs
    from the mesh an array was SAVED on (fewer/more devices after an
    elastic restart), each requested shard region is assembled from the
    overlapping saved shard files — no host ever materializes the full
    array. Spec axes absent from the target mesh replicate that dim (with
    a warning); `restorable()` is the static pre-check.
    """
    with obs.span('checkpoint.load_sharded',
                  dir=os.path.basename(ckpt_dir)):
        return _load_sharded_impl(ckpt_dir, mesh, verify_crc)


def _mesh_desc(axes, shape):
    return ','.join('%s=%d' % (a, s) for a, s in zip(axes, shape))


def _load_sharded_impl(ckpt_dir, mesh, verify_crc):
    import jax
    from jax.sharding import Mesh, NamedSharding

    manifest = _merged_manifest(ckpt_dir)

    # reshard-on-restore accounting: arrays whose saved mesh geometry
    # differs from the target mesh get reassembled below; the span makes
    # that visible (from/to shapes) instead of silent per-array work
    resharded = []
    if mesh is not None:
        tgt = (tuple(str(a) for a in mesh.axis_names),
               tuple(int(s) for s in mesh.devices.shape))
        for name, entry in manifest.get('arrays', {}).items():
            if 'spec' not in entry:
                continue
            src = (tuple(entry.get('mesh_axes', ())),
                   tuple(entry.get('mesh_shape', ())))
            if src != tgt:
                resharded.append((name, src))
    if resharded:
        src = resharded[0][1]
        with obs.span('checkpoint.reshard', arrays=len(resharded),
                      dir=os.path.basename(ckpt_dir),
                      from_mesh=_mesh_desc(*src),
                      to_mesh=_mesh_desc(*tgt)):
            return _load_arrays(ckpt_dir, manifest, mesh, verify_crc)
    return _load_arrays(ckpt_dir, manifest, mesh, verify_crc)


def _spec_for_mesh(spec, mesh, name):
    """Drop spec axes the target mesh does not have (those dims restore
    replicated) — the elastic case of restoring onto a mesh with a
    different axis set; loud, because the layout changes."""
    missing = set()
    out = []
    for e in tuple(spec):
        axes = e if isinstance(e, tuple) else ((e,) if e else ())
        keep = tuple(a for a in axes if a in mesh.shape)
        missing.update(a for a in axes if a not in mesh.shape)
        out.append(keep if len(keep) > 1 else (keep[0] if keep else None))
    if missing:
        import warnings
        from jax.sharding import PartitionSpec as P
        warnings.warn(
            'sharded checkpoint array %r: saved sharding axes %s are not '
            'on the restore mesh %r — those dims restore replicated'
            % (name, sorted(missing), dict(mesh.shape)), RuntimeWarning)
        return P(*out)
    return spec


def _load_arrays(ckpt_dir, manifest, mesh, verify_crc):
    import jax
    from jax.sharding import Mesh, NamedSharding

    mesh_cache = {}

    def get_mesh(axes, shape):
        if mesh is not None:
            return mesh
        key = (tuple(axes), tuple(shape))
        if key not in mesh_cache:
            n = int(np.prod(shape)) if shape else 1
            devs = np.asarray(jax.devices()[:n]).reshape(shape)
            mesh_cache[key] = Mesh(devs, tuple(axes))
        return mesh_cache[key]

    out = {}
    for name, entry in manifest['arrays'].items():
        shape = tuple(entry['shape'])
        dtype = entry['dtype']
        shard_map = {}
        for sh in entry['shards']:
            key = tuple((s, t) for s, t in zip(sh['start'], sh['stop']))
            shard_map[key] = sh

        def cb(index, _shape=shape, _smap=shard_map, _dtype=dtype):
            key = _index_key(index, _shape)
            if key in _smap:
                return _load_shard(ckpt_dir, _smap[key],
                                   verify_crc=verify_crc).astype(_dtype)
            # Restoring onto a different mesh/spec: assemble the requested
            # region from the overlapping saved shards (elastic restore).
            region = np.empty([t - s for s, t in key], dtype=_dtype)
            covered = np.zeros(region.shape, dtype=bool)
            for skey, sh in _smap.items():
                lo = [max(a[0], b[0]) for a, b in zip(key, skey)]
                hi = [min(a[1], b[1]) for a, b in zip(key, skey)]
                if any(l >= h for l, h in zip(lo, hi)):
                    continue
                data = _load_shard(ckpt_dir, sh, verify_crc=verify_crc)
                src = tuple(slice(l - b[0], h - b[0])
                            for l, h, b in zip(lo, hi, skey))
                dst = tuple(slice(l - a[0], h - a[0])
                            for l, h, a in zip(lo, hi, key))
                region[dst] = data[src]
                covered[dst] = True
            if not covered.all():
                raise RuntimeError(
                    "sharded checkpoint %s: saved shards do not cover "
                    "region %s of %r (missing/overwritten shard file?)"
                    % (ckpt_dir, key, _shape))
            return region.astype(_dtype)

        if 'spec' in entry:
            m = get_mesh(entry['mesh_axes'], entry['mesh_shape'])
            spec = _spec_from_json(entry['spec'])
            if mesh is not None:
                spec = _spec_for_mesh(spec, m, name)
            sharding = NamedSharding(m, spec)
        else:
            sharding = jax.sharding.SingleDeviceSharding(jax.devices()[0])
        if shape == ():
            # scalars: trivial single shard
            out[name] = jax.device_put(cb(()), sharding)
        else:
            out[name] = jax.make_array_from_callback(shape, sharding, cb)
    return out, {'step': manifest['step'], 'extra': manifest.get('extra', {})}


def restorable(src, mesh_axes):
    """Static reshard-on-restore check: can the checkpoint described by
    `src` (a merged-manifest dict, or a committed sharded checkpoint dir)
    restore cleanly onto a deployment mesh of `mesh_axes` ({'dp': 4} or
    [(name, size), ...] ordered pairs)?

    Returns a list of human-readable problems — empty means every array
    restores cleanly. Checked per array, without reading any shard
    payload: (a) the saved replica-0 shards cover the full array (their
    volumes sum to the array's — save_sharded writes disjoint shards, so
    a gap means a deleted/never-written file); (b) every saved sharding
    axis exists on the target mesh (a dropped axis restores that dim
    REPLICATED — legal but layout-changing, so it is reported); (c) each
    sharded dim tiles over its target axis product (mirroring the
    analysis ShardingUntileable posture). Wired into
    `tools/program_lint.py --mesh ... --checkpoint DIR` so an elastic
    restart can be validated before any device is touched."""
    manifest = src if isinstance(src, dict) else _merged_manifest(src)
    axes = dict(mesh_axes)
    problems = []
    for name, entry in sorted(manifest.get('arrays', {}).items()):
        shape = entry.get('shape')
        if shape is None:
            problems.append('%s: manifest entry records no shape' % name)
            continue
        shape = tuple(int(s) for s in shape)
        total = int(np.prod(shape)) if shape else 1
        covered = 0
        try:
            for sh in entry.get('shards', []):
                covered += int(np.prod(
                    [int(t) - int(s)
                     for s, t in zip(sh['start'], sh['stop'])]
                    or [1]))
        except (KeyError, TypeError, ValueError) as e:
            problems.append('%s: malformed shard entry (%r)' % (name, e))
            continue
        if covered != total:
            problems.append(
                '%s: saved shards cover %d of %d elements — a shard '
                'file is missing from the manifest (torn or pruned '
                'save?)' % (name, covered, total))
        spec = entry.get('spec')
        if not spec:
            continue  # replicated / single-device: restores anywhere
        for dim, e in zip(shape, spec):
            entry_axes = e if isinstance(e, list) else ([e] if e else [])
            prod = 1
            for a in entry_axes:
                if a not in axes:
                    problems.append(
                        '%s: sharding axis %r is not on the target mesh '
                        '%s — the dim would restore replicated'
                        % (name, a, axes))
                else:
                    prod *= int(axes[a])
            if prod > 1 and dim % prod:
                problems.append(
                    '%s: dim of size %d does not tile over the target '
                    'axis product %s=%d' % (name, dim, entry_axes, prod))
    return problems


def latest_step(base_dir, prefix='sharded_'):
    """Largest <prefix><step> subdir with a manifest, or None."""
    if not os.path.isdir(base_dir):
        return None
    best = None
    for d in os.listdir(base_dir):
        if not d.startswith(prefix):
            continue
        try:
            step = int(d[len(prefix):])
        except ValueError:
            continue
        if os.path.exists(os.path.join(base_dir, d, _MANIFEST)):
            best = step if best is None else max(best, step)
    return best
