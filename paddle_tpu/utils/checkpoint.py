"""Sharded (multi-host-safe) checkpointing for mesh-placed train state.

Parity: the reference persists per-var files through C++ save/load ops and
the trainer checkpoint dirs (reference python/paddle/fluid/io.py:468-690,
trainer.py:641 save_checkpoint). TPU-first redesign: arrays live sharded
over a jax.sharding.Mesh; gathering them to one host to .npz them would
need full-model host RAM and a cross-host transfer. Instead every process
writes only ITS addressable shards (replica 0 of each), with a manifest
recording shape/dtype/PartitionSpec per array; restore rebuilds each
jax.Array shard-by-shard via make_array_from_callback, so no host ever
materializes the full array and shardings round-trip exactly.

Format:
  <dir>/manifest.json                  process 0's view: {step, arrays}
  <dir>/manifest.p<i>.json             per-process shard listings (i > 0)
  <dir>/<escaped-name>.p<i>.shard<k>.npy   one file per distinct shard
Every process writes its own files (no filename collisions); the loader
merges all per-process manifests, so shards owned by other hosts are found
without any cross-host coordination at save time.
"""
import json
import os
import re
import threading

import numpy as np

__all__ = ['save_sharded', 'save_sharded_async', 'load_sharded',
           'latest_step', 'AsyncSave']

_MANIFEST = 'manifest.json'
# dirs with an async save in flight: overlapping saves to one dir would
# interleave identically-named shard files, so the second save raises
_INFLIGHT_DIRS = set()
_INFLIGHT_LOCK = threading.Lock()


def _escape(name):
    return re.sub(r'[^A-Za-z0-9_.@-]', '_', name)


def _spec_to_json(spec):
    out = []
    for e in tuple(spec):
        if e is None:
            out.append(None)
        elif isinstance(e, (tuple, list)):
            out.append(list(e))
        else:
            out.append(str(e))
    return out


def _spec_from_json(js):
    from jax.sharding import PartitionSpec as P
    return P(*[tuple(e) if isinstance(e, list) else e for e in js])


def _index_key(index, shape):
    """Normalize a tuple-of-slices shard index to a hashable start/stop list."""
    out = []
    for sl, n in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = n if sl.stop is None else int(sl.stop)
        out.append((start, stop))
    return tuple(out)


def _collect_shards(arrays, step, extra_meta, sink=None):
    """Walk replica-0 shards, build the manifest skeleton, and hand each
    shard to `sink(fname, host_array, shard_entry)`. With the default
    deferred sink, every shard is COPIED to host memory (copy=True — on
    the CPU backend np.asarray can be a zero-copy view of the device
    buffer, which a donating next step would clobber under the writer
    thread) and returned in `writes` for a background writer. A
    direct-write sink (the sync path) streams each shard to disk
    immediately instead, so peak host memory stays one shard, not the
    whole checkpoint."""
    import jax
    from jax.sharding import NamedSharding

    proc = jax.process_index()
    manifest = {'step': int(step), 'format': 'paddle_tpu-sharded-v1',
                'process': proc, 'extra': extra_meta or {}, 'arrays': {}}
    writes = []
    if sink is None:
        def sink(fname, shard_data, sh):
            writes.append((fname, np.array(shard_data, copy=True), sh))
    for name, arr in arrays.items():
        arr = arr if isinstance(arr, jax.Array) else jax.numpy.asarray(arr)
        sharding = arr.sharding
        entry = {'shape': list(arr.shape), 'dtype': str(arr.dtype),
                 'shards': []}
        if isinstance(sharding, NamedSharding):
            entry['mesh_axes'] = [str(a) for a in sharding.mesh.axis_names]
            entry['mesh_shape'] = [int(s) for s in sharding.mesh.devices.shape]
            entry['spec'] = _spec_to_json(sharding.spec)
        seen = set()
        base = _escape(name)
        for shard in arr.addressable_shards:
            if shard.replica_id != 0:
                continue  # some other shard/host owns this piece
            key = _index_key(shard.index, arr.shape)
            if key in seen:
                continue
            seen.add(key)
            fname = '%s.p%d.shard%d.npy' % (base, proc, len(entry['shards']))
            sh = {'file': fname, 'bytes': None,
                  'start': [k[0] for k in key],
                  'stop': [k[1] for k in key]}
            sink(fname, shard.data, sh)
            entry['shards'].append(sh)
        manifest['arrays'][name] = entry
    return manifest, writes


def _write_manifest(ckpt_dir, manifest):
    """ATOMICALLY LAST — a crash mid-save leaves either no manifest (save
    never happened) or byte counts that expose any truncated shard to
    _load_shard's corruption check."""
    proc = manifest['process']
    fname = _MANIFEST if proc == 0 else 'manifest.p%d.json' % proc
    tmp = os.path.join(ckpt_dir, fname + '.tmp')
    with open(tmp, 'w') as f:
        json.dump(manifest, f)
    os.replace(tmp, os.path.join(ckpt_dir, fname))
    return ckpt_dir


def _write_all(ckpt_dir, manifest, writes):
    """Deferred writer (async path): shard files first, manifest last."""
    os.makedirs(ckpt_dir, exist_ok=True)
    for fname, data, sh in writes:
        fpath = os.path.join(ckpt_dir, fname)
        np.save(fpath, data)
        sh['bytes'] = os.path.getsize(fpath)
    return _write_manifest(ckpt_dir, manifest)


def save_sharded(ckpt_dir, arrays, step=0, extra_meta=None):
    """Save {name: jax.Array} without gathering: each process writes the
    replica-0 shards it can address (filenames carry the process index, so
    hosts never collide) and its own manifest listing exactly those shards;
    the loader merges all manifests. Shards stream to disk one at a time
    (no whole-checkpoint host copy); the manifest commits last."""
    key = os.path.abspath(ckpt_dir)
    with _INFLIGHT_LOCK:
        if key in _INFLIGHT_DIRS:
            raise RuntimeError(
                'a save to %r is still in flight — overlapping saves '
                'would interleave identically-named shard files; wait() '
                'on the async handle (or let the sync save finish) first'
                % ckpt_dir)
        _INFLIGHT_DIRS.add(key)
    try:
        os.makedirs(ckpt_dir, exist_ok=True)

        def sink(fname, shard_data, sh):
            fpath = os.path.join(ckpt_dir, fname)
            np.save(fpath, np.asarray(shard_data))
            sh['bytes'] = os.path.getsize(fpath)

        manifest, _ = _collect_shards(arrays, step, extra_meta, sink=sink)
        return _write_manifest(ckpt_dir, manifest)
    finally:
        with _INFLIGHT_LOCK:
            _INFLIGHT_DIRS.discard(key)


class AsyncSave(object):
    """Handle for an in-flight save_sharded_async, wrapping the writer
    Future: wait() blocks and re-raises any IO error with its original
    traceback; done() polls."""

    def __init__(self, future, ckpt_dir):
        self._future = future
        self.ckpt_dir = ckpt_dir
        self._observed = False
        # a caller that never wait()s (or crashes first) must still learn
        # the checkpoint is missing/partial: surface unobserved failures
        future.add_done_callback(self._warn_unobserved)

    def _warn_unobserved(self, future):
        if self._observed:
            return
        exc = future.exception()
        if exc is not None:
            import warnings
            warnings.warn(
                'async sharded checkpoint to %r FAILED in the background '
                '(%r) — the checkpoint is missing or partial; call '
                '.wait() to re-raise with the full traceback'
                % (self.ckpt_dir, exc), RuntimeWarning)

    def done(self):
        return self._future.done()

    def wait(self, timeout=None):
        self._observed = True
        try:
            return self._future.result(timeout=timeout)
        except TimeoutError:
            self._observed = False  # the write is still in flight
            raise


def save_sharded_async(ckpt_dir, arrays, step=0, extra_meta=None):
    """save_sharded with the file IO off the critical path: device->host
    shard COPIES happen synchronously (so the caller may immediately
    donate/overwrite the device buffers — the next train step overlaps
    the disk write), then a background thread writes files and commits
    the manifest last. Returns an AsyncSave handle; call .wait() before
    relying on the checkpoint, and before issuing another save to the
    SAME directory (overlapping saves to one dir would interleave
    identically-named files — nothing serializes them for you). No orbax
    dependency — the format is identical to save_sharded's, so
    load_sharded reads both."""
    from concurrent.futures import ThreadPoolExecutor

    key = os.path.abspath(ckpt_dir)
    with _INFLIGHT_LOCK:
        if key in _INFLIGHT_DIRS:
            raise RuntimeError(
                'an async save to %r is still in flight — overlapping '
                'saves to one directory would interleave identically-'
                'named shard files; wait() on the previous handle first'
                % ckpt_dir)
        _INFLIGHT_DIRS.add(key)

    try:
        manifest, writes = _collect_shards(arrays, step, extra_meta)
        pool = ThreadPoolExecutor(max_workers=1,
                                  thread_name_prefix='paddle-tpu-async-ckpt')
        future = pool.submit(_write_all, ckpt_dir, manifest, writes)
    except BaseException:
        with _INFLIGHT_LOCK:
            _INFLIGHT_DIRS.discard(key)
        raise
    pool.shutdown(wait=False)  # lets the worker finish; nothing else queues

    def _clear_inflight(_):
        with _INFLIGHT_LOCK:
            _INFLIGHT_DIRS.discard(key)
    future.add_done_callback(_clear_inflight)
    return AsyncSave(future, ckpt_dir)


def _load_shard(ckpt_dir, sh):
    """np.load with corruption detection: a missing or size-mismatched
    (truncated / partially-written) shard file raises a RuntimeError naming
    the file instead of a cryptic numpy parse error (reference io.py's
    load_persistables raises per-var on missing files the same way)."""
    path = os.path.join(ckpt_dir, sh['file'] if isinstance(sh, dict) else sh)
    meta = sh if isinstance(sh, dict) else {}
    if not os.path.exists(path):
        raise RuntimeError(
            'sharded checkpoint shard %r is missing (deleted or never '
            'fully written)' % path)
    want = meta.get('bytes')
    if want is not None and os.path.getsize(path) != want:
        raise RuntimeError(
            'sharded checkpoint shard %r is corrupt: %d bytes on disk, '
            'manifest recorded %d (truncated write?)'
            % (path, os.path.getsize(path), want))
    try:
        return np.load(path)
    except Exception as e:
        raise RuntimeError(
            'sharded checkpoint shard %r is unreadable: %r' % (path, e))


def load_sharded(ckpt_dir, mesh=None):
    """Restore {name: jax.Array} with the saved shardings.

    mesh: the Mesh to restore onto; None re-creates one per-array from the
    manifest's (mesh_axes, mesh_shape) over jax.devices(). Returns
    (arrays, meta) where meta has 'step' and 'extra'.
    """
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    with open(os.path.join(ckpt_dir, _MANIFEST)) as f:
        manifest = json.load(f)
    # merge other hosts' shard listings into the arrays table
    for d in sorted(os.listdir(ckpt_dir)):
        if re.fullmatch(r'manifest\.p\d+\.json', d):
            with open(os.path.join(ckpt_dir, d)) as f:
                part = json.load(f)
            for name, entry in part.get('arrays', {}).items():
                if name in manifest['arrays']:
                    manifest['arrays'][name]['shards'].extend(entry['shards'])
                else:
                    manifest['arrays'][name] = entry

    mesh_cache = {}

    def get_mesh(axes, shape):
        if mesh is not None:
            return mesh
        key = (tuple(axes), tuple(shape))
        if key not in mesh_cache:
            n = int(np.prod(shape)) if shape else 1
            devs = np.asarray(jax.devices()[:n]).reshape(shape)
            mesh_cache[key] = Mesh(devs, tuple(axes))
        return mesh_cache[key]

    out = {}
    for name, entry in manifest['arrays'].items():
        shape = tuple(entry['shape'])
        dtype = entry['dtype']
        shard_map = {}
        for sh in entry['shards']:
            key = tuple((s, t) for s, t in zip(sh['start'], sh['stop']))
            shard_map[key] = sh

        def cb(index, _shape=shape, _smap=shard_map, _dtype=dtype):
            key = _index_key(index, _shape)
            if key in _smap:
                return _load_shard(ckpt_dir, _smap[key]).astype(_dtype)
            # Restoring onto a different mesh/spec: assemble the requested
            # region from the overlapping saved shards (elastic restore).
            region = np.empty([t - s for s, t in key], dtype=_dtype)
            covered = np.zeros(region.shape, dtype=bool)
            for skey, sh in _smap.items():
                lo = [max(a[0], b[0]) for a, b in zip(key, skey)]
                hi = [min(a[1], b[1]) for a, b in zip(key, skey)]
                if any(l >= h for l, h in zip(lo, hi)):
                    continue
                data = _load_shard(ckpt_dir, sh)
                src = tuple(slice(l - b[0], h - b[0])
                            for l, h, b in zip(lo, hi, skey))
                dst = tuple(slice(l - a[0], h - a[0])
                            for l, h, a in zip(lo, hi, key))
                region[dst] = data[src]
                covered[dst] = True
            if not covered.all():
                raise RuntimeError(
                    "sharded checkpoint %s: saved shards do not cover "
                    "region %s of %r (missing/overwritten shard file?)"
                    % (ckpt_dir, key, _shape))
            return region.astype(_dtype)

        if 'spec' in entry:
            m = get_mesh(entry['mesh_axes'], entry['mesh_shape'])
            sharding = NamedSharding(m, _spec_from_json(entry['spec']))
        else:
            sharding = jax.sharding.SingleDeviceSharding(jax.devices()[0])
        if shape == ():
            # scalars: trivial single shard
            out[name] = jax.device_put(cb(()), sharding)
        else:
            out[name] = jax.make_array_from_callback(shape, sharding, cb)
    return out, {'step': manifest['step'], 'extra': manifest.get('extra', {})}


def latest_step(base_dir, prefix='sharded_'):
    """Largest <prefix><step> subdir with a manifest, or None."""
    if not os.path.isdir(base_dir):
        return None
    best = None
    for d in os.listdir(base_dir):
        if not d.startswith(prefix):
            continue
        try:
            step = int(d[len(prefix):])
        except ValueError:
            continue
        if os.path.exists(os.path.join(base_dir, d, _MANIFEST)):
            best = step if best is None else max(best, step)
    return best
