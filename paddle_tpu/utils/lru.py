"""Refcounted LRU bookkeeping, shared by every resident-until-evicted map.

Two subsystems keep the same invariant with the same data structure: a
key stays RESIDENT after its last user lets go (that residency is the
whole point — the next user hits), a live reference pins it against
eviction, and pressure reclaims the least-recently-used unreferenced
entry. `serving.pages.PrefixCache` pins resident encoder pages this way
(refs = slots currently decoding against the prefix) and
`streaming.vocab.VocabTable` pins embedding rows (refs = in-flight
training batches whose sparse gradient will still write the row —
evicting one of those would tear the update). Both ride this class; the
paged decode drills and the streaming drills pin the shared behavior.

The structure is a single OrderedDict: insertion/touch order IS the
recency order (move_to_end on touch, eviction scans from the front), so
there is no separate clock to drift out of sync — the lesson of the
PrefixCache tick-bookkeeping removal (PR 11 review). Not thread-safe;
callers own their locking.
"""
import collections

__all__ = ['RefCountedLRU']


class _Entry(object):
    __slots__ = ('value', 'refs')

    def __init__(self, value, refs):
        self.value = value
        self.refs = refs


class RefCountedLRU(object):
    """key -> (value, refs) with LRU eviction of refs==0 entries."""

    def __init__(self):
        self._entries = collections.OrderedDict()

    def __len__(self):
        return len(self._entries)

    def __contains__(self, key):
        return key in self._entries

    def get(self, key):
        """The entry's value (None when absent). No recency or refcount
        side effects — the peek/probe read."""
        e = self._entries.get(key)
        return None if e is None else e.value

    def refs(self, key):
        e = self._entries.get(key)
        return 0 if e is None else e.refs

    def insert(self, key, value, refs=0):
        """Insert a NEW entry (most-recent position). Raises on a
        duplicate key — the callers' duplicate policies differ (keep
        first copy vs error), so they decide before inserting."""
        if key in self._entries:
            raise KeyError('duplicate LRU key %r' % (key,))
        self._entries[key] = _Entry(value, int(refs))

    def touch(self, key):
        """Mark `key` most recently used."""
        self._entries.move_to_end(key)

    def ref(self, key):
        """Pin: one more live user. Pinned entries are never evicted."""
        self._entries[key].refs += 1

    def unref(self, key):
        """One user let go; the entry STAYS resident (floor at 0 — a
        stray double-unref must not un-pin somebody else's reference).
        Missing keys are tolerated: the entry may have been pop()'d by
        an explicit eviction between ref and unref."""
        e = self._entries.get(key)
        if e is not None and e.refs > 0:
            e.refs -= 1

    def pop(self, key):
        """Remove `key` unconditionally, returning its value."""
        return self._entries.pop(key).value

    def evict_one(self):
        """Evict the least-recently-used UNREFERENCED entry. Returns
        (key, value), or None when everything resident is pinned."""
        victim = None
        for key, e in self._entries.items():   # front = least recent
            if e.refs == 0:
                victim = key
                break
        if victim is None:
            return None
        return victim, self._entries.pop(victim).value

    def evictable(self, weigh=None):
        """Total weight of evictable (refs==0) entries; `weigh(value)`
        defaults to 1 per entry."""
        if weigh is None:
            return sum(1 for e in self._entries.values() if e.refs == 0)
        return sum(weigh(e.value) for e in self._entries.values()
                   if e.refs == 0)

    def items(self):
        """(key, value) pairs in recency order (least recent first)."""
        return [(k, e.value) for k, e in self._entries.items()]
