"""Retry/backoff primitives for transient-failure tolerance.

Shared by dataset downloads (dataset/common.py:download), checkpoint shard
I/O (utils/checkpoint.py), and the reader fault-tolerance decorator
(paddle_tpu.reader.fault_tolerant). One implementation so every retry in
the codebase has the same shape: bounded attempts, exponential backoff
with DETERMINISTIC (seedable) jitter, and an optional wall-clock deadline
— a long-running training job must never spin forever on a dead
filesystem, and a seeded fault-injection test must see the exact same
retry schedule on every run.
"""
import random
import time

__all__ = ['RetryError', 'backoff_delays', 'retry_call', 'retrying']


class RetryError(RuntimeError):
    """All attempts failed (or the deadline expired). `last_exception`
    carries the final underlying error; it is also chained as __cause__."""

    def __init__(self, message, last_exception=None, attempts=0):
        super(RetryError, self).__init__(message)
        self.last_exception = last_exception
        self.attempts = attempts


def backoff_delays(retries, base_delay=0.1, factor=2.0, max_delay=30.0,
                   jitter=0.5, seed=None):
    """Yield `retries` sleep durations: base * factor**i, capped at
    max_delay, each multiplied by a jitter factor drawn uniformly from
    [1 - jitter, 1 + jitter]. With a seed the sequence is reproducible
    (the fault-injection tests assert on it)."""
    if not 0.0 <= jitter <= 1.0:
        raise ValueError('jitter must be in [0, 1], got %r' % (jitter,))
    rng = random.Random(seed)
    for i in range(retries):
        d = min(base_delay * (factor ** i), max_delay)
        if jitter:
            d *= 1.0 + jitter * (2.0 * rng.random() - 1.0)
        yield max(d, 0.0)


def _obs():
    # Lazy: the success path pays nothing, and utils.retry stays
    # importable standalone (paddle_tpu.obs is stdlib-only by contract).
    from .. import obs
    return obs


def retry_call(fn, args=(), kwargs=None, retries=3, base_delay=0.1,
               factor=2.0, max_delay=30.0, jitter=0.5, deadline=None,
               retry_on=(OSError, IOError), seed=None, sleep=time.sleep,
               on_retry=None, describe=None, site=None):
    """Call fn(*args, **kwargs), retrying on `retry_on` exceptions.

    retries:   additional attempts after the first (so retries=3 means at
               most 4 calls).
    deadline:  wall-clock budget in seconds measured from the first call;
               once spent, no further attempt is made and RetryError
               raises immediately (a bounded-time guarantee the backoff
               schedule alone cannot give).
    sleep:     injectable for tests (the fault suite passes a recorder so
               no real time is spent).
    on_retry:  on_retry(attempt_index, exception, delay) observer hook.
    site:      LOW-CARDINALITY call-site tag for telemetry — the
               retry.attempts / retry.backoff.seconds /
               retry.deadline_exceeded / retry.exhausted counters are
               labeled with it (docs/observability.md). Unlike
               `describe`, which may embed paths, `site` must be a stable
               name like 'checkpoint.write_shard'. Defaults to the
               callable's __name__.
    Raises RetryError (chaining the last exception) when attempts or the
    deadline are exhausted. Non-retryable exceptions propagate untouched.
    A first-try success records no telemetry at all.
    """
    kwargs = kwargs or {}
    t0 = time.monotonic()
    delays = backoff_delays(retries, base_delay=base_delay, factor=factor,
                            max_delay=max_delay, jitter=jitter, seed=seed)
    last = None
    attempts = 0
    site = site or getattr(fn, '__name__', 'call')
    for attempt in range(retries + 1):
        try:
            return fn(*args, **kwargs)
        except retry_on as e:
            last = e
            attempts = attempt + 1
            delay = next(delays, None)
            if delay is None:
                break
            if deadline is not None \
                    and time.monotonic() - t0 + delay > deadline:
                obs = _obs()
                obs.counter('retry.deadline_exceeded', site=site).inc()
                obs.event('retry.deadline_exceeded', site=site,
                          attempts=attempts, deadline_s=deadline,
                          error=repr(e))
                raise RetryError(
                    '%s: deadline of %.3fs would be exceeded after %d '
                    'attempt(s): %r'
                    % (describe or getattr(fn, '__name__', 'call'),
                       deadline, attempts, e),
                    last_exception=e, attempts=attempts) from e
            obs = _obs()
            obs.counter('retry.attempts', site=site).inc()
            obs.counter('retry.backoff.seconds', site=site).inc(delay)
            obs.event('retry.attempt', site=site, attempt=attempt,
                      delay_s=delay, error=repr(e))
            if on_retry is not None:
                on_retry(attempt, e, delay)
            sleep(delay)
    obs = _obs()
    obs.counter('retry.exhausted', site=site).inc()
    obs.event('retry.exhausted', site=site, attempts=attempts,
              error=repr(last))
    raise RetryError(
        '%s: all %d attempt(s) failed: %r'
        % (describe or getattr(fn, '__name__', 'call'), attempts, last),
        last_exception=last, attempts=attempts) from last


def retrying(**cfg):
    """Decorator form of retry_call:

        @retrying(retries=5, retry_on=(IOError,), seed=0)
        def fetch(...): ...
    """
    def deco(fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            return retry_call(fn, args=args, kwargs=kwargs, **cfg)
        return wrapper
    return deco
