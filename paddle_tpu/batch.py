"""paddle.batch. Parity: reference python/paddle/batch.py."""

__all__ = ['batch']


def batch(reader, batch_size, drop_last=False):
    """Create a batched reader from a sample-level reader."""

    def batch_reader():
        r = reader()
        b = []
        for instance in r:
            b.append(instance)
            if len(b) == batch_size:
                yield b
                b = []
        if drop_last is False and len(b) != 0:
            yield b

    return batch_reader
