"""Inference deployment runtime.

Parity: reference paddle/fluid/inference + paddle/capi (load a saved
inference model and execute it without the training framework). TPU-first
there are two artifacts:

1. A program bundle (fluid.io.save_inference_model: JSON ProgramDesc +
   persistables) loaded by `Predictor` — the fluid-level path, runs through
   the normal Executor lowering with the jit cache.
2. A compiler-level artifact: `export_compiled` lowers the pruned program
   to a serialized StableHLO module via jax.export — load with
   `load_compiled` and call with no framework at all (the reference's
   C-API / inference-library equivalent; the artifact is
   compiler-portable across hosts with the same jax version).
"""
import os

import numpy as np

__all__ = ['Predictor', 'export_compiled', 'load_compiled']

_ARTIFACT = '__model__.stablehlo'
_META = '__model__.meta.json'


class Predictor(object):
    """Load + run a saved inference model (reference: NativePaddlePredictor,
    inference/api/api_impl.cc)."""

    def __init__(self, dirname, place=None):
        from ..fluid import core, io
        from ..fluid.executor import Executor, Scope, scope_guard
        self._scope = Scope()
        self._place = place or (core.TPUPlace(0) if core.is_compiled_with_tpu()
                                else core.CPUPlace())
        self._exe = Executor(self._place)
        with scope_guard(self._scope):
            prog, feeds, fetches = io.load_inference_model(dirname, self._exe)
        self._program = prog
        self.feed_names = feeds
        self._fetch_vars = fetches

    @property
    def fetch_names(self):
        return [v.name for v in self._fetch_vars]

    def run(self, feed):
        """feed: dict name -> ndarray/LoDTensor. Returns list of ndarrays."""
        from ..fluid.executor import scope_guard
        with scope_guard(self._scope):
            return self._exe.run(self._program, feed=feed,
                                 fetch_list=self._fetch_vars)


def export_compiled(dirname, feed_example, target_vars, executor,
                    main_program=None):
    """Lower the pruned inference graph to ONE serialized StableHLO module.

    feed_example: dict name -> example ndarray fixing shapes/dtypes (pass
    DENSE arrays; sequence (lod) inputs are exported with every row
    treated full-length — pad at inference time).
    Writes `__model__.stablehlo` (jax.export serialization, params baked
    in as constants) + a meta file; returns the artifact path.
    """
    import json

    import jax
    import jax.numpy as jnp

    from ..fluid import framework
    from ..fluid.executor import global_scope
    from ..fluid.lowering import SeqValue

    if main_program is None:
        main_program = framework.default_main_program()
    if not isinstance(target_vars, (list, tuple)):
        target_vars = [target_vars]
    fetch_names = [v.name if isinstance(v, framework.Variable) else str(v)
                   for v in target_vars]
    infer = main_program.clone(for_test=True).prune(target_vars)

    # run once through the executor to build+cache the pure step fn
    executor.run(infer, feed=dict(feed_example), fetch_list=fetch_names)
    compiled = None
    for k, c in executor._cache.items():
        pid, fetches = k[0], k[3]  # (uid, version, feed_sig, fetches, ...)
        if pid == infer._uid and tuple(fetches) == tuple(fetch_names):
            compiled = c
    assert compiled is not None
    scope = global_scope()
    persist = {n: scope.vars[n] for n in compiled.persist_in}
    feed_names = sorted(feed_example)

    # reproduce Executor.run's feed wrapping: lod-level vars were traced as
    # SeqValue(data, lengths) (dense feed = every row full-length)
    blk = infer.global_block()
    lod_feed = {n for n in feed_names
                if blk.vars.get(n) is not None and blk.vars[n].lod_level > 0}

    def fn(*arrays):
        feed = {}
        for n, a in zip(feed_names, arrays):
            var = blk.vars.get(n)
            if var is not None and var.dtype not in (str(a.dtype), 'bfloat16'):
                a = a.astype(np.dtype(var.dtype))
            if n in lod_feed:
                lens = jnp.full((a.shape[0],), a.shape[1], jnp.int32)
                feed[n] = SeqValue(a, lens)
            else:
                feed[n] = a
        fetches, _, _ = compiled._step(persist, feed, jax.random.key(0))
        return [f.data if isinstance(f, SeqValue) else f for f in fetches]

    args = [jnp.asarray(feed_example[n]) for n in feed_names]
    exported = jax.export.export(jax.jit(fn))(*args)
    os.makedirs(dirname, exist_ok=True)
    path = os.path.join(dirname, _ARTIFACT)
    with open(path, 'wb') as f:
        f.write(exported.serialize())
    with open(os.path.join(dirname, _META), 'w') as f:
        json.dump({'feed_names': feed_names, 'fetch_names': fetch_names,
                   'stablehlo': exported.mlir_module()[:10000]}, f)
    return path


def load_compiled(dirname):
    """Load an export_compiled artifact -> callable(feed dict) -> [np]."""
    import json

    import jax
    import jax.numpy as jnp

    with open(os.path.join(dirname, _ARTIFACT), 'rb') as f:
        exported = jax.export.deserialize(f.read())
    with open(os.path.join(dirname, _META)) as f:
        meta = json.load(f)
    feed_names = meta['feed_names']

    def run(feed):
        args = [jnp.asarray(np.asarray(feed[n])) for n in feed_names]
        out = exported.call(*args)
        return [np.asarray(o) for o in out]

    run.feed_names = feed_names
    run.fetch_names = meta['fetch_names']
    return run
