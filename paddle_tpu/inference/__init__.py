"""Inference deployment runtime.

Parity: reference paddle/fluid/inference + paddle/capi (load a saved
inference model and execute it without the training framework). TPU-first
there are two artifacts:

1. A program bundle (fluid.io.save_inference_model: JSON ProgramDesc +
   persistables) loaded by `Predictor` — the fluid-level path, runs through
   the normal Executor lowering with the jit cache.
2. A compiler-level artifact: `export_compiled` lowers the pruned program
   to a serialized StableHLO module via jax.export — load with
   `load_compiled` and call with no framework at all (the reference's
   C-API / inference-library equivalent; the artifact is
   compiler-portable across hosts with the same jax version).
"""
import os

import numpy as np

__all__ = ['Predictor', 'export_compiled', 'load_compiled']

_ARTIFACT = '__model__.stablehlo'
_META = '__model__.meta.json'


class Predictor(object):
    """Load + run a saved inference model (reference: NativePaddlePredictor,
    inference/api/api_impl.cc).

    Thread-safe: the model's variables live in a PRIVATE scope that is
    passed explicitly through `Executor.run(scope=...)` — never via the
    process-global `scope_guard`, which two predictors (or two threads
    on one predictor) would race on. The serving engine
    (paddle_tpu.serving) relies on this.

    `kernels`: the predictor-config surface of the pallas kernel knob
    (docs/perf.md#kernel-layer) — same grammar as the PADDLE_TPU_KERNELS
    env ('all', 'paged_attention', 'all,-sparse_adam', an iterable, a
    bool). Routes to `ops.kernels.configure()`; the enablement is
    process-level (the compile cache keys on it), and None leaves the
    env in charge."""

    def __init__(self, dirname, place=None, kernels=None):
        from ..fluid import core, io
        from ..fluid.executor import Executor, Scope
        if kernels is not None:
            from ..ops import kernels as kernels_mod
            kernels_mod.configure(kernels)
        self._scope = Scope()
        self._place = place or (core.TPUPlace(0) if core.is_compiled_with_tpu()
                                else core.CPUPlace())
        self._exe = Executor(self._place)
        prog, feeds, fetches = io.load_inference_model(dirname, self._exe,
                                                       scope=self._scope)
        # Ahead-of-lowering verification (PADDLE_TPU_VERIFY, docs/
        # analysis.md): a Predictor's program runs CONCURRENTLY against one
        # scope (multi-threaded run(), the serving engine), so a saved
        # artifact that still writes persistables is a scope race — reject
        # it at load time, not as corrupted params under load.
        from ..fluid import analysis
        analysis.maybe_verify(
            prog, where='predictor', feeds=list(feeds),
            fetches=[v.name for v in fetches], concurrent=True)
        self._program = prog
        self.feed_names = feeds
        self._fetch_vars = fetches

    @property
    def fetch_names(self):
        return [v.name for v in self._fetch_vars]

    @property
    def input_spec(self):
        """{feed name: (shape, dtype str)} from the loaded program; the
        leading batch dim is -1 (any). The serving engine's warmup uses
        this to build per-bucket feeds without an example."""
        blk = self._program.global_block()
        spec = {}
        for n in self.feed_names:
            v = blk.vars.get(n)
            if v is not None:
                spec[n] = (tuple(int(d) for d in v.shape), str(v.dtype))
        return spec

    def run(self, feed):
        """feed: dict name -> ndarray/LoDTensor. Returns list of ndarrays."""
        return self._exe.run(self._program, feed=feed,
                             fetch_list=self._fetch_vars, scope=self._scope)


def export_compiled(dirname, feed_example, target_vars, executor,
                    main_program=None):
    """Lower the pruned inference graph to ONE serialized StableHLO module.

    feed_example: dict name -> example ndarray fixing shapes/dtypes (pass
    DENSE arrays; sequence (lod) inputs are exported with every row
    treated full-length — pad at inference time).
    Writes `__model__.stablehlo` (jax.export serialization, params baked
    in as constants) + a meta file; returns the artifact path.
    """
    import json

    import jax
    import jax.numpy as jnp
    # jax>=0.4.30 ships export as a real submodule that must be imported
    # explicitly (the bare `jax.export` attribute was removed)
    from jax import export as jax_export

    from ..fluid import framework
    from ..fluid.executor import global_scope
    from ..fluid.lowering import SeqValue

    if main_program is None:
        main_program = framework.default_main_program()
    if not isinstance(target_vars, (list, tuple)):
        target_vars = [target_vars]
    fetch_names = [v.name if isinstance(v, framework.Variable) else str(v)
                   for v in target_vars]
    infer = main_program.clone(for_test=True).prune(target_vars)

    # run once through the executor to build+cache the pure step fn
    executor.run(infer, feed=dict(feed_example), fetch_list=fetch_names)
    compiled = None
    for k, c in executor._cache.items():
        pid, fetches = k[0], k[3]  # (uid, version, feed_sig, fetches, ...)
        if pid == infer._uid and tuple(fetches) == tuple(fetch_names):
            compiled = c
    assert compiled is not None
    scope = global_scope()
    persist = {n: scope.vars[n] for n in compiled.persist_in}
    feed_names = sorted(feed_example)

    # reproduce Executor.run's feed wrapping: lod-level vars were traced as
    # SeqValue(data, lengths) (dense feed = every row full-length)
    blk = infer.global_block()
    lod_feed = {n for n in feed_names
                if blk.vars.get(n) is not None and blk.vars[n].lod_level > 0}

    def fn(*arrays):
        feed = {}
        for n, a in zip(feed_names, arrays):
            var = blk.vars.get(n)
            if var is not None and var.dtype not in (str(a.dtype), 'bfloat16'):
                a = a.astype(np.dtype(var.dtype))
            if n in lod_feed:
                lens = jnp.full((a.shape[0],), a.shape[1], jnp.int32)
                feed[n] = SeqValue(a, lens)
            else:
                feed[n] = a
        fetches, _, _ = compiled._step(persist, feed, jax.random.key(0))
        return [f.data if isinstance(f, SeqValue) else f for f in fetches]

    args = [jnp.asarray(feed_example[n]) for n in feed_names]
    exported = jax_export.export(jax.jit(fn))(*args)
    os.makedirs(dirname, exist_ok=True)
    path = os.path.join(dirname, _ARTIFACT)
    with open(path, 'wb') as f:
        f.write(exported.serialize())
    # per-input shapes/dtypes AS EXPORTED (post jnp.asarray, so an int64
    # example records the int32 the x64-disabled module actually takes):
    # load_compiled validates feeds against these instead of letting jax
    # fail deep inside exported.call
    inputs = {n: {'shape': list(a.shape), 'dtype': str(a.dtype)}
              for n, a in zip(feed_names, args)}
    with open(os.path.join(dirname, _META), 'w') as f:
        json.dump({'feed_names': feed_names, 'fetch_names': fetch_names,
                   'inputs': inputs,
                   'stablehlo': exported.mlir_module()[:10000]}, f)
    return path


def load_compiled(dirname):
    """Load an export_compiled artifact -> callable(feed dict) -> [np].

    Feeds are validated against the per-input shapes/dtypes recorded in
    `__model__.meta.json` at export time: a missing/unknown name, a
    wrong shape (the exported module is FIXED-shape, batch dim
    included), or an unsafely-cast dtype raises a ValueError naming the
    offending input instead of failing deep inside `exported.call`.
    Artifacts exported before the meta carried `inputs` skip the
    shape/dtype checks."""
    import json

    import jax.numpy as jnp
    from jax import export as jax_export

    with open(os.path.join(dirname, _ARTIFACT), 'rb') as f:
        exported = jax_export.deserialize(f.read())
    with open(os.path.join(dirname, _META)) as f:
        meta = json.load(f)
    feed_names = meta['feed_names']
    inputs = meta.get('inputs') or {}

    def _validated(name, val):
        a = np.asarray(val)
        spec = inputs.get(name)
        if spec is None:
            return jnp.asarray(a)
        want_shape = tuple(spec['shape'])
        want_dtype = np.dtype(spec['dtype'])
        if a.dtype != want_dtype:
            # accept safe casts plus WITHIN-kind narrowing (int64->int32,
            # float64->float32: what jnp.asarray already applied silently
            # under disabled x64); reject kind-crossing unsafe casts
            # (int32 fed to a float32 input is a client bug worth naming)
            if np.can_cast(a.dtype, want_dtype, 'safe') or (
                    a.dtype.kind == want_dtype.kind
                    and np.can_cast(a.dtype, want_dtype, 'same_kind')):
                a = a.astype(want_dtype)
            else:
                raise ValueError(
                    'input %r: dtype %s cannot safely cast to the '
                    'exported dtype %s' % (name, a.dtype, want_dtype))
        if tuple(a.shape) != want_shape:
            raise ValueError(
                'input %r: shape %r does not match the exported shape %r '
                '(the compiled artifact is fixed-shape; pad/bucket the '
                'feed, e.g. via paddle_tpu.serving)'
                % (name, tuple(a.shape), want_shape))
        return jnp.asarray(a)

    def run(feed):
        missing = [n for n in feed_names if n not in feed]
        if missing:
            raise ValueError(
                'missing input(s) %r; the artifact expects exactly %r'
                % (missing, feed_names))
        extra = sorted(set(feed) - set(feed_names))
        if extra:
            raise ValueError(
                'unknown input(s) %r; the artifact expects exactly %r'
                % (extra, feed_names))
        args = [_validated(n, feed[n]) for n in feed_names]
        out = exported.call(*args)
        return [np.asarray(o) for o in out]

    run.feed_names = feed_names
    run.fetch_names = meta['fetch_names']
    run.input_spec = {n: (tuple(s['shape']), s['dtype'])
                      for n, s in inputs.items()}
    return run
