"""Local SGD — the TPU analogue of the reference's async training mode.

Reference transpiler/distribute_transpiler.py:185-206 (sync_mode=False,
wired into listen_and_serv at :281) lets every trainer push gradients and
pull parameters without a barrier: replicas advance on stale parameters and
updates mix asynchronously. That shape exists to hide slow-network latency
behind computation; inside one XLA module there is no lock-free parameter
server to talk to, and GSPMD's replicated parameters are bit-identical by
construction.

The honest TPU mapping is LOCAL SGD (post-local SGD): each dp replica owns
ITS OWN parameter copy (a leading replica axis sharded over dp), takes
`sync_steps` purely local optimizer steps — no cross-replica traffic at all
— then one `pmean` over ICI averages the copies. Statistically this is the
same regime async pserver training targets (replica divergence between
mixes, periodic consensus) with strictly cheaper communication.

Used directly (functional API), and pointed to by the Executor's loud
warning when a DistributeTranspiler program carries sync_mode=False.
"""
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

try:  # jax>=0.4.35 moved shard_map out of experimental
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map

__all__ = ['LocalSGD']


def _leaf_spec(x, axis):
    """Shard the leading (replica) axis; everything else stays local.
    0-d leaves (scalar step counts, temperatures) have no leading dim to
    split — they replicate to every replica."""
    if jnp.ndim(x) == 0:
        return P()
    return P(axis, *([None] * (jnp.ndim(x) - 1)))


class LocalSGD(object):
    """Drive per-replica optimizer steps with periodic parameter averaging.

    step_fn(params, batch) -> (new_params, aux) is the USER's purely local
    update (forward + grad + optimizer) written for ONE replica; params is
    any pytree. LocalSGD runs it under shard_map so each dp shard advances
    its own copy, and `sync` averages the copies with one collective.

        ls = LocalSGD(step_fn, mesh, axis='dp', sync_steps=4)
        params = ls.replicate(params)       # add + shard the replica axis
        for i, batch in enumerate(stream):
            params, aux = ls.step(params, batch)   # zero ICI traffic
            if (i + 1) % ls.sync_steps == 0:
                params = ls.sync(params)           # one pmean over ICI
        final = ls.collapse(params)         # consensus copy, replica axis

    sync_steps=1 degenerates to synchronous data-parallel (every step
    averages), matching the reference's sync_mode=True semantics.
    """

    def __init__(self, step_fn, mesh, axis='dp', sync_steps=1):
        self.mesh = mesh
        self.axis = axis
        self.sync_steps = int(sync_steps)
        self.n = mesh.shape[axis]
        ax = axis

        def local_body(params, batch):
            # shard_map hands each device its [1, ...] slice of the
            # replica axis; strip it, step locally, put it back
            p = jax.tree_util.tree_map(lambda x: x[0], params)
            new_p, aux = step_fn(p, batch)
            return (jax.tree_util.tree_map(lambda x: x[None], new_p),
                    jax.tree_util.tree_map(lambda x: jnp.asarray(x)[None],
                                           aux))

        def sync_body(params):
            return jax.tree_util.tree_map(
                lambda x: jax.lax.pmean(x, ax), params)

        def specs_like(tree, leading_only=False):
            return jax.tree_util.tree_map(
                lambda x: P(ax) if leading_only else _leaf_spec(x, ax), tree)

        def _step(params, batch):
            return _shard_map(
                local_body, mesh=self.mesh,
                in_specs=(specs_like(params), specs_like(batch)),
                out_specs=(specs_like(params), P(ax)),
            )(params, batch)

        def _sync(params):
            return _shard_map(
                sync_body, mesh=self.mesh,
                in_specs=(specs_like(params),),
                out_specs=specs_like(params),
            )(params)

        self._step = jax.jit(_step)
        self._sync = jax.jit(_sync)

    # -- state movement -------------------------------------------------
    def replicate(self, params):
        """Tile every leaf with a leading replica axis of size n, sharded
        over the mesh axis (each device starts from the same copy)."""
        def place(x):
            x = jnp.asarray(x)
            tiled = jnp.broadcast_to(x[None], (self.n,) + x.shape)
            sh = NamedSharding(self.mesh, _leaf_spec(tiled, self.axis))
            return jax.device_put(tiled, sh)
        return jax.tree_util.tree_map(place, params)

    def shard_batch(self, batch):
        """Split a host batch along dim 0 across replicas."""
        def place(x):
            x = jnp.asarray(x)
            sh = NamedSharding(self.mesh, _leaf_spec(x, self.axis))
            return jax.device_put(x, sh)
        return jax.tree_util.tree_map(place, batch)

    def collapse(self, params):
        """Average the replica copies down to one ordinary pytree."""
        synced = self._sync(params)
        return jax.tree_util.tree_map(lambda x: np_like(x), synced)

    # -- the two phases -------------------------------------------------
    def step(self, params, batch):
        """One purely local step on every replica (no collectives)."""
        return self._step(params, batch)

    def sync(self, params):
        """Average all replica copies (one pmean over the mesh axis)."""
        return self._sync(params)


def np_like(x):
    """First replica of a synced leaf (all replicas equal post-sync)."""
    import numpy as np
    return np.asarray(x[0])
