"""All-to-all (Ulysses-style) sequence parallelism.

The second long-context strategy next to ring attention
(ring_attention.py): instead of rotating K/V shards around the ring, ONE
all-to-all re-partitions the sharded tensors from sequence-sharded
[B, H, T/n, D] to head-sharded [B, H/n, T, D], the fused flash-attention
kernel runs locally per head group, and a second all-to-all restores the
sequence sharding. Comm volume is O(1) exchanges instead of n ppermute
steps, at the price of requiring n | H; memory stays O(T) per chip since
the local compute is the flash kernel. Ring wins when T is extreme; both
ride the same mesh axis and are interchangeable (key_bias is a
non-differentiable mask in both, matching ops.flash_attention).

(The reference has no counterpart — sequence length there is capped by
single-GPU memory.)
"""
from jax import lax

from ..ops.flash_attention import flash_attention
from ._sp import sp_shard_map

__all__ = ['ulysses_attention', 'ulysses_self_attention']


def ulysses_attention(q, k, v, axis_name, key_bias=None, causal=False,
                      sm_scale=None):
    """Per-shard body (call inside shard_map).

    q, k, v: [B, H, T_local, D] with the sequence axis sharded over
    axis_name; H must be divisible by the axis size. key_bias is the
    LOCAL [B, T_local] additive key bias (or None).
    """
    # seq-sharded -> head-sharded: each device now owns H/n heads, full T
    qg = lax.all_to_all(q, axis_name, split_axis=1, concat_axis=2,
                        tiled=True)
    kg = lax.all_to_all(k, axis_name, split_axis=1, concat_axis=2,
                        tiled=True)
    vg = lax.all_to_all(v, axis_name, split_axis=1, concat_axis=2,
                        tiled=True)
    kb = None
    if key_bias is not None:
        kb = lax.all_gather(key_bias, axis_name, axis=1, tiled=True)
    out = flash_attention(qg, kg, vg, key_bias=kb, causal=causal,
                          sm_scale=sm_scale)
    # head-sharded -> seq-sharded
    return lax.all_to_all(out, axis_name, split_axis=2, concat_axis=1,
                          tiled=True)


def ulysses_self_attention(mesh, q, k, v, axis='sp', key_bias=None,
                           causal=False, sm_scale=None):
    """pjit-level entry: q/k/v [B, H, T, D] with T sharded over mesh
    axis `axis` (same contract as ring_self_attention)."""
    n = mesh.shape[axis]
    if q.shape[1] % n != 0:
        raise ValueError(
            'ulysses needs heads %% mesh axis == 0 (H=%d, %s=%d); use '
            'ring_self_attention for head counts that do not divide'
            % (q.shape[1], axis, n))

    def body(q, k, v, kb):
        return ulysses_attention(q, k, v, axis, key_bias=kb, causal=causal,
                                 sm_scale=sm_scale)

    return sp_shard_map(body, mesh, q, k, v, axis, key_bias,
                        check_vma=False)  # pallas flash kernel inside
