"""Host liveness for elastic multi-process training (docs/robustness.md).

jax.distributed has no failure detector: when a host of a multi-process
mesh dies (hardware fault, OOM-kill, preemption the supervisor never
signaled), the survivors learn about it only by HANGING in the next
cross-host collective. This layer detects the loss BEFORE the next
dispatch: every process writes a monotonically increasing beat to a
shared directory (the checkpoint filesystem — elastic training already
requires one), and peers judge staleness by LOCAL monotonic time since a
peer's counter last advanced. Judging progress rather than wall-clock
mtimes makes the detector immune to cross-host clock skew, and
file-based beats make it dependency-free (no side control-plane service).

The Trainer consumes this (``Trainer(heartbeat=Heartbeat(...))``): a
stale peer surfaces as the typed :class:`HostLost` after an emergency
checkpoint flush, so a supervisor can restart the job on the surviving
topology and resume from the last committed serial
(``utils.checkpoint.load_latest_verified``).

Every staleness verdict lands in the ``parallel.heartbeat.stale``
counter and run-log event (docs/observability.md).
"""
import os
import threading
import time

from .. import obs

__all__ = ['Heartbeat', 'HostLost']


class HostLost(RuntimeError):
    """A peer process of the multi-process runtime stopped heartbeating.

    Raised by :meth:`Heartbeat.check` (and surfaced through
    ``Trainer.train``) once a peer's beat counter has not advanced for
    longer than the configured timeout. ``.stale`` lists the lost
    process ids, so a supervisor can log/restart on the surviving
    topology."""

    def __init__(self, message, stale=()):
        super(HostLost, self).__init__(message)
        self.stale = list(stale)


def _beat_path(beat_dir, process_id):
    return os.path.join(beat_dir, 'beat.p%d' % process_id)


class Heartbeat(object):
    """Per-host beat writer + stale-peer detector.

    beat_dir: shared directory (every process of the job must see it —
        the checkpoint dir is the natural choice).
    process_id / num_processes: default from the initialized jax
        runtime (jax.process_index / jax.process_count); explicit values
        let tests drive several instances inside one process.
    interval: seconds between background beats (start()).
    timeout: seconds a peer's counter may stand still before it counts
        as stale — must comfortably exceed the longest step + checkpoint
        pause of the training loop, or a slow-but-alive host reads as
        dead.

    A peer is tracked from the moment start()/check() first runs; a peer
    whose beat file never appears at all becomes stale after `timeout`
    too (a host that never came up is as lost as one that died)."""

    def __init__(self, beat_dir, process_id=None, num_processes=None,
                 interval=0.25, timeout=2.0):
        import jax
        self.dir = beat_dir
        os.makedirs(beat_dir, exist_ok=True)
        self.process_id = (jax.process_index() if process_id is None
                           else int(process_id))
        self.num_processes = (jax.process_count() if num_processes is None
                              else int(num_processes))
        self.interval = float(interval)
        self.timeout = float(timeout)
        self._seq = 0
        # per-writer nonce: a RESTARTED writer (new process — or a new
        # Heartbeat instance in tests) starts again at seq 1, but its
        # fresh nonce makes that first beat read as progress to peers
        self._nonce = int.from_bytes(os.urandom(4), 'little')
        self._thread = None
        self._stop = threading.Event()
        self._peers = {}     # pid -> {'seq': last seen, 'since': monotonic}
        self._reported = set()   # peers already counted stale

    @property
    def running(self):
        return self._thread is not None and self._thread.is_alive()

    def _track_peers(self):
        now = time.monotonic()
        for i in range(self.num_processes):
            if i != self.process_id:
                self._peers.setdefault(i, {'seq': None, 'since': now})

    # -- dynamic membership (pod serving, serving/pod.py) -------------------
    #
    # Training jobs declare a fixed num_processes up front; a serving pod
    # does not — replicas register and retire while the pod runs. These
    # two calls let a watcher (the PodRouter's replica registry) track an
    # explicit peer set on top of the same beat files and the same
    # staleness judgement: pass num_processes=0 at construction (beat-only
    # writer / pure watcher) and watch()/unwatch() hosts as they register.

    def watch(self, process_id):
        """Track an explicit peer from now on (it gets the full
        `timeout` grace before it can read as stale)."""
        pid = int(process_id)
        if pid != self.process_id:
            self._peers.setdefault(
                pid, {'seq': None, 'since': time.monotonic()})
        return self

    def unwatch(self, process_id):
        """Stop tracking a peer (a retired host must not read as lost)."""
        pid = int(process_id)
        self._peers.pop(pid, None)
        self._reported.discard(pid)
        return self

    def beat(self):
        """Write one beat (atomic tmp+replace: readers never see a torn
        payload). Manual loops call this directly; start() runs it on a
        background thread."""
        self._seq += 1
        path = _beat_path(self.dir, self.process_id)
        tmp = '%s.tmp%d' % (path, os.getpid())
        with open(tmp, 'w') as f:
            f.write('%d %d\n' % (self._seq, self._nonce))
        os.replace(tmp, path)
        return self._seq

    def start(self):
        """Start the background beat thread (daemon — a SIGKILLed host
        stops beating by construction, which is the whole signal)."""
        if self.running:
            return self
        self._stop.clear()
        self._track_peers()
        self.beat()

        def loop():
            while not self._stop.wait(self.interval):
                try:
                    self.beat()
                except OSError:
                    pass  # transient FS hiccup: the next beat retries

        self._thread = threading.Thread(
            target=loop, name='paddle-tpu-heartbeat', daemon=True)
        self._thread.start()
        return self

    def stop(self):
        """Stop the background beats (the beat files remain — peers will
        judge this host stale, which is correct for a stopping host)."""
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=max(1.0, 4 * self.interval))
            self._thread = None

    def _read_beat(self, process_id):
        """(seq, writer-nonce) of a peer's beat, or None. Progress is
        judged on the PAIR: a restarted peer begins again at seq 1, but
        its fresh nonce makes that first beat read as progress."""
        try:
            with open(_beat_path(self.dir, process_id)) as f:
                parts = f.read().split()
            return (int(parts[0]), int(parts[1]))
        except (OSError, ValueError, IndexError):
            return None

    def _confirm_alive(self, pid, last):
        """Bounded liveness confirmation: wait for ONE more beat from
        the peer. Live peers beat every `interval`, so a window of a few
        intervals decides; a dead peer's file never changes again."""
        deadline = time.monotonic() + min(self.timeout,
                                          3 * self.interval + 0.05)
        while time.monotonic() < deadline:
            time.sleep(min(0.02, self.interval / 4))
            cur = self._read_beat(pid)
            if cur is not None and cur != last:
                return True
        return False

    def check(self, raise_error=True):
        """Scan every peer's beat file; returns the sorted stale process
        ids (empty = all alive). With raise_error (the default), any
        staleness raises :class:`HostLost` instead. Cheap — one small
        file read per peer — so the training loop runs it every step."""
        self._track_peers()
        now = time.monotonic()
        stale = []
        for pid in sorted(self._peers):
            st = self._peers[pid]
            gap = now - st.get('checked', now)
            st['checked'] = now
            seq = self._read_beat(pid)
            if seq is not None and seq != st['seq']:
                prev = st['seq']
                st['seq'] = seq
                # An advance observed after a BLIND window longer than
                # the timeout proves nothing about the peer being alive
                # NOW — a peer that died mid-window still shows the
                # beats it banked first, and crediting them as fresh
                # would send the caller into one more collective
                # dispatch against a dead host (which hangs). Confirm
                # current liveness with a short bounded re-poll: a live
                # peer produces its next beat within ~interval; a dead
                # one stays silent and goes stale on the spot. Checks
                # at a normal cadence (gap <= timeout) skip the poll,
                # so the steady state pays nothing and a live-but-slow
                # peer can never accumulate drift toward a spurious
                # verdict. A restarted writer (new nonce) is fresh by
                # construction.
                suspect = (prev is not None and seq[1] == prev[1]
                           and gap > self.timeout)
                if suspect and not self._confirm_alive(pid, seq):
                    # liveness unproven: stale as of this check
                    st['since'] = now - self.timeout - self.interval
                else:
                    st['since'] = now
                    self._reported.discard(pid)   # peer (re)alive
                    continue
            age = now - st['since']
            if age > self.timeout:
                stale.append(pid)
                if pid not in self._reported:
                    self._reported.add(pid)
                    obs.counter('parallel.heartbeat.stale').inc()
                    obs.event('parallel.heartbeat.stale', peer=pid,
                              age=round(age, 3), timeout=self.timeout,
                              dir=os.path.basename(self.dir))
        if stale and raise_error:
            raise HostLost(
                'process(es) %s stopped heartbeating (no beat for more '
                'than %.1fs under %r) — the host is gone or wedged'
                % (stale, self.timeout, self.dir), stale=stale)
        return stale
