"""Per-layer tensor-parallel sharding rules derived from a Program graph.

Replaces hand-written Megatron-style model parallelism (the reference has
none — its model-parallel story was pserver sharding of large embeddings,
transpiler/distribute_transpiler.py slice_var_up): walk the Program, find
the fc/embedding (and thereby attention-projection) parameters, and emit
(name-pattern, PartitionSpec) rules for shard_params_by_rules. GSPMD then
partitions every matmul touching a sharded weight and inserts the
collectives, so the rules decide LAYOUT (where the all-reduces land), not
numerics — any rule set computes the same result.

The layout heuristic is the Megatron alternation: an fc whose input is
already hidden-sharded becomes ROW-parallel ([tp, None] — its matmul
reduces over the sharded dim, one psum at the output); otherwise it is
COLUMN-parallel ([None, tp] — output stays hidden-sharded, bias shards
with it). Elementwise/activation ops propagate hidden-sharding; ops that
mix the last dim (softmax over features, layer_norm) consume it. Embedding
tables shard the hidden dim so lookups need no gather.
"""
import re

from jax.sharding import PartitionSpec as P

__all__ = ['auto_tp_rules', 'annotate_tp']

# ops through which a tp-sharded last (hidden) dim propagates unchanged
_PASSTHRU = {
    'relu', 'gelu', 'tanh', 'sigmoid', 'swish', 'leaky_relu', 'elu',
    'relu6', 'soft_relu', 'brelu', 'softplus', 'softsign', 'square',
    'sqrt', 'abs', 'exp', 'scale', 'dropout', 'cast', 'clip',
    'elementwise_sub', 'elementwise_mul',
    'elementwise_div', 'elementwise_max', 'elementwise_min', 'sum',
    'reshape',  # common [B,T,d]<->[B*T,d] flattens keep the last dim
}


def _is_param(var):
    from ..fluid.framework import Parameter
    return isinstance(var, Parameter) or getattr(var, 'persistable', False)


def auto_tp_rules(program, axis='tp'):
    """Return [(regex, PartitionSpec)] tensor-parallel rules for every
    fc/embedding parameter in `program`, Megatron column/row alternation.

    Feed the result to shard_params_by_rules (or merge with your own rules;
    earlier entries win there, so prepend overrides).
    """
    rules = []
    sharded = set()   # var names whose last dim is tp-sharded

    for op in program.global_block().ops:
        ins = op.inputs
        outs = [v for vs in op.outputs.values() for v in vs]

        if op.type == 'mul' and 'Y' in ins and ins['Y'] \
                and _is_param(ins['Y'][0]):
            x = ins['X'][0] if ins.get('X') else None
            w = ins['Y'][0]
            if x is not None and x.name in sharded:
                # row-parallel: contraction dim sharded; output is full
                # after GSPMD's psum
                rules.append(('^' + re.escape(w.name) + '$', P(axis, None)))
            else:
                rules.append(('^' + re.escape(w.name) + '$', P(None, axis)))
                for o in outs:
                    sharded.add(o.name)
        elif op.type == 'lookup_table' and ins.get('W') \
                and _is_param(ins['W'][0]):
            w = ins['W'][0]
            rules.append(('^' + re.escape(w.name) + '$', P(None, axis)))
            for o in outs:
                sharded.add(o.name)
        elif op.type == 'elementwise_add':
            x = ins.get('X', [None])[0]
            y = ins.get('Y', [None])[0]
            if x is not None and x.name in sharded:
                # bias of a column-parallel fc shards with the output
                if y is not None and _is_param(y) and len(y.shape) == 1:
                    rules.append(('^' + re.escape(y.name) + '$', P(axis)))
                for o in outs:
                    sharded.add(o.name)
            elif y is not None and y.name in sharded:
                for o in outs:
                    sharded.add(o.name)
        elif op.type in _PASSTHRU:
            if any(v.name in sharded for vs in op.inputs.values()
                   for v in vs):
                for o in outs:
                    sharded.add(o.name)
        # every other op (softmax/layer_norm/matmul/reduce/...) consumes
        # the hidden sharding: its outputs are treated as full

    return rules


def annotate_tp(program, axis='tp'):
    """Stamp auto_tp_rules onto the Program as first-class sharding
    annotations (docs/parallel.md): each matched parameter gets
    ``var.sharding`` set to its Megatron layout, so the tp strategy is a
    property of the Program — carried through clone/serialization,
    checked by ``fluid.analysis.sharding``, and lowered by plain
    ``Executor.run``/``run_bundle`` once the program declares a mesh with
    the axis (``program.set_mesh({'dp': N, 'tp': M})``). The
    array-placement path (shard_params_by_rules over a live scope)
    remains for scopes loaded outside the Program's lifecycle.

    Returns {param_name: spec tuple} for what was annotated. First
    matching rule wins, mirroring shard_params_by_rules precedence; an
    explicit pre-existing annotation is never overwritten."""
    rules = auto_tp_rules(program, axis=axis)
    annotated = {}
    for blk in program.blocks:
        for v in blk.vars.values():
            if not getattr(v, 'persistable', False) or v.sharding:
                continue
            for pat, spec in rules:
                if re.search(pat, v.name):
                    v.sharding = tuple(spec)
                    annotated[v.name] = v.sharding
                    break
    return annotated
