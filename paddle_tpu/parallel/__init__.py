"""Mesh / sharding / collective utilities — the distributed backbone.

TPU-first replacement for the reference's NCCL AllReduce (paddle/fluid/
platform/nccl_helper.h + framework/details/nccl_all_reduce_op_handle.*) and
the pserver/gRPC distributed runtime (operators/send_recv + Go pserver):
parallelism is expressed as jax.sharding over a device Mesh and XLA GSPMD
inserts the collectives on ICI/DCN. Multi-host scale-out is the same program
over a bigger mesh (jax.distributed.initialize on each host).

The moe `all_to_all` dispatch pattern here (parallel/moe.py) is also the
wire under `paddle_tpu.embedding` — row-sharded huge-vocab lookup tables
with bucket/dedup/exchange lookups and per-shard sparse updates, the
pserver workload rebuilt TPU-native (docs/embedding.md).
"""
import re

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ['annotate_tp', 'auto_tp_rules', 'fsdp_shard_params',
           'make_mesh', 'data_sharding', 'replicated', 'shard_batch',
           'replicate', 'shard_params_by_rules', 'psum', 'all_gather',
           'reduce_scatter', 'ppermute', 'shard_optimizer_states',
           'init_multihost', 'init_distributed', 'process_count',
           'process_index', 'global_batch', 'Mesh', 'NamedSharding', 'P',
           'Heartbeat', 'HostLost',
           'ring_attention', 'ring_self_attention',
           'ulysses_attention', 'ulysses_self_attention',
           'pipeline_apply', 'pipeline_manual_axes', 'stack_stage_params',
           'moe_apply', 'stack_expert_params', 'LocalSGD']

from .ring_attention import ring_attention, ring_self_attention  # noqa: E402
from .ulysses import ulysses_attention, ulysses_self_attention  # noqa: E402
from .tp import annotate_tp, auto_tp_rules  # noqa: E402
from .pipeline import (pipeline_apply, pipeline_manual_axes,  # noqa: E402
                       stack_stage_params)
from .moe import moe_apply, stack_expert_params  # noqa: E402
from .local_sgd import LocalSGD  # noqa: E402
from .heartbeat import Heartbeat, HostLost  # noqa: E402


def init_distributed(coordinator_address=None, num_processes=None,
                     process_id=None, local_device_ids=None):
    """Join the multi-process GSPMD runtime (docs/parallel.md): wraps
    jax.distributed.initialize so every host sees the global device set,
    after which ONE annotated Program spans every host's chips — the
    Executor assembles each host's per-host feed slice into the global
    sharded batch (parallel.global_batch) and XLA places the collectives
    on ICI/DCN. The production sibling of init_multihost (which keeps the
    reference's PADDLE_TRAINER_* env compatibility).

    num_processes=1 (or unset, outside any cluster) is the single-process
    no-op: nothing to initialize, the local devices ARE the mesh. Returns
    {'num_processes', 'process_id', 'initialized'} so launchers can log
    what they joined."""
    if num_processes is None and coordinator_address is None \
            and process_id is None:
        num_processes = 1
    if num_processes is not None and int(num_processes) <= 1:
        return {'num_processes': 1, 'process_id': 0, 'initialized': False}
    if coordinator_address is None or process_id is None \
            or num_processes is None:
        raise ValueError(
            'init_distributed needs coordinator_address, num_processes '
            'and process_id for a %r-process cluster (got %r, %r, %r)'
            % (num_processes, coordinator_address, num_processes,
               process_id))
    _arm_cpu_collectives()
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=int(num_processes), process_id=int(process_id),
        local_device_ids=local_device_ids)
    return {'num_processes': int(num_processes),
            'process_id': int(process_id), 'initialized': True}


def _arm_cpu_collectives():
    """On the CPU platform, default the cross-process collectives
    implementation to gloo BEFORE the backend initializes — without it
    the old XLA CPU runtime raises "Multiprocess computations aren't
    implemented" at the first cross-host dispatch. Only the 'none'
    default is replaced (an explicit mpi/gloo choice wins); newer jax
    without the flag, or a non-CPU platform, is a no-op."""
    try:
        plats = jax.config.jax_platforms
    except AttributeError:
        plats = None
    # unset platform config means jax will AUTO-SELECT — which on a
    # chipless host IS the CPU backend, exactly where the flag matters;
    # only an explicit non-cpu platform choice skips the arming (the
    # flag is inert on TPU/GPU backends anyway)
    if plats and 'cpu' not in str(plats):
        return
    try:
        cur = getattr(jax.config, 'jax_cpu_collectives_implementation',
                      None)
        if cur is None:
            # jax<0.5 exposes it as a Flag holder, not a config attr
            from jax._src.config import config as _jc
            cur = _jc._value_holders[
                'jax_cpu_collectives_implementation'].value
        if cur in (None, 'none'):
            jax.config.update('jax_cpu_collectives_implementation', 'gloo')
    except Exception:
        pass  # flag absent/renamed in this jax: leave the default


def process_count():
    """Number of processes in the (initialized) runtime; 1 single-host."""
    return jax.process_count()


def process_index():
    """This process's id in the runtime; 0 single-host."""
    return jax.process_index()


def global_batch(sharding, local_data):
    """Assemble a global sharded array from THIS process's slice of the
    batch (docs/parallel.md): under a multi-process mesh each host feeds
    only the rows its devices own (`reader.shard(num_hosts, host_id)`
    upstream), and jax.make_array_from_process_local_data stitches the
    per-host slices into one global jax.Array — no host ever
    materializes (or transfers) the whole batch. Single-process, the
    local slice IS the global batch and this is a plain device_put."""
    if jax.process_count() > 1 and hasattr(
            jax, 'make_array_from_process_local_data'):
        return jax.make_array_from_process_local_data(
            sharding, np.asarray(local_data))
    if not isinstance(local_data, jax.Array):
        # device_put straight from host memory into the sharded
        # placement — staging through jnp.asarray would commit the whole
        # batch to device 0 first
        local_data = np.asarray(local_data)
    return jax.device_put(local_data, sharding)


_mh_warned = [False]


def init_multihost(coordinator_address=None, num_processes=None,
                   process_id=None, local_device_ids=None):
    """Join a multi-host mesh: wraps jax.distributed.initialize so every
    host sees the global device set, then the SAME GSPMD program spans
    ICI+DCN (the reference instead spawned pserver processes and connected
    trainers over gRPC, transpiler/distribute_transpiler.py:167).

    Arguments default from the reference's launcher environment
    (PADDLE_TRAINER_ENDPOINTS/PADDLE_TRAINERS/PADDLE_TRAINER_ID) so
    reference-style cluster scripts work unchanged; returns False (no-op)
    when neither args nor env describe a cluster — single-host dev keeps
    working without any setup.

    DEPRECATED shim (docs/migration.md): `init_distributed` is the
    first-class multi-process entry of the GSPMD executor path — explicit
    cluster arguments, a structured return, and the documented pairing
    with `reader.shard` + per-host feeds. This wrapper survives for the
    PADDLE_TRAINER_* env compatibility only.
    """
    import os
    import warnings
    if not _mh_warned[0]:
        _mh_warned[0] = True
        warnings.warn(
            'parallel.init_multihost is deprecated: call '
            'parallel.init_distributed(coordinator_address=..., '
            'num_processes=..., process_id=...) — the multi-process init '
            'of the first-class GSPMD path (docs/parallel.md, '
            'docs/migration.md). init_multihost remains only for '
            'PADDLE_TRAINER_* env-driven launchers.',
            DeprecationWarning, stacklevel=2)
    if coordinator_address is None:
        eps = os.environ.get('PADDLE_TRAINER_ENDPOINTS', '')
        if eps:
            coordinator_address = eps.split(',')[0].strip()
    if num_processes is None and os.environ.get('PADDLE_TRAINERS'):
        num_processes = int(os.environ['PADDLE_TRAINERS'])
    if process_id is None and os.environ.get('PADDLE_TRAINER_ID'):
        process_id = int(os.environ['PADDLE_TRAINER_ID'])
    if (coordinator_address is None or process_id is None
            or num_processes in (None, 0, 1)):
        return False  # incomplete cluster description: single-host no-op
    _arm_cpu_collectives()
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes, process_id=process_id,
        local_device_ids=local_device_ids)
    return True


def make_mesh(axes=None, devices=None):
    """Build a Mesh from {'dp': 2, 'tp': 4}-style axis sizes (row-major)."""
    devices = list(devices if devices is not None else jax.devices())
    if axes is None:
        axes = {'dp': len(devices)}
    names = tuple(axes.keys())
    sizes = tuple(axes.values())
    n = int(np.prod(sizes))
    if n > len(devices):
        raise ValueError("mesh needs %d devices, only %d available"
                         % (n, len(devices)))
    arr = np.asarray(devices[:n]).reshape(sizes)
    return Mesh(arr, names)


def data_sharding(mesh, axis='dp', ndim=2):
    return NamedSharding(mesh, P(axis, *([None] * (ndim - 1))))


def replicated(mesh):
    return NamedSharding(mesh, P())


def shard_batch(mesh, value, axis='dp'):
    """Place a host batch sharded along its leading dim."""
    arr = jnp.asarray(np.asarray(value))
    return jax.device_put(arr, data_sharding(mesh, axis, arr.ndim))


def replicate(mesh, value):
    return jax.device_put(jnp.asarray(np.asarray(value)), replicated(mesh))


def shard_params_by_rules(values, mesh, rules):
    """Apply tensor-parallel shardings by name pattern.

    values: dict name -> array; rules: [(regex, PartitionSpec)]. Unmatched
    names are replicated. This is how tp/ep layouts are declared — GSPMD
    then partitions every matmul touching the sharded weights and inserts
    the all-reduces, replacing hand-written Megatron-style comm.
    """
    out = {}
    for name, v in values.items():
        spec = None
        for pat, s in rules:
            if re.search(pat, name):
                spec = s
                break
        sh = NamedSharding(mesh, spec if spec is not None else P())
        try:
            out[name] = jax.device_put(v, sh)
        except ValueError as e:
            import warnings
            warnings.warn(
                "shard_params_by_rules: %s does not fit spec %s (%s); "
                "replicating instead" % (name, spec, e))
            out[name] = jax.device_put(v, replicated(mesh))
    return out


def _already_mesh_placed(v):
    """True for values a previous sharding pass placed with a
    non-replicated NamedSharding — later passes leave them alone so
    composed recipes (ZeRO state + FSDP params) don't undo each other."""
    sh = getattr(v, 'sharding', None)
    return (isinstance(sh, NamedSharding)
            and any(s is not None for s in sh.spec))


def shard_optimizer_states(values, mesh, axis='dp'):
    """ZeRO-style sharding of optimizer accumulators over the dp axis —
    the TPU answer to pserver memory scaling (each "server shard" is a mesh
    coordinate holding 1/N of the state). Values already mesh-sharded by a
    previous pass are left untouched."""
    out = {}
    n = mesh.shape[axis]
    for name, v in values.items():
        if _already_mesh_placed(v):
            out[name] = v
        elif v.ndim >= 1 and v.shape[0] % n == 0:
            out[name] = jax.device_put(
                v, NamedSharding(mesh, P(axis, *([None] * (v.ndim - 1)))))
        else:
            out[name] = jax.device_put(v, replicated(mesh))
    return out


def fsdp_shard_params(values, mesh, axis='dp', min_size=1024):
    """ZeRO-3 / FSDP parameter sharding: every large parameter is sharded
    over the data axis (first divisible dim), so per-chip parameter HBM
    scales 1/N; GSPMD inserts the all-gather at each use site and the
    matching reduce-scatter on the gradient, which is exactly the FSDP
    schedule. Small tensors (< min_size elements) stay replicated — the
    gather latency outweighs the memory.

    Beyond the reference: its pserver sharding (slice_var_up) only moved
    OPTIMIZER memory off the trainers; this shards the parameters
    themselves. Combine with shard_optimizer_states for full ZeRO-3 (in
    either order — both passes skip values the other already sharded).
    """
    out = {}
    n = mesh.shape[axis]
    for name, v in values.items():
        if _already_mesh_placed(v):
            out[name] = v
            continue
        spec = None
        if hasattr(v, 'ndim') and v.ndim >= 1 and v.size >= min_size:
            for d in range(v.ndim):
                if v.shape[d] % n == 0:
                    spec = P(*([None] * d), axis)
                    break
        if spec is None:
            out[name] = jax.device_put(v, replicated(mesh))
        else:
            out[name] = jax.device_put(v, NamedSharding(mesh, spec))
    return out


# -- collective wrappers (usable inside shard_map'ped fns) --
def psum(x, axis_name):
    return jax.lax.psum(x, axis_name)


def all_gather(x, axis_name, axis=0, tiled=True):
    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name, scatter_dimension=0):
    return jax.lax.psum_scatter(x, axis_name,
                                scatter_dimension=scatter_dimension,
                                tiled=True)


def ppermute(x, axis_name, perm):
    return jax.lax.ppermute(x, axis_name, perm)
