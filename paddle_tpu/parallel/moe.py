"""Expert parallelism: top-k gated mixture-of-experts with all_to_all
dispatch over a mesh axis.

TPU-first design (no reference counterpart — the reference predates MoE
layers; its conditional-computation ancestor is fluid/layers/control_flow.py
Switch): experts live along the `ep` mesh axis (expert weights stacked
[n_experts, ...] and sharded like pipeline stages), with experts-per-device
= n_experts / axis_size when the counts differ (divisibility required).
Tokens are gated top-k (k=1 Switch-style raw-probability gates; k>1
GShard-style gates renormalized over the selected experts), packed into
fixed per-expert capacity slots (static shapes — overflow tokens are
dropped, the standard TPU MoE trade, with all first choices claiming slots
before any second choice), sent to their expert with ONE all_to_all,
transformed, and returned with a second all_to_all; dropped tokens pass
through gate-weighted as zeros.

`load_balancing_loss` is the Switch/GShard auxiliary objective
E * sum_e f_e * P_e — differentiable through P_e, minimized at 1.0 by a
uniform router — to be added to the model loss with a small weight.
"""
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ._sp import stack_unit_params

__all__ = ['moe_apply', 'stack_expert_params', 'router_topk', 'pack_topk',
           'combine_topk', 'pack_top1', 'combine_top1',
           'load_balancing_loss']

# [{param pytree} per expert] -> pytree with leading [n_experts, ...] axis
stack_expert_params = stack_unit_params


def router_topk(logits, top_k):
    """Routing decisions shared by the dense and sharded paths.

    Returns (expert [k, nt] int, gate [k, nt] f32). k=1 keeps the Switch
    semantics (gate = raw softmax probability of the chosen expert); k>1
    renormalizes the selected probabilities to sum to 1 per token (GShard).
    """
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)  # [nt, E]
    _, idx = lax.top_k(logits, top_k)                            # [nt, k]
    gate = jnp.take_along_axis(probs, idx, axis=-1)              # [nt, k]
    if top_k > 1:
        gate = gate / jnp.sum(gate, axis=-1, keepdims=True)
    return idx.T, gate.T


def load_balancing_loss(logits, top_k=1):
    """Switch/GShard auxiliary load-balancing loss: E * sum_e f_e * P_e,
    where f_e is the fraction of (token, choice) assignments routed to
    expert e and P_e the mean router probability of e. Equals 1.0 for a
    perfectly uniform router, approaches E under total collapse; the f_e
    factor is non-differentiable (argmax) so gradients flow through P_e,
    pushing probability mass away from overloaded experts."""
    n_exp = logits.shape[-1]
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    _, idx = lax.top_k(logits, top_k)                            # [nt, k]
    f = jnp.mean(jax.nn.one_hot(idx, n_exp, dtype=jnp.float32), axis=(0, 1))
    p = jnp.mean(probs, axis=0)
    return n_exp * jnp.sum(f * p)


def pack_topk(xs, logits, n_exp, cap, top_k=1):
    """Top-k routing + fixed-capacity packing (shared by the sharded
    all_to_all path below and ops_impl/moe_ops.py's dense fallback, so the
    two stay numerically identical).

    Capacity slots are claimed in choice-major order — every token's first
    choice before any token's second choice (GShard priority), then token
    order within a choice level.

    Returns (send [n_exp, cap, d], route) where route carries the
    (expert, slot, keep, gate) [k, nt] arrays needed to combine."""
    nt, d = xs.shape
    expert, gate = router_topk(logits, top_k)                # [k, nt]
    onehot = jax.nn.one_hot(expert.reshape(-1), n_exp,
                            dtype=jnp.int32)                 # [k*nt, E]
    pos = jnp.cumsum(onehot, axis=0) * onehot                # 1-based
    slot = (jnp.sum(pos, axis=-1) - 1).reshape(top_k, nt)    # [k, nt]
    keep = slot < cap
    xs_k = jnp.broadcast_to(xs[None], (top_k, nt, d))
    send = jnp.zeros((n_exp, cap, d), xs.dtype)
    send = send.at[jnp.where(keep, expert, 0).reshape(-1),
                   jnp.where(keep, slot, 0).reshape(-1)].add(
        jnp.where(keep.reshape(-1)[:, None], xs_k.reshape(-1, d), 0.0))
    return send, (expert, slot, keep, gate)


def combine_topk(back, route, dtype):
    """Unpack expert outputs [n_exp, cap, d_out] by route, gate-weight and
    sum over the k choices; dropped assignments contribute zeros."""
    expert, slot, keep, gate = route                         # [k, nt]
    y = back[jnp.where(keep, expert, 0), jnp.where(keep, slot, 0)]
    y = jnp.where(keep[..., None], y, 0.0)                   # [k, nt, d_out]
    return jnp.sum(y.astype(jnp.float32) * gate[..., None],
                   axis=0).astype(dtype)


def pack_top1(xs, logits, n_exp, cap):
    """Top-1 convenience wrapper (route arrays squeezed to [nt])."""
    send, (expert, slot, keep, gate) = pack_topk(xs, logits, n_exp, cap, 1)
    return send, (expert[0], slot[0], keep[0], gate[0])


def combine_top1(back, route, dtype):
    expert, slot, keep, gate = route
    return combine_topk(back, (expert[None], slot[None], keep[None],
                               gate[None]), dtype)


def _n_experts_of(stacked, mesh, axis):
    """Leading dim of the stacked expert pytree; must be a positive
    multiple of the mesh axis (experts-per-device >= 1, sharded evenly —
    a non-multiple would shard raggedly or drop experts silently)."""
    leaves = jax.tree_util.tree_leaves(stacked)
    n_exp = leaves[0].shape[0]
    ws = mesh.shape[axis]
    for leaf in leaves:
        if leaf.shape[0] != n_exp:
            raise ValueError('expert: inconsistent stacked leading dims '
                             '%d vs %d' % (leaf.shape[0], n_exp))
    if n_exp % ws or n_exp < ws:
        raise ValueError(
            'expert: stacked leading dim %d must equal mesh axis %r size %d '
            'or a multiple of it (experts-per-device)' % (n_exp, axis, ws))
    return n_exp


def moe_apply(expert_fn, stacked_params, x, gate_logits, mesh, axis='ep',
              capacity_factor=2.0, top_k=1):
    """Dispatch tokens to experts and combine.

    expert_fn(params, x) -> y        applied per expert on [cap, d]
    stacked_params: leaves [n_experts, ...], sharded over `axis`
                    (n_experts must be a multiple of the axis size;
                    each device holds n_experts/axis_size experts)
    x:           [n_tokens, d] tokens, sharded over `axis` (token shards)
    gate_logits: [n_tokens, n_experts], sharded like x
    Returns [n_tokens, d_out]: gate-weighted expert outputs (0 for dropped).
    """
    ws = mesh.shape[axis]
    n_exp = _n_experts_of(stacked_params, mesh, axis)
    epd = n_exp // ws                          # experts per device
    if gate_logits.shape[-1] != n_exp:
        raise ValueError(
            'gate_logits last dim %d must equal the stacked expert count %d'
            % (gate_logits.shape[-1], n_exp))
    from ._compat import shard_map

    def body(params, xs, logits):
        # params leaves [epd, ...]: this device's expert block — expert e
        # lives on device e // epd at local index e % epd, matching the
        # [ws, epd, ...] reshape of the send buffer below
        nt, d = xs.shape
        cap = int(max(1, capacity_factor * top_k * nt / n_exp))

        # pack: [E, cap, d] send buffer (local tokens destined per expert)
        send, route = pack_topk(xs, logits, n_exp, cap, top_k)

        # exchange: device j receives every shard's buffers for its block
        # of experts [j*epd, (j+1)*epd)
        recv = lax.all_to_all(send.reshape(ws, epd, cap, d), axis,
                              split_axis=0, concat_axis=0, tiled=True)
        toks = recv.reshape(ws, epd, cap, d).transpose(1, 0, 2, 3)
        out = jax.vmap(expert_fn)(params, toks.reshape(epd, ws * cap, d))
        d_out = out.shape[-1]
        out = out.reshape(epd, ws, cap, d_out).transpose(1, 0, 2, 3)
        back = lax.all_to_all(out, axis, split_axis=0, concat_axis=0,
                              tiled=True).reshape(n_exp, cap, d_out)

        return combine_topk(back, route, xs.dtype)

    fn = shard_map(
        body, mesh=mesh,
        in_specs=(jax.tree_util.tree_map(lambda _: P(axis), stacked_params),
                  P(axis), P(axis)),
        out_specs=P(axis), check_vma=False)
    return fn(stacked_params, x, gate_logits)
