"""Expert parallelism: top-1 gated mixture-of-experts with all_to_all
dispatch over a mesh axis.

TPU-first design (no reference counterpart — the reference predates MoE
layers): experts live one-per-device along the `ep` mesh axis (expert
weights stacked [n_experts, ...] and sharded like pipeline stages). Tokens
are gated top-1, packed into fixed per-expert capacity slots (static
shapes — overflow tokens are dropped, the standard TPU MoE trade), sent to
their expert with ONE all_to_all, transformed, and returned with a second
all_to_all; dropped tokens pass through the residual unchanged.
"""
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ._sp import stack_unit_params, check_units_match_axis

__all__ = ['moe_apply', 'stack_expert_params', 'pack_top1', 'combine_top1']

# [{param pytree} per expert] -> pytree with leading [n_experts, ...] axis
stack_expert_params = stack_unit_params


def pack_top1(xs, logits, n_exp, cap):
    """Top-1 routing + fixed-capacity packing (shared by the sharded
    all_to_all path below and ops_impl/moe_ops.py's dense fallback, so the
    two stay numerically identical).

    Returns (send [n_exp, cap, d], route) where route carries the
    (expert, slot, keep, gate) needed to combine."""
    nt, d = xs.shape
    expert = jnp.argmax(logits, axis=-1)                     # [nt]
    gate = jax.nn.softmax(logits.astype(jnp.float32),
                          axis=-1)[jnp.arange(nt), expert]   # [nt]
    # position of each token within its expert's capacity buffer
    onehot = jax.nn.one_hot(expert, n_exp, dtype=jnp.int32)  # [nt, E]
    pos = jnp.cumsum(onehot, axis=0) * onehot                # 1-based
    slot = jnp.sum(pos, axis=-1) - 1                         # [nt]
    keep = slot < cap
    send = jnp.zeros((n_exp, cap, d), xs.dtype)
    send = send.at[jnp.where(keep, expert, 0),
                   jnp.where(keep, slot, 0)].add(
        jnp.where(keep[:, None], xs, 0.0))
    return send, (expert, slot, keep, gate)


def combine_top1(back, route, dtype):
    """Unpack expert outputs [n_exp, cap, d] by route and gate-weight;
    dropped tokens get zeros."""
    expert, slot, keep, gate = route
    y = back[jnp.where(keep, expert, 0), jnp.where(keep, slot, 0)]
    y = jnp.where(keep[:, None], y, 0.0)
    return (y.astype(jnp.float32) * gate[:, None]).astype(dtype)


def moe_apply(expert_fn, stacked_params, x, gate_logits, mesh, axis='ep',
              capacity_factor=2.0):
    """Dispatch tokens to experts and combine.

    expert_fn(params, x) -> y        applied per expert on [cap, d]
    stacked_params: leaves [n_experts, ...], sharded over `axis`
    x:           [n_tokens, d] tokens, sharded over `axis` (token shards)
    gate_logits: [n_tokens, n_experts], sharded like x
    Returns [n_tokens, d]: gate-weighted expert outputs (0 for dropped).
    """
    n_exp = mesh.shape[axis]
    check_units_match_axis(stacked_params, mesh, axis, 'expert')
    if gate_logits.shape[-1] != n_exp:
        raise ValueError(
            'gate_logits last dim %d must equal mesh axis %r size %d (one '
            'expert per device)' % (gate_logits.shape[-1], axis, n_exp))
    from jax import shard_map

    def body(params, xs, logits):
        p_local = jax.tree_util.tree_map(lambda p: p[0], params)
        nt, d = xs.shape
        cap = int(max(1, capacity_factor * nt / n_exp))

        # pack: [E, cap, d] send buffer (local tokens destined per expert)
        send, route = pack_top1(xs, logits, n_exp, cap)

        # exchange: device e receives every shard's buffer for expert e
        recv = lax.all_to_all(send, axis, split_axis=0, concat_axis=0,
                              tiled=True)                        # [E*cap, d]
        out = expert_fn(p_local, recv.reshape(-1, d))
        out = out.reshape(n_exp, cap, d)
        back = lax.all_to_all(out, axis, split_axis=0, concat_axis=0,
                              tiled=True).reshape(n_exp, cap, d)

        return combine_top1(back, route, xs.dtype)

    fn = shard_map(
        body, mesh=mesh,
        in_specs=(jax.tree_util.tree_map(lambda _: P(axis), stacked_params),
                  P(axis), P(axis)),
        out_specs=P(axis), check_vma=False)
    return fn(stacked_params, x, gate_logits)
