"""Ring attention: sequence/context parallelism over a mesh axis.

TPU-first answer to long-context scaling (the reference caps sequence
length by single-GPU memory; see machine_translation.py max_length): shard
the sequence axis of q/k/v over a mesh axis, keep q local, and rotate the
k/v shards around the ring with ppermute while accumulating the online
softmax — each device only ever holds O(T/n) keys, so max context scales
linearly with the ring size, and the ppermute rides the ICI torus
concurrently with the local attention block (compute hides comm).

Use inside shard_map (ring_attention) or via the pjit-level wrapper
(ring_self_attention) which sets up the shard_map over a Mesh axis.
"""
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

NEG_BIG = -1e9


def _resolve_impl(impl):
    """None -> env override or backend default; typos raise rather than
    silently running the O(Tl^2) dense body."""
    if impl is None:
        import os
        impl = os.environ.get(
            'PADDLE_TPU_RING_IMPL',
            'flash' if jax.default_backend() == 'tpu' else 'dense')
    if impl not in ('flash', 'dense'):
        raise ValueError(
            "ring attention impl must be 'flash' or 'dense', got %r" % impl)
    return impl


def ring_attention(q, k, v, axis_name, key_bias=None, causal=False,
                   sm_scale=None, impl=None):
    """Per-shard body (call inside shard_map).

    q, k, v: [B, H, T_local, D] — the sequence axis sharded over axis_name.
    key_bias: [B, T_local] additive bias for the local keys (or None).
    impl: 'flash' runs each local block through the pallas flash kernel
        (no [Tl, Tl] score matrix ever materializes — the long-context MXU
        path) and merges ring steps with logsumexp statistics; 'dense' is
        the plain-XLA einsum body. None auto-selects flash on TPU
        (overridable with PADDLE_TPU_RING_IMPL).
    """
    impl = _resolve_impl(impl)
    if impl == 'flash':
        return _ring_attention_flash(q, k, v, axis_name, key_bias, causal,
                                     sm_scale)
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    B, H, Tl, D = q.shape
    if sm_scale is None:
        sm_scale = D ** -0.5
    qf = q.astype(jnp.float32) * sm_scale
    if key_bias is None:
        key_bias = jnp.zeros((B, Tl), jnp.float32)
    # non-differentiable mask, matching ops.flash_attention / ulysses
    key_bias = lax.stop_gradient(key_bias)

    m = jnp.full((B, H, Tl), -1e30, jnp.float32)
    l = jnp.zeros((B, H, Tl), jnp.float32)
    acc = jnp.zeros((B, H, Tl, D), jnp.float32)
    kc, vc, kbc = k, v, key_bias
    perm = [(i, (i + 1) % n) for i in range(n)]

    qpos = idx * Tl + jnp.arange(Tl)

    def one_step(s, m, l, acc, kc, vc, kbc):
        src = (idx - s) % n           # whose kv shard we currently hold
        sc = jnp.einsum('bhqd,bhkd->bhqk', qf, kc.astype(jnp.float32))
        sc = sc + kbc[:, None, None, :].astype(jnp.float32)
        if causal:
            kpos = src * Tl + jnp.arange(Tl)
            sc = jnp.where(qpos[:, None] >= kpos[None, :], sc, NEG_BIG)
        m_new = jnp.maximum(m, sc.max(axis=-1))
        p = jnp.exp(sc - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l = l * alpha + p.sum(axis=-1)
        acc = acc * alpha[..., None] + jnp.einsum(
            'bhqk,bhkd->bhqd', p, vc.astype(jnp.float32))
        if s != n - 1:   # the last shard needs no further rotation
            kc = lax.ppermute(kc, axis_name, perm)
            vc = lax.ppermute(vc, axis_name, perm)
            kbc = lax.ppermute(kbc, axis_name, perm)
        return m_new, l, acc, kc, vc, kbc

    # ring size = mesh axis size is static, so the loop unrolls at trace time
    for s in range(int(n)):
        m, l, acc, kc, vc, kbc = one_step(s, m, l, acc, kc, vc, kbc)

    l = jnp.maximum(l, 1e-30)
    return (acc / l[..., None]).astype(q.dtype)


def _ring_attention_flash(q, k, v, axis_name, key_bias, causal, sm_scale):
    """Ring schedule with the pallas flash kernel as the per-step block.

    Each ring step computes (o_s, lse_s) = flash(q_local, kv_shard); steps
    merge with the standard partial-softmax combine
        lse' = logaddexp(lse, lse_s)
        o'   = o * e^{lse-lse'} + o_s * e^{lse_s-lse'}
    which is exact (the union of key shards IS full attention). Causality
    across shards is a per-step trichotomy on the ring offset — fully
    visible (earlier shard: plain kernel), diagonal (own shard: causal
    kernel), fully masked (later shard: skip) — so the kernel's local
    causal mask is always position-correct. Gradients flow through both
    kernel outputs (ops.flash_attention._flash_lse_bwd) and the combine.
    """
    from ..ops.flash_attention import flash_attention_lse

    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    B, H, Tl, D = q.shape
    if key_bias is None:
        key_bias = jnp.zeros((B, Tl), jnp.float32)
    key_bias = lax.stop_gradient(key_bias)

    o = jnp.zeros((B, H, Tl, D), jnp.float32)
    lse = jnp.full((B, H, Tl), -1e30, jnp.float32)
    kc, vc, kbc = k, v, key_bias
    perm = [(i, (i + 1) % n) for i in range(n)]

    def merge(o, lse, o_s, lse_s):
        lse_new = jnp.logaddexp(lse, lse_s)
        w = jnp.exp(lse - lse_new)[..., None]
        w_s = jnp.exp(lse_s - lse_new)[..., None]
        return o * w + o_s.astype(jnp.float32) * w_s, lse_new

    for s in range(int(n)):
        src = (idx - s) % n           # whose kv shard we currently hold
        if causal:
            def visible(kc=kc, vc=vc, kbc=kbc):
                return flash_attention_lse(q, kc, vc, key_bias=kbc,
                                           causal=False, sm_scale=sm_scale)

            def diagonal(kc=kc, vc=vc, kbc=kbc):
                return flash_attention_lse(q, kc, vc, key_bias=kbc,
                                           causal=True, sm_scale=sm_scale)

            def masked():
                return (jnp.zeros((B, H, Tl, D), q.dtype),
                        jnp.full((B, H, Tl), -1e30, jnp.float32))

            o_s, lse_s = lax.cond(
                src > idx, masked,
                lambda: lax.cond(src == idx, diagonal, visible))
        else:
            o_s, lse_s = flash_attention_lse(q, kc, vc, key_bias=kbc,
                                             causal=False, sm_scale=sm_scale)
        o, lse = merge(o, lse, o_s, lse_s)
        if s != n - 1:   # the last shard needs no further rotation
            kc = lax.ppermute(kc, axis_name, perm)
            vc = lax.ppermute(vc, axis_name, perm)
            kbc = lax.ppermute(kbc, axis_name, perm)
    return o.astype(q.dtype)


def ring_self_attention(mesh, q, k, v, axis='sp', key_bias=None,
                        causal=False, sm_scale=None, impl=None):
    """pjit-level entry: q/k/v [B, H, T, D] with T sharded over mesh axis."""
    from ._sp import sp_shard_map
    impl = _resolve_impl(impl)  # resolve HERE so check_vma is exact

    def body(q, k, v, kb):
        return ring_attention(q, k, v, axis, key_bias=kb, causal=causal,
                              sm_scale=sm_scale, impl=impl)

    # pallas ShapeDtypeStructs carry no varying-mesh-axes info, so the vma
    # check must be off when the flash body runs
    return sp_shard_map(body, mesh, q, k, v, axis, key_bias,
                        check_vma=impl == 'dense')
