"""Shared plumbing for the sequence-parallel attention strategies
(ring_attention.py, ulysses.py): both take [B, H, T, D] q/k/v with T
sharded over one mesh axis and an optional [B, T] additive key bias."""
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def sp_shard_map(body, mesh, q, k, v, axis, key_bias):
    """Wrap a per-shard attention body in shard_map with the sequence
    sharding contract; defaults a zero key bias."""
    from jax import shard_map

    qkv_spec = P(None, None, axis, None)
    kb_spec = P(None, axis)
    if key_bias is None:
        key_bias = jnp.zeros((q.shape[0], k.shape[2]), jnp.float32)
    # check_vma=False: the pallas flash kernel's ShapeDtypeStructs carry
    # no varying-mesh-axes info, which the default vma check rejects
    fn = shard_map(body, mesh=mesh,
                   in_specs=(qkv_spec, qkv_spec, qkv_spec, kb_spec),
                   out_specs=qkv_spec, check_vma=False)
    return fn(q, k, v, key_bias)
