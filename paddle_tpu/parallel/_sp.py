"""Shared plumbing for the sequence-parallel attention strategies
(ring_attention.py, ulysses.py): both take [B, H, T, D] q/k/v with T
sharded over one mesh axis and an optional [B, T] additive key bias."""
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def sp_shard_map(body, mesh, q, k, v, axis, key_bias, check_vma=True):
    """Wrap a per-shard attention body in shard_map with the sequence
    sharding contract; defaults a zero key bias. check_vma=False only for
    bodies containing pallas calls, whose ShapeDtypeStructs carry no
    varying-mesh-axes info (the default check rejects them). When the mesh
    also carries 'dp', the batch dim stays dp-sharded — each dp replica
    runs its own sequence ring/all_to_all over its batch slice instead of
    re-computing the global batch."""
    from ._compat import shard_map

    bdim = 'dp' if ('dp' in mesh.shape and axis != 'dp') else None
    if bdim is not None and q.shape[0] % mesh.shape['dp']:
        raise ValueError(
            'sequence-parallel attention on a dp-carrying mesh: batch %d '
            'must be divisible by dp=%d (drop the remainder, e.g. '
            'paddle.batch(..., drop_last=True))'
            % (q.shape[0], mesh.shape['dp']))
    qkv_spec = P(bdim, None, axis, None)
    kb_spec = P(bdim, axis)
    if key_bias is None:
        key_bias = jnp.zeros((q.shape[0], k.shape[2]), jnp.float32)
    fn = shard_map(body, mesh=mesh,
                   in_specs=(qkv_spec, qkv_spec, qkv_spec, kb_spec),
                   out_specs=qkv_spec, check_vma=check_vma)
    return fn(q, k, v, key_bias)


def stack_unit_params(per_unit_params):
    """[{param pytree} per stage/expert] -> one pytree with a leading unit
    axis (shard it over the pp/ep mesh axis)."""
    import jax
    import jax.numpy as jnp
    return jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs, axis=0), *per_unit_params)


