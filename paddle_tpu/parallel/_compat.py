"""jax version compatibility for the parallel layer.

jax >= 0.6 exposes `jax.shard_map` with `axis_names=` (the MANUAL axis
set) and `check_vma=`; earlier releases only have
`jax.experimental.shard_map.shard_map` with the complementary `auto=`
frozenset and `check_rep=`. One shim so every shard_map call site in
this package writes the modern signature.
"""
try:  # jax>=0.6
    from jax import shard_map  # noqa: F401
except ImportError:  # pragma: no cover — depends on the installed jax
    from jax.experimental.shard_map import shard_map as _exp_shard_map

    def shard_map(f, mesh, in_specs, out_specs, axis_names=None,
                  check_vma=True):
        names = mesh.axis_names if axis_names is None else axis_names
        auto = frozenset(mesh.axis_names) - frozenset(names)
        # check_vma maps onto check_rep, except that partially-auto maps
        # cannot check at all in this jax; an explicit check_vma=False
        # (pallas bodies whose ShapeDtypeStructs carry no replication
        # info) must stay honored
        return _exp_shard_map(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs,
                              check_rep=bool(check_vma) and not auto,
                              auto=auto)
