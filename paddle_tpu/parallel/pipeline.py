"""Pipeline parallelism: GPipe-style microbatch streaming over a mesh axis.

TPU-first design (the reference's closest notion is device placement of
ops; it has no pipeline engine): stage parameters are STACKED on a leading
[n_stages, ...] axis sharded over the `pp` mesh axis, so each device holds
exactly its stage's weights. Inside shard_map, a lax.scan runs the classic
collective-permute pipeline: every tick each device applies its stage to
the activation it holds, then the ring `ppermute` hands the result to the
next stage while the first stage ingests the next microbatch. After
n_micro + n_stages - 1 ticks the last stage has emitted every microbatch.
Bubble fraction is (n_stages-1)/(n_micro+n_stages-1) — the standard GPipe
trade; raise n_micro to amortize.

`extras` are per-call tensors every stage reads but none produce (pad-mask
biases, encoder output for a pipelined decoder stack): replicated over the
pp axis and passed to stage_fn after the activation. This is what lets a
full Fluid transformer stack — not just a toy closure — run through the
pipeline (see fluid/transpiler/pipeline_transpiler.py).
"""
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ._sp import stack_unit_params, check_units_match_axis

__all__ = ['pipeline_apply', 'stack_stage_params']

# [{param pytree} per stage] -> pytree with leading [n_stages, ...] axis
stack_stage_params = stack_unit_params


def pipeline_apply(stage_fn, stacked_params, microbatches, mesh, axis='pp',
                   extras=(), extras_streamed=()):
    """Run the pipeline.

    stage_fn(params, x, *extras_streamed_mb, *extras) -> y
                    same signature for every stage; all stages must map
                    [mb, ...] -> same shape/dtype (equal widths — pad if
                    needed)
    stacked_params: pytree, leaves [n_stages, ...], sharded over `axis`
    microbatches:   [n_micro, mb, ...] (replicated or batch-sharded on dp)
    extras:         global tensors every stage reads whole (tied weights,
                    precomputed tables) — replicated over `axis`
    extras_streamed: batch-aligned tensors ([n_micro, mb, ...], microbatched
                    like x: pad-mask biases, a pipelined decoder's encoder
                    output). At tick t, stage k is processing microbatch
                    t - k, so each device dynamic-indexes its OWN in-flight
                    microbatch slice — the tensors do not ride the ring.
    Returns [n_micro, mb, ...]: the last stage's output per microbatch.
    """
    n_stages = mesh.shape[axis]
    n_micro = microbatches.shape[0]
    check_units_match_axis(stacked_params, mesh, axis, 'pipeline stage')
    from jax import shard_map
    n_stream = len(extras_streamed)

    def body(params, mbs, *ex):
        stream, glob = ex[:n_stream], ex[n_stream:]
        # params leaves arrive as [1, ...] (this device's stage); unstack
        p_local = jax.tree_util.tree_map(lambda x: x[0], params)
        idx = lax.axis_index(axis)
        is_first = idx == 0
        is_last = idx == n_stages - 1
        T = n_micro + n_stages - 1
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def tick(carry, t):
            held = carry  # [mb, ...] activation each device currently holds
            # first stage ingests microbatch t (or zeros past the end)
            mb_idx = jnp.minimum(t, n_micro - 1)
            fresh = lax.dynamic_index_in_dim(mbs, mb_idx, axis=0,
                                             keepdims=False)
            x = jnp.where(is_first, fresh, held)
            # stage idx processes microbatch t - idx at tick t (clipped to
            # a valid index during fill/drain; those results are discarded)
            my_mb = jnp.clip(t - idx, 0, n_micro - 1)
            sex = [lax.dynamic_index_in_dim(e, my_mb, axis=0,
                                            keepdims=False) for e in stream]
            y = stage_fn(p_local, x, *sex, *glob)
            # last stage emits y at tick t when t - (n_stages-1) >= 0
            emit_idx = t - (n_stages - 1)
            # everyone passes its output to the next stage; the wraparound
            # (last -> first) is ignored by the first stage's ingest above
            handed = lax.ppermute(y, axis, perm)
            return handed, (y, emit_idx)

        init = jnp.zeros(mbs.shape[1:], mbs.dtype)
        _, (ys, emit_idxs) = lax.scan(tick, init, jnp.arange(T))
        # gather the last stage's outputs in microbatch order
        out = jnp.zeros((n_micro,) + ys.shape[1:], ys.dtype)
        valid = emit_idxs >= 0
        valid_b = valid.reshape(valid.shape + (1,) * (ys.ndim - 1))
        out = out.at[jnp.where(valid, emit_idxs, 0)].add(
            jnp.where(valid_b, ys, 0.0))
        # only the last stage holds real outputs; broadcast them to all
        # shards so the result is replicated over the pp axis
        out = jnp.where(is_last, out, 0.0)
        out = lax.psum(out, axis)
        return out

    # compose with data parallel: when the mesh also carries 'dp', the
    # microbatch dim (dim 1 of [n_micro, mb, ...]) stays dp-sharded and
    # every dp slice runs its own pipeline; global extras stay replicated
    if 'dp' in mesh.shape and 'dp' != axis:
        dp = mesh.shape['dp']
        if microbatches.shape[1] % dp:
            raise ValueError(
                'per-microbatch size %d does not divide the dp mesh axis '
                '%d — lower n_micro or the dp size so every dp shard gets '
                'whole microbatch rows' % (microbatches.shape[1], dp))
        mb_spec = P(None, 'dp')
    else:
        mb_spec = P()
    # manual ONLY over dp + the pipeline axis: any other mesh axis (tp)
    # stays automatic, so GSPMD partitions the matmuls INSIDE each stage
    # by the stacked params' Megatron shardings and inserts the tp
    # all-reduces — the Megatron-style dp x pp x tp layout with no
    # hand-written tensor-parallel collectives
    manual = frozenset(a for a in ('dp', axis) if a in mesh.shape)
    fn = shard_map(
        body, mesh=mesh,
        in_specs=(jax.tree_util.tree_map(lambda _: P(axis), stacked_params),
                  mb_spec)
                 + tuple(mb_spec for _ in extras_streamed)
                 + tuple(P() for _ in extras),
        out_specs=mb_spec, axis_names=manual, check_vma=False)
    return fn(stacked_params, microbatches, *extras_streamed, *extras)
