"""Pipeline parallelism: microbatch streaming over a mesh axis — GPipe and
the circular (interleaved / virtual-stage) schedule.

TPU-first design (the reference's closest notion is device placement of
ops; it has no pipeline engine): stage parameters are STACKED on a leading
[n_stages, ...] axis sharded over the `pp` mesh axis, so each device holds
exactly its stages' weights. Inside shard_map, a lax.scan runs the classic
collective-permute pipeline: every tick each device applies one stage to
the activation it holds, then the ring `ppermute` hands the result to the
next device while the first device ingests the next microbatch.

With n_virtual == 1 this is GPipe: n_micro + S - 1 ticks, bubble fraction
(S-1)/(n_micro+S-1) — raise n_micro to amortize.

With n_virtual == v > 1 it is the circular schedule (Megatron/praxis
"interleaved 1F1B" loop placement): the model is cut into v*S chunks,
device d holding chunks {p*S + d : p < v}, and each microbatch rides the
ring v times. Microbatches are injected in rounds of S (n_micro must be a
multiple of S); the schedule position u = t - d decomposes uniquely as
u = ((r*v + p)*S + j), so every device applies exactly one chunk per tick
with no collisions. Total ticks v*n_micro + S - 1, each 1/v the cost of a
GPipe stage — the fill/drain bubble shrinks by v while per-device weight
memory stays the same. The backward schedule falls out of XLA transposing
the scan, exactly as for GPipe.

`extras` are per-call tensors every stage reads but none produce (pad-mask
biases, encoder output for a pipelined decoder stack): replicated over the
pp axis and passed to stage_fn after the activation. This is what lets a
full Fluid transformer stack — not just a toy closure — run through the
pipeline (see fluid/transpiler/pipeline_transpiler.py).
"""
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ._sp import stack_unit_params

__all__ = ['pipeline_apply', 'pipeline_manual_axes', 'stack_stage_params']


def pipeline_manual_axes(mesh, axis='pp'):
    """The mesh axes pipeline_apply's shard_map goes MANUAL over: dp, sp
    and the pipeline axis (tp stays automatic for GSPMD). Single source of
    truth — the Executor passes this same set into the stage Ctx so the
    attention lowering's per-shard routing always agrees with the actual
    shard_map axis_names."""
    return frozenset(a for a in ('dp', 'sp', axis) if a in mesh.shape)

# [{param pytree} per stage] -> pytree with leading [n_stages, ...] axis
stack_stage_params = stack_unit_params


def pipeline_apply(stage_fn, stacked_params, microbatches, mesh, axis='pp',
                   extras=(), extras_streamed=(), n_virtual=1,
                   param_specs=None):
    """Run the pipeline.

    stage_fn(params, x, *extras_streamed_mb, *extras) -> y
                    same signature for every stage; all stages must map
                    [mb, ...] -> same shape/dtype (equal widths — pad if
                    needed)
    stacked_params: pytree, leaves [n_virtual * S, ...] (S = pp axis size)
                    in sequential stage order — chunk g runs as phase
                    g // S on device g % S
    microbatches:   [n_micro, mb, ...] (replicated or batch-sharded on dp)
    extras:         global tensors every stage reads whole (tied weights,
                    precomputed tables) — replicated over `axis`
    extras_streamed: batch-aligned tensors ([n_micro, mb, ...], microbatched
                    like x: pad-mask biases, a pipelined decoder's encoder
                    output). Each device dynamic-indexes its OWN in-flight
                    microbatch slice — the tensors do not ride the ring.
                    CONTRACT under an 'sp' mesh axis: every streamed extra
                    must be sequence-shaped [batch, seq, ...] (seq % sp
                    == 0) — dim 2 post-microbatching is sharded over sp
                    like the activation's. A per-row feature extra
                    [batch, d] would have its FEATURE dim sharded;
                    restructure it as a replicated `extras` entry or fold
                    it into the activation when composing with sp.
    n_virtual:      chunks per device (circular schedule); > 1 requires
                    n_micro to be a multiple of S.
    Returns [n_micro, mb, ...]: the final chunk's output per microbatch.
    """
    S = mesh.shape[axis]
    v = int(n_virtual)
    n_micro = microbatches.shape[0]
    if v < 1:
        raise ValueError('n_virtual must be >= 1, got %d' % v)
    # an empty pytree (activation-only stages) is valid: nothing to shard
    for leaf in jax.tree_util.tree_leaves(stacked_params):
        if leaf.shape[0] != v * S:
            raise ValueError(
                'pipeline stage: stacked leading dim %d (leaf shape %r) '
                'must equal mesh axis %r size %d times n_virtual=%d (one '
                'chunk per device per phase)'
                % (leaf.shape[0], tuple(leaf.shape), axis, S, v))
    if v > 1 and n_micro % S:
        raise ValueError(
            'circular pipeline (n_virtual=%d) injects microbatches in '
            'rounds of S=%d; n_micro=%d is not a multiple' % (v, S, n_micro))
    from ._compat import shard_map
    n_stream = len(extras_streamed)

    # [v*S, ...] sequential chunk order -> [v, S, ...]: row p column d is
    # chunk p*S + d, so sharding dim 1 over the pp axis gives device d its
    # phase-indexed chunk block [v, 1, ...]
    stacked_params = jax.tree_util.tree_map(
        lambda w: w.reshape((v, S) + w.shape[1:]), stacked_params)
    if param_specs is not None:
        # pin the reshaped stack's layout: phase dim replicated, stage dim
        # over the pipeline axis, trailing dims keeping each weight's own
        # (tp) spec — GSPMD otherwise invents the transition from the
        # per-stage persisted shardings and falls back to full remat
        stacked_params = jax.tree_util.tree_map(
            lambda w, sp: lax.with_sharding_constraint(
                w, jax.sharding.NamedSharding(
                    mesh, P(None, axis, *sp))),
            stacked_params, param_specs)

    # Axes left AUTOMATIC inside the shard_map (tp): the per-tick
    # dynamic-slice of the microbatch stack, the scan carry, and the ring
    # ppermute output carry no natural tp sharding, so GSPMD used to
    # invent transitions for them — "Involuntary full rematerialization"
    # (replicate-then-repartition every tick; MULTICHIP_r04 tail). The
    # Megatron layout is unambiguous: ACTIVATIONS are replicated over tp,
    # only weights are tp-sharded (the column-split matmul consumes a
    # replicated x; the row-split one psums back to replicated). Pin that
    # with explicit constraints — specs mention no manual axis, so they
    # are legal inside the manual shard_map.
    manual_set = pipeline_manual_axes(mesh, axis)
    auto_axes = [a for a in mesh.shape if a not in manual_set]
    if auto_axes:
        # NamedSharding over a mesh whose axis types MATCH the shard_map
        # context (dp/pp/sp Manual, tp Auto): the raw all-Auto mesh fails
        # the context-mesh check when jax transposes the constraint in the
        # backward pass, and a bare PartitionSpec is too weak to stop the
        # partitioner's replicate-then-repartition on the matmul cotangent
        try:
            from jax.sharding import AxisType, Mesh as _Mesh, NamedSharding
        except ImportError:
            # jax<0.6 has no AxisType; skip the pin — a PERFORMANCE hint
            # (stops replicate-then-repartition on the cotangent), never
            # a correctness requirement
            _tp_replicated = lambda t: t
        else:
            pin_mesh = _Mesh(
                mesh.devices, mesh.axis_names,
                axis_types=tuple(AxisType.Manual if n in manual_set
                                 else AxisType.Auto for n in mesh.axis_names))
            _tp_replicated = lambda t: lax.with_sharding_constraint(
                t, NamedSharding(pin_mesh, P()))
    else:
        _tp_replicated = lambda t: t

    def body(params, mbs, *ex):
        stream, glob = ex[:n_stream], ex[n_stream:]
        idx = lax.axis_index(axis)
        is_first = idx == 0
        is_last = idx == S - 1
        T = v * n_micro + S - 1
        perm = [(i, (i + 1) % S) for i in range(S)]

        def tick(carry, t):
            held = carry  # [mb, ...] activation each device currently holds
            # schedule position: u = ((r*v + p)*S + j) uniquely — device
            # idx works round r, phase p, round-slot j at tick t
            u = t - idx
            j = u % S
            q = u // S
            if v > 1:
                p = q % v
                mb = (q // v) * S + j
            else:
                p = 0
                mb = u
            mb_c = jnp.clip(mb, 0, n_micro - 1)
            # first device ingests a fresh microbatch on phase 0; on later
            # phases it consumes the wrap-around activation from the ring
            # slice with keepdims and pin the 4-D [1, mb, ...] slice
            # BEFORE dropping the unit dim: the transpose of this chain is
            # a dynamic-update-slice of exactly that [1, mb, ...] cotangent
            # chunk, so the pin sits next to the scatter input. (One
            # degenerate cotangent transition in the dp x pp x tp segment
            # still draws a partitioner warning — docs/distributed.md,
            # "Known partitioner residue".)
            def slice_mb(t):
                s = _tp_replicated(
                    lax.dynamic_slice_in_dim(t, mb_c, 1, axis=0))
                return s[0]
            fresh = slice_mb(mbs)
            ingest = is_first if v == 1 else (is_first & (p == 0))
            # constraining x (not just fresh) matters for the BACKWARD
            # too: dx, the stage matmul's input cotangent, inherits the pin
            x = _tp_replicated(jnp.where(ingest, fresh, held))
            sex = [slice_mb(e) for e in stream]
            if v > 1:
                chunk = jax.tree_util.tree_map(
                    lambda w: lax.dynamic_index_in_dim(
                        w, p, axis=0, keepdims=False)[0], params)
            else:
                chunk = jax.tree_util.tree_map(lambda w: w[0, 0], params)
            y = stage_fn(chunk, x, *sex, *glob)
            # the last device completes microbatch mb on the final phase
            emit = (u >= 0) & (mb < n_micro) & (p == v - 1)
            emit_idx = jnp.where(emit, mb_c, -1)
            # everyone passes its output to the next device; the wraparound
            # (last -> first) either advances the phase or is ignored by
            # the first device's ingest above
            y = _tp_replicated(y)
            handed = lax.ppermute(y, axis, perm)
            return handed, (y, emit_idx)

        init = jnp.zeros(mbs.shape[1:], mbs.dtype)
        _, (ys, emit_idxs) = lax.scan(tick, init, jnp.arange(T))
        # gather the last device's completed outputs in microbatch order
        out = jnp.zeros((n_micro,) + ys.shape[1:], ys.dtype)
        valid = emit_idxs >= 0
        valid_b = valid.reshape(valid.shape + (1,) * (ys.ndim - 1))
        out = out.at[jnp.where(valid, emit_idxs, 0)].add(
            jnp.where(valid_b, ys, 0.0))
        # only the last device holds real outputs; broadcast them to all
        # shards so the result is replicated over the pp axis
        out = jnp.where(is_last, out, 0.0)
        out = lax.psum(out, axis)
        return out

    # compose with data parallel: when the mesh also carries 'dp', the
    # microbatch dim (dim 1 of [n_micro, mb, ...]) stays dp-sharded and
    # every dp slice runs its own pipeline; global extras stay replicated
    dp_axis = 'dp' if ('dp' in mesh.shape and 'dp' != axis) else None
    if dp_axis and microbatches.shape[1] % mesh.shape['dp']:
        raise ValueError(
            'per-microbatch size %d does not divide the dp mesh axis '
            '%d — lower n_micro or the dp size so every dp shard gets '
            'whole microbatch rows' % (microbatches.shape[1],
                                       mesh.shape['dp']))
    # compose with sequence parallel: an 'sp' mesh axis shards the
    # SEQUENCE dim (dim 2 of [n_micro, mb, T, ...]) of the activation and
    # every streamed extra; stage bodies then run sequence-local and the
    # attention lowering rides the sp ring via its per-shard collective
    # body (ops_impl/nn_ops.py routes on ctx.manual_axes)
    sp_axis = 'sp' if ('sp' in mesh.shape and 'sp' != axis) else None
    if sp_axis:
        sp = mesh.shape['sp']
        for t, name in [(microbatches, 'activation')] + \
                [(e, 'streamed extra') for e in extras_streamed]:
            if t.ndim < 3 or t.shape[2] % sp:
                raise ValueError(
                    'pp x sp: the %s (shape %r) needs a sequence dim at '
                    'index 2 divisible by the sp mesh axis size %d — '
                    'under sp every streamed extra must be sequence-shaped '
                    '[batch, seq, ...]; pass per-row features as a '
                    'replicated extra instead (see pipeline_apply '
                    'docstring)' % (name, tuple(t.shape), sp))

    def mbspec(ndim):
        spec = [None, dp_axis, sp_axis] + [None] * (ndim - 3)
        return P(*spec[:ndim])

    mb_spec = mbspec(microbatches.ndim)
    # manual ONLY over dp + sp + the pipeline axis: any other mesh axis
    # (tp) stays automatic, so GSPMD partitions the matmuls INSIDE each
    # stage by the stacked params' Megatron shardings and inserts the tp
    # all-reduces — the Megatron-style dp x pp x tp layout with no
    # hand-written tensor-parallel collectives
    manual = pipeline_manual_axes(mesh, axis)
    fn = shard_map(
        body, mesh=mesh,
        in_specs=(jax.tree_util.tree_map(lambda _: P(None, axis),
                                         stacked_params),
                  mb_spec)
                 + tuple(mbspec(e.ndim) for e in extras_streamed)
                 + tuple(P() for _ in extras),
        out_specs=mb_spec, axis_names=manual, check_vma=False)
    return fn(stacked_params, microbatches, *extras_streamed, *extras)
