"""Pipeline parallelism: GPipe-style microbatch streaming over a mesh axis.

TPU-first design (the reference's closest notion is device placement of
ops; it has no pipeline engine): stage parameters are STACKED on a leading
[n_stages, ...] axis sharded over the `pp` mesh axis, so each device holds
exactly its stage's weights. Inside shard_map, a lax.scan runs the classic
collective-permute pipeline: every tick each device applies its stage to
the activation it holds, then the ring `ppermute` hands the result to the
next stage while the first stage ingests the next microbatch. After
n_micro + n_stages - 1 ticks the last stage has emitted every microbatch.
Bubble fraction is (n_stages-1)/(n_micro+n_stages-1) — the standard GPipe
trade; raise n_micro to amortize.
"""
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ._sp import stack_unit_params, check_units_match_axis

__all__ = ['pipeline_apply', 'stack_stage_params']

# [{param pytree} per stage] -> pytree with leading [n_stages, ...] axis
stack_stage_params = stack_unit_params


def pipeline_apply(stage_fn, stacked_params, microbatches, mesh, axis='pp'):
    """Run the pipeline.

    stage_fn(params, x) -> y        same signature for every stage; all
                                    stages must map [mb, d] -> [mb, d]
                                    (equal widths — pad if needed)
    stacked_params: pytree, leaves [n_stages, ...], sharded over `axis`
    microbatches:   [n_micro, mb, d] (replicated or batch-sharded on dp)
    Returns [n_micro, mb, d]: the last stage's output per microbatch.
    """
    n_stages = mesh.shape[axis]
    n_micro = microbatches.shape[0]
    check_units_match_axis(stacked_params, mesh, axis, 'pipeline stage')
    from jax import shard_map

    def body(params, mbs):
        # params leaves arrive as [1, ...] (this device's stage); unstack
        p_local = jax.tree_util.tree_map(lambda x: x[0], params)
        idx = lax.axis_index(axis)
        is_first = idx == 0
        is_last = idx == n_stages - 1
        T = n_micro + n_stages - 1
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def tick(carry, t):
            held = carry  # [mb, d] activation each device currently holds
            # first stage ingests microbatch t (or zeros past the end)
            mb_idx = jnp.minimum(t, n_micro - 1)
            fresh = lax.dynamic_index_in_dim(mbs, mb_idx, axis=0,
                                             keepdims=False)
            x = jnp.where(is_first, fresh, held)
            y = stage_fn(p_local, x)
            # last stage emits y at tick t when t - (n_stages-1) >= 0
            emit_idx = t - (n_stages - 1)
            # everyone passes its output to the next stage; the wraparound
            # (last -> first) is ignored by the first stage's ingest above
            handed = lax.ppermute(y, axis, perm)
            return handed, (y, emit_idx)

        init = jnp.zeros(mbs.shape[1:], mbs.dtype)
        _, (ys, emit_idxs) = lax.scan(tick, init, jnp.arange(T))
        # gather the last stage's outputs in microbatch order
        out = jnp.zeros((n_micro,) + mbs.shape[1:], mbs.dtype)
        valid = emit_idxs >= 0
        out = out.at[jnp.where(valid, emit_idxs, 0)].add(
            jnp.where(valid[:, None, None], ys, 0.0))
        # only the last stage holds real outputs; broadcast them to all
        # shards so the result is replicated over the pp axis
        out = jnp.where(is_last, out, 0.0)
        out = lax.psum(out, axis)
        return out

    fn = shard_map(
        body, mesh=mesh,
        in_specs=(jax.tree_util.tree_map(lambda _: P(axis), stacked_params),
                  P()),
        out_specs=P(), check_vma=False)
    return fn(stacked_params, microbatches)
