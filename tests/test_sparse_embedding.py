"""Sparse embedding gradient path (is_sparse=True).

Parity: reference lookup_table_op.cc emits a SelectedRows grad when
is_sparse=True and the sgd/adagrad/adam ops update only the touched rows
(operators/sgd_op.h, adagrad_op.h, adam_op.h SelectedRows branches, with
MergeAdd merging duplicate ids first). Here the executor differentiates
w.r.t. a zero tap on each lookup's gathered rows and hands the optimizer a
lowering.SparseRows(ids, rows) — the vocab-sized dense @GRAD buffer never
materializes (VERDICT r4 item 4)."""
import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers

from util import fresh_program

VOCAB, DIM = 50, 8


def _default_model(is_sparse):
    """ids -> embedding(is_sparse) -> fc -> mean((pred - 1)^2)."""
    ids = layers.data(name='ids', shape=[4, 1], dtype='int64')
    emb = layers.embedding(ids, size=[VOCAB, DIM], is_sparse=is_sparse,
                           param_attr=fluid.ParamAttr(name='emb_w'))
    pred = layers.fc(input=emb, size=1, num_flatten_dims=2,
                     bias_attr=False,
                     param_attr=fluid.ParamAttr(name='fc_w'))
    return layers.mean(layers.square(pred - 1.0)), 'emb_w'


def _run_model(optimizer, is_sparse, ids_batches, seed=7, fetch_grad=False,
               dp=0, build=None):
    """Run a tiny embedding regression; returns (losses, table, plans,
    extra_scope_vars). `build(is_sparse) -> (loss, table_name)` swaps the
    model (default: _default_model)."""
    with fresh_program() as (main, startup):
        main.random_seed = seed
        startup.random_seed = seed
        loss, table_name = (build or _default_model)(is_sparse)
        optimizer().minimize(loss)
        if dp:
            fluid.DistributeTranspiler().transpile(trainer_id=0,
                                                   trainers=dp)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        fetch = [loss] + (['%s@GRAD' % table_name] if fetch_grad else [])
        losses = []
        for b in ids_batches:
            feed = b if isinstance(b, dict) else {'ids': b}
            out = exe.run(main, feed=feed, fetch_list=fetch)
            losses.append(float(np.asarray(out[0])))
        from paddle_tpu.fluid.executor import global_scope
        scope = global_scope()
        table = np.asarray(scope.find_var(table_name).get_tensor())
        plans = [s.sparse_plan for s in exe._cache.values()]
        extras = {n: np.asarray(scope.find_var(n).get_tensor())
                  for n in scope.vars if 'moment' in n or table_name == n}
        return losses, table, plans, extras


def _batches(seed=3, n=3, dup=False):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        b = rng.randint(0, VOCAB, size=(6, 4, 1)).astype('int64')
        if dup:
            b[:3] = b[3:6]  # force duplicate ids within the batch
        out.append(b.reshape(6, 4, 1))
    return out


def test_sparse_sgd_matches_dense_exactly():
    """SGD is linear in the gradient: the scatter-add row update equals
    the dense result up to float accumulation order, duplicates
    included."""
    sgd = lambda: fluid.optimizer.SGD(learning_rate=0.1)
    batches = _batches(dup=True)
    dense_l, dense_t, dense_plans, _ = _run_model(sgd, False, batches)
    sparse_l, sparse_t, sparse_plans, _ = _run_model(sgd, True, batches)
    assert any(p for p in sparse_plans), 'sparse plan never activated'
    assert not any(p for p in dense_plans)
    np.testing.assert_allclose(sparse_l, dense_l, rtol=1e-5)
    np.testing.assert_allclose(sparse_t, dense_t, rtol=1e-4, atol=1e-6)


def test_sparse_adagrad_matches_dense():
    """Dense adagrad leaves untouched rows exactly unchanged (g=0 =>
    m+=0, p-=0), so the touched-rows-only sparse update must agree
    everywhere — with duplicates MERGED before the nonlinear g^2
    (reference MergeAdd + adagrad_op.h)."""
    opt = lambda: fluid.optimizer.Adagrad(learning_rate=0.1)
    batches = _batches(dup=True)
    dense_l, dense_t, _, _ = _run_model(opt, False, batches)
    sparse_l, sparse_t, plans, _ = _run_model(opt, True, batches)
    assert any(p for p in plans)
    np.testing.assert_allclose(sparse_l, dense_l, rtol=1e-5)
    np.testing.assert_allclose(sparse_t, dense_t, rtol=1e-4, atol=1e-6)


def test_sparse_adam_lazy_rows_semantics():
    """Sparse adam is the reference's lazy SelectedRows semantic: rows the
    batch does not touch keep their params AND moments (dense adam decays
    every row's moments each step). Touched rows follow the merged-grad
    adam formula."""
    opt = lambda: fluid.optimizer.Adam(learning_rate=0.01)
    # batch 1 touches only ids 0..3, batch 2 only ids 4..7
    b1 = np.array([0, 1, 2, 3] * 6).reshape(6, 4, 1).astype('int64')
    b2 = np.array([4, 5, 6, 7] * 6).reshape(6, 4, 1).astype('int64')
    losses, table, plans, extras = _run_model(opt, True, [b1, b2])
    assert any(p for p in plans)
    # ids >= 8 never touched: table rows must equal their init — compare
    # against a run with zero steps
    _, table0, _, _ = _run_model(opt, True, [])
    np.testing.assert_array_equal(table[8:], table0[8:])
    # rows 0..3 were touched in step 1 only; their moments are nonzero
    m1 = next(v for n, v in extras.items() if 'moment1' in n and
              v.shape == (VOCAB, DIM))
    assert np.abs(m1[:4]).max() > 0
    assert np.abs(m1[8:]).max() == 0      # untouched: moments never built


def test_sparse_falls_back_dense_when_grad_is_fetched():
    """Fetching W@GRAD forces the dense buffer (the wrapper is internal)."""
    sgd = lambda: fluid.optimizer.SGD(learning_rate=0.1)
    batches = _batches(n=1)
    losses, _, plans, _ = _run_model(sgd, True, batches, fetch_grad=True)
    assert not any(p for p in plans)


def test_sparse_falls_back_dense_under_mesh():
    """Under dp the dense grad is the all-reducible thing — plan empty,
    numerics still match single-device."""
    sgd = lambda: fluid.optimizer.SGD(learning_rate=0.1)
    batches = _batches(n=2)
    base_l, base_t, _, _ = _run_model(sgd, True, batches)
    dp_l, dp_t, plans, _ = _run_model(sgd, True, batches, dp=2)
    assert not any(p for p in plans)
    np.testing.assert_allclose(dp_l, base_l, rtol=1e-5)
    np.testing.assert_allclose(dp_t, base_t, rtol=1e-5)


def test_sparse_grad_never_materializes_dense_buffer():
    """The compiled HLO of the sparse step contains no vocab-sized
    gradient temporary: every [VOCAB, DIM] tensor in the module is the
    table or its scatter-update chain, and the lowered step's adagrad
    update is scatter-based."""
    opt = lambda: fluid.optimizer.SGD(learning_rate=0.1)
    with fresh_program() as (main, startup):
        ids = layers.data(name='ids', shape=[4, 1], dtype='int64')
        emb = layers.embedding(ids, size=[VOCAB, DIM], is_sparse=True,
                               param_attr=fluid.ParamAttr(name='emb_w'))
        pred = layers.fc(input=emb, size=1, num_flatten_dims=2,
                         bias_attr=False)
        loss = layers.mean(layers.square(pred - 1.0))
        opt().minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        feed = {'ids': np.zeros((6, 4, 1), 'int64')}
        hlo = exe.lowered_hlo(main, feed, [loss], optimized=True)
        assert 'scatter' in hlo
        # the dense path's signature move — subtract over the full table
        # (p - lr*g as one [VOCAB, DIM] subtract) — must be absent; the
        # sparse update touches [24, DIM] row blocks instead
        assert 'subtract(f32[%d,%d]' % (VOCAB, DIM) not in hlo.replace(
            ' ', '')


def test_sparse_handles_multiple_lookups_of_one_table():
    """A table read by TWO is_sparse lookups (shared embedding, e.g. the
    book's tied 'vemb') still takes the sparse path: both taps' rows
    concatenate into one SparseRows and the update matches dense."""
    sgd = lambda: fluid.optimizer.SGD(learning_rate=0.1)

    def build(is_sparse):
        a = layers.data(name='a', shape=[3, 1], dtype='int64')
        b = layers.data(name='b', shape=[2, 1], dtype='int64')
        ea = layers.embedding(a, size=[VOCAB, DIM], is_sparse=is_sparse,
                              param_attr=fluid.ParamAttr(name='shared_w'))
        eb = layers.embedding(b, size=[VOCAB, DIM], is_sparse=is_sparse,
                              param_attr=fluid.ParamAttr(name='shared_w'))
        pa = layers.fc(input=ea, size=1, num_flatten_dims=2,
                       bias_attr=False,
                       param_attr=fluid.ParamAttr(name='fa'))
        pb = layers.fc(input=eb, size=1, num_flatten_dims=2,
                       bias_attr=False,
                       param_attr=fluid.ParamAttr(name='fb'))
        loss = layers.mean(layers.square(pa - 1.0)) + \
            layers.mean(layers.square(pb + 1.0))
        return loss, 'shared_w'

    rng = np.random.RandomState(5)
    batches = [{
        'a': rng.randint(0, VOCAB, size=(4, 3, 1)).astype('int64'),
        'b': rng.randint(0, VOCAB, size=(4, 2, 1)).astype('int64'),
    } for _ in range(3)]
    dl, dt, dplans, _ = _run_model(sgd, False, batches, seed=11,
                                   build=build)
    sl, st, splans, _ = _run_model(sgd, True, batches, seed=11, build=build)
    assert not any(p for p in dplans if p)
    assert any('shared_w' in p for p in splans if p)
    # both lookups listed under the one plan entry
    plan = next(p for p in splans if p)['shared_w']
    assert len(plan['lookups']) == 2
    np.testing.assert_allclose(sl, dl, rtol=1e-5)
    np.testing.assert_allclose(st, dt, rtol=1e-4, atol=1e-6)
