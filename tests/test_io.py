"""io: persistables save/load, checkpoint/resume. Mirrors reference
test_io_save_load / checkpoint utilities."""
import numpy as np

import paddle_tpu.fluid as fluid
import paddle_tpu.fluid.layers as layers

from util import fresh_program


def _small_net():
    x = layers.data(name='x', shape=[4])
    y = layers.data(name='y', shape=[1])
    pred = layers.fc(input=x, size=1)
    loss = layers.mean(layers.square_error_cost(input=pred, label=y))
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return pred, loss


def test_save_load_persistables_round_trip(tmp_path):
    r = np.random.RandomState(0)
    xv = r.rand(8, 4).astype('float32')
    yv = r.rand(8, 1).astype('float32')
    with fresh_program() as (main, startup):
        pred, loss = _small_net()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        exe.run(main, feed={'x': xv, 'y': yv}, fetch_list=[loss])
        fluid.io.save_persistables(exe, str(tmp_path), main_program=main)
        want, = exe.run(main.clone(for_test=True).prune([pred]), feed={'x': xv},
                        fetch_list=[pred])
    with fresh_program() as (main2, startup2):
        pred2, loss2 = _small_net()
        exe2 = fluid.Executor(fluid.CPUPlace())
        exe2.run(startup2)
        fluid.io.load_persistables(exe2, str(tmp_path), main_program=main2)
        got, = exe2.run(main2.clone(for_test=True).prune([pred2]), feed={'x': xv},
                        fetch_list=[pred2])
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_checkpoint_resume(tmp_path):
    r = np.random.RandomState(1)
    xv = r.rand(8, 4).astype('float32')
    yv = r.rand(8, 1).astype('float32')
    with fresh_program() as (main, startup):
        pred, loss = _small_net()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        for _ in range(3):
            exe.run(main, feed={'x': xv, 'y': yv}, fetch_list=[loss])
        fluid.io.save_checkpoint(exe, str(tmp_path), main_program=main,
                                 step=3)
        want, = exe.run(main.clone(for_test=True).prune([pred]), feed={'x': xv},
                        fetch_list=[pred])
    with fresh_program() as (main2, startup2):
        pred2, loss2 = _small_net()
        exe2 = fluid.Executor(fluid.CPUPlace())
        exe2.run(startup2)
        meta = fluid.io.load_checkpoint(exe2, str(tmp_path),
                                        main_program=main2)
        assert meta['step'] == 3
        got, = exe2.run(main2.clone(for_test=True).prune([pred2]), feed={'x': xv},
                        fetch_list=[pred2])
    np.testing.assert_allclose(got, want, rtol=1e-6)
