"""Shared test helpers: fresh programs per test."""
import contextlib

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import framework, unique_name
from paddle_tpu.fluid.executor import Scope, _switch_scope


@contextlib.contextmanager
def fresh_program():
    """Isolated main/startup program + scope + name generator."""
    main = framework.Program()
    startup = framework.Program()
    scope = Scope()
    prev_scope = _switch_scope(scope)
    with unique_name.guard():
        with framework.program_guard(main, startup):
            try:
                yield main, startup
            finally:
                _switch_scope(prev_scope)
