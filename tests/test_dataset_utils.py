"""Dataset utility APIs: common.split/cluster_files_reader/convert and
the per-dataset convert/info helpers (reference python/paddle/dataset/
common.py + tests/common_test.py)."""
import pickle

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.dataset import common, movielens
from paddle_tpu.reader.recordio import RecordIOReader


def _ints(n):
    def r():
        for i in range(n):
            yield (i, i * i)
    return r


def test_split_and_cluster_files_reader(tmp_path):
    suffix = str(tmp_path / 'part-%05d.pickle')
    n_files = common.split(_ints(25), line_count=10, suffix=suffix)
    assert n_files == 3
    # every trainer sees a disjoint round-robin subset; union == all
    seen = []
    for tid in range(2):
        r = common.cluster_files_reader(str(tmp_path / 'part-*.pickle'),
                                        trainer_count=2, trainer_id=tid)
        seen.append(sorted(s[0] for s in r()))
    assert sorted(seen[0] + seen[1]) == list(range(25))
    assert not set(seen[0]) & set(seen[1])
    with pytest.raises(TypeError):
        common.split(_ints(3), 2, suffix, dumper="not callable")


def test_convert_writes_recordio_shards(tmp_path):
    n = common.convert(str(tmp_path), _ints(23), 10, "toy")
    assert n == 23
    files = sorted(p.name for p in tmp_path.iterdir())
    assert files == ['toy-00000', 'toy-00001', 'toy-00002']
    samples = []
    for f in files:
        for payload in RecordIOReader(str(tmp_path / f)):
            samples.append(pickle.loads(payload))
    assert sorted(s[0] for s in samples) == list(range(23))


def test_dataset_convert_wrappers(tmp_path):
    # smoke one light wrapper end-to-end (uci-free: mnist is big; use
    # imikolov which is 4096+512 small tuples)
    paddle.dataset.imikolov.convert(str(tmp_path))
    names = sorted(p.name for p in tmp_path.iterdir())
    assert any(n.startswith('imikolov_train-') for n in names)
    assert any(n.startswith('imikolov_test-') for n in names)
    payload = next(iter(RecordIOReader(
        str(tmp_path / [n for n in names if 'train' in n][0]))))
    sample = pickle.loads(payload)
    assert len(sample) == 5  # 5-gram


def test_movielens_info():
    movies = movielens.movie_info()
    users = movielens.user_info()
    assert len(movies) == movielens.max_movie_id()
    assert len(users) == movielens.max_user_id()
    m = movies[1]
    idx, cats, title = m.value()
    assert idx == 1 and len(cats) == 1 and len(title) == 3
    assert 'MovieInfo' in repr(m)
    u = users[1]
    uv = u.value()
    assert uv[0] == 1 and uv[1] in (0, 1)
    assert 0 <= uv[2] < len(movielens.age_table)
    assert 'UserInfo' in repr(u)


def test_wmt_dict_helpers():
    src, trg = paddle.dataset.wmt14.get_dict(100)
    assert src[5] == 'w5'  # reversed: id -> word
    d = paddle.dataset.wmt16.get_dict('en', 50)
    assert d['w7'] == 7
    assert paddle.dataset.wmt16.fetch() is None
    val = paddle.dataset.wmt16.validation(100, 100)
    s = next(val())
    assert len(s) == 3
    assert paddle.dataset.imdb.build_dict() == paddle.dataset.imdb.word_dict()
