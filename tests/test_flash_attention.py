"""Flash-attention kernel numerics (pallas interpret mode on CPU) and the
fused_attention fluid op, vs the plain-XLA oracle."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu import ops
import paddle_tpu.fluid as fluid
import paddle_tpu.fluid.layers as layers

from util import fresh_program


def _rand_qkv(B=2, H=2, Tq=20, Tk=20, D=16, seed=0):
    r = np.random.RandomState(seed)
    q = r.randn(B, H, Tq, D).astype('float32')
    k = r.randn(B, H, Tk, D).astype('float32')
    v = r.randn(B, H, Tk, D).astype('float32')
    kb = np.where(r.rand(B, Tk) < 0.25, -1e9, 0.0).astype('float32')
    kb[:, 0] = 0.0   # keep at least one live key per row
    return q, k, v, kb


@pytest.mark.parametrize('causal', [False, True])
@pytest.mark.parametrize('with_bias', [False, True])
def test_forward_matches_reference(causal, with_bias):
    q, k, v, kb = _rand_qkv()
    bias = kb if with_bias else None
    got = ops.flash_attention(q, k, v, key_bias=bias, causal=causal,
                              interpret=True)
    want = ops.reference_attention(q, k, v, key_bias=bias, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_forward_uneven_lengths():
    # Tq != Tk and non-multiple-of-block sizes exercise the padding path
    q, k, v, kb = _rand_qkv(Tq=9, Tk=33)
    got = ops.flash_attention(q, k, v, key_bias=kb, interpret=True)
    want = ops.reference_attention(q, k, v, key_bias=kb)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize('causal', [False, True])
def test_gradients_match_reference(causal):
    q, k, v, kb = _rand_qkv(B=1, H=2, Tq=12, Tk=12, D=8, seed=1)

    def loss_flash(q, k, v):
        o = ops.flash_attention(q, k, v, key_bias=kb, causal=causal,
                                interpret=True)
        return jnp.sum(o * jnp.cos(o))

    def loss_ref(q, k, v):
        o = ops.reference_attention(q, k, v, key_bias=kb, causal=causal)
        return jnp.sum(o * jnp.cos(o))

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g1, g2, 'qkv'):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-4, atol=3e-4, err_msg=name)


def test_fused_attention_layer():
    B, H, T, D = 2, 2, 6, 4
    r = np.random.RandomState(3)
    qv = r.randn(B, H, T, D).astype('float32')
    kv = r.randn(B, H, T, D).astype('float32')
    vv = r.randn(B, H, T, D).astype('float32')
    with fresh_program() as (main, startup):
        q = layers.data(name='q', shape=[H, T, D], dtype='float32')
        k = layers.data(name='k', shape=[H, T, D], dtype='float32')
        v = layers.data(name='v', shape=[H, T, D], dtype='float32')
        out = layers.fused_attention(q, k, v, causal=True)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        got, = exe.run(main, feed={'q': qv, 'k': kv, 'v': vv},
                       fetch_list=[out])
    want = ops.reference_attention(qv, kv, vv, causal=True)
    np.testing.assert_allclose(got, np.asarray(want), rtol=1e-5, atol=1e-5)


def test_ring_attention_matches_full():
    from paddle_tpu import parallel
    from paddle_tpu.parallel.ring_attention import ring_self_attention
    mesh = parallel.make_mesh({'sp': 8})
    B, H, T, D = 2, 2, 16, 4
    r = np.random.RandomState(4)
    q = r.randn(B, H, T, D).astype('float32')
    k = r.randn(B, H, T, D).astype('float32')
    v = r.randn(B, H, T, D).astype('float32')
    kb = np.where(r.rand(B, T) < 0.25, -1e9, 0.0).astype('float32')
    kb[:, 0] = 0.0
    for causal in (False, True):
        got = ring_self_attention(mesh, jnp.asarray(q), jnp.asarray(k),
                                  jnp.asarray(v), axis='sp',
                                  key_bias=jnp.asarray(kb), causal=causal)
        want = ops.reference_attention(q, k, v, key_bias=kb, causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5,
                                   err_msg='causal=%s' % causal)


def test_ulysses_attention_matches_full_and_ring():
    from paddle_tpu import parallel
    from paddle_tpu.parallel.ring_attention import ring_self_attention
    from paddle_tpu.parallel.ulysses import ulysses_self_attention
    mesh = parallel.make_mesh({'sp': 8})
    B, H, T, D = 2, 8, 16, 4       # H divisible by sp=8
    r = np.random.RandomState(5)
    q = r.randn(B, H, T, D).astype('float32')
    k = r.randn(B, H, T, D).astype('float32')
    v = r.randn(B, H, T, D).astype('float32')
    kb = np.where(r.rand(B, T) < 0.25, -1e9, 0.0).astype('float32')
    kb[:, 0] = 0.0
    for causal in (False, True):
        got = ulysses_self_attention(mesh, jnp.asarray(q), jnp.asarray(k),
                                     jnp.asarray(v), axis='sp',
                                     key_bias=jnp.asarray(kb), causal=causal)
        want = ops.reference_attention(q, k, v, key_bias=kb, causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5,
                                   err_msg='causal=%s' % causal)
        ring = ring_self_attention(mesh, jnp.asarray(q), jnp.asarray(k),
                                   jnp.asarray(v), axis='sp',
                                   key_bias=jnp.asarray(kb), causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ring),
                                   rtol=2e-5, atol=2e-5)


def test_ulysses_rejects_indivisible_heads():
    import pytest
    from paddle_tpu import parallel
    from paddle_tpu.parallel.ulysses import ulysses_self_attention
    mesh = parallel.make_mesh({'sp': 8})
    q = jnp.zeros((1, 3, 16, 4), jnp.float32)   # 3 heads, sp=8
    with pytest.raises(ValueError, match='ring_self_attention'):
        ulysses_self_attention(mesh, q, q, q, axis='sp')


def test_forward_multiblock_grids():
    # multi-block q AND k grids (2x2) — exercises the scratch accumulation
    # across the innermost grid dim and the revisited output block
    q, k, v, kb = _rand_qkv(B=2, H=2, Tq=256, Tk=256, D=32, seed=7)
    for causal in (False, True):
        got = ops.flash_attention(q, k, v, key_bias=kb, causal=causal,
                                  interpret=True)
        want = ops.reference_attention(q, k, v, key_bias=kb, causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5,
                                   err_msg='causal=%s' % causal)


def test_gradients_multiblock():
    q, k, v, kb = _rand_qkv(B=1, H=1, Tq=256, Tk=256, D=16, seed=8)

    def mk(fn):
        def g(q, k, v):
            o = fn(q, k, v, key_bias=kb, causal=True)
            return jnp.sum(o * jnp.sin(o))
        return jax.grad(g, argnums=(0, 1, 2))

    g1 = mk(lambda *a, **kw: ops.flash_attention(*a, interpret=True, **kw))(q, k, v)
    g2 = mk(ops.reference_attention)(q, k, v)
    for a, b, name in zip(g1, g2, 'qkv'):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-4, err_msg=name)


def test_flash_attention_lse_forward_and_grads():
    """(o, lse) wrapper: lse matches the oracle logsumexp, and gradients
    flow correctly through BOTH outputs (the delta - dlse trick)."""
    q, k, v, kb = _rand_qkv(B=1, H=2, Tq=12, Tk=12, D=8, seed=5)

    def ref_o_lse(q, k, v, causal):
        D = q.shape[-1]
        s = jnp.einsum('bhqd,bhkd->bhqk', q, k) * D ** -0.5
        s = s + kb[:, None, None, :]
        if causal:
            T = q.shape[2]
            m = jnp.arange(T)[:, None] >= jnp.arange(T)[None, :]
            s = jnp.where(m, s, -1e9)
        lse = jax.scipy.special.logsumexp(s, axis=-1)
        o = jnp.einsum('bhqk,bhkd->bhqd', jax.nn.softmax(s, -1), v)
        return o, lse

    for causal in (False, True):
        o, lse = ops.flash_attention_lse(q, k, v, key_bias=kb,
                                         causal=causal, interpret=True)
        ro, rlse = ref_o_lse(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                             causal)
        np.testing.assert_allclose(np.asarray(o), np.asarray(ro),
                                   rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(np.asarray(lse), np.asarray(rlse),
                                   rtol=2e-5, atol=2e-5)

        # a loss touching BOTH o and lse — this exercises the lse cotangent
        def loss_flash(q, k, v, _c=causal):
            o, lse = ops.flash_attention_lse(q, k, v, key_bias=kb,
                                             causal=_c, interpret=True)
            return jnp.sum(o * jnp.cos(o)) + jnp.sum(jnp.sin(lse))

        def loss_ref(q, k, v, _c=causal):
            o, lse = ref_o_lse(q, k, v, _c)
            return jnp.sum(o * jnp.cos(o)) + jnp.sum(jnp.sin(lse))

        g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
        g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
        for a, b, name in zip(g1, g2, 'qkv'):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=3e-4, atol=3e-4,
                                       err_msg='causal=%s %s' % (causal, name))


def test_ring_attention_flash_impl_matches_dense_and_full():
    """The flash-backed ring (per-shard pallas blocks + lse merge) agrees
    with the dense ring and the full-attention oracle, fwd and bwd."""
    from paddle_tpu import parallel
    from paddle_tpu.parallel.ring_attention import ring_self_attention
    mesh = parallel.make_mesh({'sp': 4})
    B, H, T, D = 2, 2, 16, 4
    r = np.random.RandomState(6)
    q = jnp.asarray(r.randn(B, H, T, D).astype('float32'))
    k = jnp.asarray(r.randn(B, H, T, D).astype('float32'))
    v = jnp.asarray(r.randn(B, H, T, D).astype('float32'))
    kbn = np.where(r.rand(B, T) < 0.25, -1e9, 0.0).astype('float32')
    kbn[:, 0] = 0.0
    kb = jnp.asarray(kbn)
    for causal in (False, True):
        got = ring_self_attention(mesh, q, k, v, axis='sp', key_bias=kb,
                                  causal=causal, impl='flash')
        want = ops.reference_attention(q, k, v, key_bias=kb, causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=3e-5, atol=3e-5,
                                   err_msg='causal=%s' % causal)

        def loss_ring(q, k, v, _c=causal):
            o = ring_self_attention(mesh, q, k, v, axis='sp', key_bias=kb,
                                    causal=_c, impl='flash')
            return jnp.sum(o * jnp.cos(o))

        def loss_full(q, k, v, _c=causal):
            o = ops.reference_attention(q, k, v, key_bias=kb, causal=_c)
            return jnp.sum(o * jnp.cos(o))

        g1 = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(g1, g2, 'qkv'):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=5e-4, atol=5e-4,
                                       err_msg='causal=%s %s' % (causal, name))


def test_tri_maps_enumerate_lower_triangle():
    from paddle_tpu.ops.flash_attention import (_tri_maps, _tri_maps_kv,
                                                _use_tri)
    for n in (1, 2, 3, 5):
        im, jm = _tri_maps(n)
        assert len(im) == n * (n + 1) // 2
        assert set(zip(im.tolist(), jm.tolist())) == {
            (i, j) for i in range(n) for j in range(i + 1)}
        # row-major: q-block index non-decreasing, each row starts at j=0
        assert all(im[t] <= im[t + 1] for t in range(len(im) - 1))
        im2, jm2 = _tri_maps_kv(n)
        assert set(zip(im2.tolist(), jm2.tolist())) == {
            (i, j) for i in range(n) for j in range(i + 1)}
        # k-block-major: within a k-block, q runs j..n-1 consecutively
        starts = [t for t in range(len(im2)) if im2[t] == jm2[t]]
        assert len(starts) == n
    # selection predicate: aligned causal self-attention only
    assert _use_tri(True, 256, 256, 128, 128)
    assert not _use_tri(False, 256, 256, 128, 128)   # not causal
    assert not _use_tri(True, 256, 512, 128, 128)    # cross lengths
    assert not _use_tri(True, 256, 256, 128, 64)     # uneven blocks
    assert not _use_tri(True, 128, 128, 128, 128)    # single block


def test_causal_triangular_grid_3x3_forward_and_grads():
    """3x3-block causal triangle (T=384, bq=bk=128): the scalar-prefetch
    grid must agree with the XLA oracle through forward and backward."""
    q, k, v, kb = _rand_qkv(B=2, H=1, Tq=384, Tk=384, D=16, seed=11)
    got = ops.flash_attention(q, k, v, key_bias=kb, causal=True,
                              interpret=True)
    want = ops.reference_attention(q, k, v, key_bias=kb, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)

    def mk(fn):
        def g(q, k, v):
            o = fn(q, k, v, key_bias=kb, causal=True)
            return jnp.sum(o * jnp.sin(o))
        return jax.grad(g, argnums=(0, 1, 2))

    g1 = mk(lambda *a, **kw: ops.flash_attention(*a, interpret=True, **kw))(q, k, v)
    g2 = mk(ops.reference_attention)(q, k, v)
    for a, b, name in zip(g1, g2, 'qkv'):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-4, err_msg=name)
