"""Elastic pod training drills (docs/robustness.md#elastic).

The PR-1 fault-tolerance story re-done at pod scale: annotated (mesh)
programs checkpoint SHARDED through the Trainer (each host writes only
its shards — never a gathered dense table), saves commit atomically
(staging dir + manifest-last + rename, so a SIGKILL mid-save can never
leave a latest-looking torn serial), restore reshards onto whatever
topology survives (8 devices -> 4), and a heartbeat layer surfaces a
dead host as the typed parallel.HostLost after an emergency flush.

Every drill injects its faults through utils.faults.FaultInjector (or a
real SIGKILL on a child process), and the telemetry assertions verify an
operator could have SEEN each decision (docs/observability.md).
"""
import json
import os
import re
import signal
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

import jax

import paddle_tpu.fluid as fluid
from paddle_tpu import obs, parallel
from paddle_tpu.obs import report as obs_report
from paddle_tpu.parallel import Heartbeat, HostLost
from paddle_tpu.utils import checkpoint as ck
from paddle_tpu.utils.faults import FaultInjector

pytestmark = pytest.mark.elastic

VOCAB, DIM = 64, 4


@pytest.fixture
def obs_events(tmp_path):
    """Run-log reader fixture (the test_faults idiom): behavior AND its
    telemetry are both asserted."""
    obs.enable(str(tmp_path / 'obs'))

    def read(name=None):
        path = obs.run_log_path()
        if path is None:
            return []
        events, errors = obs_report.load_events(path)
        assert errors == [], errors
        return [e for e in events if name is None or e['name'] == name]

    try:
        yield read
    finally:
        obs._reset()


# ---------------------------------------------------------------------------
# helpers: annotated trainers
# ---------------------------------------------------------------------------

_W = np.array([[1.5], [-2.0], [0.5], [3.0]], 'float32')


def _linear_train_func():
    x = fluid.layers.data(name='x', shape=[4], dtype='float32')
    y = fluid.layers.data(name='y', shape=[1], dtype='float32')
    pred = fluid.layers.fc(input=x, size=1,
                           param_attr=fluid.ParamAttr(name='w'),
                           bias_attr=fluid.ParamAttr(name='b'))
    return fluid.layers.mean(
        fluid.layers.square_error_cost(input=pred, label=y))


def _linear_reader(n=64, batch=8, seed=0):
    def r():
        rng = np.random.RandomState(seed)
        for _ in range(n // batch):
            xs = rng.rand(batch, 4).astype('float32')
            ys = xs @ _W
            yield [(xs[i], ys[i]) for i in range(batch)]
    return r


def _emb_train_func():
    """Vocab-sharded table + fc head: the state whose checkpoint must
    NEVER gather dense (the adam moments inherit the annotation)."""
    ids = fluid.layers.data(name='ids', shape=[2, 1], dtype='int64')
    emb = fluid.layers.embedding(
        ids, size=[VOCAB, DIM],
        param_attr=fluid.ParamAttr(name='emb_w', sharding=('dp', None)))
    pred = fluid.layers.fc(input=emb, size=1, num_flatten_dims=2,
                           bias_attr=False,
                           param_attr=fluid.ParamAttr(name='fc_w'))
    return fluid.layers.mean(fluid.layers.square(pred - 1.0))


def _emb_reader(n_batches=16, batch=8, seed=3):
    def r():
        rng = np.random.RandomState(seed)
        for _ in range(n_batches):
            b = rng.randint(0, VOCAB, size=(batch, 2, 1)).astype('int64')
            yield [(b[i],) for i in range(batch)]
    return r


def _mesh_hook(axes):
    return lambda p: p.set_mesh(axes)


def _sgd():
    return fluid.optimizer.SGD(learning_rate=0.1)


def _adam():
    return fluid.optimizer.Adam(learning_rate=0.05)


class Crash(Exception):
    pass


def _losses_handler(losses, crash_at=None):
    def handler(ev):
        if isinstance(ev, fluid.EndStepEvent):
            losses.append(((ev.epoch, ev.step),
                           float(np.asarray(ev.metrics[0]))))
            if crash_at is not None and (ev.epoch, ev.step) == crash_at:
                raise Crash()
    return handler


def _named_shardings(state):
    from jax.sharding import NamedSharding
    return {n: v.sharding for n, v in state.items()
            if isinstance(v.sharding, NamedSharding)}


# ---------------------------------------------------------------------------
# Executor.state_dict / load_state_dict: the sharded-checkpoint seam
# ---------------------------------------------------------------------------

def test_state_dict_walks_placements_and_round_trips(tmp_path):
    """state_dict returns the LIVE mesh placements (the vocab-sharded
    table as 8 device shards, moments inheriting the annotation) and
    load_state_dict restores them bit-exact."""
    tr = fluid.Trainer(train_func=_emb_train_func, optimizer_func=_adam,
                       place=fluid.CPUPlace(),
                       transpiler_fn=_mesh_hook({'dp': 8}))
    tr.train(num_epochs=1, event_handler=lambda ev: None,
             reader=_emb_reader(4), feed_order=['ids'])
    state = tr.exe.state_dict(tr.train_program, scope=tr.scope)
    assert 'emb_w' in state and 'fc_w' in state
    sh = _named_shardings(state)
    assert str(sh['emb_w'].spec) == "PartitionSpec('dp',)" \
        or str(sh['emb_w'].spec) == "PartitionSpec('dp', None)"
    # every device holds 1/8 of the vocab — never the dense table
    assert state['emb_w'].addressable_shards[0].data.shape == (VOCAB // 8,
                                                               DIM)
    moments = [n for n in state
               if 'emb_w' in n and n != 'emb_w'
               and state[n].shape == (VOCAB, DIM)]
    assert moments, sorted(state)
    for m in moments:
        assert state[m].addressable_shards[0].data.shape == (VOCAB // 8,
                                                             DIM), m
    # round trip: clobber the scope, restore, compare bit-exact
    want = {n: np.array(np.asarray(v), copy=True)
            for n, v in state.items()}
    snapshot = dict(state)
    for n in snapshot:
        tr.scope.vars[n] = jax.numpy.zeros_like(snapshot[n])
    restored = tr.exe.load_state_dict(snapshot, tr.train_program,
                                      scope=tr.scope)
    assert set(restored) == set(snapshot)
    for n, v in want.items():
        np.testing.assert_array_equal(
            np.asarray(tr.scope.vars[n]), v, err_msg=n)
    # unknown entries are skipped with a warning, not written
    with pytest.warns(RuntimeWarning, match='not persistables'):
        tr.exe.load_state_dict({'no_such_var': np.zeros(3, 'f4')},
                               tr.train_program, scope=tr.scope)
    assert 'no_such_var' not in tr.scope.vars


def test_dense_save_checkpoint_warns_on_annotated_program(tmp_path):
    """fluid.io.save_checkpoint gathers dense — on a mesh-annotated
    program that is the OOM-on-a-pod hazard, so it must say so."""
    tr = fluid.Trainer(train_func=_linear_train_func, optimizer_func=_sgd,
                       place=fluid.CPUPlace(),
                       transpiler_fn=_mesh_hook({'dp': 8}))
    tr.train(num_epochs=1, event_handler=lambda ev: None,
             reader=_linear_reader(16), feed_order=['x', 'y'])
    with tr._prog_and_scope_guard():
        with pytest.warns(RuntimeWarning, match='mesh-annotated'):
            fluid.io.save_checkpoint(tr.exe, str(tmp_path / 'dense'),
                                     main_program=tr.train_program)


# ---------------------------------------------------------------------------
# Trainer: sharded periodic checkpoints + topology-changing resume
# ---------------------------------------------------------------------------

def test_trainer_topology_change_resume_linear(tmp_path, obs_events):
    """The headline drill shape: an annotated trainer on an 8-device
    mesh crashes mid-epoch; a 4-device trainer over the same dir resumes
    from the newest committed sharded serial at the exact next step and
    the loss trajectory continues (matches an uninterrupted 8-device
    reference run step for step)."""
    # reference: uninterrupted run on the 8-mesh
    ref = []
    t0 = fluid.Trainer(train_func=_linear_train_func, optimizer_func=_sgd,
                       place=fluid.CPUPlace(),
                       transpiler_fn=_mesh_hook({'dp': 8}))
    t0.train(num_epochs=2, event_handler=_losses_handler(ref),
             reader=_linear_reader(), feed_order=['x', 'y'])

    ckpt = str(tmp_path / 'ckpt')
    cfg = fluid.CheckpointConfig(checkpoint_dir=ckpt, max_num_checkpoints=3,
                                 epoch_interval=1, step_interval=1)
    before = []
    t1 = fluid.Trainer(train_func=_linear_train_func, optimizer_func=_sgd,
                       place=fluid.CPUPlace(), checkpoint_config=cfg,
                       transpiler_fn=_mesh_hook({'dp': 8}))
    with pytest.raises(Crash):
        t1.train(num_epochs=2,
                 event_handler=_losses_handler(before, crash_at=(0, 5)),
                 reader=_linear_reader(), feed_order=['x', 'y'])
    w_at_crash = np.asarray(t1.scope.vars['w'])
    serials = [d for d in os.listdir(ckpt) if re.fullmatch(r'sharded_\d+', d)]
    assert serials, os.listdir(ckpt)
    # the commit protocol's artifacts: manifest + verified .sum sidecar
    newest = os.path.join(ckpt, 'sharded_%d'
                          % max(int(d.split('_')[1]) for d in serials))
    assert os.path.exists(os.path.join(newest, 'manifest.json'))
    assert os.path.exists(os.path.join(newest, 'manifest.json.sum'))
    assert not [d for d in os.listdir(ckpt) if d.endswith('.tmp')]
    assert obs_events('checkpoint.commit')

    # resume on HALF the devices
    cfg2 = fluid.CheckpointConfig(checkpoint_dir=ckpt,
                                  max_num_checkpoints=3,
                                  epoch_interval=1, step_interval=1)
    after = []
    t2 = fluid.Trainer(train_func=_linear_train_func, optimizer_func=_sgd,
                       place=fluid.CPUPlace(), checkpoint_config=cfg2,
                       transpiler_fn=_mesh_hook({'dp': 4}))
    assert cfg2.load_serial  # resumed from a sharded serial
    np.testing.assert_array_equal(np.asarray(t2.scope.vars['w']),
                                  w_at_crash)
    # restored state lives on the 4-device mesh
    assert len(t2.scope.vars['w'].sharding.device_set) == 4
    ev = obs_events('elastic.resume')
    assert ev and ev[-1]['fields']['from_mesh'] == [['dp', 8]]
    assert ev[-1]['fields']['to_mesh'] == [['dp', 4]]
    t2.train(num_epochs=2, event_handler=_losses_handler(after),
             reader=_linear_reader(), feed_order=['x', 'y'])
    # exact-step resume: (0, 5) is never replayed, (0, 6) is next
    steps_after = [s for s, _ in after]
    assert (0, 5) not in steps_after
    assert steps_after[0] == (0, 6)
    # trajectory continuity: resumed losses match the uninterrupted
    # reference at the same steps (dp=4 vs dp=8 differ only in float
    # reduction order)
    ref_map = dict(ref)
    for s, loss in after:
        np.testing.assert_allclose(loss, ref_map[s], rtol=1e-3,
                                   atol=1e-6, err_msg=str(s))
    # clean finish removes its sharded serials (and only them)
    assert not [d for d in os.listdir(ckpt) if d.startswith('sharded_')]


def test_trainer_sharded_embedding_topology_change(tmp_path):
    """The acceptance drill's state shape: a vocab-sharded table AND its
    sharded adam moments checkpoint as per-shard files (sizes checked —
    the dense table never materializes), then restore 8 -> 4 devices
    with resharding and exact values."""
    ckpt = str(tmp_path / 'ckpt')
    cfg = fluid.CheckpointConfig(checkpoint_dir=ckpt, max_num_checkpoints=2,
                                 epoch_interval=1, step_interval=1)
    t1 = fluid.Trainer(train_func=_emb_train_func, optimizer_func=_adam,
                       place=fluid.CPUPlace(), checkpoint_config=cfg,
                       transpiler_fn=_mesh_hook({'dp': 8}))
    losses = []
    with pytest.raises(Crash):
        t1.train(num_epochs=2,
                 event_handler=_losses_handler(losses, crash_at=(0, 5)),
                 reader=_emb_reader(), feed_order=['ids'])
    emb_at_crash = np.asarray(t1.scope.vars['emb_w'])
    moment_names = [n for n in t1.scope.vars
                    if 'emb_w' in n and n != 'emb_w'
                    and getattr(t1.scope.vars[n], 'shape', None)
                    == (VOCAB, DIM)]
    assert moment_names
    moments_at_crash = {n: np.asarray(t1.scope.vars[n])
                        for n in moment_names}

    newest = max(int(d.split('_')[1]) for d in os.listdir(ckpt)
                 if re.fullmatch(r'sharded_\d+', d))
    sdir = os.path.join(ckpt, 'sharded_%d' % newest)
    # NO dense materialization: every emb_w / moment shard file holds
    # exactly one device's rows (VOCAB/8), never the whole table
    vocab_files = [f for f in os.listdir(sdir)
                   if 'emb_w' in f and f.endswith('.npy')]
    assert len(vocab_files) >= 8
    for f in vocab_files:
        arr = np.load(os.path.join(sdir, f))
        if arr.ndim == 2 and arr.shape[1] == DIM:
            assert arr.shape[0] == VOCAB // 8, (f, arr.shape)
    # static restorability onto the surviving topology
    assert ck.restorable(sdir, {'dp': 4}) == []

    cfg2 = fluid.CheckpointConfig(checkpoint_dir=ckpt,
                                  max_num_checkpoints=2,
                                  epoch_interval=1, step_interval=1)
    t2 = fluid.Trainer(train_func=_emb_train_func, optimizer_func=_adam,
                       place=fluid.CPUPlace(), checkpoint_config=cfg2,
                       transpiler_fn=_mesh_hook({'dp': 4}))
    assert cfg2.load_serial
    np.testing.assert_array_equal(np.asarray(t2.scope.vars['emb_w']),
                                  emb_at_crash)
    for n, v in moments_at_crash.items():
        np.testing.assert_array_equal(np.asarray(t2.scope.vars[n]), v,
                                      err_msg=n)
    # resharded placements: table and moments each hold VOCAB/4 rows
    # per device on the new mesh — checked through the state_dict seam
    state = t2.exe.state_dict(t2.train_program, scope=t2.scope)
    for n in ['emb_w'] + moment_names:
        assert state[n].addressable_shards[0].data.shape \
            == (VOCAB // 4, DIM), n
        assert len(state[n].sharding.device_set) == 4, n
    # training continues
    cont = []
    t2.train(num_epochs=1, event_handler=_losses_handler(cont),
             reader=_emb_reader(), feed_order=['ids'])
    assert cont and all(np.isfinite(l) for _, l in cont)
    assert cont[0][0] == (0, 6)   # exact-step resume, no epoch replay


# ---------------------------------------------------------------------------
# atomic commit: torn writes can never look committed
# ---------------------------------------------------------------------------

def _state_arrays(seed=0):
    rng = np.random.RandomState(seed)
    return {'w': rng.rand(8, 8).astype('float32'),
            'b': rng.rand(8).astype('float32')}


_TORN_CHILD = r"""
import os, sys, time
import jax
jax.config.update('jax_platforms', 'cpu')
try:
    jax.config.update('jax_num_cpu_devices', 2)
except AttributeError:
    # jax<0.5: the XLA flag is the fallback spelling — ONLY then (newer
    # jax rejects having both mechanisms set); the backend has not
    # initialized yet, so setting it post-import still applies
    os.environ['XLA_FLAGS'] = (os.environ.get('XLA_FLAGS', '')
                               + ' --xla_force_host_platform_device_count=2')
import numpy as np
from paddle_tpu.utils import checkpoint as ck

base, marker = sys.argv[1], sys.argv[2]
orig = ck._write_shard

def slow(path, data, sh):
    orig(path, data, sh)
    with open(marker, 'w') as f:
        f.write('mid-save')
    time.sleep(120)   # the parent SIGKILLs us here — mid-save

ck._write_shard = slow
state = {'w': np.arange(64, dtype=np.float32).reshape(8, 8),
         'b': np.ones(8, np.float32)}
ck.save_sharded(os.path.join(base, 'sharded_2'), state, step=2)
print('UNEXPECTED: save committed')
"""


def test_sigkill_mid_save_leaves_no_committed_dir(tmp_path):
    """The torn-write acceptance drill: SIGKILL during save_sharded (a
    real child process, killed mid-shard-write) leaves only the staging
    dir; load_latest_verified falls back LOUDLY to the previous intact
    serial."""
    base = str(tmp_path / 'ckpts')
    ck.save_sharded(os.path.join(base, 'sharded_1'), _state_arrays(1),
                    step=1)
    marker = str(tmp_path / 'mid_save_marker')
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, PYTHONPATH=here)
    env.pop('JAX_PLATFORMS', None)
    env.pop('XLA_FLAGS', None)
    proc = subprocess.Popen([sys.executable, '-c', _TORN_CHILD, base,
                             marker], env=env, cwd=here,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True)
    try:
        deadline = time.monotonic() + 180
        while not os.path.exists(marker):
            assert proc.poll() is None, proc.communicate()
            assert time.monotonic() < deadline, 'child never reached save'
            time.sleep(0.05)
        FaultInjector(0).kill_process(proc)   # the host-failure fault
        proc.wait(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert proc.returncode == -signal.SIGKILL
    # the save never committed: staging dir only, no sharded_2
    assert os.path.isdir(os.path.join(base, 'sharded_2.tmp'))
    assert not os.path.isdir(os.path.join(base, 'sharded_2'))
    assert ck.latest_step(base) == 1
    with pytest.warns(RuntimeWarning, match='uncommitted'):
        arrays, meta = ck.load_latest_verified(base)
    assert meta['step'] == 1
    np.testing.assert_array_equal(np.asarray(arrays['w']),
                                  _state_arrays(1)['w'])


def test_commit_timeout_is_typed_and_leaves_staging(tmp_path,
                                                    monkeypatch):
    """A peer that never stages its manifest: process 0's commit raises
    the typed CommitTimeout (the Trainer's periodic path treats it as a
    missed checkpoint, not a dead run) and the staging dir survives,
    uncommitted."""
    monkeypatch.setattr(jax, 'process_count', lambda: 2)
    d = str(tmp_path / 'ck' / 'sharded_1')
    with pytest.raises(ck.CommitTimeout, match='UNCOMMITTED'):
        ck.save_sharded(d, _state_arrays(), step=1, commit_timeout=0.3)
    assert os.path.isdir(d + '.tmp')
    assert not os.path.isdir(d)


def test_overwrite_commit_swaps_without_deleting_first(tmp_path):
    """Re-saving an existing serial replaces it atomically (swap, not
    rmtree-then-rename) and leaves no .old/.tmp debris on success."""
    d = str(tmp_path / 'ck' / 'sharded_1')
    ck.save_sharded(d, _state_arrays(1), step=1)
    ck.save_sharded(d, _state_arrays(2), step=1)
    arrays, _ = ck.load_sharded(d)
    np.testing.assert_array_equal(np.asarray(arrays['w']),
                                  _state_arrays(2)['w'])
    parent = os.path.dirname(d)
    assert [x for x in os.listdir(parent)] == ['sharded_1']


def test_kill_process_refuses_self():
    with pytest.raises(ValueError, match='CHILD'):
        FaultInjector(0).kill_process(os.getpid())


def test_only_uncommitted_dirs_is_a_loud_failure(tmp_path):
    base = str(tmp_path / 'ckpts')
    os.makedirs(os.path.join(base, 'sharded_3.tmp'))
    with pytest.warns(RuntimeWarning, match='uncommitted'):
        with pytest.raises(RuntimeError, match='no committed'):
            ck.load_latest_verified(base)


@pytest.mark.parametrize('what', ['drop_manifest', 'truncate_manifest',
                                  'corrupt_manifest', 'drop_shard',
                                  'truncate_shard'])
def test_torn_checkpoint_variants_fall_back(tmp_path, what):
    """FaultInjector.torn_checkpoint: every tear mode of the newest
    serial (manifest vs shard, drop vs truncate vs same-size bit rot —
    the last only the .sum CRC catches) falls back to the previous
    intact serial with a warning, never a raw JSON/KeyError."""
    base = str(tmp_path / 'ckpts')
    ck.save_sharded(os.path.join(base, 'sharded_1'), _state_arrays(1),
                    step=1)
    ck.save_sharded(os.path.join(base, 'sharded_2'), _state_arrays(2),
                    step=2)
    inj = FaultInjector(seed=5)
    mode, path = inj.torn_checkpoint(os.path.join(base, 'sharded_2'),
                                     what=what)
    assert mode == what
    problems = ck.verify_sharded(os.path.join(base, 'sharded_2'))
    assert problems, what
    with pytest.warns(RuntimeWarning, match='FAILED verification'):
        arrays, meta = ck.load_latest_verified(base)
    assert meta['step'] == 1
    np.testing.assert_array_equal(np.asarray(arrays['w']),
                                  _state_arrays(1)['w'])


def test_manifest_bit_rot_is_a_typed_verification_failure(tmp_path):
    """Same-size manifest corruption: without the .sum sidecar this was
    a raw json error; now it is a typed RuntimeError naming the
    manifest."""
    d = str(tmp_path / 'ck')
    ck.save_sharded(d, _state_arrays(), step=1)
    FaultInjector(seed=2).corrupt_file(os.path.join(d, 'manifest.json'))
    with pytest.raises(RuntimeError, match='manifest.*corrupt|corrupt.*manifest'):
        ck.load_sharded(d)
    problems = ck.verify_sharded(d)
    assert problems and 'manifest' in problems[0]


def test_old_format_checkpoints_still_load(tmp_path):
    """Checkpoints without the .sum sidecar (pre-elastic format) load
    and verify exactly as before."""
    d = str(tmp_path / 'ck')
    ck.save_sharded(d, _state_arrays(3), step=4)
    for f in list(os.listdir(d)):
        if f.endswith('.sum'):
            os.remove(os.path.join(d, f))
    assert ck.verify_sharded(d) == []
    arrays, meta = ck.load_sharded(d)
    assert meta['step'] == 4
    np.testing.assert_array_equal(np.asarray(arrays['w']),
                                  _state_arrays(3)['w'])


# ---------------------------------------------------------------------------
# restorable(): the static reshard-on-restore check (+ program_lint)
# ---------------------------------------------------------------------------

def _sharded_table_ckpt(tmp_path):
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    mesh = Mesh(np.asarray(jax.devices()[:8]), ('dp',))
    state = {'emb': jax.device_put(
        np.arange(VOCAB * DIM, dtype=np.float32).reshape(VOCAB, DIM),
        NamedSharding(mesh, P('dp', None))),
        'b': jax.device_put(np.ones(8, np.float32),
                            NamedSharding(mesh, P()))}
    d = str(tmp_path / 'table_ck')
    ck.save_sharded(d, state, step=1)
    return d


def test_restorable_static_check(tmp_path):
    d = _sharded_table_ckpt(tmp_path)
    assert ck.restorable(d, {'dp': 4}) == []
    assert ck.restorable(d, {'dp': 16}) == []     # grow works too
    bad = ck.restorable(d, {'dp': 5})
    assert bad and 'tile' in bad[0]
    bad = ck.restorable(d, {'model': 4})
    assert bad and 'not on the target mesh' in bad[0]
    # coverage gap: a deleted shard file is visible statically
    victim = [f for f in os.listdir(d)
              if f.startswith('emb') and f.endswith('.npy')][0]
    os.remove(os.path.join(d, victim))
    man = ck._merged_manifest(d)
    man['arrays']['emb']['shards'] = \
        man['arrays']['emb']['shards'][:-1]
    bad = ck.restorable(man, {'dp': 4})
    assert bad and 'cover' in bad[0]


def test_program_lint_checkpoint_flag(tmp_path):
    """tools/program_lint.py --mesh ... --checkpoint DIR: the elastic
    restart pre-check, wired next to the sharding lint."""
    import importlib.util
    import io as _io
    from contextlib import redirect_stdout
    from util import fresh_program

    d = _sharded_table_ckpt(tmp_path)
    with fresh_program() as (main, startup):
        x = fluid.layers.data(name='x', shape=[16], dtype='float32')
        pred = fluid.layers.fc(input=x, size=32)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        m = str(tmp_path / 'model')
        fluid.io.save_inference_model(m, ['x'], [pred], exe,
                                      main_program=main)

    spec = importlib.util.spec_from_file_location(
        'program_lint', os.path.join(os.path.dirname(__file__), '..',
                                     'tools', 'program_lint.py'))
    lint = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lint)

    def run(argv):
        buf = _io.StringIO()
        with redirect_stdout(buf):
            rc = lint.main(argv)
        return rc, buf.getvalue()

    rc, out = run([m, '--mesh', 'dpx4', '--checkpoint', d, '--json'])
    doc = json.loads(out)
    assert rc == 0
    assert doc['checkpoint']['restorable'] is True
    rc, out = run([m, '--mesh', 'dpx5', '--checkpoint', d, '--json'])
    doc = json.loads(out)
    assert rc == 1
    assert doc['checkpoint']['restorable'] is False
    assert doc['checkpoint']['problems']
    # --checkpoint without --mesh is a usage error
    rc, _ = run([m, '--checkpoint', d])
    assert rc == 2


def test_reshard_restore_emits_span(tmp_path, obs_events):
    d = _sharded_table_ckpt(tmp_path)
    from jax.sharding import Mesh
    small = Mesh(np.asarray(jax.devices()[:4]), ('dp',))
    arrays, _ = ck.load_sharded(d, mesh=small)
    np.testing.assert_array_equal(
        np.asarray(arrays['emb']),
        np.arange(VOCAB * DIM, dtype=np.float32).reshape(VOCAB, DIM))
    spans = obs_events('checkpoint.reshard')
    assert spans
    f = spans[-1]['fields']
    assert f['from_mesh'] == 'dp=8' and f['to_mesh'] == 'dp=4'


def test_reshard_onto_mesh_missing_axis_replicates(tmp_path):
    """A saved axis absent from the restore mesh replicates that dim,
    loudly — the axis-set-changing elastic case."""
    d = _sharded_table_ckpt(tmp_path)
    from jax.sharding import Mesh
    other = Mesh(np.asarray(jax.devices()[:4]).reshape(2, 2),
                 ('x', 'y'))
    with pytest.warns(RuntimeWarning, match='restore replicated'):
        arrays, _ = ck.load_sharded(d, mesh=other)
    np.testing.assert_array_equal(
        np.asarray(arrays['emb']),
        np.arange(VOCAB * DIM, dtype=np.float32).reshape(VOCAB, DIM))


# ---------------------------------------------------------------------------
# heartbeat: host-failure detection
# ---------------------------------------------------------------------------

def test_heartbeat_stale_detection_unit(tmp_path, obs_events):
    d = str(tmp_path / 'beats')
    hb0 = Heartbeat(d, process_id=0, num_processes=2, interval=0.03,
                    timeout=0.25)
    hb1 = Heartbeat(d, process_id=1, num_processes=2, interval=0.03,
                    timeout=0.25)
    hb0.start()
    hb1.start()
    try:
        deadline = time.monotonic() + 5
        while hb0.check(raise_error=False) and time.monotonic() < deadline:
            time.sleep(0.05)
        assert hb0.check(raise_error=False) == []
        # peer 1 dies (its beats stop — stop() simulates the SIGKILL)
        hb1.stop()
        time.sleep(0.4)
        assert hb0.check(raise_error=False) == [1]
        with pytest.raises(HostLost) as ei:
            hb0.check()
        assert ei.value.stale == [1]
        ev = obs_events('parallel.heartbeat.stale')
        assert ev and ev[0]['fields']['peer'] == 1
        assert obs.counter('parallel.heartbeat.stale').value >= 1
        # peer restarts (fresh counter) -> recovery
        hb1b = Heartbeat(d, process_id=1, num_processes=2, interval=0.03,
                         timeout=0.25)
        hb1b.beat()
        assert hb0.check(raise_error=False) == []
        hb1b.stop()
    finally:
        hb0.stop()
        hb1.stop()


def test_heartbeat_never_arrived_peer_goes_stale(tmp_path):
    hb = Heartbeat(str(tmp_path / 'beats'), process_id=0, num_processes=3,
                   interval=0.03, timeout=0.2)
    hb.start()
    try:
        time.sleep(0.35)
        assert hb.check(raise_error=False) == [1, 2]
    finally:
        hb.stop()


def test_trainer_host_lost_flushes_and_raises(tmp_path, obs_events):
    """The Trainer surface: a stale peer raises typed HostLost AFTER an
    emergency sharded checkpoint, and a smaller-topology trainer resumes
    from it at the exact step."""
    ckpt = str(tmp_path / 'ckpt')
    cfg = fluid.CheckpointConfig(checkpoint_dir=ckpt, max_num_checkpoints=5,
                                 epoch_interval=1, step_interval=1)
    # peer 1 of a declared 2-process job never beats: this host must
    # notice and bail out (the single-process commit still succeeds, so
    # the emergency flush is committed and resumable)
    hb = Heartbeat(str(tmp_path / 'beats'), process_id=0, num_processes=2,
                   interval=0.05, timeout=0.2)
    seen = []
    t1 = fluid.Trainer(train_func=_linear_train_func, optimizer_func=_sgd,
                       place=fluid.CPUPlace(), checkpoint_config=cfg,
                       transpiler_fn=_mesh_hook({'dp': 8}), heartbeat=hb)

    def handler(ev):
        if isinstance(ev, fluid.EndStepEvent):
            seen.append((ev.epoch, ev.step))
            time.sleep(0.3)   # let the peer's absence cross the timeout

    with pytest.warns(RuntimeWarning, match='lost'):
        with pytest.raises(HostLost) as ei:
            t1.train(num_epochs=2, event_handler=handler,
                     reader=_linear_reader(), feed_order=['x', 'y'])
    assert ei.value.stale == [1]
    assert t1.host_lost and t1.host_lost['stale'] == [1]
    assert t1.host_lost['last_done'] == seen[-1]
    assert t1.host_lost['emergency_checkpoint']   # committed (1-process)
    assert obs_events('elastic.host_lost')
    last_done = seen[-1]

    # supervisor restart on the surviving topology
    cfg2 = fluid.CheckpointConfig(checkpoint_dir=ckpt,
                                  max_num_checkpoints=5,
                                  epoch_interval=1, step_interval=1)
    after = []
    t2 = fluid.Trainer(train_func=_linear_train_func, optimizer_func=_sgd,
                       place=fluid.CPUPlace(), checkpoint_config=cfg2,
                       transpiler_fn=_mesh_hook({'dp': 4}))
    assert cfg2.load_serial
    assert (cfg2.epoch_id, cfg2.step_id) == last_done
    t2.train(num_epochs=1, event_handler=_losses_handler(after),
             reader=_linear_reader(), feed_order=['x', 'y'])
    if last_done[0] == 0:
        steps_after = [s for s, _ in after]
        assert last_done not in steps_after
        assert steps_after[0] == (0, last_done[1] + 1)


# ---------------------------------------------------------------------------
# the multi-process drill: SIGKILL one worker of a 2-host (8-device)
# job; the survivor detects, flushes, exits; resume on 4 devices
# ---------------------------------------------------------------------------

_MP_CHILD = r"""
import os, sys, time, signal, json
import jax
jax.config.update('jax_platforms', 'cpu')
try:
    jax.config.update('jax_num_cpu_devices', 4)
except AttributeError:
    # jax<0.5 fallback; never set BOTH (newer jax rejects the combo)
    os.environ['XLA_FLAGS'] = (os.environ.get('XLA_FLAGS', '')
                               + ' --xla_force_host_platform_device_count=4')
import numpy as np
from paddle_tpu import parallel
import paddle_tpu.fluid as fluid

rank = int(sys.argv[1])
coord = sys.argv[2]
ckpt = sys.argv[3]
beats = sys.argv[4]
kill_step = int(sys.argv[5])
loss_log = sys.argv[6]

parallel.init_distributed(coordinator_address=coord, num_processes=2,
                          process_id=rank)
assert len(jax.devices()) == 8, jax.devices()

VOCAB, DIM = 64, 4

def train_func():
    ids = fluid.layers.data(name='ids', shape=[2, 1], dtype='int64')
    emb = fluid.layers.embedding(
        ids, size=[VOCAB, DIM],
        param_attr=fluid.ParamAttr(name='emb_w', sharding=('dp', None)))
    pred = fluid.layers.fc(input=emb, size=1, num_flatten_dims=2,
                           bias_attr=False,
                           param_attr=fluid.ParamAttr(name='fc_w'))
    return fluid.layers.mean(fluid.layers.square(pred - 1.0))

def global_batch(t):
    rng = np.random.RandomState(100 + t)
    return rng.randint(0, VOCAB, size=(8, 2, 1)).astype('int64')

def reader():
    # per-host slice of the deterministic global batch: host r feeds
    # rows [r*4, (r+1)*4) — make_array_from_process_local_data stitches
    for t in range(12):
        g = global_batch(t)[rank * 4:(rank + 1) * 4]
        yield [(g[i],) for i in range(4)]

hb = parallel.Heartbeat(beats, interval=0.1, timeout=1.2)
cfg = fluid.CheckpointConfig(checkpoint_dir=ckpt, max_num_checkpoints=50,
                             epoch_interval=1, step_interval=1,
                             commit_timeout=60.0)
trainer = fluid.Trainer(train_func=train_func,
                        optimizer_func=lambda: fluid.optimizer.Adam(
                            learning_rate=0.05),
                        place=fluid.CPUPlace(), checkpoint_config=cfg,
                        transpiler_fn=lambda p: p.set_mesh({'dp': 8}),
                        heartbeat=hb)

losses = []

def handler(ev):
    if isinstance(ev, fluid.EndStepEvent):
        losses.append([ev.epoch, ev.step,
                       float(np.asarray(ev.metrics[0]))])
        if rank == 1 and ev.step == kill_step:
            os.kill(os.getpid(), signal.SIGKILL)   # host dies, no cleanup
        if rank == 0 and ev.step >= kill_step:
            time.sleep(2.0)   # let the dead peer's staleness accrue

try:
    trainer.train(num_epochs=1, event_handler=handler,
                  reader=lambda: reader(), feed_order=['ids'])
    print('FINISHED-WITHOUT-HOSTLOST')
    sys.exit(3)
except parallel.HostLost as e:
    with open(loss_log, 'w') as f:
        json.dump({'losses': losses, 'stale': e.stale,
                   'host_lost': trainer.host_lost is not None}, f)
    print('HOSTLOST', e.stale)
    sys.stdout.flush()
    # exit WITHOUT the atexit jax.distributed.shutdown barrier: with a
    # dead peer that barrier blocks until the coordination service
    # aborts the process (~100s later, SIGABRT) — a supervisor needs
    # the exit NOW, and the emergency state is already flushed
    os._exit(7)
"""


@pytest.mark.slow
def test_multiprocess_kill_one_worker_resumes_8_to_4(tmp_path):
    """The full elastic acceptance drill: 2 processes x 4 devices train
    one annotated Program on a dp=8 mesh with per-step sharded
    checkpoints; worker 1 is SIGKILLed mid-training; worker 0's
    heartbeat surfaces HostLost and exits cleanly; a 4-device restart
    resumes from the last COMMITTED serial (the survivor's emergency
    flush cannot commit — its peer is dead — and is skipped as
    uncommitted) at the exact next step, with the vocab-sharded table,
    its adam moments, and the loss trajectory continuing."""
    ckpt = str(tmp_path / 'ckpt')
    beats = str(tmp_path / 'beats')
    loss_log = str(tmp_path / 'losses.p0.json')
    kill_step = 5
    s = socket.socket()
    s.bind(('127.0.0.1', 0))
    port = s.getsockname()[1]
    s.close()
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = []
    for rank in (0, 1):
        env = dict(os.environ, PYTHONPATH=here)
        env.pop('JAX_PLATFORMS', None)
        env.pop('XLA_FLAGS', None)
        procs.append(subprocess.Popen(
            [sys.executable, '-c', _MP_CHILD, str(rank),
             '127.0.0.1:%d' % port, ckpt, beats, str(kill_step),
             loss_log], env=env, cwd=here, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True))
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=420)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    rc0, out0, err0 = outs[0]
    rc1, out1, err1 = outs[1]
    assert rc1 == -signal.SIGKILL, (rc1, out1, err1[-2000:])
    assert rc0 == 7, (rc0, out0, err0[-2000:])
    assert 'HOSTLOST' in out0

    log = json.load(open(loss_log))
    assert log['stale'] == [1]
    pre_losses = {(e, s): l for e, s, l in log['losses']}
    assert (0, kill_step) in pre_losses

    # the last COMMITTED serial records kill_step; the survivor's
    # emergency flush stayed an uncommitted staging dir
    assert ck.latest_step(ckpt) is not None
    tmp_dirs = [d for d in os.listdir(ckpt) if d.endswith('.tmp')]
    assert tmp_dirs, os.listdir(ckpt)

    # ---- restart on the surviving topology: 4 devices (this process
    # has 8 but the program meshes only dp=4) -------------------------
    import warnings as _warnings
    cfg = fluid.CheckpointConfig(checkpoint_dir=ckpt,
                                 max_num_checkpoints=50,
                                 epoch_interval=1, step_interval=1)
    with _warnings.catch_warnings(record=True) as rec:
        _warnings.simplefilter('always')
        t2 = fluid.Trainer(train_func=_mp_emb_train_func,
                           optimizer_func=lambda: fluid.optimizer.Adam(
                               learning_rate=0.05),
                           place=fluid.CPUPlace(), checkpoint_config=cfg,
                           transpiler_fn=_mesh_hook({'dp': 4}))
    assert any('uncommitted' in str(w.message) for w in rec)
    assert cfg.load_serial
    assert (cfg.epoch_id, cfg.step_id) == (0, kill_step)
    # restored sharded placements on the smaller mesh — and per-shard
    # file sizes in the committed serial prove no host ever wrote the
    # dense table
    sdir = os.path.join(ckpt, 'sharded_%d' % ck.latest_step(ckpt))
    for f in os.listdir(sdir):
        if 'emb_w' in f and f.endswith('.npy'):
            arr = np.load(os.path.join(sdir, f))
            if arr.ndim == 2 and arr.shape[1] == DIM:
                assert arr.shape[0] == VOCAB // 8, (f, arr.shape)
    state = t2.exe.state_dict(t2.train_program, scope=t2.scope)
    assert state['emb_w'].addressable_shards[0].data.shape \
        == (VOCAB // 4, DIM)

    cont = []
    t2.train(num_epochs=1, event_handler=_losses_handler(cont),
             reader=_mp_global_reader(), feed_order=['ids'])
    steps = [s for s, _ in cont]
    assert (0, kill_step) not in steps       # exact-step resume
    assert steps[0] == (0, kill_step + 1)
    assert all(np.isfinite(l) for _, l in cont)
    # trajectory continuity: the resumed run's first losses stay in the
    # converged regime the pre-kill run reached, not a cold restart
    pre_last = pre_losses[(0, kill_step)]
    assert cont[0][1] <= max(4 * pre_last, pre_last + 0.1), (
        pre_last, cont[0][1])


def _mp_emb_train_func():
    # the _MP_CHILD model, rebuilt in-parent for the resume phase
    return _emb_train_func()


def _mp_global_reader():
    def r():
        for t in range(12):
            rng = np.random.RandomState(100 + t)
            g = rng.randint(0, VOCAB, size=(8, 2, 1)).astype('int64')
            yield [(g[i],) for i in range(8)]
    return r
