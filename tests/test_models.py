"""Every benchmark/book model builds and trains a step on tiny shapes."""
import itertools

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid

from util import fresh_program


def _run_steps(main, startup, feeds, reader, fetch, n=3, feed_transform=None):
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    feeder = fluid.DataFeeder(
        place=fluid.CPUPlace(),
        feed_list=[main.global_block().var(f) for f in feeds])
    out = None
    for batch in itertools.islice(reader(), n):
        if feed_transform:
            batch = feed_transform(batch)
        out = exe.run(main, feed=feeder.feed(batch), fetch_list=fetch)
    return out


def test_resnet_cifar10_step():
    from paddle_tpu.models import resnet
    with fresh_program() as (main, startup):
        avg_cost, acc, train_reader, _ = resnet.get_model(
            data_set='cifar10', depth=8, batch_size=8)
        out = _run_steps(main, startup, ['data', 'label'], train_reader,
                         [avg_cost, acc],
                         feed_transform=lambda b: [
                             (x.reshape(3, 32, 32), y) for x, y in b])
        assert np.isfinite(out[0]).all()


def test_vgg_cifar10_step():
    from paddle_tpu.models import vgg
    with fresh_program() as (main, startup):
        avg_cost, _, train_reader, _, acc = vgg.get_model(
            data_set='cifar10', batch_size=4)
        out = _run_steps(main, startup, ['data', 'label'], train_reader,
                         [avg_cost],
                         feed_transform=lambda b: [
                             (x.reshape(3, 32, 32), y) for x, y in b], n=2)
        assert np.isfinite(out[0]).all()


def test_word2vec_steps():
    from paddle_tpu.models import word2vec
    with fresh_program() as (main, startup):
        avg_cost, _, train_reader, _, feeds = word2vec.get_model(
            batch_size=32)
        out = _run_steps(main, startup, feeds, train_reader, [avg_cost], n=5)
        assert np.isfinite(out[0]).all()


def test_understand_sentiment_steps():
    from paddle_tpu.models import understand_sentiment
    with fresh_program() as (main, startup):
        avg_cost, acc, train_reader, _, feeds = \
            understand_sentiment.get_model(batch_size=8)
        out = _run_steps(main, startup, feeds, train_reader, [avg_cost, acc],
                         n=2)
        assert np.isfinite(out[0]).all()


def test_deepfm_steps():
    from paddle_tpu.models import deepfm
    with fresh_program() as (main, startup):
        avg_cost, auc, train_reader, _, feeds = deepfm.get_model(
            batch_size=64)
        out = _run_steps(main, startup, feeds, train_reader, [avg_cost, auc],
                         n=4)
        assert np.isfinite(out[0]).all()
        assert 0.0 <= float(out[1]) <= 1.0


def test_transformer_overfits_batch():
    from paddle_tpu.models import transformer as T
    with fresh_program() as (main, startup):
        avg_cost, tok, train_reader, _, feeds = T.get_model(
            batch_size=8, max_length=16, n_layer=1, d_model=32, n_head=2,
            d_inner=64, dict_size=60, warmup_steps=50)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        batch = next(iter(train_reader()))
        fd = {n: np.stack([r[i] for r in batch])
              for i, n in enumerate(feeds)}
        losses = []
        for _ in range(40):
            loss, = exe.run(main, feed=fd, fetch_list=[avg_cost])
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.8, (losses[0], losses[-1])


def test_seq2seq_attention_step():
    from paddle_tpu.models import machine_translation as mt
    with fresh_program() as (main, startup):
        avg_cost, _, train_reader, _, feeds = mt.get_model(
            batch_size=4, embedding_dim=16, encoder_size=16,
            decoder_size=16, dict_size=40)
        out = _run_steps(main, startup, feeds, train_reader, [avg_cost], n=2)
        assert np.isfinite(out[0]).all()


def test_stacked_lstm_step():
    from paddle_tpu.models import stacked_dynamic_lstm as sl
    with fresh_program() as (main, startup):
        data = fluid.layers.data(name="words", shape=[1], lod_level=1,
                                 dtype='int64')
        label = fluid.layers.data(name='label', shape=[1], dtype='int64')
        logit = sl.lstm_net(data, 200, lstm_size=16, emb_dim=16)
        loss = fluid.layers.mean(
            fluid.layers.cross_entropy(input=logit, label=label))
        fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)

        def reader():
            rng = np.random.RandomState(7)
            while True:
                yield [(list(rng.randint(0, 200, size=rng.randint(3, 9))),
                        int(rng.randint(0, 2))) for _ in range(4)]
        out = _run_steps(main, startup, ['words', 'label'], reader, [loss],
                         n=3)
        assert np.isfinite(out[0]).all()
