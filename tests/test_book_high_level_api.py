"""High-level-api book flow: Trainer trains a conv MNIST net, saves
params, Inferencer serves them (reference
fluid/tests/book/high-level-api/recognize_digits/
test_recognize_digits_conv.py)."""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid


def _conv_net():
    img = fluid.layers.data(name='img', shape=[1, 28, 28], dtype='float32')
    predict = fluid.nets.simple_img_conv_pool(
        input=img, filter_size=5, num_filters=8, pool_size=2, pool_stride=2,
        act='relu')
    return fluid.layers.fc(input=predict, size=10, act='softmax')


def _train_func():
    predict = _conv_net()
    label = fluid.layers.data(name='label', shape=[1], dtype='int64')
    cost = fluid.layers.cross_entropy(input=predict, label=label)
    avg_cost = fluid.layers.mean(cost)
    acc = fluid.layers.accuracy(input=predict, label=label)
    return [avg_cost, acc]


def _infer_func():
    return _conv_net()


def test_recognize_digits_conv_high_level_api(tmp_path):
    trainer = fluid.Trainer(
        train_func=_train_func,
        optimizer_func=lambda: fluid.optimizer.Adam(learning_rate=0.005),
        place=fluid.CPUPlace())

    accs = []

    def event_handler(event):
        if isinstance(event, fluid.EndStepEvent):
            accs.append(float(np.asarray(event.metrics[1]).squeeze()))
        if isinstance(event, fluid.EndEpochEvent):
            trainer.stop()

    reader = paddle.batch(
        paddle.reader.shuffle(paddle.dataset.mnist.train(), buf_size=500),
        batch_size=64)
    trainer.train(num_epochs=1, event_handler=event_handler, reader=reader,
                  feed_order=['img', 'label'])
    assert np.mean(accs[-5:]) > 0.9, accs[-5:]

    param_path = str(tmp_path / 'params')
    trainer.save_params(param_path)

    inferencer = fluid.Inferencer(infer_func=_infer_func,
                                  param_path=param_path,
                                  place=fluid.CPUPlace())
    batch = next(paddle.batch(paddle.dataset.mnist.test(), 16)())
    imgs = np.stack([np.asarray(s[0], 'float32').reshape(1, 28, 28)
                     for s in batch])
    labels = np.array([s[1] for s in batch])
    probs, = inferencer.infer({'img': imgs})
    probs = np.asarray(probs)
    assert probs.shape == (16, 10)
    np.testing.assert_allclose(probs.sum(-1), 1.0, rtol=1e-4)
    # the served model is the trained one: it should mostly agree
    assert (probs.argmax(-1) == labels).mean() > 0.8
