"""Tiered embedding storage (docs/embedding.md#tiers).

The host-RAM spill tier behind the HBM table — `HostArena` +
`TieredVocabTable` (paddle_tpu/embedding/tiers.py):

  * the arena: preallocated mmap-backed slot store, bit-exact put/peek
    round trip, free-list recycling gated on checkpoint marks, the
    atomic-replace manifest (+ .sum sidecar) torn-write drills;
  * the REGRESSION the tier exists to fix: today's evict -> re-admit
    cycle zeroes a row's trained state (row AND optimizer moments) —
    the tiered twin restores both bit-exactly;
  * the trainer seam: spill/restore at the step boundary through ONE
    gather+zero and ONE scatter fixed-signature dispatch (zero steady
    compiles), prefetch on the double-buffer worker, checkpoint/resume
    carrying the arena spill map exactly, the publisher seeing every
    device-mutated row;
  * the loud fallbacks: arena-full -> zeroing with a typed event +
    warning (never a silent wrong row), CRC-failed slot -> dropped
    loudly; dim-sharded tables refused typed (ROADMAP leftover);
  * the acceptance drill: a zipf stream over a table 8x the HBM row
    budget — the tiered loss trajectory is BIT-exact vs a no-eviction
    reference, while the plain-vocab leg diverges on re-admission.
"""
import json
import os

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers
from paddle_tpu.fluid.trainer import CheckpointConfig, Trainer
from paddle_tpu.streaming import (ArenaCorrupt, ArenaFull, DeltaPublisher,
                                  DimShardingUnsupported, HostArena,
                                  TieredVocabTable, VocabTable, host_arena,
                                  table_state_names)

from test_streaming import (CAP, DIM, FIELDS, _SinkEngine, _batches, _opt,
                            _stream_reader, _train_func)

pytestmark = pytest.mark.tiered


def _vecs(k, n_arrays=3, dim=DIM):
    """Distinct, reproducible per-id row vectors."""
    return [np.full((dim,), k * 10.0 + i, np.float32)
            for i in range(n_arrays)]


def _arena(tmp_path, slots=8, sub='arena'):
    return HostArena(str(tmp_path / sub), slots)


# ---------------------------------------------------------------------------
# HostArena: the slot store
# ---------------------------------------------------------------------------

def test_arena_roundtrip_bit_exact_and_checkpoint_gated_recycle(tmp_path):
    a = _arena(tmp_path, slots=4)
    assert a.put_many([(42, _vecs(42))]) == []
    got = a.peek(42)
    for x, y in zip(got, _vecs(42)):
        np.testing.assert_array_equal(x, y)
    assert 42 in a and len(a) == 1
    a.discard_many([42])
    assert 42 not in a
    # the released slot sits in LIMBO: the last committed serial may
    # still reference it, so it recycles only after a checkpoint mark
    assert a.put_many([(i, _vecs(i)) for i in range(3)]) == []
    assert a.put_many([(99, _vecs(99))]) == [99]
    a.mark_checkpoint()
    assert a.put_many([(99, _vecs(99))]) == []
    st = a.stats()
    assert st['used'] == 4 and st['free'] == 0 and st['limbo'] == 0


def test_arena_full_typed_and_mixed_dtype_rejected(tmp_path):
    a = _arena(tmp_path, slots=1)
    a.put(7, _vecs(7))
    with pytest.raises(ArenaFull, match='no free slot'):
        a.put(8, _vecs(8))
    b = _arena(tmp_path, slots=2, sub='b')
    with pytest.raises(ValueError, match='mixed dtypes'):
        b.put(1, [np.zeros(DIM, np.float32), np.zeros(DIM, np.float64)])


def test_arena_snapshot_roundtrip_and_geometry_mismatch(tmp_path):
    a = _arena(tmp_path, slots=4)
    a.put_many([(5, _vecs(5)), (6, _vecs(6))])
    snap = a.snapshot()
    json.dumps(snap)                       # checkpoint-meta JSON-able
    b = HostArena(a.path, slots=4)
    b.discard_many([5, 6])                 # drift b away from the snap
    b.load_snapshot(snap)                  # ...then restore it exactly
    assert sorted(b._entries) == [5, 6]
    for x, y in zip(b.peek(6), _vecs(6)):
        np.testing.assert_array_equal(x, y)
    c = _arena(tmp_path, slots=9, sub='c')
    with pytest.raises(ValueError, match='geometry mismatch'):
        c.load_snapshot(snap)


def test_arena_reopen_adopts_committed_manifest_bit_exact(tmp_path):
    a = _arena(tmp_path, slots=4)
    a.put_many([(5, _vecs(5)), (6, _vecs(6))])
    b = HostArena(a.path, slots=4)         # same dir: standalone reopen
    assert sorted(b._entries) == [5, 6]
    for x, y in zip(b.peek(5), _vecs(5)):
        np.testing.assert_array_equal(x, y)


def test_host_arena_path_is_per_process(tmp_path):
    a = host_arena(str(tmp_path / 'tier'), slots=2)
    assert os.path.basename(a.path) == 'h0'   # single-process: index 0


# ---------------------------------------------------------------------------
# fault drills: torn writes against the arena (satellite: SIGKILL mid-spill)
# ---------------------------------------------------------------------------

@pytest.mark.faults
@pytest.mark.parametrize('what', ['truncate_manifest', 'corrupt_manifest'])
def test_arena_torn_manifest_typed_on_reopen(tmp_path, what):
    """A torn/bit-rotted manifest NEVER adopts silently: the .sum
    sidecar exposes it as the typed ArenaCorrupt (FaultInjector's
    checkpoint tear modes work unmodified against the arena dir —
    same manifest.json + .sum + .npy layout)."""
    from paddle_tpu.utils.faults import FaultInjector
    a = _arena(tmp_path, slots=4)
    a.put_many([(5, _vecs(5))])
    FaultInjector(seed=0).torn_checkpoint(a.path, what=what)
    with pytest.raises(ArenaCorrupt):
        HostArena(a.path, slots=4)


@pytest.mark.faults
def test_arena_dropped_manifest_adopts_empty_never_torn_slots(tmp_path):
    """Crash BEFORE the first manifest commit (or its loss): the data
    file alone proves nothing — the arena adopts EMPTY; uncommitted
    slots are never adoptable."""
    from paddle_tpu.utils.faults import FaultInjector
    a = _arena(tmp_path, slots=4)
    a.put_many([(5, _vecs(5))])
    FaultInjector(seed=0).torn_checkpoint(a.path, what='drop_manifest')
    b = HostArena(a.path, slots=4)
    assert len(b) == 0 and b.peek(5) is None


@pytest.mark.faults
def test_arena_truncated_data_file_fails_crc_loudly(tmp_path):
    """Slot data torn under a valid manifest: the per-slot CRC refuses
    to serve it — typed, never a silently wrong row."""
    from paddle_tpu.utils.faults import FaultInjector
    a = _arena(tmp_path, slots=4)
    a.put_many([(5, _vecs(5))])
    FaultInjector(seed=0).torn_checkpoint(a.path, what='truncate_shard')
    b = HostArena(a.path, slots=4)         # manifest itself verifies
    with pytest.raises(ArenaCorrupt, match='CRC32'):
        b.peek(5)


@pytest.mark.faults
def test_arena_sigkill_mid_spill_uncommitted_slot_not_adopted(tmp_path):
    """SIGKILL between the slot write and the manifest commit: on
    resume the committed manifest still rules — the half-written slot
    is unreferenced (invisible), the committed entries intact."""
    a = _arena(tmp_path, slots=4)
    a.put_many([(5, _vecs(5))])
    # simulate the kill: scribble a new id's bytes straight into a free
    # slot of the data file WITHOUT a manifest commit
    mm = np.lib.format.open_memmap(a._data_path(), mode='r+')
    free_slot = a._free[-1]
    mm[free_slot, :, :] = 777.0
    mm.flush()
    del mm
    b = HostArena(a.path, slots=4)
    assert sorted(b._entries) == [5]       # the torn slot never adopted
    for x, y in zip(b.peek(5), _vecs(5)):
        np.testing.assert_array_equal(x, y)


# ---------------------------------------------------------------------------
# the regression the tier fixes, and its tiered twin
# ---------------------------------------------------------------------------

def _train_phase(t, tt, ids_seq):
    """One batch per id in ids_seq (the eviction-drill shape)."""
    b = [[(np.full((FIELDS, 1), i, 'int64'), np.ones((1,), 'float32'))]
         for i in ids_seq]
    t.train_stream(_stream_reader(b), vocabs={'ids': tt})


def test_regression_plain_vocab_evict_readmit_loses_trained_state():
    """The drill that motivates the tier: with a PLAIN VocabTable,
    evict -> re-admit zeroes the id's trained row and moments — hours
    of training on that id are gone (the tiered twin below restores
    them bit-exactly)."""
    vt = VocabTable(capacity=4, table='emb_w', admit_count=1)
    t = Trainer(_train_func, _opt)
    _train_phase(t, vt, (1, 2, 3))
    names = table_state_names(t.train_program, 'emb_w')
    row1 = int(vt.lookup([1])[0])
    saved = {n: np.asarray(t.scope._chain_get(n))[row1].copy()
             for n in names}
    assert any(np.abs(saved[n]).max() > 0 for n in names)
    _train_phase(t, vt, (9,))              # evicts LRU id 1
    assert vt.rows_evicted == 1
    # re-admit id 1: translate + boundary zeroing (no training step, so
    # the restored-or-zeroed state is inspectable)
    rows, lease = vt.translate([1])
    lease.release()
    for row in vt.drain_resets():
        for n in names:
            arr = np.array(t.scope._chain_get(n))
            arr[row] = 0
            t.scope._chain_set(n, arr)
    new_row = int(vt.lookup([1])[0])
    for n in names:
        got = np.asarray(t.scope._chain_get(n))[new_row]
        assert not np.array_equal(got, saved[n]) or \
            np.abs(saved[n]).max() == 0
    # the row is plain zeros: the trained state is LOST
    assert all(np.abs(np.asarray(t.scope._chain_get(n))[new_row]
                      ).max() == 0 for n in names)


def test_tiered_evict_readmit_restores_row_and_moments_bit_exact(tmp_path):
    """The tiered twin: eviction SPILLS the row + every optimizer
    moment into the arena; re-admission restores all of them
    bit-exactly (names from table_state_names — nothing hardcodes
    adam)."""
    vt = VocabTable(capacity=4, table='emb_w', admit_count=1)
    tt = TieredVocabTable(vt, _arena(tmp_path, slots=16))
    t = Trainer(_train_func, _opt)
    _train_phase(t, tt, (1, 2, 3))
    names = table_state_names(t.train_program, 'emb_w')
    assert len(names) >= 3                 # table + adam moments
    row1 = int(vt.lookup([1])[0])
    saved = {n: np.asarray(t.scope._chain_get(n))[row1].copy()
             for n in names}
    assert any(np.abs(saved[n]).max() > 0 for n in names if n != 'emb_w')
    _train_phase(t, tt, (9,))              # evicts id 1 -> spilled
    assert vt.rows_evicted == 1 and 1 in tt.arena
    np.testing.assert_array_equal(          # HBM row was zeroed...
        np.asarray(t.scope._chain_get('emb_w'))[row1] * 0,
        np.zeros(DIM, np.float32))
    rows, lease = tt.translate(np.full((FIELDS, 1), 1, 'int64'))
    lease.release()
    tt.apply_step_boundary(t.scope._chain_get, t.scope._chain_set, names)
    new_row = int(vt.lookup([1])[0])
    for n in names:                        # ...and restored bit-exact
        np.testing.assert_array_equal(
            np.asarray(t.scope._chain_get(n))[new_row], saved[n])
    assert 1 not in tt.arena               # slot released (to limbo)
    assert tt.tier_hits >= 1 and tt.restored >= 1


def test_tiered_same_window_evict_and_readmit_restores_exact(tmp_path):
    """Evict + re-admit inside ONE prefetch window (no boundary in
    between): the restore resolves against the spill that lands in the
    same apply_step_boundary call — state survives exactly."""
    vt = VocabTable(capacity=4, table='w', admit_count=1)
    tt = TieredVocabTable(vt, _arena(tmp_path, slots=8))
    store = {'w': np.arange(16, dtype=np.float32).reshape(4, 4),
             'm': np.arange(16, 32, dtype=np.float32).reshape(4, 4)}
    read = store.__getitem__

    def write(n, v):
        store[n] = np.asarray(v)

    r, l = tt.translate([1, 2, 3])
    l.release()
    tt.apply_step_boundary(read, write, ['w', 'm'])
    row1 = int(vt.lookup([1])[0])
    saved = (store['w'][row1].copy(), store['m'][row1].copy())
    r, l = tt.translate([9])               # evicts id 1
    l.release()
    r, l = tt.translate([1])               # re-admits id 1 (evicts 2)
    l.release()
    ch = tt.apply_step_boundary(read, write, ['w', 'm'])
    new_row = int(vt.lookup([1])[0])
    np.testing.assert_array_equal(store['w'][new_row], saved[0])
    np.testing.assert_array_equal(store['m'][new_row], saved[1])
    assert new_row in set(int(x) for x in ch['w'])
    assert tt.tier_hits >= 1


# ---------------------------------------------------------------------------
# loud fallbacks: arena full, dim sharding
# ---------------------------------------------------------------------------

def test_tiered_arena_full_falls_back_to_zeroing_loudly(tmp_path):
    """Arena exhausted: the evicted id falls back to the OLD zeroing
    path — typed event + RuntimeWarning + counted, never a silently
    wrong (stale or unzeroed) row."""
    from paddle_tpu import obs
    obs.enable(str(tmp_path / 'obs'))
    try:
        vt = VocabTable(capacity=4, table='w', admit_count=1)
        tt = TieredVocabTable(vt, _arena(tmp_path, slots=1))
        store = {'w': np.arange(16, dtype=np.float32).reshape(4, 4)}
        read = store.__getitem__

        def write(n, v):
            store[n] = np.asarray(v)

        r, l = tt.translate([1, 2, 3])
        l.release()
        tt.apply_step_boundary(read, write, ['w'])
        r, l = tt.translate([7, 8])        # two evictions, one slot
        l.release()
        with pytest.warns(RuntimeWarning, match='FULL'):
            tt.apply_step_boundary(read, write, ['w'])
        assert tt.dropped_full == 1 and len(tt.arena) == 1
        # both evicted rows were still ZEROED (the spill dispatch is
        # gather+zero regardless of whether the arena kept the gather)
        for raw in (7, 8):
            row = int(vt.lookup([raw])[0])
            np.testing.assert_array_equal(store['w'][row],
                                          np.zeros(4, np.float32))
        from paddle_tpu.obs import report as obs_report
        events, errors = obs_report.load_events(obs.run_log_path())
        assert errors == []
        assert 'streaming.tier.arena_full' in [e['name'] for e in events]
    finally:
        obs._reset()


def test_tiered_dim_sharded_table_refused_typed():
    """Column (dim) sharding spills would tear rows across hosts —
    out of scope (ROADMAP item 3 leftover), refused TYPED at
    train_stream entry, not silently mis-spilled."""
    vt = VocabTable(capacity=4, table='emb_w', admit_count=1)
    tt = TieredVocabTable(vt, HostArena('/tmp/unused-dimshard', 2))
    t = Trainer(_train_func, _opt)
    t.train_stream(_stream_reader([]), vocabs={'ids': tt})  # builds prog
    tvar = t.train_program.global_block().vars['emb_w']
    tvar.sharding = (None, 'model')        # dim-sharded annotation
    with pytest.raises(DimShardingUnsupported, match='EMBEDDING dim'):
        t.train_stream(_stream_reader(_batches(1)), vocabs={'ids': tt})
    tvar.sharding = ('model', None)        # row sharding is supported
    tt.validate_program(t.train_program)


# ---------------------------------------------------------------------------
# trainer seam: checkpoint/resume, publisher, zero steady compiles, obs
# ---------------------------------------------------------------------------

def test_tiered_checkpoint_resume_preserves_arena_and_spill_map(tmp_path):
    """The spill map rides the checkpoint meta; a resumed trainer (new
    process shape: fresh vocab + fresh arena object over the same dir)
    re-admits a pre-crash spilled id BIT-exactly."""
    ck = str(tmp_path / 'ck')
    ar = str(tmp_path / 'tier')
    vt = VocabTable(capacity=4, table='emb_w', admit_count=1)
    tt = TieredVocabTable(vt, HostArena(ar, 16))
    t = Trainer(_train_func, _opt,
                checkpoint_config=CheckpointConfig(checkpoint_dir=ck,
                                                   step_interval=1))
    _train_phase(t, tt, (1, 2, 3))
    names = table_state_names(t.train_program, 'emb_w')
    row1 = int(vt.lookup([1])[0])
    saved = {n: np.asarray(t.scope._chain_get(n))[row1].copy()
             for n in names}
    # two steps so the step_interval=1 cadence fires AFTER the spill
    # (step 0 never checkpoints — the serial must capture the arena)
    _train_phase(t, tt, (9, 9))            # evicts + spills id 1
    assert 1 in tt.arena
    spill_map = sorted(tt.arena._entries.items())

    t2 = Trainer(_train_func, _opt,
                 checkpoint_config=CheckpointConfig(checkpoint_dir=ck,
                                                    step_interval=1))
    assert t2.checkpoint_cfg.load_serial
    vt2 = VocabTable(capacity=4, table='emb_w', admit_count=1)
    tt2 = TieredVocabTable(vt2, HostArena(ar, 16))
    t2.train_stream(_stream_reader([]), vocabs={'ids': tt2})
    assert sorted(tt2.arena._entries.items()) == spill_map
    assert vt2.resident_ids() == vt.resident_ids()
    rows, lease = tt2.translate(np.full((FIELDS, 1), 1, 'int64'))
    lease.release()
    tt2.apply_step_boundary(t2.scope._chain_get, t2.scope._chain_set,
                            names)
    new_row = int(vt2.lookup([1])[0])
    for n in names:
        np.testing.assert_array_equal(
            np.asarray(t2.scope._chain_get(n))[new_row], saved[n])


def test_tiered_publisher_sees_every_device_mutated_row(tmp_path):
    """Every row apply_step_boundary mutates (zeroed OR restored) lands
    in that step's delta push — serving replicas converge after a
    spill/restore cycle even when the mutation came from a PREFETCHED
    batch's translation (double_buffer).

    Capacity 8 (7 assignable) keeps evictions deterministic under the
    double buffer: at most 2 in-flight leases pin 6 rows, so a new id
    always finds an unpinned victim (a smaller table would DEFER
    admissions to the cold row whenever every row is pinned)."""
    vt = VocabTable(capacity=8, table='emb_w', admit_count=1)
    tt = TieredVocabTable(vt, _arena(tmp_path, slots=64))
    boundary_rows = []
    orig = tt.apply_step_boundary

    def spy(read, write, names):
        out = orig(read, write, names)
        boundary_rows.append(
            sorted(int(r) for r in out['emb_w']) if out else [])
        return out

    tt.apply_step_boundary = spy
    sink = _SinkEngine()
    pub = DeltaPublisher(sink, interval_steps=1)
    t = Trainer(_train_func, _opt, double_buffer=True)
    seq = (1, 2, 3, 4, 5, 6, 7,            # fill the 7 assignable rows
           11, 12,                         # evict + spill two of them
           1, 2, 3, 4, 5)                  # re-admit: warm restores
    b = [[(np.full((FIELDS, 1), i, 'int64'), np.ones((1,), 'float32'))]
         for i in seq]
    t.train_stream(_stream_reader(b), vocabs={'ids': tt}, publisher=pub)
    assert tt.spilled >= 1 and tt.restored >= 1
    assert len(sink.pushed) == len(seq)
    for rows, push in zip(boundary_rows, sink.pushed):
        pushed = set(np.asarray(push['emb_w'][0]).tolist())
        assert set(rows) <= pushed, (rows, pushed)


def test_tiered_zero_steady_compiles_and_obs_report_section(tmp_path):
    """Churny eviction/restore traffic holds the fixed-signature
    contract: ONE spill jit, ONE restore jit, zero executor cache
    misses in the steady leg — and the obs run log renders the
    `-- tiers --` report section."""
    from paddle_tpu import obs
    from paddle_tpu.obs import report as obs_report
    obs.enable(str(tmp_path / 'obs'))
    try:
        # capacity 8: eviction stays deterministic under the double
        # buffer (<= 6 rows pinned by in-flight leases, 7 assignable)
        vt = VocabTable(capacity=8, table='emb_w', admit_count=1)
        tt = TieredVocabTable(vt, _arena(tmp_path, slots=64))
        t = Trainer(_train_func, _opt, double_buffer=True)
        warm = [1, 2, 3, 4, 5, 6, 7,       # fill
                11, 12, 13,                # spill three residents
                1, 2, 3, 4, 5, 6, 7]      # warm restores
        _train_phase(t, tt, warm)          # warm leg: compiles happen
        misses0 = t.exe.cache_stats['misses']
        spill_fns = len(tt._spiller._fns)
        restore_fns = len(tt._restorer._fns)
        assert tt.spilled >= 1 and tt.restored >= 1
        steady = [21, 22, 23, 1, 2, 3, 4, 5, 6, 7]
        _train_phase(t, tt, steady)        # steady leg: churn continues
        assert t.exe.cache_stats['misses'] == misses0, \
            'tier traffic caused steady-state compiles'
        assert len(tt._spiller._fns) == spill_fns <= 1
        assert len(tt._restorer._fns) == restore_fns <= 1
        events, errors = obs_report.load_events(obs.run_log_path())
        assert errors == []
        names = [e['name'] for e in events]
        assert 'streaming.tier.spill' in names
        assert 'streaming.tier.restore' in names
        assert 'streaming.tier.prefetch' in names
        text = obs_report.summarize(events)
        assert '-- tiers --' in text
        assert 'restored warm' in text
    finally:
        obs._reset()


# ---------------------------------------------------------------------------
# acceptance: zipf stream over a table 8x the HBM row budget
# ---------------------------------------------------------------------------

HBM_BUDGET = 4                             # vocab capacity (3 + cold)
UNIVERSE = 8 * HBM_BUDGET                  # id space: 8x the budget


def _zero_init_net():
    """The A/B net: Constant(0) table init makes a freshly-zeroed row
    IDENTICAL to a never-trained one, so the only divergence lever
    left is trained state lost (or kept) across evict/re-admit."""
    fluid.default_main_program().random_seed = 7
    fluid.default_startup_program().random_seed = 7
    ids = layers.data(name='ids', shape=[FIELDS, 1], dtype='int64')
    label = layers.data(name='label', shape=[1], dtype='float32')
    emb = layers.embedding(
        ids, size=[UNIVERSE + 1, DIM], is_sparse=True,
        param_attr=fluid.ParamAttr(
            name='emb_w', initializer=fluid.initializer.Constant(0.0)))
    pred = layers.fc(input=emb, size=1, num_flatten_dims=2,
                     param_attr=fluid.ParamAttr(name='fc_w'))
    score = layers.reduce_sum(pred, dim=1)
    loss = layers.mean(layers.square(score - label))
    return [loss]


def _zipf_batches(n, seed=3):
    """Zipf-weighted draws over the 8x universe with a drifting hot
    set: plenty of evictions AND warm re-admissions."""
    rng = np.random.RandomState(seed)
    ranks = np.arange(1, UNIVERSE + 1, dtype=np.float64)
    p = 1.0 / ranks
    p /= p.sum()
    out = []
    for k in range(n):
        shift = (k // 4) % UNIVERSE        # the hot set drifts
        ids = (rng.choice(UNIVERSE, size=FIELDS, replace=False, p=p)
               + shift) % UNIVERSE
        lbl = rng.randn(1).astype('float32')
        out.append([(ids.reshape(FIELDS, 1).astype('int64'), lbl)])
    return out


def test_e2e_zipf_8x_budget_tiered_bit_exact_plain_diverges(tmp_path):
    """The acceptance drill. Three legs over the SAME zipf stream, a
    table 8x the HBM row budget:

      reference — capacity covers the universe, nothing ever evicted;
      tiered    — capacity 4 + host arena: constant spill/restore;
      plain     — capacity 4, today's zeroing eviction.

    The tiered loss trajectory is BIT-exact vs the reference (warm
    re-admission restores trained state exactly; a cold admission
    equals the Constant(0) init), the plain leg DIVERGES once a
    trained id re-admits zeroed — and the tiered leg stays at zero
    steady-state compiles."""
    batches = _zipf_batches(24)
    warm, steady = batches[:12], batches[12:]

    def run_leg(tt_or_vt):
        # double_buffer=False: translation runs inline, so no lease
        # from a still-in-flight step can pin rows at admission time —
        # every new id admits (never defers to the cold row) and all
        # three legs make IDENTICAL vocab decisions, the precondition
        # for the bit-exact compare (the prefetch leg is exercised by
        # the zero-compile and publisher drills above)
        t = Trainer(_zero_init_net, _opt, double_buffer=False)
        losses = []

        def on_event(ev):
            if hasattr(ev, 'metrics') and ev.metrics:
                losses.append(np.asarray(ev.metrics[0]).copy())

        t.train_stream(_stream_reader(warm), vocabs={'ids': tt_or_vt},
                       event_handler=on_event)
        misses0 = t.exe.cache_stats['misses']
        t.train_stream(_stream_reader(steady), vocabs={'ids': tt_or_vt},
                       event_handler=on_event)
        steady_misses = t.exe.cache_stats['misses'] - misses0
        return losses, steady_misses

    ref_losses, _ = run_leg(
        VocabTable(UNIVERSE + 1, table='emb_w', admit_count=1))
    tt = TieredVocabTable(
        VocabTable(HBM_BUDGET, table='emb_w', admit_count=1),
        _arena(tmp_path, slots=4 * UNIVERSE))
    tier_losses, tier_misses = run_leg(tt)
    plain_losses, _ = run_leg(
        VocabTable(HBM_BUDGET, table='emb_w', admit_count=1))

    assert len(ref_losses) == len(tier_losses) == len(batches)
    # the tier actually worked: evictions happened, re-admissions hit
    assert tt.spilled >= 3 and tt.tier_hits >= 1, tt.stats()
    assert tt.hit_rate() > 0
    # tiered == reference, BIT-exact, every step
    for a, b in zip(ref_losses, tier_losses):
        np.testing.assert_array_equal(a, b)
    # plain leg loses trained state on re-admission: it must diverge
    assert any(not np.array_equal(a, b)
               for a, b in zip(ref_losses, plain_losses)), \
        'plain leg never diverged — the drill admitted no trained id?'
    assert tier_misses == 0, 'tiered steady leg recompiled'
