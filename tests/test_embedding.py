"""Sharded-embedding subsystem (docs/embedding.md).

`embedding(is_sparse=True, is_distributed=True)` on a row-sharded table
(`ParamAttr(sharding=(axis, None))` + `Program.set_mesh`) lowers the
lookup to the all_to_all wire (paddle_tpu.embedding.lookup) and keeps the
gradient a touched-rows-only SparseRows applied per shard — the dense
[vocab, dim] gradient never exists. These drills pin:

  * the wire itself (bucket/dedup/exchange) against the dense gather,
    bit-exact, duplicates and padding_idx included;
  * the A/B contract on the 8-device CPU mesh: sharded-sparse training
    matches the replicated-dense path for fetches AND post-step table
    rows (documented tolerance: one float32 rounding from the merge's
    accumulation order), through run(), run_bundle(), and a 2-step
    trained deepfm, with steady-state compiles == 0 via cache_stats;
  * loud inertness (the silently-ignored-attr bug this PR retires), the
    untileable-vocab fallback, the DistributeTranspiler shim's
    annotation translation, and the obs events.

Conftest forces the 8-virtual-device CPU platform, so every mesh here is
real (8 shards), just not fast.
"""
import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu.fluid as fluid
from paddle_tpu import embedding as emb_mod
from paddle_tpu.fluid import layers

from util import fresh_program

pytestmark = pytest.mark.embedding

VOCAB, DIM = 48, 8          # 48 rows over 8 shards: 6 rows per shard
AXIS = 'model'


def _mesh8():
    from paddle_tpu import parallel
    return parallel.make_mesh({AXIS: 8})


# ---------------------------------------------------------------------------
# the functional wire
# ---------------------------------------------------------------------------

def test_sharded_lookup_matches_dense_gather_bit_exact():
    """Forward wire vs jnp.take over duplicate-heavy ids of every shape:
    a gather is a gather no matter which shard answered it."""
    rng = np.random.RandomState(0)
    w = jnp.asarray(rng.randn(VOCAB, DIM).astype('float32'))
    mesh = _mesh8()
    for shape in [(5,), (6, 4), (3, 2, 2)]:
        ids = jnp.asarray(rng.randint(0, VOCAB, size=shape), jnp.int32)
        out = emb_mod.sharded_lookup(w, ids, mesh, AXIS)
        np.testing.assert_array_equal(np.asarray(out),
                                      np.asarray(jnp.take(w, ids, axis=0)))


def test_sharded_lookup_padding_idx_zeroes_rows():
    rng = np.random.RandomState(1)
    w = jnp.asarray(rng.randn(VOCAB, DIM).astype('float32'))
    ids = jnp.asarray([3, 7, 3, 0, 7], jnp.int32)
    out = np.asarray(emb_mod.sharded_lookup(w, ids, _mesh8(), AXIS,
                                            padding_idx=7))
    assert np.all(out[[1, 4]] == 0)
    np.testing.assert_array_equal(out[0], np.asarray(w[3]))


def test_sharded_lookup_rejects_untileable_vocab():
    w = jnp.zeros((50, DIM))     # 50 % 8 != 0
    with pytest.raises(ValueError, match='pad_vocab'):
        emb_mod.sharded_lookup(w, jnp.zeros((4,), jnp.int32), _mesh8(),
                               AXIS)


def test_dedup_plan_collapses_duplicates():
    ids = jnp.asarray([9, 3, 9, 9, 3, 41], jnp.int32)
    uids, seg, order, n_unique = emb_mod.dedup_plan(ids)
    assert int(n_unique) == 3
    assert sorted(np.asarray(uids[:3]).tolist()) == [3, 9, 41]
    # every occurrence maps (through sort order + seg) back to its own id
    sid = np.asarray(ids)[np.asarray(order)]
    np.testing.assert_array_equal(np.asarray(uids)[np.asarray(seg)], sid)


def test_pad_vocab_and_wire_stats():
    assert emb_mod.pad_vocab(6041, 8) == 6048
    assert emb_mod.pad_vocab(48, 8) == 48
    s = emb_mod.wire_stats(24, VOCAB, DIM, 8)
    assert s['query_capacity'] == 3
    assert s['row_bytes_per_device'] == 3 * 8 * DIM * 4


# ---------------------------------------------------------------------------
# the Program path: A/B vs replicated dense on the same 8-device mesh
# ---------------------------------------------------------------------------

def _build(sharded, is_sparse, optimizer, seed=7, mesh_axes=None,
           vocab=VOCAB):
    main = fluid.default_main_program()
    startup = fluid.default_startup_program()
    main.random_seed = seed
    startup.random_seed = seed
    ids = layers.data(name='ids', shape=[4, 1], dtype='int64')
    pa = fluid.ParamAttr(name='emb_w',
                         sharding=(AXIS, None) if sharded else None)
    emb = layers.embedding(ids, size=[vocab, DIM], is_sparse=is_sparse,
                           is_distributed=sharded, param_attr=pa)
    pred = layers.fc(input=emb, size=1, num_flatten_dims=2,
                     bias_attr=False,
                     param_attr=fluid.ParamAttr(name='fc_w'))
    loss = layers.mean(layers.square(pred - 1.0))
    optimizer().minimize(loss)
    if mesh_axes is not False:
        main.set_mesh(mesh_axes or {AXIS: 8})
    return main, startup, loss


def _train(sharded, is_sparse, optimizer, batches, bundle=0,
           mesh_axes=None, vocab=VOCAB, seed=7):
    """Returns (losses, table, plans, exe) after len(batches) steps."""
    with fresh_program() as (_, _s):
        main, startup, loss = _build(sharded, is_sparse, optimizer,
                                     mesh_axes=mesh_axes, vocab=vocab,
                                     seed=seed)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        losses = []
        if bundle:
            for i in range(0, len(batches), bundle):
                feeds = [{'ids': b} for b in batches[i:i + bundle]]
                out = exe.run_bundle(main, feeds=feeds, fetch_list=[loss])
                losses.extend(np.asarray(out[0]).reshape(-1).tolist())
        else:
            for b in batches:
                out = exe.run(main, feed={'ids': b}, fetch_list=[loss])
                losses.append(float(np.asarray(out[0]).reshape(())))
        from paddle_tpu.fluid.executor import global_scope
        table = np.asarray(global_scope().find_var('emb_w').get_tensor())
        plans = [c.sparse_plan for c in exe._cache.values()]
        return losses, table, plans, exe


def _batches(n=3, seed=3, dup=True):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        b = rng.randint(0, VOCAB, size=(6, 4, 1)).astype('int64')
        if dup:
            b[:3] = b[3:6]
        out.append(b)
    return out


def test_sharded_sparse_matches_replicated_dense_sgd():
    """The acceptance A/B: fetches and post-step table rows match the
    replicated dense path; the sparse plan is armed; steady-state
    compiles are zero (each signature compiles exactly once)."""
    sgd = lambda: fluid.optimizer.SGD(learning_rate=0.1)
    batches = _batches()
    dl, dt, dplans, _ = _train(False, False, sgd, batches)
    sl, st, splans, exe = _train(True, True, sgd, batches)
    assert any('emb_w' in p for p in splans if p)
    assert not any(p for p in dplans)
    # documented tolerance: the merge/scatter accumulation order differs
    # from the dense subtract by at most a float32 rounding per step
    np.testing.assert_allclose(sl, dl, rtol=1e-5)
    np.testing.assert_allclose(st, dt, rtol=1e-4, atol=1e-6)
    # steady state = zero recompiles: 2 keys (startup, step), each missed
    # once, and every later run hit
    stats = exe.cache_stats
    assert stats['misses'] == 2
    assert stats['hits'] == len(batches) - 1


def test_sharded_sparse_matches_unsharded_sparse_adagrad_and_adam():
    """Nonlinear updates see each touched row once (merged duplicates) —
    per shard — and trajectories match the single-device SPARSE path
    (same merge math; only the partitioning differs). The dense path is
    NOT the reference here: adagrad/adam's first touch of a row moves it
    by ~lr*sign(g), so a near-zero gradient makes dense-vs-merged float
    noise flip signs — the dense<->sparse equivalence itself is pinned
    (well-away from that edge) in test_sparse_embedding.py."""
    for opt in (lambda: fluid.optimizer.Adagrad(learning_rate=0.1),
                lambda: fluid.optimizer.Adam(learning_rate=0.01)):
        batches = _batches()
        ul, ut, uplans, _ = _train(False, True, opt, batches,
                                   mesh_axes=False)
        sl, st, splans, _ = _train(True, True, opt, batches)
        assert any('emb_w' in p for p in uplans if p)
        assert any('emb_w' in p for p in splans if p)
        np.testing.assert_allclose(sl, ul, rtol=1e-5)
        np.testing.assert_allclose(st, ut, rtol=1e-4, atol=1e-6)


def test_sharded_sparse_run_bundle_matches_unbundled():
    """K-step bundling composes with the sharded wire + sparse update:
    the scan body is the same step, so trajectories agree."""
    sgd = lambda: fluid.optimizer.SGD(learning_rate=0.1)
    batches = _batches(n=4)
    ul, ut, _, _ = _train(True, True, sgd, batches)
    bl, bt, bplans, _ = _train(True, True, sgd, batches, bundle=2)
    assert any('emb_w' in p for p in bplans if p)
    np.testing.assert_allclose(bl, ul, rtol=1e-5)
    np.testing.assert_allclose(bt, ut, rtol=1e-5, atol=1e-7)


def test_sharded_dense_grad_path_without_is_sparse():
    """is_sparse=False + is_distributed=True: the wire still serves the
    lookup and jax.grad flows back through BOTH all_to_alls (transpose =
    all_to_all) into a row-sharded dense grad. No sparse plan."""
    sgd = lambda: fluid.optimizer.SGD(learning_rate=0.1)
    batches = _batches(n=2)
    dl, dt, _, _ = _train(False, False, sgd, batches)
    sl, st, splans, _ = _train(True, False, sgd, batches)
    assert not any(p for p in splans)
    np.testing.assert_allclose(sl, dl, rtol=1e-5)
    np.testing.assert_allclose(st, dt, rtol=1e-4, atol=1e-6)


def test_sharded_sparse_on_dp_model_mesh():
    """dp x model composition: batch shards over dp, table rows over
    model; the wire runs inside each dp row."""
    sgd = lambda: fluid.optimizer.SGD(learning_rate=0.1)
    batches = _batches(n=2)
    base_l, base_t, _, _ = _train(False, False, sgd, batches)
    sl, st, splans, _ = _train(True, True, sgd, batches,
                               mesh_axes={'dp': 2, AXIS: 4})
    assert any('emb_w' in p for p in splans if p)
    np.testing.assert_allclose(sl, base_l, rtol=1e-5)
    np.testing.assert_allclose(st, base_t, rtol=1e-4, atol=1e-6)


def test_untileable_vocab_falls_back_dense_with_warning():
    """vocab 50 over 8 shards: the rule warns and serves the dense gather
    — numerics match the replicated path exactly (the statically-checked
    EmbeddingShardUntileable case reached at runtime)."""
    sgd = lambda: fluid.optimizer.SGD(learning_rate=0.1)
    rng = np.random.RandomState(5)
    batches = [rng.randint(0, 50, size=(6, 4, 1)).astype('int64')
               for _ in range(2)]
    dl, dt, _, _ = _train(False, False, sgd, batches, vocab=50)
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter('always')
        sl, st, _, _ = _train(True, True, sgd, batches, vocab=50)
    assert any('does not tile' in str(w.message) for w in rec)
    np.testing.assert_allclose(sl, dl, rtol=1e-5)
    np.testing.assert_allclose(st, dt, rtol=1e-4, atol=1e-6)


def test_trained_deepfm_sharded_matches_unsharded():
    """2-step trained deepfm (both FM tables sharded-sparse, adam) vs the
    same model single-device sparse: the model the subsystem exists for.
    Small config — the 1e6-vocab footprint proof lives in bench.py
    --phase embedding."""
    from paddle_tpu.models.deepfm import deepfm

    def run(dist):
        with fresh_program() as (main, startup):
            main.random_seed = 11
            startup.random_seed = 11
            feat = layers.data(name='feat_ids', shape=[6], dtype='int64')
            label = layers.data(name='label', shape=[1], dtype='int64')
            cost, _, _ = deepfm(feat, label, num_fields=6, vocab_size=64,
                                embed_dim=4, hidden=[16],
                                dist_axis=AXIS if dist else None,
                                is_sparse=True)
            fluid.optimizer.Adam(learning_rate=1e-2).minimize(cost)
            if dist:
                main.set_mesh({AXIS: 8})
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            rng = np.random.RandomState(2)
            losses = []
            for _ in range(2):
                feed = {'feat_ids': rng.randint(0, 64, size=(8, 6))
                        .astype('int64'),
                        'label': rng.randint(0, 2, size=(8, 1))
                        .astype('int64')}
                out = exe.run(main, feed=feed, fetch_list=[cost])
                losses.append(float(np.asarray(out[0]).reshape(())))
            from paddle_tpu.fluid.executor import global_scope
            tables = {n: np.asarray(global_scope().find_var(n).get_tensor())
                      for n in ('fm_first_w', 'fm_embed')}
            plans = [c.sparse_plan for c in exe._cache.values()]
            return losses, tables, plans

    ul, utab, uplans = run(False)
    sl, stab, splans = run(True)
    assert any(set(p) == {'fm_first_w', 'fm_embed'}
               for p in splans if p)
    assert any(set(p) == {'fm_first_w', 'fm_embed'}
               for p in uplans if p)
    np.testing.assert_allclose(sl, ul, rtol=1e-4)
    for n in utab:
        np.testing.assert_allclose(stab[n], utab[n], rtol=1e-3,
                                   atol=1e-6)


# ---------------------------------------------------------------------------
# loud inertness + shims
# ---------------------------------------------------------------------------

def test_is_distributed_without_annotation_warns_at_build():
    with fresh_program():
        ids = layers.data(name='ids', shape=[1], dtype='int64')
        with pytest.warns(UserWarning, match='INERT'):
            layers.embedding(ids, size=[VOCAB, DIM], is_sparse=True,
                             is_distributed=True)


def test_annotated_without_mesh_warns_at_compile():
    """The annotation is declared but the TRAINING program never calls
    set_mesh: the compile warns, naming the table and the missing axis,
    and the lookup serves dense-replicated. Inference programs are
    exempt (the gather_table + set_mesh(None) export seam runs
    dense-after-gather on purpose)."""
    with fresh_program():
        ids = layers.data(name='ids', shape=[4, 1], dtype='int64')
        emb = layers.embedding(
            ids, size=[VOCAB, DIM], is_sparse=True, is_distributed=True,
            param_attr=fluid.ParamAttr(name='emb_w',
                                       sharding=(AXIS, None)))
        pred = layers.fc(input=emb, size=1, num_flatten_dims=2,
                         bias_attr=False)
        loss = layers.mean(layers.square(pred - 1.0))
        infer = fluid.default_main_program().clone(for_test=True)
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        feed = {'ids': np.zeros((4, 4, 1), 'int64')}
        with pytest.warns(UserWarning, match='no mesh'):
            exe.run(fluid.default_main_program(), feed=feed,
                    fetch_list=[loss])
        # the for_test clone (no autodiff) compiles WITHOUT the warning
        with warnings.catch_warnings(record=True) as rec:
            warnings.simplefilter('always')
            exe.run(infer, feed=feed, fetch_list=[loss])
        assert not [w for w in rec if 'no mesh' in str(w.message)]


def test_distribute_transpiler_shim_translates_to_annotations():
    """transpile() deprecation-warns and stamps the row-sharding
    annotation + dist_axis routing attr on is_distributed tables — the
    pserver -> sharded-embedding migration, mechanically applied."""
    with fresh_program() as (main, _):
        ids = layers.data(name='ids', shape=[4, 1], dtype='int64')
        with warnings.catch_warnings():
            warnings.simplefilter('ignore')  # inert-annotation warning
            emb = layers.embedding(ids, size=[VOCAB, DIM], is_sparse=True,
                                   is_distributed=True,
                                   param_attr=fluid.ParamAttr(
                                       name='emb_w'))
        pred = layers.fc(input=emb, size=1, num_flatten_dims=2,
                         bias_attr=False)
        loss = layers.mean(layers.square(pred - 1.0))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        with pytest.warns(DeprecationWarning, match='sharded-embedding'):
            fluid.DistributeTranspiler().transpile(trainer_id=0,
                                                   trainers=2)
        w = main.global_block().vars['emb_w']
        assert w.sharding == ('dp', None)
        op = next(o for o in main.global_block().ops
                  if o.type == 'lookup_table')
        assert op.attrs['dist_axis'] == 'dp'
        # and the legacy path still trains (dense grad, wire lookup over
        # the dp mesh), matching the untranspiled single-device numerics
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        out = exe.run(main, feed={'ids': np.zeros((4, 4, 1), 'int64')},
                      fetch_list=[loss])
        assert np.isfinite(np.asarray(out[0])).all()


def test_ps_dispatcher_shims_deprecated():
    from paddle_tpu.fluid.transpiler.ps_dispatcher import (HashName,
                                                           RoundRobin)

    class V(object):
        def __init__(self, name):
            self.name = name

    with pytest.warns(DeprecationWarning, match='mesh sharding'):
        rr = RoundRobin(['a:1', 'b:2'])
    assert rr.dispatch([V('x'), V('y'), V('z')]) == ['a:1', 'b:2', 'a:1']
    with pytest.warns(DeprecationWarning):
        HashName(['a:1', 'b:2'])


# ---------------------------------------------------------------------------
# observability
# ---------------------------------------------------------------------------

def test_embedding_obs_events_and_rows_counter(tmp_path):
    import json

    from paddle_tpu import obs
    obs.enable(str(tmp_path))
    try:
        sgd = lambda: fluid.optimizer.SGD(learning_rate=0.1)
        base = obs.REGISTRY.total('embedding.rows_touched') or 0
        _train(True, True, sgd, _batches(n=2))
        delta = obs.REGISTRY.total('embedding.rows_touched') - base
        assert delta == 2 * 6 * 4          # 2 steps x 24 ids
    finally:
        obs._reset()
    events = []
    for p in tmp_path.glob('*.jsonl'):
        with open(p) as f:
            events.extend(json.loads(l) for l in f if l.strip())
    lookups = [e for e in events if e.get('name') == 'embedding.lookup']
    updates = [e for e in events
               if e.get('name') == 'embedding.update_rows']
    assert lookups and lookups[0]['fields']['axis_size'] == 8
    assert updates and updates[0]['fields']['rows_per_step'] == 24
    assert updates[0]['fields']['tables'] == ['emb_w']


# ---------------------------------------------------------------------------
# movielens end-to-end (slow): sharded train -> export -> serve
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_movielens_sharded_train_export_serve(tmp_path):
    """The pipeline the subsystem exists for: recommender_system with
    row-sharded user/movie/title tables trained on an 8-shard mesh
    (sharded-sparse), tables gathered at the export seam, the inference
    tower exported via export_compiled, and ONE batch served through the
    ServingEngine."""
    import paddle_tpu as paddle
    from paddle_tpu import serving
    from paddle_tpu.models import recommender_system as rs

    with fresh_program() as (main, startup):
        main.random_seed = 5
        startup.random_seed = 5
        scale_infer, avg_cost = rs.model(emb_dim=8, tower_dim=16,
                                         dist_axis=AXIS, axis_size=8,
                                         is_sparse=True)
        infer_prog = main.clone(for_test=True)
        fluid.optimizer.SGD(learning_rate=0.1).minimize(avg_cost)
        main.set_mesh({AXIS: 8})
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)

        reader = paddle.batch(paddle.dataset.movielens.train(),
                              batch_size=16)
        feeder = fluid.DataFeeder(
            feed_list=[main.global_block().vars[n]
                       for n in rs.FEED_ORDER], place=fluid.CPUPlace())
        losses = []
        for i, batch in enumerate(reader()):
            out = exe.run(main, feed=feeder.feed(batch),
                          fetch_list=[avg_cost])
            losses.append(float(np.asarray(out[0]).reshape(())))
            if i >= 1:
                break
        assert np.isfinite(losses).all()
        assert any(c.sparse_plan for c in exe._cache.values())

        # export seam: gather the sharded tables to host values so the
        # (un-meshed) inference tower traces single-device
        from paddle_tpu.fluid.executor import global_scope
        scope = global_scope()
        for v in main.list_vars():
            if v.persistable and scope._chain_get(v.name) is not None:
                scope._chain_set(
                    v.name, jnp.asarray(emb_mod.gather_table(scope,
                                                             v.name)))
        infer_prog.set_mesh(None)
        feed_example = {}
        example = feeder.feed(batch)
        for n in rs.FEED_ORDER[:-1]:   # every input but the score label
            val = example[n]
            arr = np.asarray(val.data if hasattr(val, 'data') else val)
            feed_example[n] = arr
        from paddle_tpu import inference
        inference.export_compiled(
            str(tmp_path / 'model'), feed_example, [scale_infer], exe,
            main_program=infer_prog)
        runner = inference.load_compiled(str(tmp_path / 'model'))

        # the exported module is fixed-shape (batch 16): one bucket
        engine = serving.ServingEngine(
            runner, serving.ServingConfig(max_batch_size=16,
                                          buckets=[16],
                                          max_queue_delay_ms=1.0))
        try:
            engine.warmup()
            fut = engine.submit({n: feed_example[n]
                                 for n in feed_example})
            scores = fut.result(timeout=60)[0]
            assert np.isfinite(np.asarray(scores)).all()
        finally:
            engine.shutdown()
