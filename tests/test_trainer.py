"""High-level Trainer/Inferencer API.

Parity: reference python/paddle/fluid/trainer.py:169 + inferencer.py:31
(the book-chapter train_func/optimizer_func loop, events, CheckpointConfig
crash-resume, save_params -> Inferencer round trip).
"""
import numpy as np
import pytest

import paddle_tpu.fluid as fluid


def _linear_train_func():
    x = fluid.layers.data(name='x', shape=[4], dtype='float32')
    y = fluid.layers.data(name='y', shape=[1], dtype='float32')
    pred = fluid.layers.fc(input=x, size=1, act=None)
    return fluid.layers.mean(
        fluid.layers.square_error_cost(input=pred, label=y))


def _infer_func():
    x = fluid.layers.data(name='x', shape=[4], dtype='float32')
    return fluid.layers.fc(input=x, size=1, act=None)


_W = np.array([[1.5], [-2.0], [0.5], [3.0]], 'float32')


def _reader(n=64, batch=8, seed=0):
    def r():
        rng = np.random.RandomState(seed)
        for _ in range(n // batch):
            xs = rng.rand(batch, 4).astype('float32')
            ys = xs @ _W
            yield [(xs[i], ys[i]) for i in range(batch)]
    return r


def _sgd():
    return fluid.optimizer.SGD(learning_rate=0.1)


def test_trainer_converges_and_fires_events(tmp_path):
    events = []
    losses = []

    def handler(ev):
        events.append(type(ev).__name__)
        if isinstance(ev, fluid.EndStepEvent):
            losses.append(float(np.asarray(ev.metrics[0])))

    trainer = fluid.Trainer(train_func=_linear_train_func,
                            optimizer_func=_sgd, place=fluid.CPUPlace())
    trainer.train(num_epochs=30, event_handler=handler,
                  reader=_reader(), feed_order=['x', 'y'])
    assert losses[0] > 1.0 and losses[-1] < 0.01, (losses[0], losses[-1])
    assert events[0] == 'BeginEpochEvent'
    assert events.count('BeginEpochEvent') == 30
    assert events.count('EndEpochEvent') == 30
    assert events.count('EndStepEvent') == 30 * 8
    # test() averages metrics on the for_test clone
    test_loss = trainer.test(reader=_reader(seed=1), feed_order=['x', 'y'])
    assert test_loss[0] < 0.01

    # save_params -> Inferencer round trip
    trainer.save_params(str(tmp_path / 'model'))
    inf = fluid.Inferencer(infer_func=_infer_func,
                           param_path=str(tmp_path / 'model'),
                           place=fluid.CPUPlace())
    xs = np.random.RandomState(2).rand(8, 4).astype('float32')
    out = inf.infer({'x': xs})[0]
    np.testing.assert_allclose(out, xs @ _W, atol=0.1)


def test_trainer_stop():
    seen = []

    def handler(ev):
        if isinstance(ev, fluid.EndStepEvent):
            seen.append(ev.step)
            if len(seen) >= 3:
                trainer.stop()

    trainer = fluid.Trainer(train_func=_linear_train_func,
                            optimizer_func=_sgd, place=fluid.CPUPlace())
    trainer.train(num_epochs=10, event_handler=handler, reader=_reader(),
                  feed_order=['x', 'y'])
    assert len(seen) == 3


def test_trainer_checkpoint_resume(tmp_path):
    """Simulated crash mid-training: a fresh Trainer over the same
    checkpoint dir resumes from the last snapshot instead of cold-starting,
    and skips the already-done steps of the crash epoch."""
    ckpt = str(tmp_path / 'ckpt')
    cfg = fluid.CheckpointConfig(checkpoint_dir=ckpt, max_num_checkpoints=2,
                                 epoch_interval=1, step_interval=1)

    class Crash(Exception):
        pass

    steps_a = []

    def crash_handler(ev):
        if isinstance(ev, fluid.EndStepEvent):
            steps_a.append((ev.epoch, ev.step))
            if ev.epoch == 1 and ev.step == 3:
                raise Crash()  # hard kill: no cleanup runs

    t1 = fluid.Trainer(train_func=_linear_train_func, optimizer_func=_sgd,
                       place=fluid.CPUPlace(), checkpoint_config=cfg)
    with pytest.raises(Crash):
        t1.train(num_epochs=4, event_handler=crash_handler,
                 reader=_reader(), feed_order=['x', 'y'])
    import os
    assert os.path.isdir(ckpt) and os.listdir(ckpt)
    w_at_crash = np.asarray(
        t1.scope.vars[[n for n in t1.scope.vars if n.endswith('.w_0')][0]])

    steps_b = []

    def handler(ev):
        if isinstance(ev, fluid.EndStepEvent):
            steps_b.append((ev.epoch, ev.step))

    cfg2 = fluid.CheckpointConfig(checkpoint_dir=ckpt, max_num_checkpoints=2,
                                  epoch_interval=1, step_interval=1)
    t2 = fluid.Trainer(train_func=_linear_train_func, optimizer_func=_sgd,
                       place=fluid.CPUPlace(), checkpoint_config=cfg2)
    # resumed params match the crash-time params (last checkpoint = step 3)
    w_resumed = np.asarray(
        t2.scope.vars[[n for n in t2.scope.vars if n.endswith('.w_0')][0]])
    np.testing.assert_allclose(w_resumed, w_at_crash, rtol=1e-6)
    stray = os.path.join(ckpt, 'user_notes.txt')
    open(stray, 'w').write('not a checkpoint')
    t2.train(num_epochs=4, event_handler=handler, reader=_reader(),
             feed_order=['x', 'y'])
    # epoch 0 fully skipped; epoch 1 resumes after step 3
    assert (1, 3) not in steps_b
    assert (1, 4) in steps_b
    assert min(e for e, s in steps_b) == 1
    assert steps_b[-1] == (3, 7)
    # successful finish removes the checkpoint_<n> serials but ONLY them
    assert not [d for d in os.listdir(ckpt) if d.startswith('checkpoint_')]
    assert os.path.exists(stray)


def test_trainer_resume_skips_torn_checkpoint(tmp_path):
    """A meta.json torn by a crash mid-save must fall back to the previous
    intact serial instead of crashing Trainer construction forever."""
    import os
    ckpt = str(tmp_path / 'ckpt')
    cfg = fluid.CheckpointConfig(checkpoint_dir=ckpt, max_num_checkpoints=5,
                                 epoch_interval=1, step_interval=1)

    class Crash(Exception):
        pass

    def crash_handler(ev):
        if isinstance(ev, fluid.EndStepEvent) and ev.step == 4:
            raise Crash()

    t1 = fluid.Trainer(train_func=_linear_train_func, optimizer_func=_sgd,
                       place=fluid.CPUPlace(), checkpoint_config=cfg)
    with pytest.raises(Crash):
        t1.train(num_epochs=1, event_handler=crash_handler,
                 reader=_reader(), feed_order=['x', 'y'])
    serials = sorted(int(d.split('_')[1]) for d in os.listdir(ckpt))
    # tear the newest checkpoint's meta
    with open(os.path.join(ckpt, 'checkpoint_%d' % serials[-1],
                           'meta.json'), 'w') as f:
        f.write('{"step": 5, "trainer_')
    cfg2 = fluid.CheckpointConfig(checkpoint_dir=ckpt)
    t2 = fluid.Trainer(train_func=_linear_train_func, optimizer_func=_sgd,
                       place=fluid.CPUPlace(), checkpoint_config=cfg2)
    assert cfg2.load_serial == serials[-2]  # previous intact snapshot


def test_trainer_parallel_path():
    """parallel=True routes through ParallelExecutor (GSPMD dp mesh)."""
    losses = []

    def handler(ev):
        if isinstance(ev, fluid.EndStepEvent):
            losses.append(float(np.asarray(ev.metrics[0])))

    trainer = fluid.Trainer(train_func=_linear_train_func,
                            optimizer_func=_sgd, place=fluid.CPUPlace(),
                            parallel=True)
    trainer.train(num_epochs=10, event_handler=handler, reader=_reader(),
                  feed_order=['x', 'y'])
    assert losses[-1] < losses[0]


def test_trainer_transpiler_fn_hook():
    """transpiler_fn: the Program transpilers from the high-level API —
    a tp=2 trainer matches the plain one and actually shards weights."""
    def train_func():
        x = fluid.layers.data(name='x', shape=[4], dtype='float32')
        y = fluid.layers.data(name='y', shape=[1], dtype='float32')
        h = fluid.layers.fc(input=x, size=8, act='tanh')
        pred = fluid.layers.fc(input=h, size=1, act=None)
        return fluid.layers.mean(
            fluid.layers.square_error_cost(input=pred, label=y))

    def run(hook):
        losses = []

        def handler(ev):
            if isinstance(ev, fluid.EndStepEvent):
                losses.append(float(np.asarray(ev.metrics[0])))

        tr = fluid.Trainer(train_func=train_func,
                           optimizer_func=_sgd, place=fluid.CPUPlace(),
                           transpiler_fn=hook)
        tr.train(num_epochs=10, event_handler=handler,
                 reader=_reader(), feed_order=['x', 'y'])
        sharded = any(
            'tp' in str(v.sharding.spec)
            for v in tr.scope.vars.values()
            if hasattr(v, 'sharding')
            and type(v.sharding).__name__ == 'NamedSharding')
        return losses, sharded

    base, _ = run(None)
    tp, sharded = run(
        lambda p: fluid.TensorParallelTranspiler(tp=2).transpile(p))
    assert sharded   # the hidden fc weight [4, 8] really sharded over tp
    assert base[0] != base[1]
    np.testing.assert_allclose(tp, base, rtol=1e-4, atol=1e-6)


def test_trainer_transpiler_fn_test_clone_and_parallel_guard():
    def train_func():
        x = fluid.layers.data(name='x', shape=[4], dtype='float32')
        y = fluid.layers.data(name='y', shape=[1], dtype='float32')
        h = fluid.layers.fc(input=x, size=8, act='tanh')
        pred = fluid.layers.fc(input=h, size=1, act=None)
        return fluid.layers.mean(
            fluid.layers.square_error_cost(input=pred, label=y))

    hook = lambda p: fluid.TensorParallelTranspiler(tp=2).transpile(p)
    tr = fluid.Trainer(train_func=train_func, optimizer_func=_sgd,
                       place=fluid.CPUPlace(), transpiler_fn=hook)
    tr.train(num_epochs=3, event_handler=lambda ev: None,
             reader=_reader(), feed_order=['x', 'y'])
    # the for_test clone must run on the same mesh as training
    test_loss = tr.test(reader=_reader(seed=1), feed_order=['x', 'y'])
    assert np.isfinite(float(np.asarray(test_loss[0])))

    with pytest.raises(ValueError, match='parallel=True'):
        fluid.Trainer(train_func=train_func, optimizer_func=_sgd,
                      place=fluid.CPUPlace(), parallel=True,
                      transpiler_fn=hook)
