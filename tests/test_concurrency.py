"""CSP channel/select primitives (parity: reference
python/paddle/fluid/tests/notest_concurrency.py + concurrency.py API)."""
import time

import paddle_tpu.fluid as fluid


def test_buffered_channel_send_recv():
    ch = fluid.make_channel(dtype='int64', capacity=10)
    for i in range(5):
        assert fluid.channel_send(ch, i)
    got = [fluid.channel_recv(ch)[0] for _ in range(5)]
    assert got == [0, 1, 2, 3, 4]


def test_channel_close_semantics():
    ch = fluid.make_channel(dtype='int64', capacity=4)
    fluid.channel_send(ch, 7)
    fluid.channel_close(ch)
    v, ok = fluid.channel_recv(ch)
    assert ok and v == 7          # buffered values drain after close
    v, ok = fluid.channel_recv(ch)
    assert not ok and v is None   # then recv reports closed
    assert not fluid.channel_send(ch, 1)


def test_goroutine_pipeline_unbuffered():
    """Producer goroutine -> unbuffered channel -> consumer (the reference's
    fibonacci Go/channel demo shape)."""
    ch = fluid.make_channel(dtype='int64')  # capacity 0: rendezvous
    result = []

    def producer():
        a, b = 0, 1
        for _ in range(10):
            fluid.channel_send(ch, a)
            a, b = b, a + b
        fluid.channel_close(ch)

    with fluid.Go() as g:
        g.run(producer)
        while True:
            v, ok = fluid.channel_recv(ch)
            if not ok:
                break
            result.append(v)
        g.join(timeout=5)
    assert result == [0, 1, 1, 2, 3, 5, 8, 13, 21, 34]


def test_select_recv_and_default():
    a = fluid.make_channel(dtype='int64', capacity=1)
    b = fluid.make_channel(dtype='int64', capacity=1)
    got = {}
    sel = fluid.Select()
    sel.case(a, 'recv', lambda v: got.setdefault('a', v))
    sel.case(b, 'recv', lambda v: got.setdefault('b', v))
    fluid.channel_send(b, 99)
    idx = sel(timeout=5)
    assert idx == 1 and got == {'b': 99}

    empty = fluid.Select()
    empty.case(a, 'recv', lambda v: None)
    empty.default(lambda: got.setdefault('idle', True))
    assert empty() == -1 and got.get('idle')


def test_select_send_case():
    ch = fluid.make_channel(dtype='int64', capacity=1)
    fired = []
    sel = fluid.Select()
    sel.case(ch, 'send', 5, lambda: fired.append(True))
    assert sel(timeout=5) == 0
    assert fired == [True]
    assert fluid.channel_recv(ch) == (5, True)


def test_executor_close_and_reuse():
    import numpy as np
    from paddle_tpu.fluid import layers
    from util import fresh_program
    with fresh_program() as (main, startup):
        x = layers.data(name='x', shape=[4], dtype='float32')
        y = layers.scale(x, scale=3.0)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        xs = np.ones((2, 4), 'float32')
        out1, = exe.run(main, feed={'x': xs}, fetch_list=[y])
        exe.close()
        assert not exe._cache
        # run after close recompiles transparently
        out2, = exe.run(main, feed={'x': xs}, fetch_list=[y])
    np.testing.assert_allclose(np.asarray(out1), np.asarray(out2))
