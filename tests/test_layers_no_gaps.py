"""Execute the public layer functions that no other test or example calls
by name, so every `fluid.layers.__all__` entry runs through the Executor
at least once (SURVEY §4: reference-style per-op smoke coverage)."""
import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers

from util import fresh_program


def _run(build, feed):
    with fresh_program() as (main, startup):
        outs = build()
        outs = [o for o in (outs if isinstance(outs, (list, tuple))
                            else [outs]) if o is not None]
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        res = exe.run(main, feed=feed, fetch_list=list(outs))
    return [np.asarray(r) for r in res]


def test_dynamic_lstmp():
    def build():
        x = fluid.layers.data(name='x', shape=[8], dtype='float32',
                              lod_level=1)
        px = layers.fc(input=x, size=16, num_flatten_dims=2)
        h, c = layers.dynamic_lstmp(px, size=16, proj_size=3)
        return h

    h, = _run(build, {'x': np.random.rand(2, 5, 8).astype('float32')})
    # SeqValue fetch flattens to [total_tokens, proj]
    assert h.shape[-1] == 3 and h.shape[0] == 10
    assert np.isfinite(h).all()


def test_gru_and_lstm_units():
    def build():
        x2 = fluid.layers.data(name='x2', shape=[8], dtype='float32')
        hid = fluid.layers.data(name='hid', shape=[4], dtype='float32')
        gin = layers.fc(input=x2, size=12)
        gh = layers.gru_unit(gin, hid, size=12)[0]
        cell = fluid.layers.data(name='cell', shape=[4], dtype='float32')
        xt = layers.fc(input=x2, size=4)
        lh, lc = layers.lstm_unit(xt, hid, cell)
        return gh, lh, lc

    gh, lh, lc = _run(build, {
        'x2': np.random.rand(2, 8).astype('float32'),
        'hid': np.zeros((2, 4), 'float32'),
        'cell': np.zeros((2, 4), 'float32')})
    assert gh.shape == (2, 4) and lh.shape == (2, 4) and lc.shape == (2, 4)


def test_im2sequence():
    def build():
        img = fluid.layers.data(name='img', shape=[1, 6, 6],
                                dtype='float32')
        return layers.im2sequence(img, filter_size=2, stride=2)

    seq, = _run(build, {'img': np.random.rand(1, 1, 6, 6)
                        .astype('float32')})
    # 3x3 patch grid of 1x2x2 patches, flattened tokens
    assert seq.shape == (9, 4)


def test_lod_reset():
    def build():
        x = fluid.layers.data(name='s', shape=[4], dtype='float32',
                              lod_level=1)
        return layers.lod_reset(x, target_lod=[0, 2, 4])

    src = np.arange(16, dtype='float32').reshape(1, 4, 4)
    out, = _run(build, {'s': src})
    # one 4-token sequence regrouped into two 2-token sequences: the flat
    # token stream is preserved ([tok0 tok1 | tok2 tok3])
    np.testing.assert_allclose(out.reshape(4, 4), src.reshape(4, 4))


def test_roi_pool():
    def build():
        img = fluid.layers.data(name='img', shape=[1, 6, 6],
                                dtype='float32')
        rois = fluid.layers.data(name='rois', shape=[4], dtype='float32')
        return layers.roi_pool(img, rois, pooled_height=2, pooled_width=2,
                               spatial_scale=1.0)

    pooled, = _run(build, {
        'img': np.random.rand(1, 1, 6, 6).astype('float32'),
        'rois': np.array([[0, 0, 3, 3]], 'float32')})
    assert pooled.shape[-2:] == (2, 2) and np.isfinite(pooled).all()


def test_beam_search_step_and_decode():
    B, K, V = 2, 3, 10

    def build():
        pre_ids = fluid.layers.data(name='pids', shape=[1], dtype='int64')
        pre_scores = fluid.layers.data(name='psc', shape=[1],
                                       dtype='float32')
        ids = fluid.layers.data(name='ids', shape=[V], dtype='int64')
        scores = fluid.layers.data(name='sc', shape=[V], dtype='float32')
        sel_ids, sel_sc, parents = layers.beam_search(
            pre_ids, pre_scores, ids, scores, beam_size=K, end_id=0,
            return_parent_idx=True)
        stacked_ids = layers.reshape(sel_ids, shape=[1, -1, K])
        stacked_sc = layers.reshape(sel_sc, shape=[1, -1, K])
        stacked_par = layers.reshape(parents, shape=[1, -1, K])
        sent_ids, sent_sc = layers.beam_search_decode(
            stacked_ids, stacked_sc, beam_size=K, end_id=0,
            parents=stacked_par)
        return sel_ids, sel_sc, sent_ids

    rng = np.random.RandomState(0)
    Bb = B * K
    sel_ids, sel_sc, sent_ids = _run(build, {
        'pids': np.ones((Bb, 1), 'int64'),
        'psc': np.zeros((Bb, 1), 'float32'),
        'ids': np.tile(np.arange(V, dtype='int64'), (Bb, 1)),
        'sc': rng.rand(Bb, V).astype('float32')})
    assert sel_ids.shape == (Bb, 1) and np.isfinite(sel_sc).all()
    assert sent_ids.size


def test_prior_box_anchor_generator_box_coder():
    def build():
        feat = fluid.layers.data(name='feat', shape=[3, 4, 4],
                                 dtype='float32')
        img = fluid.layers.data(name='im', shape=[3, 32, 32],
                                dtype='float32')
        boxes, vars_ = layers.prior_box(feat, img, min_sizes=[4.0])
        anchors, avars = layers.anchor_generator(
            feat, anchor_sizes=[32.0], aspect_ratios=[1.0], stride=[8, 8])
        flat_boxes = layers.reshape(boxes, shape=[-1, 4])
        flat_vars = layers.reshape(vars_, shape=[-1, 4])
        tgt = fluid.layers.data(name='tb', shape=[4], dtype='float32')
        coded = layers.box_coder(
            prior_box=flat_boxes, prior_box_var=flat_vars, target_box=tgt,
            code_type='encode_center_size')
        return boxes, anchors, coded

    boxes, anchors, coded = _run(build, {
        'feat': np.random.rand(1, 3, 4, 4).astype('float32'),
        'im': np.random.rand(1, 3, 32, 32).astype('float32'),
        'tb': np.random.rand(16, 4).astype('float32')})
    assert boxes.shape[-1] == 4 and anchors.shape[-1] == 4
    assert np.isfinite(coded).all()


def test_target_assign():
    def build():
        x = fluid.layers.data(name='x', shape=[5, 4], dtype='float32')
        mi = fluid.layers.data(name='mi', shape=[5], dtype='int32')
        out, w = layers.target_assign(x, mi, mismatch_value=0)
        return out, w

    out, w = _run(build, {
        'x': np.random.rand(1, 5, 4).astype('float32'),
        'mi': np.array([[0, 2, -1, 1, 4]], 'int32')})
    assert out.shape == (1, 5, 4)
    # mismatched row (-1) zero weight
    assert w[0, 2, 0] == 0.0 and w[0, 0, 0] == 1.0


def test_is_empty_and_print():
    def build():
        x = fluid.layers.data(name='x', shape=[3], dtype='float32')
        e = layers.is_empty(x)
        p = layers.Print(x, message='dbg')
        return e, p

    e, p = _run(build, {'x': np.ones((2, 3), 'float32')})
    assert not bool(np.asarray(e).reshape(-1)[0])
    assert p.shape == (2, 3)


def test_parallel_do_shim_raises():
    with pytest.raises(NotImplementedError, match='ParallelExecutor'):
        layers.ParallelDo(None)


def test_reorder_lod_tensor_by_rank_identity():
    with fresh_program():
        x = fluid.layers.data(name='x', shape=[2], dtype='float32',
                              lod_level=1)
        rank = fluid.layers.data(name='r', shape=[1], dtype='int64')
        # padded-dense layout: documented identity
        assert layers.reorder_lod_tensor_by_rank(x, rank) is x


def test_open_files_reader(tmp_path):
    from paddle_tpu.reader import recordio as rio
    path = str(tmp_path / 'f.recordio')
    samples = [(np.full((4,), i, 'float32'),) for i in range(6)]
    rio.write_samples(path, samples)

    with fresh_program():
        reader = layers.open_files([path], shapes=[[-1, 4]],
                                   lod_levels=[0], dtypes=['float32'])
        reader = layers.batch(reader, batch_size=2)
        got = sum(1 for _ in reader._gen())
    assert got == 3  # 6 samples / batch 2


def test_preprocessor_api(tmp_path):
    from paddle_tpu.reader import recordio as rio
    path = str(tmp_path / 'g.recordio')
    rio.write_samples(path, [(np.full((4,), i, 'float32'),)
                             for i in range(4)])
    with fresh_program() as (main, startup):
        reader = layers.open_files([path], shapes=[[-1, 4]],
                                   lod_levels=[0], dtypes=['float32'])
        pre = layers.Preprocessor(reader)
        with pre.block():
            ins = pre.inputs()
            pre.outputs(*[v * 2.0 for v in
                          (ins if isinstance(ins, (list, tuple))
                           else [ins])])
        # the transform ops run host-side, not in the main program
        assert not any(op.type == 'scale' or op.type == 'elementwise_mul'
                       for op in main.global_block().ops)
        vals = [s for s in reader()]
    assert len(vals) == 4
    # x*2 actually applied to the streamed slots
    np.testing.assert_allclose(np.asarray(vals[1][0]).reshape(-1),
                               np.full((4,), 2.0, 'float32'))


def test_append_LARS():
    with fresh_program() as (main, startup):
        x = fluid.layers.data(name='x', shape=[4], dtype='float32')
        pred = fluid.layers.fc(input=x, size=1)
        loss = fluid.layers.mean(pred)
        fluid.backward.append_backward(loss)
        block = main.global_block()
        params = [v for v in block.vars.values()
                  if getattr(v, 'trainable', False)]
        assert params
        # per-layer LARS lr from (param, grad) pairs; grad vars are the
        # @GRAD twins append_backward declared
        pgs = [(p, block.vars[p.name + '@GRAD']) for p in params]
        lrs = layers.append_LARS(pgs, learning_rate=0.1, weight_decay=1e-4)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        outs = exe.run(main,
                       feed={'x': np.random.rand(3, 4).astype('float32')},
                       fetch_list=list(lrs))
    for lr in outs:
        v = float(np.asarray(lr).reshape(-1)[0])
        assert np.isfinite(v) and v >= 0.0


def test_lod_reset_repartition_same_count():
    """Equal sequence COUNT but different partition must still regroup
    the flat token stream (not keep padded rows)."""
    def build():
        x = fluid.layers.data(name='s', shape=[1], dtype='float32',
                              lod_level=1)
        return layers.lod_reset(x, target_lod=[0, 3, 4])

    # two sequences [3, 1]: flat token stream 10,11,12 | 20
    from paddle_tpu.fluid.lod_tensor import create_lod_tensor
    lt = create_lod_tensor(
        np.array([[10.], [11.], [12.], [20.]], 'float32'), [[3, 1]],
        fluid.CPUPlace())
    out, = _run(build, {'s': lt})
    # regrouped [0,3,4]: seq0 = 10,11,12; seq1 = 20
    np.testing.assert_allclose(out.reshape(-1)[:4], [10., 11., 12., 20.])


def test_lod_reset_dense_rows_are_tokens():
    """Dense [N, d] input: rows are tokens; the feature dim survives."""
    def build():
        x = fluid.layers.data(name='d', shape=[3], dtype='float32')
        return layers.lod_reset(x, target_lod=[0, 2, 4])

    src = np.arange(12, dtype='float32').reshape(4, 3)
    out, = _run(build, {'d': src})
    assert out.shape[-1] == 3
    np.testing.assert_allclose(out.reshape(4, 3), src)


def test_lod_reset_rejects_bad_offsets():
    with fresh_program() as (main, startup):
        x = fluid.layers.data(name='s', shape=[1], dtype='float32',
                              lod_level=1)
        out = layers.lod_reset(x, target_lod=[0, 3, 2])
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        with pytest.raises(Exception, match='non-decreasing'):
            exe.run(main, feed={'s': np.zeros((1, 4, 1), 'float32')},
                    fetch_list=[out])


def test_preprocessor_uses_scope_params_and_cleans_on_error(tmp_path):
    from paddle_tpu.reader import recordio as rio
    path = str(tmp_path / 'h.recordio')
    rio.write_samples(path, [(np.ones((4,), 'float32'),)
                             for _ in range(2)])
    with fresh_program() as (main, startup):
        reader = layers.open_files([path], shapes=[[-1, 4]],
                                   lod_levels=[0], dtypes=['float32'])
        pre = layers.Preprocessor(reader)
        with pre.block():
            x, = pre.inputs()
            # fc inside the block: its weight lives in the scope
            pre.outputs(layers.fc(input=x, size=3))
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        vals = [s for s in reader()]
        assert len(vals) == 2 and vals[0][0].shape == (1, 3)

        # a failing block leaves no transform ops behind
        n_ops = len(main.global_block().ops)
        reader2 = layers.open_files([path], shapes=[[-1, 4]],
                                    lod_levels=[0], dtypes=['float32'])
        pre2 = layers.Preprocessor(reader2)
        with pytest.raises(NameError):
            with pre2.block():
                x2, = pre2.inputs()
                y2 = x2 * 2.0
                raise NameError('user bug')
        assert len(main.global_block().ops) == n_ops


def test_uniform_random_hard_shrink_thresholded_relu():
    """The three layers/ops.py stragglers (reference layers/ops.py:77,97,140)
    match numpy semantics."""
    src = np.array([[-2.0, -0.6, -0.3, 0.0, 0.4, 0.8, 1.5]], 'float32')

    def build():
        u = layers.uniform_random(shape=[4, 6], min=2.0, max=3.0)
        x = fluid.layers.data(name='hx', shape=[7], dtype='float32')
        hs = layers.hard_shrink(x, threshold=0.5)
        hs_d = layers.hard_shrink(x)             # default threshold 0.5
        tr = layers.thresholded_relu(x, threshold=0.4)
        tr_d = layers.thresholded_relu(x)        # default threshold 1.0
        return u, hs, hs_d, tr, tr_d

    u, hs, hs_d, tr, tr_d = _run(build, {'hx': src})
    assert u.shape == (4, 6) and (u >= 2.0).all() and (u < 3.0).all()
    np.testing.assert_allclose(hs, np.where(np.abs(src) > 0.5, src, 0.0))
    np.testing.assert_allclose(hs_d, hs)
    np.testing.assert_allclose(tr, np.where(src > 0.4, src, 0.0))
    np.testing.assert_allclose(tr_d, np.where(src > 1.0, src, 0.0))
