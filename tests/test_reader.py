"""paddle.reader decorators (parity: reference
python/paddle/reader/tests/decorator_test.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.reader as reader


def _ints(n, start=0):
    def r():
        for i in range(start, start + n):
            yield i
    return r


def test_map_readers():
    out = list(reader.map_readers(lambda a, b: a + b, _ints(5), _ints(5, 10))())
    assert out == [10, 12, 14, 16, 18]


def test_shuffle_preserves_multiset():
    out = list(reader.shuffle(_ints(100), buf_size=10)())
    assert sorted(out) == list(range(100))
    big = list(reader.shuffle(_ints(100), buf_size=1000)())
    assert sorted(big) == list(range(100))


def test_chain():
    out = list(reader.chain(_ints(3), _ints(3, 10))())
    assert out == [0, 1, 2, 10, 11, 12]


def test_compose_and_alignment():
    c = reader.compose(_ints(3), _ints(3, 10))
    assert list(c()) == [(0, 10), (1, 11), (2, 12)]

    def tup(n):
        def r():
            for i in range(n):
                yield (i, i * 2)
        return r
    c2 = reader.compose(tup(2), _ints(2, 5))
    assert list(c2()) == [(0, 0, 5), (1, 2, 6)]


def test_buffered_yields_everything():
    out = list(reader.buffered(_ints(50), size=4)())
    assert out == list(range(50))


def test_firstn():
    assert list(reader.firstn(_ints(100), 7)()) == list(range(7))
    assert list(reader.firstn(_ints(3), 10)()) == [0, 1, 2]


def test_xmap_readers_unordered_and_ordered():
    got = sorted(reader.xmap_readers(lambda x: x * 2, _ints(40), 4, 8)())
    assert got == [2 * i for i in range(40)]
    ordered = list(reader.xmap_readers(lambda x: x + 1, _ints(20), 3, 8,
                                       order=True)())
    assert ordered == [i + 1 for i in range(20)]


def test_cache_replays_without_source():
    calls = []

    def src():
        calls.append(1)
        for i in range(4):
            yield i
    c = reader.cache(src)
    assert list(c()) == [0, 1, 2, 3]
    assert list(c()) == [0, 1, 2, 3]
    assert len(calls) == 1  # second pass served from cache


def test_fake():
    fake = reader.Fake()
    f = fake(_ints(100), 5)
    assert list(f()) == [0] * 5
    assert list(f()) == [0] * 5  # resets after exhaustion


def test_pipe_reader_plain_and_gzip(tmp_path):
    import gzip
    lines = ['alpha 1', 'beta 2', 'gamma 3']
    p = tmp_path / 'data.txt'
    p.write_text('\n'.join(lines) + '\n')
    pr = reader.PipeReader('cat %s' % p, bufsize=4)  # tiny buffer: splits
    got = [l for l in pr.get_line() if l]
    assert got == lines

    gz = tmp_path / 'data.gz'
    with gzip.open(gz, 'wt') as f:
        f.write('\n'.join(lines) + '\n')
    pr2 = reader.PipeReader('cat %s' % gz, file_type='gzip')
    got2 = [l for l in pr2.get_line() if l]
    assert got2 == lines

    import pytest
    with pytest.raises(TypeError):
        reader.PipeReader(['not', 'a', 'string'])
    with pytest.raises(TypeError):
        reader.PipeReader('cat x', file_type='bz2')


def test_pipe_reader_robustness(tmp_path):
    import pytest
    # multi-byte chars straddling a tiny buffer boundary
    p = tmp_path / 'utf8.txt'
    p.write_text('αβγδ\nεζηθ\n', encoding='utf-8')
    got = [l for l in reader.PipeReader('cat %s' % p, bufsize=3).get_line()
           if l]
    assert got == ['αβγδ', 'εζηθ']
    # quoted path with a space
    sp = tmp_path / 'my file.txt'
    sp.write_text('hello\n')
    got = [l for l in reader.PipeReader('cat "%s"' % sp).get_line() if l]
    assert got == ['hello']
    # failing command raises instead of yielding a truncated dataset
    with pytest.raises(IOError, match='exited with'):
        list(reader.PipeReader('cat %s' % (tmp_path / 'missing')).get_line())
    # abandoning the stream reaps the child
    pr = reader.PipeReader('cat %s' % p)
    gen = pr.get_line()
    next(gen)
    gen.close()
    assert pr.process.poll() is not None  # no zombie left running


def test_batch():
    bs = list(paddle.batch(_ints(7), batch_size=3)())
    assert [len(b) for b in bs] == [3, 3, 1]
    assert bs[2] == [6]


def test_batch_drop_last():
    bs = list(paddle.batch(_ints(7), batch_size=3, drop_last=True)())
    assert [len(b) for b in bs] == [3, 3]


def test_creator_module(tmp_path):
    """paddle.reader.creator parity (reference reader/creator.py)."""
    import numpy as np
    from paddle_tpu.reader import creator
    from paddle_tpu.reader.recordio import RecordIOWriter

    r = creator.np_array(np.arange(6).reshape(3, 2))
    assert [list(x) for x in r()] == [[0, 1], [2, 3], [4, 5]]

    p = str(tmp_path / 't.txt')
    with open(p, 'w') as f:
        f.write('a\nbb\n')
    assert list(creator.text_file(p)()) == ['a', 'bb']

    rp = str(tmp_path / 'r.recordio')
    w = RecordIOWriter(rp)
    w.write(b'x1')
    w.write(b'y22')
    w.close()
    assert list(creator.recordio(rp)()) == [b'x1', b'y22']
    # comma-separated multi-file form
    assert list(creator.recordio('%s,%s' % (rp, rp))()) == \
        [b'x1', b'y22', b'x1', b'y22']


def test_decorator_module_alias():
    """from paddle.reader.decorator import shuffle ports verbatim."""
    import paddle_tpu as paddle
    from paddle_tpu.reader import decorator
    for name in decorator.__all__:
        assert getattr(decorator, name) is getattr(paddle.reader, name)


def test_compose_misaligned_raises():
    import pytest
    c = reader.compose(_ints(3), _ints(5))
    with pytest.raises(reader.ComposeNotAligned):
        list(c())
    # without the check the stream just stops at the shortest reader
    c2 = reader.compose(_ints(3), _ints(5), check_alignment=False)
    assert len(list(c2())) == 3


def test_xmap_ordered_under_jitter():
    """Ordering must hold even when later samples finish mapping first."""
    import time, random as _r
    rng = _r.Random(0)

    def slow_sq(x, _rng=rng):
        time.sleep(_rng.uniform(0, 0.005))
        return x * x

    got = list(reader.xmap_readers(slow_sq, _ints(60), 4, 8, order=True)())
    assert got == [i * i for i in range(60)]


def test_xmap_mapper_exception_propagates():
    import pytest

    def bad(x):
        if x == 7:
            raise RuntimeError('mapper blew up on 7')
        return x

    for order in (False, True):
        with pytest.raises(RuntimeError, match='blew up'):
            list(reader.xmap_readers(bad, _ints(30), 3, 4, order=order)())


def test_source_reader_exception_propagates():
    """Errors in the SOURCE reader (not just the mapper) must surface at
    the consumer instead of truncating the stream to a silent EOF."""
    import pytest

    def broken():
        yield from range(5)
        raise IOError('shard corrupt')

    with pytest.raises(IOError, match='shard corrupt'):
        list(reader.buffered(broken, size=2)())
    for order in (False, True):
        with pytest.raises(IOError, match='shard corrupt'):
            list(reader.xmap_readers(lambda x: x, broken, 2, 4,
                                     order=order)())


# ---------------------------------------------------------------------------
# reader.pipeline.prefetch / bundle (the run_bundle feed pipeline)
# ---------------------------------------------------------------------------

def test_prefetch_worker_exception_propagates():
    """A reader crash must surface in the CONSUMER — the old
    `finally: put(_END)` shape turned it into a silent short epoch."""
    import pytest
    from paddle_tpu.reader.pipeline import prefetch

    def broken():
        yield 1
        yield 2
        raise IOError('reader shard corrupt')

    got = []
    with pytest.raises(IOError, match='shard corrupt'):
        for item in prefetch(lambda: broken(), depth=2)():
            got.append(item)
    assert got == [1, 2]   # everything before the crash was delivered


def test_prefetch_early_close_unblocks_worker():
    """A consumer that stops early must release the worker thread, which
    would otherwise block on q.put forever (depth-1 queue guarantees the
    worker IS blocked mid-put when the consumer walks away)."""
    import threading
    import time
    from paddle_tpu.reader.pipeline import prefetch

    produced = []

    def infinite():
        i = 0
        while True:
            produced.append(i)
            yield i
            i += 1

    before = threading.active_count()
    it = prefetch(lambda: infinite(), depth=1)()
    assert next(it) == 0
    assert next(it) == 1
    it.close()   # GeneratorExit -> stop event + queue drain
    deadline = time.monotonic() + 5.0
    while threading.active_count() > before and time.monotonic() < deadline:
        time.sleep(0.01)
    assert threading.active_count() <= before, \
        'prefetch worker thread still alive after consumer close'
    n_after_close = len(produced)
    time.sleep(0.2)
    assert len(produced) == n_after_close   # worker really stopped


def test_prefetch_transform_runs_in_worker():
    """transform (the device-put staging hook) is applied to every item,
    off the consumer thread."""
    import threading
    from paddle_tpu.reader.pipeline import prefetch

    main = threading.get_ident()
    seen_threads = set()

    def stage(x):
        seen_threads.add(threading.get_ident())
        return x * 10

    got = list(prefetch(lambda: iter(range(5)), depth=2,
                        transform=stage)())
    assert got == [0, 10, 20, 30, 40]
    assert main not in seen_threads


def test_bundle_groups_batches():
    from paddle_tpu.reader.pipeline import bundle
    assert list(bundle(lambda: iter(range(7)), 3)()) \
        == [[0, 1, 2], [3, 4, 5], [6]]
    assert list(bundle(lambda: iter(range(7)), 3, drop_last=True)()) \
        == [[0, 1, 2], [3, 4, 5]]
    assert list(bundle(lambda: iter([]), 3)()) == []
    import pytest
    with pytest.raises(ValueError):
        bundle(lambda: iter(range(3)), 0)
