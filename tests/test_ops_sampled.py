"""NCE / hierarchical sigmoid / beam search numeric + behavioral checks.

Mirrors reference unittests/test_nce.py, test_hsigmoid_op.py,
test_beam_search_op.py, test_beam_search_decode_op.py.
"""
import numpy as np
import jax
import jax.numpy as jnp

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid.lowering import Ctx, SeqValue
from paddle_tpu.fluid.ops_impl import sampled_ops as M

from util import fresh_program

rng = np.random.RandomState(3)


def ctx():
    return Ctx(jax.random.key(0))


def test_nce_trains_down():
    B, D, N = 8, 16, 50
    x = rng.randn(B, D).astype(np.float32)
    lab = rng.randint(0, N, (B, 1)).astype(np.int64)

    def loss(params):
        ins = {'Input': [jnp.asarray(x)], 'Label': [jnp.asarray(lab)],
               'Weight': [params['w']], 'Bias': [params['b']]}
        return jnp.mean(M._nce(ins, {'num_total_classes': N,
                                     'num_neg_samples': 10}, ctx())['Cost'])

    params = {'w': jnp.asarray(rng.randn(N, D).astype(np.float32) * 0.1),
              'b': jnp.zeros((N, 1))}
    l0 = float(loss(params))
    g = jax.grad(loss)(params)
    for _ in range(40):
        g = jax.grad(loss)(params)
        params = jax.tree_util.tree_map(lambda p, gr: p - 0.3 * gr, params, g)
    assert float(loss(params)) < l0


def test_hsigmoid_learns_label():
    """Minimizing hsigmoid must make the tree walk reproduce the label:
    check by computing class probs via the same path logic."""
    B, D, C = 4, 8, 10
    x = rng.randn(B, D).astype(np.float32)
    lab = np.array([1, 5, 7, 3], np.int64)

    def loss(w):
        ins = {'X': [jnp.asarray(x)], 'W': [w],
               'Label': [jnp.asarray(lab)]}
        return jnp.mean(M._hsigmoid(ins, {'num_classes': C}, ctx())['Out'])

    w = jnp.asarray(rng.randn(C - 1, D).astype(np.float32) * 0.1)
    l0 = float(loss(w))
    for _ in range(60):
        w = w - 0.5 * jax.grad(loss)(w)
    lN = float(loss(w))
    assert lN < l0 and lN < 0.1  # near-perfect fit on 4 points


def test_hsigmoid_probs_sum_to_one():
    """Class probabilities implied by the tree must sum to 1."""
    D, C = 6, 7
    x = rng.randn(1, D).astype(np.float32)
    w = rng.randn(C - 1, D).astype(np.float32)
    tot = 0.0
    for c in range(C):
        ins = {'X': [jnp.asarray(x)], 'W': [jnp.asarray(w)],
               'Label': [jnp.asarray(np.array([c], np.int64))]}
        nll = float(M._hsigmoid(ins, {'num_classes': C}, ctx())['Out'][0, 0])
        tot += np.exp(-nll)
    assert abs(tot - 1.0) < 1e-4


def test_beam_search_step():
    # B=1 source, beam=2, V candidates K=3 per beam
    pre_ids = np.array([[4], [5]], np.int64)        # no end yet
    ids = np.array([[10, 11, 12], [20, 21, 22]], np.int64)
    scores = np.array([[0.1, 0.9, 0.3], [0.8, 0.2, 0.7]], np.float32)
    pre_scores = np.array([[0.0], [0.0]], np.float32)
    out = M._beam_search(
        {'pre_ids': [jnp.asarray(pre_ids)], 'pre_scores': [jnp.asarray(pre_scores)],
         'ids': [jnp.asarray(ids)], 'scores': [jnp.asarray(scores)]},
        {'beam_size': 2, 'end_id': 1}, ctx())
    sel = np.asarray(out['selected_ids'])[:, 0]
    par = np.asarray(out['parent_idx'])
    assert list(sel) == [11, 20]                     # top-2 of joint scores
    assert list(par) == [0, 1]


def test_beam_search_finished_beam_carries_score():
    pre_ids = np.array([[1], [5]], np.int64)         # beam 0 finished (end=1)
    pre_scores = np.array([[2.0], [0.0]], np.float32)
    ids = np.array([[10, 11], [20, 21]], np.int64)
    scores = np.array([[9.9, 9.8], [0.5, 0.4]], np.float32)  # would win, but frozen
    out = M._beam_search(
        {'pre_ids': [jnp.asarray(pre_ids)], 'pre_scores': [jnp.asarray(pre_scores)],
         'ids': [jnp.asarray(ids)], 'scores': [jnp.asarray(scores)]},
        {'beam_size': 2, 'end_id': 1}, ctx())
    sel = np.asarray(out['selected_ids'])[:, 0]
    sc = np.asarray(out['selected_scores'])[:, 0]
    assert sel[0] == 1 and abs(sc[0] - 2.0) < 1e-6   # end_id with carried score


def test_beam_search_decode_backtrace():
    # T=3, B=1, beam=2; lineage: final beam0 <- step2 parent0 <- step1 parent1
    ids = np.array([[[7, 8]], [[9, 10]], [[11, 12]]], np.int64)    # [T,1,2]
    parents = np.array([[[0, 1]], [[1, 0]], [[0, 1]]], np.int64)
    scores = np.zeros((3, 1, 2), np.float32)
    scores[-1] = [[5.0, 3.0]]
    out = M._beam_search_decode(
        {'Ids': [jnp.asarray(ids)], 'Scores': [jnp.asarray(scores)],
         'Parents': [jnp.asarray(parents)]}, {}, ctx())
    sent = np.asarray(out['SentenceIds'])            # [1, 2, 3]
    # beam 0 at t2: token 11, parent 0 -> t1 token 9, parent 1 -> t0 token 8
    assert list(sent[0, 0]) == [8, 9, 11]
    # beam 1 at t2: token 12, parent 1 -> t1 token 10, parent 0 -> t0 token 7
    assert list(sent[0, 1]) == [7, 10, 12]
    np.testing.assert_allclose(np.asarray(out['SentenceScores']), [[5.0, 3.0]])


def test_nce_hsigmoid_layers_build_and_run():
    with fresh_program() as (main, startup):
        x = fluid.layers.data('x', shape=[16], dtype='float32')
        y = fluid.layers.data('y', shape=[1], dtype='int64')
        cost_nce = fluid.layers.nce(input=x, label=y, num_total_classes=30,
                                    num_neg_samples=5)
        cost_hs = fluid.layers.hsigmoid(input=x, label=y, num_classes=30)
        total = fluid.layers.mean(cost_nce) + fluid.layers.mean(cost_hs)
        fluid.optimizer.SGD(learning_rate=0.1).minimize(total)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        xs = rng.randn(8, 16).astype(np.float32)
        ys = rng.randint(0, 30, (8, 1)).astype(np.int64)
        v0, = exe.run(main, feed={'x': xs, 'y': ys}, fetch_list=[total])
        for _ in range(20):
            v, = exe.run(main, feed={'x': xs, 'y': ys}, fetch_list=[total])
        assert float(v) < float(v0)


def test_seq2seq_generation():
    """Train the tiny seq2seq to echo the source token, then beam-decode."""
    import paddle_tpu.fluid.core as core
    from paddle_tpu.fluid.lod_tensor import create_lod_tensor
    from paddle_tpu.models import machine_translation as mt
    V = 12
    with fresh_program() as (main, startup):
        avg_cost, feeding = mt.seq_to_seq_net(
            embedding_dim=16, encoder_size=16, decoder_size=16,
            source_dict_dim=V, target_dict_dim=V, is_generating=False)
        fluid.optimizer.Adam(learning_rate=0.01).minimize(avg_cost)
        from paddle_tpu.fluid import unique_name
        infer_prog = fluid.Program()
        with fluid.program_guard(infer_prog, fluid.Program()):
            with unique_name.guard():  # param names line up with training
                sent_ids, sent_scores = mt.seq_to_seq_net(
                    embedding_dim=16, encoder_size=16, decoder_size=16,
                    source_dict_dim=V, target_dict_dim=V, is_generating=True,
                    beam_size=2, max_length=4)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        # task: target = [src_tok, <end>=1]; start token 0
        B = 8
        losses = []
        for it in range(150):
            toks = rng.randint(2, V, (B,)).astype(np.int64)
            src = create_lod_tensor(toks[:, None], [[1] * B], core.CPUPlace())
            trg = create_lod_tensor(
                np.stack([np.zeros(B, np.int64), toks], 1).reshape(-1, 1),
                [[2] * B], core.CPUPlace())
            lab = create_lod_tensor(
                np.stack([toks, np.ones(B, np.int64)], 1).reshape(-1, 1),
                [[2] * B], core.CPUPlace())
            loss, = exe.run(main, feed={'source_sequence': src,
                                        'target_sequence': trg,
                                        'label_sequence': lab},
                            fetch_list=[avg_cost])
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])
        # decode: best beam should emit [src_tok, end, ...]
        toks = np.array([3, 7], np.int64)
        src = create_lod_tensor(toks[:, None], [[1, 1]], core.CPUPlace())
        out_ids, out_scores = exe.run(
            infer_prog, feed={'source_sequence': src},
            fetch_list=[sent_ids, sent_scores])
        assert out_ids.shape == (2, 2, 4)
        assert out_ids[0, 0, 0] == 3 and out_ids[1, 0, 0] == 7
        assert out_ids[0, 0, 1] == 1 and out_ids[1, 0, 1] == 1  # <end>
