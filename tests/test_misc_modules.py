"""Small parity modules: annotations, default_scope_funcs, graphviz,
net_drawer, recordio_writer (reference python/paddle/fluid/<same>.py)."""
import os

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import (annotations, default_scope_funcs, graphviz,
                              net_drawer, recordio_writer)

from util import fresh_program


def test_deprecated_decorator(capsys):
    @annotations.deprecated(since='0.14', instead='new_api')
    def old_api(x):
        return x + 1
    assert old_api(1) == 2
    err = capsys.readouterr().err
    assert 'deprecated' in err and 'new_api' in err
    assert 'Warning' in old_api.__doc__


def test_default_scope_funcs():
    d = default_scope_funcs
    root = d.get_cur_scope()
    d.var('x').set(42)
    assert d.find_var('x').get() == 42
    d.enter_local_scope()
    assert d.get_cur_scope() is not root
    assert d.find_var('x').get() == 42      # falls back to parent
    d.var('y').set(7)
    d.leave_local_scope()
    assert d.get_cur_scope() is root
    assert d.find_var('y') is None          # local var gone with its scope

    seen = []
    d.scoped_function(lambda: seen.append(d.var('tmp').set(1)))
    assert d.find_var('tmp') is None


def test_executor_runs_under_child_scope():
    """Params initialized in a parent scope resolve (and update in place)
    when running under a kid scope — the reference's local-scope pattern."""
    from paddle_tpu.fluid.executor import global_scope
    with fresh_program() as (main, startup):
        x = fluid.layers.data(name='x', shape=[3], dtype='float32')
        y = fluid.layers.data(name='y', shape=[1], dtype='float32')
        pred = fluid.layers.fc(input=x, size=1)
        cost = fluid.layers.mean(
            fluid.layers.square_error_cost(input=pred, label=y))
        fluid.optimizer.SGD(learning_rate=0.5).minimize(cost)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        parent = global_scope()
        wname = [n for n in parent.vars if n.endswith('.w_0')][0]
        w_before = np.asarray(parent.vars[wname]).copy()
        child = parent.new_scope()
        assert wname in child                 # __contains__ chains
        feed = {'x': np.ones((4, 3), 'float32'),
                'y': np.zeros((4, 1), 'float32')}
        exe.run(main, feed=feed, fetch_list=[cost], scope=child)
    # the SGD update landed on the parent-owned param, not a shadow copy
    w_after = np.asarray(parent.vars[wname])
    assert not np.allclose(w_before, w_after)
    assert wname not in child.vars            # no local shadow created


def test_graphviz_graph_builds_dot(tmp_path):
    g = graphviz.Graph('T', rankdir='TB')
    a = g.add_node('A', shape='rect')
    b = g.add_node('B')
    g.add_edge(a, b, label='ab')
    dot = str(g)
    assert 'digraph G' in dot and '->' in dot and 'label="ab"' in dot
    p = str(tmp_path / 'g.dot')
    g.compile(p)
    assert os.path.exists(p)

    gen = graphviz.GraphPreviewGenerator('prev')
    pn = gen.add_param('w', 'float32')
    on = gen.add_op('matmul')
    gen.add_edge(pn, on)
    out = gen(str(tmp_path / 'prev.dot'))
    assert os.path.exists(str(tmp_path / 'prev.dot'))


def test_net_drawer_draws_program(tmp_path):
    with fresh_program() as (main, startup):
        x = fluid.layers.data(name='x', shape=[4], dtype='float32')
        y = fluid.layers.fc(input=x, size=2)
        path = str(tmp_path / 'net.dot')
        net_drawer.draw_graph(startup, main, path=path)
    txt = open(path).read()
    assert 'mul' in txt or 'fc' in txt
    assert 'x' in txt


def test_recordio_writer_roundtrip(tmp_path):
    from paddle_tpu.reader import recordio as rio
    with fresh_program() as (main, startup):
        img = fluid.layers.data(name='img', shape=[4], dtype='float32')
        lbl = fluid.layers.data(name='lbl', shape=[1], dtype='int64')
        feeder = fluid.DataFeeder(feed_list=[img, lbl],
                                  place=fluid.CPUPlace())

        def reader():
            rng = np.random.RandomState(0)
            for i in range(7):
                yield [(rng.rand(4).astype('float32'), [i])]

        path = str(tmp_path / 'data.recordio')
        n = recordio_writer.convert_reader_to_recordio_file(
            path, reader, feeder)
        assert n == 7
        payloads = list(rio.RecordIOReader(path))
        assert len(payloads) == 7
        slots = recordio_writer.unpack_feed_record(payloads[3])
        assert len(slots) == 2
        assert slots[0].shape[-1] == 4
        assert int(np.asarray(slots[1]).reshape(-1)[0]) == 3


def test_recordio_writer_preserves_lod(tmp_path):
    from paddle_tpu.reader import recordio as rio
    with fresh_program() as (main, startup):
        seq = fluid.layers.data(name='seq', shape=[1], dtype='int64',
                                lod_level=1)
        feeder = fluid.DataFeeder(feed_list=[seq], place=fluid.CPUPlace())

        def reader():
            yield [(np.array([[1], [2], [3]], 'int64'),),
                   (np.array([[9]], 'int64'),)]

        path = str(tmp_path / 'seq.recordio')
        n = recordio_writer.convert_reader_to_recordio_file(
            path, reader, feeder)
        assert n == 1
        slot, = recordio_writer.unpack_feed_record(
            next(iter(rio.RecordIOReader(path))))
    # sequence structure survives: flat tokens + per-sample lengths
    assert slot.recursive_sequence_lengths() == [[3, 1]]
    np.testing.assert_array_equal(
        np.asarray(slot.data).reshape(-1), [1, 2, 3, 9])


def test_recordio_writer_multi_files(tmp_path):
    with fresh_program() as (main, startup):
        img = fluid.layers.data(name='img', shape=[2], dtype='float32')
        feeder = fluid.DataFeeder(feed_list=[img], place=fluid.CPUPlace())

        def reader():
            for i in range(5):
                yield [(np.full(2, i, 'float32'),)]

        base = str(tmp_path / 'part.recordio')
        n = recordio_writer.convert_reader_to_recordio_files(
            base, 2, reader, feeder)
        assert n == 5
        files = sorted(os.listdir(str(tmp_path)))
        assert files == ['part-00000.recordio', 'part-00001.recordio',
                         'part-00002.recordio']


def test_layer_function_generator():
    from paddle_tpu.fluid.layers import layer_function_generator as lfg
    import pytest
    relu = lfg.generate_layer_fn('relu')
    add = lfg.generate_layer_fn('elementwise_add')
    with pytest.raises(ValueError):
        lfg.generate_layer_fn('no_such_op_xyz')
    with fresh_program() as (main, startup):
        x = fluid.layers.data(name='x', shape=[4], dtype='float32')
        z = add(relu(x), relu(x))
        # act= must fuse an activation like the reference generator does
        za = add(x, x, act='relu')
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        out, outa = exe.run(
            main, feed={'x': np.array([[-1., 2., -3., 4.]], 'float32')},
            fetch_list=[z.name, za.name])
    np.testing.assert_allclose(out, [[0., 4., 0., 8.]])
    np.testing.assert_allclose(outa, [[0., 4., 0., 8.]])

    @lfg.templatedoc('relu')
    def docfn():
        """${comment} takes ${x_comment} of ${x_type}."""
    assert docfn.__doc__ == 'The relu operator. takes x of Variable.'

    import warnings

    @lfg.deprecated
    def oldfn():
        return 7
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter('always')
        assert oldfn() == 7
        assert any(issubclass(x.category, DeprecationWarning) for x in w)


def test_distribute_transpiler_config():
    # reference-level spelling: importable straight off fluid
    assert fluid.DistributeTranspilerConfig is \
        fluid.transpiler.DistributeTranspilerConfig
    cfg = fluid.transpiler.DistributeTranspilerConfig()
    assert cfg.slice_var_up is True and cfg.min_block_size == 8192
    cfg.slice_var_up = False
    t = fluid.transpiler.DistributeTranspiler(config=cfg)
    with fresh_program() as (main, startup):
        x = fluid.layers.data(name='x', shape=[2], dtype='float32')
        y = fluid.layers.fc(input=x, size=2)
        loss = fluid.layers.mean(y)
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        t.transpile(trainer_id=0, program=main, trainers=2,
                    startup_program=startup)
        assert main._dist_config['shard_optimizer_states'] is False


def test_compat():
    c = paddle.compat
    assert c.to_text(b'abc') == 'abc'
    assert c.to_text(['a', b'b', None]) == ['a', 'b', None]
    # non-string objects pass through unchanged (no repr coercion)
    assert c.to_text([1, b'a']) == [1, 'a']
    assert c.to_bytes([2, 'a']) == [2, b'a']
    s = {b'x', 'y'}
    assert c.to_text(s, inplace=True) is s and s == {'x', 'y'}
    assert c.to_bytes('abc') == b'abc'
    lst = ['a', b'b']
    assert c.to_bytes(lst, inplace=True) is lst and lst == [b'a', b'b']
    # half-away-from-zero, unlike python3's half-to-even
    assert c.round(0.5) == 1.0 and c.round(-0.5) == -1.0
    assert c.round(2.675, 2) == 2.68
    assert c.round(0.0) == 0.0
    assert c.floor_division(7, 2) == 3
    assert c.get_exception_message(ValueError('boom')) == 'boom'
    assert c.long_type is int


def test_version_module():
    import paddle_tpu.version as v
    assert paddle.__version__ == v.full_version
    assert paddle.__git_commit__ == v.commit
    assert v.full_version.startswith('%d.%d.%s' % (v.major, v.minor,
                                                   v.patch))
    assert v.mkl() == 'OFF'
    v.show()  # must not raise
