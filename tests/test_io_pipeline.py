"""layers.io reader pipeline: py_reader / open_recordio_file /
double_buffer / shuffle / batch feeding training (parity: reference
layers/io.py reader-op chain + tests/unittests/test_py_reader*)."""
import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers
from paddle_tpu.reader import recordio as rio

from util import fresh_program


def test_py_reader_feeds_training():
    with fresh_program() as (main, startup):
        reader = layers.py_reader(capacity=8, shapes=[[-1, 4], [-1, 1]],
                                  dtypes=['float32', 'float32'],
                                  name='train_reader')
        x, y = layers.read_file(reader)
        pred = layers.fc(input=x, size=1)
        cost = layers.mean(layers.square_error_cost(input=pred, label=y))
        fluid.optimizer.Adam(learning_rate=0.1).minimize(cost)

        rng = np.random.RandomState(0)
        W = np.array([[1.], [-2.], [3.], [0.5]], 'float32')

        def gen():
            for _ in range(16):
                xs = rng.rand(32, 4).astype('float32')
                yield xs, xs @ W

        reader.decorate_paddle_reader(gen)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        losses = []
        for epoch in range(6):
            reader.start()
            while True:
                try:
                    xs, ys = reader.next()
                except StopIteration:
                    break
                l, = exe.run(main, feed={x.name: xs, y.name: ys},
                             fetch_list=[cost])
                losses.append(float(np.asarray(l).squeeze()))
            reader.reset()
        assert losses[-1] < losses[0] * 0.1, (losses[0], losses[-1])


def test_open_recordio_file_chain(tmp_path):
    # write samples, then read through the full chain:
    # open_recordio_file -> shuffle -> batch -> double_buffer
    path = str(tmp_path / 'train.ptrio')
    rng = np.random.RandomState(1)
    samples = [(rng.rand(4).astype('float32'),
                np.array([i % 3], 'int64')) for i in range(64)]
    rio.write_samples(path, samples)

    with fresh_program() as (main, startup):
        reader = layers.open_recordio_file(
            path, shapes=[[-1, 4], [-1, 1]], lod_levels=[0, 0],
            dtypes=['float32', 'int64'], pass_num=2)
        reader = layers.shuffle(reader, buffer_size=16)
        reader = layers.batch(reader, batch_size=8)
        reader = layers.double_buffer(reader)
        seen = 0
        xs_all = []
        for batch in reader():
            xs = np.stack([s[0] for s in batch])
            assert xs.shape == (8, 4)
            xs_all.append(xs)
            seen += len(batch)
        assert seen == 128  # 64 samples x 2 passes
    # shuffle actually permuted the stream
    flat = np.concatenate(xs_all)[:64]
    orig = np.stack([s[0] for s in samples])
    assert not np.allclose(flat, orig)


def test_double_buffer_preserves_order_and_content():
    def gen():
        for i in range(50):
            yield (np.full((2,), i, 'float32'),)

    buffered = layers.double_buffer(gen)
    got = [int(s[0][0]) for s in buffered()]
    assert got == list(range(50))


def test_random_data_generator_shapes():
    gen = layers.random_data_generator(low=-1.0, high=1.0,
                                       shapes=[[8, 3], [8, 1]],
                                       lod_levels=[0, 0])
    it = gen() if callable(gen) else gen
    sample = next(it() if callable(it) else it)
    assert sample[0].shape == (8, 3) and sample[1].shape == (8, 1)
    assert (np.abs(sample[0]) <= 1.0).all()
