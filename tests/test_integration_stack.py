"""Cross-cutting integration: the round-2 features composed in one flow —
Trainer + moe_mlp layer + amp (bf16) + CheckpointConfig crash-resume.
Each piece has its own unit tests; this guards their interplay."""
import numpy as np

import paddle_tpu.fluid as fluid


def _train_func():
    x = fluid.layers.data(name='x', shape=[16], dtype='float32')
    y = fluid.layers.data(name='y', shape=[1], dtype='float32')
    h = fluid.layers.moe_mlp(x, num_experts=2, hidden_size=16, act='relu',
                             capacity_factor=8.0)
    pred = fluid.layers.fc(input=h, size=1)
    cost = fluid.layers.mean(
        fluid.layers.square_error_cost(input=pred, label=y))
    fluid.amp.decorate_program()
    return cost


def _optimizer_func():
    return fluid.optimizer.Adam(learning_rate=1e-2)


def _reader():
    rng = np.random.RandomState(0)
    X = rng.randn(64, 16).astype('float32')
    W = rng.randn(16, 1).astype('float32')
    for i in range(0, 64, 16):
        yield [(X[j], X[j] @ W) for j in range(i, i + 16)]


def test_trainer_moe_amp_checkpoint_resume(tmp_path):
    ckpt = str(tmp_path / 'ckpt')
    losses = []

    def handler(event):
        if isinstance(event, fluid.EndStepEvent):
            losses.append(float(np.asarray(event.metrics[0]).mean()))

    class _SimulatedCrash(Exception):
        pass

    def crashing_handler(event):
        handler(event)
        # die mid-epoch-8: a real crash, not a graceful stop() (which
        # would rightly clean the checkpoints like the reference)
        if isinstance(event, fluid.EndStepEvent) and len(losses) >= 30:
            raise _SimulatedCrash()

    cfg = fluid.CheckpointConfig(checkpoint_dir=ckpt, epoch_interval=1,
                                 step_interval=1)
    trainer = fluid.Trainer(train_func=_train_func,
                            optimizer_func=_optimizer_func,
                            place=fluid.CPUPlace(), checkpoint_config=cfg)
    # amp genuinely decorates the trainer's program (not a vacuous guard)
    assert fluid.amp.is_amp(trainer.train_program)
    import pytest
    with pytest.raises(_SimulatedCrash):
        trainer.train(num_epochs=10, event_handler=crashing_handler,
                      reader=_reader, feed_order=['x', 'y'])
    first_epoch = float(np.mean(losses[:4]))
    last_epoch = float(np.mean(losses[-4:]))
    assert last_epoch < first_epoch * 0.2, (first_epoch, last_epoch)

    # simulated crash: a NEW Trainer on the same checkpoint dir resumes
    # from the persisted epoch/step instead of restarting
    losses2 = []

    def handler2(event):
        if isinstance(event, fluid.EndStepEvent):
            losses2.append(float(np.asarray(event.metrics[0]).mean()))

    cfg2 = fluid.CheckpointConfig(checkpoint_dir=ckpt, epoch_interval=1,
                                  step_interval=1)
    trainer2 = fluid.Trainer(train_func=_train_func,
                             optimizer_func=_optimizer_func,
                             place=fluid.CPUPlace(),
                             checkpoint_config=cfg2)
    epochs_seen = []

    def handler2_with_epochs(event):
        handler2(event)
        if isinstance(event, fluid.EndStepEvent):
            epochs_seen.append(event.epoch)

    trainer2.train(num_epochs=11, event_handler=handler2_with_epochs,
                   reader=_reader, feed_order=['x', 'y'])
    # resumed training continues from the persisted EPOCH/STEP, not from
    # scratch: crash was at epoch 7 step 1 (30 steps in), so the resumed
    # run starts at epoch 7 and re-runs only steps 2.. of it
    assert losses2, 'resumed run produced no steps'
    assert epochs_seen[0] == 7, epochs_seen[:3]
    assert len(losses2) == (4 - 2) + 4 * (11 - 8), len(losses2)
    # and from the trained state: far below the cold-start first epoch
    resumed_first = float(np.mean(losses2[:4]))
    assert resumed_first < first_epoch * 0.2, (first_epoch, resumed_first)

    # inference through the Trainer's test program matches training state
    t_loss = trainer2.test(reader=_reader, feed_order=['x', 'y'])
    assert np.isfinite(float(np.asarray(t_loss[0]).mean()))
