"""Step-artifact tier (ROADMAP item 5): one compiled-step artifact, four
thin drivers, and the pipeline overlap it unlocks.

Drills:
  * driver equivalence — run / run_bundle(K=1) / StepHandle.step / the
    serving dispatch produce BIT-identical fetches and share ONE
    compiled-step cache entry for the same program (the exact-arithmetic
    feed makes any summation order produce the same bits, so the
    assertion is equality, not allclose);
  * donate-exactly-once — every jitted entry point (step, each bundle K)
    compiles exactly once across repeated calls (the PR 4 "warm twice"
    run_bundle wart: uncommitted first-call state re-specialized the
    executable on call two);
  * double-buffered feeds — Trainer(double_buffer=True) trains
    bit-identically to the inline path while staging input assembly on a
    background thread (trainer.input_stage spans prove where the time
    went);
  * async sharded checkpointing — commits off the step path, emergency
    flush drains-and-commits before exit, and a SIGKILL mid-async-save
    never leaves a latest-looking torn serial (subprocess drill);
  * AOT warm signatures — an exported blob warms a COLD process to zero
    online compiles (aot_hit classified in cache_stats), and
    step_artifact.aot_check types a stale blob statically
    (tools/program_lint.py --aot).
"""
import json
import os
import re
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu import obs
from paddle_tpu.fluid import step_artifact
from paddle_tpu.fluid.executor import StepArtifact, _CompiledStep
from paddle_tpu.obs import report as obs_report

pytestmark = pytest.mark.artifact

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def obs_events(tmp_path):
    obs.enable(str(tmp_path / 'obs'))

    def read(name=None):
        path = obs.run_log_path()
        if path is None:
            return []
        events, errors = obs_report.load_events(path)
        assert errors == [], errors
        return [e for e in events if name is None or e['name'] == name]

    try:
        yield read
    finally:
        obs._reset()


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

def _forward_program():
    """Inference-shaped program whose arithmetic is EXACT in float32:
    weights and feeds are small powers of two, so every product and
    every partial sum is representable — any op ordering (run vs scan vs
    serving batch) must produce identical bits."""
    from paddle_tpu.fluid import unique_name
    prog, start = fluid.Program(), fluid.Program()
    with unique_name.guard():
        with fluid.program_guard(prog, start):
            x = fluid.layers.data(name='x', shape=[8], dtype='float32')
            out = fluid.layers.fc(input=x, size=1, act=None,
                                  param_attr=fluid.ParamAttr(name='w'),
                                  bias_attr=fluid.ParamAttr(name='b'))
    return prog, start, out


def _exact_feed(batch=8):
    rng = np.random.RandomState(0)
    x = 2.0 ** rng.randint(-2, 2, size=(batch, 8))
    return {'x': x.astype('float32')}


def _init_exact_params(scope):
    w = (2.0 ** (-(np.arange(8) % 4))).astype('float32').reshape(8, 1)
    scope.vars['w'] = w
    scope.vars['b'] = np.asarray([0.125], 'float32')


def _regression(lr=0.1):
    from paddle_tpu.fluid import unique_name
    prog, start = fluid.Program(), fluid.Program()
    with unique_name.guard():
        with fluid.program_guard(prog, start):
            x = fluid.layers.data(name='x', shape=[13], dtype='float32')
            y = fluid.layers.data(name='y', shape=[1], dtype='float32')
            pred = fluid.layers.fc(input=x, size=1)
            loss = fluid.layers.mean(
                fluid.layers.square_error_cost(input=pred, label=y))
            fluid.optimizer.SGD(learning_rate=lr).minimize(loss)
    return prog, start, loss


def _feeds(n, seed=0, batch=16):
    rng = np.random.RandomState(seed)
    return [{'x': rng.rand(batch, 13).astype('float32'),
             'y': rng.rand(batch, 1).astype('float32')} for _ in range(n)]


# ---------------------------------------------------------------------------
# one artifact, four drivers
# ---------------------------------------------------------------------------

def test_four_drivers_share_one_artifact_and_match_bitwise():
    """run / run_bundle(K=1) / StepHandle.step / serving dispatch: ONE
    compiled-step cache entry, one shared key, bit-identical fetches."""
    from paddle_tpu import serving

    prog, _start, out = _forward_program()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    feed = _exact_feed()
    keys = []

    with fluid.scope_guard(scope):
        _init_exact_params(scope)

        r_run = np.asarray(
            exe.run(prog, feed=feed, fetch_list=[out])[0])
        keys.append(exe._last_cache_lookup['key'])
        assert exe._last_cache_lookup['outcome'] == 'miss'

        r_bundle = np.asarray(
            exe.run_bundle(prog, feeds=[feed], fetch_list=[out])[0])[0]
        keys.append(exe._last_cache_lookup['key'])
        assert exe._last_cache_lookup['outcome'] == 'hit'

        handle = exe.acquire_step(prog, feed=feed, fetch_list=[out])
        keys.append(exe._last_cache_lookup['key'])
        r_handle = np.asarray(handle.step(
            {'x': feed['x']})[0])

        class _Model(object):
            feed_names = ['x']
            fetch_names = [out.name]

            def run(self, f):
                with fluid.scope_guard(scope):
                    r = exe.run(prog, feed=f, fetch_list=[out])
                keys.append(exe._last_cache_lookup['key'])
                return r

        eng = serving.ServingEngine(
            _Model(), serving.ServingConfig(max_batch_size=8, buckets=[8]))
        try:
            r_serve = np.asarray(eng.predict(feed)[0])
        finally:
            eng.shutdown()

    # bit-identical across every driver (exact arithmetic: no tolerance)
    np.testing.assert_array_equal(r_run, r_bundle)
    np.testing.assert_array_equal(r_run, r_handle)
    np.testing.assert_array_equal(r_run, r_serve)
    # ONE artifact: a single cache entry, one miss, every driver on the
    # same key
    stats = exe.cache_stats
    assert stats['entries'] == 1, stats
    assert stats['misses'] == 1, stats
    assert len(set(keys)) == 1, keys
    # and the artifact enumerates both compiled entry points
    art = list(exe._cache.values())[0]
    assert isinstance(art, StepArtifact)
    assert _CompiledStep is StepArtifact  # migration alias holds
    assert ('step',) in art.signatures()
    assert ('bundle', 1) in art.signatures()


def test_each_signature_compiles_exactly_once():
    """The warm-twice regression drill: repeated run() and run_bundle()
    calls never re-specialize a jitted entry — each signature holds ONE
    executable (pin_state commits the donated state before the first
    call, so call one and call N share an argument signature)."""
    prog, start, loss = _regression()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    feeds = _feeds(12, seed=3)
    with fluid.scope_guard(scope):
        exe.run(start)
        for f in feeds[:3]:
            exe.run(prog, feed=f, fetch_list=[loss])
        for i in range(3):
            exe.run_bundle(prog, feeds=feeds[3 + 3 * i:6 + 3 * i],
                           fetch_list=[loss])
    art = [a for a in exe._cache.values() if 3 in a._bundles][0]
    if not hasattr(art._jitted, '_cache_size'):
        pytest.skip('jax jit wrapper lacks _cache_size introspection')
    assert art._jitted._cache_size() == 1
    assert art._bundles[3]._cache_size() == 1


def test_pin_state_commits_scope_arrays_once():
    prog, start, loss = _regression()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(start)
        # fresh startup outputs are uncommitted; the first _prepare pins
        # them (committed device arrays) and syncs the scope
        exe.run(prog, feed=_feeds(1)[0], fetch_list=[loss])
        art = [a for a in exe._cache.values() if a.ad_idx is not None][0]
        persist = {n: scope._chain_get(n) for n in art.persist_in}
        assert art.pin_state(persist, exe._device()) == []


def test_step_handle_state_dict_seam():
    prog, start, loss = _regression()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(start)
        h = exe.acquire_step(prog, feed=_feeds(1)[0], fetch_list=[loss])
        sd = h.state_dict()
    assert set(sd) == set(h._compiled.state_names)
    for n, v in sd.items():
        np.testing.assert_array_equal(np.asarray(v),
                                      np.asarray(scope._chain_get(n)))


# ---------------------------------------------------------------------------
# double-buffered feeds
# ---------------------------------------------------------------------------

_TRAIN_W = np.array([[1.5], [-2.0], [0.5], [3.0]], 'float32')


def _train_func():
    x = fluid.layers.data(name='x', shape=[4], dtype='float32')
    y = fluid.layers.data(name='y', shape=[1], dtype='float32')
    pred = fluid.layers.fc(input=x, size=1,
                           param_attr=fluid.ParamAttr(name='w'),
                           bias_attr=fluid.ParamAttr(name='b'))
    return fluid.layers.mean(
        fluid.layers.square_error_cost(input=pred, label=y))


def _train_reader(n=48, batch=8, seed=0):
    def r():
        rng = np.random.RandomState(seed)
        for _ in range(n // batch):
            xs = rng.rand(batch, 4).astype('float32')
            ys = xs @ _TRAIN_W
            yield [(xs[i], ys[i]) for i in range(batch)]
    return r


def _sgd():
    return fluid.optimizer.SGD(learning_rate=0.1)


def _run_trainer(double_buffer, bundle_steps=1, epochs=3):
    losses = []

    def handler(ev):
        if isinstance(ev, fluid.EndStepEvent) and ev.metrics:
            losses.append(float(np.asarray(ev.metrics[0]).reshape(-1)[0]))

    tr = fluid.Trainer(train_func=_train_func, optimizer_func=_sgd,
                       place=fluid.CPUPlace(), double_buffer=double_buffer,
                       bundle_steps=bundle_steps)
    tr.train(epochs, handler, reader=_train_reader(),
             feed_order=['x', 'y'])
    w = np.asarray(tr.scope.vars['w']).copy()
    return losses, w, tr


def test_trainer_double_buffer_bit_identical(obs_events):
    """Staging moves WHERE the feed work happens, never what is fed:
    losses and parameters are bit-identical with double_buffer on/off,
    and the on-leg records staged trainer.input_stage spans."""
    l_off, w_off, tr_off = _run_trainer(False)
    l_on, w_on, tr_on = _run_trainer(True)
    assert l_off == l_on
    np.testing.assert_array_equal(w_off, w_on)
    assert tr_on.batches_fed == tr_off.batches_fed > 0
    spans = obs_events('trainer.input_stage')
    assert any(s['fields'].get('staged') for s in spans)
    assert any(not s['fields'].get('staged') for s in spans)


def test_trainer_double_buffer_bundled_loop():
    l_off, w_off, _ = _run_trainer(False, bundle_steps=3)
    l_on, w_on, _ = _run_trainer(True, bundle_steps=3)
    assert l_off == l_on
    np.testing.assert_array_equal(w_off, w_on)


# ---------------------------------------------------------------------------
# async sharded checkpointing
# ---------------------------------------------------------------------------

def _mesh_hook(axes):
    return lambda p: p.set_mesh(axes)


def test_async_checkpoint_commits_and_resumes_exact_step(tmp_path,
                                                         obs_events):
    """CheckpointConfig(async_save=True): periodic saves commit from the
    writer thread (checkpoint.snapshot + committed events), training
    stats match the sync path, and a successor Trainer resumes at the
    exact next step."""
    ckpt = str(tmp_path / 'ck')
    cfg = fluid.CheckpointConfig(checkpoint_dir=ckpt, step_interval=2,
                                 max_num_checkpoints=3, async_save=True)
    steps = []

    def handler(ev):
        if isinstance(ev, fluid.EndStepEvent):
            steps.append((ev.epoch, ev.step))
            if ev.epoch == 1 and ev.step == 3:
                tr.request_preemption()

    tr = fluid.Trainer(train_func=_train_func, optimizer_func=_sgd,
                       place=fluid.CPUPlace(), checkpoint_config=cfg,
                       transpiler_fn=_mesh_hook({'dp': 8}))
    tr.train(3, handler, reader=_train_reader(), feed_order=['x', 'y'])
    assert tr.preempted
    assert tr._async_ckpt is None   # drained before train() returned
    # the emergency flush committed SYNCHRONOUSLY for the exact step
    from paddle_tpu.utils import checkpoint as ck
    arrays, meta = ck.load_latest_verified(ckpt)
    args = meta['extra']['trainer_args']
    assert (args['epoch_id'], args['step_id']) == (1, 3)
    assert args.get('preempted') is True
    # no staging leftovers pretending to be checkpoints
    assert not [d for d in os.listdir(ckpt) if d.endswith('.tmp')]
    # snapshots happened (async periodic path) and commits were observed
    assert obs_events('checkpoint.snapshot')
    assert obs_events('checkpoint.committed')

    # successor resumes at the exact next step
    seen = []

    def handler2(ev):
        if isinstance(ev, fluid.BeginStepEvent):
            seen.append((ev.epoch, ev.step))

    cfg2 = fluid.CheckpointConfig(checkpoint_dir=ckpt, step_interval=2,
                                  max_num_checkpoints=3, async_save=True)
    tr2 = fluid.Trainer(train_func=_train_func, optimizer_func=_sgd,
                        place=fluid.CPUPlace(), checkpoint_config=cfg2,
                        transpiler_fn=_mesh_hook({'dp': 8}))
    tr2.train(2, handler2, reader=_train_reader(), feed_order=['x', 'y'])
    assert seen[0] == (1, 4), seen[:3]


def test_async_checkpoint_matches_sync_trajectory(tmp_path):
    """async_save changes WHEN the files are written, never the training
    arithmetic: identical loss trajectories and final params."""
    def leg(async_save, sub):
        ckpt = str(tmp_path / sub)
        cfg = fluid.CheckpointConfig(checkpoint_dir=ckpt, step_interval=3,
                                     max_num_checkpoints=2,
                                     async_save=async_save)
        losses = []

        def handler(ev):
            if isinstance(ev, fluid.EndStepEvent) and ev.metrics:
                losses.append(float(np.asarray(
                    ev.metrics[0]).reshape(-1)[0]))

        tr = fluid.Trainer(train_func=_train_func, optimizer_func=_sgd,
                           place=fluid.CPUPlace(), checkpoint_config=cfg,
                           transpiler_fn=_mesh_hook({'dp': 8}))
        tr.train(2, handler, reader=_train_reader(),
                 feed_order=['x', 'y'])
        return losses, np.asarray(tr.scope.vars['w']).copy()

    l_sync, w_sync = leg(False, 'sync')
    l_async, w_async = leg(True, 'async')
    assert l_sync == l_async
    np.testing.assert_array_equal(w_sync, w_async)


_KILL_CHILD = r"""
import os, sys, time
sys.path.insert(0, %(repo)r)
import numpy as np
from paddle_tpu.utils import checkpoint as shck

base = sys.argv[1]
arrays = {'w%%d' %% i: np.full((64, 64), float(i), 'float32')
          for i in range(4)}
# serial 1: committed cleanly — the fallback the torn serial must not mask
shck.save_sharded(os.path.join(base, 'sharded_1'), arrays, step=1)

# slow every shard write down so the parent can SIGKILL mid-save
_orig = shck._write_shard
def slow(fpath, data, sh):
    time.sleep(0.4)
    return _orig(fpath, data, sh)
shck._write_shard = slow

h = shck.save_sharded_async(os.path.join(base, 'sharded_2'),
                            arrays, step=2)
print('ASYNC_STARTED', flush=True)
h.wait()
print('NEVER_REACHED', flush=True)
time.sleep(60)
"""


def test_sigkill_mid_async_save_never_leaves_torn_serial(tmp_path):
    """The PR 10 torn-write drill re-run against the ASYNC path: SIGKILL
    while the background writer is mid-save leaves only the staging dir,
    which restore skips (loudly) in favor of the previous committed
    serial."""
    base = str(tmp_path / 'ck')
    os.makedirs(base)
    child = subprocess.Popen(
        [sys.executable, '-c', _KILL_CHILD % {'repo': _REPO}, base],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=dict(os.environ, JAX_PLATFORMS='cpu'))
    try:
        line = child.stdout.readline()
        assert 'ASYNC_STARTED' in line, line
        # wait until the writer has staged at least one shard file, so
        # the kill lands genuinely mid-save
        staging = os.path.join(base, 'sharded_2.tmp')
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if os.path.isdir(staging) and os.listdir(staging):
                break
            time.sleep(0.05)
        else:
            pytest.fail('async writer never staged a shard')
        os.kill(child.pid, signal.SIGKILL)
        child.wait(timeout=30)
    finally:
        if child.poll() is None:
            child.kill()
    # the torn save is only ever the .tmp staging dir; serial 2 must not
    # exist committed, and restore falls back to serial 1 with a warning
    assert not os.path.isdir(os.path.join(base, 'sharded_2'))
    from paddle_tpu.utils import checkpoint as ck
    with pytest.warns(RuntimeWarning, match='uncommitted'):
        arrays, meta = ck.load_latest_verified(base)
    assert meta['step'] == 1
    np.testing.assert_array_equal(np.asarray(arrays['w3']),
                                  np.full((64, 64), 3.0, 'float32'))


def test_overlapping_async_saves_to_one_dir_rejected(tmp_path):
    from paddle_tpu.utils import checkpoint as shck
    arrays = {'w': np.zeros((256, 256), 'float32')}
    dest = str(tmp_path / 'sharded_1')
    h = shck.save_sharded_async(dest, arrays, step=1)
    try:
        if not h.done():
            with pytest.raises(RuntimeError, match='in flight'):
                shck.save_sharded_async(dest, arrays, step=1)
    finally:
        h.wait()
    # after the writer finishes, a new save to the same dir is legal
    h2 = shck.save_sharded_async(dest, arrays, step=2)
    h2.wait()


# ---------------------------------------------------------------------------
# AOT warm signatures
# ---------------------------------------------------------------------------

_AOT_CHILD = r"""
import json, os, sys
sys.path.insert(0, %(repo)r)
import numpy as np
import paddle_tpu.fluid as fluid

mode, aot_dir = sys.argv[1], sys.argv[2]
prog, start = fluid.Program(), fluid.Program()
with fluid.program_guard(prog, start):
    x = fluid.layers.data(name='x', shape=[13], dtype='float32')
    y = fluid.layers.data(name='y', shape=[1], dtype='float32')
    pred = fluid.layers.fc(input=x, size=1)
    loss = fluid.layers.mean(
        fluid.layers.square_error_cost(input=pred, label=y))
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
exe = fluid.Executor(fluid.CPUPlace())
if mode == 'import':
    exe.load_warm_signatures(aot_dir)
exe.run(start)
rng = np.random.RandomState(0)
feed = {'x': rng.rand(16, 13).astype('float32'),
        'y': rng.rand(16, 1).astype('float32')}
exe.run(prog, feed=feed, fetch_list=[loss])
exe.run_bundle(prog, feeds=[feed, feed], fetch_list=[loss])
if mode == 'export':
    exe.export_warm_signatures(aot_dir)
if mode == 'import':
    # a bundle length the blob never warmed: must compile as an
    # ORDINARY first call, not flag the blob stale
    exe.run_bundle(prog, feeds=[feed, feed, feed], fetch_list=[loss])
print('STATS=' + json.dumps(exe.cache_stats))
"""


def _run_aot_child(mode, aot_dir, cache_dir, obs_dir):
    env = dict(os.environ, JAX_PLATFORMS='cpu',
               PADDLE_TPU_OBS_DIR=str(obs_dir))
    env.pop('PADDLE_TPU_OBS_RUN_FILE', None)
    if cache_dir is not None:
        env['PADDLE_TPU_COMPILE_CACHE'] = str(cache_dir)
    else:
        env.pop('PADDLE_TPU_COMPILE_CACHE', None)
    r = subprocess.run(
        [sys.executable, '-c', _AOT_CHILD % {'repo': _REPO}, mode,
         str(aot_dir)],
        capture_output=True, text=True, timeout=300, env=env, cwd=_REPO)
    assert r.returncode == 0, r.stderr[-3000:]
    stats = json.loads([ln for ln in r.stdout.splitlines()
                        if ln.startswith('STATS=')][0][len('STATS='):])
    logs = [os.path.join(str(obs_dir), f)
            for f in os.listdir(str(obs_dir))]
    assert len(logs) == 1
    events, errors = obs_report.load_events(logs[0])
    assert errors == []
    return stats, events


def test_aot_export_warms_cold_process_to_zero_compiles(tmp_path):
    """The cold-replica contract: a fresh process (no pre-wired compile
    cache at all) that loads the exported blob reaches its first step
    AND first bundle with ZERO executor.compile spans — every first call
    classifies aot_hit."""
    aot = tmp_path / 'aot'
    stats1, ev1 = _run_aot_child('export', aot, tmp_path / 'cc',
                                 tmp_path / 'obs1')
    compiles1 = [e for e in ev1 if e['name'] == 'executor.compile']
    assert compiles1 and stats1['aot_hits'] == 0
    man = step_artifact.read_aot(str(aot))
    assert man['signatures'] and man['cache_entries']
    # startup + train artifacts, the train one with its K=2 bundle
    assert any(s['bundles'] == [2] for s in man['signatures'])

    stats2, ev2 = _run_aot_child('import', aot, None, tmp_path / 'obs2')
    compiles2 = [e for e in ev2 if e['name'] == 'executor.compile']
    # the ONLY online compile is the deliberately un-warmed K=3 bundle —
    # and it classifies as an ordinary compile, never as a stale blob
    assert [e['fields'].get('bundle_steps') for e in compiles2] == [3], \
        compiles2
    assert stats2['online_compiles'] == 1
    assert stats2['aot_hits'] == len(compiles1)
    assert stats2['aot_stale'] == 0
    hits = [e for e in ev2 if e['name'] == 'executor.compile.aot_hit']
    assert len(hits) == len(compiles1)
    assert [e for e in ev2 if e['name'] == 'executor.aot.loaded']
    # the step-artifact obs section renders the split
    text = obs_report.summarize(ev2)
    assert '-- step artifact --' in text
    assert 'AOT-hit' in text


def test_aot_check_types_stale_blobs():
    """step_artifact.aot_check (program_lint --aot): a fresh manifest is
    clean against its program; a drifted program / tampered manifest is
    a typed problem list, not a silent online recompile."""
    prog, start, loss = _regression()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(start)
        exe.run(prog, feed=_feeds(1)[0], fetch_list=[loss])
    man = step_artifact.aot_manifest(exe)
    # drop the startup artifact: check the TRAIN signature set
    man['signatures'] = [s for s in man['signatures']
                         if s['fetches'] == [loss.name]]
    assert step_artifact.aot_check(man, prog) == []

    # a structurally different program (extra layer) fingerprints apart
    from paddle_tpu.fluid import unique_name
    other, o_start = fluid.Program(), fluid.Program()
    with unique_name.guard():
        with fluid.program_guard(other, o_start):
            x = fluid.layers.data(name='x', shape=[13], dtype='float32')
            y = fluid.layers.data(name='y', shape=[1], dtype='float32')
            h = fluid.layers.fc(input=x, size=4)
            pred = fluid.layers.fc(input=h, size=1)
            o_loss = fluid.layers.mean(
                fluid.layers.square_error_cost(input=pred, label=y))
            fluid.optimizer.SGD(learning_rate=0.1).minimize(o_loss)
    probs = step_artifact.aot_check(man, other)
    assert any('no exported signature matches' in p for p in probs)

    bad = json.loads(json.dumps(man))
    bad['signatures'][0]['feeds'][0]['dtype'] = 'int32'
    bad['signatures'][0]['donates'].append('ghost')
    probs = step_artifact.aot_check(bad, prog)
    assert any('recorded dtype' in p for p in probs)
    assert any('ghost' in p for p in probs)

    alien = dict(man, jax='0.0.1')
    probs = step_artifact.aot_check(alien, prog)
    assert any('jax' in p for p in probs)


def test_stable_signature_ignores_process_identity():
    """Two same-shaped builds in one process get the same stable
    signature (it must survive restarts, unlike the _uid-keyed cache
    key)."""
    sigs = []
    for _ in range(2):
        prog, start, loss = _regression()
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(start)
            exe.run(prog, feed=_feeds(1)[0], fetch_list=[loss])
        art = [a for a in exe._cache.values()
               if a.fetch_names == [loss.name]][0]
        sigs.append(step_artifact.stable_signature(art))
    assert sigs[0] == sigs[1]


# ---------------------------------------------------------------------------
# obs report section
# ---------------------------------------------------------------------------

def test_obs_report_step_artifact_section_renders():
    def ev(name, kind='event', dur=None, **fields):
        rec = {'ts': 1.0, 'name': name, 'kind': kind, 'fields': fields}
        if kind == 'span':
            rec['dur_s'] = dur if dur is not None else 0.01
        return rec

    events = [
        ev('executor.artifact', key='abc', feeds=2, fetches=1,
           persistables=3, donates=3, mesh=False),
        ev('executor.compile', kind='span', dur=0.5, key='abc'),
        ev('executor.compile.aot_hit', key='abc', seconds=0.02),
        ev('executor.aot.loaded', signatures=2,
           cache_entries_imported=3),
        ev('trainer.step', kind='span', dur=0.1),
        ev('trainer.input_stage', kind='span', dur=0.001, staged=True),
        ev('checkpoint.snapshot', kind='span', dur=0.004, step=1,
           arrays=3),
        ev('checkpoint.commit', kind='span', dur=0.002, step=1),
        ev('trainer.checkpoint.async_wait', kind='span', dur=0.0005,
           ready=True),
    ]
    text = obs_report.summarize(events)
    assert '-- step artifact --' in text
    assert '1 artifact(s) built' in text
    assert '1 compiled online' in text and '1 AOT-hit' in text
    assert 'AOT blob loaded' in text
    assert 'input stage' in text and 'overlap ratio' in text
    assert 'async checkpoint snapshots' in text
    assert 'async-save waits' in text
