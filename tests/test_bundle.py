"""Pipelined training hot loop (ISSUE 4): K-step bundling via
Executor.run_bundle / Trainer(bundle_steps=K), the async fetch window
(run(sync='async') FetchHandles + Trainer in-flight window), and the
persistent XLA compilation cache (PADDLE_TPU_COMPILE_CACHE).

Equivalence contract proved here:
  - K=1 vs K=4 bundles reach BIT-IDENTICAL parameters (the scan body
    compiles the same regardless of trip count);
  - per-step RNG (dropout masks) is bit-identical between K unbundled
    run() calls and one K-bundle (same seed integers, same keys);
  - the anomaly guard skips/rolls back PER INNER STEP inside a bundle
    exactly as it does unbundled, and escalation still fires;
  - a second process over the same PADDLE_TPU_COMPILE_CACHE dir records
    ZERO executor.compile spans for already-cached keys.
"""
import gc
import json
import os
import subprocess
import sys
import warnings

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu import obs
from paddle_tpu.fluid.executor import FetchHandle
from paddle_tpu.obs import report as obs_report
from paddle_tpu.utils.faults import FaultInjector

pytestmark = pytest.mark.bundle


@pytest.fixture
def obs_events(tmp_path):
    obs.enable(str(tmp_path / 'obs'))

    def read(name=None):
        path = obs.run_log_path()
        if path is None:
            return []
        events, errors = obs_report.load_events(path)
        assert errors == [], errors
        return [e for e in events if name is None or e['name'] == name]

    try:
        yield read
    finally:
        obs._reset()


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

def _regression(lr=0.1, guard=False, max_skips=None):
    """fit_a_line-shaped net: fc -> square_error -> mean -> SGD. Built
    under a fresh unique_name guard so two builds name vars identically
    (the cross-executor equivalence comparisons key on names)."""
    from paddle_tpu.fluid import unique_name
    prog, start = fluid.Program(), fluid.Program()
    with unique_name.guard():
        with fluid.program_guard(prog, start):
            x = fluid.layers.data(name='x', shape=[13], dtype='float32')
            y = fluid.layers.data(name='y', shape=[1], dtype='float32')
            pred = fluid.layers.fc(input=x, size=1)
            loss = fluid.layers.mean(
                fluid.layers.square_error_cost(input=pred, label=y))
            fluid.optimizer.SGD(learning_rate=lr).minimize(loss)
    if guard:
        fluid.anomaly_guard(prog, max_consecutive_skips=max_skips)
    w_names = sorted(v.name for v in prog.list_vars()
                     if v.persistable and 'fc' in v.name)
    return prog, start, loss, w_names


def _feeds(n, seed=0, batch=16):
    rng = np.random.RandomState(seed)
    return [{'x': rng.rand(batch, 13).astype('float32'),
             'y': rng.rand(batch, 1).astype('float32')} for _ in range(n)]


def _train_bundled(feeds, K, guard=False, max_skips=None):
    """Fresh program/executor/scope; run all feeds in K-bundles. Returns
    (per-step losses, {w_name: value}, exe)."""
    prog, start, loss, w_names = _regression(guard=guard,
                                             max_skips=max_skips)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    losses = []
    with fluid.scope_guard(scope):
        exe.run(start)
        for i in range(0, len(feeds), K):
            out = exe.run_bundle(prog, feeds=feeds[i:i + K],
                                 fetch_list=[loss])
            losses.extend(np.asarray(out[0]).reshape(-1).tolist())
        ws = {n: np.asarray(scope.vars[n]).copy() for n in w_names}
    return losses, ws, exe


# ---------------------------------------------------------------------------
# bundled-vs-unbundled equivalence
# ---------------------------------------------------------------------------

def test_bundle_k1_vs_k4_params_bit_identical():
    """The acceptance equivalence: identical parameters after N steps
    with K=1 vs K=4 — bit-exact, because both are the SAME scan body."""
    feeds = _feeds(8)
    l1, w1, _ = _train_bundled(feeds, 1)
    l4, w4, _ = _train_bundled(feeds, 4)
    assert l1 == l4
    assert sorted(w1) == sorted(w4)
    for n in w1:
        np.testing.assert_array_equal(w1[n], w4[n])


def test_bundle_matches_unbundled_run_trajectory():
    """One bundle vs K run() calls: same data, same seeds -> the same
    training trajectory. run() and the scan are DIFFERENT XLA modules, so
    individual reductions may round one ulp apart (docs/perf.md) — the
    assertion is allclose-tight, with the bit-exact guarantee covered by
    the K-vs-K test above."""
    feeds = _feeds(8)
    prog, start, loss, w_names = _regression()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(start)
        lu = [float(np.asarray(exe.run(prog, feed=f, fetch_list=[loss])[0])
                    .reshape(-1)[0]) for f in feeds]
        wu = {n: np.asarray(scope.vars[n]).copy() for n in w_names}
    lb, wb, _ = _train_bundled(feeds, 4)
    np.testing.assert_allclose(lu, lb, rtol=1e-6, atol=1e-7)
    for n in w_names:
        np.testing.assert_allclose(wu[n], wb[n], rtol=1e-5, atol=1e-7)


def test_bundle_fetches_stacked_per_step(obs_events):
    feeds = _feeds(6)
    prog, start, loss, _ = _regression()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(start)
        out = exe.run_bundle(prog, feeds=feeds, fetch_list=[loss], steps=6)
    assert len(out) == 1
    assert np.asarray(out[0]).shape[0] == 6     # stacked leading K axis
    bundles = obs_events('executor.bundle')
    assert len(bundles) == 1
    assert bundles[0]['fields']['steps'] == 6
    assert obs.REGISTRY.total('executor.bundle.steps') >= 6


def test_bundle_validation_errors():
    feeds = _feeds(4)
    prog, start, loss, _ = _regression()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(start)
        with pytest.raises(ValueError, match='non-empty'):
            exe.run_bundle(prog, feeds=[], fetch_list=[loss])
        with pytest.raises(ValueError, match='steps=3'):
            exe.run_bundle(prog, feeds=feeds, fetch_list=[loss], steps=3)
        bad_shape = dict(feeds[1], x=feeds[1]['x'][:5])
        with pytest.raises(ValueError, match='shape'):
            exe.run_bundle(prog, feeds=[feeds[0], bad_shape],
                           fetch_list=[loss])
        bad_names = {'x': feeds[1]['x']}
        with pytest.raises(ValueError, match='names'):
            exe.run_bundle(prog, feeds=[feeds[0], bad_names],
                           fetch_list=[loss])
        with pytest.raises(ValueError, match="sync"):
            exe.run_bundle(prog, feeds=feeds, fetch_list=[loss],
                           sync='nope')


# ---------------------------------------------------------------------------
# per-step RNG parity
# ---------------------------------------------------------------------------

def test_bundle_per_step_rng_parity():
    """Dropout masks at bundled inner step j equal unbundled run j's,
    bit-exactly: the scan body derives its key from the same seed integer
    run() hands jax.random.key. Dropout is applied DIRECTLY to the fed
    tensor so the comparison sees pure mask bits, no upstream matmul."""
    def build():
        prog, start = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, start):
            x = fluid.layers.data(name='x', shape=[32], dtype='float32')
            out = fluid.layers.dropout(x, dropout_prob=0.5)
        return prog, start, out

    feeds = [{'x': np.ones((4, 32), 'float32')} for _ in range(4)]

    prog, start, out = build()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(start)
        masks_u = [np.asarray(exe.run(prog, feed=f, fetch_list=[out])[0])
                   for f in feeds]

    prog, start, out = build()
    exe2 = fluid.Executor(fluid.CPUPlace())
    scope2 = fluid.Scope()
    with fluid.scope_guard(scope2):
        exe2.run(start)
        stacked = exe2.run_bundle(prog, feeds=feeds, fetch_list=[out])
    masks_b = np.asarray(stacked[0])
    assert masks_b.shape[0] == 4
    dropped = 0
    for j in range(4):
        np.testing.assert_array_equal(masks_u[j], masks_b[j])
        dropped += int((masks_b[j] == 0).sum())
    assert dropped > 0                       # dropout actually dropped
    assert any(not np.array_equal(masks_b[0], masks_b[j])
               for j in range(1, 4))         # and per-step masks DIFFER


# ---------------------------------------------------------------------------
# anomaly guard inside a bundle
# ---------------------------------------------------------------------------

def test_bundle_anomaly_guard_per_step_skip(obs_events):
    feeds = _feeds(4)
    inj = FaultInjector(seed=3)
    feeds[1] = dict(feeds[1], x=inj.poison_nan(feeds[1]['x'], rate=0.5))

    prog, start, loss, w_names = _regression(guard=True)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(start)
        with warnings.catch_warnings(record=True) as rec:
            warnings.simplefilter('always')
            exe.run_bundle(prog, feeds=feeds, fetch_list=[loss])
    # exactly ONE inner step skipped, observed per step on the host
    assert exe.skipped_steps == 1
    assert any('anomaly guard' in str(w.message) for w in rec)
    skips = obs_events('anomaly.skip')
    assert len(skips) == 1
    # the run id in the event names the INNER step (2nd of the bundle:
    # startup was run 1, so the poisoned step is run 3)
    assert skips[0]['fields']['run'] == 3
    # a healthy step after the poisoned one cleared the streak
    assert exe._consecutive_skips == 0
    assert bool(exe.last_step_health['healthy'])


def test_bundle_anomaly_guard_rollback_parity():
    """An all-poisoned bundle leaves params BIT-IDENTICAL to before it —
    the in-graph where-select rollback works per inner step under scan
    exactly as it does unbundled."""
    feeds = _feeds(4)
    inj = FaultInjector(seed=5)
    feeds = [dict(f, x=inj.poison_nan(f['x'], rate=1.0)) for f in feeds]

    prog, start, loss, w_names = _regression(guard=True)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(start)
        good = _feeds(1, seed=9)[0]
        exe.run(prog, feed=good, fetch_list=[loss])   # one real step
        before = {n: np.asarray(scope.vars[n]).copy() for n in w_names}
        with warnings.catch_warnings():
            warnings.simplefilter('ignore')
            exe.run_bundle(prog, feeds=feeds, fetch_list=[loss])
        after = {n: np.asarray(scope.vars[n]) for n in w_names}
    assert exe.skipped_steps == 4
    for n in w_names:
        np.testing.assert_array_equal(before[n], after[n])


def test_bundle_anomaly_guard_escalation():
    """max_consecutive_skips fires from WITHIN a bundle's host-side
    per-step observation (divergence does not hide behind bundling)."""
    feeds = _feeds(6)
    inj = FaultInjector(seed=7)
    feeds = [dict(f, x=inj.poison_nan(f['x'], rate=1.0)) for f in feeds]
    prog, start, loss, _ = _regression(guard=True, max_skips=3)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(start)
        with warnings.catch_warnings():
            warnings.simplefilter('ignore')
            with pytest.raises(FloatingPointError, match='consecutive'):
                exe.run_bundle(prog, feeds=feeds, fetch_list=[loss])
    assert exe.skipped_steps == 3   # raised at the limit, not after K


# ---------------------------------------------------------------------------
# async fetch window
# ---------------------------------------------------------------------------

def test_async_run_returns_lazy_handles(obs_events):
    feeds = _feeds(3)
    prog, start, loss, _ = _regression()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(start)
        blocking = float(np.asarray(
            exe.run(prog, feed=feeds[0], fetch_list=[loss])[0])
            .reshape(-1)[0])

    exe2 = fluid.Executor(fluid.CPUPlace())
    scope2 = fluid.Scope()
    prog, start, loss, _ = _regression()
    with fluid.scope_guard(scope2):
        exe2.run(start)
        h, = exe2.run(prog, feed=feeds[0], fetch_list=[loss],
                      sync='async')
    assert isinstance(h, FetchHandle)
    assert float(h) == blocking            # sync-on-demand, same value
    assert h.ready
    np.testing.assert_array_equal(np.asarray(h), np.asarray(h))  # cached
    assert obs.histogram('executor.host_stall.seconds').count >= 1
    gc.collect()
    assert obs.gauge('executor.inflight').value == 0


def test_async_handle_defers_and_rereaises_errors():
    """A failure materializing the value surfaces at FIRST READ and again
    at every later read; the inflight slot is released exactly once."""
    calls = []

    def boom():
        calls.append(1)
        raise RuntimeError('device exploded')

    g = obs.gauge('executor.inflight')
    base = g.value or 0
    h = FetchHandle(np.zeros(3), boom)
    assert (g.value or 0) == base + 1
    with pytest.raises(RuntimeError, match='device exploded'):
        h.block()
    with pytest.raises(RuntimeError, match='device exploded'):
        np.asarray(h)
    assert calls == [1]                    # materialized once, cached
    assert (g.value or 0) == base


def test_async_unread_handle_releases_inflight_slot():
    h = FetchHandle(np.arange(4.0))
    g = obs.gauge('executor.inflight')
    assert (g.value or 0) >= 1
    del h
    gc.collect()
    assert (g.value or 0) == 0


def test_float_on_multi_element_handle_raises():
    h = FetchHandle(np.arange(4.0))
    with pytest.raises(TypeError, match='one-element'):
        float(h)
    h.block()


# ---------------------------------------------------------------------------
# Trainer integration
# ---------------------------------------------------------------------------

def _trainer_pieces():
    def train_func():
        x = fluid.layers.data(name='x', shape=[13], dtype='float32')
        y = fluid.layers.data(name='y', shape=[1], dtype='float32')
        pred = fluid.layers.fc(input=x, size=1)
        return fluid.layers.mean(
            fluid.layers.square_error_cost(input=pred, label=y))

    def opt_func():
        return fluid.optimizer.SGD(learning_rate=0.1)

    rows = _feeds(10, seed=1, batch=4)

    def reader():
        for f in rows:
            yield [(f['x'][i], f['y'][i]) for i in range(len(f['x']))]

    return train_func, opt_func, reader


def _run_trainer(collect, **kw):
    train_func, opt_func, reader = _trainer_pieces()
    t = fluid.Trainer(train_func, opt_func, place=fluid.CPUPlace(), **kw)
    t.train(num_epochs=1, event_handler=collect, reader=reader,
            feed_order=['x', 'y'])
    w = {n: np.asarray(t.scope.vars[n]).copy() for n in t.scope.vars
         if n.endswith('.w_0')}
    return w


def test_trainer_bundled_event_stream_and_parity():
    events_plain, events_bundled = [], []

    def mk(sink):
        def handler(e):
            if isinstance(e, fluid.EndStepEvent):
                sink.append((e.step,
                             float(np.asarray(e.metrics[0]).reshape(-1)[0])))
        return handler

    w_plain = _run_trainer(mk(events_plain))
    # K=4 over 10 steps: two full bundles + one partial (10 = 4+4+2)
    w_bundled = _run_trainer(mk(events_bundled), bundle_steps=4)
    assert [s for s, _ in events_bundled] == [s for s, _ in events_plain]
    np.testing.assert_allclose([v for _, v in events_plain],
                               [v for _, v in events_bundled],
                               rtol=1e-6, atol=1e-7)
    for n in w_plain:
        np.testing.assert_allclose(w_plain[n], w_bundled[n],
                                   rtol=1e-5, atol=1e-7)


def test_trainer_async_window_syncs_at_handler_and_drains():
    losses = []

    def handler(e):
        if isinstance(e, fluid.EndStepEvent) and e.metrics:
            # reading the metric here IS the sync boundary
            losses.append(float(np.asarray(e.metrics[0]).reshape(-1)[0]))

    plain = []

    def phandler(e):
        if isinstance(e, fluid.EndStepEvent) and e.metrics:
            plain.append(float(np.asarray(e.metrics[0]).reshape(-1)[0]))

    _run_trainer(phandler)
    _run_trainer(handler, sync='async', async_window=2)
    np.testing.assert_allclose(plain, losses, rtol=1e-6, atol=0)
    gc.collect()
    assert obs.gauge('executor.inflight').value == 0


def test_trainer_async_window_handler_exception_mid_window():
    """A handler blowing up at step 3 (two steps still in flight) must
    propagate, and every in-flight handle must release its slot."""
    def handler(e):
        if isinstance(e, fluid.EndStepEvent) and e.step == 3:
            raise RuntimeError('handler crashed mid-window')

    with pytest.raises(RuntimeError, match='mid-window'):
        _run_trainer(handler, sync='async', async_window=2)
    gc.collect()
    assert obs.gauge('executor.inflight').value == 0


def test_trainer_rejects_incompatible_configs():
    train_func, opt_func, _ = _trainer_pieces()
    with pytest.raises(ValueError, match='bundle_steps'):
        fluid.Trainer(train_func, opt_func, bundle_steps=0)
    with pytest.raises(ValueError, match='sync'):
        fluid.Trainer(train_func, opt_func, sync='never')
    with pytest.raises(ValueError, match='parallel'):
        fluid.Trainer(train_func, opt_func, parallel=True, bundle_steps=4)


# ---------------------------------------------------------------------------
# persistent compile cache across processes
# ---------------------------------------------------------------------------

_CHILD = r"""
import json, os, sys
import numpy as np
import paddle_tpu.fluid as fluid

prog, start = fluid.Program(), fluid.Program()
with fluid.program_guard(prog, start):
    x = fluid.layers.data(name='x', shape=[13], dtype='float32')
    y = fluid.layers.data(name='y', shape=[1], dtype='float32')
    pred = fluid.layers.fc(input=x, size=1)
    loss = fluid.layers.mean(
        fluid.layers.square_error_cost(input=pred, label=y))
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
exe = fluid.Executor(fluid.CPUPlace())
exe.run(start)
rng = np.random.RandomState(0)
feed = {'x': rng.rand(16, 13).astype('float32'),
        'y': rng.rand(16, 1).astype('float32')}
exe.run(prog, feed=feed, fetch_list=[loss])
exe.run(prog, feed=feed, fetch_list=[loss])
print('STATS=' + json.dumps(exe.cache_stats))
"""


def test_persistent_cache_second_process_zero_compiles(tmp_path):
    """The acceptance drill: process 1 cold-compiles into the cache dir;
    process 2 (same program, same feed signature) records ZERO
    executor.compile spans — every first call deserializes
    (executor.compile.persistent_hit events + cache_stats counter)."""
    cache = tmp_path / 'cc'

    def run_child(obs_dir):
        env = dict(os.environ,
                   JAX_PLATFORMS='cpu',
                   PADDLE_TPU_COMPILE_CACHE=str(cache),
                   PADDLE_TPU_OBS_DIR=str(obs_dir))
        env.pop('PADDLE_TPU_OBS_RUN_FILE', None)
        r = subprocess.run([sys.executable, '-c', _CHILD],
                           capture_output=True, text=True, timeout=300,
                           env=env, cwd=os.path.dirname(
                               os.path.dirname(os.path.abspath(__file__))))
        assert r.returncode == 0, r.stderr[-2000:]
        stats = json.loads(
            [ln for ln in r.stdout.splitlines()
             if ln.startswith('STATS=')][0][len('STATS='):])
        obs_dir = str(obs_dir)
        logs = [os.path.join(obs_dir, f) for f in os.listdir(obs_dir)]
        assert len(logs) == 1
        events, errors = obs_report.load_events(logs[0])
        assert errors == []
        return stats, events

    stats1, ev1 = run_child(tmp_path / 'obs1')
    compiles1 = [e for e in ev1 if e['name'] == 'executor.compile']
    assert compiles1, 'first process must cold-compile'
    assert stats1['persistent_hits'] == 0

    stats2, ev2 = run_child(tmp_path / 'obs2')
    compiles2 = [e for e in ev2 if e['name'] == 'executor.compile']
    assert compiles2 == [], \
        'second process re-compiled already-cached keys: %r' % compiles2
    phits = [e for e in ev2
             if e['name'] == 'executor.compile.persistent_hit']
    assert len(phits) == len(compiles1)
    assert stats2['persistent_hits'] == len(compiles1)
    # and the steps that hit carry the outcome in their span fields
    steps2 = [e for e in ev2 if e['name'] == 'executor.step'
              and e.get('fields', {}).get('cache') == 'persistent_hit']
    assert steps2


def test_trainer_bundled_handles_short_last_batch():
    """Readers rarely divide evenly: the bundled loop must flush the
    buffer when the batch shape changes (short last batch) instead of
    poisoning one bundle with mixed signatures — caught live on
    uci_housing (404 rows / batch 32)."""
    train_func, opt_func, _ = _trainer_pieces()
    rows = _feeds(1, seed=2, batch=23)[0]   # 23 = 5 batches of 4 + one of 3

    def reader():
        for i in range(0, 23, 4):
            xb, yb = rows['x'][i:i + 4], rows['y'][i:i + 4]
            yield [(xb[j], yb[j]) for j in range(len(xb))]

    seen = []

    def handler(e):
        if isinstance(e, fluid.EndStepEvent):
            seen.append((e.step,
                         float(np.asarray(e.metrics[0]).reshape(-1)[0])))

    t = fluid.Trainer(train_func, opt_func, place=fluid.CPUPlace(),
                      bundle_steps=4)
    t.train(num_epochs=1, event_handler=handler, reader=reader,
            feed_order=['x', 'y'])
    assert [s for s, _ in seen] == [0, 1, 2, 3, 4, 5]   # no step dropped
    assert all(np.isfinite(v) for _, v in seen)


def test_trainer_rejects_bundle_plus_async():
    train_func, opt_func, _ = _trainer_pieces()
    with pytest.raises(ValueError, match="sync='async'"):
        fluid.Trainer(train_func, opt_func, bundle_steps=4, sync='async')


def test_trainer_bundled_periodic_checkpoints_fire(tmp_path, obs_events):
    """K=8 bundles with step_interval=10: no bundle BOUNDARY ever lands
    on a multiple of 10, but steps 0 and 10 cross inside bundles — the
    range gate must fire for them (the naive modulo-on-boundary gate
    saved nothing, ever)."""
    train_func, opt_func, reader = _trainer_pieces()   # 10 steps/epoch
    cfg = fluid.CheckpointConfig(checkpoint_dir=str(tmp_path / 'ck'),
                                 step_interval=10)
    t = fluid.Trainer(train_func, opt_func, place=fluid.CPUPlace(),
                      bundle_steps=8, checkpoint_config=cfg)
    t.train(num_epochs=1, event_handler=lambda e: None, reader=reader,
            feed_order=['x', 'y'])
    saves = obs_events('trainer.checkpoint.save')
    # step 0 crosses in bundle [0..7]; the short bundle [8..9] has no
    # multiple of 10 inside it
    assert len(saves) == 1
    assert saves[0]['fields']['step'] == 7   # bundle-end state recorded
