"""Execute (not just build) every layer family with small inputs.

Parity: reference tests/unittests/test_layers.py, plus numeric checks for
the conv/pool/norm families and finite-difference gradient checks
(reference op_test.py check_grad machinery).
"""
import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers
from paddle_tpu.fluid.executor import global_scope

from util import fresh_program


def _run(main, startup, feed, fetch_list):
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    return exe.run(main, feed=feed, fetch_list=fetch_list)


# ---------------------------------------------------------------------------
# activations / generated ops
# ---------------------------------------------------------------------------

ACTIVATIONS = ['sigmoid', 'logsigmoid', 'exp', 'tanh', 'tanh_shrink',
               'softshrink', 'sqrt', 'abs', 'ceil', 'floor', 'cos', 'sin',
               'round', 'reciprocal', 'square', 'softplus', 'softsign',
               'brelu', 'leaky_relu', 'soft_relu', 'elu', 'relu6', 'stanh',
               'hard_sigmoid', 'swish', 'relu']


def test_all_activations_execute():
    with fresh_program() as (main, startup):
        x = layers.data(name='x', shape=[6], dtype='float32')
        outs = [getattr(layers, a)(x) for a in ACTIVATIONS]
        outs.append(layers.pow(x, factor=2.0))
        outs.append(layers.prelu(x, mode='all'))
        xs = np.random.RandomState(0).rand(3, 6).astype('float32') + 0.1
        res = _run(main, startup, {'x': xs}, outs)
    for name, r in zip(ACTIVATIONS + ['pow', 'prelu'], res):
        assert r.shape == (3, 6), name
        assert np.isfinite(r).all(), name
    i = ACTIVATIONS.index('sigmoid')
    np.testing.assert_allclose(res[i], 1 / (1 + np.exp(-xs)), rtol=1e-5)
    np.testing.assert_allclose(res[ACTIVATIONS.index('square')], xs * xs,
                               rtol=1e-5)


def test_elementwise_and_logical():
    with fresh_program() as (main, startup):
        a = layers.data(name='a', shape=[4], dtype='float32')
        b = layers.data(name='b', shape=[4], dtype='float32')
        outs = [layers.elementwise_add(a, b), layers.elementwise_sub(a, b),
                layers.elementwise_mul(a, b), layers.elementwise_div(a, b),
                layers.elementwise_max(a, b), layers.elementwise_min(a, b),
                layers.elementwise_pow(a, b)]
        la = layers.cast(layers.less_than(a, b), 'bool')
        lb = layers.logical_not(la)
        outs += [layers.logical_and(la, lb), layers.logical_or(la, lb),
                 layers.logical_xor(la, lb)]
        av = np.random.RandomState(1).rand(2, 4).astype('float32') + 0.5
        bv = np.random.RandomState(2).rand(2, 4).astype('float32') + 0.5
        res = _run(main, startup, {'a': av, 'b': bv}, outs)
    np.testing.assert_allclose(res[0], av + bv, rtol=1e-5)
    np.testing.assert_allclose(res[3], av / bv, rtol=1e-5)
    np.testing.assert_allclose(res[6], av ** bv, rtol=1e-4)
    assert not res[7].any()          # a AND (not a) == False
    assert res[8].all()              # a OR (not a) == True


def test_reduce_family_and_friends():
    with fresh_program() as (main, startup):
        x = layers.data(name='x', shape=[3, 4], dtype='float32')
        outs = [layers.reduce_sum(x), layers.reduce_mean(x),
                layers.reduce_max(x), layers.reduce_min(x),
                layers.reduce_prod(x),
                layers.reduce_sum(x, dim=1, keep_dim=True),
                layers.scale(x, scale=2.5, bias=1.0),
                layers.clip(x, min=0.2, max=0.8),
                layers.clip_by_norm(x, max_norm=1.0),
                layers.sum([x, x]),
                layers.cos_sim(x, x),
                layers.l2_normalize(x, axis=-1)]
        xs = np.random.RandomState(3).rand(2, 3, 4).astype('float32')
        res = _run(main, startup, {'x': xs}, outs)
    np.testing.assert_allclose(res[0].ravel(), xs.reshape(2, -1).sum(-1).sum(),
                               rtol=1e-5)
    np.testing.assert_allclose(res[6], xs * 2.5 + 1.0, rtol=1e-5)
    np.testing.assert_allclose(res[7], np.clip(xs, 0.2, 0.8), rtol=1e-5)
    np.testing.assert_allclose(res[9], 2 * xs, rtol=1e-5)


def test_tensor_ops():
    with fresh_program() as (main, startup):
        x = layers.data(name='x', shape=[4], dtype='float32')
        t = layers.create_tensor(dtype='float32')
        layers.assign(x, output=t)
        gv = layers.create_global_var(shape=[1], value=3.0, dtype='float32',
                                      persistable=True)
        outs = [t,
                layers.cast(x, 'int32'),
                layers.concat([x, x], axis=1),
                layers.sums([x, x]),
                layers.fill_constant(shape=[2, 2], value=5.0, dtype='float32'),
                layers.fill_constant_batch_size_like(
                    x, shape=[-1, 3], value=1.5, dtype='float32'),
                layers.argmin(x, axis=1), layers.argmax(x, axis=1),
                layers.argsort(x, axis=1)[1],
                layers.ones(shape=[3], dtype='float32'),
                layers.zeros(shape=[3], dtype='float32'),
                layers.reverse(x, axis=1),
                layers.shape(x),
                layers.slice(x, axes=[1], starts=[1], ends=[3]),
                gv]
        xs = np.random.RandomState(4).rand(2, 4).astype('float32')
        res = _run(main, startup, {'x': xs}, outs)
    np.testing.assert_allclose(res[0], xs, rtol=1e-6)
    np.testing.assert_allclose(res[2], np.concatenate([xs, xs], 1), rtol=1e-6)
    assert res[4].shape == (2, 2) and (res[4] == 5.0).all()
    assert res[5].shape == (2, 3) and (res[5] == 1.5).all()
    np.testing.assert_array_equal(res[7].ravel(), xs.argmax(1))
    np.testing.assert_allclose(res[11], xs[:, ::-1], rtol=1e-6)
    np.testing.assert_array_equal(res[13], xs[:, 1:3])
    assert float(res[14]) == 3.0


def test_shape_manipulation():
    with fresh_program() as (main, startup):
        x = layers.data(name='x', shape=[2, 6], dtype='float32')
        idx = layers.data(name='idx', shape=[1], dtype='int32',
                          append_batch_size=False)
        outs = [layers.reshape(x, shape=[-1, 12]),
                layers.transpose(x, perm=[0, 2, 1]),
                layers.split(x, num_or_sections=2, dim=2)[0],
                layers.stack([x, x], axis=0),
                layers.flatten(x, axis=1),
                layers.pad(x, paddings=[0, 0, 1, 1, 0, 0], pad_value=9.0),
                layers.crop(x, shape=[-1, 1, 3]),
                layers.gather(layers.reshape(x, shape=[-1, 6]), idx),
                layers.topk(x, k=2)[0],
                layers.one_hot(layers.cast(idx, 'int64'), depth=4)]
        xs = np.arange(24, dtype='float32').reshape(2, 2, 6)
        res = _run(main, startup, {'x': xs, 'idx': np.array([1], 'int32')},
                   outs)
    assert res[0].shape == (2, 12)
    assert res[1].shape == (2, 6, 2)
    assert res[2].shape == (2, 2, 3)
    assert res[3].shape == (2, 2, 2, 6)
    assert res[4].shape == (2, 12)
    assert res[5].shape == (2, 4, 6) and res[5][0, 0, 0] == 9.0
    np.testing.assert_allclose(res[8], np.sort(xs, -1)[..., ::-1][..., :2])


def test_scatter_multiplex_random_crop():
    with fresh_program() as (main, startup):
        x = layers.data(name='x', shape=[4], dtype='float32')
        ids = layers.data(name='ids', shape=[2], dtype='int32',
                          append_batch_size=False)
        upd = layers.data(name='upd', shape=[2, 4], dtype='float32',
                          append_batch_size=False)
        sc = layers.scatter(layers.reshape(x, shape=[-1, 4]), ids, upd)
        a = layers.data(name='a', shape=[4], dtype='float32')
        b = layers.data(name='b', shape=[4], dtype='float32')
        which = layers.data(name='which', shape=[1], dtype='int32')
        mx = layers.multiplex(inputs=[a, b], index=which)
        rc = layers.random_crop(x, shape=[2])
        feed = {'x': np.ones((3, 4), 'float32'),
                'ids': np.array([0, 2], 'int32'),
                'upd': np.full((2, 4), 7.0, 'float32'),
                'a': np.zeros((2, 4), 'float32'),
                'b': np.ones((2, 4), 'float32'),
                'which': np.array([[0], [1]], 'int32')}
        res = _run(main, startup, feed, [sc, mx, rc])
    assert (res[0][0] == 7).all() and (res[0][1] == 1).all()
    np.testing.assert_allclose(res[1][0], np.zeros(4))
    np.testing.assert_allclose(res[1][1], np.ones(4))
    assert res[2].shape == (3, 2)


def test_conv2d_numeric():
    """conv2d vs a hand-rolled correlation on a tiny case."""
    with fresh_program() as (main, startup):
        x = layers.data(name='x', shape=[1, 4, 4], dtype='float32')
        y = layers.conv2d(input=x, num_filters=1, filter_size=3, padding=0,
                          bias_attr=False,
                          param_attr=fluid.ParamAttr(
                              initializer=fluid.initializer.Constant(1.0)))
        xs = np.arange(16, dtype='float32').reshape(1, 1, 4, 4)
        res = _run(main, startup, {'x': xs}, [y])[0]
    expect = np.zeros((1, 1, 2, 2), 'float32')
    for i in range(2):
        for j in range(2):
            expect[0, 0, i, j] = xs[0, 0, i:i + 3, j:j + 3].sum()
    np.testing.assert_allclose(res, expect, rtol=1e-5)


def test_conv_family_shapes():
    with fresh_program() as (main, startup):
        x = layers.data(name='x', shape=[2, 8, 8], dtype='float32')
        v = layers.data(name='v', shape=[2, 4, 4, 4], dtype='float32')
        outs = [layers.conv2d(x, num_filters=3, filter_size=3, padding=1),
                layers.conv2d(x, num_filters=4, filter_size=3, padding=1,
                              groups=2, dilation=2),
                layers.conv2d_transpose(x, num_filters=3, filter_size=2,
                                        stride=2),
                layers.conv3d(v, num_filters=3, filter_size=3, padding=1),
                layers.conv3d_transpose(v, num_filters=2, filter_size=2,
                                        stride=2),
                layers.pool2d(x, pool_size=2, pool_type='max', pool_stride=2),
                layers.pool2d(x, pool_size=2, pool_type='avg', pool_stride=2,
                              global_pooling=True),
                layers.pool3d(v, pool_size=2, pool_type='max', pool_stride=2)]
        feed = {'x': np.random.RandomState(5).rand(2, 2, 8, 8).astype('float32'),
                'v': np.random.RandomState(6).rand(2, 2, 4, 4, 4).astype('float32')}
        res = _run(main, startup, feed, outs)
    assert res[0].shape == (2, 3, 8, 8)
    assert res[1].shape == (2, 4, 6, 6)
    assert res[2].shape == (2, 3, 16, 16)
    assert res[3].shape == (2, 3, 4, 4, 4)
    assert res[4].shape == (2, 2, 8, 8, 8)
    assert res[5].shape == (2, 2, 4, 4)
    assert res[6].shape == (2, 2, 1, 1)
    assert res[7].shape == (2, 2, 2, 2, 2)


def test_pool2d_numeric():
    with fresh_program() as (main, startup):
        x = layers.data(name='x', shape=[1, 4, 4], dtype='float32')
        mx = layers.pool2d(x, pool_size=2, pool_type='max', pool_stride=2)
        av = layers.pool2d(x, pool_size=2, pool_type='avg', pool_stride=2)
        xs = np.arange(16, dtype='float32').reshape(1, 1, 4, 4)
        rm, ra = _run(main, startup, {'x': xs}, [mx, av])
    np.testing.assert_allclose(rm[0, 0], [[5, 7], [13, 15]])
    np.testing.assert_allclose(ra[0, 0], [[2.5, 4.5], [10.5, 12.5]])


def test_batch_norm_inference_numeric():
    with fresh_program() as (main, startup):
        x = layers.data(name='x', shape=[3, 2, 2], dtype='float32')
        y = layers.batch_norm(input=x, is_test=True, epsilon=1e-5,
                              moving_mean_name='bn_mean',
                              moving_variance_name='bn_var')
        infer = main.clone(for_test=True)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        import jax.numpy as jnp
        scope = global_scope()
        rng = np.random.RandomState(7)
        mean = rng.rand(3).astype('float32')
        var = rng.rand(3).astype('float32') + 0.5
        scale = rng.rand(3).astype('float32')
        bias = rng.rand(3).astype('float32')
        scope.vars['bn_mean'] = jnp.asarray(mean)
        scope.vars['bn_var'] = jnp.asarray(var)
        for n in list(scope.vars):
            if 'batch_norm' in n and n.endswith('.w_0'):
                scope.vars[n] = jnp.asarray(scale)
            elif 'batch_norm' in n and n.endswith('.b_0'):
                scope.vars[n] = jnp.asarray(bias)
        xs = rng.rand(2, 3, 2, 2).astype('float32')
        res = exe.run(infer, feed={'x': xs}, fetch_list=[y])[0]
    expect = (xs - mean[None, :, None, None]) / \
        np.sqrt(var[None, :, None, None] + 1e-5) * \
        scale[None, :, None, None] + bias[None, :, None, None]
    np.testing.assert_allclose(res, expect, rtol=1e-4)


def test_norm_family():
    with fresh_program() as (main, startup):
        x = layers.data(name='x', shape=[4, 4, 4], dtype='float32')
        flat = layers.data(name='f', shape=[8], dtype='float32')
        outs = [layers.batch_norm(input=x),
                layers.layer_norm(input=x),
                layers.lrn(input=x),
                layers.maxout(layers.data(name='m', shape=[4, 2, 2],
                                          dtype='float32'), groups=2)]
        feed = {'x': np.random.RandomState(8).rand(2, 4, 4, 4).astype('float32'),
                'f': np.random.RandomState(9).rand(2, 8).astype('float32'),
                'm': np.random.RandomState(10).rand(2, 4, 2, 2).astype('float32')}
        res = _run(main, startup, feed, outs)
    assert res[0].shape == (2, 4, 4, 4)
    assert res[1].shape == (2, 4, 4, 4)
    assert res[2].shape == (2, 4, 4, 4)
    assert res[3].shape == (2, 2, 2, 2)
    ln = res[1].reshape(2, -1)
    np.testing.assert_allclose(ln.mean(1), 0, atol=1e-4)


def test_loss_family():
    with fresh_program() as (main, startup):
        logits = layers.data(name='logits', shape=[5], dtype='float32')
        label = layers.data(name='label', shape=[1], dtype='int64')
        flabel = layers.data(name='flabel', shape=[5], dtype='float32')
        pred = layers.softmax(logits)
        outs = [layers.cross_entropy(input=pred, label=label),
                layers.softmax_with_cross_entropy(logits, label),
                layers.square_error_cost(input=logits, label=flabel),
                layers.smooth_l1(x=logits, y=flabel),
                layers.sigmoid_cross_entropy_with_logits(x=logits, label=flabel),
                layers.dice_loss(layers.sigmoid(logits), layers.cast(
                    layers.reshape(label, shape=[-1, 1]), 'int64')),
                layers.rank_loss(
                    label=layers.reshape(flabel, shape=[-1, 5]),
                    left=layers.reshape(logits, shape=[-1, 5]),
                    right=layers.reshape(flabel, shape=[-1, 5])),
                layers.label_smooth(layers.one_hot(label, depth=5),
                                    epsilon=0.1)]
        rng = np.random.RandomState(11)
        lg = rng.rand(3, 5).astype('float32')
        lb = rng.randint(0, 5, (3, 1)).astype('int64')
        fl = rng.rand(3, 5).astype('float32')
        res = _run(main, startup, {'logits': lg, 'label': lb, 'flabel': fl},
                   outs)
    # cross_entropy(softmax(x)) == softmax_with_cross_entropy(x)
    np.testing.assert_allclose(res[0], res[1], rtol=1e-4)
    np.testing.assert_allclose(res[2], (lg - fl) ** 2, rtol=1e-5)
    sm = np.exp(lg) / np.exp(lg).sum(-1, keepdims=True)
    expect_ce = -np.log(sm[np.arange(3), lb.ravel()])[:, None]
    np.testing.assert_allclose(res[0], expect_ce, rtol=1e-4)


def test_fc_embedding_matmul():
    with fresh_program() as (main, startup):
        x = layers.data(name='x', shape=[3, 4], dtype='float32')
        ids = layers.data(name='ids', shape=[3], dtype='int64')
        emb = layers.embedding(input=ids, size=[10, 6])
        f1 = layers.fc(input=x, size=5, num_flatten_dims=2)
        f2 = layers.fc(input=[x, x], size=5, num_flatten_dims=2)
        a = layers.data(name='a', shape=[2, 3], dtype='float32')
        b = layers.data(name='b', shape=[3, 2], dtype='float32')
        mm = layers.matmul(a, b)
        mmt = layers.matmul(a, a, transpose_y=True)
        ml = layers.mul(layers.reshape(a, shape=[-1, 3]),
                        layers.reshape(b, shape=[3, -1]))
        rng = np.random.RandomState(12)
        feed = {'x': rng.rand(2, 3, 4).astype('float32'),
                'ids': rng.randint(0, 10, (2, 3)).astype('int64'),
                'a': rng.rand(2, 2, 3).astype('float32'),
                'b': rng.rand(2, 3, 2).astype('float32')}
        res = _run(main, startup, feed, [emb, f1, f2, mm, mmt, ml])
    assert res[0].shape == (2, 3, 6)
    assert res[1].shape == (2, 3, 5)
    assert res[2].shape == (2, 3, 5)
    np.testing.assert_allclose(res[3], feed['a'] @ feed['b'], rtol=1e-5)
    np.testing.assert_allclose(
        res[4], feed['a'] @ feed['a'].transpose(0, 2, 1), rtol=1e-5)


def test_dropout_train_vs_test():
    with fresh_program() as (main, startup):
        x = layers.data(name='x', shape=[100], dtype='float32')
        y = layers.dropout(x, dropout_prob=0.5)
        infer = main.clone(for_test=True)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        xs = np.ones((4, 100), 'float32')
        train = exe.run(main, feed={'x': xs}, fetch_list=[y])[0]
        test = exe.run(infer, feed={'x': xs}, fetch_list=[y])[0]
    assert (train == 0).mean() > 0.2          # some units dropped
    # reference dropout_op.h:67 — inference scales by (1 - dropout_prob)
    np.testing.assert_allclose(test, xs * 0.5)


def test_image_resize_family():
    with fresh_program() as (main, startup):
        x = layers.data(name='x', shape=[1, 4, 4], dtype='float32')
        outs = [layers.image_resize(x, out_shape=[8, 8]),
                layers.resize_bilinear(x, out_shape=[2, 2]),
                layers.image_resize_short(x, out_short_len=8)]
        xs = np.arange(16, dtype='float32').reshape(1, 1, 4, 4)
        res = _run(main, startup, {'x': xs}, outs)
    assert res[0].shape == (1, 1, 8, 8)
    assert res[1].shape == (1, 1, 2, 2)
    assert res[2].shape == (1, 1, 8, 8)


def test_misc_ops():
    with fresh_program() as (main, startup):
        x = layers.data(name='x', shape=[4], dtype='float32')
        pr = layers.data(name='pr', shape=[2, 4, 4], dtype='float32')
        step = layers.autoincreased_step_counter()
        outs = [layers.mean(x),
                layers.gaussian_random(shape=[3, 3]),
                layers.gaussian_random_batch_size_like(x, shape=[-1, 5]),
                layers.uniform_random_batch_size_like(x, shape=[-1, 5]),
                layers.mean_iou(
                    layers.fill_constant(shape=[4], value=1, dtype='int32'),
                    layers.fill_constant(shape=[4], value=1, dtype='int32'),
                    2)[0],
                step]
        feed = {'x': np.random.RandomState(13).rand(2, 4).astype('float32'),
                'pr': np.random.RandomState(14).rand(1, 2, 4, 4).astype('float32')}
        res = _run(main, startup, feed, outs)
    assert res[1].shape == (3, 3)
    assert res[2].shape == (2, 5)
    assert res[3].shape == (2, 5)
    assert np.isclose(float(res[4]), 1.0)


def test_lr_schedulers_numeric():
    from paddle_tpu.fluid.layers import learning_rate_scheduler as lrs
    cases = {
        'exponential_decay': (lambda: lrs.exponential_decay(0.1, 10, 0.9),
                              lambda t: 0.1 * 0.9 ** (t / 10.0)),
        'natural_exp_decay': (lambda: lrs.natural_exp_decay(0.1, 10, 0.9),
                              lambda t: 0.1 * np.exp(-0.9 * (t / 10.0))),
        'inverse_time_decay': (lambda: lrs.inverse_time_decay(0.1, 10, 0.9),
                               lambda t: 0.1 / (1 + 0.9 * (t / 10.0))),
        'polynomial_decay': (lambda: lrs.polynomial_decay(0.1, 100, 0.01, 2.0),
                             lambda t: (0.1 - 0.01) *
                             (1 - min(t, 100) / 100.0) ** 2 + 0.01),
        'noam_decay': (lambda: lrs.noam_decay(64, 100),
                       lambda t: 64 ** -0.5 * min((t + 1) ** -0.5,
                                                  (t + 1) * 100 ** -1.5)),
    }
    for name, (build, expect) in cases.items():
        with fresh_program() as (main, startup):
            x = layers.data(name='x', shape=[1], dtype='float32')
            lr = build()
            out = layers.elementwise_mul(
                layers.reduce_sum(x), lr) if name != 'noam_decay' else lr
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            vals = [float(np.asarray(
                exe.run(main, feed={'x': np.ones((1, 1), 'float32')},
                        fetch_list=[lr])[0]))
                for _ in range(4)]
        for t, v in enumerate(vals):
            assert np.isclose(v, expect(t), rtol=1e-4), (name, t, v, expect(t))


def test_piecewise_decay():
    from paddle_tpu.fluid.layers import learning_rate_scheduler as lrs
    with fresh_program() as (main, startup):
        lr = lrs.piecewise_decay(boundaries=[2, 4], values=[1.0, 0.5, 0.1])
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        vals = [float(np.asarray(exe.run(main, feed={}, fetch_list=[lr])[0]))
                for _ in range(6)]
    np.testing.assert_allclose(vals, [1.0, 1.0, 0.5, 0.5, 0.1, 0.1],
                               rtol=1e-6)


def test_metric_ops():
    with fresh_program() as (main, startup):
        pred = layers.data(name='pred', shape=[4], dtype='float32')
        label = layers.data(name='label', shape=[1], dtype='int64')
        acc = layers.accuracy(input=pred, label=label)
        auc_out, _, _ = layers.auc(
            input=layers.concat([1.0 - pred, pred], axis=1)
            if False else pred, label=label) \
            if isinstance(layers.auc(input=pred, label=label), tuple) \
            else (layers.auc(input=pred, label=label), None, None)
        p = np.array([[0.1, 0.6, 0.2, 0.1],
                      [0.7, 0.1, 0.1, 0.1]], 'float32')
        l = np.array([[1], [2]], 'int64')
        res = _run(main, startup, {'pred': p, 'label': l}, [acc])
    assert np.isclose(float(res[0]), 0.5)


def test_nce_hsigmoid_build_and_run():
    with fresh_program() as (main, startup):
        x = layers.data(name='x', shape=[8], dtype='float32')
        label = layers.data(name='label', shape=[1], dtype='int64')
        nce_loss = layers.nce(input=x, label=label, num_total_classes=20,
                              num_neg_samples=4)
        hs_loss = layers.hsigmoid(input=x, label=label, num_classes=20)
        rng = np.random.RandomState(15)
        feed = {'x': rng.rand(3, 8).astype('float32'),
                'label': rng.randint(0, 20, (3, 1)).astype('int64')}
        res = _run(main, startup, feed, [nce_loss, hs_loss])
    assert np.isfinite(res[0]).all() and np.isfinite(res[1]).all()


def test_gradient_check_conv_pool_bn():
    """Finite-difference gradient check through conv+pool+bn+fc (the
    reference's op_test check_grad, composed)."""
    import jax.numpy as jnp
    with fresh_program() as (main, startup):
        x = layers.data(name='x', shape=[2, 6, 6], dtype='float32')
        h = layers.conv2d(x, num_filters=3, filter_size=3, padding=1,
                          act='relu')
        h = layers.pool2d(h, pool_size=2, pool_stride=2, pool_type='avg')
        h = layers.batch_norm(h)
        pred = layers.fc(input=h, size=1)
        loss = layers.reduce_sum(pred)
        from paddle_tpu.fluid.backward import append_backward
        append_backward(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        scope = global_scope()
        w_name = [n for n in scope.vars if 'conv' in n and n.endswith('.w_0')][0]
        xs = np.random.RandomState(16).rand(2, 2, 6, 6).astype('float32')
        g = exe.run(main, feed={'x': xs},
                    fetch_list=[loss, w_name + '@GRAD'])[1]
        w0 = np.asarray(scope.vars[w_name]).copy()
        # eps=1e-2 was too coarse for this composition: the relu kink +
        # BN renormalization bend the loss enough within ±1e-2 that the
        # central difference is ~5% off the true derivative (autodiff
        # agrees with FD to <0.02% at eps<=5e-3 — verified by sweeping
        # eps; the analytic gradient was right all along)
        eps = 5e-3
        idx = (0, 0, 1, 1)
        for sign in (1, -1):
            wp = w0.copy()
            wp[idx] += sign * eps
            scope.vars[w_name] = jnp.asarray(wp)
            val = float(exe.run(main, feed={'x': xs}, fetch_list=[loss])[0])
            if sign == 1:
                plus = val
            else:
                minus = val
        fd = (plus - minus) / (2 * eps)
    assert np.isclose(g[idx], fd, rtol=2e-2), (g[idx], fd)


def test_gradient_check_sequence_lstm():
    """Finite-difference check through embedding + dynamic_lstm."""
    import jax.numpy as jnp
    with fresh_program() as (main, startup):
        ids = layers.data(name='ids', shape=[1], dtype='int64', lod_level=1)
        emb = layers.embedding(input=ids, size=[12, 8])
        fc = layers.fc(input=emb, size=16)
        h, c = layers.dynamic_lstm(input=fc, size=16)
        loss = layers.reduce_sum(layers.sequence_pool(h, 'sum'))
        from paddle_tpu.fluid.backward import append_backward
        append_backward(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        scope = global_scope()
        lt = fluid.create_lod_tensor(
            np.array([[1], [2], [3], [4], [5]], 'int64'), [[3, 2]])
        emb_name = [n for n in scope.vars if 'emb' in n][0]
        g = exe.run(main, feed={'ids': lt},
                    fetch_list=[loss, emb_name + '@GRAD'])[1]
        w0 = np.asarray(scope.vars[emb_name]).copy()
        eps, idx = 1e-2, (2, 3)
        vals = {}
        for sign in (1, -1):
            wp = w0.copy()
            wp[idx] += sign * eps
            scope.vars[emb_name] = jnp.asarray(wp)
            vals[sign] = float(exe.run(main, feed={'ids': lt},
                                       fetch_list=[loss])[0])
        fd = (vals[1] - vals[-1]) / (2 * eps)
    assert np.isclose(g[idx], fd, rtol=2e-2, atol=1e-3), (g[idx], fd)
