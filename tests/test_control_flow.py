"""Structured control flow: While / IfElse / Switch / StaticRNN / DynamicRNN
+ LoDTensorArray ops. Mirrors reference unittests test_while_op.py,
test_recurrent_op.py, test_dyn_rnn.py, test_switch.py, test_array_read_write_op.py.
"""
import numpy as np
import pytest

import paddle_tpu.fluid as fluid
import paddle_tpu.fluid.layers as layers

from util import fresh_program


def _run(main, startup, feed, fetch):
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    return exe.run(main, feed=feed, fetch_list=fetch)


def test_while_scalar_accumulation():
    with fresh_program() as (main, startup):
        limit = layers.fill_constant(shape=[1], dtype='int64', value=10)
        i = layers.zeros(shape=[1], dtype='int64')
        acc = layers.zeros(shape=[1], dtype='float32')
        cond = layers.less_than(x=i, y=limit)
        w = layers.While(cond=cond)
        with w.block():
            fi = layers.cast(i, 'float32')
            new_acc = layers.elementwise_add(acc, fi)
            layers.assign(new_acc, output=acc)
            layers.increment(x=i, in_place=True)
            layers.less_than(x=i, y=limit, cond=cond)
        out, iters = _run(main, startup, {}, [acc, i])
    assert float(out[0]) == sum(range(10))
    assert int(iters[0]) == 10


def test_while_array_read_write():
    # the classic test_while_op shape: mem[t+1] = mem[t] + data[t]
    np.random.seed(0)
    d = np.random.rand(6, 8).astype('float32')
    with fresh_program() as (main, startup):
        x = layers.data(name='x', shape=[6, 8], append_batch_size=False)
        zero = layers.zeros(shape=[1], dtype='int64')
        arr = layers.create_array('float32')
        # preload data rows into an array
        i = layers.zeros(shape=[1], dtype='int64')
        n = layers.fill_constant(shape=[1], dtype='int64', value=6)
        cond = layers.less_than(x=i, y=n)
        w0 = layers.While(cond=cond)
        # seed the array so it's a legal carry
        row0 = layers.slice(x, axes=[0], starts=[0], ends=[1])
        row0 = layers.reshape(row0, shape=[8])
        layers.array_write(row0, i=zero, array=arr)
        with w0.block():
            # arr[i] = x[i] via gather
            row = layers.reshape(layers.gather(x, layers.cast(i, 'int32')),
                                 shape=[8])
            layers.array_write(row, i=i, array=arr)
            layers.increment(x=i, in_place=True)
            layers.less_than(x=i, y=n, cond=cond)
        # now sum the array with a second while
        j = layers.zeros(shape=[1], dtype='int64')
        total = layers.zeros(shape=[8], dtype='float32')
        cond2 = layers.less_than(x=j, y=n)
        w1 = layers.While(cond=cond2)
        with w1.block():
            v = layers.array_read(arr, i=j)
            s = layers.elementwise_add(total, v)
            layers.assign(s, output=total)
            layers.increment(x=j, in_place=True)
            layers.less_than(x=j, y=n, cond=cond2)
        length = layers.array_length(arr)
        out, ln = _run(main, startup, {'x': d}, [total, length])
    np.testing.assert_allclose(out, d.sum(0), rtol=1e-5)
    assert int(ln[0]) == 6


def test_while_max_iters_backward():
    # bounded (differentiable) while on the loss path: y = x * w^3
    with fresh_program() as (main, startup):
        x = layers.data(name='x', shape=[4], append_batch_size=False,
                        stop_gradient=False)
        w = layers.create_parameter(shape=[4], dtype='float32',
                                    default_initializer=fluid.initializer.Constant(2.0))
        limit = layers.fill_constant(shape=[1], dtype='int64', value=3)
        i = layers.zeros(shape=[1], dtype='int64')
        acc = layers.ones(shape=[4], dtype='float32')
        acc.stop_gradient = False
        cond = layers.less_than(x=i, y=limit)
        loop = layers.While(cond=cond, max_iters=8)
        with loop.block():
            nxt = layers.elementwise_mul(acc, w)
            layers.assign(nxt, output=acc)
            layers.increment(x=i, in_place=True)
            layers.less_than(x=i, y=limit, cond=cond)
        y = layers.elementwise_mul(acc, x)
        loss = layers.reduce_mean(y)
        opt = fluid.optimizer.SGD(learning_rate=0.0)
        opt.minimize(loss)
        xv = np.arange(4).astype('float32')
        out, g = _run(main, startup, {'x': xv},
                      [loss, w.name + '@GRAD'])
    # loss = mean(x * w^3); dloss/dw = 3 w^2 x / 4
    np.testing.assert_allclose(out[()], np.mean(xv * 8.0), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(g), 3 * 4.0 * xv / 4, rtol=1e-5)


def test_ifelse_merge():
    np.random.seed(1)
    xv = np.random.randn(6, 1).astype('float32')
    with fresh_program() as (main, startup):
        x = layers.data(name='x', shape=[1])
        zero = layers.fill_constant_batch_size_like(x, shape=[-1, 1],
                                                    dtype='float32', value=0.0)
        cond = layers.less_than(x=zero, y=x)   # x > 0
        ie = layers.IfElse(cond)
        with ie.true_block():
            t = ie.input(x)
            ie.output(layers.scale(t, scale=2.0))
        with ie.false_block():
            f = ie.input(x)
            ie.output(layers.scale(f, scale=-1.0))
        merged = ie()[0]
        out, = _run(main, startup, {'x': xv}, [merged])
    np.testing.assert_allclose(out, np.where(xv > 0, 2 * xv, -xv), rtol=1e-5)


@pytest.mark.parametrize('step_val,expect', [(3, 1.0), (7, 0.1)])
def test_switch(step_val, expect):
    with fresh_program() as (main, startup):
        step = layers.data(name='step', shape=[1], append_batch_size=False,
                           dtype='int64')
        five = layers.fill_constant(shape=[1], dtype='int64', value=5)
        lr = layers.fill_constant(shape=[1], dtype='float32', value=0.0)
        cond = layers.less_than(x=step, y=five)
        with layers.Switch() as switch:
            with switch.case(cond):
                layers.assign(np.array([1.0], dtype='float32'), output=lr)
            with switch.default():
                layers.assign(np.array([0.1], dtype='float32'), output=lr)
        out, = _run(main, startup,
                    {'step': np.array([step_val], dtype='int64')}, [lr])
    assert abs(float(out[0]) - expect) < 1e-6


def test_static_rnn_cumsum():
    np.random.seed(2)
    T, B, D = 5, 3, 4
    xv = np.random.randn(T, B, D).astype('float32')
    with fresh_program() as (main, startup):
        x = layers.data(name='x', shape=[T, B, D], append_batch_size=False)
        rnn = layers.StaticRNN()
        with rnn.step():
            x_t = rnn.step_input(x)
            prev = rnn.memory(shape=[D], batch_ref=x_t)
            h = layers.elementwise_add(x_t, prev)
            rnn.update_memory(prev, h)
            rnn.step_output(h)
        out_seq = rnn()
        out, = _run(main, startup, {'x': xv}, [out_seq])
    np.testing.assert_allclose(out, np.cumsum(xv, axis=0), rtol=1e-5)


def test_static_rnn_fc_backward():
    T, B, D, H = 4, 2, 3, 5
    np.random.seed(3)
    xv = np.random.randn(T, B, D).astype('float32')
    with fresh_program() as (main, startup):
        x = layers.data(name='x', shape=[T, B, D], append_batch_size=False)
        rnn = layers.StaticRNN()
        with rnn.step():
            x_t = rnn.step_input(x)
            prev = rnn.memory(shape=[H], batch_ref=x_t)
            h = layers.fc(input=[x_t, prev], size=H, act='tanh')
            rnn.update_memory(prev, h)
            rnn.step_output(h)
        out_seq = rnn()
        loss = layers.reduce_mean(out_seq)
        opt = fluid.optimizer.SGD(learning_rate=0.1)
        opt.minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        losses = [float(exe.run(main, feed={'x': xv},
                                fetch_list=[loss])[0][()])
                  for _ in range(3)]
    assert np.all(np.isfinite(losses))


def test_dynamic_rnn_masked_cumsum():
    B, T, D = 3, 5, 2
    lengths = [5, 3, 1]
    np.random.seed(4)
    flat = np.random.randn(sum(lengths), D).astype('float32')
    lt = fluid.create_lod_tensor(flat, [lengths])
    with fresh_program() as (main, startup):
        x = layers.data(name='x', shape=[D], lod_level=1)
        drnn = layers.DynamicRNN()
        with drnn.block():
            x_t = drnn.step_input(x)
            mem = drnn.memory(shape=[D], value=0.0)
            h = layers.elementwise_add(x_t, mem)
            drnn.update_memory(mem, h)
            drnn.output(h)
        out_var = drnn()
        last = layers.sequence_last_step(out_var)
        out, = _run(main, startup, {'x': lt}, [last])
    # last step of the masked cumsum == per-sequence sum
    off = np.cumsum([0] + lengths)
    want = np.stack([flat[off[i]:off[i + 1]].sum(0) for i in range(B)])
    np.testing.assert_allclose(out, want, rtol=1e-5)


def test_array_ops_outside_loop():
    with fresh_program() as (main, startup):
        v1 = layers.fill_constant(shape=[3], dtype='float32', value=1.0)
        v2 = layers.fill_constant(shape=[3], dtype='float32', value=2.0)
        i0 = layers.zeros(shape=[1], dtype='int64')
        i1 = layers.fill_constant(shape=[1], dtype='int64', value=1)
        arr = layers.array_write(v1, i=i0)
        layers.array_write(v2, i=i1, array=arr)
        r = layers.array_read(arr, i=i1)
        n = layers.array_length(arr)
        out, ln = _run(main, startup, {}, [r, n])
    np.testing.assert_allclose(out, np.full(3, 2.0))
    assert int(ln[0]) == 2


def test_ifelse_outer_write_merged():
    with fresh_program() as (main, startup):
        x = layers.data(name='x', shape=[1], append_batch_size=False)
        zero = layers.fill_constant(shape=[1], dtype='float32', value=0.0)
        flag = layers.fill_constant(shape=[1], dtype='float32', value=-1.0)
        cond = layers.less_than(x=zero, y=x)
        ie = layers.IfElse(cond)
        with ie.true_block():
            t = ie.input(x)
            layers.assign(layers.scale(t, scale=10.0), output=flag)
            ie.output(t)
        with ie.false_block():
            f = ie.input(x)
            ie.output(f)
        ie()
        pos, = _run(main, startup, {'x': np.array([2.0], 'float32')}, [flag])
        exe = fluid.Executor(fluid.CPUPlace())
        neg = exe.run(main, feed={'x': np.array([-2.0], 'float32')},
                      fetch_list=[flag])[0]
    assert float(pos[0]) == 20.0     # true branch's outer write applied
    assert float(neg[0]) == -1.0     # false branch keeps prior value


def test_loop_dropout_varies_per_step():
    T, B, D = 6, 2, 64
    with fresh_program() as (main, startup):
        x = layers.data(name='x', shape=[T, B, D], append_batch_size=False)
        rnn = layers.StaticRNN()
        with rnn.step():
            x_t = rnn.step_input(x)
            prev = rnn.memory(shape=[D], batch_ref=x_t)
            h = layers.dropout(x_t, dropout_prob=0.5)
            rnn.update_memory(prev, h)
            rnn.step_output(h)
        out_seq = rnn()
        out, = _run(main, startup, {'x': np.ones((T, B, D), 'float32')},
                    [out_seq])
    masks = (out != 0).reshape(T, -1)
    # distinct iterations must draw distinct dropout masks
    assert any(not np.array_equal(masks[0], masks[t]) for t in range(1, T))


def test_while_capacity_widening_for_lod_beam_arrays():
    """The decode idiom writes one row per source into LoD arrays BEFORE a
    While whose body writes beam_size rows per source: the widening pass
    (block_ops._widen_carry_to_body + ArrayValue grow-on-write) must bring
    the pre-loop slots to capacity with each source's rows at its block
    start. Regression guard for the book decode_main path independent of
    the reference file."""
    from paddle_tpu.fluid.lod_tensor import create_lod_tensor
    B, K, V = 2, 2, 12
    with fresh_program() as (main, startup):
        init_ids = layers.data(name='init_ids', shape=[1], dtype='int64',
                               lod_level=2)
        init_scores = layers.data(name='init_scores', shape=[1],
                                  dtype='float32', lod_level=2)
        emb_w = layers.create_parameter([V, 8], 'float32', name='bm_emb')
        counter = layers.zeros(shape=[1], dtype='int64', force_cpu=True)
        max_len = layers.fill_constant(shape=[1], dtype='int64', value=4)
        ids_arr = layers.create_array('int64')
        sc_arr = layers.create_array('float32')
        layers.array_write(init_ids, array=ids_arr, i=counter)
        layers.array_write(init_scores, array=sc_arr, i=counter)
        cond = layers.less_than(x=counter, y=max_len)
        w = layers.While(cond=cond)
        with w.block():
            pre_ids = layers.array_read(array=ids_arr, i=counter)
            pre_sc = layers.array_read(array=sc_arr, i=counter)
            emb = layers.embedding(pre_ids, size=[V, 8],
                                   param_attr=fluid.ParamAttr(name='bm_emb'))
            score = layers.fc(input=emb, size=V, num_flatten_dims=2,
                              act='softmax')
            tk_sc, tk_idx = layers.topk(score, k=K)
            accu = layers.elementwise_add(
                x=layers.log(tk_sc),
                y=layers.reshape(pre_sc, shape=[-1]), axis=0)
            sel_ids, sel_sc = layers.beam_search(
                pre_ids, pre_sc, tk_idx, accu, K, end_id=0, level=0)
            layers.increment(x=counter, value=1, in_place=True)
            layers.array_write(sel_ids, array=ids_arr, i=counter)
            layers.array_write(sel_sc, array=sc_arr, i=counter)
            layers.logical_and(
                x=layers.less_than(x=counter, y=max_len),
                y=layers.logical_not(layers.is_empty(x=sel_ids)), out=cond)
        tr_ids, tr_sc = layers.beam_search_decode(ids_arr, sc_arr,
                                                  beam_size=K, end_id=0)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        feed = {
            'init_ids': create_lod_tensor(
                np.ones((B, 1), 'int64'), [[1] * B, [1] * B]),
            'init_scores': create_lod_tensor(
                np.ones((B, 1), 'float32'), [[1] * B, [1] * B]),
        }
        out_ids, out_sc = exe.run(main, feed=feed,
                                  fetch_list=[tr_ids, tr_sc],
                                  return_numpy=False)
    lens = out_ids.recursive_sequence_lengths()
    # 2-level LoD: up to K hypotheses per source, each a non-empty
    # token sequence bounded by the loop length
    assert len(lens) == 2 and len(lens[0]) == B
    assert all(1 <= h <= K for h in lens[0])
    assert all(1 <= L <= 5 for L in lens[1])
    assert sum(lens[1]) == np.asarray(out_ids.data).shape[0]
    # scores regroup in lockstep with ids
    assert out_sc.recursive_sequence_lengths() == lens
    assert np.asarray(out_sc.data).shape[0] == sum(lens[1])

    # the full decode program (While sub-block, beam ops, LoD arrays)
    # survives the desc round-trip bit-identically (the protobuf
    # guarantee test_program_fuzz.py checks for flat graphs)
    from paddle_tpu.fluid import framework
    from paddle_tpu.fluid.executor import Scope, scope_guard
    main2 = framework.Program._from_dict(main._to_dict())
    assert main2._to_dict() == main._to_dict()
    with scope_guard(Scope()):
        exe2 = fluid.Executor(fluid.CPUPlace())
        exe2.run(startup)
        out_ids2, out_sc2 = exe2.run(
            main2, feed=feed,
            fetch_list=[main2.global_block().var(tr_ids.name),
                        main2.global_block().var(tr_sc.name)],
            return_numpy=False)
    assert out_ids2.recursive_sequence_lengths() == lens
    np.testing.assert_array_equal(np.asarray(out_ids2.data),
                                  np.asarray(out_ids.data))
    assert out_sc2.recursive_sequence_lengths() == lens
    np.testing.assert_array_equal(np.asarray(out_sc2.data),
                                  np.asarray(out_sc.data))
