"""Numeric forward + gradient checks for the conv/pool/norm op families
against torch-cpu (parity with reference tests/unittests/test_conv2d_op.py,
test_pool2d_op.py, test_batch_norm_op.py, ... which check against their own
numpy refs; torch is an independent oracle here)."""
import numpy as np
import pytest
import torch
import torch.nn.functional as F

import jax.numpy as jnp
import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers
from paddle_tpu.fluid.backward import append_backward
from paddle_tpu.fluid.executor import global_scope

from util import fresh_program

RTOL = 2e-4
ATOL = 2e-4


def _run_with_weights(build, feed=None, fetch_extra=(), weight_map=None):
    """Build a program, overwrite weights, run, return fetches as numpy.

    `build` receives no args and returns the output var(s); inputs that
    need gradients should be created with layers.create_parameter (grads
    exist only for Parameters — data vars are stop_gradient like the
    reference) and their values passed via weight_map.
    """
    with fresh_program() as (main, startup):
        outs = build()
        outs = outs if isinstance(outs, (list, tuple)) else [outs]
        loss = layers.reduce_sum(outs[0])
        append_backward(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        scope = global_scope()
        if weight_map:
            for pat, w in weight_map.items():
                names = [n for n in scope.vars if pat in n]
                assert names, (pat, list(scope.vars))
                scope.vars[names[0]] = jnp.asarray(w)
        res = exe.run(main, feed=feed or {},
                      fetch_list=list(outs) + [loss] + list(fetch_extra))
    return [np.asarray(r) for r in res]


def _param_input(name, value):
    return layers.create_parameter(shape=list(value.shape), dtype='float32',
                                   name=name)


# ---------------------------------------------------------------------------
# conv family
# ---------------------------------------------------------------------------

def test_conv2d_forward_and_grads_vs_torch():
    rng = np.random.RandomState(0)
    x = rng.rand(2, 3, 8, 8).astype('float32')
    w = (rng.rand(4, 3, 3, 3) * 0.2 - 0.1).astype('float32')

    def build():
        xv = _param_input('xin', x)
        return layers.conv2d(xv, num_filters=4, filter_size=3, stride=2,
                             padding=1, bias_attr=False)
    out, _, gx, gw = _run_with_weights(
        build, fetch_extra=['xin@GRAD', 'conv2d_0.w_0@GRAD'],
        weight_map={'xin': x, 'conv2d_0.w_0': w})

    tx = torch.tensor(x, requires_grad=True)
    tw = torch.tensor(w, requires_grad=True)
    ty = F.conv2d(tx, tw, stride=2, padding=1)
    ty.sum().backward()
    np.testing.assert_allclose(out, ty.detach().numpy(), rtol=RTOL, atol=ATOL)
    np.testing.assert_allclose(gx, tx.grad.numpy(), rtol=RTOL, atol=ATOL)
    np.testing.assert_allclose(gw, tw.grad.numpy(), rtol=RTOL, atol=ATOL)


def test_conv2d_groups_dilation_vs_torch():
    rng = np.random.RandomState(1)
    x = rng.rand(1, 4, 9, 9).astype('float32')
    w = (rng.rand(6, 2, 3, 3) * 0.2 - 0.1).astype('float32')  # groups=2

    def build():
        xv = layers.data(name='x', shape=[4, 9, 9], dtype='float32')
        return layers.conv2d(xv, num_filters=6, filter_size=3, groups=2,
                             dilation=2, bias_attr=False)
    out, _, gw = _run_with_weights(
        build, {'x': x}, fetch_extra=['conv2d_0.w_0@GRAD'],
        weight_map={'conv2d_0.w_0': w})
    tx = torch.tensor(x)
    tw = torch.tensor(w, requires_grad=True)
    ty = F.conv2d(tx, tw, groups=2, dilation=2)
    ty.sum().backward()
    np.testing.assert_allclose(out, ty.detach().numpy(), rtol=RTOL, atol=ATOL)
    np.testing.assert_allclose(gw, tw.grad.numpy(), rtol=RTOL, atol=ATOL)


def test_conv3d_forward_vs_torch():
    rng = np.random.RandomState(2)
    x = rng.rand(1, 2, 5, 6, 6).astype('float32')
    w = (rng.rand(3, 2, 3, 3, 3) * 0.2 - 0.1).astype('float32')

    def build():
        xv = layers.data(name='x', shape=[2, 5, 6, 6], dtype='float32')
        return layers.conv3d(xv, num_filters=3, filter_size=3, padding=1,
                             bias_attr=False)
    out = _run_with_weights(build, {'x': x},
                            weight_map={'conv3d_0.w_0': w})[0]
    ty = F.conv3d(torch.tensor(x), torch.tensor(w), padding=1)
    np.testing.assert_allclose(out, ty.numpy(), rtol=RTOL, atol=ATOL)


def test_conv2d_transpose_forward_and_grad_vs_torch():
    rng = np.random.RandomState(3)
    x = rng.rand(2, 3, 5, 5).astype('float32')
    w = (rng.rand(3, 4, 3, 3) * 0.2 - 0.1).astype('float32')  # [in, out, kh, kw]

    def build():
        xv = _param_input('xin', x)
        return layers.conv2d_transpose(xv, num_filters=4, filter_size=3,
                                       stride=2, padding=1, bias_attr=False)
    out, _, gx = _run_with_weights(
        build, fetch_extra=['xin@GRAD'],
        weight_map={'xin': x, 'conv2d_transpose_0.w_0': w})
    tx = torch.tensor(x, requires_grad=True)
    ty = F.conv_transpose2d(tx, torch.tensor(w), stride=2, padding=1)
    ty.sum().backward()
    np.testing.assert_allclose(out, ty.detach().numpy(), rtol=RTOL, atol=ATOL)
    np.testing.assert_allclose(gx, tx.grad.numpy(), rtol=RTOL, atol=ATOL)


def test_conv3d_transpose_forward_vs_torch():
    rng = np.random.RandomState(4)
    x = rng.rand(1, 2, 4, 4, 4).astype('float32')
    w = (rng.rand(2, 3, 3, 3, 3) * 0.2 - 0.1).astype('float32')

    def build():
        xv = layers.data(name='x', shape=[2, 4, 4, 4], dtype='float32')
        return layers.conv3d_transpose(xv, num_filters=3, filter_size=3,
                                       stride=1, padding=0, bias_attr=False)
    out = _run_with_weights(build, {'x': x},
                            weight_map={'conv3d_transpose_0.w_0': w})[0]
    ty = F.conv_transpose3d(torch.tensor(x), torch.tensor(w))
    np.testing.assert_allclose(out, ty.numpy(), rtol=RTOL, atol=ATOL)


# ---------------------------------------------------------------------------
# pool family
# ---------------------------------------------------------------------------

@pytest.mark.parametrize('ptype', ['max', 'avg'])
def test_pool2d_forward_and_grad_vs_torch(ptype):
    rng = np.random.RandomState(5)
    x = rng.rand(2, 3, 8, 8).astype('float32')

    def build():
        xv = _param_input('xin', x)
        return layers.pool2d(xv, pool_size=2, pool_type=ptype, pool_stride=2)
    out, _, gx = _run_with_weights(build, fetch_extra=['xin@GRAD'],
                                   weight_map={'xin': x})
    tx = torch.tensor(x, requires_grad=True)
    ty = (F.max_pool2d(tx, 2, 2) if ptype == 'max'
          else F.avg_pool2d(tx, 2, 2))
    ty.sum().backward()
    np.testing.assert_allclose(out, ty.detach().numpy(), rtol=RTOL, atol=ATOL)
    np.testing.assert_allclose(gx, tx.grad.numpy(), rtol=RTOL, atol=ATOL)


def test_pool2d_padding_and_global():
    rng = np.random.RandomState(6)
    x = rng.rand(1, 2, 6, 6).astype('float32')

    def build():
        xv = layers.data(name='x', shape=[2, 6, 6], dtype='float32')
        a = layers.pool2d(xv, pool_size=3, pool_type='avg', pool_stride=3,
                          pool_padding=0)
        g = layers.pool2d(xv, pool_size=1, pool_type='max',
                          global_pooling=True)
        return [a, g]
    with fresh_program() as (main, startup):
        xv = layers.data(name='x', shape=[2, 6, 6], dtype='float32')
        a = layers.pool2d(xv, pool_size=3, pool_type='avg', pool_stride=3)
        g = layers.pool2d(xv, pool_size=1, pool_type='max',
                          global_pooling=True)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        ra, rg = exe.run(main, feed={'x': x}, fetch_list=[a, g])
    np.testing.assert_allclose(np.asarray(ra),
                               F.avg_pool2d(torch.tensor(x), 3, 3).numpy(),
                               rtol=RTOL, atol=ATOL)
    np.testing.assert_allclose(np.asarray(rg).reshape(1, 2),
                               x.max(axis=(2, 3)), rtol=RTOL, atol=ATOL)


def test_pool3d_forward_vs_torch():
    rng = np.random.RandomState(7)
    x = rng.rand(1, 2, 4, 6, 6).astype('float32')
    with fresh_program() as (main, startup):
        xv = layers.data(name='x', shape=[2, 4, 6, 6], dtype='float32')
        y = layers.pool3d(xv, pool_size=2, pool_type='max', pool_stride=2)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        out, = exe.run(main, feed={'x': x}, fetch_list=[y])
    np.testing.assert_allclose(np.asarray(out),
                               F.max_pool3d(torch.tensor(x), 2, 2).numpy(),
                               rtol=RTOL, atol=ATOL)


# ---------------------------------------------------------------------------
# norm family
# ---------------------------------------------------------------------------

def test_batch_norm_train_stats_vs_torch():
    rng = np.random.RandomState(8)
    x = rng.rand(4, 3, 5, 5).astype('float32')
    with fresh_program() as (main, startup):
        xv = layers.data(name='x', shape=[3, 5, 5], dtype='float32')
        y = layers.batch_norm(xv, epsilon=1e-5, momentum=0.9,
                              moving_mean_name='mm', moving_variance_name='mv')
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        out, = exe.run(main, feed={'x': x}, fetch_list=[y])
        scope = global_scope()
        mm = np.asarray(scope.vars['mm'])
        mv = np.asarray(scope.vars['mv'])
    tb = torch.nn.BatchNorm2d(3, eps=1e-5, momentum=0.1)
    tb.train()
    ty = tb(torch.tensor(x))
    np.testing.assert_allclose(out, ty.detach().numpy(), rtol=1e-3, atol=1e-3)
    # running stats: ours new = old*momentum + batch*(1-momentum); torch
    # running_mean uses the same update with its momentum=1-ours
    np.testing.assert_allclose(mm, tb.running_mean.numpy(), rtol=1e-3,
                               atol=1e-4)


def test_batch_norm_grad_vs_torch():
    rng = np.random.RandomState(9)
    x = rng.rand(4, 3, 4, 4).astype('float32')
    with fresh_program() as (main, startup):
        xv = layers.create_parameter(shape=[4, 3, 4, 4], dtype='float32',
                                     name='xin')
        y = layers.batch_norm(xv)
        loss = layers.reduce_sum(layers.square(y))
        append_backward(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        global_scope().vars['xin'] = jnp.asarray(x)
        gx, = exe.run(main, feed={}, fetch_list=['xin@GRAD'])
    tx = torch.tensor(x, requires_grad=True)
    tb = torch.nn.BatchNorm2d(3)
    (tb(tx) ** 2).sum().backward()
    np.testing.assert_allclose(np.asarray(gx), tx.grad.numpy(), rtol=1e-3,
                               atol=1e-3)


def test_layer_norm_forward_and_grad_vs_torch():
    rng = np.random.RandomState(10)
    x = rng.rand(4, 12).astype('float32')
    with fresh_program() as (main, startup):
        xv = layers.create_parameter(shape=[4, 12], dtype='float32',
                                     name='xin')
        y = layers.layer_norm(xv, begin_norm_axis=1)
        loss = layers.reduce_sum(layers.square(y))
        append_backward(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        global_scope().vars['xin'] = jnp.asarray(x)
        out, gx = exe.run(main, feed={}, fetch_list=[y, 'xin@GRAD'])
    tx = torch.tensor(x, requires_grad=True)
    tl = torch.nn.LayerNorm(12)
    ty = tl(tx)
    (ty ** 2).sum().backward()
    np.testing.assert_allclose(np.asarray(out), ty.detach().numpy(),
                               rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(np.asarray(gx), tx.grad.numpy(), rtol=1e-3,
                               atol=1e-3)
