"""Sharded checkpoint save/restore on the 8-virtual-device CPU mesh.

Parity target: reference per-var save infra (io.py:468-690) scaled to
mesh-sharded state — no host gathers the full array (every shard file
holds exactly one device's piece) and shardings round-trip.
"""
import os

import numpy as np
import pytest

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.utils import checkpoint as ck


def _mesh(shape=(4, 2), axes=('dp', 'tp')):
    devs = np.asarray(jax.devices()[:int(np.prod(shape))]).reshape(shape)
    return Mesh(devs, axes)


def _state(mesh):
    w = jax.device_put(np.arange(64, dtype=np.float32).reshape(8, 8),
                       NamedSharding(mesh, P('dp', 'tp')))
    emb = jax.device_put(np.random.RandomState(0).rand(16, 4).astype('float32'),
                         NamedSharding(mesh, P(None, 'tp')))
    bias = jax.device_put(np.ones((8,), np.float32),
                          NamedSharding(mesh, P()))      # replicated
    step_arr = jax.device_put(np.float32(3.5),
                              NamedSharding(mesh, P()))  # scalar
    return {'fc_0.w_0': w, 'emb@table': emb, 'fc_0.b_0': bias,
            'lr': step_arr}


def test_round_trip_preserves_values_and_shardings(tmp_path):
    mesh = _mesh()
    state = _state(mesh)
    d = str(tmp_path / 'ck')
    ck.save_sharded(d, state, step=7, extra_meta={'note': 'r2'})
    got, meta = ck.load_sharded(d, mesh=mesh)
    assert meta['step'] == 7
    assert meta['extra'] == {'note': 'r2'}
    assert set(got) == set(state)
    for name in state:
        np.testing.assert_array_equal(np.asarray(got[name]),
                                      np.asarray(state[name]))
        assert got[name].sharding.spec == state[name].sharding.spec, name
        assert got[name].sharding.mesh.shape == state[name].sharding.mesh.shape


def test_no_shard_file_holds_the_full_sharded_array(tmp_path):
    """The point of sharded save: the fully-sharded array is written as 8
    per-device pieces, never one big file."""
    mesh = _mesh()
    state = _state(mesh)
    d = str(tmp_path / 'ck')
    ck.save_sharded(d, state, step=1)
    w_files = [f for f in os.listdir(d) if f.startswith('fc_0.w_0.p0.shard')]
    assert len(w_files) == 8          # 4x2 mesh, fully sharded
    for f in w_files:
        assert np.load(os.path.join(d, f)).shape == (2, 4)
    # replicated arrays dedupe to a single shard file
    b_files = [f for f in os.listdir(d) if f.startswith('fc_0.b_0.p0.shard')]
    assert len(b_files) == 1


def test_restore_without_mesh_rebuilds_from_manifest(tmp_path):
    mesh = _mesh()
    state = _state(mesh)
    d = str(tmp_path / 'ck')
    ck.save_sharded(d, state, step=2)
    got, _ = ck.load_sharded(d)           # mesh=None: rebuild from manifest
    for name in state:
        np.testing.assert_array_equal(np.asarray(got[name]),
                                      np.asarray(state[name]))
        assert got[name].sharding.spec == state[name].sharding.spec


def test_elastic_restore_onto_different_mesh(tmp_path):
    """A checkpoint saved on a 4x2 mesh restores onto a 2x2 mesh (values
    assembled from overlapping shards)."""
    mesh = _mesh((4, 2))
    state = _state(mesh)
    d = str(tmp_path / 'ck')
    ck.save_sharded(d, state, step=3)
    small = _mesh((2, 2))
    got, _ = ck.load_sharded(d, mesh=small)
    for name in state:
        np.testing.assert_array_equal(np.asarray(got[name]),
                                      np.asarray(state[name]))
        assert got[name].sharding.mesh.shape == {'dp': 2, 'tp': 2}


def test_missing_shard_detected_on_elastic_restore(tmp_path):
    """Elastic reassembly must raise on uncovered regions, never return
    uninitialized memory."""
    mesh = _mesh((4, 2))
    state = {'w': jax.device_put(
        np.arange(64, dtype=np.float32).reshape(8, 8),
        NamedSharding(mesh, P('dp', 'tp')))}
    d = str(tmp_path / 'ck')
    ck.save_sharded(d, state, step=1)
    victim = [f for f in os.listdir(d) if f.startswith('w.') and
              f.endswith('.npy')][0]
    os.remove(os.path.join(d, victim))
    small = _mesh((2, 2))
    with pytest.raises((RuntimeError, FileNotFoundError)):
        got, _ = ck.load_sharded(d, mesh=small)
        np.asarray(got['w'])  # force materialization


def test_shard_files_carry_process_index(tmp_path):
    """Filenames embed the process index so multi-host saves to a shared
    dir never collide."""
    mesh = _mesh((2, 2))
    d = str(tmp_path / 'ck')
    ck.save_sharded(d, _state(mesh), step=1)
    shard_files = [f for f in os.listdir(d) if f.endswith('.npy')]
    assert shard_files
    assert all('.p0.shard' in f for f in shard_files)


def test_latest_step(tmp_path):
    base = str(tmp_path)
    assert ck.latest_step(base) is None
    mesh = _mesh((2, 2))
    for s in (1, 5, 3):
        ck.save_sharded(os.path.join(base, 'sharded_%d' % s),
                        {'x': jax.device_put(np.zeros(4, np.float32),
                                             NamedSharding(mesh, P('dp')))},
                        step=s)
    assert ck.latest_step(base) == 5


def test_truncated_shard_file_raises_clear_error(tmp_path):
    """Corruption story: a truncated (partially-written) shard file fails
    restore with an error naming the file, not a cryptic numpy parse
    error (reference io.py load raises per-var the same way)."""
    mesh = _mesh((2, 2))
    d = str(tmp_path / 'ck')
    ck.save_sharded(d, _state(mesh), step=1)
    victim = sorted(f for f in os.listdir(d)
                    if f.startswith('fc_0.w') and f.endswith('.npy'))[0]
    path = os.path.join(d, victim)
    with open(path, 'r+b') as f:
        f.truncate(os.path.getsize(path) // 2)
    with pytest.raises(RuntimeError, match='truncated|corrupt'):
        got, _ = ck.load_sharded(d, mesh=mesh)
        np.asarray(got['fc_0.w_0'])  # make_array_from_callback is eager


def test_missing_shard_file_raises_clear_error(tmp_path):
    mesh = _mesh((2, 2))
    d = str(tmp_path / 'ck')
    ck.save_sharded(d, _state(mesh), step=1)
    victim = sorted(f for f in os.listdir(d)
                    if f.startswith('fc_0.w') and f.endswith('.npy'))[0]
    os.remove(os.path.join(d, victim))
    with pytest.raises(RuntimeError, match='missing'):
        got, _ = ck.load_sharded(d, mesh=mesh)
        np.asarray(got['fc_0.w_0'])


def test_async_save_round_trip_and_snapshot_semantics(tmp_path):
    """save_sharded_async: values are snapshotted BEFORE the handle
    returns (caller may donate/overwrite device buffers immediately);
    wait() commits; load matches the state at call time."""
    mesh = _mesh()
    state = _state(mesh)
    expect = {k: np.array(np.asarray(v), copy=True)
              for k, v in state.items()}
    d = str(tmp_path / 'async_ck')
    h = ck.save_sharded_async(d, state, step=9)
    # DONATE the saved buffers while the writer may still be running: the
    # snapshot must have copied, so the checkpoint holds the ORIGINAL
    # values even though XLA reuses the donated memory for the update
    bump = jax.jit(lambda t: jax.tree_util.tree_map(lambda a: a * 0 - 1, t),
                   donate_argnums=0)
    clobbered = bump(state)
    jax.block_until_ready(clobbered)
    assert h.wait() == d
    assert h.done()
    loaded, meta = ck.load_sharded(d, mesh=mesh)
    assert meta['step'] == 9
    for k, v in expect.items():
        np.testing.assert_array_equal(np.asarray(loaded[k]), v)
        assert loaded[k].sharding == clobbered[k].sharding


def test_async_save_error_surfaces_on_wait(tmp_path):
    """IO failures in the background writer re-raise from wait(), not
    silently vanish."""
    mesh = _mesh()
    state = _state(mesh)
    blocker = tmp_path / 'not_a_dir'
    blocker.write_text('file where the ckpt dir should go')
    h = ck.save_sharded_async(str(blocker), state, step=1)
    with pytest.raises(OSError):   # os.makedirs on a file path
        h.wait()


def test_async_manifest_commits_last(tmp_path):
    """After wait(), the manifest byte counts match the shard files — the
    corruption detector would catch any torn write."""
    mesh = _mesh()
    state = _state(mesh)
    d = str(tmp_path / 'async_ck2')
    ck.save_sharded_async(d, state, step=2).wait()
    import json as _json
    man = _json.load(open(os.path.join(d, 'manifest.json')))
    for entry in man['arrays'].values():
        for sh in entry['shards']:
            assert sh['bytes'] == os.path.getsize(
                os.path.join(d, sh['file']))


def test_async_save_rejects_overlapping_same_dir(tmp_path, monkeypatch):
    """A second async save to a dir with one in flight raises instead of
    interleaving identically-named shard files (round-4 advisor)."""
    import threading
    mesh = _mesh()
    state = _state(mesh)
    d = str(tmp_path / 'overlap_ck')
    gate = threading.Event()
    orig = ck._write_all

    def slow_write(*a, **kw):
        gate.wait(timeout=30)
        return orig(*a, **kw)

    monkeypatch.setattr(ck, '_write_all', slow_write)
    h = ck.save_sharded_async(d, state, step=1)
    try:
        with pytest.raises(RuntimeError, match='in flight'):
            ck.save_sharded_async(d, state, step=2)
    finally:
        gate.set()
        h.wait()
    # completed: the same dir is writable again
    ck.save_sharded_async(d, state, step=3).wait()


def test_async_save_warns_when_failure_unobserved(tmp_path):
    """Background write failures surface as a RuntimeWarning — but only
    once the handle is finalized without ever being wait()ed (round-4
    advisor: silent missing checkpoint; round-5 ADVICE: the warning must
    NOT fire eagerly from the pool thread while the caller can still
    wait() and observe the failure properly)."""
    import gc
    import warnings as _warnings
    mesh = _mesh()
    state = _state(mesh)
    blocker = tmp_path / 'not_a_dir2'
    blocker.write_text('file where the ckpt dir should go')
    with _warnings.catch_warnings(record=True) as rec:
        _warnings.simplefilter('always')
        h = ck.save_sharded_async(str(blocker), state, step=1)
        deadline = 30.0
        import time as _time
        while not h.done() and deadline > 0:
            _time.sleep(0.05)
            deadline -= 0.05
        assert h.done()
        # failure already happened, but the handle is still observable:
        # no warning yet
        assert not any('FAILED in the background' in str(w.message)
                       for w in rec)
        del h          # abandoned without wait(): NOW it must warn
        gc.collect()
    assert any(issubclass(w.category, RuntimeWarning)
               and 'FAILED in the background' in str(w.message)
               for w in rec)


def test_async_save_stays_silent_when_failure_observed(tmp_path):
    """wait() re-raises the background failure; an observed failure must
    not ALSO warn at finalization (round-5 ADVICE)."""
    import gc
    import warnings as _warnings
    mesh = _mesh()
    state = _state(mesh)
    blocker = tmp_path / 'not_a_dir3'
    blocker.write_text('file where the ckpt dir should go')
    with _warnings.catch_warnings(record=True) as rec:
        _warnings.simplefilter('always')
        h = ck.save_sharded_async(str(blocker), state, step=1)
        with pytest.raises(Exception):
            h.wait()
        del h
        gc.collect()
    assert not any('FAILED in the background' in str(w.message)
                   for w in rec)
