"""NHWC (channels-last) conv path: numerics must match NCHW with the
SAME OIHW weights — the layout switch is a pure performance knob."""
import numpy as np

import jax.numpy as jnp
import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers
from paddle_tpu.fluid.executor import global_scope
from paddle_tpu.models import resnet

from util import fresh_program


def _run_layout(data_format, x_nchw, build):
    with fresh_program() as (main, startup):
        out = build(data_format)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        params = {n: np.asarray(v) for n, v in global_scope().vars.items()}
        feed = x_nchw if data_format == 'NCHW' \
            else np.ascontiguousarray(x_nchw.transpose(0, 2, 3, 1))
        res, = exe.run(main, feed={'img': feed}, fetch_list=[out])
    return np.asarray(res), params


def test_conv_pool_bn_nhwc_matches_nchw():
    rng = np.random.RandomState(0)
    x = rng.rand(2, 3, 16, 16).astype('float32')

    def build(fmt):
        shape = [3, 16, 16] if fmt == 'NCHW' else [16, 16, 3]
        img = layers.data(name='img', shape=shape, dtype='float32')
        h = layers.conv2d(img, num_filters=4, filter_size=3, padding=1,
                          stride=2, data_format=fmt)
        h = layers.batch_norm(h, data_layout=fmt)
        h = layers.pool2d(h, pool_size=2, pool_type='max', pool_stride=2,
                          data_format=fmt)
        return h

    got_nchw, p1 = _run_layout('NCHW', x, build)
    got_nhwc, p2 = _run_layout('NHWC', x, build)
    assert got_nhwc.shape == (2, 4, 4, 4) and \
        np.isfinite(got_nhwc).all()
    # same param shapes (OIHW filters + per-channel bn) in both layouts
    assert {n: v.shape for n, v in p1.items()} == \
           {n: v.shape for n, v in p2.items()}
    # align params: re-run NHWC with NCHW's initialized weights
    with fresh_program() as (main, startup):
        out = build('NHWC')
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        sc = global_scope()
        for n, v in p1.items():
            sc.vars[n] = jnp.asarray(v)
        res, = exe.run(main, feed={
            'img': np.ascontiguousarray(x.transpose(0, 2, 3, 1))},
            fetch_list=[out])
    np.testing.assert_allclose(np.asarray(res).transpose(0, 3, 1, 2),
                               got_nchw, rtol=1e-4, atol=1e-5)


def test_nhwc_validation_and_bn_fold():
    import pytest
    with fresh_program() as (main, startup):
        img = layers.data(name='img', shape=[8, 8, 3], dtype='float32')
        with pytest.raises(ValueError, match='data_format'):
            layers.conv2d(img, num_filters=2, filter_size=3,
                          data_format='nhwc')
        with pytest.raises(ValueError, match='data_format'):
            layers.pool2d(img, pool_size=2, data_format='NWHC')

    # BN fold after an NHWC conv broadcasts the bias on the channel axis
    rng = np.random.RandomState(2)
    x = rng.rand(2, 3, 10, 10).astype('float32')
    with fresh_program() as (main, startup):
        img = layers.data(name='img', shape=[10, 10, 3], dtype='float32')
        h = layers.conv2d(img, num_filters=4, filter_size=3,
                          data_format='NHWC', bias_attr=False)
        h = layers.batch_norm(h, data_layout='NHWC', is_test=True)
        infer = main.clone(for_test=True)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        sc = global_scope()
        for n in list(sc.vars):  # non-trivial BN stats so the fold matters
            if n.endswith('.w_1'):
                sc.vars[n] = jnp.asarray(rng.rand(4).astype('float32'))
            elif n.endswith('.w_2'):
                sc.vars[n] = jnp.asarray(rng.rand(4).astype('float32') + .5)
        feed = {'img': np.ascontiguousarray(x.transpose(0, 2, 3, 1))}
        want, = exe.run(infer, feed=feed, fetch_list=[h])
        t = fluid.InferenceTranspiler()
        folded = t.transpile(infer, fluid.CPUPlace())
        got, = exe.run(folded, feed=feed, fetch_list=[h])
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-5)


def test_resnet18_nhwc_matches_nchw():
    rng = np.random.RandomState(1)
    x = rng.rand(2, 3, 32, 32).astype('float32')

    def run(fmt, params=None):
        with fresh_program() as (main, startup):
            shape = [3, 32, 32] if fmt == 'NCHW' else [32, 32, 3]
            img = layers.data(name='img', shape=shape, dtype='float32')
            out = resnet.resnet_imagenet(img, class_dim=10, depth=18,
                                         data_format=fmt)
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            sc = global_scope()
            if params is not None:
                for n, v in params.items():
                    sc.vars[n] = jnp.asarray(v)
            snap = {n: np.asarray(v) for n, v in sc.vars.items()}
            feed = x if fmt == 'NCHW' \
                else np.ascontiguousarray(x.transpose(0, 2, 3, 1))
            res, = exe.run(main, feed={'img': feed}, fetch_list=[out])
        return np.asarray(res), snap

    want, params = run('NCHW')
    got, _ = run('NHWC', params=params)
    # fp32 accumulation order differs per layout; over 18 conv layers the
    # softmax outputs drift ~1e-4 — identical math, different reductions
    np.testing.assert_allclose(got, want, rtol=5e-3, atol=2e-4)
    assert got.argmax(-1).tolist() == want.argmax(-1).tolist()
