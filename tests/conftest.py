"""Force a deterministic 8-virtual-device CPU platform for all tests."""
import os

os.environ.setdefault('JAX_PLATFORMS', 'cpu')
flags = os.environ.get('XLA_FLAGS', '')
if '--xla_force_host_platform_device_count' not in flags:
    os.environ['XLA_FLAGS'] = (
        flags + ' --xla_force_host_platform_device_count=8').strip()
