"""Force a deterministic 8-virtual-device CPU platform for all tests.

Note: this environment bakes in an `axon` TPU plugin that overrides
JAX_PLATFORMS env vars, so the switch must go through jax.config.
"""
import jax

jax.config.update('jax_platforms', 'cpu')
jax.config.update('jax_num_cpu_devices', 8)
