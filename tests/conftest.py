"""Force a deterministic 8-virtual-device CPU platform for all tests.

Note: this environment bakes in an `axon` TPU plugin that overrides
JAX_PLATFORMS env vars, so the switch must go through jax.config.
"""
import os

# jax < 0.5 has no jax_num_cpu_devices config; the XLA flag is the
# portable spelling and must be set before any backend initializes
# (importing this conftest happens before any test module imports jax).
_flags = os.environ.get('XLA_FLAGS', '')
if '--xla_force_host_platform_device_count' not in _flags:
    os.environ['XLA_FLAGS'] = (
        _flags + ' --xla_force_host_platform_device_count=8').strip()

import jax

jax.config.update('jax_platforms', 'cpu')
try:
    jax.config.update('jax_num_cpu_devices', 8)
except AttributeError:
    pass  # older jax: XLA_FLAGS above already forced 8 CPU devices

# ---------------------------------------------------------------------------
# slow-test tier: every test measured > 8s on one CPU core (pytest
# --durations) is marked `slow` here, centrally, so the fast tier
# (`pytest -m "not slow"`, < 10 min) stays usable as the inner-loop check
# while the full suite remains the nightly-style gate. Each entry's module
# keeps faster siblings in the fast tier, so every subsystem still gets
# default coverage. Re-measure with `pytest --durations=60` when adding
# heavyweight tests.
# ---------------------------------------------------------------------------
_SLOW_TESTS = {
    'test_flash_attention.py::test_ring_attention_flash_impl_matches_dense_and_full',
    'test_reference_book_compat.py::test_reference_image_classification_vgg_runs_verbatim',
    'test_reference_book_compat.py::test_reference_image_classification_resnet_runs_verbatim',
    'test_reference_book_compat.py::test_reference_rnn_encoder_decoder_runs_verbatim',
    'test_reference_book_compat.py::test_reference_label_semantic_roles_runs_verbatim',
    'test_reference_book_compat.py::test_reference_machine_translation_train_runs_verbatim',
    'test_reference_book_compat.py::test_reference_machine_translation_decode_runs_verbatim',
    'test_reference_book_compat.py::test_reference_recommender_system_runs_verbatim',
    'test_reference_book_compat.py::test_reference_word2vec_runs_verbatim',
    'test_reference_book_compat.py::test_reference_hl_recognize_digits_conv_runs_verbatim',
    'test_reference_book_compat.py::test_reference_hl_sentiment_conv_runs_verbatim',
    'test_reference_book_compat.py::test_reference_hl_sentiment_dynamic_rnn_runs_verbatim',
    'test_reference_book_compat.py::test_reference_hl_sentiment_stacked_lstm_runs_verbatim',
    'test_examples.py::test_parallelism_example',
    'test_fluid_benchmark.py::test_transformer_model_with_sequence_parallel',
    'test_parallel.py::test_dryrun_multichip',
    'test_parallel.py::test_three_way_composition_compiles_remat_free',
    'test_pipeline_fluid.py::test_pipeline_transformer_matches_sequential',
    'test_nhwc.py::test_resnet18_nhwc_matches_nchw',
    'test_pipeline_fluid.py::test_pipeline_multi_layer_stages',
    'test_sp_fluid.py::test_sp_and_pp_compose_with_amp',
    'test_sp_fluid.py::test_pp_sp_composition_matches_single_device',
    'test_sp_fluid.py::test_three_way_dp_pp_sp_composition',
    'test_sp_fluid.py::test_pp_sp_ulysses_strategy',
    'test_tp_fluid.py::test_dp_pp_tp_three_way_matches_single_device[pp_first]',
    'test_sp_fluid.py::test_sp_transformer_matches_single_device',
    'test_tp_fluid.py::test_dp_pp_tp_three_way_matches_single_device[tp_first]',
    'test_models.py::test_vgg_cifar10_step',
    'test_sp_fluid.py::test_sp_dp_composition_matches_single_device',
    'test_models.py::test_transformer_overfits_batch',
    'test_flash_attention.py::test_ulysses_attention_matches_full_and_ring',
    'test_sp_fluid.py::test_sp_ulysses_strategy_matches_single_device',
    'test_tp_fluid.py::test_dp_tp_matches_single_device',
    'test_flash_attention.py::test_ring_attention_matches_full',
    'test_ops_sampled.py::test_seq2seq_generation',
    'test_sp_fluid.py::test_three_way_dp_tp_sp_composition',
    'test_models.py::test_seq2seq_attention_step',
    'test_integration_stack.py::test_trainer_moe_amp_checkpoint_resume',
    'test_book_label_semantic_roles.py::test_label_semantic_roles_trains_and_decodes',
    'test_tp_fluid.py::test_tp_matches_single_device_and_actually_shards',
    'test_multihost.py::test_two_process_loopback_cluster',
    'test_fluid_benchmark.py::test_mnist_local_runs_and_learns',
    'test_ssd_integration.py::test_ssd_trains_and_infers',
    'test_models.py::test_resnet_cifar10_step',
    'test_fluid_benchmark.py::test_mnist_pserver_transpiled',
    'test_fluid_benchmark.py::test_mnist_parallel_chips',
    'test_tp_fluid.py::test_tp_with_zero_composes_dp_sharding',
    'test_models.py::test_deepfm_steps',
    'test_models.py::test_stacked_lstm_step',
    'test_fluid_benchmark.py::test_mnist_tensor_parallel_flag',
    'test_layers.py::test_conv_family_shapes',
    'test_models.py::test_understand_sentiment_steps',
    'test_flash_attention.py::test_causal_triangular_grid_3x3_forward_and_grads',
    'test_ops_sampled.py::test_nce_hsigmoid_layers_build_and_run',
    'test_book_recognize_digits.py::test_mnist_lenet_trains',
    'test_nhwc.py::test_conv_pool_bn_nhwc_matches_nchw',
    'test_examples.py::test_recognize_digits_example',
    'test_book_recommender_system.py::test_recommender_system_converges',
    'test_ops_sampled.py::test_nce_trains_down',
    'test_ops_nn.py::test_conv2d_forward_and_grads_vs_torch',
    'test_contrib.py::test_training_decoder_converges',
    'test_nets.py::test_scaled_dot_product_attention_fused_matches_chain',
    'test_pipeline_moe.py::test_moe_capacity_drops_overflow',
    'test_pipeline_moe.py::test_circular_schedule_matches_sequential',
    'test_pipeline_fluid.py::test_circular_pipeline_matches_sequential_training',
}


def pytest_collection_modifyitems(config, items):
    import pytest
    import warnings
    matched = set()
    for item in items:
        name = '%s::%s' % (item.path.name, item.name)
        if name in _SLOW_TESTS:
            matched.add(name)
            item.add_marker(pytest.mark.slow)
    # a renamed/deleted test would silently fall back into the fast tier;
    # surface stale entries at collection time (only when the whole suite
    # was collected — a -k/path-filtered run legitimately matches fewer)
    stale = _SLOW_TESTS - matched
    if stale and len(items) > 400:
        warnings.warn('stale _SLOW_TESTS entries (renamed/deleted?): %s'
                      % sorted(stale))
