"""The runnable book examples (examples/) execute end-to-end with tiny
step caps. Heavier chapters are exercised by their test_book_* siblings;
here the user-facing script surface itself is driven."""
import os
import sys

import numpy as np

EX = os.path.join(os.path.dirname(__file__), '..', 'examples')
sys.path.insert(0, EX)


def _run_example(mod_name, argv):
    import importlib
    old_argv = sys.argv
    sys.argv = [mod_name] + argv
    try:
        mod = importlib.import_module(mod_name)
        return mod.main()
    finally:
        sys.argv = old_argv
        # examples share this process's global scope/default programs;
        # drop whatever state (incl. mesh-placed arrays) the script left
        # so later tests' same-named vars don't collide with it. Never
        # mask the example's own exception with a cleanup failure.
        try:
            import common
            common.fresh_session()
        except Exception:
            pass


def test_fit_a_line_example(tmp_path):
    loss = _run_example('fit_a_line',
                        ['--epochs', '4', '--save_dir', str(tmp_path)])
    assert np.isfinite(loss) and loss < 100.0


def test_recognize_digits_example(tmp_path):
    acc = _run_example('recognize_digits',
                       ['--epochs', '1', '--steps', '20',
                        '--save_dir', str(tmp_path)])
    assert acc > 0.5


def test_word2vec_example(tmp_path):
    loss = _run_example('word2vec',
                        ['--epochs', '1', '--steps', '20',
                         '--save_dir', str(tmp_path)])
    assert np.isfinite(loss)


def test_high_level_api_example(tmp_path):
    pred = _run_example('high_level_api',
                        ['--epochs', '4', '--save_dir', str(tmp_path)])
    assert np.isfinite(pred)


def test_parallelism_example():
    loss = _run_example('parallelism', ['--steps', '2'])
    assert np.isfinite(loss)


def test_serving_example(tmp_path):
    pred = _run_example('serving', ['--requests', '32',
                                    '--save_dir', str(tmp_path)])
    assert np.isfinite(pred)


def test_sharded_recommender_example(tmp_path):
    loss = _run_example('sharded_recommender',
                        ['--steps', '4', '--bundle', '2',
                         '--requests', '2', '--save_dir', str(tmp_path)])
    assert np.isfinite(loss)
