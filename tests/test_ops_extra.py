"""Long-tail op rules vs numpy references (ops_impl/extra_ops.py — the
reference's C++-only operators, reached through generate_layer_fn like the
reference's own generated-layer mechanism)."""
import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid.layers.layer_function_generator import \
    generate_layer_fn
from paddle_tpu.fluid.layer_helper import LayerHelper

from util import fresh_program


def _run_op(op_type, feed_arrays, attrs=None, n_out=1, out_slots=None):
    """Build a one-op program via the registry and run it."""
    with fresh_program() as (main, startup):
        helper = LayerHelper(op_type)
        inputs = {}
        feed = {}
        for slot, arr in feed_arrays.items():
            v = fluid.layers.data(name='in_%s' % slot.lower(),
                                  shape=list(arr.shape[1:]),
                                  dtype=str(arr.dtype))
            inputs[slot] = [v]
            feed[v.name] = arr
        outs = []
        outputs = {}
        for s in (out_slots or ['Out'] * n_out):
            o = helper.create_variable_for_type_inference('float32')
            outputs.setdefault(s, []).append(o)
            outs.append(o)
        helper.append_op(type=op_type, inputs=inputs, outputs=outputs,
                         attrs=attrs or {})
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        res = exe.run(main, feed=feed, fetch_list=[o.name for o in outs])
    return [np.asarray(r) for r in res]


def test_sign_cumsum():
    x = np.array([[-2., 0., 3.], [1., -1., 4.]], 'float32')
    out, = _run_op('sign', {'X': x})
    np.testing.assert_array_equal(out, np.sign(x))

    c, = _run_op('cumsum', {'X': x}, attrs={'axis': 1})
    np.testing.assert_allclose(c, np.cumsum(x, axis=1))
    ce, = _run_op('cumsum', {'X': x}, attrs={'axis': 1, 'exclusive': True})
    np.testing.assert_allclose(ce, np.cumsum(x, 1) - x)
    cr, = _run_op('cumsum', {'X': x}, attrs={'axis': 1, 'reverse': True})
    np.testing.assert_allclose(cr, np.cumsum(x[:, ::-1], 1)[:, ::-1])


def test_norms_and_distance():
    rng = np.random.RandomState(0)
    x = rng.randn(3, 4).astype('float32')
    y = rng.randn(3, 4).astype('float32')
    out, = _run_op('l1_norm', {'X': x})
    np.testing.assert_allclose(out, [np.abs(x).sum()], rtol=1e-6)
    out, = _run_op('squared_l2_norm', {'X': x})
    np.testing.assert_allclose(out, [(x ** 2).sum()], rtol=1e-6)
    d, sub = _run_op('squared_l2_distance', {'X': x, 'Y': y},
                     out_slots=['Out', 'sub_result'])
    np.testing.assert_allclose(d, ((x - y) ** 2).sum(1, keepdims=True),
                               rtol=1e-5)
    np.testing.assert_allclose(sub, x - y, rtol=1e-6)
    o, n = _run_op('norm', {'X': x}, attrs={'axis': 1, 'epsilon': 1e-10},
                   out_slots=['Out', 'Norm'])
    want_norm = np.sqrt((x ** 2).sum(1, keepdims=True) + 1e-10)
    np.testing.assert_allclose(o, x / want_norm, rtol=1e-5)
    np.testing.assert_allclose(n, want_norm, rtol=1e-5)


def test_simple_elementwise():
    rng = np.random.RandomState(1)
    x = rng.randn(2, 3).astype('float32')
    y = rng.randn(2, 3).astype('float32')
    out, = _run_op('minus', {'X': x, 'Y': y})
    np.testing.assert_allclose(out, x - y, rtol=1e-6)
    z, = _run_op('fill_zeros_like', {'X': x})
    np.testing.assert_array_equal(z, np.zeros_like(x))


def test_fill():
    out, = _run_op('fill', {}, attrs={'shape': [2, 3],
                                      'value': [1, 2, 3, 4, 5, 6],
                                      'dtype': 'float32'})
    np.testing.assert_allclose(out, np.arange(1, 7, dtype='float32')
                               .reshape(2, 3))


def test_loss_family():
    rng = np.random.RandomState(2)
    p = rng.uniform(0.05, 0.95, (4, 1)).astype('float32')
    y = (rng.rand(4, 1) > 0.5).astype('float32')
    eps = 1e-4
    out, = _run_op('log_loss', {'Predicted': p, 'Labels': y},
                   attrs={'epsilon': eps}, out_slots=['Loss'])
    want = -y * np.log(p + eps) - (1 - y) * np.log(1 - p + eps)
    np.testing.assert_allclose(out, want, rtol=1e-5)

    logits = rng.randn(4, 1).astype('float32')
    out, = _run_op('hinge_loss', {'Logits': logits, 'Labels': y},
                   out_slots=['Loss'])
    np.testing.assert_allclose(
        out, np.maximum(0, 1 - (2 * y - 1) * logits), rtol=1e-5)

    x1 = rng.randn(4, 1).astype('float32')
    x2 = rng.randn(4, 1).astype('float32')
    lbl = np.where(rng.rand(4, 1) > 0.5, 1.0, -1.0).astype('float32')
    out, act = _run_op('margin_rank_loss',
                       {'Label': lbl, 'X1': x1, 'X2': x2},
                       attrs={'margin': 0.1},
                       out_slots=['Out', 'Activated'])
    want = np.maximum(0, -lbl * (x1 - x2) + 0.1)
    np.testing.assert_allclose(out, want, rtol=1e-5)

    xh = rng.randn(4, 1).astype('float32')
    out, inter = _run_op('modified_huber_loss', {'X': xh, 'Y': y},
                         out_slots=['Out', 'IntermediateVal'])
    z = xh * (2 * y - 1)
    want = np.where(z >= -1, np.maximum(0, 1 - z) ** 2, -4 * z)
    np.testing.assert_allclose(out, want, rtol=1e-5)


def test_sampling_id_distribution():
    # a peaked distribution must mostly sample its mode
    p = np.tile(np.array([[0.01, 0.01, 0.97, 0.01]], 'float32'), (64, 1))
    out, = _run_op('sampling_id', {'X': p})
    assert out.shape == (64,)
    assert (out == 2).mean() > 0.8


def test_conv_shift():
    rng = np.random.RandomState(3)
    x = rng.randn(2, 6).astype('float32')
    y = rng.randn(2, 3).astype('float32')
    out, = _run_op('conv_shift', {'X': x, 'Y': y})
    n, m = 6, 3
    want = np.zeros_like(x)
    for b in range(2):
        for j in range(n):
            for k in range(m):
                want[b, j] += x[b, (j + k - m // 2) % n] * y[b, k]
    np.testing.assert_allclose(out, want, rtol=1e-5)


def test_generate_layer_fn_reaches_extra_ops():
    """The reference's generated-layer mechanism exposes these ops."""
    sign = generate_layer_fn('sign')
    with fresh_program() as (main, startup):
        x = fluid.layers.data(name='x', shape=[3], dtype='float32')
        s = sign(x)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        out, = exe.run(main, feed={'x': np.array([[-1., 0., 5.]],
                                                 'float32')},
                       fetch_list=[s.name])
    np.testing.assert_array_equal(out, [[-1., 0., 1.]])


def _seq(data, lens):
    """Build a feed LoDTensor from padded [B, T] data + lengths."""
    from paddle_tpu.fluid.lod_tensor import create_lod_tensor
    flat = []
    for row, l in zip(data, lens):
        flat.extend(row[:l])
    arr = np.asarray(flat).reshape(-1, *np.asarray(data).shape[2:]) \
        if np.asarray(data).ndim > 2 else np.asarray(flat).reshape(-1, 1)
    return create_lod_tensor(arr, [list(lens)], fluid.CPUPlace())


def test_bilinear_tensor_product():
    rng = np.random.RandomState(4)
    x = rng.randn(3, 4).astype('float32')
    y = rng.randn(3, 5).astype('float32')
    w = rng.randn(2, 4, 5).astype('float32')
    out, = _run_op('bilinear_tensor_product',
                   {'X': x, 'Y': y, 'Weight': w})
    want = np.einsum('bi,kij,bj->bk', x, w, y)
    np.testing.assert_allclose(out, want, rtol=1e-4)


def test_sequence_concat():
    with fresh_program() as (main, startup):
        a = fluid.layers.data(name='a', shape=[1], dtype='float32',
                              lod_level=1)
        b = fluid.layers.data(name='b', shape=[1], dtype='float32',
                              lod_level=1)
        helper = LayerHelper('sequence_concat')
        out = helper.create_variable_for_type_inference('float32')
        out.lod_level = 1
        helper.append_op(type='sequence_concat', inputs={'X': [a, b]},
                         outputs={'Out': [out]}, attrs={})
        pooled = fluid.layers.sequence_pool(out, pool_type='sum')
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        fa = _seq([[1., 2., 0.], [5., 0., 0.]], [2, 1])
        fb = _seq([[10., 0., 0.], [20., 30., 0.]], [1, 2])
        res, = exe.run(main, feed={'a': fa, 'b': fb},
                       fetch_list=[pooled])
    # row0: 1+2+10, row1: 5+20+30
    np.testing.assert_allclose(np.asarray(res).reshape(-1), [13., 55.])


def test_sequence_slice():
    with fresh_program() as (main, startup):
        x = fluid.layers.data(name='x', shape=[1], dtype='float32',
                              lod_level=1)
        off = fluid.layers.data(name='off', shape=[1], dtype='int64')
        ln = fluid.layers.data(name='ln', shape=[1], dtype='int64')
        helper = LayerHelper('sequence_slice')
        out = helper.create_variable_for_type_inference('float32')
        out.lod_level = 1
        helper.append_op(type='sequence_slice',
                         inputs={'X': [x], 'Offset': [off],
                                 'Length': [ln]},
                         outputs={'Out': [out]}, attrs={})
        pooled = fluid.layers.sequence_pool(out, pool_type='sum')
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        fx = _seq([[1., 2., 3., 4.], [5., 6., 7., 0.]], [4, 3])
        res, = exe.run(main, feed={
            'x': fx, 'off': np.array([[1], [0]], 'int64'),
            'ln': np.array([[2], [1]], 'int64')}, fetch_list=[pooled])
    # row0: x[1:3] = 2+3; row1: x[0:1] = 5
    np.testing.assert_allclose(np.asarray(res).reshape(-1), [5., 5.])


def test_sequence_erase():
    with fresh_program() as (main, startup):
        x = fluid.layers.data(name='x', shape=[1], dtype='int64',
                              lod_level=1)
        helper = LayerHelper('sequence_erase')
        out = helper.create_variable_for_type_inference('int64')
        out.lod_level = 1
        helper.append_op(type='sequence_erase', inputs={'X': [x]},
                         outputs={'Out': [out]},
                         attrs={'tokens': [2, 5]})
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        fx = _seq([[1, 2, 3, 2], [5, 5, 9, 0]], [4, 3])
        res, = exe.run(main, feed={'x': fx}, fetch_list=[out],
                       return_numpy=False)
    lt = res[0] if isinstance(res, (list, tuple)) else res
    assert lt.recursive_sequence_lengths() == [[2, 1]]
    np.testing.assert_array_equal(
        np.asarray(lt.data).reshape(-1)[:3], [1, 3, 9])


def test_proximal_rules():
    p = np.array([[1.0, -2.0]], 'float32')
    g = np.array([[0.5, 0.5]], 'float32')
    lr = np.array([0.1], 'float32')
    out, = _run_op('proximal_gd',
                   {'Param': p, 'Grad': g, 'LearningRate': lr},
                   attrs={'l1': 0.1, 'l2': 0.2},
                   out_slots=['ParamOut'])
    z = p - 0.1 * g
    want = np.sign(z) * np.maximum(np.abs(z) - 0.1 * 0.1, 0) / (1 + 0.1 * 0.2)
    np.testing.assert_allclose(out, want, rtol=1e-5)

    m = np.array([[0.4, 0.4]], 'float32')
    out, mout = _run_op('proximal_adagrad',
                        {'Param': p, 'Grad': g, 'Moment': m,
                         'LearningRate': lr},
                        attrs={'l1': 0.1, 'l2': 0.2},
                        out_slots=['ParamOut', 'MomentOut'])
    m2 = m + g * g
    # gradient step uses the adaptive lr; the shrinkage the PLAIN lr
    z = p - 0.1 / np.sqrt(m2) * g
    want = np.sign(z) * np.maximum(np.abs(z) - 0.1 * 0.1, 0) / (1 + 0.1 * 0.2)
    np.testing.assert_allclose(out, want, rtol=1e-5)
    np.testing.assert_allclose(mout, m2, rtol=1e-6)


def test_max_pool_with_index_and_unpool():
    rng = np.random.RandomState(5)
    x = rng.randn(1, 1, 4, 4).astype('float32')
    out, mask = _run_op('max_pool2d_with_index', {'X': x},
                        attrs={'ksize': [2, 2], 'strides': [2, 2],
                               'paddings': [0, 0]},
                        out_slots=['Out', 'Mask'])
    # forward max matches plain pooling
    want = x.reshape(1, 1, 2, 2, 2, 2).max(axis=(3, 5))
    np.testing.assert_allclose(out, want, rtol=1e-6)
    # each index points at the max element of its window
    flat = x.reshape(-1)
    np.testing.assert_allclose(flat[mask.reshape(-1).astype(int)],
                               out.reshape(-1), rtol=1e-6)

    # unpool scatters the pooled values back to their positions
    with fresh_program() as (main, startup):
        xo = fluid.layers.data(name='xo', shape=[1, 2, 2],
                               dtype='float32')
        mi = fluid.layers.data(name='mi', shape=[1, 2, 2], dtype='int32')
        helper = LayerHelper('unpool')
        o = helper.create_variable_for_type_inference('float32')
        # no output_size: dims derive from ksize/strides/paddings
        # like the reference InferShape
        helper.append_op(type='unpool',
                         inputs={'X': [xo], 'Indices': [mi]},
                         outputs={'Out': [o]},
                         attrs={'unpooling_type': 'max',
                                'ksize': [2, 2], 'strides': [2, 2],
                                'paddings': [0, 0]})
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        up, = exe.run(main, feed={'xo': out, 'mi': mask},
                      fetch_list=[o])
    up = np.asarray(up)
    assert up.shape == (1, 1, 4, 4)
    # the max positions carry the values; everything else is zero
    np.testing.assert_allclose(
        up.reshape(-1)[mask.reshape(-1).astype(int)],
        out.reshape(-1), rtol=1e-6)
    assert (up != 0).sum() == (out != 0).sum()
    np.testing.assert_allclose(up.sum(), out.sum(), rtol=1e-5)


def test_spp_pyramid():
    rng = np.random.RandomState(6)
    x = rng.randn(2, 3, 8, 8).astype('float32')
    out, = _run_op('spp', {'X': x},
                   attrs={'pyramid_height': 2, 'pooling_type': 'max'})
    # (4^2-1)/3 = 5 bins x 3 channels
    assert out.shape == (2, 15)
    np.testing.assert_allclose(out[:, :3], x.max(axis=(2, 3)), rtol=1e-6)
    # level 1 flattens CHANNEL-major (reference spp_op.h layout):
    # cols 3..6 are channel 0's 2x2 bin maxes, first of which is the
    # top-left 4x4 quadrant
    quad = x.reshape(2, 3, 2, 4, 2, 4).max(axis=(3, 5))  # [N,C,2,2]
    np.testing.assert_allclose(out[:, 3:], quad.reshape(2, -1), rtol=1e-6)
    np.testing.assert_allclose(out[:, 3], x[:, 0, :4, :4].max(axis=(1, 2)),
                               rtol=1e-6)

    # avg pooling divides by the full kernel area (0.14 semantics)
    oa, = _run_op('spp', {'X': x},
                  attrs={'pyramid_height': 1, 'pooling_type': 'avg'})
    np.testing.assert_allclose(oa, x.mean(axis=(2, 3)), rtol=1e-5)

    # non-divisible size: reference kernel/pad schedule (H=7, level 1:
    # kernel 4, pad 1 -> windows rows -1..2 / 3..6)
    x7 = rng.randn(1, 1, 7, 7).astype('float32')
    o7, = _run_op('spp', {'X': x7},
                  attrs={'pyramid_height': 2, 'pooling_type': 'max'})
    assert o7.shape == (1, 5)
    np.testing.assert_allclose(o7[0, 1], x7[0, 0, :3, :3].max(), rtol=1e-6)


def test_positive_negative_pair():
    # query 1: scores [3,1] labels [1,0] -> pos pair
    # query 2: scores [1,2] labels [1,0] -> neg pair; tie pair neutral
    score = np.array([[3.], [1.], [1.], [2.], [5.], [5.]], 'float32')
    label = np.array([[1.], [0.], [1.], [0.], [1.], [0.]], 'float32')
    query = np.array([[1], [1], [2], [2], [3], [3]], 'int64')
    pos, neg, neu = _run_op(
        'positive_negative_pair',
        {'Score': score, 'Label': label, 'QueryID': query},
        attrs={'column': -1},
        out_slots=['PositivePair', 'NegativePair', 'NeutralPair'])
    assert float(pos[0]) == 1.0 and float(neg[0]) == 1.0 and float(neu[0]) == 1.0

    # accumulators chain
    pos2, neg2, neu2 = _run_op(
        'positive_negative_pair',
        {'Score': score, 'Label': label, 'QueryID': query,
         'AccumulatePositivePair': np.array([10.], 'float32'),
         'AccumulateNegativePair': np.array([20.], 'float32'),
         'AccumulateNeutralPair': np.array([30.], 'float32')},
        attrs={'column': -1},
        out_slots=['PositivePair', 'NegativePair', 'NeutralPair'])
    assert float(pos2[0]) == 11.0 and float(neg2[0]) == 21.0 and float(neu2[0]) == 31.0


def test_precision_recall():
    # 2 classes; preds [0,0,1,1], labels [0,1,1,1]
    idx = np.array([[0], [0], [1], [1]], 'int32')
    lbl = np.array([[0], [1], [1], [1]], 'int32')
    batch, accum, states = _run_op(
        'precision_recall', {'Indices': idx, 'Labels': lbl},
        attrs={'class_number': 2},
        out_slots=['BatchMetrics', 'AccumMetrics', 'AccumStatesInfo'])
    # class0: tp=1 fp=1 fn=0; class1: tp=2 fp=0 fn=1
    np.testing.assert_allclose(states[0], [1, 1, 2, 0], atol=1e-6)
    np.testing.assert_allclose(states[1], [2, 0, 1, 1], atol=1e-6)
    macro_p = (1 / 2 + 2 / 2) / 2
    macro_r = (1 / 1 + 2 / 3) / 2
    np.testing.assert_allclose(batch[0], macro_p, rtol=1e-5)
    np.testing.assert_allclose(batch[1], macro_r, rtol=1e-5)
    # macro F1 is F1 OF the averaged p/r (reference CalcF1Score)
    np.testing.assert_allclose(
        batch[2], 2 * macro_p * macro_r / (macro_p + macro_r), rtol=1e-5)
    # micro: tp=3 fp=1 fn=1
    np.testing.assert_allclose(batch[3], 3 / 4, rtol=1e-5)
    np.testing.assert_allclose(batch[4], 3 / 4, rtol=1e-5)
    np.testing.assert_allclose(batch[5], 3 / 4, rtol=1e-5)

    # an absent class contributes 1.0 to macro precision/recall
    b3, _, _ = _run_op(
        'precision_recall', {'Indices': idx, 'Labels': lbl},
        attrs={'class_number': 3},
        out_slots=['BatchMetrics', 'AccumMetrics', 'AccumStatesInfo'])
    np.testing.assert_allclose(b3[0], (1 / 2 + 1 + 1) / 3, rtol=1e-5)

    # chaining states doubles the counts
    _, accum2, states2 = _run_op(
        'precision_recall', {'Indices': idx, 'Labels': lbl,
                             'StatesInfo': states},
        attrs={'class_number': 2},
        out_slots=['BatchMetrics', 'AccumMetrics', 'AccumStatesInfo'])
    np.testing.assert_allclose(states2, states * 2, atol=1e-6)


def test_fake_quantize_roundtrip():
    rng = np.random.RandomState(7)
    x = rng.randn(4, 6).astype('float32')
    out, scale = _run_op('fake_quantize', {'X': x},
                         attrs={'bit_length': 8,
                                'quantize_type': 'abs_max'},
                         out_slots=['Out', 'OutMovingScale'])
    s = np.abs(x).max()
    q = x / s * 127
    want = np.sign(q) * np.floor(np.abs(q) + 0.5)  # half-away-from-zero
    np.testing.assert_allclose(out, want, atol=1e-5)
    np.testing.assert_allclose(scale, [s], rtol=1e-6)

    deq, = _run_op('fake_dequantize_max_abs',
                   {'X': out.astype('float32'),
                    'Scale': np.array([s], 'float32')},
                   attrs={'num_bits': 8})
    # quantize->dequantize reproduces x within one quantization step
    assert np.abs(deq - x).max() <= s / 127 * 0.5 + 1e-6


def test_mine_hard_examples():
    # image 0: 1 pos (prior 0), ratio 2 -> up to 2 negs from candidates
    cls = np.array([[0.1, 0.9, 0.5, 0.8, 0.2]], 'float32')
    match = np.array([[3, -1, -1, -1, -1]], 'int32')
    dist = np.array([[0.9, 0.1, 0.2, 0.1, 0.6]], 'float32')
    with fresh_program() as (main, startup):
        c = fluid.layers.data(name='c', shape=[5], dtype='float32')
        m = fluid.layers.data(name='m', shape=[5], dtype='int32')
        d = fluid.layers.data(name='d', shape=[5], dtype='float32')
        helper = LayerHelper('mine_hard_examples')
        neg = helper.create_variable_for_type_inference('int32')
        neg.lod_level = 1
        upd = helper.create_variable_for_type_inference('int32')
        helper.append_op(type='mine_hard_examples',
                         inputs={'ClsLoss': [c], 'MatchIndices': [m],
                                 'MatchDist': [d]},
                         outputs={'NegIndices': [neg],
                                  'UpdatedMatchIndices': [upd]},
                         attrs={'mining_type': 'max_negative',
                                'neg_pos_ratio': 2.0,
                                'neg_dist_threshold': 0.5})
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        res, u = exe.run(main, feed={'c': cls, 'm': match, 'd': dist},
                         fetch_list=[neg, upd], return_numpy=False)
    # candidates: priors 1,2,3 (dist<0.5, unmatched); prior 4 excluded
    # (dist 0.6); top-2 by loss among candidates: priors 1 (0.9), 3 (0.8)
    assert res.recursive_sequence_lengths() == [[2]]
    np.testing.assert_array_equal(
        np.asarray(res.data).reshape(-1)[:2], [1, 3])
    np.testing.assert_array_equal(np.asarray(u), match)


def test_sign_cumsum_named_layers():
    """fluid.layers.sign / fluid.layers.cumsum named wrappers."""
    with fresh_program() as (main, startup):
        x = fluid.layers.data(name='x', shape=[3], dtype='float32')
        s = fluid.layers.sign(x)
        c = fluid.layers.cumsum(x, axis=1, reverse=True)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        o1, o2 = exe.run(main,
                         feed={'x': np.array([[-2., 0., 5.]], 'float32')},
                         fetch_list=[s, c])
    np.testing.assert_array_equal(o1, [[-1., 0., 1.]])
    np.testing.assert_allclose(o2, [[3., 5., 5.]])
