"""Inference deployment: Predictor (program bundle) + compiled StableHLO
artifact (jax.export). Parity: reference inference/api tests + capi."""
import threading

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
import paddle_tpu.fluid.layers as layers
from paddle_tpu import inference

from util import fresh_program


def _build_and_save(tmpdir, compiled=False):
    with fresh_program() as (main, startup):
        x = layers.data(name='x', shape=[8])
        y = layers.data(name='y', shape=[1])
        h = layers.fc(input=x, size=16, act='relu')
        pred = layers.fc(input=h, size=1)
        loss = layers.mean(layers.square_error_cost(input=pred, label=y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        xv = np.random.RandomState(0).rand(4, 8).astype('float32')
        yv = xv.sum(1, keepdims=True).astype('float32')
        exe.run(main, feed={'x': xv, 'y': yv}, fetch_list=[loss])
        fluid.io.save_inference_model(str(tmpdir), ['x'], [pred], exe,
                                      main_program=main)
        if compiled:
            inference.export_compiled(str(tmpdir), {'x': xv}, [pred], exe,
                                      main_program=main)
        want, = exe.run(main.clone(for_test=True).prune([pred]),
                        feed={'x': xv}, fetch_list=[pred])
        return xv, want


def test_predictor_matches_training_graph(tmp_path):
    xv, want = _build_and_save(tmp_path)
    p = inference.Predictor(str(tmp_path), place=fluid.CPUPlace())
    assert p.feed_names == ['x']
    got, = p.run({'x': xv})
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_predictor_concurrent_threads_no_global_scope_race(tmp_path):
    """Two Predictors over DIFFERENT weights running on different threads
    must not race on the process-global scope: each run passes its
    private scope explicitly through Executor.run(scope=...) (the old
    scope_guard entry mutated the global and corrupted concurrent
    runs). Regression test for the serving PR's thread-safety fix."""
    from paddle_tpu.fluid.executor import global_scope
    dirs, wants = [], []
    xv = np.random.RandomState(0).rand(4, 8).astype('float32')
    for k in range(2):
        d = tmp_path / ('m%d' % k)
        with fresh_program() as (main, startup):
            x = layers.data(name='x', shape=[8])
            pred = layers.fc(
                input=x, size=1,
                param_attr=fluid.ParamAttr(
                    initializer=fluid.initializer.Constant(float(k + 1))))
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            fluid.io.save_inference_model(str(d), ['x'], [pred], exe,
                                          main_program=main)
            want, = exe.run(main.clone(for_test=True).prune([pred]),
                            feed={'x': xv}, fetch_list=[pred])
        dirs.append(str(d))
        wants.append(want)
    base_scope = global_scope()
    preds = [inference.Predictor(d, place=fluid.CPUPlace()) for d in dirs]
    errors = []

    def hammer(k):
        try:
            for _ in range(20):
                got, = preds[k].run({'x': xv})
                np.testing.assert_allclose(got, wants[k], rtol=1e-5,
                                           atol=1e-6)
        except Exception as e:  # noqa: BLE001 — surface in the main thread
            errors.append((k, e))

    ts = [threading.Thread(target=hammer, args=(k,)) for k in (0, 1, 0, 1)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(60)
    assert errors == []
    # the predictors' private vars never leaked into the global scope
    assert global_scope() is base_scope
    assert all(n not in base_scope.vars for p in preds
               for n in p._scope.vars)


def test_compiled_artifact_round_trip(tmp_path):
    xv, want = _build_and_save(tmp_path, compiled=True)
    run = inference.load_compiled(str(tmp_path))
    assert run.feed_names == ['x']
    got, = run({'x': xv})
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_compiled_artifact_validates_feeds(tmp_path):
    """load_compiled checks names/dtypes/shapes against the exported
    meta and names the offending input, instead of failing deep inside
    exported.call."""
    xv, want = _build_and_save(tmp_path, compiled=True)
    run = inference.load_compiled(str(tmp_path))
    assert run.input_spec == {'x': ((4, 8), 'float32')}
    with pytest.raises(ValueError, match="missing input.*'x'"):
        run({})
    with pytest.raises(ValueError, match="unknown input.*'bogus'"):
        run({'x': xv, 'bogus': xv})
    with pytest.raises(ValueError, match="input 'x'.*shape.*exported"):
        run({'x': xv[:2]})
    with pytest.raises(ValueError, match="input 'x'.*dtype"):
        run({'x': xv.astype('int32')})
    # same-kind narrowing stays accepted (float64 fed what was exported
    # as float32 — the narrowing jnp.asarray always applied)
    got, = run({'x': xv.astype('float64')})
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_compiled_artifact_sequence_model(tmp_path):
    # lod (sequence) input path through export_compiled
    with fresh_program() as (main, startup):
        words = layers.data(name='words', shape=[1], dtype='int64',
                            lod_level=1)
        emb = layers.embedding(input=words, size=[30, 8])
        pooled = layers.sequence_pool(input=emb, pool_type='average')
        pred = layers.fc(input=pooled, size=3, act='softmax')
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        ids = np.random.RandomState(1).randint(0, 30, size=(2, 5, 1)).astype('int64')
        inference.export_compiled(str(tmp_path), {'words': ids}, [pred], exe,
                                  main_program=main)
        want, = exe.run(main.clone(for_test=True).prune([pred]),
                        feed={'words': ids}, fetch_list=[pred])
    run = inference.load_compiled(str(tmp_path))
    got, = run({'words': ids})
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_export_compiled_from_tp_transpiled_program(tmp_path):
    """StableHLO export round-trips from a mesh-transpiled (tp=2) training
    program: the pruned inference graph loads and runs frameworkless."""
    from paddle_tpu import inference
    with fresh_program() as (main, startup):
        x = fluid.layers.data(name='x', shape=[8], dtype='float32')
        y = fluid.layers.data(name='y', shape=[1], dtype='float32')
        h = layers.fc(input=x, size=8, act='tanh')
        pred = layers.fc(input=h, size=1)
        cost = fluid.layers.mean(
            fluid.layers.square_error_cost(input=pred, label=y))
        fluid.optimizer.SGD(learning_rate=0.05).minimize(cost)
        fluid.TensorParallelTranspiler(tp=2).transpile(main)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        xs = np.random.RandomState(0).rand(4, 8).astype('float32')
        exe.run(main, feed={'x': xs, 'y': np.zeros((4, 1), 'float32')},
                fetch_list=[cost])
        want, = exe.run(main.clone(for_test=True),
                        feed={'x': xs, 'y': np.zeros((4, 1), 'float32')},
                        fetch_list=[pred])
        d = str(tmp_path / 'hlo')
        inference.export_compiled(d, {'x': xs}, [pred], exe,
                                  main_program=main)
        fn = inference.load_compiled(d)
        got = fn({'x': xs})
    np.testing.assert_allclose(np.asarray(got[0]), np.asarray(want),
                               rtol=1e-5, atol=1e-6)
