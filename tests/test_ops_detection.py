"""Detection op family: matching, NMS, fused SSD loss, RPN targets, mAP.
Mirrors reference unittests test_bipartite_match_op / test_multiclass_nms_op
/ test_ssd_loss / test_rpn_target_assign_op / test_detection_map_op."""
import numpy as np
import pytest

import paddle_tpu.fluid as fluid
import paddle_tpu.fluid.layers as layers
from paddle_tpu.fluid.layers import detection

from util import fresh_program


def _run(main, startup, feed, fetch):
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    return exe.run(main, feed=feed, fetch_list=fetch)


def _np_iou(a, b):
    inter_w = np.maximum(np.minimum(a[:, None, 2], b[None, :, 2]) -
                         np.maximum(a[:, None, 0], b[None, :, 0]), 0)
    inter_h = np.maximum(np.minimum(a[:, None, 3], b[None, :, 3]) -
                         np.maximum(a[:, None, 1], b[None, :, 1]), 0)
    inter = inter_w * inter_h
    aa = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
    bb = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    union = aa[:, None] + bb[None, :] - inter
    return np.where(union > 0, inter / np.maximum(union, 1e-10), 0)


def test_iou_similarity():
    r = np.random.RandomState(0)
    x = np.sort(r.rand(5, 4).astype('float32'), -1)
    y = np.sort(r.rand(7, 4).astype('float32'), -1)
    with fresh_program() as (main, startup):
        xv = layers.data(name='x', shape=[5, 4], append_batch_size=False)
        yv = layers.data(name='y', shape=[7, 4], append_batch_size=False)
        out = detection.iou_similarity(xv, yv)
        got, = _run(main, startup, {'x': x, 'y': y}, [out])
    np.testing.assert_allclose(got, _np_iou(x, y), rtol=1e-5, atol=1e-6)


def test_bipartite_match_greedy():
    # hand-checkable matrix: global greedy picks (1,0)=0.9 then (0,2)=0.8
    dist = np.array([[0.5, 0.1, 0.8],
                     [0.9, 0.2, 0.7]], dtype='float32')
    with fresh_program() as (main, startup):
        d = layers.data(name='d', shape=[2, 3], append_batch_size=False)
        idx, md = detection.bipartite_match(d)
        got_i, got_d = _run(main, startup, {'d': dist}, [idx, md])
    np.testing.assert_array_equal(got_i[0], [1, -1, 0])
    np.testing.assert_allclose(got_d[0], [0.9, 0.0, 0.8], rtol=1e-6)


def test_bipartite_match_per_prediction():
    dist = np.array([[0.5, 0.6, 0.8],
                     [0.9, 0.2, 0.7]], dtype='float32')
    with fresh_program() as (main, startup):
        d = layers.data(name='d', shape=[2, 3], append_batch_size=False)
        idx, md = detection.bipartite_match(d, match_type='per_prediction',
                                            dist_threshold=0.55)
        got_i, _ = _run(main, startup, {'d': dist}, [idx, md])
    # col1 unmatched by bipartite, filled since max(0.6, 0.2) > 0.55
    np.testing.assert_array_equal(got_i[0], [1, 0, 0])


def test_multiclass_nms_dense():
    # two overlapping boxes + one distinct; NMS keeps the high-score of the
    # overlapping pair and the distinct box
    boxes = np.array([[[0.0, 0.0, 0.4, 0.4],
                       [0.01, 0.01, 0.41, 0.41],
                       [0.6, 0.6, 0.9, 0.9]]], dtype='float32')
    scores = np.zeros((1, 2, 3), dtype='float32')   # [B, C, M], class 0 = bg
    scores[0, 1] = [0.9, 0.8, 0.7]
    with fresh_program() as (main, startup):
        b = layers.data(name='b', shape=[1, 3, 4], append_batch_size=False)
        s = layers.data(name='s', shape=[1, 2, 3], append_batch_size=False)
        out_var = main.global_block().create_var(name='nms_out',
                                                 shape=[1, 4, 6],
                                                 dtype='float32')
        main.global_block().append_op(
            type='multiclass_nms', inputs={'BBoxes': [b], 'Scores': [s]},
            outputs={'Out': [out_var]},
            attrs={'background_label': 0, 'nms_threshold': 0.5,
                   'nms_top_k': 3, 'keep_top_k': 4, 'score_threshold': 0.01,
                   'nms_eta': 1.0}, infer_shape=False)
        got, = _run(main, startup, {'b': boxes, 's': scores}, [out_var])
    kept = got[0][got[0][:, 0] >= 0]
    assert len(kept) == 2
    np.testing.assert_allclose(sorted(kept[:, 1]), [0.7, 0.9], rtol=1e-6)


def test_ssd_loss_decreases():
    r = np.random.RandomState(1)
    B, P, C, G = 2, 16, 4, 3
    priors = np.sort(r.rand(P, 4).astype('float32') * 0.8, -1)
    priors[:, 2:] += 0.2
    gt_flat = np.sort(r.rand(B * G, 4).astype('float32') * 0.8, -1)
    gt_flat[:, 2:] += 0.2
    lbl_flat = r.randint(1, C, size=(B * G, 1)).astype('int64')
    gt_lt = fluid.create_lod_tensor(gt_flat, [[G, G]])
    lbl_lt = fluid.create_lod_tensor(lbl_flat, [[G, G]])
    with fresh_program() as (main, startup):
        feat = layers.data(name='feat', shape=[8])
        loc = layers.reshape(layers.fc(input=feat, size=P * 4),
                             shape=[-1, P, 4])
        conf = layers.reshape(layers.fc(input=feat, size=P * C),
                              shape=[-1, P, C])
        gt_box = layers.data(name='gt', shape=[4], lod_level=1)
        gt_lbl = layers.data(name='lbl', shape=[1], lod_level=1,
                             dtype='int64')
        pb = layers.assign(priors)
        loss = detection.ssd_loss(loc, conf, gt_box, gt_lbl, pb)
        avg = layers.reduce_mean(loss)
        fluid.optimizer.Adam(learning_rate=0.05).minimize(avg)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        x = r.rand(B, 8).astype('float32')
        losses = [float(np.asarray(
            exe.run(main, feed={'feat': x, 'gt': gt_lt, 'lbl': lbl_lt},
                    fetch_list=[avg])[0]))
            for _ in range(25)]
    assert np.all(np.isfinite(losses))
    assert losses[-1] < losses[0] * 0.7, losses


def test_rpn_target_assign_shapes_and_labels():
    r = np.random.RandomState(2)
    B, A, G, S = 2, 32, 4, 8
    anchors = np.sort(r.rand(A, 4).astype('float32') * 0.8, -1)
    anchors[:, 2:] += 0.2
    # ground truth = a few anchors exactly (guaranteed positives)
    gt_flat = np.concatenate([anchors[:G], anchors[:G]], 0).copy()
    gt_lt = fluid.create_lod_tensor(gt_flat, [[G, G]])
    with fresh_program() as (main, startup):
        loc = layers.data(name='loc', shape=[A, 4])
        score = layers.data(name='score', shape=[A, 1])
        anc = layers.assign(anchors)
        gt = layers.data(name='gt', shape=[4], lod_level=1)
        ps, pl, tl, tb = detection.rpn_target_assign(
            loc, score, anc, gt, rpn_batch_size_per_im=S,
            rpn_positive_overlap=0.7, rpn_negative_overlap=0.3)
        got = _run(main, startup,
                   {'loc': r.rand(B, A, 4).astype('float32'),
                    'score': r.rand(B, A, 1).astype('float32'),
                    'gt': gt_lt}, [ps, pl, tl, tb])
    ps_v, pl_v, tl_v, tb_v = got
    assert ps_v.shape == (B, S, 1) and pl_v.shape == (B, S, 4)
    assert tl_v.shape == (B, S, 1) and tb_v.shape == (B, S, 4)
    # positives capped at fg_fraction * S per image; exact-match anchors
    # guarantee that many exist
    n_fg = int(S * 0.25)
    assert (tl_v == 1).sum() == B * n_fg
    assert set(np.unique(tl_v)) <= {-1, 0, 1}


def test_detection_map_perfect_and_empty():
    # one gt box per image, detection == gt -> mAP 1; no detection -> 0
    gt_flat = np.array([[1, 0.1, 0.1, 0.4, 0.4],
                        [1, 0.5, 0.5, 0.8, 0.8]], dtype='float32')
    lab_lt = fluid.create_lod_tensor(gt_flat, [[1, 1]])
    perfect = np.full((2, 3, 6), -1.0, dtype='float32')
    perfect[0, 0] = [1, 0.9, 0.1, 0.1, 0.4, 0.4]
    perfect[1, 0] = [1, 0.8, 0.5, 0.5, 0.8, 0.8]
    empty = np.full((2, 3, 6), -1.0, dtype='float32')
    for det, want in ((perfect, 1.0), (empty, 0.0)):
        with fresh_program() as (main, startup):
            d = layers.data(name='d', shape=[2, 3, 6],
                            append_batch_size=False)
            lab = layers.data(name='lab', shape=[5], lod_level=1)
            m = detection.detection_map(d, lab, class_num=2)
            got, = _run(main, startup, {'d': det, 'lab': lab_lt}, [m])
        assert abs(float(got) - want) < 1e-6, (float(got), want)
