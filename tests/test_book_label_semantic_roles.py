"""End-to-end SRL db_lstm + CRF (reference
fluid/tests/book/test_label_semantic_roles.py) on synthetic conll05."""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid
from paddle_tpu.models import label_semantic_roles as M

from util import fresh_program


def test_label_semantic_roles_trains_and_decodes():
    with fresh_program() as (main, startup):
        avg_cost, crf_decode, train_reader, feed_order = M.get_model(
            word_dim=16, mark_dim=4, hidden_dim=32, depth=2, batch_size=16)
        fluid.optimizer.Adam(learning_rate=0.05).minimize(avg_cost)

        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        # the frozen word/ctx table must come from the pretrained embedding
        shape = M.load_pretrained_embedding()
        assert shape[1] == 16  # sliced to the model's word_dim
        feed_list = [main.global_block().var(n) for n in feed_order]
        feeder = fluid.DataFeeder(feed_list=feed_list,
                                  place=fluid.CPUPlace())
        # fixed batch: per-batch CRF normalizers vary with sequence
        # lengths, so convergence is asserted on one batch re-fed
        batch0 = next(train_reader())
        feed0 = feeder.feed(batch0)
        losses = []
        for _ in range(40):
            loss, = exe.run(main, feed=feed0, fetch_list=[avg_cost])
            losses.append(float(np.asarray(loss).squeeze()))
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0] * 0.8, (losses[0], losses[-1])

        # decode path: valid label ids for every token
        batch = next(train_reader())
        dec, = exe.run(main, feed=feeder.feed(batch),
                       fetch_list=[crf_decode])
        dec = np.asarray(dec)
        word_dict, _, label_dict = paddle.dataset.conll05.get_dict()
        assert ((dec >= 0) & (dec < len(label_dict))).all()
