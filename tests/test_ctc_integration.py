"""CTC pipeline composed end-to-end: per-frame classifier -> warpctc
training -> ctc_greedy_decoder + edit_distance evaluation (the
reference's OCR/CRNN recipe; op-level CTC tests live in
test_ops_crf_ctc.py)."""
import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers

from util import fresh_program

VOCAB = 5        # 0 = blank, classes 1..4
FRAME_DIM = 8


def _make_batch(rng, n, t=10):
    """Frames carry a (noisy) one-hot of the class emitted at that step;
    labels are the deduplicated non-blank sequence — learnable alignment."""
    xs, labels, lens = [], [], []
    for _ in range(n):
        cls = rng.randint(1, VOCAB, size=3)
        # each class occupies a few frames, blanks between
        frames = []
        emit = []
        for c in cls:
            for _ in range(rng.randint(2, 4)):
                frames.append(c)
            emit.append(c)
            frames.append(0)  # blank separator
        frames = frames[:t] + [0] * max(0, t - len(frames))
        x = np.zeros((t, FRAME_DIM), 'float32')
        for i, c in enumerate(frames[:t]):
            x[i, c] = 1.0
        x += rng.rand(t, FRAME_DIM).astype('float32') * 0.1
        xs.append(x)
        labels.append(np.array(emit, 'int64')[:, None])
        lens.append(len(emit))
    return xs, labels, lens


def test_ctc_trains_and_decodes():
    rng = np.random.RandomState(0)
    with fresh_program() as (main, startup):
        x = layers.data(name='x', shape=[FRAME_DIM], dtype='float32',
                        lod_level=1)
        label = layers.data(name='label', shape=[1], dtype='int64',
                            lod_level=1)
        logits = layers.fc(input=x, size=VOCAB)
        loss = layers.mean(layers.warpctc(input=logits, label=label,
                                          blank=0))
        fluid.optimizer.Adam(learning_rate=0.05).minimize(loss)
        decoded = layers.ctc_greedy_decoder(
            layers.softmax(logits), blank=0)
        dist, seq_num = layers.edit_distance(decoded, label,
                                             normalized=False)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)

        xs, labels, lens = _make_batch(rng, 16)
        x_feed = fluid.create_lod_tensor(
            np.concatenate(xs), [[len(s) for s in xs]])
        l_feed = fluid.create_lod_tensor(
            np.concatenate(labels), [lens])
        feed = {'x': x_feed, 'label': l_feed}

        losses = []
        for _ in range(60):
            l, = exe.run(main, feed=feed, fetch_list=[loss])
            losses.append(float(np.asarray(l).squeeze()))
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])

        d, n = exe.run(main, feed=feed, fetch_list=[dist, seq_num])
        d = np.asarray(d)
        # after training, the greedy decode is close to the labels:
        # average edit distance well below the ~3-token label length
        assert float(d.mean()) < 1.5, d.squeeze()
        assert int(np.asarray(n).reshape(-1)[0]) == 16
