"""Optimizer-pass tier (docs/passes.md).

Per-pass unit drills (DCE, constant folding, CSE, the AMP IR rewrite,
the donation/memory plan), the PADDLE_TPU_OPT executor wiring
(once-per-cache-key, key separation, crash fallback), and the A/B
equivalence contract: `PADDLE_TPU_OPT=default` must be FETCH-EQUIVALENT
to `off` — bit-exact for DCE/CSE/folding (RNG streams included: op
removal must not shift another op's dropout mask), within one bf16
rounding per rewritten op for the AMP pass — across the program-fuzz
generator and the book models.
"""
import contextlib
import os

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers, passes
from paddle_tpu.fluid import analysis
from paddle_tpu.fluid.executor import Scope, _switch_scope
from paddle_tpu import obs

from util import fresh_program

pytestmark = pytest.mark.passes


@contextlib.contextmanager
def _opt_env(mode):
    prev = os.environ.get(passes.ENV_OPT)
    os.environ[passes.ENV_OPT] = mode
    try:
        yield
    finally:
        if prev is None:
            os.environ.pop(passes.ENV_OPT, None)
        else:
            os.environ[passes.ENV_OPT] = prev


def _run_arm(main, startup, feed, fetch_list, mode, n=3, run=None):
    """One A/B arm: fresh scope + fresh executor (so RNG counters align
    across arms), `n` runs of the same feed under PADDLE_TPU_OPT=mode."""
    with _opt_env(mode):
        sc = Scope()
        prev = _switch_scope(sc)
        try:
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            if run is not None:
                return run(exe, sc)
            return [np.asarray(exe.run(main, feed=feed,
                                       fetch_list=fetch_list)[0])
                    for _ in range(n)]
        finally:
            _switch_scope(prev)


# ------------------------------------------------------------- unit: dce

def test_dce_removes_dead_ops_keeps_persistable_writers():
    with fresh_program() as (main, startup):
        x = layers.data(name='x', shape=[8], dtype='float32')
        y = layers.data(name='y', shape=[1], dtype='float32')
        h = layers.fc(input=x, size=8, act='relu')
        layers.exp(h)                      # dead: never fetched
        layers.softmax(h)                  # dead
        pred = layers.fc(input=h, size=1)
        cost = layers.mean(layers.square_error_cost(input=pred, label=y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(cost)

        n0 = len(main.global_block().ops)
        opt, report = passes.optimize(main, fetches=[cost.name])
        assert report.ops_after < report.ops_before == n0
        assert report.passes['dce']['ops_removed'] >= 2
        types = [op.type for op in opt.global_block().ops]
        assert 'exp' not in types and 'softmax' not in types
        # optimizer ops (persistable writers) all survive
        assert types.count('sgd') == [op.type for op in
                                      main.global_block().ops].count('sgd')
        # the original program is untouched
        assert len(main.global_block().ops) == n0
        # the optimized clone still verifies clean for this fetch set
        assert analysis.analyze(opt, fetches=[cost.name],
                                dead_ops=False) == []


def test_dce_empty_fetch_list_keeps_training_step():
    """fetch_list=[] (a pure training step): everything reaching the
    persistable updates stays, exactly like the startup program."""
    with fresh_program() as (main, startup):
        x = layers.data(name='x', shape=[4], dtype='float32')
        y = layers.data(name='y', shape=[1], dtype='float32')
        pred = layers.fc(input=x, size=1)
        cost = layers.mean(layers.square_error_cost(input=pred, label=y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(cost)
        opt, report = passes.optimize(main, fetches=[])
        types = [op.type for op in opt.global_block().ops]
        assert 'autodiff' in types and 'sgd' in types
        feed = {'x': np.ones((2, 4), 'float32'),
                'y': np.ones((2, 1), 'float32')}
        a = _run_arm(main, startup, feed, [cost], 'off')
        b = _run_arm(main, startup, feed, [cost], 'default')
        np.testing.assert_array_equal(a, b)


def test_dce_kept_effectful_op_pins_its_producers():
    """A retained print op's whole producer chain must survive DCE (a
    kept op reading a pruned name would KeyError at trace time), and the
    program still runs under OPT=default."""
    with fresh_program() as (main, startup):
        x = layers.data(name='x', shape=[4], dtype='float32')
        h = layers.relu(x)
        layers.Print(h)                       # effectful, not fetched
        out = layers.scale(x, scale=2.0)
        opt, report = passes.optimize(main, fetches=[out.name])
        types = [op.type for op in opt.global_block().ops]
        assert 'print' in types and 'relu' in types
        feed = {'x': np.ones((2, 4), 'float32')}
        a = _run_arm(main, startup, feed, [out], 'off', n=1)
        b = _run_arm(main, startup, feed, [out], 'default', n=1)
    np.testing.assert_array_equal(a[0], b[0])


def test_optimizer_self_check_falls_back_not_crashes():
    """A pass bug that corrupts the graph must surface as the executor's
    documented fallback (warn + unoptimized lowering), never a raw trace
    error: drill it by breaking the optimized clone via a monkeypatched
    pass."""
    import paddle_tpu.fluid.passes.dce as dce_mod
    orig = dce_mod.run

    def broken(program, report, fetches):
        block = program.global_block()
        block.ops = [op for op in block.ops if op.type != 'relu']
        return 1

    with fresh_program() as (main, startup):
        x = layers.data(name='x', shape=[4], dtype='float32')
        h = layers.relu(x)
        out = layers.scale(h, scale=2.0)
        feed = {'x': np.ones((2, 4), 'float32')}
        a = _run_arm(main, startup, feed, [out], 'off', n=1)
        dce_mod.run = broken
        try:
            with pytest.warns(RuntimeWarning, match='optimization failed'):
                b = _run_arm(main, startup, feed, [out], 'default', n=1)
        finally:
            dce_mod.run = orig
    np.testing.assert_array_equal(a[0], b[0])


# ------------------------------------------------------------ unit: fold

def test_fold_constant_chain_bit_exact():
    with fresh_program() as (main, startup):
        x = layers.data(name='x', shape=[4], dtype='float32')
        c = layers.fill_constant(shape=[4], dtype='float32', value=2.5)
        c2 = layers.scale(c, scale=3.0, bias=1.0)     # foldable
        c3 = layers.elementwise_add(c2, c2)           # foldable
        out = layers.elementwise_add(x, c3)
        opt, report = passes.optimize(main, fetches=[out.name])
        assert report.passes['fold']['ops_folded'] >= 2
        types = [op.type for op in opt.global_block().ops]
        assert 'scale' not in types
        assert 'assign_value' in types
        # fill_constant + intermediate folds are dead afterwards: swept
        assert report.passes['dce']['ops_removed'] >= 1
        feed = {'x': np.arange(8, dtype='float32').reshape(2, 4)}
        a = _run_arm(main, startup, feed, [out], 'off', n=1)
        b = _run_arm(main, startup, feed, [out], 'default', n=1)
        np.testing.assert_array_equal(a, b)


def test_fold_skips_rng_and_respects_cap():
    with fresh_program() as (main, startup):
        r = layers.uniform_random([4, 4], dtype='float32')
        out1 = layers.scale(r, scale=2.0)             # rng upstream
        big = layers.fill_constant(shape=[128, 128], dtype='float32',
                                   value=1.0)
        out2 = layers.scale(big, scale=2.0)           # 16384 > default cap
        opt, report = passes.optimize(
            main, fetches=[out1.name, out2.name])
        types = [op.type for op in opt.global_block().ops]
        assert 'uniform_random' in types
        assert types.count('scale') == 2              # neither folded
        opt2, report2 = passes.optimize(
            main, fetches=[out1.name, out2.name], level='aggressive')
        assert report2.passes['fold']['ops_folded'] == 1   # big one folds


# ------------------------------------------------------------- unit: cse

def test_cse_merges_duplicates_not_rng():
    with fresh_program() as (main, startup):
        x = layers.data(name='x', shape=[8], dtype='float32')
        a = layers.tanh(x)
        b = layers.tanh(x)                  # duplicate
        d1 = layers.dropout(x, dropout_prob=0.5)
        d2 = layers.dropout(x, dropout_prob=0.5)   # NOT a duplicate (rng)
        out = layers.elementwise_add(layers.elementwise_add(a, b),
                                     layers.elementwise_add(d1, d2))
        opt, report = passes.optimize(main, fetches=[out.name])
        assert report.passes['cse']['ops_merged'] == 1
        types = [op.type for op in opt.global_block().ops]
        assert types.count('tanh') == 1
        assert types.count('dropout') == 2
        feed = {'x': np.random.RandomState(3).rand(4, 8).astype('float32')}
        a_ = _run_arm(main, startup, feed, [out], 'off', n=2)
        b_ = _run_arm(main, startup, feed, [out], 'default', n=2)
        np.testing.assert_array_equal(a_, b_)      # dropout masks included


def test_cse_protects_attr_referenced_names():
    """Control-flow rules resolve some env names from ATTRS (switch
    cond_names, static_rnn step_ins/mems) — the rename walk cannot see
    those, so a duplicate whose output is attr-referenced must never be
    merged (previously: KeyError at trace time under OPT=default)."""
    with fresh_program() as (main, startup):
        i = layers.fill_constant(shape=[1], dtype='float32', value=3.0)
        n = layers.data(name='n', shape=[1], dtype='float32')
        c1 = layers.less_than(i, n)
        c2 = layers.less_than(i, n)            # duplicate, feeds Switch
        out = layers.create_global_var(shape=[1], value=0.0,
                                       dtype='float32',
                                       persistable=False, name='sw_out')
        with layers.Switch() as switch:
            with switch.case(c2):
                layers.assign(layers.fill_constant(
                    shape=[1], dtype='float32', value=1.0), out)
            with switch.default():
                layers.assign(layers.fill_constant(
                    shape=[1], dtype='float32', value=2.0), out)
        _ = c1
        feed = {'n': np.full((1, 1), 5.0, 'float32')}
        a = _run_arm(main, startup, feed, [out], 'off', n=1)
        b = _run_arm(main, startup, feed, [out], 'default', n=1)
    np.testing.assert_array_equal(a[0], b[0])


def _append_undeclared_write_loop(main, target):
    """Hand-append a `while` op whose body writes `target` WITHOUT
    listing it in the op's outputs — the write class the layer builders
    always declare but hand-built / deserialized programs may not
    (analysis models it via dataflow._block_writes). Returns the while op."""
    cond = layers.fill_constant(shape=[1], dtype='bool', value=False)
    sub = main.create_block()
    five = sub.create_var(name='five@sbw', shape=[1], dtype='float32')
    sub.append_op(type='fill_constant', inputs={}, outputs={'Out': [five]},
                  attrs={'shape': [1], 'dtype': 'float32', 'value': 5.0},
                  infer_shape=False)
    sub.append_op(type='assign', inputs={'X': [five]},
                  outputs={'Out': [target]}, infer_shape=False)
    main.rollback()
    return main.current_block().append_op(
        type='while', inputs={'Condition': [cond], 'X': []},
        outputs={'Out': [cond]}, attrs={'sub_block': sub.idx},
        infer_shape=False)


def test_cse_sees_undeclared_sub_block_writes():
    """Two identical pure reads straddling a sub-block that writes their
    input without declaring it as the loop op's output must NOT merge:
    CSE's version map bumps written_names (declared outputs + sub-block
    writes), matching the analysis layer's write model, so the second
    read is never proven to be the same value."""
    with fresh_program() as (main, _):
        w = layers.create_global_var(shape=[1], value=3.0, dtype='float32',
                                     persistable=True, name='w@sbw')
        pre = layers.scale(w, scale=2.0)
        _append_undeclared_write_loop(main, w)
        post = layers.scale(w, scale=2.0)
        out = layers.elementwise_add(pre, post)
        opt, report = passes.optimize(main, fetches=[out.name])
        assert report.passes['cse']['ops_merged'] == 0
        types = [op.type for op in opt.global_block().ops]
        assert types.count('scale') == 2


def test_amp_cast_cache_sees_undeclared_sub_block_writes():
    """The AMP rewrite's cast cache has the same rule: an undeclared
    sub-block write to an f32 operand between two rewritten ops must
    invalidate the cached bf16 cast, so each matmul casts the value it
    actually reads."""
    with fresh_program() as (main, _):
        x = layers.data(name='x', shape=[4], dtype='float32')
        w = layers.create_global_var(shape=[4, 4], value=1.0,
                                     dtype='float32', persistable=True,
                                     name='amp_w@sbw')
        a = layers.matmul(x, w)
        _append_undeclared_write_loop(main, w)
        b = layers.matmul(x, w)
        out = layers.elementwise_add(a, b)
        fluid.amp.decorate_program(main)
        opt, report = passes.optimize(main, fetches=[out.name])
        casts_of_w = [op for op in opt.global_block().ops
                      if op.type == 'cast'
                      and op.input_arg_names == ['amp_w@sbw']]
        assert len(casts_of_w) == 2, \
            'second matmul must re-cast w after the sub-block write'


def test_cse_skips_fetched_and_persistable_outputs():
    with fresh_program() as (main, startup):
        x = layers.data(name='x', shape=[8], dtype='float32')
        a = layers.tanh(x)
        b = layers.tanh(x)
        opt, report = passes.optimize(main, fetches=[a.name, b.name])
        # both tanh outputs are fetch targets: neither may disappear
        assert report.passes['cse']['ops_merged'] == 0
        types = [op.type for op in opt.global_block().ops]
        assert types.count('tanh') == 2


# ------------------------------------------------------------- unit: amp

def test_amp_rewrite_inserts_visible_casts():
    with fresh_program() as (main, startup):
        x = layers.data(name='x', shape=[8], dtype='float32')
        y = layers.data(name='y', shape=[1], dtype='float32')
        h = layers.fc(input=x, size=16, act='relu')
        pred = layers.fc(input=h, size=1)
        cost = layers.mean(layers.square_error_cost(input=pred, label=y))
        fluid.optimizer.SGD(learning_rate=0.05).minimize(cost)
        fluid.amp.decorate_program(main)
        opt, report = passes.optimize(main, fetches=[cost.name])
        assert report.passes['amp']['ops_rewritten'] >= 2   # the two muls
        assert report.passes['amp']['casts_inserted'] >= 4
        assert getattr(opt, '_amp_ir', False) and not opt._amp
        casts = [op for op in opt.global_block().ops if op.type == 'cast']
        assert casts, 'bf16 boundaries must be visible cast ops'
        # bf16 boundaries visible to ANALYSIS too: declared dtypes of the
        # cast temps are bfloat16 and the optimized program still
        # verifies (shape pass runs the same rules)
        bf16 = [v for v in opt.list_vars() if v.dtype == 'bfloat16']
        assert bf16
        assert analysis.analyze(opt, fetches=[cost.name],
                                dead_ops=False) == []

        feed = {'x': np.random.RandomState(0).rand(4, 8).astype('float32'),
                'y': np.random.RandomState(1).rand(4, 1).astype('float32')}
        a = _run_arm(main, startup, feed, [cost], 'off')
        b = _run_arm(main, startup, feed, [cost], 'default')
        # documented tolerance: one extra bf16 rounding per rewritten op
        np.testing.assert_allclose(np.asarray(a).ravel(),
                                   np.asarray(b).ravel(), rtol=2e-2)


# ----------------------------------------------------- donation/memory plan

def test_memory_plan_train_vs_inference():
    with fresh_program() as (main, startup):
        x = layers.data(name='x', shape=[4], dtype='float32')
        y = layers.data(name='y', shape=[1], dtype='float32')
        pred = layers.fc(input=x, size=1)
        cost = layers.mean(layers.square_error_cost(input=pred, label=y))
        infer = main.clone(for_test=True)
        fluid.optimizer.SGD(learning_rate=0.1).minimize(cost)
        train_plan = passes.memory_plan(main)
        infer_plan = passes.memory_plan(infer)
    assert train_plan.donates and train_plan.write_set
    assert not infer_plan.donates and not infer_plan.write_set
    assert infer_plan.readonly_names(['a', 'b']) == ['a', 'b']
    assert train_plan.persist_out() == sorted(train_plan.write_set)


def test_plan_readonly_persistables_not_donated_or_refreshed():
    """A persistable the step only READS keeps its scope buffer: it is
    neither donated (stays valid) nor re-exposed as an output (no
    passthrough copy per step)."""
    import jax.numpy as jnp
    with fresh_program() as (main, startup):
        x = layers.data(name='x', shape=[4], dtype='float32')
        y = layers.data(name='y', shape=[1], dtype='float32')
        table = layers.create_parameter([4], 'float32', name='frozen_w')
        table.stop_gradient = True
        xx = layers.elementwise_add(x, table)
        pred = layers.fc(input=xx, size=1)
        cost = layers.mean(layers.square_error_cost(input=pred, label=y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(cost)

        sc = Scope()
        prev = _switch_scope(sc)
        try:
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            frozen_before = sc.vars['frozen_w']
            feed = {'x': np.ones((2, 4), 'float32'),
                    'y': np.ones((2, 1), 'float32')}
            exe.run(main, feed=feed, fetch_list=[cost])
            (compiled,) = [c for c in exe._cache.values()
                           if c.ad_idx is not None]
            assert compiled.plan.donates
            assert 'frozen_w' in compiled.readonly_names
            assert 'frozen_w' not in compiled.donate_names
            assert 'frozen_w' not in compiled.persist_out
            # buffer identity preserved AND still readable (not donated)
            assert sc.vars['frozen_w'] is frozen_before
            np.testing.assert_array_equal(np.asarray(frozen_before),
                                          np.asarray(sc.vars['frozen_w']))
            # while the written params DID refresh
            w = [n for n in compiled.donate_names if n.endswith('.w_0')]
            assert w
            exe.run(main, feed=feed, fetch_list=[cost])
        finally:
            _switch_scope(prev)


# ------------------------------------------------------- executor wiring

def test_opt_env_knob_once_per_cache_key():
    hist = obs.REGISTRY.histogram('passes.optimize.seconds')
    with fresh_program() as (main, startup):
        x = layers.data(name='x', shape=[4], dtype='float32')
        out = layers.scale(x, scale=2.0)
        feed = {'x': np.ones((2, 4), 'float32')}
        with _opt_env('default'):
            sc = Scope()
            prev = _switch_scope(sc)
            try:
                exe = fluid.Executor(fluid.CPUPlace())
                before = hist.snapshot()['count']
                r1 = exe.run(main, feed=feed, fetch_list=[out])
                r2 = exe.run(main, feed=feed, fetch_list=[out])
                # ONE passes.optimize span for two runs of the same key
                assert hist.snapshot()['count'] == before + 1
            finally:
                _switch_scope(prev)


def test_opt_mode_is_part_of_the_cache_key():
    with fresh_program() as (main, startup):
        x = layers.data(name='x', shape=[4], dtype='float32')
        out = layers.scale(x, scale=2.0)
        feed = {'x': np.ones((2, 4), 'float32')}
        sc = Scope()
        prev = _switch_scope(sc)
        try:
            exe = fluid.Executor(fluid.CPUPlace())
            with _opt_env('off'):
                exe.run(main, feed=feed, fetch_list=[out])
            n_off = exe.cache_stats['entries']
            with _opt_env('default'):
                exe.run(main, feed=feed, fetch_list=[out])
            assert exe.cache_stats['entries'] == n_off + 1
        finally:
            _switch_scope(prev)


def test_opt_counters_report_op_deltas():
    c_removed = obs.REGISTRY.counter('passes.dce.ops_removed')
    c_progs = obs.REGISTRY.counter('passes.programs_optimized')
    before = c_removed.snapshot()['value']
    before_p = c_progs.snapshot()['value']
    with fresh_program() as (main, startup):
        x = layers.data(name='x', shape=[4], dtype='float32')
        layers.exp(x)     # dead
        out = layers.scale(x, scale=2.0)
        passes.optimize(main, fetches=[out.name])
    assert c_removed.snapshot()['value'] > before
    assert c_progs.snapshot()['value'] == before_p + 1


def test_program_optimize_api():
    with fresh_program() as (main, startup):
        x = layers.data(name='x', shape=[4], dtype='float32')
        layers.exp(x)
        out = layers.scale(x, scale=2.0)
        opt = main.optimize(fetches=[out.name])
        assert opt is not main
        assert opt._opt_report.ops_after < opt._opt_report.ops_before
        assert len(opt.global_block().ops) < len(main.global_block().ops)


def test_program_optimize_returns_owned_clone_on_skip():
    """Program.optimize() promises a program the caller owns even when
    the pipeline skips (level='off'): mutating the result must never
    corrupt the original. (passes.optimize itself keeps the aliasing —
    the executor wants no extra clone on its fallback path.)"""
    with fresh_program() as (main, _):
        x = layers.data(name='x', shape=[4], dtype='float32')
        layers.scale(x, scale=2.0)
        q = main.optimize(level='off')
        assert q is not main
        assert q._opt_report.skipped == 'level=off'
        n = len(main.global_block().ops)
        q.global_block().ops.pop()
        assert len(main.global_block().ops) == n


def test_pipeline_programs_are_left_alone():
    with fresh_program() as (main, startup):
        x = layers.data(name='x', shape=[4], dtype='float32')
        out = layers.scale(x, scale=2.0)
        main._pipeline_config = {'sentinel': True}   # transpiled marker
        opt, report = passes.optimize(main, fetches=[out.name])
        assert opt is main
        assert 'pipeline' in report.skipped


# ------------------------------------------------- A/B: fuzz + bundling

def test_fuzz_graphs_bit_exact_off_vs_default():
    from test_program_fuzz import _random_graph
    for seed in range(6):
        rng = np.random.RandomState(seed)
        feed = {'x': rng.randn(4, 8).astype('float32')}
        with fresh_program() as (main, startup):
            x = layers.data(name='x', shape=[8], dtype='float32')
            out = _random_graph(rng, x)
            a = _run_arm(main, startup, feed, [out], 'off', n=1)
            b = _run_arm(main, startup, feed, [out], 'default', n=1)
        np.testing.assert_array_equal(
            a[0], b[0], err_msg='seed %d diverged under optimization'
            % seed)


def test_training_with_dropout_bit_exact_off_vs_default():
    """The strictest RNG drill: a trained-through dropout program with a
    dead branch — DCE removes an op BEFORE the dropout, and the mask
    stream must not move (op_seq stamping)."""
    feed = {'x': np.random.RandomState(0).rand(8, 8).astype('float32'),
            'y': np.random.RandomState(1).rand(8, 1).astype('float32')}

    def build():
        x = layers.data(name='x', shape=[8], dtype='float32')
        y = layers.data(name='y', shape=[1], dtype='float32')
        h = layers.fc(input=x, size=16, act='relu')
        layers.exp(h)                          # dead
        d = layers.dropout(h, dropout_prob=0.3)
        pred = layers.fc(input=d, size=1)
        cost = layers.mean(layers.square_error_cost(input=pred, label=y))
        fluid.optimizer.SGD(learning_rate=0.05).minimize(cost)
        return cost

    with fresh_program() as (main, startup):
        cost = build()
        a = _run_arm(main, startup, feed, [cost], 'off', n=4)
        b = _run_arm(main, startup, feed, [cost], 'default', n=4)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_run_bundle_off_vs_default_bit_exact():
    feeds = [{'x': np.random.RandomState(i).rand(4, 4).astype('float32'),
              'y': np.random.RandomState(100 + i).rand(4, 1)
              .astype('float32')} for i in range(4)]
    with fresh_program() as (main, startup):
        x = layers.data(name='x', shape=[4], dtype='float32')
        y = layers.data(name='y', shape=[1], dtype='float32')
        pred = layers.fc(input=x, size=1)
        cost = layers.mean(layers.square_error_cost(input=pred, label=y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(cost)

        def bundle_arm(exe, sc):
            out, = exe.run_bundle(main, feeds=feeds, fetch_list=[cost])
            return [np.asarray(out)]

        a = _run_arm(main, startup, None, None, 'off', run=bundle_arm)
        b = _run_arm(main, startup, None, None, 'default', run=bundle_arm)
    np.testing.assert_array_equal(a[0], b[0])


# ------------------------------------------------------ transpiler shims

def test_transpiler_shims_deprecate_over_passes():
    with fresh_program() as (main, startup):
        x = layers.data(name='x', shape=[4], dtype='float32')
        pred = layers.fc(input=x, size=1)
        with pytest.warns(DeprecationWarning, match='memory_optimize'):
            fluid.memory_optimize(main)
        assert main._use_remat
        with pytest.warns(DeprecationWarning, match='fold_batch_norm'):
            fluid.InferenceTranspiler().transpile(main, fluid.CPUPlace())


# ----------------------------------------------------- book-model sweep

_SWEEP = {
    'fit_a_line': dict(kwargs=dict(batch_size=4), feeds=['x', 'y']),
    'mnist': dict(kwargs=dict(batch_size=4), feeds=['pixel', 'label'],
                  transform=lambda b: [(np.reshape(i, (1, 28, 28)), l)
                                       for i, l in b]),
    'vgg': dict(kwargs=dict(batch_size=2), feeds=['data', 'label'],
                transform=lambda b: [(np.reshape(i, (3, 32, 32)), l)
                                     for i, l in b], slow=True),
    'resnet': dict(kwargs=dict(depth=8, batch_size=2),
                   feeds=['data', 'label'],
                   transform=lambda b: [(np.reshape(i, (3, 32, 32)), l)
                                        for i, l in b], slow=True),
    'stacked_dynamic_lstm': dict(
        kwargs=dict(batch_size=2, lstm_size=16, emb_dim=16),
        feeds=['words', 'label']),
    'machine_translation': dict(
        kwargs=dict(batch_size=2, embedding_dim=16, encoder_size=16,
                    decoder_size=16, dict_size=40), feeds_idx=4),
    'transformer': dict(
        kwargs=dict(batch_size=2, max_length=8, n_layer=1, d_model=32,
                    n_head=2, d_inner=32, dict_size=60, warmup_steps=50),
        feeds_idx=4, stack=True),
    'deepfm': dict(kwargs=dict(batch_size=4, embed_dim=4), feeds_idx=4),
    'word2vec': dict(kwargs=dict(batch_size=4), feeds_idx=4),
    'se_resnext': dict(kwargs=dict(batch_size=2, class_dim=4),
                       feeds_idx=4, slow=True),
    'understand_sentiment': dict(kwargs=dict(batch_size=4), feeds_idx=4),
    'label_semantic_roles': dict(
        kwargs=dict(batch_size=2, word_dim=8, mark_dim=2, hidden_dim=16,
                    depth=2), reader_idx=2, feeds_idx=3),
    'recommender_system': dict(
        kwargs=dict(batch_size=4, emb_dim=8, tower_dim=16),
        reader_idx=3, feeds_idx=5),
}


def _sweep_params():
    from paddle_tpu import models
    assert set(_SWEEP) == set(models.model_list)
    return [pytest.param(n, marks=pytest.mark.slow)
            if _SWEEP[n].get('slow') else n for n in models.model_list]


@pytest.mark.parametrize('name', _sweep_params())
def test_book_model_off_vs_default_equivalent(name):
    """Acceptance: PADDLE_TPU_OPT=default is fetch-equivalent to off on
    every book model — bit-exact (none of them use AMP), across two
    training steps including every dropout mask and optimizer update."""
    from paddle_tpu import models
    mod = models.get_model_module(name)
    spec = _SWEEP[name]
    with fresh_program() as (main, startup):
        ret = mod.get_model(**spec.get('kwargs', {}))
        cost = ret[0]
        reader = ret[spec.get('reader_idx', 2)]
        feeds = spec.get('feeds') or ret[spec['feeds_idx']]
        batch = next(iter(reader()))
        if spec.get('transform'):
            batch = spec['transform'](batch)
        if spec.get('stack'):
            feed = {n: np.stack([r[i] for r in batch])
                    for i, n in enumerate(feeds)}
        else:
            feeder = fluid.DataFeeder(
                place=fluid.CPUPlace(),
                feed_list=[main.global_block().var(f) for f in feeds])
            feed = feeder.feed(batch)
        a = _run_arm(main, startup, feed, [cost], 'off', n=2)
        b = _run_arm(main, startup, feed, [cost], 'default', n=2)
    np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b),
        err_msg='%s diverged under PADDLE_TPU_OPT=default' % name)


def test_book_model_op_count_reduction_reported():
    """At least one real model must show an op-count REDUCTION, reported
    through the passes.* obs counters (the attribution contract for
    obs_report / bench_sentinel): label_semantic_roles builds a CRF
    decode path the training fetch never uses — dead for the cost-only
    fetch set the trainer runs."""
    from paddle_tpu import models
    c_removed = obs.REGISTRY.counter('passes.ops_removed')
    before = c_removed.snapshot()['value']
    mod = models.get_model_module('label_semantic_roles')
    with fresh_program() as (main, startup):
        ret = mod.get_model(**_SWEEP['label_semantic_roles']['kwargs'])
        cost = ret[0]
        opt, report = passes.optimize(main, fetches=[cost.name])
    assert report.ops_after < report.ops_before, report
    assert c_removed.snapshot()['value'] > before
