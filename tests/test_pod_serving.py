"""Pod-scale serving drills (docs/serving.md#pod).

The serving tier crossed the line training crossed in PRs 7/9/10: a
`set_mesh`-annotated Program (row-sharded embedding table) serves as a
single Router replica through the GSPMD executor — restored from a
SHARDED checkpoint, never materialized dense — replicas register across
hosts through a shared-filesystem registry, and a dead serving host is
detected by heartbeat, its futures RE-ROUTED to survivors (zero dropped
futures) and its replica RE-SHARDED onto the surviving topology.

Every in-process drill simulates host death via `simulate_death()`
(beats stop + loops freeze: indistinguishable from SIGKILL to the
router); the 2-process drill (additionally `slow`, the test_elastic.py
harness) uses a real SIGKILL. Telemetry assertions verify an operator
could have SEEN each decision (docs/observability.md).
"""
import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu import obs, serving
from paddle_tpu.fluid import framework, unique_name
from paddle_tpu.fluid.executor import Scope, _switch_scope
from paddle_tpu.obs import report as obs_report
from paddle_tpu.obs import trace
from paddle_tpu.parallel import HostLost
from paddle_tpu.serving import (AutoscalePolicy, Autoscaler, DecodeConfig,
                                DecodeEngine, PodRouter, PodWorker, Router,
                                ServerClosed, ServingConfig, ServingEngine,
                                ShardedPredictor, TransportError)
from paddle_tpu.serving.transport import Channel, RpcServer
from paddle_tpu.utils import checkpoint as ck
from paddle_tpu.utils.faults import FaultInjector

pytestmark = pytest.mark.pod

VOCAB, DIM = 64, 4


@pytest.fixture
def obs_events(tmp_path):
    obs.enable(str(tmp_path / 'obs'))

    def read(name=None):
        path = obs.run_log_path()
        if path is None:
            return []
        events, errors = obs_report.load_events(path)
        assert errors == [], errors
        return [e for e in events if name is None or e['name'] == name]

    try:
        yield read
    finally:
        obs._reset()


@pytest.fixture(params=['file', 'rpc'])
def transport(request):
    """Every pod drill runs on BOTH wires — the shared-filesystem
    mailbox and the length-prefixed TCP rpc transport — from ONE test
    body. The only knob is the PodWorker(transport=...) seam; the
    router discovers the wire from the registration record."""
    return request.param


# ---------------------------------------------------------------------------
# shared artifacts: a trained sharded-embedding scorer + sharded ckpt
# ---------------------------------------------------------------------------

@pytest.fixture(scope='module')
def artifacts(tmp_path_factory):
    """Train the acceptance-drill model (vocab-sharded table + fc head)
    on the dp=8 mesh, save a SHARDED checkpoint + the program-only
    serving artifact, and record dense reference scores for a probe."""
    base = tmp_path_factory.mktemp('pod_artifacts')
    model_dir = str(base / 'model')
    ckpt_dir = str(base / 'ckpt')
    main, startup, scope = (framework.Program(), framework.Program(),
                            Scope())
    prev = _switch_scope(scope)
    try:
        with unique_name.guard():
            with framework.program_guard(main, startup):
                ids = fluid.layers.data(name='ids', shape=[2, 1],
                                        dtype='int64')
                emb = fluid.layers.embedding(
                    ids, size=[VOCAB, DIM], is_sparse=True,
                    is_distributed=True,
                    param_attr=fluid.ParamAttr(name='emb_w',
                                               sharding=('dp', None)))
                pred = fluid.layers.fc(
                    input=emb, size=1, num_flatten_dims=2,
                    bias_attr=False,
                    param_attr=fluid.ParamAttr(name='fc_w'))
                loss = fluid.layers.mean(fluid.layers.square(pred - 1.0))
                fluid.optimizer.Adam(learning_rate=0.05).minimize(loss)
                main.set_mesh({'dp': 8})
                exe = fluid.Executor(fluid.CPUPlace())
                exe.run(startup)
                rng = np.random.RandomState(0)
                for _ in range(3):
                    b = rng.randint(0, VOCAB, (8, 2, 1)).astype('int64')
                    exe.run(main, feed={'ids': b}, fetch_list=[loss])
                state = exe.state_dict(main, scope=scope)
                ck.save_sharded(os.path.join(ckpt_dir, 'sharded_7'),
                                {'emb_w': state['emb_w'],
                                 'fc_w': state['fc_w']}, step=7)
                serving.save_serving_program(model_dir, ['ids'], [pred],
                                             main_program=main)
                probe = rng.randint(0, VOCAB, (8, 2, 1)).astype('int64')
                infer = main.clone(for_test=True).prune([pred])
                ref = exe.run(infer, feed={'ids': probe},
                              fetch_list=[pred.name], scope=scope)
    finally:
        _switch_scope(prev)
    return {'model_dir': model_dir, 'ckpt_dir': ckpt_dir,
            'probe': probe, 'ref': np.asarray(ref[0])}


def _cfg(**kw):
    base = dict(max_batch_size=8, buckets=[8], max_queue_delay_ms=1.0)
    base.update(kw)
    return ServingConfig(**base)


def _builder(art, mesh_n, buckets=(8,)):
    def b(reason):
        return serving.sharded_replica(
            art['model_dir'], mesh_axes={'dp': mesh_n},
            ckpt_dir=art['ckpt_dir'], config=_cfg(buckets=list(buckets)))
    return b


# ---------------------------------------------------------------------------
# satellite 1: the replica registration-handle seam on the Router
# ---------------------------------------------------------------------------

class _StubEngine(object):
    """Engine-protocol stub: controllable window, recorded calls."""

    feed_names = ['x']

    def __init__(self, window=None, result=1.0):
        self.window = dict(window or {})
        self.result = result
        self.shutdowns = []
        self.pushed = []

    def submit(self, feed, **kw):
        import concurrent.futures
        f = concurrent.futures.Future()
        f.set_result([np.asarray(feed['x']) * self.result])
        return f

    def stats_window(self):
        return dict(self.window)

    def push_rows(self, deltas):
        self.pushed.append(deltas)
        return sum(len(i) for i, _ in deltas.values())

    def shutdown(self, drain=True, timeout=None):
        self.shutdowns.append(drain)
        return True


def test_replica_handles_add_remove(obs_events):
    r = Router(window_s=0.0)
    e1, e2, e3 = _StubEngine(), _StubEngine(), _StubEngine()
    r.add_model('m', [e1, e2])
    view = r.replicas('m')
    rids = [v['rid'] for v in view]
    assert len(set(rids)) == 2
    assert all(v['host'] is None and v['key'] is None for v in view)
    # add_replica returns the handle; registry coordinates stick
    rid3 = r.add_replica('m', e3, host=5, key='5.m-1')
    view = {v['rid']: v for v in r.replicas('m')}
    assert view[rid3]['host'] == 5 and view[rid3]['key'] == '5.m-1'
    ev = obs_events('serving.replica.register')
    assert ev and ev[-1]['fields']['host'] == 5
    # pod_size gauge: local host + host 5
    assert obs.gauge('router.pod_size').value == 2
    # remove by handle: drained in the background, typed event
    got = r.remove_replica('m', rid3, drain=True, reason='scale_down')
    assert got is e3
    deadline = time.monotonic() + 5
    while not e3.shutdowns and time.monotonic() < deadline:
        time.sleep(0.01)
    assert e3.shutdowns == [True]
    ev = obs_events('serving.replica.drain')
    assert ev and ev[-1]['fields']['reason'] == 'scale_down'
    assert len(r.replicas('m')) == 2
    # unknown handle is a no-op, not an error
    assert r.remove_replica('m', 999999) is None
    # detach (host-loss posture): engine untouched
    rid1 = r.replicas('m')[0]['rid']
    r.remove_replica('m', rid1, drain=False, reason='host_lost')
    assert e1.shutdowns == []
    assert obs.gauge('router.pod_size').value == 1
    r.shutdown(drain=False)


def test_sample_windows_refreshes_pressure():
    r = Router(window_s=0.0)
    e = _StubEngine(window={'queue_depth': 3, 'inflight': 2,
                            'queue_high_water': 5})
    r.add_model('m', [e])
    s = r.sample_windows('m')
    assert s[0]['window']['queue_depth'] == 3
    e.window['queue_depth'] = 0
    s = r.sample_windows('m')
    assert s[0]['window']['queue_depth'] == 0
    r.shutdown(drain=False)


# ---------------------------------------------------------------------------
# autoscaling: queue-depth-driven capacity on the add/remove seam
# ---------------------------------------------------------------------------

def test_autoscaler_up_down_with_cooldown(obs_events):
    r = Router(window_s=0.0)
    hot = {'queue_depth': 6, 'queue_high_water': 6}
    cold = {'queue_depth': 0, 'queue_high_water': 0}
    e0 = _StubEngine(window=dict(hot))
    r.add_model('m', [e0])
    built = []

    def builder(reason):
        built.append(reason)
        return _StubEngine(window=dict(cold))

    a = Autoscaler(r, 'm', AutoscalePolicy(
        min_replicas=1, max_replicas=2, scale_up_at=4.0,
        scale_down_at=0.5, cooldown_s=0.2), builder=builder)
    assert a.tick() == 'up'
    # the build runs OFF the tick thread (poll must not stall on a
    # sharded restore); the replica lands shortly after
    deadline = time.monotonic() + 5
    while len(r.replicas('m')) < 2 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert built == ['scale_up']
    assert len(r.replicas('m')) == 2
    # cooldown: no immediate second action even though pressure persists
    assert a.tick() is None
    time.sleep(0.25)
    # at max_replicas: pressure can no longer scale up
    assert a.tick() is None
    # pressure drops -> scale down to min, draining the idle replica
    e0.window = dict(cold)
    time.sleep(0.25)
    assert a.tick() == 'down'
    assert len(r.replicas('m')) == 1
    time.sleep(0.25)
    assert a.tick() is None          # min_replicas floor
    ev = obs_events('serving.autoscale')
    assert [e['fields']['direction'] for e in ev] == ['up', 'down']
    ev = obs_events('serving.replica.drain')
    assert ev and ev[-1]['fields']['reason'] == 'scale_down'
    r.shutdown(drain=False)


def test_autoscale_policy_validation():
    with pytest.raises(ValueError, match='min_replicas'):
        AutoscalePolicy(min_replicas=0)
    with pytest.raises(ValueError, match='scale_down_at'):
        AutoscalePolicy(scale_up_at=1.0, scale_down_at=2.0)
    with pytest.raises(ValueError, match='builder'):
        Autoscaler(Router(), 'm', AutoscalePolicy())


# ---------------------------------------------------------------------------
# sharded replicas: program-only artifact + sharded-checkpoint restore
# ---------------------------------------------------------------------------

def test_save_serving_program_writes_no_params(artifacts):
    names = os.listdir(artifacts['model_dir'])
    assert '__model__.json' in names
    assert not [n for n in names if 'params' in n], names


def test_sharded_predictor_never_dense_and_matches(artifacts,
                                                   obs_events):
    pred = ShardedPredictor(artifacts['model_dir'],
                            mesh_axes={'dp': 8},
                            ckpt_dir=artifacts['ckpt_dir'])
    # the table lives as per-device row shards — never dense anywhere
    assert pred.shard_shapes()['emb_w'] == (VOCAB // 8, DIM)
    assert pred.state_step == 7
    out = pred.run({'ids': artifacts['probe']})
    np.testing.assert_allclose(np.asarray(out[0]), artifacts['ref'],
                               rtol=1e-4, atol=1e-5)
    sp = obs_events('serving.sharded_restore')
    assert sp and sp[-1]['fields']['restored'] == 2
    # reshard-on-restore: the same checkpoint (saved on dp=8) comes up
    # on a dp=4 serving mesh, still sharded, same scores
    pred4 = ShardedPredictor(artifacts['model_dir'],
                             mesh_axes={'dp': 4},
                             ckpt_dir=artifacts['ckpt_dir'])
    assert pred4.shard_shapes()['emb_w'] == (VOCAB // 4, DIM)
    out4 = pred4.run({'ids': artifacts['probe']})
    np.testing.assert_allclose(np.asarray(out4[0]), artifacts['ref'],
                               rtol=1e-4, atol=1e-5)


def test_sharded_predictor_serving_wire_zero_steady_compiles(artifacts):
    """The all_to_all lookup wire on the SERVING path: engine warmup
    pre-compiles the bucket set, then steady traffic performs zero
    compiles (the PR 8 contract, now over a sharded Program)."""
    eng = serving.sharded_replica(
        artifacts['model_dir'], mesh_axes={'dp': 8},
        ckpt_dir=artifacts['ckpt_dir'], config=_cfg(buckets=[4, 8]))
    try:
        exe = eng._model._exe
        misses0 = exe.cache_stats['misses']
        for i in range(6):
            n = 3 if i % 2 else 8     # both buckets exercised
            out = eng.predict({'ids': artifacts['probe'][:n]},
                              timeout=60)
            np.testing.assert_allclose(np.asarray(out[0]),
                                       artifacts['ref'][:n],
                                       rtol=1e-4, atol=1e-5)
        assert exe.cache_stats['misses'] == misses0
    finally:
        eng.shutdown()


def test_sharded_predictor_missing_state_is_typed(artifacts, tmp_path):
    partial = str(tmp_path / 'partial_ck')
    arrays, _ = ck.load_latest_verified(artifacts['ckpt_dir'])
    ck.save_sharded(os.path.join(partial, 'sharded_1'),
                    {'emb_w': arrays['emb_w']}, step=1)
    with pytest.raises(RuntimeError, match='fc_w'):
        ShardedPredictor(artifacts['model_dir'], mesh_axes={'dp': 8},
                         ckpt_dir=partial)


def test_sharded_predictor_needs_a_mesh(artifacts, tmp_path):
    # strip the mesh from a copy of the program artifact
    with open(os.path.join(artifacts['model_dir'],
                           '__model__.json')) as f:
        meta = json.load(f)
    meta['program'].pop('mesh', None)
    os.makedirs(str(tmp_path / 'm'))
    with open(str(tmp_path / 'm' / '__model__.json'), 'w') as f:
        json.dump(meta, f)
    with pytest.raises(ValueError, match='mesh'):
        ShardedPredictor(str(tmp_path / 'm'))


def test_sharded_replica_takes_row_deltas(artifacts):
    """The streaming freshness path lands on a SHARDED table: push_rows
    scatters into the mesh-placed array; scores move accordingly."""
    eng = serving.sharded_replica(
        artifacts['model_dir'], mesh_axes={'dp': 8},
        ckpt_dir=artifacts['ckpt_dir'], config=_cfg())
    try:
        probe = np.zeros((8, 2, 1), np.int64)     # every lookup hits row 0
        before = np.asarray(eng.predict({'ids': probe}, timeout=60)[0])
        rows = np.full((1, DIM), 3.0, np.float32)
        assert eng.push_rows({'emb_w': (np.array([0]), rows)}) == 1
        after = np.asarray(eng.predict({'ids': probe}, timeout=60)[0])
        assert not np.allclose(before, after)
    finally:
        eng.shutdown()


# ---------------------------------------------------------------------------
# pod registry + cross-host routing (in-process workers)
# ---------------------------------------------------------------------------

def _fake_model(delay=0.0, scale=2.0):
    class M(object):
        feed_names = ['x']

        def run(self, feed):
            if delay:
                time.sleep(delay)
            return [np.asarray(feed['x']) * scale]
    return M()


def _fake_engine(delay=0.0, scale=2.0, **cfg):
    cfg.setdefault('max_batch_size', 4)
    cfg.setdefault('buckets', [4])
    cfg.setdefault('max_queue_delay_ms', 0.5)
    return ServingEngine(_fake_model(delay, scale), ServingConfig(**cfg))


def test_pod_registry_roundtrip_and_retire(tmp_path, obs_events,
                                           transport):
    pod = str(tmp_path / 'pod')
    w = PodWorker(pod, host=0, beat_interval=0.05, transport=transport)
    r = PodRouter(pod, poll_s=0.05, window_s=0.05,
                  heartbeat_timeout=5.0, start=False)
    try:
        key = w.serve('m', _fake_engine())
        assert os.path.exists(os.path.join(
            pod, 'registry', 'replica.%s.json' % key))
        view = r.wait_for_replicas('m', 1, timeout=10)
        assert view[0]['host'] == 0 and view[0]['key'] == key
        out = r.predict('m', {'x': np.ones((2, 3), np.float32)},
                        timeout=20)
        np.testing.assert_allclose(out[0],
                                   2.0 * np.ones((2, 3), np.float32))
        # voluntary retire: registration file gone -> replica removed
        w.retire(key)
        deadline = time.monotonic() + 10
        while r.replicas('m') and time.monotonic() < deadline:
            r.poll()
            time.sleep(0.05)
        assert r.replicas('m') == []
        ev = obs_events('serving.replica.register')
        assert any(e['fields'].get('key') == key for e in ev)
    finally:
        r.shutdown(drain=False)
        w.shutdown()


def test_remote_typed_errors_cross_the_wire(tmp_path, transport):
    pod = str(tmp_path / 'pod')
    w = PodWorker(pod, host=0, beat_interval=0.05, transport=transport)
    r = PodRouter(pod, poll_s=0.05, window_s=0.05,
                  heartbeat_timeout=5.0, start=False)
    try:
        w.serve('m', _fake_engine())
        r.wait_for_replicas('m', 1, timeout=10)
        # a malformed feed fails TYPED through the wire (ValueError
        # from the remote engine, not an opaque timeout)
        fut = r.submit('m', {'wrong_name': np.ones((2, 3), np.float32)})
        with pytest.raises(ValueError, match='feed names'):
            fut.result(20)
    finally:
        r.shutdown(drain=False)
        w.shutdown()


def test_pod_host_loss_rerouted_futures_and_heal(tmp_path, obs_events,
                                                 transport):
    """The in-process self-healing drill: two hosts serve one model;
    host 1 dies mid-traffic (beats stop, spool freezes — SIGKILL as the
    router sees it); every future pending against it is re-routed to
    host 0 (ZERO dropped futures), the loss is typed HostLost, and the
    heal path builds a replacement on the survivor."""
    pod = str(tmp_path / 'pod')
    built = []

    def builder(reason):
        built.append(reason)
        return _fake_engine()

    w0 = PodWorker(pod, host=0, builders={'m': builder},
                   beat_interval=0.05, transport=transport)
    w1 = PodWorker(pod, host=1, beat_interval=0.05, transport=transport)
    r = PodRouter(pod, poll_s=0.05, window_s=0.05,
                  heartbeat_timeout=0.5, start=False)
    x = np.ones((2, 3), np.float32)
    try:
        w0.serve('m', _fake_engine())
        w1.serve('m', _fake_engine())
        r.wait_for_replicas('m', 2, timeout=10)
        # warm the dispatch path, then kill host 1 with traffic pending
        assert r.predict('m', {'x': x}, timeout=20)
        w1.simulate_death()
        futs = [r.submit('m', {'x': x}) for _ in range(12)]
        deadline = time.monotonic() + 15
        while not r.lost_hosts and time.monotonic() < deadline:
            r.poll()
            time.sleep(0.05)
        rec = r.lost_hosts[0]
        assert rec['host'] == 1 and rec['stale'] == [1]
        assert 'HostLost' in rec['error']           # typed verdict
        # zero dropped futures: every submit resolves with the right value
        for f in futs:
            np.testing.assert_allclose(f.result(30)[0], 2.0 * x)
        # self-heal: the survivor built + registered a replacement
        deadline = time.monotonic() + 20
        while len(r.replicas('m')) < 2 and time.monotonic() < deadline:
            r.poll()
            time.sleep(0.05)
        view = r.replicas('m')
        assert len(view) == 2 and all(v['host'] == 0 for v in view)
        assert built and built[0] == 'host_lost'
        ev = obs_events('serving.replica.lost')
        assert ev and ev[-1]['fields']['host'] == 1
        ev = obs_events('serving.replica.reshard')
        assert ev and ev[-1]['fields']['host'] == 0
        assert obs_events('router.host_lost')
        # a push against a bare-callable replica is refused TYPED
        # through the wire (DeltaUnsupported — no parameter scope), not
        # an opaque timeout: the remote error mapping covers the
        # publisher's failure posture
        from paddle_tpu.serving.engine import DeltaUnsupported
        with pytest.raises(DeltaUnsupported):
            r.push_deltas('m', {'w': (np.array([0]),
                                      np.zeros((1, 2), np.float32))})
        # the dead host is no longer a heal/scale candidate: a fresh
        # capacity request must land on the survivor, never on the
        # orphaned host-1 advert (its ctl mailbox answers nothing)
        assert 1 not in r._hosts
        token = r.request_heal('m', reason='scale_up')
        assert token is not None
        deadline = time.monotonic() + 20
        while len(r.replicas('m')) < 3 and time.monotonic() < deadline:
            r.poll()
            time.sleep(0.05)
        assert [v['host'] for v in r.replicas('m')] == [0, 0, 0]
    finally:
        r.shutdown(drain=False)
        w0.shutdown()
        w1.shutdown()


def test_pod_push_deltas_reaches_survivor_set(tmp_path, artifacts,
                                              transport):
    """Sharded replicas + host loss + heal, then Router.push_deltas —
    the DeltaPublisher contract against the RE-REGISTERED set: the push
    lands on every live (healed) replica through the wire."""
    pod = str(tmp_path / 'pod')
    w0 = PodWorker(pod, host=0,
                   builders={'rec': _builder(artifacts, 4)},
                   beat_interval=0.05, transport=transport)
    w1 = PodWorker(pod, host=1, beat_interval=0.05, transport=transport)
    r = PodRouter(pod, poll_s=0.05, window_s=0.05,
                  heartbeat_timeout=0.5, start=False)
    try:
        w0.serve('rec', _builder(artifacts, 8)('boot'))
        w1.serve('rec', _builder(artifacts, 4)('boot'))
        r.wait_for_replicas('rec', 2, timeout=30)
        w1.simulate_death()
        deadline = time.monotonic() + 15
        while not r.lost_hosts and time.monotonic() < deadline:
            r.poll()
            time.sleep(0.05)
        deadline = time.monotonic() + 60
        while len(r.replicas('rec')) < 2 and time.monotonic() < deadline:
            r.poll()
            time.sleep(0.05)
        assert all(v['host'] == 0 for v in r.replicas('rec'))
        rows = np.full((2, DIM), 0.25, np.float32)
        pushed = r.push_deltas('rec', {'emb_w': (np.array([0, 1]), rows)})
        assert pushed == 2                      # both healed replicas
        probe = np.zeros((8, 2, 1), np.int64)
        out = np.asarray(r.predict('rec', {'ids': probe}, timeout=60)[0])
        assert np.isfinite(out).all()
    finally:
        r.shutdown(drain=False)
        w0.shutdown()
        w1.shutdown()


def test_pod_autoscale_up_via_heal_and_down(tmp_path, obs_events,
                                            transport):
    pod = str(tmp_path / 'pod')
    built = []

    def builder(reason):
        built.append(reason)
        return _fake_engine()

    w = PodWorker(pod, host=0, builders={'m': builder},
                  beat_interval=0.05, transport=transport)
    r = PodRouter(pod, poll_s=0.05, window_s=0.0,
                  heartbeat_timeout=5.0, start=False)
    try:
        # a slow replica so queued pressure is visible in the window
        w.serve('m', _fake_engine(delay=0.05))
        r.wait_for_replicas('m', 1, timeout=10)
        a = r.enable_autoscale('m', AutoscalePolicy(
            min_replicas=1, max_replicas=2, scale_up_at=3.0,
            scale_down_at=0.25, cooldown_s=0.3))
        x = np.ones((1, 2), np.float32)
        futs = [r.submit('m', {'x': x}) for _ in range(10)]
        deadline = time.monotonic() + 20
        while len(r.replicas('m')) < 2 and time.monotonic() < deadline:
            r.poll()
            time.sleep(0.05)
        assert len(r.replicas('m')) == 2        # scaled up via heal
        assert built == ['scale_up']
        for f in futs:
            f.result(30)
        # idle -> scale back down to the floor
        deadline = time.monotonic() + 30
        while len(r.replicas('m')) > 1 and time.monotonic() < deadline:
            r.poll()
            time.sleep(0.1)
        assert len(r.replicas('m')) == 1
        dirs = [e['fields']['direction']
                for e in obs_events('serving.autoscale')]
        assert dirs[0] == 'up' and 'down' in dirs
    finally:
        r.shutdown(drain=False)
        w.shutdown()


def test_heal_failure_redispatches_to_capable_host(tmp_path,
                                                   obs_events,
                                                   transport):
    pod = str(tmp_path / 'pod')
    built = []

    def bad_builder(reason):
        raise RuntimeError('no capacity on this host')

    def good_builder(reason):
        built.append(reason)
        return _fake_engine()

    w1 = PodWorker(pod, host=1, builders={'m': bad_builder},
                   beat_interval=0.05, transport=transport)
    w2 = PodWorker(pod, host=2, builders={'m': good_builder},
                   beat_interval=0.05, transport=transport)
    r = PodRouter(pod, poll_s=0.05, window_s=0.05,
                  heartbeat_timeout=5.0, start=False)
    try:
        key = w2.serve('m', _fake_engine())
        r.wait_for_replicas('m', 1, timeout=10)
        # host 1 has fewer replicas -> picked first; its failure must
        # re-dispatch to host 2 (one bounded retry, typed event)
        token = r.request_heal('m', reason='drill')
        assert token is not None
        deadline = time.monotonic() + 20
        while len(r.replicas('m')) < 2 and time.monotonic() < deadline:
            r.poll()
            time.sleep(0.05)
        assert len(r.replicas('m')) == 2
        assert built == ['drill']
        ev = obs_events('serving.pod.heal_failed')
        assert ev and ev[-1]['fields']['host'] == 1
        ev = obs_events('serving.replica.reshard')
        assert ev and ev[-1]['fields']['host'] == 2
        del key
    finally:
        r.shutdown(drain=False)
        w1.shutdown()
        w2.shutdown()


def test_decode_engine_replica_behind_the_pod_wire(tmp_path, transport):
    """The decode path rides the same registry: a DecodeEngine replica
    registered by a PodWorker serves autoregressive requests through
    the PodRouter — result tuples (ids, scores) and decode kwargs
    (max_new_tokens) cross the wire, matching the in-process engine."""
    rng = np.random.RandomState(7)
    weights = {
        'w_dec': (rng.randn(8 + 6, 32) * 0.3).astype(np.float32),
        'u_dec': (rng.randn(8, 32) * 0.3).astype(np.float32),
        'b_dec': (rng.randn(1, 32) * 0.1).astype(np.float32),
        'w_q': (rng.randn(8, 6) * 0.3).astype(np.float32),
        'w_emb': (rng.randn(20, 8) * 0.3).astype(np.float32),
        'w_out': (rng.randn(8, 20) * 0.3).astype(np.float32),
        'b_out': (rng.randn(1, 20) * 0.1).astype(np.float32),
    }

    def build():
        return DecodeEngine(weights, DecodeConfig(
            slots=2, beam_size=3, max_len=8, src_cap=5))

    enc = (rng.randn(4, 6) * 0.5).astype(np.float32)
    local = build()
    want_ids, want_scores = local.submit(
        {'enc': enc}, max_new_tokens=6).result(60)
    local.shutdown()

    pod = str(tmp_path / 'pod')
    w = PodWorker(pod, host=0, beat_interval=0.05, transport=transport)
    r = PodRouter(pod, poll_s=0.05, window_s=0.05,
                  heartbeat_timeout=5.0, start=False)
    try:
        w.serve('mt', build())
        r.wait_for_replicas('mt', 1, timeout=10)
        got = r.submit('mt', {'enc': enc}, max_new_tokens=6).result(60)
        np.testing.assert_array_equal(np.asarray(got[0]), want_ids)
        np.testing.assert_allclose(np.asarray(got[1]), want_scores,
                                   rtol=1e-5, atol=1e-6)
    finally:
        r.shutdown(drain=False)
        w.shutdown()


def test_heal_chain_terminates_when_every_builder_fails(tmp_path,
                                                        obs_events,
                                                        transport):
    """The exclude set ACCUMULATES through the re-dispatch token chain:
    with every capable host failing its build, the chain ends in a
    typed heal_unroutable instead of ping-ponging forever."""
    def bad(reason):
        raise RuntimeError('corrupt checkpoint')

    pod_dir = str(tmp_path / 'pod')
    w1 = PodWorker(pod_dir, host=1, builders={'m': bad},
                   beat_interval=0.05, transport=transport)
    w2 = PodWorker(pod_dir, host=2, builders={'m': bad},
                   beat_interval=0.05, transport=transport)
    r = PodRouter(pod_dir, poll_s=0.05, window_s=0.05,
                  heartbeat_timeout=5.0, start=False)
    try:
        w2.serve('m', _fake_engine())
        r.wait_for_replicas('m', 1, timeout=10)
        assert r.request_heal('m', reason='drill') is not None
        deadline = time.monotonic() + 20
        while not obs_events('serving.pod.heal_unroutable') \
                and time.monotonic() < deadline:
            r.poll()
            time.sleep(0.05)
        assert obs_events('serving.pod.heal_unroutable')
        assert r.pending_heals() == {}          # chain terminated
        # exactly one failure per capable host, no ping-pong
        redispatches = obs_events('serving.pod.heal_redispatch')
        assert 1 <= len(redispatches) <= 2
        assert len(r.replicas('m')) == 1        # nothing half-built
    finally:
        r.shutdown(drain=False)
        w1.shutdown()
        w2.shutdown()


# ---------------------------------------------------------------------------
# tentpole: the rpc pod wire — frames, chaos, per-token streams, failover
# ---------------------------------------------------------------------------

def _mt_weights(vocab=20, dim=8, src=6, hidden=32, seed=7):
    rng = np.random.RandomState(seed)
    w = {
        'w_dec': (rng.randn(dim + src, hidden) * 0.3).astype(np.float32),
        'u_dec': (rng.randn(dim, hidden) * 0.3).astype(np.float32),
        'b_dec': (rng.randn(1, hidden) * 0.1).astype(np.float32),
        'w_q': (rng.randn(dim, src) * 0.3).astype(np.float32),
        'w_emb': (rng.randn(vocab, dim) * 0.3).astype(np.float32),
        'w_out': (rng.randn(dim, vocab) * 0.3).astype(np.float32),
        'b_out': (rng.randn(1, vocab) * 0.1).astype(np.float32),
    }
    enc = (rng.randn(4, src) * 0.5).astype(np.float32)
    return w, enc


def _wait(pred, timeout=10.0, step=0.02):
    deadline = time.monotonic() + timeout
    while not pred() and time.monotonic() < deadline:
        time.sleep(step)
    return pred()


class _PollPump(object):
    """Drive PodRouter.poll() from a background thread while a test
    body blocks on a stream — failover detection must not depend on
    the consumer's goodwill."""

    def __init__(self, router, period=0.05):
        self._r, self._period = router, period
        self._stop = threading.Event()
        self._t = threading.Thread(target=self._run, daemon=True)

    def _run(self):
        while not self._stop.is_set():
            self._r.poll()
            time.sleep(self._period)

    def __enter__(self):
        self._t.start()
        return self

    def __exit__(self, *exc):
        self._stop.set()
        self._t.join(5)


def test_transport_frame_roundtrip_and_counters():
    """The length-prefixed frame codec end to end: JSON header plus raw
    ndarray blobs cross a real socket BIT-EXACT (no base64, no pickle),
    and the wire telemetry counts frames/bytes both ways."""
    f_out0 = obs.counter('serving.transport.frames_out').value
    f_in0 = obs.counter('serving.transport.frames_in').value
    got = []
    ev = threading.Event()

    def handler(conn, header, arrays):
        conn.send({'uid': header['uid'], 'final': True,
                   'echo': header['meta']},
                  {k: v for k, v in arrays.items()})

    srv = RpcServer(handler)
    arrays = {
        'f:a': np.arange(12, dtype=np.float32).reshape(3, 4),
        'f:b': np.array([[1, -2], [3, -4]], np.int64),
        'f:c': np.array([True, False]),
    }

    def on_frame(header, arrs):
        got.append((header, arrs))
        ev.set()

    ch = Channel(srv.addr, on_frame, seed=1)
    try:
        meta = {'max_new_tokens': 6, 'nested': {'x': [1, 2.5, None]}}
        assert _wait(lambda: ch.send(
            {'op': 'submit', 'uid': 'u1', 'meta': meta}, arrays), 5)
        assert ev.wait(10), 'no echo frame'
        header, arrs = got[0]
        assert header['echo'] == meta          # JSON survives verbatim
        for name, want in arrays.items():
            assert arrs[name].dtype == want.dtype
            np.testing.assert_array_equal(arrs[name], want)
        assert obs.counter('serving.transport.frames_out').value > f_out0
        assert obs.counter('serving.transport.frames_in').value > f_in0
    finally:
        ch.close()
        srv.close()


def test_transport_overload_rejects_typed():
    """Wire-level admission: a server at max_inflight answers a typed
    ServerOverloaded error frame instead of queueing unboundedly — the
    engine admission contract, enforced one layer down."""
    release = threading.Event()

    def handler(conn, header, arrays):
        # reply later, off the reader thread (the engine posture)
        def finish():
            release.wait(20)
            conn.send({'uid': header['uid'], 'final': True})
        threading.Thread(target=finish, daemon=True).start()

    srv = RpcServer(handler, max_inflight=1)
    frames = []
    ev = threading.Event()

    def on_frame(header, arrs):
        frames.append(header)
        ev.set()

    ch = Channel(srv.addr, on_frame, seed=2)
    try:
        assert _wait(lambda: ch.send({'op': 'submit', 'uid': 'u1'}), 5)
        # second submit while the first is parked at the handler
        assert _wait(lambda: ch.send({'op': 'submit', 'uid': 'u2'}), 5)
        assert ev.wait(10)
        rejected = [h for h in frames if h.get('error')]
        assert rejected, frames
        assert rejected[0]['error']['type'] == 'ServerOverloaded'
        release.set()
        assert _wait(lambda: any(not h.get('error') for h in frames), 10)
    finally:
        release.set()
        ch.close()
        srv.close()


def test_chaos_garble_fails_typed_never_hangs():
    """A corrupted in-flight frame must surface as a typed
    TransportError at the reader — bad magic/bounds, not a hang and
    not a silently misparsed frame."""
    def handler(conn, header, arrays):
        conn.send({'uid': header['uid'], 'final': True},
                  {'a': arrays['f:a']})

    srv = RpcServer(handler)
    fi = FaultInjector(seed=3)
    proxy = fi.chaos_proxy(srv.addr)
    frames, errs = [], []
    ev = threading.Event()
    ch = Channel(proxy.addr, lambda h, a: (frames.append(h), ev.set()),
                 on_wire_error=errs.append, seed=11)
    a = np.ones((2, 3), np.float32)
    try:
        assert _wait(lambda: ch.send(
            {'op': 'submit', 'uid': 'u1'}, {'f:a': a}), 5)
        assert ev.wait(10)
        # corrupt the next server->client chunk: the reply frame
        proxy.garble(8, direction='down')
        ch.send({'op': 'submit', 'uid': 'u2'}, {'f:a': a})
        assert _wait(lambda: errs, 10), 'garble never surfaced'
        assert isinstance(errs[0], TransportError)
        assert obs.counter('serving.transport.errors').value >= 1
    finally:
        ch.close()
        proxy.close()
        srv.close()


def test_chaos_sever_reconnects_with_backoff():
    """A mid-stream connection cut is a network blip, not a dead host:
    the Channel redials on the shared utils/retry backoff schedule and
    traffic flows again through a NEW pairing."""
    def handler(conn, header, arrays):
        conn.send({'uid': header['uid'], 'final': True,
                   'echo': header.get('x')})

    srv = RpcServer(handler)
    fi = FaultInjector(seed=5)
    proxy = fi.chaos_proxy(srv.addr)
    frames, reconnects = [], []
    ev = threading.Event()
    ch = Channel(proxy.addr, lambda h, a: (frames.append(h), ev.set()),
                 on_reconnect=lambda: reconnects.append(1), seed=13)
    try:
        assert _wait(lambda: ch.send(
            {'op': 'submit', 'uid': 'u1', 'x': 1}), 5)
        assert ev.wait(10)
        proxy.sever()
        ev.clear()
        del frames[:]

        def resend():
            ch.send({'op': 'submit', 'uid': 'u2', 'x': 2})
            # a straggler duplicate echo of u1 may race the clear above
            # (the chaos proxy duplicates frames); the contract is that
            # the NEW pairing carries u2's echo, not that nothing stale
            # ever lands first
            return any(f.get('echo') == 2 for f in frames)

        assert _wait(resend, 15, step=0.1), 'no echo after sever'
        assert reconnects, 'reconnect hook never fired'
    finally:
        ch.close()
        proxy.close()
        srv.close()


def test_stream_inprocess_matches_submit(obs_events):
    """Router.stream over a local DecodeEngine: per-token callbacks
    arrive ordered 1..N, the final result is BIT-EQUAL to a plain
    submit of the same request, and TTFT is stamped end to end."""
    weights, enc = _mt_weights()

    def build():
        return DecodeEngine(weights, DecodeConfig(
            slots=2, beam_size=3, max_len=8, src_cap=5))

    ref_eng = build()
    want_ids, want_scores = ref_eng.submit(
        {'enc': enc}, max_new_tokens=6).result(60)
    ref_eng.shutdown()

    r = Router(window_s=0.0)
    r.add_model('mt', [build()])
    try:
        s = r.stream('mt', {'enc': enc}, max_new_tokens=6)
        toks = [(t, ids.copy()) for t, ids in s]
        assert [t for t, _ in toks] == list(range(1, 7))
        got_ids, got_scores = s.result(10)
        np.testing.assert_array_equal(np.asarray(got_ids), want_ids)
        np.testing.assert_allclose(np.asarray(got_scores), want_scores,
                                   rtol=1e-5, atol=1e-6)
        assert s.ttft_s is not None and s.ttft_s > 0
        assert obs_events('serving.stream.open')
        first = obs_events('serving.stream.first_token')
        assert first and first[-1]['fields']['ttft_s'] > 0
        # done-callbacks race the result() waiter: wait for the close
        assert _wait(lambda: obs_events('serving.stream.close'), 5)
        closes = obs_events('serving.stream.close')
        assert closes[-1]['fields']['tokens'] == 6
    finally:
        r.shutdown(drain=False)


def test_stream_backpressure_never_drops_or_reorders(tmp_path):
    """A slow consumer on the rpc wire: the producer decodes far ahead
    of the reader, yet every token arrives exactly once, in order —
    the wire may buffer or stall, it may never drop or reorder."""
    weights, enc = _mt_weights()

    def build():
        return DecodeEngine(weights, DecodeConfig(
            slots=2, beam_size=1, max_len=16, src_cap=5))

    pod = str(tmp_path / 'pod')
    w = PodWorker(pod, host=0, beat_interval=0.05, transport='rpc')
    r = PodRouter(pod, poll_s=0.05, window_s=0.05,
                  heartbeat_timeout=5.0, start=False)
    try:
        w.serve('mt', build())
        r.wait_for_replicas('mt', 1, timeout=30)
        s = r.stream('mt', {'enc': enc}, max_new_tokens=12)
        ts = []
        for t, ids in s:
            ts.append(t)
            time.sleep(0.03)          # consumer far slower than decode
        assert ts == list(range(1, 13)), ts
        ids, scores = s.result(10)
        assert np.asarray(ids).shape[1] == 12
    finally:
        r.shutdown(drain=False)
        w.shutdown()


def test_stream_on_file_wire_is_typed_error(tmp_path):
    """The file mailbox cannot carry per-token frames: asking it to
    stream fails TYPED at submit time, naming the rpc transport —
    never a silent fallback to a whole-response future."""
    weights, enc = _mt_weights()
    pod = str(tmp_path / 'pod')
    w = PodWorker(pod, host=0, beat_interval=0.05, transport='file')
    r = PodRouter(pod, poll_s=0.05, window_s=0.05,
                  heartbeat_timeout=5.0, start=False)
    try:
        w.serve('mt', DecodeEngine(weights, DecodeConfig(
            slots=2, beam_size=1, max_len=8, src_cap=5)))
        r.wait_for_replicas('mt', 1, timeout=30)
        with pytest.raises(ValueError, match="transport='rpc'"):
            s = r.stream('mt', {'enc': enc}, max_new_tokens=4)
            s.result(20)
    finally:
        r.shutdown(drain=False)
        w.shutdown()


def test_stream_cancel_frees_slot_and_pages():
    """Mid-stream disconnect posture: cancelling a live stream aborts
    the slot and returns its PAGES to the pool — an abandoned stream
    must not leak decode capacity."""
    weights, enc = _mt_weights()
    eng = DecodeEngine(weights, DecodeConfig(
        slots=2, beam_size=1, max_len=64, src_cap=5,
        page_size=4, pages=40, prefix_cache=False))
    r = Router(window_s=0.0)
    r.add_model('mt', [eng])
    try:
        base = eng.stats
        seen = []
        s = r.stream('mt', {'enc': enc}, max_new_tokens=60)
        for t, ids in s:
            seen.append(t)
            if t >= 3:
                break
        s.cancel()
        with pytest.raises(Exception) as ei:
            s.result(20)
        assert type(ei.value).__name__ in ('StreamCancelled',
                                           'CancelledError')
        assert _wait(lambda: eng.stats['slots_occupied'] == 0, 10)
        assert _wait(lambda: eng.stats['pages_free']
                     == base['pages_free'], 10), eng.stats
        assert eng.stats['cancelled'] >= 1
        # capacity really is back: a fresh request decodes to the end
        ids, scores = r.predict('mt', {'enc': enc}, timeout=60,
                                max_new_tokens=4)
        assert np.asarray(ids).shape[1] == 4
    finally:
        r.shutdown(drain=False)


def test_stream_cadence_zero_host_loss_is_typed(tmp_path, obs_events):
    """ckpt_every=0 means the stream opted OUT of failover: losing the
    host mid-generation surfaces a typed HostLost naming the cadence
    knob — never a resume from state that was never checkpointed and
    never a hang."""
    weights, enc = _mt_weights()

    def build():
        return DecodeEngine(weights, DecodeConfig(
            slots=2, beam_size=1, max_len=40, src_cap=5))

    pod = str(tmp_path / 'pod')
    w = PodWorker(pod, host=0, beat_interval=0.05, transport='rpc')
    r = PodRouter(pod, poll_s=0.05, window_s=0.05,
                  heartbeat_timeout=0.5, start=False)
    try:
        w.serve('mt', build())
        r.wait_for_replicas('mt', 1, timeout=30)
        r.predict('mt', {'enc': enc}, timeout=120, max_new_tokens=2)
        with _PollPump(r):
            s = r.stream('mt', {'enc': enc}, max_new_tokens=32)
            for t, ids in s:
                if t == 3:
                    w.simulate_death()
                    break
            with pytest.raises(HostLost, match='ckpt_every'):
                s.result(60)
        ev = obs_events('serving.stream.failover')
        assert ev and ev[-1]['fields']['resumed'] is False
    finally:
        r.shutdown(drain=False)
        w.shutdown()


def test_decode_stream_failover_token_exact(tmp_path, obs_events):
    """THE HEADLINE DRILL: a decode stream survives the death of the
    host generating it. Host 0 dies (SIGKILL posture: rpc frames
    freeze, beats stop, the checkpoint goes stale) mid-generation;
    the router re-routes the stream to the survivor, which resumes
    from the per-slot checkpoint. The client sees one ordered token
    sequence 1..N and a final result BIT-EQUAL to an uninterrupted
    reference — zero dropped futures, no restart from token 0."""
    weights, enc = _mt_weights()
    N = 32

    def build():
        return DecodeEngine(weights, DecodeConfig(
            slots=2, beam_size=1, max_len=40, src_cap=5))

    ref_eng = build()
    want_ids, want_scores = ref_eng.submit(
        {'enc': enc}, max_new_tokens=N).result(120)
    ref_eng.shutdown()

    pod = str(tmp_path / 'pod')
    w0 = PodWorker(pod, host=0, beat_interval=0.05, transport='rpc')
    w1 = PodWorker(pod, host=1, beat_interval=0.05, transport='rpc')
    r = PodRouter(pod, poll_s=0.05, window_s=0.05,
                  heartbeat_timeout=0.5, start=False)
    workers = {0: w0, 1: w1}
    resumes0 = obs.counter('serving.stream.resumes').value
    try:
        e0 = build()
        e1 = build()
        engines = {0: e0, 1: e1}
        # warm BOTH engines so post-kill compiles are attributable to
        # the resume path alone (the zero-new-signatures contract)
        for e in (e0, e1):
            e.submit({'enc': enc}, max_new_tokens=2).result(120)
        misses_before = {h: e.cache_stats()['misses']
                         for h, e in engines.items()}
        w0.serve('mt', e0)
        w1.serve('mt', e1)
        r.wait_for_replicas('mt', 2, timeout=60)

        toks, killed = [], []
        with _PollPump(r):
            s = r.stream('mt', {'enc': enc}, ckpt_every=2,
                         max_new_tokens=N)
            for t, ids in s:
                toks.append((t, np.asarray(ids).copy()))
                if t == 3 and not killed:
                    for info in list(r._known.values()):
                        if info['proxy'].outstanding():
                            workers[info['host']].simulate_death()
                            killed.append(info['host'])
            got_ids, got_scores = s.result(120)
        assert len(killed) == 1                      # one host died
        survivor = engines[1 - killed[0]]
        # one ordered stream, no gap, no duplicate, no restart at 0
        assert [t for t, _ in toks] == list(range(1, N + 1))
        # token-exact: final beams bit-equal to the uninterrupted run
        np.testing.assert_array_equal(np.asarray(got_ids), want_ids)
        np.testing.assert_allclose(np.asarray(got_scores), want_scores,
                                   rtol=1e-5, atol=1e-6)
        # the resume rode the checkpoint (typed event + counters), and
        # the survivor resumed WITHOUT compiling a new signature
        assert obs.counter('serving.stream.resumes').value == resumes0 + 1
        ev = obs_events('serving.stream.resume')
        assert ev, 'no stream.resume event'
        f = ev[-1]['fields']
        assert f['from_t'] >= 1 and f['replayed'] >= 0
        assert survivor.stats['resumed'] >= 1
        assert survivor.cache_stats()['misses'] \
            == misses_before[1 - killed[0]]
        ev = obs_events('router.host_lost')
        assert ev and ev[-1]['fields']['host'] == killed[0]
    finally:
        r.shutdown(drain=False)
        w0.shutdown()
        w1.shutdown()


# ---------------------------------------------------------------------------
# distributed tracing across the pod (docs/observability.md#tracing)
# ---------------------------------------------------------------------------

def test_trace_stitched_timeline_across_the_wire(tmp_path, obs_events,
                                                 transport):
    """One request over EACH wire produces ONE stitched timeline: the
    caller's trace context crosses the wire (rpc frame header / file
    __meta__ JSON), the worker re-enters it, and the collector stitches
    router + host spans into monotonic stage boundaries under a single
    trace_id."""
    weights, enc = _mt_weights()
    pod = str(tmp_path / 'pod')
    w = PodWorker(pod, host=0, beat_interval=0.05, transport=transport)
    r = PodRouter(pod, poll_s=0.05, window_s=0.05,
                  heartbeat_timeout=5.0, start=False)
    try:
        w.serve('mt', DecodeEngine(weights, DecodeConfig(
            slots=2, beam_size=1, max_len=12, src_cap=5)))
        r.wait_for_replicas('mt', 1, timeout=30)
        ctx = trace.new_trace()
        with trace.activate(ctx, node='client'):
            if transport == 'rpc':
                s = r.stream('mt', {'enc': enc}, max_new_tokens=6)
                assert [t for t, _ in s] == list(range(1, 7))
                s.result(60)
                # BOTH TTFT views exposed: client-side and the
                # server-side dispatch->token-1 twin off the frame header
                assert s.ttft_s is not None and s.ttft_s > 0
                assert s.server_ttft_s is not None
                assert 0 < s.server_ttft_s <= s.ttft_s
            else:
                r.predict('mt', {'enc': enc}, timeout=60,
                          max_new_tokens=6)
        r.spill_traces(force=True)
        coll = trace.TraceCollector(os.path.join(pod, 'traces'))
        coll.load()
        assert ctx.trace_id in coll.traces()
        tl = coll.timeline(ctx.trace_id)
        assert 'router' in tl['nodes'] and 'h0' in tl['nodes']
        serves = [s_ for s_ in tl['spans']
                  if s_['name'] == 'serving.pod.serve']
        assert serves and serves[0]['fields'].get('wire') == transport
        assert tl['orphans'] == []
        # stage boundaries exist and are MONOTONIC end to end
        names = [m['name'] for m in tl['milestones']]
        assert names[0] == 'admit' and names[-1] == 'done'
        assert 'serve' in names and 'dispatch' in names
        if transport == 'rpc':
            assert 'first_token' in names
        ts = [m['t'] for m in tl['milestones']]
        assert ts == sorted(ts)
        assert all(st['seconds'] >= 0 for st in tl['stages'])
    finally:
        r.shutdown(drain=False)
        w.shutdown()


def test_trace_survives_stream_failover_with_orphan_flag(tmp_path,
                                                         obs_events):
    """SIGKILL mid-stream: the resumed segment rides the ORIGINAL
    trace_id (the router re-activates the stashed context before the
    survivor dispatch) and the dead host's serve span — spilled open,
    never closed — is flagged as an orphan in the stitched timeline."""
    weights, enc = _mt_weights()
    N = 16

    def build():
        return DecodeEngine(weights, DecodeConfig(
            slots=2, beam_size=1, max_len=24, src_cap=5))

    pod = str(tmp_path / 'pod')
    w0 = PodWorker(pod, host=0, beat_interval=0.05, transport='rpc')
    w1 = PodWorker(pod, host=1, beat_interval=0.05, transport='rpc')
    r = PodRouter(pod, poll_s=0.05, window_s=0.05,
                  heartbeat_timeout=0.5, start=False)
    workers = {0: w0, 1: w1}
    try:
        w0.serve('mt', build())
        w1.serve('mt', build())
        r.wait_for_replicas('mt', 2, timeout=60)
        ctx = trace.new_trace()
        killed = []
        with _PollPump(r):
            with trace.activate(ctx, node='client'):
                s = r.stream('mt', {'enc': enc}, ckpt_every=2,
                             max_new_tokens=N)
            toks = []
            for t, ids in s:
                toks.append(t)
                if t == 3 and not killed:
                    for info in list(r._known.values()):
                        if info['proxy'].outstanding():
                            workers[info['host']].simulate_death()
                            killed.append(info['host'])
            s.result(120)
        assert len(killed) == 1
        assert toks == list(range(1, N + 1))     # token-exact resume
        r.spill_traces(force=True)
        coll = trace.TraceCollector(os.path.join(pod, 'traces'))
        coll.load()
        tl = coll.timeline(ctx.trace_id)
        serves = [s_ for s_ in tl['spans']
                  if s_['name'] == 'serving.pod.serve']
        hosts = {s_['node'] for s_ in serves}
        # BOTH segments — killed host's and survivor's — carry the
        # SAME trace_id
        assert hosts == {'h0', 'h1'}
        # the dead host's span never closed: flagged orphan
        assert len(tl['orphans']) >= 1
        orphan_nodes = {o['node'] for o in tl['orphans']}
        assert 'h%d' % killed[0] in orphan_nodes
        # the survivor's segment DID close inside the same trace
        closed = [s_ for s_ in serves if s_['t1'] is not None]
        assert any(s_['node'] == 'h%d' % (1 - killed[0])
                   for s_ in closed)
    finally:
        r.shutdown(drain=False)
        w0.shutdown()
        w1.shutdown()


def test_rpc_metrics_op_and_prom_dump(tmp_path):
    """Prometheus exposition over the pod: the rpc wire serves a
    `metrics` control frame (scrape without touching the registry
    process-locally) and the worker dumps the same text to
    `metrics.h<host>.prom` in the pod dir on its stats cadence."""
    pod = str(tmp_path / 'pod')
    w = PodWorker(pod, host=0, beat_interval=0.05, transport='rpc')
    r = PodRouter(pod, poll_s=0.05, window_s=0.05,
                  heartbeat_timeout=5.0, start=False)
    try:
        w.serve('m', _fake_engine())
        r.wait_for_replicas('m', 1, timeout=30)
        r.predict('m', {'x': np.ones((2, 3), np.float32)}, timeout=20)
        proxy = next(iter(r._known.values()))['proxy']
        text = proxy.metrics_text(timeout=10)
        assert '# TYPE' in text and '# HELP' in text
        assert 'serving_requests_total' in text
        # the file dump carries the SAME exposition format
        w._host_telemetry(force=True)
        path = os.path.join(pod, 'metrics.h0.prom')
        assert os.path.exists(path)
        assert '# TYPE' in open(path).read()
    finally:
        r.shutdown(drain=False)
        w.shutdown()


def test_set_mesh_data_axis_false_survives_round_trip():
    """The forced-replicate serving posture is a Program property like
    the amp flags: it must survive clone() and the _to_dict/_from_dict
    artifact round-trip (None would re-derive 'dp' on reload and
    silently re-shard request batches)."""
    p = framework.Program()
    p.set_mesh({'dp': 8}, data_axis=False)
    assert p._mesh_data_axis is False
    q = framework.Program._from_dict(p._to_dict())
    assert q.mesh_axes == {'dp': 8}
    assert q._mesh_data_axis is False
    assert p.clone()._mesh_data_axis is False
    # the default derivation is untouched
    d = framework.Program()
    d.set_mesh({'dp': 8})
    assert d._mesh_data_axis == 'dp'
    assert framework.Program._from_dict(
        d._to_dict())._mesh_data_axis == 'dp'


def test_pod_report_section(obs_events):
    obs.event('serving.replica.register', model='m', host=0, key='0.m-1')
    obs.event('serving.replica.register', model='m', host=1, key='1.m-1')
    obs.event('serving.replica.lost', model='m', host=1, key='1.m-1',
              pending=3)
    obs.event('router.host_lost', host=1, replicas=1, rerouted=3,
              heals=1)
    obs.event('serving.replica.reshard', model='m', host=0, key='0.m-2',
              token='t', heal_s=2.5)
    obs.event('serving.pod.heal_requested', model='m', host=0,
              token='t', reason='host_lost')
    obs.event('serving.autoscale', model='m', direction='up',
              replicas=1, pressure=5.0)
    text = obs_report.summarize(obs_events())
    assert '-- pod serving --' in text
    assert '2 registered across 2 host(s)' in text
    assert 'host LOST: h1' in text and '3 future(s) re-routed' in text
    assert 'reshard: model=m -> h0' in text
    assert 'autoscale: 1 up, 0 down' in text


def test_transport_streams_report_section(obs_events):
    obs.event('serving.transport.connect', addr=['127.0.0.1', 1])
    obs.event('serving.transport.reconnect', addr=['127.0.0.1', 1],
              attempts=3)
    obs.event('serving.transport.error', error='bad frame magic')
    obs.event('serving.stream.open', model='mt')
    obs.event('serving.stream.open', model='mt')
    obs.event('serving.stream.first_token', model='mt', ttft_s=0.2)
    obs.event('serving.stream.first_token', model='mt', ttft_s=0.4)
    obs.event('serving.stream.resume', model='mt', sid='s1', from_t=4,
              seen_t=5, replayed=1)
    obs.event('serving.stream.failover', model='mt', sid='None',
              resumed=False, seen_t=3)
    obs.event('serving.stream.close', model='mt', tokens=8, error=None)
    obs.event('serving.stream.close', model='mt', tokens=3,
              error='HostLost')
    text = obs_report.summarize(obs_events())
    assert '-- transport / streams --' in text
    assert '1 connect(s), 1 reconnect(s), 1 wire error(s)' in text
    assert 'streams: 2 opened, 2 closed (1 failed)' in text
    assert 'ttft: min=' in text
    assert '2 stream(s) lost a host, 1 resumed token-exact ' \
           '(1 token(s) replayed)' in text
    assert 'NOT resumed (ckpt_every=0)' in text


# ---------------------------------------------------------------------------
# the 2-process SIGKILL drill (the test_elastic.py harness, serving-side)
# ---------------------------------------------------------------------------

_POD_CHILD = r"""
import os, sys, time
import jax
jax.config.update('jax_platforms', 'cpu')
try:
    jax.config.update('jax_num_cpu_devices', 8)
except AttributeError:
    os.environ['XLA_FLAGS'] = (os.environ.get('XLA_FLAGS', '')
                               + ' --xla_force_host_platform_device_count=8')
import numpy as np
from paddle_tpu import serving

host = int(sys.argv[1])
pod_dir, model_dir, ckpt_dir = sys.argv[2], sys.argv[3], sys.argv[4]
mesh_n, heal_n = int(sys.argv[5]), int(sys.argv[6])
stop_file = sys.argv[7]
transport = sys.argv[8] if len(sys.argv) > 8 else 'file'


def build(n):
    def b(reason):
        return serving.sharded_replica(
            model_dir, mesh_axes={'dp': n}, ckpt_dir=ckpt_dir,
            config=serving.ServingConfig(max_batch_size=8, buckets=[8],
                                         max_queue_delay_ms=1.0))
    return b


w = serving.PodWorker(pod_dir, host=host, transport=transport,
                      builders={'rec': build(heal_n)})
w.serve('rec', build(mesh_n)('boot'))
print('SERVING %d' % host)
sys.stdout.flush()
while not os.path.exists(stop_file):
    time.sleep(0.1)
w.shutdown()
print('STOPPED %d' % host)
"""


@pytest.mark.slow
def test_two_process_sigkill_mid_traffic(artifacts, tmp_path,
                                         obs_events, transport):
    """The acceptance drill: 2 serving host PROCESSES each serve the
    set_mesh-sharded Program (row-sharded table restored from the
    sharded checkpoint — never dense); one is SIGKILLed mid-traffic.
    Runs on BOTH wires: the rpc leg is the real-TCP SIGKILL case (the
    kernel resets the sockets; the router must see HostLost, not hang).
    Asserts: typed HostLost, ZERO dropped futures (every submit
    resolves with the right scores), the replica re-shards onto the
    survivor (dp=8 -> dp=4 via the PR 10 restore path), and post-
    recovery traffic performs zero steady-state compiles."""
    pod = str(tmp_path / 'pod')
    stop_file = str(tmp_path / 'stop')
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = []
    for host, mesh_n, heal_n in ((0, 8, 4), (1, 8, 4)):
        env = dict(os.environ, PYTHONPATH=here)
        env.pop('JAX_PLATFORMS', None)
        env.pop('XLA_FLAGS', None)
        env.pop('PADDLE_TPU_OBS_DIR', None)
        procs.append(subprocess.Popen(
            [sys.executable, '-c', _POD_CHILD, str(host), pod,
             artifacts['model_dir'], artifacts['ckpt_dir'],
             str(mesh_n), str(heal_n), stop_file, transport],
            env=env, cwd=here, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True))
    r = PodRouter(pod, poll_s=0.1, window_s=0.1, heartbeat_timeout=1.5)
    probe, ref = artifacts['probe'], artifacts['ref']
    results, errors = [], []
    lock = threading.Lock()
    stop_traffic = threading.Event()

    def driver():
        while not stop_traffic.is_set():
            try:
                f = r.submit('rec', {'ids': probe})
                out = np.asarray(f.result(60)[0])
                with lock:
                    results.append(out)
            except Exception as e:  # noqa: BLE001 — counted, must be 0
                with lock:
                    errors.append(e)
            time.sleep(0.02)

    try:
        r.wait_for_replicas('rec', 2, timeout=240)
        threads = [threading.Thread(target=driver) for _ in range(4)]
        for t in threads:
            t.start()
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            with lock:
                if len(results) >= 8:
                    break
            time.sleep(0.1)
        with lock:
            n_before = len(results)
        assert n_before >= 8, 'no pre-kill traffic completed'
        # SIGKILL host 1 mid-traffic (the elastic harness fault)
        procs[1].send_signal(signal.SIGKILL)
        t_kill = time.monotonic()
        while time.monotonic() - t_kill < 120:
            if r.lost_hosts:
                break
            time.sleep(0.1)
        assert r.lost_hosts and r.lost_hosts[0]['host'] == 1
        assert 'HostLost' in r.lost_hosts[0]['error']
        # survivor heals: replacement replica re-sharded onto dp=4
        deadline = time.monotonic() + 240
        while time.monotonic() < deadline:
            view = r.replicas('rec')
            if len(view) >= 2 and all(v['host'] == 0 for v in view):
                break
            time.sleep(0.2)
        view = r.replicas('rec')
        assert len(view) >= 2 and all(v['host'] == 0 for v in view)
        ev = obs_events('serving.replica.reshard')
        assert ev and ev[-1]['fields']['host'] == 0
        assert ev[-1]['fields'].get('mesh') == [['dp', 4]]
        # steady state after recovery: more traffic, zero compiles on
        # the survivor (its stats publish the executor counters)
        caches0 = {v['key']: 0 for v in view}
        time.sleep(1.0)
        for info in r._known.values():
            caches0[info['proxy'].key] = \
                (info['proxy'].cache_stats() or {}).get('misses') or 0
        with lock:
            n_mid = len(results)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            with lock:
                if len(results) >= n_mid + 12:
                    break
            time.sleep(0.1)
        stop_traffic.set()
        for t in threads:
            t.join(60)
        for info in r._known.values():
            after = (info['proxy'].cache_stats() or {}).get('misses') or 0
            assert after == caches0.get(info['proxy'].key, after), \
                'replica %s compiled in steady state' % info['proxy'].key
        # ZERO dropped futures, every result correct
        assert errors == [], errors[:3]
        with lock:
            assert len(results) > n_before
            for out in results:
                np.testing.assert_allclose(out, ref, rtol=1e-4,
                                           atol=1e-5)
    finally:
        stop_traffic.set()
        with open(stop_file, 'w') as f:
            f.write('stop')
        r.shutdown(drain=False)
        for p in procs:
            try:
                p.wait(timeout=30)
            except subprocess.TimeoutExpired:
                p.kill()
    assert procs[1].returncode == -signal.SIGKILL
