"""fluid.nets composites vs numpy references (parity: reference
nets.py + tests/unittests coverage of the composites)."""
import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers, nets

from util import fresh_program


def _run(build, feed):
    with fresh_program() as (main, startup):
        outs = build()
        outs = outs if isinstance(outs, (list, tuple)) else [outs]
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        res = exe.run(main, feed=feed, fetch_list=list(outs))
    return [np.asarray(r) for r in res]


def test_glu_numeric():
    rng = np.random.RandomState(0)
    x = rng.randn(3, 8).astype('float32')

    def build():
        xv = layers.data(name='x', shape=[8], dtype='float32')
        return nets.glu(xv, dim=-1)
    out, = _run(build, {'x': x})
    a, b = x[:, :4], x[:, 4:]
    expect = a * (1.0 / (1.0 + np.exp(-b)))
    np.testing.assert_allclose(out, expect, rtol=1e-5, atol=1e-6)


def test_simple_img_conv_pool_shapes():
    rng = np.random.RandomState(1)
    x = rng.rand(2, 1, 12, 12).astype('float32')

    def build():
        xv = layers.data(name='x', shape=[1, 12, 12], dtype='float32')
        return nets.simple_img_conv_pool(
            input=xv, num_filters=4, filter_size=3, pool_size=2,
            pool_stride=2, act='relu')
    out, = _run(build, {'x': x})
    assert out.shape[0] == 2 and out.shape[1] == 4
    assert (out >= 0).all()  # relu


def test_img_conv_group_vgg_block():
    rng = np.random.RandomState(2)
    x = rng.rand(2, 3, 8, 8).astype('float32')

    def build():
        xv = layers.data(name='x', shape=[3, 8, 8], dtype='float32')
        return nets.img_conv_group(
            input=xv, conv_num_filter=[4, 4], pool_size=2, pool_stride=2,
            conv_with_batchnorm=True, conv_batchnorm_drop_rate=0.0,
            pool_type='max')
    out, = _run(build, {'x': x})
    assert out.shape == (2, 4, 4, 4)  # two 3x3 convs + 2x2/s2 pool
    assert np.isfinite(out).all()


def test_scaled_dot_product_attention_single_head():
    rng = np.random.RandomState(3)
    q = rng.rand(2, 5, 8).astype('float32')
    k = rng.rand(2, 7, 8).astype('float32')
    v = rng.rand(2, 7, 8).astype('float32')

    def build():
        qv = layers.data(name='q', shape=[5, 8], dtype='float32')
        kv = layers.data(name='k', shape=[7, 8], dtype='float32')
        vv = layers.data(name='v', shape=[7, 8], dtype='float32')
        return nets.scaled_dot_product_attention(qv, kv, vv, num_heads=1)
    out, = _run(build, {'q': q, 'k': k, 'v': v})
    s = np.einsum('bqd,bkd->bqk', q * (8 ** -0.5), k)
    w = np.exp(s - s.max(-1, keepdims=True))
    w /= w.sum(-1, keepdims=True)
    expect = np.einsum('bqk,bkd->bqd', w, v)
    np.testing.assert_allclose(out, expect, rtol=1e-4, atol=1e-5)


def test_scaled_dot_product_attention_multi_head():
    rng = np.random.RandomState(4)
    q = rng.rand(2, 5, 8).astype('float32')

    def build():
        qv = layers.data(name='q', shape=[5, 8], dtype='float32')
        return nets.scaled_dot_product_attention(qv, qv, qv, num_heads=2)
    out, = _run(build, {'q': q})
    assert out.shape == (2, 5, 8)
    assert np.isfinite(out).all()


def test_scaled_dot_product_attention_fused_matches_chain():
    """num_heads>1 + dropout 0 routes through the fused flash op; its
    output must match the unfused scale/matmul/softmax/matmul chain
    (which dropout_rate>0 still uses, in train mode)."""
    rng = np.random.RandomState(5)
    q = rng.rand(2, 6, 8).astype('float32')
    k = rng.rand(2, 4, 8).astype('float32')
    v = rng.rand(2, 4, 8).astype('float32')

    def build_fused():
        qv = layers.data(name='q', shape=[6, 8], dtype='float32')
        kv = layers.data(name='k', shape=[4, 8], dtype='float32')
        vv = layers.data(name='v', shape=[4, 8], dtype='float32')
        return nets.scaled_dot_product_attention(qv, kv, vv, num_heads=2)

    def build_chain():
        qv = layers.data(name='q', shape=[6, 8], dtype='float32')
        kv = layers.data(name='k', shape=[4, 8], dtype='float32')
        vv = layers.data(name='v', shape=[4, 8], dtype='float32')
        # dropout_rate>0 keeps the unfused path; prob 0.0 at the dropout
        # op level is a no-op numerically but still exercises that chain
        out = nets.scaled_dot_product_attention(qv, kv, vv, num_heads=2,
                                                dropout_rate=1e-12)
        return out

    feed = {'q': q, 'k': k, 'v': v}
    fused, = _run(build_fused, feed)
    chain, = _run(build_chain, feed)
    np.testing.assert_allclose(fused, chain, rtol=2e-4, atol=1e-5)
