"""bf16 mixed precision: numerics stay close to fp32, dtype stays fp32."""
import numpy as np

import paddle_tpu.fluid as fluid

from util import fresh_program


def _build_and_train(amp, steps=10):
    with fresh_program() as (main, startup):
        x = fluid.layers.data(name='x', shape=[16], dtype='float32')
        y = fluid.layers.data(name='y', shape=[1], dtype='float32')
        h = fluid.layers.fc(input=x, size=32, act='relu')
        pred = fluid.layers.fc(input=h, size=1)
        cost = fluid.layers.mean(
            fluid.layers.square_error_cost(input=pred, label=y))
        fluid.optimizer.SGD(learning_rate=0.05).minimize(cost)
        if amp:
            fluid.amp.decorate_program(main)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        rng = np.random.RandomState(3)
        xs = rng.rand(32, 16).astype('float32')
        ys = (xs.sum(axis=1, keepdims=True) * 0.1).astype('float32')
        losses = []
        for _ in range(steps):
            loss, = exe.run(main, feed={'x': xs, 'y': ys},
                            fetch_list=[cost])
            losses.append(float(loss))
        return losses


def test_amp_matches_fp32_closely():
    fp32 = _build_and_train(amp=False)
    bf16 = _build_and_train(amp=True)
    assert bf16[-1] < bf16[0], "amp training diverged"
    # same trajectory within bf16 tolerance
    np.testing.assert_allclose(fp32, bf16, rtol=0.1, atol=1e-2)


def test_amp_output_dtype_stays_fp32():
    with fresh_program() as (main, startup):
        x = fluid.layers.data(name='x', shape=[8], dtype='float32')
        out = fluid.layers.fc(input=x, size=4)
        fluid.amp.decorate_program(main)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        res, = exe.run(main, feed={'x': np.ones((2, 8), 'float32')},
                       fetch_list=[out])
        assert res.dtype == np.float32
