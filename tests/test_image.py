"""dataset.image augmentation pipeline (parity: reference
python/paddle/dataset/image.py + tests/test_image.py behavior)."""
import numpy as np

from paddle_tpu.dataset import image, flowers


def test_resize_short_aspect():
    im = (np.random.rand(100, 200, 3) * 255).astype('uint8')
    out = image.resize_short(im, 50)
    assert out.shape == (50, 100, 3)
    tall = image.resize_short(im.transpose(1, 0, 2), 50)
    assert tall.shape == (100, 50, 3)
    assert out.dtype == np.uint8


def test_resize_identity_and_values():
    im = np.arange(16, dtype='float32').reshape(4, 4)
    assert np.array_equal(image.resize_short(im, 4), im)
    # upscaling a constant image stays constant
    const = np.full((10, 12, 3), 7, dtype='uint8')
    assert (image.resize_short(const, 20) == 7).all()


def test_crops_and_flip():
    im = (np.random.rand(60, 80, 3) * 255).astype('uint8')
    cc = image.center_crop(im, 32)
    assert cc.shape == (32, 32, 3)
    assert np.array_equal(cc, im[14:46, 24:56])
    rc = image.random_crop(im, 32)
    assert rc.shape == (32, 32, 3)
    fl = image.left_right_flip(im)
    assert np.array_equal(fl, im[:, ::-1, :])
    gray = im[:, :, 0]
    assert image.center_crop(gray, 32, is_color=False).shape == (32, 32)
    assert np.array_equal(image.left_right_flip(gray, is_color=False),
                          gray[:, ::-1])


def test_to_chw():
    im = np.random.rand(8, 9, 3).astype('float32')
    assert image.to_chw(im).shape == (3, 8, 9)


def test_simple_transform_train_and_eval():
    im = (np.random.rand(300, 400, 3) * 255).astype('uint8')
    tr = image.simple_transform(im, 256, 224, True,
                                mean=[103.94, 116.78, 123.68])
    assert tr.shape == (3, 224, 224) and tr.dtype == np.float32
    ev = image.simple_transform(im, 256, 224, False, mean=127.5)
    assert ev.shape == (3, 224, 224)
    # eval path is deterministic
    ev2 = image.simple_transform(im, 256, 224, False, mean=127.5)
    assert np.array_equal(ev, ev2)
    # per-channel mean actually subtracted
    raw = image.simple_transform(im, 256, 224, False)
    m = image.simple_transform(im, 256, 224, False, mean=[10., 20., 30.])
    np.testing.assert_allclose(raw[0] - m[0], 10.0, atol=1e-5)
    np.testing.assert_allclose(raw[2] - m[2], 30.0, atol=1e-5)


def test_load_image_bytes_roundtrip(tmp_path):
    import io
    from PIL import Image as PILImage
    arr = (np.random.rand(20, 30, 3) * 255).astype('uint8')
    buf = io.BytesIO()
    PILImage.fromarray(arr).save(buf, format='PNG')
    out = image.load_image_bytes(buf.getvalue())
    assert np.array_equal(out, arr)
    p = tmp_path / 'x.png'
    p.write_bytes(buf.getvalue())
    assert np.array_equal(image.load_image(str(p)), arr)
    gray = image.load_image(str(p), is_color=False)
    assert gray.shape == (20, 30)


def test_flowers_reader_feeds_augmented_samples():
    r = flowers.train(use_xmap=False)
    img, label = next(r())
    assert img.shape == (3 * 224 * 224,) and img.dtype == np.float32
    assert 0 <= label < 102
    ev = flowers.test(use_xmap=True, buffered_size=8)
    imgs = [s for _, s in zip(range(4), ev())]
    assert all(i[0].shape == (3 * 224 * 224,) for i in imgs)


def test_simple_transform_batch_matches_per_image():
    """Native C++ batch kernel (csrc/image_aug.cpp) vs numpy per-image:
    same crop geometry and mean handling; values within 1 uint8 level
    (bilinear tie-rounding may differ by 1 ulp on real resizes)."""
    rng = np.random.RandomState(3)
    batch = (rng.rand(4, 300, 400, 3) * 255).astype('uint8')
    mean = [10., 20., 30.]
    out = image.simple_transform_batch(batch, 256, 224, False, mean=mean)
    ref = np.stack([image.simple_transform(im, 256, 224, False, mean=mean)
                    for im in batch])
    assert out.shape == (4, 3, 224, 224) and out.dtype == np.float32
    assert np.abs(out - ref).max() <= 1.0
    # train path: deterministic per seed, varies across seeds
    a = image.simple_transform_batch(batch, 256, 224, True, seed=5)
    b = image.simple_transform_batch(batch, 256, 224, True, seed=5)
    c = image.simple_transform_batch(batch, 256, 224, True, seed=6)
    np.testing.assert_array_equal(a, b)
    assert not np.array_equal(a, c)


def test_simple_transform_batch_fallback_deterministic(monkeypatch):
    """The numpy fallback honors `seed` (and a full CHW mean image works
    on both paths)."""
    from paddle_tpu.utils import native
    rng = np.random.RandomState(4)
    batch = (rng.rand(3, 260, 340, 3) * 255).astype('uint8')
    mimg = (rng.rand(3, 224, 224) * 50).astype('float32')
    nat = image.simple_transform_batch(batch, 256, 224, False, mean=mimg)
    monkeypatch.setattr(native, 'image_transform_batch',
                        lambda *a, **k: None)
    a = image.simple_transform_batch(batch, 256, 224, True, seed=5)
    b = image.simple_transform_batch(batch, 256, 224, True, seed=5)
    c = image.simple_transform_batch(batch, 256, 224, True, seed=6)
    np.testing.assert_array_equal(a, b)
    assert not np.array_equal(a, c)
    fb = image.simple_transform_batch(batch, 256, 224, False, mean=mimg)
    if nat is not None:
        assert np.abs(np.asarray(nat) - fb).max() <= 1.0


def test_batch_images_from_tar(tmp_path):
    import tarfile, io
    from PIL import Image as PILImage
    tar_path = tmp_path / 'data.tar'
    img2label = {}
    with tarfile.open(tar_path, 'w') as tf:
        for i in range(5):
            arr = (np.random.rand(8, 8, 3) * 255).astype('uint8')
            buf = io.BytesIO()
            PILImage.fromarray(arr).save(buf, format='PNG')
            data = buf.getvalue()
            info = tarfile.TarInfo('img_%d.png' % i)
            info.size = len(data)
            tf.addfile(info, io.BytesIO(data))
            img2label['img_%d.png' % i] = i % 3
    meta = image.batch_images_from_tar(str(tar_path), 'toy', img2label,
                                       num_per_batch=2)
    files = open(meta).read().splitlines()
    assert len(files) == 3  # 5 images, 2 per batch
    total = 0
    for f in files:
        z = np.load(f, allow_pickle=True)
        assert len(z['data']) == len(z['label'])
        total += len(z['label'])
        decoded = image.load_image_bytes(z['data'][0])
        assert decoded.shape == (8, 8, 3)
    assert total == 5
