"""Numeric forward + gradient checks for the sequence op family against
independent numpy references (parity: reference
tests/unittests/test_seq_pool.py, test_sequence_softmax_op.py,
test_sequence_expand.py, test_sequence_conv.py, test_row_conv_op.py,
test_gru_op.py, test_lstm_op.py)."""
import numpy as np
import pytest

import jax.numpy as jnp
import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers
from paddle_tpu.fluid.backward import append_backward
from paddle_tpu.fluid.executor import global_scope

from util import fresh_program

LENS = [3, 1, 4]
D = 2


def _lod_feed(rng, d=D, lens=LENS):
    total = sum(lens)
    data = rng.rand(total, d).astype('float32')
    return fluid.create_lod_tensor(data, [list(lens)]), data


def _split(data, lens=LENS):
    out, off = [], 0
    for l in lens:
        out.append(data[off:off + l])
        off += l
    return out


def _run(build, feed):
    with fresh_program() as (main, startup):
        outs = build()
        outs = outs if isinstance(outs, (list, tuple)) else [outs]
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        res = exe.run(main, feed=feed, fetch_list=list(outs))
    return [np.asarray(r) for r in res]


# ---------------------------------------------------------------------------
# pooling / softmax / expand / first / last
# ---------------------------------------------------------------------------

@pytest.mark.parametrize('ptype,ref', [
    ('sum', lambda s: s.sum(0)),
    ('average', lambda s: s.mean(0)),
    ('sqrt', lambda s: s.sum(0) / np.sqrt(len(s))),
    ('max', lambda s: s.max(0)),
    ('first', lambda s: s[0]),
    ('last', lambda s: s[-1]),
])
def test_sequence_pool_types(ptype, ref):
    rng = np.random.RandomState(0)
    t, data = _lod_feed(rng)

    def build():
        x = layers.data(name='x', shape=[D], dtype='float32', lod_level=1)
        return layers.sequence_pool(input=x, pool_type=ptype)
    out, = _run(build, {'x': t})
    expect = np.stack([ref(s) for s in _split(data)])
    np.testing.assert_allclose(out, expect, rtol=1e-5, atol=1e-6)


def test_sequence_first_last_step():
    rng = np.random.RandomState(1)
    t, data = _lod_feed(rng)

    def build():
        x = layers.data(name='x', shape=[D], dtype='float32', lod_level=1)
        return [layers.sequence_first_step(input=x),
                layers.sequence_last_step(input=x)]
    first, last = _run(build, {'x': t})
    np.testing.assert_allclose(first, np.stack([s[0] for s in _split(data)]),
                               rtol=1e-6)
    np.testing.assert_allclose(last, np.stack([s[-1] for s in _split(data)]),
                               rtol=1e-6)


def test_sequence_softmax():
    rng = np.random.RandomState(2)
    total = sum(LENS)
    data = rng.rand(total, 1).astype('float32')
    t = fluid.create_lod_tensor(data, [list(LENS)])

    def build():
        x = layers.data(name='x', shape=[1], dtype='float32', lod_level=1)
        return layers.sequence_softmax(input=x)
    out, = _run(build, {'x': t})
    ref = []
    for s in _split(data):
        e = np.exp(s - s.max())
        ref.append(e / e.sum())
    np.testing.assert_allclose(out, np.concatenate(ref), rtol=1e-5,
                               atol=1e-6)


def test_sequence_expand_rows():
    rng = np.random.RandomState(3)
    x_data = rng.rand(3, D).astype('float32')           # one row per seq
    y_t, _ = _lod_feed(rng)

    def build():
        x = layers.data(name='xrow', shape=[D], dtype='float32')
        y = layers.data(name='y', shape=[D], dtype='float32', lod_level=1)
        return layers.sequence_expand(x=x, y=y)
    out, = _run(build, {'xrow': x_data, 'y': y_t})
    expect = np.concatenate(
        [np.repeat(x_data[i:i + 1], l, axis=0) for i, l in enumerate(LENS)])
    np.testing.assert_allclose(out, expect, rtol=1e-6)


def test_sequence_reshape_and_mask():
    with fresh_program() as (main, startup):
        x = layers.data(name='x', shape=[2], dtype='float32', lod_level=1)
        m = layers.sequence_mask(
            layers.data(name='lens', shape=[1], dtype='int64'), maxlen=5)
        r = layers.sequence_reshape(input=x, new_dim=4)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        t = fluid.create_lod_tensor(
            np.arange(12, dtype='float32').reshape(6, 2), [[2, 4]])
        mv, rv = exe.run(main, feed={
            'x': t, 'lens': np.array([[2], [4]], 'int64')},
            fetch_list=[m, r])
    mv = np.asarray(mv)
    np.testing.assert_array_equal(
        mv.reshape(2, 5), [[1, 1, 0, 0, 0], [1, 1, 1, 1, 0]])
    # 2 cols -> 4 cols halves each sequence's steps
    rv = np.asarray(rv)
    assert rv.shape[-1] == 4


# ---------------------------------------------------------------------------
# context convs
# ---------------------------------------------------------------------------

def test_sequence_conv_numeric():
    rng = np.random.RandomState(4)
    t, data = _lod_feed(rng)
    n_filt, clen = 3, 3
    w = (rng.rand(clen * D, n_filt) - 0.5).astype('float32')

    def conv_ref(seq):
        T = len(seq)
        out = np.zeros((T, n_filt), 'float32')
        for i in range(T):
            ctx = []
            for off in range(-(clen - 1) // 2, (clen - 1) // 2 + 1):
                j = i + off
                ctx.append(seq[j] if 0 <= j < T else np.zeros(D, 'float32'))
            out[i] = np.concatenate(ctx) @ w
        return out

    with fresh_program() as (main, startup):
        x = layers.data(name='x', shape=[D], dtype='float32', lod_level=1)
        y = layers.sequence_conv(input=x, num_filters=n_filt,
                                 filter_size=clen, bias_attr=False)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        scope = global_scope()
        wname = [n for n in scope.vars if 'sequence_conv' in n][0]
        scope.vars[wname] = jnp.asarray(w)
        out, = exe.run(main, feed={'x': t}, fetch_list=[y])
    expect = np.concatenate([conv_ref(s) for s in _split(data)])
    np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-4, atol=1e-5)


def test_row_conv_numeric():
    rng = np.random.RandomState(5)
    t, data = _lod_feed(rng)
    k = 2  # future context
    w = (rng.rand(k + 1, D) - 0.5).astype('float32')

    def ref(seq):
        T = len(seq)
        out = np.zeros_like(seq)
        for i in range(T):
            for j in range(k + 1):
                if i + j < T:
                    out[i] += w[j] * seq[i + j]
        return out

    with fresh_program() as (main, startup):
        x = layers.data(name='x', shape=[D], dtype='float32', lod_level=1)
        y = layers.row_conv(input=x, future_context_size=k)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        scope = global_scope()
        wname = [n for n in scope.vars if 'row_conv' in n][0]
        scope.vars[wname] = jnp.asarray(w)
        out, = exe.run(main, feed={'x': t}, fetch_list=[y])
    expect = np.concatenate([ref(s) for s in _split(data)])
    np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-4, atol=1e-5)


# ---------------------------------------------------------------------------
# recurrent: gru / lstm numerics vs independent numpy scans
# ---------------------------------------------------------------------------

def _sigmoid(v):
    return 1.0 / (1.0 + np.exp(-v))


def test_dynamic_gru_numeric():
    rng = np.random.RandomState(6)
    DH = 3
    lens = [2, 4]
    total = sum(lens)
    xin = (rng.rand(total, 3 * DH) - 0.5).astype('float32')
    t = fluid.create_lod_tensor(xin, [lens])
    w = (rng.rand(DH, 3 * DH) - 0.5).astype('float32')

    def gru_ref(seq):
        h = np.zeros(DH, 'float32')
        out = []
        for x_t in seq:
            g = x_t[:2 * DH] + h @ w[:, :2 * DH]
            u = _sigmoid(g[:DH])
            r = _sigmoid(g[DH:])
            c = np.tanh(x_t[2 * DH:] + (r * h) @ w[:, 2 * DH:])
            h = u * h + (1 - u) * c
            out.append(h.copy())
        return np.stack(out)

    with fresh_program() as (main, startup):
        x = layers.data(name='x', shape=[3 * DH], dtype='float32',
                        lod_level=1)
        y = layers.dynamic_gru(input=x, size=DH)   # bias default-init to 0
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        scope = global_scope()
        wname = [n for n in scope.vars if 'gru' in n and '.w_' in n][0]
        scope.vars[wname] = jnp.asarray(w)
        out, = exe.run(main, feed={'x': t}, fetch_list=[y])
    off = 0
    expect = []
    for l in lens:
        expect.append(gru_ref(xin[off:off + l]))
        off += l
    np.testing.assert_allclose(np.asarray(out), np.concatenate(expect),
                               rtol=1e-4, atol=1e-5)


def test_dynamic_lstm_numeric_no_peepholes():
    rng = np.random.RandomState(7)
    DH = 3
    lens = [3, 2]
    total = sum(lens)
    xin = (rng.rand(total, 4 * DH) - 0.5).astype('float32')
    t = fluid.create_lod_tensor(xin, [lens])
    w = (rng.rand(DH, 4 * DH) - 0.5).astype('float32')

    def lstm_ref(seq):
        h = np.zeros(DH, 'float32')
        c = np.zeros(DH, 'float32')
        out = []
        for x_t in seq:
            g = x_t + h @ w
            gi, gf, gc, go = np.split(g, 4)
            i, f, o = _sigmoid(gi), _sigmoid(gf), _sigmoid(go)
            cand = np.tanh(gc)
            c = f * c + i * cand
            h = o * np.tanh(c)
            out.append(h.copy())
        return np.stack(out)

    with fresh_program() as (main, startup):
        x = layers.data(name='x', shape=[4 * DH], dtype='float32',
                        lod_level=1)
        h, _ = layers.dynamic_lstm(input=x, size=4 * DH,
                                   use_peepholes=False)  # zero-init bias
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        scope = global_scope()
        wname = [n for n in scope.vars if 'lstm' in n and '.w_' in n][0]
        scope.vars[wname] = jnp.asarray(w)
        out, = exe.run(main, feed={'x': t}, fetch_list=[h])
    off = 0
    expect = []
    for l in lens:
        expect.append(lstm_ref(xin[off:off + l]))
        off += l
    np.testing.assert_allclose(np.asarray(out), np.concatenate(expect),
                               rtol=1e-4, atol=1e-5)


def test_dynamic_gru_grad_finite_diff():
    rng = np.random.RandomState(8)
    DH = 3
    lens = [2, 3]
    xin = (rng.rand(sum(lens), 3 * DH) - 0.5).astype('float32')
    t = fluid.create_lod_tensor(xin, [lens])
    with fresh_program() as (main, startup):
        x = layers.data(name='x', shape=[3 * DH], dtype='float32',
                        lod_level=1)
        h = layers.dynamic_gru(input=x, size=DH)
        loss = layers.reduce_sum(layers.sequence_pool(h, 'sum'))
        append_backward(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        scope = global_scope()
        wname = [n for n in scope.vars if 'gru' in n and '.w_' in n][0]
        g, = exe.run(main, feed={'x': t}, fetch_list=[wname + '@GRAD'])
        g = np.asarray(g)
        w0 = np.asarray(scope.vars[wname]).copy()
        eps, idx = 1e-3, (1, 2)
        vals = {}
        for sign in (1, -1):
            wp = w0.copy()
            wp[idx] += sign * eps
            scope.vars[wname] = jnp.asarray(wp)
            vals[sign] = float(np.asarray(
                exe.run(main, feed={'x': t}, fetch_list=[loss])[0]).squeeze())
        fd = (vals[1] - vals[-1]) / (2 * eps)
    assert np.isclose(g[idx], fd, rtol=2e-2, atol=1e-4), (g[idx], fd)
