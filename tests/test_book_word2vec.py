"""End-to-end n-gram word2vec (reference fluid/tests/book/test_word2vec.py)."""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid

from util import fresh_program


def test_word2vec_converges():
    with fresh_program() as (main, startup):
        word_dict = paddle.dataset.imikolov.build_dict()
        dict_size = len(word_dict)
        EMB, HID, N = 32, 64, 5
        words = [fluid.layers.data(name='word_%d' % i, shape=[1],
                                   dtype='int64') for i in range(N)]
        embeds = [fluid.layers.embedding(
            input=w, size=[dict_size, EMB],
            param_attr=fluid.ParamAttr(name='shared_w')) for w in words[:-1]]
        concat = fluid.layers.concat(input=embeds, axis=1)
        hidden = fluid.layers.fc(input=concat, size=HID, act='sigmoid')
        predict = fluid.layers.softmax(
            fluid.layers.fc(input=hidden, size=dict_size))
        cost = fluid.layers.cross_entropy(input=predict, label=words[-1])
        avg_cost = fluid.layers.mean(x=cost)
        fluid.optimizer.Adam(learning_rate=3e-2).minimize(avg_cost)

        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        feeder = fluid.DataFeeder(place=fluid.CPUPlace(), feed_list=words)
        reader = paddle.batch(paddle.dataset.imikolov.train(word_dict, N),
                              batch_size=512)
        first = last = None
        for epoch in range(12):
            for batch in reader():
                loss, = exe.run(main, feed=feeder.feed(batch),
                                fetch_list=[avg_cost])
                if first is None:
                    first = float(np.asarray(loss).squeeze())
                last = float(np.asarray(loss).squeeze())
        # the synthetic imikolov chain is 80% deterministic (imikolov.py):
        # uniform-vocab CE is ~7.6; the model must actually learn the chain
        assert first > 6.0 and last < 1.5, (first, last)
