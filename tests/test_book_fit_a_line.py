"""End-to-end linear regression (reference fluid/tests/book/test_fit_a_line.py)."""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid

from util import fresh_program


def test_fit_a_line_converges():
    with fresh_program() as (main, startup):
        x = fluid.layers.data(name='x', shape=[13], dtype='float32')
        y = fluid.layers.data(name='y', shape=[1], dtype='float32')
        y_predict = fluid.layers.fc(input=x, size=1, act=None)
        cost = fluid.layers.square_error_cost(input=y_predict, label=y)
        avg_cost = fluid.layers.mean(x=cost)
        fluid.optimizer.SGD(learning_rate=0.01).minimize(avg_cost)

        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        feeder = fluid.DataFeeder(place=fluid.CPUPlace(), feed_list=[x, y])
        reader = paddle.batch(paddle.dataset.uci_housing.train(),
                              batch_size=23)
        first = None
        for epoch in range(10):
            for batch in reader():
                loss, = exe.run(main, feed=feeder.feed(batch),
                                fetch_list=[avg_cost])
                if first is None:
                    first = float(loss)
        assert float(loss) < first * 0.2, (first, float(loss))


def test_infer_after_train_and_save_load(tmp_path):
    with fresh_program() as (main, startup):
        x = fluid.layers.data(name='x', shape=[13], dtype='float32')
        y = fluid.layers.data(name='y', shape=[1], dtype='float32')
        y_predict = fluid.layers.fc(input=x, size=1, act=None)
        cost = fluid.layers.mean(
            fluid.layers.square_error_cost(input=y_predict, label=y))
        fluid.optimizer.SGD(learning_rate=0.01).minimize(cost)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        xin = np.random.rand(4, 13).astype('float32')
        yin = np.random.rand(4, 1).astype('float32')
        exe.run(main, feed={'x': xin, 'y': yin}, fetch_list=[cost])
        fluid.io.save_inference_model(str(tmp_path), ['x'], [y_predict], exe,
                                      main_program=main)
        prog2, feed_names, fetch_vars = fluid.io.load_inference_model(
            str(tmp_path), exe)
        assert feed_names == ['x']
        out, = exe.run(prog2, feed={'x': xin}, fetch_list=fetch_vars)
        assert out.shape == (4, 1)
