"""Fluid-level tensor parallelism: TensorParallelTranspiler places
fc/embedding parameters by parallel.auto_tp_rules over a tp mesh axis —
layouts only, so tp == single-device exactly."""
import numpy as np
import pytest

import jax
from jax.sharding import NamedSharding

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid.executor import global_scope

from util import fresh_program


def _train(mode, steps=2, seed=61):
    from paddle_tpu.models import transformer as T
    rng = np.random.RandomState(seed)
    vocab, seq, batch = 32, 8, 4
    feed_ids = {n: rng.randint(1, vocab, size=(batch, seq)).astype('int64')
                for n in ('src_word', 'trg_word', 'lbl_word')}
    with fresh_program() as (main, startup):
        avg_cost, _, feeds = T.transformer(
            vocab, vocab, seq, n_layer=1, d_model=16, n_head=2, d_inner=32,
            dropout_rate=0.0)
        fluid.optimizer.Adam(learning_rate=1e-3).minimize(avg_cost)
        if mode == 'tp':
            fluid.TensorParallelTranspiler(tp=2).transpile(main)
        elif mode == 'dp_tp':
            fluid.DistributeTranspiler().transpile(trainer_id=0, trainers=2)
            fluid.TensorParallelTranspiler(tp=2).transpile(main)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        losses = [float(exe.run(main, feed=feed_ids,
                                fetch_list=[avg_cost])[0])
                  for _ in range(steps)]
        sharded = [n for n, v in global_scope().vars.items()
                   if isinstance(v, jax.Array)
                   and isinstance(v.sharding, NamedSharding)
                   and 'tp' in str(v.sharding.spec)]
    return losses, sharded


def test_tp_matches_single_device_and_actually_shards():
    base, _ = _train(None)
    tp, sharded = _train('tp')
    assert base[0] != base[1]
    np.testing.assert_allclose(tp, base, rtol=2e-4)
    # fc weights AND their Adam moments carry the tp layout
    assert any('.w' in n or 'emb' in n for n in sharded), sharded
    assert any('moment' in n for n in sharded), sharded


def test_dp_tp_matches_single_device():
    base, _ = _train(None)
    both, sharded = _train('dp_tp')
    np.testing.assert_allclose(both, base, rtol=2e-4)
    assert sharded


def test_tp_validation_and_pp_composition():
    with fresh_program() as (main, startup):
        x = fluid.layers.data(name='x', shape=[4], dtype='float32')
        fluid.layers.relu(x)
        with pytest.raises(ValueError, match='no tensor-parallelizable'):
            fluid.TensorParallelTranspiler(tp=2).transpile(main)
    with pytest.raises(ValueError, match='tp must be'):
        fluid.TensorParallelTranspiler(tp=1)

    # pp x tp composes (both transpile orders), and the annotation names
    # both axes
    from paddle_tpu.models import transformer as T
    for order in ('pp_first', 'tp_first'):
        with fresh_program() as (main, startup):
            avg_cost, _, _ = T.transformer(32, 32, 8, n_layer=2, d_model=16,
                                           n_head=2, d_inner=32,
                                           dropout_rate=0.0, pp_decoder=True)
            fluid.optimizer.SGD(learning_rate=0.01).minimize(avg_cost)
            if order == 'pp_first':
                fluid.PipelineTranspiler(n_micro=2).transpile(main)
                fluid.TensorParallelTranspiler(tp=2).transpile(main)
            else:
                fluid.TensorParallelTranspiler(tp=2).transpile(main)
                fluid.PipelineTranspiler(n_micro=2).transpile(main)
            assert main._dist_config['pp_size'] == 2
            assert main._dist_config['tp_size'] == 2
            assert main._dist_config['mesh_axes'] == ('tp', 'pp'), \
                main._dist_config['mesh_axes']


@pytest.mark.parametrize('order', ['pp_first', 'tp_first'])
def test_dp_pp_tp_three_way_matches_single_device(order):
    """The Megatron large-model layout: dp x pp x tp on one mesh — a
    pipelined Fluid Transformer decoder with tp-sharded stage weights
    trains identically to the single-device program."""
    from paddle_tpu.models import transformer as T
    rng = np.random.RandomState(81)
    vocab, seq, batch = 32, 8, 8
    feed_ids = {n: rng.randint(1, vocab, size=(batch, seq)).astype('int64')
                for n in ('src_word', 'trg_word', 'lbl_word')}

    def run(transpile):
        with fresh_program() as (main, startup):
            avg_cost, _, _ = T.transformer(
                vocab, vocab, seq, n_layer=2, d_model=16, n_head=2,
                d_inner=32, dropout_rate=0.0, pp_decoder=True)
            fluid.optimizer.Adam(learning_rate=1e-3).minimize(avg_cost)
            if transpile:
                if order == 'pp_first':
                    fluid.PipelineTranspiler(n_micro=2).transpile(main)
                    fluid.TensorParallelTranspiler(tp=2).transpile(main)
                else:
                    fluid.TensorParallelTranspiler(tp=2).transpile(main)
                    fluid.PipelineTranspiler(n_micro=2).transpile(main)
                fluid.DistributeTranspiler().transpile(
                    trainer_id=0, trainers=2)
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            losses = [float(exe.run(main, feed=feed_ids,
                                    fetch_list=[avg_cost])[0])
                      for _ in range(3)]
            sharded = [n for n, v in global_scope().vars.items()
                       if isinstance(v, jax.Array)
                       and isinstance(v.sharding, NamedSharding)
                       and 'tp' in str(v.sharding.spec)]
        return losses, sharded

    base, _ = run(False)
    three, sharded = run(True)
    assert base[0] != base[1]
    np.testing.assert_allclose(three, base, rtol=2e-4)
    assert sharded, 'no tp-sharded params on the 3-way mesh'


def test_tp_with_zero_composes_dp_sharding(monkeypatch):
    """shard_optimizer_states + tp: accumulators carry BOTH axes where a
    dim allows; dp capped away entirely (2 devices, tp=2) must not crash."""
    from paddle_tpu.models import transformer as T
    # the tiny test model's 1-D vars are all under the production ZeRO
    # floor; drop it so the ('tp','dp')-product path is exercised
    from paddle_tpu.fluid import executor as executor_mod
    monkeypatch.setattr(executor_mod, '_ZERO_MIN_SIZE', 0)
    rng = np.random.RandomState(71)
    vocab, seq, batch = 32, 8, 4
    feed_ids = {n: rng.randint(1, vocab, size=(batch, seq)).astype('int64')
                for n in ('src_word', 'trg_word', 'lbl_word')}
    with fresh_program() as (main, startup):
        avg_cost, _, feeds = T.transformer(
            vocab, vocab, seq, n_layer=1, d_model=16, n_head=2, d_inner=32,
            dropout_rate=0.0)
        fluid.optimizer.Adam(learning_rate=1e-3).minimize(avg_cost)
        cfg = fluid.DistributeTranspilerConfig()
        t = fluid.DistributeTranspiler(config=cfg)
        t.transpile(trainer_id=0, trainers=4, slice_var_up=True)
        fluid.TensorParallelTranspiler(tp=2).transpile(main)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        import warnings as _w
        with _w.catch_warnings(record=True) as caught:
            _w.simplefilter('always')
            loss = float(exe.run(main, feed=feed_ids,
                                 fetch_list=[avg_cost])[0])
        assert np.isfinite(loss)
        # the ('tp','dp')-product fix leaves nothing to forfeit: a 1-D
        # var whose only dim is taken by tp now shards over the product
        forfeits = [str(w.message) for w in caught
                    if 'forfeited' in str(w.message)]
        assert not forfeits, forfeits
        specs = {n: v.sharding.spec
                 for n, v in global_scope().vars.items()
                 if isinstance(v, jax.Array)
                 and isinstance(v.sharding, NamedSharding)}
        # some tp-matched Adam moment composed BOTH axes
        assert any('tp' in str(s) and 'dp' in str(s)
                   for n, s in specs.items() if 'moment' in n), specs
        # 1-D accumulators shard over the full ('tp','dp') product: each
        # device holds size/(tp*dp) elements — the ZeRO memory scaling
        composed_1d = [n for n, v in global_scope().vars.items()
                       if isinstance(v, jax.Array) and v.ndim == 1
                       and 'moment' in n
                       and v.sharding.spec == (('tp', 'dp'),)]
        assert composed_1d, specs
        for n in composed_1d:
            v = global_scope().vars[n]
            n_mesh = len(v.sharding.device_set)
            assert v.addressable_shards[0].data.size == v.size // n_mesh, n

    # degenerate: only 2 devices visible -> dp caps to 1, mesh is tp-only;
    # ZeRO branches must not KeyError on the absent dp axis
    import jax as _jax
    devs = _jax.devices()[:2]
    import unittest.mock as mock
    with fresh_program() as (main, startup):
        avg_cost, _, feeds = T.transformer(
            vocab, vocab, seq, n_layer=1, d_model=16, n_head=2, d_inner=32,
            dropout_rate=0.0)
        fluid.optimizer.Adam(learning_rate=1e-3).minimize(avg_cost)
        t = fluid.DistributeTranspiler()
        t.transpile(trainer_id=0, trainers=2, slice_var_up=True)
        fluid.TensorParallelTranspiler(tp=2).transpile(main)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        with mock.patch.object(_jax, 'devices', lambda *a: devs):
            loss = float(exe.run(main, feed=feed_ids,
                                 fetch_list=[avg_cost])[0])
        assert np.isfinite(loss)
