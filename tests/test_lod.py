"""LoDTensor semantics (parity: reference python/paddle/fluid/lod_tensor.py
+ tests/unittests/test_lod_tensor.py): lengths<->offsets, validation,
SeqValue round-trip, and feeding LoD data through the Executor."""
import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers
from paddle_tpu.fluid.lod_tensor import (LoDTensor, create_lod_tensor,
                                         create_random_int_lodtensor)

from util import fresh_program


def test_lengths_offsets_roundtrip():
    t = LoDTensor(np.zeros((6, 2), 'float32'), [[2, 1, 3]])
    assert t.recursive_sequence_lengths() == [[2, 1, 3]]
    assert t.lod() == [[0, 2, 3, 6]]
    t.set_lod([[0, 1, 4, 6]])
    assert t.recursive_sequence_lengths() == [[1, 3, 2]]


def test_validity_check():
    good = LoDTensor(np.zeros((6, 1)), [[2, 4]])
    assert good.has_valid_recursive_sequence_lengths()
    bad = LoDTensor(np.zeros((6, 1)), [[2, 5]])
    assert not bad.has_valid_recursive_sequence_lengths()
    with pytest.raises(ValueError):
        create_lod_tensor(np.zeros((6, 1)), [[2, 5]])


def test_create_lod_tensor_from_list():
    t = create_lod_tensor([[1, 2, 3], [4], [5, 6]], None)
    assert t.recursive_sequence_lengths() == [[3, 1, 2]]
    assert t.data.shape == (6, 1)
    np.testing.assert_array_equal(t.data.squeeze(-1), [1, 2, 3, 4, 5, 6])


def test_create_random_int_lodtensor():
    t = create_random_int_lodtensor([[2, 3]], base_shape=[1], place=None,
                                    low=0, high=9)
    assert t.data.shape == (5, 1)
    assert t.data.dtype == np.int64
    assert (t.data >= 0).all() and (t.data <= 9).all()


def test_seq_value_roundtrip_level1():
    t = create_lod_tensor(np.arange(12, dtype='float32').reshape(6, 2),
                          [[2, 1, 3]])
    sv = t.to_seq_value()
    assert sv.data.shape == (3, 3, 2)          # [batch, max_len, d]
    assert list(np.asarray(sv.lengths)) == [2, 1, 3]
    # pads are zero
    assert float(np.asarray(sv.data)[1, 1:].sum()) == 0.0
    back = LoDTensor.from_seq_value(sv)
    np.testing.assert_array_equal(back.data, t.data)
    assert back.recursive_sequence_lengths() == [[2, 1, 3]]


def test_seq_value_roundtrip_level2():
    # 2 'documents' of 2 and 1 sentences; 3 sentences total
    t = create_lod_tensor(np.arange(8, dtype='float32').reshape(8, 1),
                          [[2, 1], [3, 2, 3]])
    sv = t.to_seq_value()
    assert sv.outer_lengths is not None
    assert list(np.asarray(sv.outer_lengths[-1])) == [2, 1]
    back = LoDTensor.from_seq_value(sv)
    np.testing.assert_array_equal(back.data, t.data)
    assert back.recursive_sequence_lengths() == [[2, 1], [3, 2, 3]]


def test_seq_value_roundtrip_level3():
    """Arbitrary-depth LoD (reference lod_tensor.h recursive LoD table):
    every level above the innermost rides the SeqValue as one outer-lengths
    vector, outermost first, and survives the device round-trip."""
    # 2 books of [2, 1] chapters; 3 chapters of [2, 1, 2] sentences;
    # 5 sentences of [2, 3, 1, 2, 2] words = 10 rows
    lens = [[2, 1], [2, 1, 2], [2, 3, 1, 2, 2]]
    t = create_lod_tensor(np.arange(10, dtype='float32').reshape(10, 1), lens)
    assert t.has_valid_recursive_sequence_lengths()
    sv = t.to_seq_value()
    assert len(sv.outer_lengths) == 2
    assert list(np.asarray(sv.outer_lengths[0])) == [2, 1]
    assert list(np.asarray(sv.outer_lengths[1])) == [2, 1, 2]
    back = LoDTensor.from_seq_value(sv)
    np.testing.assert_array_equal(back.data, t.data)
    assert back.recursive_sequence_lengths() == lens
    # SeqValue is a pytree: deep LoD must survive jit tracing untouched
    import jax
    sv2 = jax.jit(lambda s: s)(sv)
    assert back.recursive_sequence_lengths() == \
        LoDTensor.from_seq_value(sv2).recursive_sequence_lengths()


def test_multilevel_validity_check():
    # level counts must chain: len(level k) == sum(level k-1)
    bad = LoDTensor(np.zeros((5, 1)), [[2, 1], [2, 3]])  # 3 != 2 entries
    assert not bad.has_valid_recursive_sequence_lengths()
    good = LoDTensor(np.zeros((5, 1)), [[2, 1], [1, 2, 2]])
    assert good.has_valid_recursive_sequence_lengths()
    with pytest.raises(ValueError):
        create_lod_tensor(np.zeros((5, 1)), [[2, 1], [2, 3]])


def test_lod_tensor_array():
    """fluid.LoDTensorArray (reference core.LoDTensorArray, a
    vector<LoDTensor>): append coerces raw arrays, list semantics hold."""
    arr = fluid.LoDTensorArray()
    arr.append(np.ones((2, 3), 'float32'))
    arr.append(create_lod_tensor(np.zeros((3, 1)), [[1, 2]]))
    assert len(arr) == 2
    assert isinstance(arr[0], LoDTensor)
    assert arr[1].recursive_sequence_lengths() == [[1, 2]]
    # every mutation path coerces: ctor, extend, +=, insert, setitem
    arr2 = fluid.LoDTensorArray([np.zeros((1, 1))])
    arr2.extend([np.ones((2, 2))])
    arr2 += [np.ones((1, 3))]
    arr2.insert(0, np.zeros((4, 1)))
    arr2[1] = np.full((2, 2), 7.0)
    assert all(isinstance(t, LoDTensor) for t in arr2)
    assert float(arr2[1].data[0, 0]) == 7.0


def test_create_lod_tensor_from_nested_list():
    t = create_lod_tensor([[[1, 2], [3]], [[4, 5, 6]]], None)
    assert t.recursive_sequence_lengths() == [[2, 1], [2, 1, 3]]
    np.testing.assert_array_equal(t.data.squeeze(-1), [1, 2, 3, 4, 5, 6])


def test_nested_lod_roundtrip_fuzz():
    """Randomized depth-1..4 LoD tensors survive to_seq_value /
    from_seq_value exactly (lengths and data), and the derived lengths
    always validate — guards the recursive encoding."""
    rng = np.random.RandomState(7)
    for _ in range(60):
        depth = int(rng.randint(1, 5))
        # build level lengths top-down: level k entries = sum(level k-1)
        levels = [[int(rng.randint(1, 4))
                   for _ in range(int(rng.randint(1, 4)))]]
        for _ in range(depth - 1):
            levels.append([int(rng.randint(1, 4))
                           for _ in range(sum(levels[-1]))])
        total = sum(levels[-1])
        d = int(rng.randint(1, 3))
        t = LoDTensor(rng.randn(total, d).astype('float32'), levels)
        assert t.has_valid_recursive_sequence_lengths(), levels
        back = LoDTensor.from_seq_value(t.to_seq_value())
        assert back.recursive_sequence_lengths() == levels
        np.testing.assert_array_equal(back.data, t.data)


def test_sequence_pool_drops_innermost_lod_level():
    """Pooling a depth-2 LoD consumes the innermost level (reference
    sequence_pool_op): output rows are one per inner sequence, grouped
    under the former outer level."""
    with fresh_program() as (main, startup):
        x = layers.data(name='x', shape=[1], dtype='float32', lod_level=2)
        pooled = layers.sequence_pool(input=x, pool_type='sum')
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        t = create_lod_tensor(
            np.array([[1.], [2.], [3.], [10.], [20.], [40.]], 'float32'),
            [[2, 1], [2, 1, 3]])
        out, = exe.run(main, feed={'x': t}, fetch_list=[pooled],
                       return_numpy=False)
    # inner sums: [1+2, 3, 10+20+40] grouped as [[3, 3], [70]]
    assert out.recursive_sequence_lengths() == [[2, 1]]
    np.testing.assert_allclose(np.asarray(out.data).squeeze(-1),
                               [3., 3., 70.])


def test_executor_feed_lod_tensor_sequence_pool():
    """Feeding a LoDTensor runs masked sequence ops with true lengths."""
    with fresh_program() as (main, startup):
        x = layers.data(name='x', shape=[1], dtype='float32', lod_level=1)
        pooled = layers.sequence_pool(input=x, pool_type='sum')
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        t = create_lod_tensor(
            np.array([[1.], [2.], [3.], [10.], [20.]], 'float32'),
            [[3, 2]])
        out, = exe.run(main, feed={'x': t}, fetch_list=[pooled])
    np.testing.assert_allclose(np.asarray(out).squeeze(-1), [6., 30.])


def test_executor_feed_lod_tensor_mean_ignores_pads():
    """mean over a sequence var averages valid tokens only (the padded
    layout must not leak pad garbage into losses)."""
    with fresh_program() as (main, startup):
        x = layers.data(name='x', shape=[1], dtype='float32', lod_level=1)
        m = layers.mean(x)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        t = create_lod_tensor(
            np.array([[3.], [5.], [100.]], 'float32'), [[2, 1]])
        out, = exe.run(main, feed={'x': t}, fetch_list=[m])
    np.testing.assert_allclose(float(np.asarray(out).squeeze()), 36.0)


def test_reduce_on_seq_var_time_vs_feature_axis():
    """Reductions crossing the time axis mask pads; reductions over other
    axes keep the sequence layout without poisoning pads with ±inf."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.fluid.lowering import SeqValue, get_rule, Ctx
    ctx = Ctx(jax.random.key(0))
    sv = SeqValue(jnp.ones((2, 3, 4)), jnp.asarray([3, 1], jnp.int32))
    # over last dim: stays a sequence, finite everywhere
    out = get_rule('reduce_max')({'X': [sv]}, {'dim': [-1]}, ctx)['Out']
    assert isinstance(out, SeqValue)
    assert np.isfinite(np.asarray(out.data)).all()
    # over everything: pads excluded (here all data is 1.0)
    tot = get_rule('reduce_sum')({'X': [sv]}, {}, ctx)['Out']
    assert float(np.asarray(tot)) == (3 + 1) * 4
    # integer dtype must not overflow on min/max fill
    iv = SeqValue(jnp.full((2, 3), 5, jnp.int32), jnp.asarray([3, 1],
                                                              jnp.int32))
    assert int(np.asarray(get_rule('reduce_max')({'X': [iv]}, {},
                                                 ctx)['Out'])) == 5
    assert int(np.asarray(get_rule('reduce_min')({'X': [iv]}, {},
                                                 ctx)['Out'])) == 5


def test_fetch_lod_output_returns_unpadded():
    with fresh_program() as (main, startup):
        x = layers.data(name='x', shape=[2], dtype='float32', lod_level=1)
        y = layers.scale(x, scale=2.0)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        t = create_lod_tensor(np.ones((5, 2), 'float32'), [[2, 3]])
        out, = exe.run(main, feed={'x': t}, fetch_list=[y])
    # flattened [total_tokens, d] like the reference LoDTensor
    assert np.asarray(out).shape == (5, 2)
    np.testing.assert_allclose(np.asarray(out), 2.0)


def test_create_lod_tensor_list_validates_given_lens():
    """The list branch must honor recursive_seq_lens like the reference:
    a mismatched feed raises instead of silently deriving other lengths,
    and scalar list data lands as int64 (round-4 advisor)."""
    from paddle_tpu.fluid.lod_tensor import create_lod_tensor
    data = [[1, 2, 3], [4, 5]]
    t = create_lod_tensor(data, [[3, 2]])
    assert t.data.dtype == np.int64
    assert t.recursive_sequence_lengths() == [[3, 2]]
    with pytest.raises(ValueError, match='do not match'):
        create_lod_tensor(data, [[2, 3]])
