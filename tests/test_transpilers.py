"""Transpiler behavior: memory_optimize -> remat; inference BN fold.

Parity: reference transpiler/memory_optimization_transpiler.py (liveness
buffer reuse -> here jax.checkpoint rematerialisation) and
transpiler/inference_transpiler.py (conv+BN weight folding).
"""
import numpy as np
import pytest

import jax

import paddle_tpu.fluid as fluid

from util import fresh_program


def _mlp_program():
    x = fluid.layers.data(name='x', shape=[8], dtype='float32')
    y = fluid.layers.data(name='y', shape=[1], dtype='float32')
    h = fluid.layers.fc(input=x, size=16, act='relu')
    h = fluid.layers.fc(input=h, size=16, act='relu')
    pred = fluid.layers.fc(input=h, size=1)
    cost = fluid.layers.mean(
        fluid.layers.square_error_cost(input=pred, label=y))
    fluid.optimizer.SGD(learning_rate=0.01).minimize(cost)
    return cost


def _trace_step(main, startup, cost):
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    feed = {'x': np.random.rand(4, 8).astype('float32'),
            'y': np.random.rand(4, 1).astype('float32')}
    exe.run(main, feed=feed, fetch_list=[cost])
    (compiled,) = [c for c in exe._cache.values() if c.ad_idx is not None]
    from paddle_tpu.fluid.executor import global_scope
    persist = {n: global_scope().vars[n] for n in compiled.persist_in}
    feed_dev = {k: jax.numpy.asarray(v) for k, v in feed.items()}
    jaxpr = jax.make_jaxpr(compiled._step)(persist, feed_dev,
                                           jax.random.key(0))
    return compiled, str(jaxpr)


def test_memory_optimize_wires_remat():
    with fresh_program() as (main, startup):
        cost = _mlp_program()
        fluid.memory_optimize(main)
        compiled, jaxpr = _trace_step(main, startup, cost)
    assert compiled.use_remat
    assert 'remat' in jaxpr


def test_no_remat_by_default():
    with fresh_program() as (main, startup):
        cost = _mlp_program()
        compiled, jaxpr = _trace_step(main, startup, cost)
    assert not compiled.use_remat
    assert 'remat' not in jaxpr


def test_memory_optimize_invalidates_jit_cache():
    """Flipping the remat flag after a run must recompile, not reuse."""
    with fresh_program() as (main, startup):
        cost = _mlp_program()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        feed = {'x': np.zeros((4, 8), 'float32'),
                'y': np.zeros((4, 1), 'float32')}
        exe.run(main, feed=feed, fetch_list=[cost])
        n_before = len(exe._cache)
        fluid.memory_optimize(main)
        exe.run(main, feed=feed, fetch_list=[cost])
        assert len(exe._cache) == n_before + 1


def test_remat_matches_no_remat_numerics():
    """Remat changes memory, not math: losses must track exactly."""
    losses = {}
    for use_remat in (False, True):
        np.random.seed(0)
        with fresh_program() as (main, startup):
            cost = _mlp_program()
            if use_remat:
                fluid.memory_optimize(main)
            main.random_seed = 7
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            feed = {'x': np.random.RandomState(1).rand(4, 8).astype('float32'),
                    'y': np.random.RandomState(2).rand(4, 1).astype('float32')}
            out = [float(exe.run(main, feed=feed, fetch_list=[cost])[0])
                   for _ in range(3)]
            losses[use_remat] = out
    np.testing.assert_allclose(losses[False], losses[True], rtol=1e-6)


def test_inference_transpiler_bn_fold():
    """Conv+BN fold must preserve outputs numerically (fresh BN stats and
    trained-looking stats alike)."""
    from paddle_tpu.fluid.executor import global_scope
    with fresh_program() as (main, startup):
        img = fluid.layers.data(name='img', shape=[3, 8, 8], dtype='float32')
        conv = fluid.layers.conv2d(input=img, num_filters=4, filter_size=3,
                                   padding=1, act=None)
        bn = fluid.layers.batch_norm(input=conv, is_test=True)
        out = fluid.layers.relu(bn)
        infer_prog = main.clone(for_test=True)

        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        scope = global_scope()
        # make BN stats non-trivial so the fold actually has to work
        rng = np.random.RandomState(3)
        for name, arr in list(scope.vars.items()):
            if arr is None:
                continue
            a = np.asarray(arr)
            if 'mean' in name:
                scope.vars[name] = jax.numpy.asarray(
                    rng.normal(0.5, 0.2, a.shape).astype(a.dtype))
            elif 'variance' in name:
                scope.vars[name] = jax.numpy.asarray(
                    rng.uniform(0.5, 2.0, a.shape).astype(a.dtype))

        feed = {'img': rng.rand(2, 3, 8, 8).astype('float32')}
        ref = exe.run(infer_prog, feed=feed, fetch_list=[out])[0]

        t = fluid.InferenceTranspiler()
        t.transpile(infer_prog, fluid.CPUPlace())
        folded = exe.run(infer_prog, feed=feed, fetch_list=[out])[0]
    np.testing.assert_allclose(ref, folded, rtol=1e-4, atol=1e-5)
