"""Pallas kernel layer + int8 quant drills (docs/perf.md#kernel-layer).

Three contracts, each A/B'd against the code path it replaces:

* registry/knob — the PADDLE_TPU_KERNELS / configure() grammar, and the
  executor compile cache keying on kernels.signature() (a knob flip
  recompiles; flipping back serves the cached module again).
* kernel parity — paged decode-attention and the fused sparse
  optimizers under the pallas INTERPRETER (this suite runs on
  JAX_PLATFORMS=cpu, so the kernel bodies execute for real) against
  their XLA fallbacks, within each kernel's documented tolerance:
  paged_attention <= 1e-5 + 1e-5*|ref| (online softmax reassociates),
  sparse adagrad/adam <= 1e-6 absolute (same per-row expressions).
  Knob-off stays BIT-identical to the pre-kernel lowering (the fallback
  branch IS the original code).
* int8 quant — the quant IR pass (QDQ pipeline form + offline
  quantize_weights) within the documented round-trip bound
  (max|x[ch]|/254 per element), and the DeltaPublisher's int8 wire
  cutting push bytes to <= 0.55x fp32.

Marker: `kernels` (pytest -m kernels; routed through
tools/fault_drill.sh with the other drill families).
"""
import numpy as np
import pytest

import paddle_tpu.fluid as fluid
import paddle_tpu.fluid.layers as layers
from paddle_tpu.fluid import passes
from paddle_tpu.fluid.executor import global_scope
from paddle_tpu.fluid.passes import quant_pass
from paddle_tpu.ops import kernels

from util import fresh_program

pytestmark = pytest.mark.kernels

VOCAB, DIM = 16, 4


@pytest.fixture(autouse=True)
def _restore_knob():
    """Every test leaves the process-level knob exactly as it found it
    (enablement is global state; the suite must not leak it)."""
    prev = kernels._CONFIG
    try:
        yield
    finally:
        kernels.configure(prev)


# ---------------------------------------------------------------------------
# registry + knob grammar
# ---------------------------------------------------------------------------

def test_registry_catalog():
    names = kernels.available()
    for n in ('paged_attention', 'sparse_adagrad', 'sparse_adam'):
        assert n in names


def test_knob_grammar(monkeypatch):
    p = kernels._parse
    assert p(None) == frozenset()
    assert p('') == frozenset()
    assert p('0') == frozenset()
    assert p('off') == frozenset()
    assert p(False) == frozenset()
    everything = frozenset(kernels.available())
    assert p(True) == everything
    assert p('1') == everything
    assert p('all') == everything
    assert p('paged_attention') == frozenset(['paged_attention'])
    assert p('all,-sparse_adam') == everything - {'sparse_adam'}
    assert p(['sparse_adam', 'sparse_adagrad']) == frozenset(
        ['sparse_adam', 'sparse_adagrad'])
    # configure overrides the env while set; None hands back to the env
    monkeypatch.setenv(kernels.ENV_KERNELS, 'all')
    kernels.configure(False)
    assert not kernels.enabled('paged_attention')
    kernels.configure(None)
    assert kernels.enabled('paged_attention')
    # signature() is the enabled INTERSECTION of registered names (an
    # unknown name in the spec can never churn compile-cache keys)
    kernels.configure(['paged_attention', 'not_a_kernel'])
    assert kernels.signature() == ('paged_attention',)


# ---------------------------------------------------------------------------
# paged decode-attention parity (interpreter executes the kernel body)
# ---------------------------------------------------------------------------

def _paged_case(rng, C, beam, ps, npe, src_cap, D, masked_slot=None):
    """Random paged-encoder pool: each slot owns `npe` distinct pages,
    a per-slot length in [1, src_cap] sets the mask (0 rows for
    `masked_slot` — the fully-masked degenerate case)."""
    n_pages = C * npe + 2
    enc_pages = (rng.randn(n_pages, ps, D) * 0.5).astype(np.float32)
    mask_pages = np.zeros((n_pages, ps), np.float32)
    pt = rng.permutation(n_pages)[:C * npe].reshape(C, npe).astype(np.int32)
    for c in range(C):
        ln = 0 if masked_slot == c else int(rng.randint(1, src_cap + 1))
        for j in range(npe):
            for k in range(ps):
                if j * ps + k < ln:
                    mask_pages[pt[c, j], k] = 1.0
    q = (rng.randn(C * beam, D) * 0.7).astype(np.float32)
    return q, enc_pages, mask_pages, pt


@pytest.mark.parametrize('C,beam,ps,npe,src_cap,D', [
    (2, 3, 3, 2, 5, 16),
    (1, 1, 4, 3, 10, 8),
    (3, 2, 4, 2, 7, 8),
])
def test_paged_attention_parity(C, beam, ps, npe, src_cap, D):
    from paddle_tpu.ops.kernels import (paged_attention,
                                        paged_attention_reference)
    rng = np.random.RandomState(C * 100 + D)
    q, enc_pages, mask_pages, pt = _paged_case(rng, C, beam, ps, npe,
                                               src_cap, D)
    import jax.numpy as jnp
    args = (jnp.asarray(q), jnp.asarray(enc_pages),
            jnp.asarray(mask_pages), jnp.asarray(pt), src_cap)
    got = np.asarray(paged_attention(*args, interpret=True))
    ref = np.asarray(paged_attention_reference(*args))
    tol = 1e-5 + 1e-5 * np.abs(ref)            # the documented tolerance
    assert (np.abs(got - ref) <= tol).all(), \
        'max err %.3g' % np.abs(got - ref).max()


def test_paged_attention_fully_masked_slot():
    """A slot whose mask is all-zero degrades to the oracle's
    uniform-softmax over NEG_MASKED scores — same value, no NaN."""
    from paddle_tpu.ops.kernels import (paged_attention,
                                        paged_attention_reference)
    rng = np.random.RandomState(9)
    q, enc_pages, mask_pages, pt = _paged_case(rng, 2, 3, 3, 2, 5, 8,
                                               masked_slot=1)
    import jax.numpy as jnp
    args = (jnp.asarray(q), jnp.asarray(enc_pages),
            jnp.asarray(mask_pages), jnp.asarray(pt), 5)
    got = np.asarray(paged_attention(*args, interpret=True))
    ref = np.asarray(paged_attention_reference(*args))
    assert np.isfinite(got).all()
    assert (np.abs(got - ref) <= 1e-5 + 1e-5 * np.abs(ref)).all()


# ---------------------------------------------------------------------------
# fused sparse optimizers: parity vs the optim_ops fallback math
# ---------------------------------------------------------------------------

def _merged_case(rng, V=12, D=8):
    """A merged-row batch shaped like _merge_sparse output, including
    the write hazard the reversed grid exists for: a VALID uid-0 row at
    slot 1 while the invalid tail slots 3..5 are clamped to row 0."""
    import jax.numpy as jnp
    p = jnp.asarray((rng.randn(V, D) * 0.5).astype(np.float32))
    uids = jnp.asarray(np.array([3, 0, 7, 0, 0, 0], np.int32))
    valid = jnp.asarray(np.array([1, 1, 1, 0, 0, 0], np.int32))
    gm = (rng.randn(6, D) * 0.3).astype(np.float32)
    gm[3:] = 0.0                        # invalid merge slots carry zeros
    return p, uids, jnp.asarray(gm), valid


def test_fused_sparse_adagrad_parity():
    import jax.numpy as jnp
    from paddle_tpu.ops.kernels import fused_sparse_adagrad
    rng = np.random.RandomState(3)
    p, uids, gm, valid = _merged_case(rng)
    m = jnp.asarray(np.abs(rng.randn(*p.shape)).astype(np.float32))
    lr, eps = 0.1, 1e-6
    # the optim_ops._adagrad SelectedRows fallback, verbatim
    vm = valid.astype(jnp.float32)[:, None]
    m_rows = m[uids]
    m_new = m_rows + gm * gm
    p_delta = -lr * gm / (jnp.sqrt(m_new) + eps) * vm
    p_ref = p.at[uids].add(p_delta)
    m_ref = m.at[uids].add((m_new - m_rows) * vm)
    p_out, m_out = fused_sparse_adagrad(p, m, uids, gm, valid, lr, eps,
                                        interpret=True)
    assert np.abs(np.asarray(p_out) - np.asarray(p_ref)).max() <= 1e-6
    assert np.abs(np.asarray(m_out) - np.asarray(m_ref)).max() <= 1e-6


def test_fused_sparse_adam_parity():
    import jax.numpy as jnp
    from paddle_tpu.ops.kernels import fused_sparse_adam
    rng = np.random.RandomState(4)
    p, uids, gm, valid = _merged_case(rng)
    m1 = jnp.asarray((rng.randn(*p.shape) * 0.1).astype(np.float32))
    m2 = jnp.asarray(np.abs(rng.randn(*p.shape) * 0.1).astype(np.float32))
    b1, b2, eps = 0.9, 0.999, 1e-8
    lr = 0.01 * np.sqrt(1 - b2 ** 3) / (1 - b1 ** 3)  # bias-corrected
    vm = valid.astype(jnp.float32)[:, None]
    m1_rows, m2_rows = m1[uids], m2[uids]
    m1_new = b1 * m1_rows + (1 - b1) * gm
    m2_new = b2 * m2_rows + (1 - b2) * gm * gm
    p_delta = -lr * m1_new / (jnp.sqrt(m2_new) + eps) * vm
    p_ref = p.at[uids].add(p_delta)
    m1_ref = m1.at[uids].add((m1_new - m1_rows) * vm)
    m2_ref = m2.at[uids].add((m2_new - m2_rows) * vm)
    p_out, m1_out, m2_out = fused_sparse_adam(
        p, m1, m2, uids, gm, valid, lr, b1, b2, eps, interpret=True)
    for got, ref in ((p_out, p_ref), (m1_out, m1_ref), (m2_out, m2_ref)):
        assert np.abs(np.asarray(got) - np.asarray(ref)).max() <= 1e-6


def test_fused_sparse_all_invalid_is_bitwise_noop():
    """An all-padding merge (empty batch) must leave the tables
    BITWISE untouched — invalid slots write the row they read."""
    import jax.numpy as jnp
    from paddle_tpu.ops.kernels import fused_sparse_adagrad
    rng = np.random.RandomState(5)
    p = jnp.asarray((rng.randn(10, 6) * 0.5).astype(np.float32))
    m = jnp.asarray(np.abs(rng.randn(10, 6)).astype(np.float32))
    uids = jnp.zeros((4,), jnp.int32)
    valid = jnp.zeros((4,), jnp.int32)
    gm = jnp.zeros((4, 6), jnp.float32)
    p_out, m_out = fused_sparse_adagrad(p, m, uids, gm, valid, 0.1, 1e-6,
                                        interpret=True)
    assert np.array_equal(np.asarray(p_out), np.asarray(p))
    assert np.array_equal(np.asarray(m_out), np.asarray(m))


# ---------------------------------------------------------------------------
# program-level: knob-off bit-exactness, kernel-on parity, cache keying
# ---------------------------------------------------------------------------

def _sparse_model(opt_factory):
    """Tiny is_sparse embedding model; returns (exe, main, feed, loss)
    ready to run (startup already executed)."""
    ids = layers.data(name='ids', shape=[3, 1], dtype='int64')
    emb = layers.embedding(ids, size=[VOCAB, DIM], is_sparse=True,
                           param_attr=fluid.ParamAttr(name='emb_w'))
    pred = layers.fc(input=emb, size=1, num_flatten_dims=2,
                     bias_attr=False,
                     param_attr=fluid.ParamAttr(name='fc_w'))
    loss = layers.mean(layers.square(pred - 1.0))
    opt_factory().minimize(loss)
    return loss


def _run_sparse(opt_factory, steps=3, seed=0):
    """Train the tiny sparse model `steps` steps under the CURRENT knob
    state; returns (losses, final table, steady-state compile count —
    cache misses AFTER the first step, which must be 0)."""
    rng = np.random.RandomState(seed)
    feeds = [{'ids': rng.randint(0, VOCAB, size=(4, 3, 1)).astype('int64')}
             for _ in range(steps)]
    with fresh_program() as (main, startup):
        loss = _sparse_model(opt_factory)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        losses = [float(np.asarray(exe.run(main, feed=feeds[0],
                                           fetch_list=[loss])[0])
                        .reshape(-1)[0])]
        m1 = exe.cache_stats['misses']
        losses += [float(np.asarray(exe.run(main, feed=f,
                                            fetch_list=[loss])[0])
                         .reshape(-1)[0]) for f in feeds[1:]]
        steady = exe.cache_stats['misses'] - m1
        table = np.asarray(global_scope()._chain_get('emb_w'))
    return losses, table, steady


@pytest.mark.parametrize('opt,kname', [
    (lambda: fluid.optimizer.Adagrad(learning_rate=0.1), 'sparse_adagrad'),
    (lambda: fluid.optimizer.Adam(learning_rate=0.1), 'sparse_adam'),
])
def test_program_knob_off_bit_identical(opt, kname):
    """configure(False) and the default (env unset) lower the SAME
    modules: training is bit-for-bit identical — the fallback branch IS
    the pre-kernel code, and a disabled knob must leave no residue."""
    kernels.configure(None)
    l0, t0, _ = _run_sparse(opt)
    kernels.configure(False)
    l1, t1, _ = _run_sparse(opt)
    assert l0 == l1
    assert np.array_equal(t0, t1)


@pytest.mark.parametrize('opt,kname', [
    (lambda: fluid.optimizer.Adagrad(learning_rate=0.1), 'sparse_adagrad'),
    (lambda: fluid.optimizer.Adam(learning_rate=0.1), 'sparse_adam'),
])
def test_program_kernel_on_parity(opt, kname):
    """Kernel-enabled training (interpreted pallas on this CPU tier)
    matches knob-off within the documented 1e-6/step absolute tolerance,
    dispatches the kernel at trace time, and performs zero steady-state
    compiles after the first step's signature."""
    from paddle_tpu import obs
    kernels.configure(False)
    l_off, t_off, _ = _run_sparse(opt)
    kernels.configure(kname)
    before = float(obs.counter('kernels.%s.dispatch' % kname).value)
    l_on, t_on, steady = _run_sparse(opt)
    after = float(obs.counter('kernels.%s.dispatch' % kname).value)
    assert after > before, 'kernel never dispatched at trace time'
    assert steady == 0, 'steady-state recompile with kernel enabled'
    assert np.abs(t_on - t_off).max() <= 1e-5
    np.testing.assert_allclose(l_on, l_off, rtol=1e-5, atol=1e-6)


def test_signature_in_executor_cache_key():
    """Flipping the knob between runs of ONE executor recompiles (new
    cache entry) instead of serving the other variant's module; flipping
    back hits the original entry again."""
    rng = np.random.RandomState(1)
    feed = {'ids': rng.randint(0, VOCAB, size=(4, 3, 1)).astype('int64')}
    with fresh_program() as (main, startup):
        loss = _sparse_model(
            lambda: fluid.optimizer.Adagrad(learning_rate=0.1))
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        kernels.configure(False)
        exe.run(main, feed=feed, fetch_list=[loss])
        m0 = exe.cache_stats['misses']
        kernels.configure('sparse_adagrad')
        exe.run(main, feed=feed, fetch_list=[loss])
        assert exe.cache_stats['misses'] == m0 + 1   # knob flip recompiled
        kernels.configure(False)
        exe.run(main, feed=feed, fetch_list=[loss])
        assert exe.cache_stats['misses'] == m0 + 1   # flip back: cache hit


# ---------------------------------------------------------------------------
# quant IR pass: QDQ pipeline form + offline weight quantization
# ---------------------------------------------------------------------------

def _quant_model():
    ids = layers.data(name='ids', shape=[3, 1], dtype='int64')
    emb = layers.embedding(ids, size=[VOCAB, DIM], is_sparse=False,
                           param_attr=fluid.ParamAttr(name='emb_w'))
    out = layers.fc(input=emb, size=5, num_flatten_dims=2,
                    param_attr=fluid.ParamAttr(name='fc_w'))
    return out


def test_quant_pass_qdq_pipeline():
    """mark_quant + optimize(): every frozen f32 weight gets explicit
    QDQ ops (lookup_table rewrites to quant_lookup_table), outputs stay
    within the per-channel round-trip tolerance, and the PassReport
    carries the rewrite counts."""
    rng = np.random.RandomState(2)
    feed = {'ids': rng.randint(0, VOCAB, size=(4, 3, 1)).astype('int64')}
    with fresh_program() as (main, startup):
        out = _quant_model()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        base = np.asarray(exe.run(main, feed=feed, fetch_list=[out])[0])
        quant_pass.mark_quant(main)
        opt, report = passes.optimize(main, fetches=[out.name])
        st = report.passes['quant']
        assert st['ops_rewritten'] == 2          # lookup_table + mul
        assert st['qdq_inserted'] == 3           # 2x quantize + 1 dequant
        types = [op.type for op in opt.global_block().ops]
        assert 'quant_lookup_table' in types
        assert 'quantize' in types and 'dequantize' in types
        assert not quant_pass.is_quant(opt)      # flag became IR property
        assert getattr(opt, '_quant_ir', False)
        got = np.asarray(exe.run(opt, feed=feed, fetch_list=[out.name])[0])
    rel = np.abs(got - base).max() / max(np.abs(base).max(), 1e-9)
    assert rel < 0.05, 'quantized output drifted %.4f relative' % rel


def test_quant_pass_runs_inside_executor():
    """The executor's own optimize() call applies the rewrite: running a
    mark_quant'd program directly produces quantized (close, not
    bitwise) results with no manual pass invocation."""
    rng = np.random.RandomState(6)
    feed = {'ids': rng.randint(0, VOCAB, size=(4, 3, 1)).astype('int64')}
    with fresh_program() as (main, startup):
        out = _quant_model()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        base = np.asarray(exe.run(main, feed=feed, fetch_list=[out])[0])
        quant_pass.mark_quant(main)
        got = np.asarray(exe.run(main, feed=feed, fetch_list=[out])[0])
    assert not np.array_equal(got, base)         # the rewrite really ran
    rel = np.abs(got - base).max() / max(np.abs(base).max(), 1e-9)
    assert rel < 0.05


def test_quantize_weights_offline():
    """The deployment form: int8+scale persistables installed, consumers
    repointed, the fp32 weight DROPPED from the block (so
    save_inference_model ships no fp32 bytes), outputs within tolerance
    and the embedding rows within the documented per-element bound."""
    rng = np.random.RandomState(7)
    feed = {'ids': rng.randint(0, VOCAB, size=(4, 3, 1)).astype('int64')}
    with fresh_program() as (main, startup):
        out = _quant_model()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        base = np.asarray(exe.run(main, feed=feed, fetch_list=[out])[0])
        w_emb = np.asarray(global_scope()._chain_get('emb_w'))
        infer = main.clone(for_test=True)
        n = quant_pass.quantize_weights(infer, global_scope())
        assert n == 2
        blk = infer.global_block()
        assert 'emb_w' not in blk.vars           # fp32 table dropped
        assert blk.vars['emb_w@quant.int8'].persistable
        assert blk.vars['emb_w@quant.int8'].dtype == 'int8'
        persist = [v.name for v in infer.list_vars() if v.persistable]
        assert 'emb_w' not in persist            # artifact ships int8 only
        got = np.asarray(exe.run(infer, feed=feed, fetch_list=[out.name])[0])
        # round-trip bound on the rows themselves: half a step per
        # element, per row (axis-0 per-channel scales)
        q = np.asarray(global_scope()._chain_get('emb_w@quant.int8'))
        s = np.asarray(global_scope()._chain_get('emb_w@quant.scale'))
        deq = q.astype(np.float32) * s
        bound = np.abs(w_emb).max(axis=1, keepdims=True) / 254.0
        assert (np.abs(deq - w_emb) <= bound + 1e-7).all()
    rel = np.abs(got - base).max() / max(np.abs(base).max(), 1e-9)
    assert rel < 0.05


# ---------------------------------------------------------------------------
# int8 delta-push wire
# ---------------------------------------------------------------------------

def test_quant_rows_codec_bound():
    from paddle_tpu.embedding import quant_rows as qr
    rng = np.random.RandomState(8)
    vals = (rng.randn(32, DIM) * np.logspace(-3, 2, 32)[:, None]) \
        .astype(np.float32)
    q, scale = qr.quantize_rows(vals)
    assert q.dtype == np.int8 and scale.shape == (32, 1)
    back = qr.dequantize_rows(q, scale)
    bound = np.abs(vals).max(axis=1, keepdims=True) / 254.0
    assert (np.abs(back - vals) <= bound + 1e-9).all()
    assert qr.row_bytes(q, scale) == 32 * DIM + 32 * qr.ROW_SCALE_BYTES


def test_publisher_int8_push_bytes():
    """Same touched rows, fp32 vs int8 wire: value bytes <= 0.55x, the
    plain-sink replica holds round-trip-bounded values, and a
    codec-aware sink receives the (rows, q, scale) form untouched."""
    from paddle_tpu.streaming import DeltaPublisher
    rng = np.random.RandomState(11)
    table = (rng.randn(64, 32) * 0.5).astype(np.float32)
    rows = np.arange(0, 48, 2)

    class Plain(object):
        def __init__(self):
            self.got = {}

        def push_rows(self, deltas):
            for name, (ids, vals) in deltas.items():
                self.got[name] = (np.asarray(ids), np.asarray(vals))

    class Codec(Plain):
        def push_quantized_rows(self, deltas):
            for name, (ids, q, scale) in deltas.items():
                self.got[name] = (np.asarray(ids), np.asarray(q),
                                  np.asarray(scale))

    def push(sink, quant):
        pub = DeltaPublisher(sink, quant=quant)
        pub.collect({'emb_w': rows})
        pub.publish(lambda name: table)
        return pub

    p_fp = push(Plain(), None)
    plain = Plain()
    p_q = push(plain, 'int8')
    assert p_q.last_push_bytes <= 0.55 * p_fp.last_push_bytes
    assert p_fp.last_push_bytes == rows.size * table.shape[1] * 4
    # plain sink got fp32 values carrying exactly the quantized wire's
    # rounding: within half a step of the live rows
    ids, vals = plain.got['emb_w']
    bound = np.abs(table[ids]).max(axis=1, keepdims=True) / 254.0
    assert (np.abs(vals - table[ids]) <= bound + 1e-7).all()
    # codec-aware sink receives the int8 form itself
    codec = Codec()
    push(codec, 'int8')
    cids, q, scale = codec.got['emb_w']
    assert q.dtype == np.int8 and scale.dtype == np.float32
    assert np.array_equal(np.sort(cids), np.sort(rows))
    assert p_q.stats()['quant'] == 'int8'


# ---------------------------------------------------------------------------
# observability: dispatch events render the obs_report section
# ---------------------------------------------------------------------------

def test_dispatch_events_and_report_section(tmp_path):
    from paddle_tpu import obs
    from paddle_tpu.obs import report as obs_report
    obs.enable(str(tmp_path / 'obs'))
    try:
        kernels.note_dispatch('paged_attention', True)
        kernels.note_dispatch('paged_attention', True)
        kernels.note_dispatch('sparse_adam', False)
        events, errors = obs_report.load_events(obs.run_log_path())
        assert errors == []
        text = obs_report.summarize(events)
        assert '-- kernels --' in text
        assert 'trace-time dispatches: 2 kernel, 1 fallback' in text
        assert 'paged_attention: 2 kernel trace(s)' in text
    finally:
        obs._reset()
