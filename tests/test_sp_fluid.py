"""Fluid-level sequence parallelism: SequenceParallelTranspiler routes
every fused_attention in the program through parallel.ring_attention over
an sp mesh axis — same losses and updates as single-device execution."""
import numpy as np
import pytest

import paddle_tpu.fluid as fluid

from util import fresh_program


def _train_transformer(sp, steps=2, pp=False, amp=False, seed=21):
    from paddle_tpu.models import transformer as T
    rng = np.random.RandomState(seed)
    vocab, seq, batch = 32, 16, 4
    feed_ids = {n: rng.randint(1, vocab, size=(batch, seq)).astype('int64')
                for n in ('src_word', 'trg_word', 'lbl_word')}
    with fresh_program() as (main, startup):
        avg_cost, _, feeds = T.transformer(
            vocab, vocab, seq, n_layer=2, d_model=16, n_head=2, d_inner=32,
            dropout_rate=0.0, pp_decoder=pp)
        fluid.optimizer.SGD(learning_rate=0.01).minimize(avg_cost)
        if pp:
            fluid.PipelineTranspiler(n_micro=2).transpile(main)
        if sp:
            fluid.SequenceParallelTranspiler(sp=sp).transpile(main)
        if amp:
            fluid.amp.decorate_program(main)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        return [float(exe.run(main, feed=feed_ids,
                              fetch_list=[avg_cost])[0])
                for _ in range(steps)]


def test_sp_transformer_matches_single_device():
    seq = _train_transformer(sp=0)
    par = _train_transformer(sp=8)
    assert seq[0] != seq[1]           # the step updated the parameters
    np.testing.assert_allclose(par, seq, rtol=2e-4)


def test_sp_transpiler_validation():
    with fresh_program() as (main, startup):
        x = fluid.layers.data(name='x', shape=[4], dtype='float32')
        fluid.layers.fc(input=x, size=4)
        with pytest.raises(ValueError, match='fused_attention'):
            fluid.SequenceParallelTranspiler(sp=4).transpile(main)
    with pytest.raises(ValueError, match='sp must be'):
        fluid.SequenceParallelTranspiler(sp=1)


def test_sp_rejects_indivisible_seq():
    from paddle_tpu.models import transformer as T
    rng = np.random.RandomState(3)
    vocab, seq, batch = 32, 12, 2   # 12 % 8 != 0
    feed_ids = {n: rng.randint(1, vocab, size=(batch, seq)).astype('int64')
                for n in ('src_word', 'trg_word', 'lbl_word')}
    with fresh_program() as (main, startup):
        avg_cost, _, feeds = T.transformer(
            vocab, vocab, seq, n_layer=1, d_model=16, n_head=2, d_inner=32,
            dropout_rate=0.0)
        fluid.SequenceParallelTranspiler(sp=8).transpile(main)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        with pytest.raises(ValueError, match='must divide the seq'):
            exe.run(main, feed=feed_ids, fetch_list=[avg_cost])


def _train_pp_sp(pp, sp, dp=1, order='pp_first', seed=61, steps=2,
                 strategy='ring'):
    """Transformer with a pipelined decoder over a pp x sp (x dp) mesh."""
    from paddle_tpu.models import transformer as T
    rng = np.random.RandomState(seed)
    vocab, seq, batch = 32, 16, 4
    feed_ids = {n: rng.randint(1, vocab, size=(batch, seq)).astype('int64')
                for n in ('src_word', 'trg_word', 'lbl_word')}
    with fresh_program() as (main, startup):
        avg_cost, _, feeds = T.transformer(
            vocab, vocab, seq, n_layer=2, d_model=16, n_head=2, d_inner=32,
            dropout_rate=0.0, pp_decoder=pp)
        fluid.optimizer.SGD(learning_rate=0.01).minimize(avg_cost)
        steps_t = []
        if pp:
            steps_t.append(lambda: fluid.PipelineTranspiler(
                n_micro=2).transpile(main))
        if sp:
            steps_t.append(lambda: fluid.SequenceParallelTranspiler(
                sp=sp, strategy=strategy).transpile(main))
        if order != 'pp_first':
            steps_t.reverse()
        for t in steps_t:
            t()
        if dp > 1:
            fluid.DistributeTranspiler().transpile(trainer_id=0,
                                                   trainers=dp)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        return [float(exe.run(main, feed=feed_ids,
                              fetch_list=[avg_cost])[0])
                for _ in range(steps)]


def test_pp_sp_composition_matches_single_device():
    """pp x sp: the pipeline shard_map is manual over pp AND sp; stage
    bodies run sequence-local with the ring riding per shard. Both
    transpile orders == sequential."""
    base = _train_pp_sp(pp=False, sp=0)
    assert base[0] != base[1]
    np.testing.assert_allclose(_train_pp_sp(pp=True, sp=2), base,
                               rtol=2e-4)
    np.testing.assert_allclose(
        _train_pp_sp(pp=True, sp=2, order='sp_first'), base, rtol=2e-4)


def test_three_way_dp_pp_sp_composition():
    """dp=2 x pp=2 x sp=2 on the 8-device mesh == single-device."""
    base = _train_pp_sp(pp=False, sp=0, seed=62)
    got = _train_pp_sp(pp=True, sp=2, dp=2, seed=62)
    np.testing.assert_allclose(got, base, rtol=2e-4)


def test_pp_sp_ulysses_strategy():
    """The ulysses all-to-all per-shard body also runs inside the
    pipeline's manual shard_map (n_head=2 == sp)."""
    base = _train_pp_sp(pp=False, sp=0, seed=63)
    got = _train_pp_sp(pp=True, sp=2, seed=63, strategy='ulysses')
    np.testing.assert_allclose(got, base, rtol=2e-4)


def test_sp_dp_composition_matches_single_device():
    """dp x sp: each dp replica rings over its own batch slice — same
    numbers as single-device."""
    from paddle_tpu.models import transformer as T
    rng = np.random.RandomState(31)
    vocab, seq, batch = 32, 8, 4
    feed_ids = {n: rng.randint(1, vocab, size=(batch, seq)).astype('int64')
                for n in ('src_word', 'trg_word', 'lbl_word')}

    def run(dist):
        with fresh_program() as (main, startup):
            avg_cost, _, feeds = T.transformer(
                vocab, vocab, seq, n_layer=1, d_model=16, n_head=2,
                d_inner=32, dropout_rate=0.0)
            fluid.optimizer.SGD(learning_rate=0.01).minimize(avg_cost)
            if dist == 'dp_first':
                fluid.DistributeTranspiler().transpile(trainer_id=0,
                                                       trainers=2)
                fluid.SequenceParallelTranspiler(sp=4).transpile(main)
            elif dist == 'sp_first':   # reverse order must ALSO keep sp
                fluid.SequenceParallelTranspiler(sp=4).transpile(main)
                fluid.DistributeTranspiler().transpile(trainer_id=0,
                                                       trainers=2)
                assert main._dist_config.get('sp_size') == 4
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            return [float(exe.run(main, feed=feed_ids,
                                  fetch_list=[avg_cost])[0])
                    for _ in range(2)]

    seq_l = run(None)
    np.testing.assert_allclose(run('dp_first'), seq_l, rtol=2e-4)
    np.testing.assert_allclose(run('sp_first'), seq_l, rtol=2e-4)


def test_sp_ulysses_strategy_matches_single_device():
    from paddle_tpu.models import transformer as T
    rng = np.random.RandomState(41)
    vocab, seq, batch = 32, 16, 2
    feed_ids = {n: rng.randint(1, vocab, size=(batch, seq)).astype('int64')
                for n in ('src_word', 'trg_word', 'lbl_word')}

    def run(strategy):
        with fresh_program() as (main, startup):
            # n_head=2 == sp so ulysses' head-divisibility holds
            avg_cost, _, feeds = T.transformer(
                vocab, vocab, seq, n_layer=1, d_model=16, n_head=2,
                d_inner=32, dropout_rate=0.0)
            fluid.optimizer.SGD(learning_rate=0.01).minimize(avg_cost)
            if strategy:
                fluid.SequenceParallelTranspiler(
                    sp=2, strategy=strategy).transpile(main)
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            return [float(exe.run(main, feed=feed_ids,
                                  fetch_list=[avg_cost])[0])
                    for _ in range(2)]

    base = run(None)
    np.testing.assert_allclose(run('ulysses'), base, rtol=2e-4)
    np.testing.assert_allclose(run('ring'), base, rtol=2e-4)
    with pytest.raises(ValueError, match='ring.*ulysses|ulysses.*ring'):
        fluid.SequenceParallelTranspiler(sp=2, strategy='nope')


def test_sp_and_pp_compose_with_amp():
    """bf16 AMP through both new Program-level surfaces: the pipeline
    carry and the ring merge keep consistent dtypes."""
    base = _train_transformer(sp=0, amp=True, seed=51)
    for kw in (dict(sp=0, pp=True), dict(sp=4)):
        got = _train_transformer(amp=True, seed=51, **kw)
        assert all(np.isfinite(got)), (kw, got)
        # 5e-2 is a bf16 bound, not sloppiness: bf16 has an 8-bit mantissa
        # (relative rounding 2^-9 ~ 2e-3 PER op), and the pipeline/ring
        # regroupings reorder reductions, so two training steps compound
        # percent-level drift. The fp32 versions of these same stacks are
        # held to 2e-4 above; the bf16 run only asserts the trajectories
        # agree to bf16 precision.
        np.testing.assert_allclose(got, base, rtol=5e-2,
                                   err_msg='amp %r' % kw)


def test_three_way_dp_tp_sp_composition():
    """dp=2 x tp=2 x sp=2 on the 8-device mesh — all three Program-level
    transpilers stack; losses == single-device."""
    from paddle_tpu.models import transformer as T
    rng = np.random.RandomState(81)
    vocab, seq, batch = 32, 8, 4
    feed_ids = {n: rng.randint(1, vocab, size=(batch, seq)).astype('int64')
                for n in ('src_word', 'trg_word', 'lbl_word')}

    def run(three_way):
        with fresh_program() as (main, startup):
            avg_cost, _, feeds = T.transformer(
                vocab, vocab, seq, n_layer=1, d_model=16, n_head=2,
                d_inner=32, dropout_rate=0.0)
            fluid.optimizer.Adam(learning_rate=1e-3).minimize(avg_cost)
            if three_way:
                fluid.DistributeTranspiler().transpile(trainer_id=0,
                                                       trainers=2)
                fluid.TensorParallelTranspiler(tp=2).transpile(main)
                fluid.SequenceParallelTranspiler(sp=2).transpile(main)
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            losses = [float(exe.run(main, feed=feed_ids,
                                    fetch_list=[avg_cost])[0])
                      for _ in range(2)]
            if three_way:
                assert set(main._dist_mesh.shape) == {'dp', 'tp', 'sp'}
            return losses

    base = run(False)
    got = run(True)
    assert base[0] != base[1]   # the step actually updated parameters
    np.testing.assert_allclose(got, base, rtol=2e-4)


def test_pp_sp_rejects_sequence_mixing_stage_op():
    """A stage-body op that reduces over the sequence dim must be rejected
    loudly under pp x sp: the stage runs sequence-local inside the manual
    shard_map and only flash_attention knows how to cross shards (round-4
    advisor finding on parallel/pipeline.py)."""
    from paddle_tpu.fluid import layers

    def build(order):
        with fresh_program() as (main, startup):
            x = layers.data(name='x', shape=[8, 16], dtype='float32')
            h = x
            for k in range(2):
                with fluid.device_guard('pipe:%d' % k):
                    h = layers.fc(input=h, size=16, num_flatten_dims=2,
                                  bias_attr=False)
                    # reduce over the sequence dim inside the stage: the
                    # canonical sequence-MIXING op the validator must catch
                    pooled = layers.reduce_mean(h, dim=1, keep_dim=True)
                    h = layers.elementwise_add(h, pooled)
            # an attention op so the sp transpiler accepts the program
            q = layers.reshape(h, shape=[0, 0, 2, 8])
            q = layers.transpose(q, perm=[0, 2, 1, 3])
            ctx = layers.fused_attention(q, q, q)
            loss = layers.mean(ctx)
            transpilers = [
                lambda: fluid.PipelineTranspiler(n_micro=2).transpile(main),
                lambda: fluid.SequenceParallelTranspiler(
                    sp=2).transpile(main),
            ]
            if order == 'sp_first':
                transpilers.reverse()
            for t in transpilers:
                t()

    for order in ('pp_first', 'sp_first'):
        with pytest.raises(ValueError, match='not known to be '
                           'sequence-local'):
            build(order)


def test_pp_sp_rejects_activation_activation_matmul():
    """A hand-written q@k^T (matmul of two activations) inside a pipeline
    stage mixes sequence positions across sp shards — rejected."""
    from paddle_tpu.fluid import layers

    with fresh_program() as (main, startup):
        x = layers.data(name='x', shape=[8, 16], dtype='float32')
        h = x
        for k in range(2):
            with fluid.device_guard('pipe:%d' % k):
                h = layers.fc(input=h, size=16, num_flatten_dims=2,
                              bias_attr=False)
                scores = layers.matmul(h, h, transpose_y=True)
                h = layers.matmul(scores, h)
        q = layers.reshape(h, shape=[0, 0, 2, 8])
        q = layers.transpose(q, perm=[0, 2, 1, 3])
        ctx = layers.fused_attention(q, q, q)
        loss = layers.mean(ctx)
        fluid.PipelineTranspiler(n_micro=2).transpile(main)
        with pytest.raises(ValueError, match='contracts two activations'):
            fluid.SequenceParallelTranspiler(sp=2).transpile(main)
