"""A/B equivalence of the capacity-form LoD beam step against a numpy
transcription of the reference algorithm (operators/beam_search_op.cc:
NextItemSet / SelectTopBeamSizeItems / ToMap / PruneEndBeams), plus the
decode backtrace (beam_search_decode_op.h:Backtrace). This turns the
"dense redesign is equivalent" claim into a tested statement (VERDICT r4
item 3)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.fluid.lowering import SeqValue, ArrayValue
from paddle_tpu.fluid.ops_impl import lod_beam


# ---------------------------------------------------------------------------
# numpy transcription of beam_search_op.cc
# ---------------------------------------------------------------------------

def np_beam_search(pre_ids, pre_scores, ids, scores, src_rows, beam_size,
                   end_id):
    """pre_ids/pre_scores: flat [n_rows]; ids/scores: [n_rows, K];
    src_rows: rows per source (the abs lod[0] diffs). Returns
    (out_ids, out_scores, l0, l1_per_parent, parent_of_out_row)."""
    n_src = len(src_rows)
    offsets = np.concatenate([[0], np.cumsum(src_rows)])
    selected_per_source = []
    for s in range(n_src):
        items = []   # (offset, id, score)
        for offset in range(offsets[s], offsets[s + 1]):
            if pre_ids[offset] == end_id:
                items.append((offset, end_id, pre_scores[offset]))
            else:
                for d in range(ids.shape[1]):
                    items.append((offset, ids[offset, d], scores[offset, d]))
        # top beam_size by score (stable on encounter order for ties)
        items = sorted(items, key=lambda it: -it[2])[:beam_size]
        selected_per_source.append(items)
    # ToMap: group by parent offset
    total_rows = offsets[-1]
    by_offset = [[] for _ in range(total_rows)]
    for s in range(n_src):
        for it in selected_per_source[s]:
            by_offset[it[0]].append(it)
    # PruneEndBeams
    for s in range(n_src):
        finish = True
        for offset in range(offsets[s], offsets[s + 1]):
            for it in by_offset[offset]:
                if it[1] != end_id or pre_ids[offset] != end_id:
                    finish = False
                    break
            if not finish:
                break
        if finish:
            for offset in range(offsets[s], offsets[s + 1]):
                by_offset[offset] = []
    out_ids, out_scores, l1, parents = [], [], [], []
    for offset in range(total_rows):
        l1.append(len(by_offset[offset]))
        for it in by_offset[offset]:
            out_ids.append(it[1])
            out_scores.append(it[2])
            parents.append(offset)
    l0 = list(src_rows)
    return (np.array(out_ids), np.array(out_scores), np.array(l0),
            np.array(l1), np.array(parents))


def _to_capacity(flat, src_rows, B, K, width=None):
    """Flat per-row values -> capacity blocks [B*K, ...]."""
    out = np.zeros((B * K,) + np.shape(flat)[1:], np.asarray(flat).dtype)
    off = 0
    for s, n in enumerate(src_rows):
        out[s * K:s * K + n] = flat[off:off + n]
        off += n
    return out


def _from_capacity(sv, B, K):
    """Capacity SeqValue -> (flat rows, src_rows, l1_flat, parents)."""
    data = np.asarray(sv.data).reshape(B * K, -1)[:, 0]
    l1 = np.asarray(sv.lengths).reshape(B, K)
    rows = []
    l1_flat = []
    for s in range(B):
        n = int(l1[s].sum())
        rows.extend(data[s * K:s * K + n])
        # per-parent lengths for the LIVE parents only (reference lod[1]
        # has one entry per parent group = l0[s] of this tensor)
    return np.array(rows), l1


def _beam_inputs(seed, B=2, K=3, topk=3, end_frac=0.3):
    rng = np.random.RandomState(seed)
    src_rows = rng.randint(1, K + 1, size=B)
    n = int(src_rows.sum())
    pre_ids = np.where(rng.rand(n) < end_frac, 10,
                       rng.randint(0, 9, size=n)).astype(np.int64)
    pre_scores = rng.randn(n).astype(np.float32)
    ids = rng.randint(0, 30, size=(n, topk)).astype(np.int64)
    scores = rng.randn(n, topk).astype(np.float32)
    return src_rows, pre_ids, pre_scores, ids, scores


@pytest.mark.parametrize('seed,B,K,topk,end_frac', [
    # default shape across 8 seeds
    *[(s, 2, 3, 3, 0.3) for s in range(8)],
    (11, 3, 2, 4, 0.0),   # never-ending: pure top-k selection
    (12, 1, 4, 2, 0.5),   # single source, heavy ending
    (13, 4, 3, 3, 0.9),   # nearly all ended: PruneEndBeams fires
    (14, 2, 5, 5, 0.3),
])
def test_beam_step_matches_reference_algorithm(seed, B, K, topk, end_frac):
    """One A/B harness across seeds, beam widths, source counts, topk
    sizes and end-token densities (exercises the ended-row candidate and
    PruneEndBeams branches)."""
    end_id = 10
    src_rows, pre_ids, pre_scores, ids, scores = _beam_inputs(
        seed, B, K, topk, end_frac)
    want_ids, want_sc, want_l0, want_l1, want_par = np_beam_search(
        pre_ids, pre_scores, ids, scores, src_rows, K, end_id)

    # capacity form: per-source blocks of K rows, live rows in front; the
    # input's l1 says "children per parent of the PREVIOUS step" — for the
    # step test only row liveness matters, so mark each live row as one
    # 1-child group
    live_l1 = np.zeros(B * K, np.int32)
    for s, n in enumerate(src_rows):
        live_l1[s * K:s * K + n] = 1
    mk = lambda flat, dt: SeqValue(
        jnp.asarray(_to_capacity(flat.reshape(-1, 1), src_rows, B, K), dt),
        jnp.asarray(live_l1), (jnp.asarray(src_rows, jnp.int32),))
    sv_ids, sv_scores, parents = lod_beam.beam_search_step(
        mk(pre_ids, jnp.int64), mk(pre_scores, jnp.float32),
        jnp.asarray(_to_capacity(ids, src_rows, B, K)),
        jnp.asarray(_to_capacity(scores, src_rows, B, K)), K, end_id)

    got_rows, got_l1 = _from_capacity(sv_ids, B, K)
    got_sc_rows, _ = _from_capacity(sv_scores, B, K)
    # flat l1 comparison: capacity slots for live parents
    flat_l1 = []
    l1cap = np.asarray(sv_ids.lengths).reshape(B, K)
    for s, n in enumerate(src_rows):
        flat_l1.extend(l1cap[s, :n])
    np.testing.assert_array_equal(flat_l1, want_l1)
    np.testing.assert_array_equal(np.asarray(sv_ids.outer_lengths[0]),
                                  want_l0)
    # rows grouped by parent: compare per-parent SETS (the reference's
    # nth_element leaves within-parent order unspecified)
    def group(rows, scores_r, l1):
        out, off = [], 0
        for n in l1:
            out.append(sorted(zip(rows[off:off + n],
                                  np.round(scores_r[off:off + n], 5))))
            off += n
        return out
    assert group(got_rows, got_sc_rows, want_l1) == \
        group(want_ids, want_sc, want_l1)


def np_backtrace(step_ids, step_scores, step_l0s, step_l1s, end_id):
    """Reference Backtrace over flat per-step LoD tensors.
    step_ids[t]: flat rows; step_l0s[t]: rows-per-source of the PARENT
    grouping (lod[0] diffs in level-1 units); step_l1s[t]: children per
    parent (lod[1] diffs). Returns per-source list of hypotheses (token
    lists, forward order) + scores."""
    T = len(step_ids)
    n_src = len(step_l0s[0])
    sentences = [[] for _ in range(n_src)]
    prefix_idx = [[] for _ in range(n_src)]
    hyp_tokens = [[] for _ in range(n_src)]
    hyp_scores = [[] for _ in range(n_src)]
    for t in range(T - 1, -1, -1):
        l0, l1 = step_l0s[t], step_l1s[t]
        # abs offsets
        p_off = np.concatenate([[0], np.cumsum(l0)])     # source->parents
        c_off = np.concatenate([[0], np.cumsum(l1)])     # parent->children
        for s in range(n_src):
            if not prefix_idx[s]:
                # seed at this source's last nonempty step
                if c_off[p_off[s + 1]] - c_off[p_off[s]] == 0:
                    continue
                for p in range(p_off[s], p_off[s + 1]):
                    for c in range(c_off[p], c_off[p + 1]):
                        prefix_idx[s].append(p)
                        hyp_tokens[s].append([step_ids[t][c]])
                        hyp_scores[s].append([step_scores[t][c]])
            else:
                for h in range(len(prefix_idx[s])):
                    c = prefix_idx[s][h]
                    tok = step_ids[t][c]
                    sc = step_scores[t][c]
                    if tok != end_id or not hyp_tokens[s][h]:
                        hyp_tokens[s][h].append(tok)
                        hyp_scores[s][h].append(sc)
                    # parent for the next (earlier) step
                    parent = int(np.searchsorted(c_off, c, side='right')) - 1
                    prefix_idx[s][h] = parent
    # reverse to forward order (ConvertSentenceVector reverse=true)
    return ([[list(reversed(tk)) for tk in hyp_tokens[s]]
             for s in range(n_src)],
            [[list(reversed(sc)) for sc in hyp_scores[s]]
             for s in range(n_src)])


def test_backtrace_matches_reference_algorithm():
    """Two sources, three steps, uneven beams, one source ends early.

    INTENTIONAL LoD deviation from the reference (documented here, next
    to the A/B comparison, and in docs/robustness.md): the reference's
    Backtrace initializes SentenceVector(beam_size_), so a source pruned
    below beam_size still contributes beam_size lod[0] entries, the
    missing ones as zero-length sentences — and its
    ConvertSentenceVectorToLodTensor then reads scores.front() of those
    EMPTY sentences under sort_by_score=true, which is undefined
    behavior. beam_search_decode_arrays instead emits exactly n_hyp live
    hypotheses per source (lod[0][s] = hypotheses actually alive at the
    seed step); the np_backtrace oracle below builds the same live-only
    structure, so the A/B holds on the well-defined subset."""
    B, K, end_id = 2, 2, 10
    # step 0 (init): 1 parent, 1 child per source; tokens = start id 1
    # step 1: parents = step-0 children (1/source); children: 2 for s0,
    #         2 for s1
    # step 2: s0 children [10 (end), 7]; s1 pruned (no children)
    def cap(data, l1, l0, dt):
        sv_data = np.zeros((B * K, 1), dt)
        sv_l1 = np.zeros(B * K, np.int32)
        off = 0
        for s in range(B):
            n = sum(l1[s])
            sv_data[s * K:s * K + n, 0] = data[off:off + n]
            sv_l1[s * K:s * K + len(l1[s])] = l1[s]
            off += n
        return (jnp.asarray(sv_data), jnp.asarray(sv_l1),
                jnp.asarray(l0, jnp.int32))

    steps = [
        # (flat ids, flat scores, l1 per source (per parent), l0)
        ([1, 1], [0.0, 0.0], [[1], [1]], [1, 1]),
        ([4, 5, 6, 10], [0.1, 0.2, 0.3, 0.4], [[2], [2]], [1, 1]),
        # s1 finished+pruned: its 2 parents have 0 children each
        ([10, 7], [0.5, 0.6], [[1, 1], [0, 0]], [2, 2]),
    ]
    T_cap = 4
    bufs_i, bufs_s, bufs_l1, bufs_l0 = [], [], [], []
    for ids_f, sc_f, l1, l0 in steps:
        di, dl1, dl0 = cap(np.array(ids_f), l1, l0, np.int64)
        ds, _, _ = cap(np.array(sc_f), l1, l0, np.float32)
        bufs_i.append(di)
        bufs_s.append(ds)
        bufs_l1.append(dl1)
        bufs_l0.append(dl0)
    pad = lambda bs: jnp.stack(bs + [jnp.zeros_like(bs[0])] *
                               (T_cap - len(bs)))
    ids_arr = ArrayValue((pad(bufs_i), pad(bufs_l1), pad(bufs_l0)),
                         jnp.asarray(len(steps), jnp.int32), 1)
    sc_arr = ArrayValue((pad(bufs_s), pad(bufs_l1), pad(bufs_l0)),
                        jnp.asarray(len(steps), jnp.int32), 1)
    sent_ids, sent_scores = lod_beam.beam_search_decode_arrays(
        ids_arr, sc_arr, K, end_id)

    want_toks, want_scs = np_backtrace(
        [np.array(s[0]) for s in steps], [np.array(s[1]) for s in steps],
        [np.array(s[3]) for s in steps],
        [np.concatenate([np.asarray(s[2][0], int),
                         np.asarray(s[2][1], int)]) for s in steps],
        end_id)

    n_hyp = np.asarray(sent_ids.outer_lengths[0])
    toks = np.asarray(sent_ids.data)
    scs = np.asarray(sent_scores.data)
    lens = np.asarray(sent_ids.lengths).reshape(B, K)
    got, got_sc = [], []
    for s in range(B):
        hyps, hsc = [], []
        for h in range(n_hyp[s]):
            L = lens[s, h]
            hyps.append(list(toks[s * K + h, :L]))
            hsc.append([round(float(v), 5) for v in scs[s * K + h, :L]])
        got.append(hyps)
        got_sc.append(hsc)
    # reference sort_by_score: hypotheses per source by accumulated
    # (last-token) score descending; scores rows permute WITH their ids
    want = [sorted(zip(ws, cs), key=lambda p: -p[1][-1])
            for ws, cs in zip(want_toks, want_scs)]
    assert got == [[list(w) for w, _ in ws] for ws in want]
    assert got_sc == [[[round(float(v), 5) for v in c] for _, c in ws]
                      for ws in want]
