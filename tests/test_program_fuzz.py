"""Differential fuzz: random small op graphs must survive Program
serialize → deserialize → re-execution bit-identically (the desc
round-trip the reference guarantees through protobuf; here _to_dict/
_from_dict, framework.py)."""
import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers

from util import fresh_program

# (name, arity, builder) — shape-preserving ops over [B, 8]
_UNARY = [
    lambda v: layers.relu(v),
    lambda v: layers.sigmoid(v),
    lambda v: layers.tanh(v),
    lambda v: layers.scale(v, scale=1.5, bias=0.25),
    lambda v: layers.softmax(v),
    lambda v: layers.abs(v),
    lambda v: layers.elu(v),
    lambda v: layers.l2_normalize(v, axis=-1),
]
_BINARY = [
    lambda a, b: layers.elementwise_add(a, b),
    lambda a, b: layers.elementwise_mul(a, b),
    lambda a, b: layers.elementwise_max(a, b),
    lambda a, b: layers.elementwise_sub(a, b),
]


def _random_graph(rng, x, depth=6):
    vals = [x]
    for _ in range(depth):
        if len(vals) >= 2 and rng.rand() < 0.4:
            a, b = rng.choice(len(vals), 2, replace=True)
            vals.append(_BINARY[rng.randint(len(_BINARY))](vals[a],
                                                           vals[b]))
        else:
            v = vals[rng.randint(len(vals))]
            vals.append(_UNARY[rng.randint(len(_UNARY))](v))
    return vals[-1]


def test_serialize_roundtrip_random_graphs():
    for seed in range(8):
        rng = np.random.RandomState(seed)
        feed = rng.randn(4, 8).astype('float32')
        with fresh_program() as (main, startup):
            x = fluid.layers.data(name='x', shape=[8], dtype='float32')
            out = _random_graph(rng, x)
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            want, = exe.run(main, feed={'x': feed}, fetch_list=[out])

            # round-trip through the dict form and re-execute
            blob = main._to_dict()
            clone = fluid.Program._from_dict(blob)
            got, = exe.run(clone, feed={'x': feed},
                           fetch_list=[out.name])
        np.testing.assert_array_equal(
            np.asarray(want), np.asarray(got),
            err_msg='seed %d diverged after round-trip' % seed)


def test_serialize_roundtrip_training_graph():
    rng = np.random.RandomState(0)
    X = rng.randn(8, 8).astype('float32')
    Y = rng.randn(8, 1).astype('float32')
    with fresh_program() as (main, startup):
        x = fluid.layers.data(name='x', shape=[8], dtype='float32')
        y = fluid.layers.data(name='y', shape=[1], dtype='float32')
        h = layers.fc(input=x, size=16, act='relu')
        pred = layers.fc(input=h, size=1)
        cost = fluid.layers.mean(
            fluid.layers.square_error_cost(input=pred, label=y))
        fluid.optimizer.SGD(learning_rate=0.05).minimize(cost)
        exe = fluid.Executor(fluid.CPUPlace())

        blob = main._to_dict()
        clone = fluid.Program._from_dict(blob)

        # train the ORIGINAL three steps, snapshotting the start state
        from paddle_tpu.fluid.executor import global_scope
        exe.run(startup)
        import jax.numpy as jnp
        snap = {k: np.asarray(v)
                for k, v in global_scope().vars.items() if v is not None}
        orig = [float(np.asarray(exe.run(main, feed={'x': X, 'y': Y},
                                         fetch_list=[cost])[0]))
                for _ in range(3)]
        # restore and train the CLONE: identical trajectory
        global_scope().vars.update(
            {k: jnp.asarray(v) for k, v in snap.items()})
        cloned = [float(np.asarray(exe.run(clone, feed={'x': X, 'y': Y},
                                           fetch_list=[cost.name])[0]))
                  for _ in range(3)]
    np.testing.assert_allclose(orig, cloned, rtol=1e-6)
