"""Differential fuzz: random small op graphs must survive Program
serialize → deserialize → re-execution bit-identically (the desc
round-trip the reference guarantees through protobuf; here _to_dict/
_from_dict, framework.py) — and, since PR 5, every VALID random program
must pass the static verifier with zero findings while every seeded
mutation is caught with the right finding kind and op provenance
(docs/analysis.md)."""
import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import analysis, framework, layers
from paddle_tpu.fluid.analysis.findings import (
    DANGLING_INPUT, DTYPE_MISMATCH, UNREACHABLE_FETCH)

from util import fresh_program

# (name, arity, builder) — shape-preserving ops over [B, 8]
_UNARY = [
    lambda v: layers.relu(v),
    lambda v: layers.sigmoid(v),
    lambda v: layers.tanh(v),
    lambda v: layers.scale(v, scale=1.5, bias=0.25),
    lambda v: layers.softmax(v),
    lambda v: layers.abs(v),
    lambda v: layers.elu(v),
    lambda v: layers.l2_normalize(v, axis=-1),
]
_BINARY = [
    lambda a, b: layers.elementwise_add(a, b),
    lambda a, b: layers.elementwise_mul(a, b),
    lambda a, b: layers.elementwise_max(a, b),
    lambda a, b: layers.elementwise_sub(a, b),
]


def _random_graph(rng, x, depth=6):
    vals = [x]
    for _ in range(depth):
        if len(vals) >= 2 and rng.rand() < 0.4:
            a, b = rng.choice(len(vals), 2, replace=True)
            vals.append(_BINARY[rng.randint(len(_BINARY))](vals[a],
                                                           vals[b]))
        else:
            v = vals[rng.randint(len(vals))]
            vals.append(_UNARY[rng.randint(len(_UNARY))](v))
    return vals[-1]


def test_serialize_roundtrip_random_graphs():
    for seed in range(8):
        rng = np.random.RandomState(seed)
        feed = rng.randn(4, 8).astype('float32')
        with fresh_program() as (main, startup):
            x = fluid.layers.data(name='x', shape=[8], dtype='float32')
            out = _random_graph(rng, x)
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            want, = exe.run(main, feed={'x': feed}, fetch_list=[out])

            # round-trip through the dict form and re-execute
            blob = main._to_dict()
            clone = fluid.Program._from_dict(blob)
            got, = exe.run(clone, feed={'x': feed},
                           fetch_list=[out.name])
        np.testing.assert_array_equal(
            np.asarray(want), np.asarray(got),
            err_msg='seed %d diverged after round-trip' % seed)


def test_serialize_roundtrip_training_graph():
    rng = np.random.RandomState(0)
    X = rng.randn(8, 8).astype('float32')
    Y = rng.randn(8, 1).astype('float32')
    with fresh_program() as (main, startup):
        x = fluid.layers.data(name='x', shape=[8], dtype='float32')
        y = fluid.layers.data(name='y', shape=[1], dtype='float32')
        h = layers.fc(input=x, size=16, act='relu')
        pred = layers.fc(input=h, size=1)
        cost = fluid.layers.mean(
            fluid.layers.square_error_cost(input=pred, label=y))
        fluid.optimizer.SGD(learning_rate=0.05).minimize(cost)
        exe = fluid.Executor(fluid.CPUPlace())

        blob = main._to_dict()
        clone = fluid.Program._from_dict(blob)

        # train the ORIGINAL three steps, snapshotting the start state
        from paddle_tpu.fluid.executor import global_scope
        exe.run(startup)
        import jax.numpy as jnp
        snap = {k: np.asarray(v)
                for k, v in global_scope().vars.items() if v is not None}
        orig = [float(np.asarray(exe.run(main, feed={'x': X, 'y': Y},
                                         fetch_list=[cost])[0]))
                for _ in range(3)]
        # restore and train the CLONE: identical trajectory
        global_scope().vars.update(
            {k: jnp.asarray(v) for k, v in snap.items()})
        cloned = [float(np.asarray(exe.run(clone, feed={'x': X, 'y': Y},
                                           fetch_list=[cost.name])[0]))
                  for _ in range(3)]
    np.testing.assert_allclose(orig, cloned, rtol=1e-6)


def test_fuzz_valid_programs_verify_clean():
    """No false positives: every randomly generated valid program (and its
    serialization round-trip) passes verify() with zero findings."""
    for seed in range(8):
        rng = np.random.RandomState(seed)
        with fresh_program() as (main, startup):
            x = fluid.layers.data(name='x', shape=[8], dtype='float32')
            out = _random_graph(rng, x)
            # a random DAG legitimately grows unused branches; fetching
            # every sink makes the whole graph live, so ANY finding —
            # dead-op warnings included — is a false positive
            blk = main.global_block()
            consumed = {n for op in blk.ops for n in op.input_arg_names}
            sinks = [v.name for op in blk.ops
                     for vs in op.outputs.values() for v in vs
                     if v.name not in consumed]
            assert out.name in sinks
            assert analysis.analyze(main, startup=startup,
                                    fetches=sinks) == [], 'seed %d' % seed
            clone = fluid.Program._from_dict(main._to_dict())
            assert analysis.analyze(clone, fetches=sinks) == [], \
                'seed %d after round-trip' % seed
            assert main.verify(fetches=sinks) == []


def test_fuzz_training_program_verifies_clean():
    with fresh_program() as (main, startup):
        x = fluid.layers.data(name='x', shape=[8], dtype='float32')
        y = fluid.layers.data(name='y', shape=[1], dtype='float32')
        pred = layers.fc(input=layers.fc(input=x, size=16, act='relu'),
                         size=1)
        cost = fluid.layers.mean(
            fluid.layers.square_error_cost(input=pred, label=y))
        fluid.optimizer.SGD(learning_rate=0.05).minimize(cost)
        assert analysis.analyze(main, startup=startup,
                                fetches=[cost.name]) == []


def _fuzzed(seed):
    rng = np.random.RandomState(seed)
    x = fluid.layers.data(name='x', shape=[8], dtype='float32')
    return _random_graph(rng, x)


def test_fuzz_mutation_dangling_input():
    """Seeded mutation: an op's input is re-pointed at a var nothing
    produces — caught as DanglingInput with the op's build callsite."""
    for seed in range(4):
        with fresh_program() as (main, _):
            out = _fuzzed(seed)
            blk = main.global_block()
            rng = np.random.RandomState(1000 + seed)
            i = int(rng.randint(len(blk.ops)))
            ghost = framework.Variable(blk, name='ghost_%d' % seed,
                                       shape=[-1, 8], dtype='float32')
            slot = sorted(blk.ops[i].inputs)[0]
            blk.ops[i].inputs[slot] = [ghost]
            fs = analysis.analyze(main)
            hits = [f for f in fs if f.kind == DANGLING_INPUT]
            assert hits, 'seed %d: %s' % (seed, fs)
            assert hits[0].op_index == i
            assert hits[0].callsite and 'test_program_fuzz' in hits[0].callsite


def test_fuzz_mutation_dropped_output_var():
    """Seeded mutation: a producer loses its output binding — every
    downstream reader reports the orphaned name."""
    for seed in range(4):
        with fresh_program() as (main, _):
            out = _fuzzed(seed)
            blk = main.global_block()
            # drop the first op whose output is actually consumed later
            consumed = {n for op in blk.ops for n in op.input_arg_names}
            idx, slot = next(
                (i, s) for i, op in enumerate(blk.ops)
                for s, vs in op.outputs.items()
                if {v.name for v in vs} & consumed)
            victim = next(v.name for v in blk.ops[idx].outputs[slot]
                          if v.name in consumed)
            del blk.ops[idx].outputs[slot]
            fs = analysis.analyze(main)
            hits = [f for f in fs if f.kind == DANGLING_INPUT
                    and victim in f.var_names]
            assert hits, 'seed %d: %s' % (seed, fs)
            assert hits[0].callsite


def test_fuzz_mutation_dtype_corruption():
    """Seeded mutation: one intermediate declaration flips dtype — caught
    as DtypeMismatch at the producing op."""
    for seed in range(4):
        with fresh_program() as (main, _):
            out = _fuzzed(seed)
            blk = main.global_block()
            rng = np.random.RandomState(2000 + seed)
            produced = [v for op in blk.ops
                        for vs in op.outputs.values() for v in vs]
            victim = produced[int(rng.randint(len(produced)))]
            victim.dtype = 'int32'
            fs = analysis.analyze(main)
            hits = [f for f in fs if f.kind == DTYPE_MISMATCH
                    and victim.name in f.var_names]
            assert hits, 'seed %d: %s' % (seed, fs)
            assert hits[0].op_type is not None and hits[0].callsite


def test_fuzz_mutation_dead_fetch():
    for seed in range(4):
        with fresh_program() as (main, _):
            _fuzzed(seed)
            fs = analysis.analyze(main, fetches=['never_produced'])
            assert any(f.kind == UNREACHABLE_FETCH
                       and 'never_produced' in f.var_names for f in fs)


def test_fuzz_cost_pass_never_raises():
    """analyze() with the cost model armed keeps the never-raises
    contract: on valid random graphs it adds NOTHING (no phantom
    ImplicitReshard/HbmOverBudget under a generous budget) and it
    returns findings — not exceptions — on seeded-mutated programs."""
    for seed in range(8):
        rng = np.random.RandomState(seed)
        with fresh_program() as (main, startup):
            x = fluid.layers.data(name='x', shape=[8], dtype='float32')
            out = _random_graph(rng, x)
            blk = main.global_block()
            consumed = {n for op in blk.ops for n in op.input_arg_names}
            sinks = [v.name for op in blk.ops
                     for vs in op.outputs.values() for v in vs
                     if v.name not in consumed]
            assert analysis.analyze(main, startup=startup,
                                    fetches=sinks, cost=True,
                                    hbm_budget=1 << 40) == [], \
                'seed %d: cost pass is not finding-free' % seed
            rep = analysis.cost_report(main, fetches=sinks)
            assert rep.flops_per_step > 0
            assert rep.collectives == []

            # now corrupt it every way the mutation drills do — the
            # armed analyze must still return a list, never raise
            for mutate in (_mut_dangle, _mut_shape, _mut_dtype):
                clone = fluid.Program._from_dict(main._to_dict())
                mutate(clone, np.random.RandomState(7000 + seed))
                fs = analysis.analyze(clone, fetches=sinks, cost=True,
                                      hbm_budget=1)
                assert isinstance(fs, list)

            # a dtype no numpy understands is beyond what the shapes
            # pass tolerates, but the COST pass on its own must still
            # degrade to findings, not a traceback
            from paddle_tpu.fluid.analysis import costmodel
            clone = fluid.Program._from_dict(main._to_dict())
            clone.global_block().vars[x.name].dtype = 'not_a_dtype'
            assert isinstance(costmodel.run_pass(clone, hbm_budget=1),
                              list)


def _mut_dangle(program, rng):
    blk = program.global_block()
    i = int(rng.randint(len(blk.ops)))
    ghost = framework.Variable(blk, name='cost_ghost', shape=[-1, 8],
                               dtype='float32')
    blk.ops[i].inputs[sorted(blk.ops[i].inputs)[0]] = [ghost]


def _mut_shape(program, rng):
    blk = program.global_block()
    names = sorted(blk.vars)
    v = blk.vars[names[int(rng.randint(len(names)))]]
    v.shape = None   # shape info lost entirely: bytes must degrade to 0


def _mut_dtype(program, rng):
    blk = program.global_block()
    names = sorted(blk.vars)
    v = blk.vars[names[int(rng.randint(len(names)))]]
    v.dtype = 'float64'   # declared wide: narrowed at the device edge
