"""CRF/CTC/edit-distance/chunk_eval numeric checks vs brute force.

Mirrors reference unittests/test_linear_chain_crf_op.py, test_crf_decoding_op,
test_ctc_align_op, test_edit_distance_op, test_warpctc_op, test_chunk_eval_op.
"""
import itertools

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid.lowering import SeqValue, Ctx
from paddle_tpu.fluid.ops_impl import crf_ctc_ops as M

from util import fresh_program

rng = np.random.RandomState(7)


def ctx():
    return Ctx(jax.random.key(0))


def _seq(arr, lens):
    return SeqValue(jnp.asarray(arr), jnp.asarray(np.asarray(lens, np.int32)))


class TestCRF:
    B, T, C = 2, 4, 3

    def setup_method(self, _):
        self.em = rng.randn(self.B, self.T, self.C).astype(np.float32)
        self.lens = np.array([4, 2], np.int32)
        self.lab = rng.randint(0, self.C, (self.B, self.T)).astype(np.int64)
        self.trans = (rng.randn(self.C + 2, self.C) * 0.3).astype(np.float32)

    def _score(self, bi, seq):
        a, b, w = self.trans[0], self.trans[1], self.trans[2:]
        s = a[seq[0]] + b[seq[-1]]
        s += sum(self.em[bi, t, seq[t]] for t in range(len(seq)))
        s += sum(w[seq[t - 1], seq[t]] for t in range(1, len(seq)))
        return s

    def test_nll_matches_brute_force(self):
        ins = {'Emission': [_seq(self.em, self.lens)],
               'Transition': [jnp.asarray(self.trans)],
               'Label': [_seq(self.lab[:, :, None], self.lens)]}
        nll = np.asarray(M._linear_chain_crf(ins, {}, ctx())['LogLikelihood'])[:, 0]
        for bi in range(self.B):
            L = self.lens[bi]
            logZ = np.log(sum(np.exp(self._score(bi, s))
                              for s in itertools.product(range(self.C), repeat=L)))
            want = logZ - self._score(bi, self.lab[bi, :L])
            assert abs(nll[bi] - want) < 1e-3

    def test_viterbi_matches_brute_force(self):
        ins = {'Emission': [_seq(self.em, self.lens)],
               'Transition': [jnp.asarray(self.trans)]}
        vp = np.asarray(M._crf_decoding(ins, {}, ctx())['ViterbiPath'].data)[:, :, 0]
        for bi in range(self.B):
            L = self.lens[bi]
            best = max(itertools.product(range(self.C), repeat=L),
                       key=lambda s: self._score(bi, s))
            assert tuple(vp[bi, :L]) == best

    def test_decoding_with_label_marks_correct(self):
        ins = {'Emission': [_seq(self.em, self.lens)],
               'Transition': [jnp.asarray(self.trans)],
               'Label': [_seq(self.lab[:, :, None], self.lens)]}
        out = np.asarray(M._crf_decoding(ins, {}, ctx())['ViterbiPath'].data)
        assert set(np.unique(out)) <= {0, 1}

    def test_crf_grad_flows(self):
        def loss(trans):
            ins = {'Emission': [_seq(self.em, self.lens)],
                   'Transition': [trans],
                   'Label': [_seq(self.lab[:, :, None], self.lens)]}
            return jnp.sum(M._linear_chain_crf(ins, {}, ctx())['LogLikelihood'])
        g = jax.grad(loss)(jnp.asarray(self.trans))
        assert np.all(np.isfinite(np.asarray(g)))


def test_edit_distance():
    def lev(h, r):
        d = np.zeros((len(h) + 1, len(r) + 1))
        d[:, 0] = np.arange(len(h) + 1)
        d[0, :] = np.arange(len(r) + 1)
        for i in range(1, len(h) + 1):
            for j in range(1, len(r) + 1):
                d[i, j] = min(d[i - 1, j] + 1, d[i, j - 1] + 1,
                              d[i - 1, j - 1] + (h[i - 1] != r[j - 1]))
        return d[-1, -1]

    hl = np.array([6, 4, 2], np.int32)
    rl = np.array([5, 6, 3], np.int32)
    hyp = rng.randint(1, 5, (3, 6)).astype(np.int64)
    ref = rng.randint(1, 5, (3, 6)).astype(np.int64)
    ins = {'Hyps': [_seq(hyp[:, :, None], hl)], 'Refs': [_seq(ref[:, :, None], rl)]}
    got = np.asarray(M._edit_distance(ins, {'normalized': False}, ctx())['Out'])[:, 0]
    for bi in range(3):
        assert abs(got[bi] - lev(hyp[bi, :hl[bi]], ref[bi, :rl[bi]])) < 1e-5
    norm = np.asarray(M._edit_distance(ins, {'normalized': True}, ctx())['Out'])[:, 0]
    np.testing.assert_allclose(norm, got / np.maximum(rl, 1), rtol=1e-6)


def test_ctc_align_merge_and_blank():
    ids = np.array([[0, 1, 1, 0, 2, 2], [3, 3, 0, 1, 0, 0]])
    probs = np.zeros((2, 6, 4), np.float32)
    for b in range(2):
        for t in range(6):
            probs[b, t, ids[b, t]] = 5
    out = M._ctc_align({'Input': [_seq(probs, [6, 4])]},
                       {'blank': 0, 'merge_repeated': True}, ctx())['Output']
    o = np.asarray(out.data)[:, :, 0]
    ol = np.asarray(out.lengths)
    assert list(o[0, :ol[0]]) == [1, 2]
    assert list(o[1, :ol[1]]) == [3, 1]


def test_warpctc_matches_brute_force():
    B, T, C = 2, 5, 3
    logits = rng.randn(B, T, C).astype(np.float32)
    lab = np.array([[1, 2], [2, 1]], np.int64)
    tl = np.array([5, 4], np.int32)
    ll = np.array([2, 1], np.int32)
    ins = {'Logits': [_seq(logits, tl)], 'Label': [_seq(lab[:, :, None], ll)]}
    loss = np.asarray(M._warpctc(ins, {'blank': 0}, ctx())['Loss'])[:, 0]

    def brute(lp, lab_):
        T_, C_ = lp.shape
        tot = 0.0
        for path in itertools.product(range(C_), repeat=T_):
            col, prev = [], -1
            for p in path:
                if p != prev and p != 0:
                    col.append(p)
                prev = p
            if col == list(lab_):
                tot += np.exp(sum(lp[t, path[t]] for t in range(T_)))
        return -np.log(tot)

    for bi in range(B):
        lp = np.asarray(jax.nn.log_softmax(jnp.asarray(logits[bi, :tl[bi]]), axis=-1))
        assert abs(loss[bi] - brute(lp, lab[bi, :ll[bi]])) < 1e-3


def test_warpctc_grad_flows():
    B, T, C = 2, 5, 3
    logits = rng.randn(B, T, C).astype(np.float32)
    lab = np.array([[1, 2], [2, 1]], np.int64)

    def loss(lg):
        ins = {'Logits': [_seq(lg, [5, 4])],
               'Label': [_seq(lab[:, :, None], [2, 1])]}
        return jnp.sum(M._warpctc(ins, {'blank': 0}, ctx())['Loss'])

    g = jax.grad(loss)(jnp.asarray(logits))
    assert np.all(np.isfinite(np.asarray(g)))


def test_chunk_eval_iob():
    # types=2, IOB: B-0=0, I-0=1, B-1=2, I-1=3, O=4
    inf = np.array([[0, 1, 4, 2, 3, 4]], np.int64)
    lab = np.array([[0, 1, 4, 2, 1, 4]], np.int64)
    out = M._chunk_eval(
        {'Inference': [_seq(inf[:, :, None], [6])],
         'Label': [_seq(lab[:, :, None], [6])]},
        {'num_chunk_types': 2, 'chunk_scheme': 'IOB'}, ctx())
    assert int(out['NumInferChunks']) == 2
    assert int(out['NumLabelChunks']) == 3
    assert int(out['NumCorrectChunks']) == 1
    assert abs(float(out['Precision']) - 0.5) < 1e-6


def test_crf_layer_end_to_end():
    """linear_chain_crf + crf_decoding through the Program/Executor path
    (reference book chapter label_semantic_roles shape)."""
    with fresh_program() as (main, startup):
        feat = fluid.layers.data('feat', shape=[4], dtype='float32',
                                 lod_level=1)
        lab = fluid.layers.data('lab', shape=[1], dtype='int64', lod_level=1)
        emission = fluid.layers.fc(input=feat, size=3)
        crf_cost = fluid.layers.linear_chain_crf(
            emission, lab, param_attr=fluid.ParamAttr(name='crfw'))
        avg = fluid.layers.mean(crf_cost)
        sgd = fluid.optimizer.SGD(learning_rate=0.05)
        sgd.minimize(avg)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        from paddle_tpu.fluid.lod_tensor import create_lod_tensor
        import paddle_tpu.fluid.core as core
        feats = [rng.randn(4, 4).astype(np.float32),
                 rng.randn(6, 4).astype(np.float32)]
        labs = [rng.randint(0, 3, (4, 1)).astype(np.int64),
                rng.randint(0, 3, (6, 1)).astype(np.int64)]
        ft = create_lod_tensor(np.concatenate(feats), [[4, 6]], core.CPUPlace())
        lt = create_lod_tensor(np.concatenate(labs), [[4, 6]], core.CPUPlace())
        losses = []
        for _ in range(8):
            out, = exe.run(main, feed={'feat': ft, 'lab': lt},
                           fetch_list=[avg])
            losses.append(float(out))
        assert losses[-1] < losses[0]  # CRF NLL decreases under SGD
