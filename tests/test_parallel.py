"""Mesh / data-parallel tests on the 8-virtual-device CPU platform."""
import numpy as np

import jax
import paddle_tpu as paddle
import paddle_tpu.fluid as fluid
from paddle_tpu import parallel

from util import fresh_program


def test_eight_devices_available():
    assert len(jax.devices()) == 8


def test_parallel_executor_matches_single_device():
    """dp-sharded step must produce the same losses as single-device."""
    def build():
        x = fluid.layers.data(name='x', shape=[13], dtype='float32')
        y = fluid.layers.data(name='y', shape=[1], dtype='float32')
        pred = fluid.layers.fc(input=x, size=1,
                               param_attr=fluid.ParamAttr(
                                   initializer=fluid.initializer.Constant(0.05)))
        cost = fluid.layers.mean(
            fluid.layers.square_error_cost(input=pred, label=y))
        fluid.optimizer.SGD(learning_rate=0.05).minimize(cost)
        return cost

    rng = np.random.RandomState(0)
    xs = rng.rand(16, 13).astype('float32')
    ys = rng.rand(16, 1).astype('float32')

    with fresh_program() as (main, startup):
        cost = build()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        single = [float(exe.run(main, feed={'x': xs, 'y': ys},
                                fetch_list=[cost])[0]) for _ in range(4)]

    with fresh_program() as (main, startup):
        cost = build()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        pe = fluid.ParallelExecutor(use_cuda=False, loss_name=cost.name,
                                    main_program=main)
        par = [float(pe.run([cost.name], feed={'x': xs, 'y': ys})[0])
               for _ in range(4)]

    np.testing.assert_allclose(single, par, rtol=2e-4)


def test_parallel_executor_rejects_non_divisible_batch():
    """A batch not divisible by the mesh must raise, not silently pad
    (duplicated rows would double-weight examples in the loss)."""
    import pytest
    with fresh_program() as (main, startup):
        x = fluid.layers.data(name='x', shape=[13], dtype='float32')
        y = fluid.layers.data(name='y', shape=[1], dtype='float32')
        pred = fluid.layers.fc(input=x, size=1)
        cost = fluid.layers.mean(
            fluid.layers.square_error_cost(input=pred, label=y))
        fluid.optimizer.SGD(learning_rate=0.05).minimize(cost)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        pe = fluid.ParallelExecutor(use_cuda=False, loss_name=cost.name,
                                    main_program=main)
        xs = np.zeros((13, 13), 'float32')  # 13 % 8 != 0
        ys = np.zeros((13, 1), 'float32')
        with pytest.raises(ValueError, match='not divisible'):
            pe.run([cost.name], feed={'x': xs, 'y': ys})


def test_dryrun_multichip():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        '__graft_entry__', '__graft_entry__.py')
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod.dryrun_multichip(8)


def test_collectives_shard_map():
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P
    mesh = parallel.make_mesh({'dp': 8})
    x = np.arange(8 * 4, dtype=np.float32).reshape(8, 4)

    def f(x):
        return parallel.psum(x, 'dp')

    out = shard_map(f, mesh=mesh, in_specs=P('dp'), out_specs=P('dp'))(x)
    expect = np.broadcast_to(x.sum(0, keepdims=True), (8, 4)).reshape(8, 4)
    np.testing.assert_allclose(np.asarray(out)[0], x.sum(0))


def test_zero_sharded_optimizer_states():
    mesh = parallel.make_mesh({'dp': 8})
    vals = {'m': np.zeros((16, 4), np.float32), 's': np.zeros((3,), np.float32)}
    out = parallel.shard_optimizer_states(vals, mesh)
    assert out['m'].sharding.spec == parallel.P('dp', None)
