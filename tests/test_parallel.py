"""Mesh / data-parallel tests on the 8-virtual-device CPU platform."""
import numpy as np
import pytest

import jax
import paddle_tpu as paddle
import paddle_tpu.fluid as fluid
from paddle_tpu import parallel

from util import fresh_program


def test_eight_devices_available():
    assert len(jax.devices()) == 8


def test_parallel_executor_matches_single_device():
    """dp-sharded step must produce the same losses as single-device."""
    def build():
        x = fluid.layers.data(name='x', shape=[13], dtype='float32')
        y = fluid.layers.data(name='y', shape=[1], dtype='float32')
        pred = fluid.layers.fc(input=x, size=1,
                               param_attr=fluid.ParamAttr(
                                   initializer=fluid.initializer.Constant(0.05)))
        cost = fluid.layers.mean(
            fluid.layers.square_error_cost(input=pred, label=y))
        fluid.optimizer.SGD(learning_rate=0.05).minimize(cost)
        return cost

    rng = np.random.RandomState(0)
    xs = rng.rand(16, 13).astype('float32')
    ys = rng.rand(16, 1).astype('float32')

    with fresh_program() as (main, startup):
        cost = build()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        single = [float(exe.run(main, feed={'x': xs, 'y': ys},
                                fetch_list=[cost])[0]) for _ in range(4)]

    with fresh_program() as (main, startup):
        cost = build()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        pe = fluid.ParallelExecutor(use_cuda=False, loss_name=cost.name,
                                    main_program=main)
        par = [float(pe.run([cost.name], feed={'x': xs, 'y': ys})[0])
               for _ in range(4)]

    np.testing.assert_allclose(single, par, rtol=2e-4)


def test_parallel_executor_rejects_non_divisible_batch():
    """A batch not divisible by the mesh must raise, not silently pad
    (duplicated rows would double-weight examples in the loss)."""
    import pytest
    with fresh_program() as (main, startup):
        x = fluid.layers.data(name='x', shape=[13], dtype='float32')
        y = fluid.layers.data(name='y', shape=[1], dtype='float32')
        pred = fluid.layers.fc(input=x, size=1)
        cost = fluid.layers.mean(
            fluid.layers.square_error_cost(input=pred, label=y))
        fluid.optimizer.SGD(learning_rate=0.05).minimize(cost)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        pe = fluid.ParallelExecutor(use_cuda=False, loss_name=cost.name,
                                    main_program=main)
        xs = np.zeros((13, 13), 'float32')  # 13 % 8 != 0
        ys = np.zeros((13, 1), 'float32')
        with pytest.raises(ValueError, match='not divisible'):
            pe.run([cost.name], feed={'x': xs, 'y': ys})


def test_dryrun_multichip():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        '__graft_entry__', '__graft_entry__.py')
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod.dryrun_multichip(8)


def test_collectives_shard_map():
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P
    mesh = parallel.make_mesh({'dp': 8})
    x = np.arange(8 * 4, dtype=np.float32).reshape(8, 4)

    def f(x):
        return parallel.psum(x, 'dp')

    out = shard_map(f, mesh=mesh, in_specs=P('dp'), out_specs=P('dp'))(x)
    expect = np.broadcast_to(x.sum(0, keepdims=True), (8, 4)).reshape(8, 4)
    np.testing.assert_allclose(np.asarray(out)[0], x.sum(0))


def test_zero_sharded_optimizer_states():
    mesh = parallel.make_mesh({'dp': 8})
    vals = {'m': np.zeros((16, 4), np.float32), 's': np.zeros((3,), np.float32)}
    out = parallel.shard_optimizer_states(vals, mesh)
    assert out['m'].sharding.spec == parallel.P('dp', None)


class TestAutoTpRules:
    """parallel.auto_tp_rules: per-layer Megatron-style tp layouts derived
    from the Program graph (parallel/tp.py)."""

    @staticmethod
    def _layers():
        import paddle_tpu.fluid as fluid
        x = fluid.layers.data(name='x', shape=[12], dtype='int64')
        y = fluid.layers.data(name='y', shape=[1], dtype='float32')
        emb = fluid.layers.embedding(x, size=[50, 16])
        h = fluid.layers.fc(input=emb, size=32, act='relu',
                            num_flatten_dims=2)
        h2 = fluid.layers.fc(input=h, size=16, num_flatten_dims=2)
        pooled = fluid.layers.reduce_mean(h2, dim=1)
        pred = fluid.layers.fc(input=pooled, size=1)
        cost = fluid.layers.mean(
            fluid.layers.square_error_cost(input=pred, label=y))
        fluid.optimizer.SGD(learning_rate=0.01).minimize(cost)
        return cost

    def test_megatron_alternation(self):
        import re as _re
        from jax.sharding import PartitionSpec as P
        from paddle_tpu import parallel
        with fresh_program() as (main, startup):
            self._layers()
            rules = dict(parallel.auto_tp_rules(main, axis='tp'))
        # embedding: hidden-sharded; fc_0 consumes it -> row-parallel
        # (replicated bias); fc_1 takes the full output -> column-parallel
        # with tp-sharded bias. Patterns are exact-name anchored.
        by_name = {}
        for pat, spec in rules.items():
            for n in ('embedding_0.w_0', 'fc_0.w_0', 'fc_0.b_0',
                      'fc_1.w_0', 'fc_1.b_0'):
                if _re.search(pat, n):
                    by_name[n] = spec
        assert by_name['embedding_0.w_0'] == P(None, 'tp')
        assert by_name['fc_0.w_0'] == P('tp', None)
        assert 'fc_0.b_0' not in by_name
        assert by_name['fc_1.w_0'] == P(None, 'tp')
        assert by_name['fc_1.b_0'] == P('tp')
        # anchoring: a prefixed name must NOT match another param's rule
        assert not any(_re.search(p, 'pre_fc_0.w_0') for p in rules)

    def test_sharded_step_matches_single_device(self):
        import jax.numpy as jnp
        import paddle_tpu.fluid as fluid
        from paddle_tpu import parallel
        from paddle_tpu.fluid.executor import global_scope
        with fresh_program() as (main, startup):
            cost = self._layers()
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            scope = global_scope()
            snap = {k: np.asarray(v) for k, v in scope.vars.items()
                    if v is not None}
            rng = np.random.RandomState(0)
            X = rng.randint(0, 50, size=(8, 12)).astype('int64')
            Y = rng.randn(8, 1).astype('float32')
            single = [float(np.asarray(
                exe.run(main, feed={'x': X, 'y': Y}, fetch_list=[cost])[0]))
                for _ in range(3)]

            scope.vars.update({k: jnp.asarray(v) for k, v in snap.items()})
            mesh = parallel.make_mesh({'dp': 4, 'tp': 2})
            rules = parallel.auto_tp_rules(main, axis='tp')
            import warnings
            with warnings.catch_warnings():
                # the final [16,1] fc does not divide tp=2: replicated
                warnings.simplefilter('ignore')
                scope.vars.update(parallel.shard_params_by_rules(
                    dict(scope.vars), mesh, rules))
            feed = {'x': parallel.shard_batch(mesh, X),
                    'y': parallel.shard_batch(mesh, Y)}
            sharded = [float(np.asarray(
                exe.run(main, feed=feed, fetch_list=[cost])[0]).mean())
                for _ in range(3)]
            np.testing.assert_allclose(single, sharded, rtol=2e-4)


def test_fsdp_shard_params_matches_replicated():
    """parallel.fsdp_shard_params (ZeRO-3): params sharded over dp, GSPMD
    inserts gathers — identical training trajectory, params STAY sharded
    through the compiled step."""
    import jax.numpy as jnp
    from jax.sharding import NamedSharding
    import paddle_tpu.fluid as fluid
    from paddle_tpu import parallel
    from paddle_tpu.fluid.executor import global_scope
    from util import fresh_program

    rng = np.random.RandomState(0)
    X = rng.rand(16, 32).astype('float32')
    Y = rng.rand(16, 1).astype('float32')
    with fresh_program() as (main, startup):
        x = fluid.layers.data(name='x', shape=[32], dtype='float32')
        y = fluid.layers.data(name='y', shape=[1], dtype='float32')
        h = fluid.layers.fc(input=x, size=64, act='relu')
        pred = fluid.layers.fc(input=h, size=1)
        cost = fluid.layers.mean(
            fluid.layers.square_error_cost(input=pred, label=y))
        fluid.optimizer.Momentum(learning_rate=0.05,
                                 momentum=0.9).minimize(cost)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        scope = global_scope()
        snap = {k: np.asarray(v) for k, v in scope.vars.items()
                if v is not None}
        single = [float(np.asarray(
            exe.run(main, feed={'x': X, 'y': Y}, fetch_list=[cost])[0]))
            for _ in range(3)]

        scope.vars.update({k: jnp.asarray(v) for k, v in snap.items()})
        mesh = parallel.make_mesh({'dp': 8})
        scope.vars.update(parallel.fsdp_shard_params(
            dict(scope.vars), mesh, min_size=64))
        feed = {'x': parallel.shard_batch(mesh, X),
                'y': parallel.shard_batch(mesh, Y)}
        fsdp = [float(np.asarray(
            exe.run(main, feed=feed, fetch_list=[cost])[0]).mean())
            for _ in range(3)]
        np.testing.assert_allclose(single, fsdp, rtol=2e-4)

        # parameter is still dp-sharded after the jitted updates
        w = scope.vars['fc_0.w_0']
        assert isinstance(w.sharding, NamedSharding)
        assert 'dp' in str(w.sharding.spec)
        # small tensors (< min_size) stay replicated
        b = scope.vars['fc_1.b_0']
        assert str(getattr(b.sharding, 'spec', 'replicated')) \
            in ('PartitionSpec()', 'replicated')


def test_sharding_passes_compose():
    """fsdp_shard_params + shard_optimizer_states must not undo each
    other's placements (docs/distributed.md ZeRO-3 recipe)."""
    import jax.numpy as jnp
    mesh = parallel.make_mesh({'dp': 8})
    vals = {'w': jnp.zeros((30, 64)),      # dim0 not divisible: fsdp dim1
            'acc': jnp.zeros((64, 8))}
    a = parallel.fsdp_shard_params(vals, mesh, min_size=128)
    b = parallel.shard_optimizer_states(a, mesh)
    assert str(b['w'].sharding.spec) == "PartitionSpec(None, 'dp')"
    assert str(b['acc'].sharding.spec) == "PartitionSpec('dp',)"
    # reverse order: zero shards dim0, fsdp leaves it alone
    c = parallel.fsdp_shard_params(
        parallel.shard_optimizer_states(vals, mesh), mesh, min_size=128)
    assert str(c['w'].sharding.spec) == "PartitionSpec(None, 'dp')"
    assert str(c['acc'].sharding.spec) == "PartitionSpec('dp', None)"


def test_build_strategy_reduce_is_fsdp():
    """BuildStrategy.ReduceStrategy.Reduce (the reference's partitioned
    parameter updates) maps to ZeRO-3 parameter sharding: same losses as
    AllReduce, params dp-sharded."""
    from jax.sharding import NamedSharding

    def build():
        x = fluid.layers.data(name='x', shape=[32], dtype='float32')
        y = fluid.layers.data(name='y', shape=[1], dtype='float32')
        h = fluid.layers.fc(input=x, size=64, act='relu')
        pred = fluid.layers.fc(input=h, size=1)
        cost = fluid.layers.mean(
            fluid.layers.square_error_cost(input=pred, label=y))
        fluid.optimizer.SGD(learning_rate=0.05).minimize(cost)
        return cost

    rng = np.random.RandomState(0)
    X = rng.rand(16, 32).astype('float32')
    Y = rng.rand(16, 1).astype('float32')

    with fresh_program() as (main, startup):
        cost = build()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        pe = fluid.ParallelExecutor(use_cuda=False, loss_name=cost.name,
                                    main_program=main)
        allreduce = [float(np.asarray(pe.run([cost.name],
                                             feed={'x': X, 'y': Y})[0])
                           .mean()) for _ in range(3)]

    with fresh_program() as (main, startup):
        cost = build()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        bs = fluid.BuildStrategy()
        bs.reduce_strategy = fluid.BuildStrategy.ReduceStrategy.Reduce
        pe = fluid.ParallelExecutor(use_cuda=False, loss_name=cost.name,
                                    main_program=main, build_strategy=bs)
        reduced = [float(np.asarray(pe.run([cost.name],
                                           feed={'x': X, 'y': Y})[0])
                         .mean()) for _ in range(3)]
        from paddle_tpu.fluid.executor import global_scope
        w = global_scope().vars['fc_0.w_0']
        assert isinstance(w.sharding, NamedSharding)
        assert 'dp' in str(w.sharding.spec)
    np.testing.assert_allclose(allreduce, reduced, rtol=2e-4)


# ---------------------------------------------------------------------------
# Pod-scale GSPMD: sharding as a first-class Program concern
# (docs/parallel.md). One annotated Program through PLAIN
# Executor.run/run_bundle — no strategy wrapper — must match
# single-device execution, keep its declared layouts, and compile
# without involuntary rematerialization.
# ---------------------------------------------------------------------------

gspmd = pytest.mark.gspmd


def _annotated_net(hidden=32, mp_spec=None):
    """fc(hidden) -> fc(1) -> mse -> SGD; the first weight optionally
    carries a model-parallel annotation."""
    x = fluid.layers.data(name='x', shape=[16], dtype='float32')
    y = fluid.layers.data(name='y', shape=[1], dtype='float32')
    pa = fluid.ParamAttr(initializer=fluid.initializer.Constant(0.05),
                         sharding=mp_spec)
    h = fluid.layers.fc(input=x, size=hidden, act='relu', param_attr=pa)
    pred = fluid.layers.fc(input=h, size=1)
    cost = fluid.layers.mean(
        fluid.layers.square_error_cost(input=pred, label=y))
    fluid.optimizer.SGD(learning_rate=0.05).minimize(cost)
    return cost


def _ab_data(batch=16):
    rng = np.random.RandomState(0)
    return (rng.rand(batch, 16).astype('float32'),
            rng.rand(batch, 1).astype('float32'))


def _run_annotated(mesh_axes, mp_spec=None, steps=4):
    """Build, optionally set_mesh, run `steps` plain Executor.run steps.
    Returns (losses, first-weight jax sharding, executor)."""
    xs, ys = _ab_data()
    with fresh_program() as (main, startup):
        cost = _annotated_net(mp_spec=mp_spec)
        if mesh_axes:
            main.set_mesh(mesh_axes)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        losses = [float(exe.run(main, feed={'x': xs, 'y': ys},
                                fetch_list=[cost])[0])
                  for _ in range(steps)]
        from paddle_tpu.fluid.executor import global_scope
        w = global_scope().vars['fc_0.w_0']
        return losses, getattr(w, 'sharding', None), exe


@gspmd
def test_annotated_dp8_matches_single_device():
    """The A/B contract (same tolerance posture as test_passes.py, with
    the documented cross-device caveat): dp=8 through plain Executor.run
    reorders the batch reduction across shards, so fetches agree to
    float-sum noise, not bit-for-bit. No wrapper anywhere in the dp leg;
    the compile must also be remat-free."""
    single, _, _ = _run_annotated(None)
    dp, w_sh, exe = _run_annotated({'dp': 8})
    np.testing.assert_allclose(dp, single, rtol=2e-5)
    assert single[0] != single[3]          # training actually progressed
    # params were mesh-placed (replicated: no annotation on the weight)
    from jax.sharding import NamedSharding
    assert isinstance(w_sh, NamedSharding)
    assert len(w_sh.device_set) == 8
    assert exe.remat_detected == 0
    assert exe.cache_stats['remat_detected'] == 0


@gspmd
def test_annotated_model_parallel_matches_single_device():
    """ParamAttr(sharding=(None, 'model')) on a dp x model mesh: same
    losses, and the weight KEEPS its annotated layout across donated
    update steps (the sharding fixed point, docs/parallel.md)."""
    single, _, _ = _run_annotated(None)
    mp, w_sh, exe = _run_annotated({'dp': 2, 'model': 4},
                                   mp_spec=(None, 'model'))
    np.testing.assert_allclose(mp, single, rtol=2e-5)
    assert str(w_sh.spec) == "PartitionSpec(None, 'model')"
    assert exe.remat_detected == 0


@gspmd
def test_annotated_run_bundle_matches_plain_runs():
    """run_bundle(K=4) on the annotated Program: the scan carry rides the
    SAME shardings as the unbundled step — losses match 4 plain runs."""
    single, _, _ = _run_annotated(None)
    xs, ys = _ab_data()
    with fresh_program() as (main, startup):
        cost = _annotated_net()
        main.set_mesh({'dp': 8})
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        out, = exe.run_bundle(main, feeds=[{'x': xs, 'y': ys}] * 4,
                              fetch_list=[cost], steps=4)
        bundled = [float(v) for v in np.asarray(out).reshape(-1)]
        assert exe.remat_detected == 0
    np.testing.assert_allclose(bundled, single, rtol=2e-5)


@gspmd
def test_annotated_feed_batch_not_divisible_raises():
    """A feed whose batch the data axis cannot tile must raise with the
    drop_last hint, not silently pad (padding double-weights rows)."""
    with fresh_program() as (main, startup):
        cost = _annotated_net()
        main.set_mesh({'dp': 8})
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        with pytest.raises(ValueError, match='not divisible'):
            exe.run(main, feed={'x': np.zeros((13, 16), 'float32'),
                                'y': np.zeros((13, 1), 'float32')},
                    fetch_list=[cost])


@gspmd
def test_mesh_and_annotations_survive_clone_and_serialization():
    """set_mesh + per-var specs are Program properties: clone() and the
    _to_dict/_from_dict artifact round-trip both carry them; an
    UN-annotated program serializes without any sharding keys (artifacts
    stay byte-compatible with pre-gspmd readers)."""
    from paddle_tpu.fluid import framework
    with fresh_program() as (main, _):
        _annotated_net(mp_spec=(None, 'model'))
        main.set_mesh({'dp': 2, 'model': 4})
    d = main._to_dict()
    assert d['mesh'] == {'axes': [['dp', 2], ['model', 4]],
                         'data_axis': 'dp'}
    p2 = framework.Program._from_dict(d)
    assert p2._mesh_axes == (('dp', 2), ('model', 4))
    assert p2._mesh_data_axis == 'dp'
    assert p2.global_block().vars['fc_0.w_0'].sharding == (None, 'model')
    c = main.clone()
    assert c._mesh_axes == (('dp', 2), ('model', 4))
    assert c.global_block().vars['fc_0.w_0'].sharding == (None, 'model')

    with fresh_program() as (plain, _):
        _annotated_net()
    pd = plain._to_dict()
    assert 'mesh' not in pd
    assert all('sharding' not in v
               for b in pd['blocks'] for v in b['vars'])


@gspmd
def test_set_mesh_and_annotation_validation():
    """Bad specs fail at the declaration site, not inside jit."""
    from paddle_tpu.fluid import framework
    p = framework.Program()
    with pytest.raises(ValueError, match='duplicate mesh axis'):
        p.set_mesh([('dp', 4), ('dp', 2)])
    with pytest.raises(ValueError, match='has size'):
        p.set_mesh({'dp': 0})
    with pytest.raises(ValueError, match='not a mesh axis'):
        p.set_mesh({'dp': 8}, data_axis='model')
    with pytest.raises(ValueError, match='at least one'):
        p.set_mesh([])
    p.set_mesh({'dp': 8})
    assert p.mesh_axes == {'dp': 8} and p._mesh_data_axis == 'dp'
    p.set_mesh(None)
    assert p.mesh_axes is None
    # normalize_sharding: the ParamAttr/Variable-level half
    norm = framework.normalize_sharding
    assert norm('model') == ('model',)
    assert norm(['model', None]) == ('model', None)
    assert norm((('tp', 'dp'), None)) == (('tp', 'dp'), None)
    with pytest.raises(ValueError, match='bad sharding entry'):
        norm((1,))
    with pytest.raises(ValueError, match='sharding must be'):
        fluid.ParamAttr(sharding=7)


@gspmd
def test_init_distributed_single_process_smoke():
    """num_processes=1 (or no args outside a cluster) is the documented
    no-op; a >1-process spec without an address must fail loudly."""
    r = parallel.init_distributed()
    assert r == {'num_processes': 1, 'process_id': 0, 'initialized': False}
    assert parallel.init_distributed(num_processes=1)['initialized'] is False
    with pytest.raises(ValueError, match='coordinator_address'):
        parallel.init_distributed(num_processes=2)
    assert parallel.process_count() == 1
    assert parallel.process_index() == 0


@gspmd
def test_reader_shard_slices_reassemble_global_batch():
    """reader.shard round-robin: batched with the same per-host size, the
    hosts' step-k batches partition exactly the global step-k batch (the
    property parallel.global_batch relies on), and an uneven tail is
    dropped on EVERY host (unequal step counts would deadlock the
    collective at the shorter host's last step)."""
    from paddle_tpu import reader as rd
    n = 23                                  # 23 = 2*11 + 1: uneven tail
    base = lambda: iter(np.arange(n))
    h0 = list(rd.shard(base, 2, 0)())
    h1 = list(rd.shard(base, 2, 1)())
    assert h0 == list(range(0, 22, 2))
    assert h1 == list(range(1, 22, 2))      # sample 22 dropped everywhere
    assert len(h0) == len(h1)
    # per-host batches of 4 reassemble into the global batch of 8
    B = 4
    for k in range(len(h0) // B):
        got = sorted(h0[k * B:(k + 1) * B] + h1[k * B:(k + 1) * B])
        assert got == list(range(k * 2 * B, (k + 1) * 2 * B))
    # single-process global_batch: the local slice IS the global array
    mesh = parallel.make_mesh({'dp': 8})
    local = np.arange(16, dtype=np.float32).reshape(8, 2)
    arr = parallel.global_batch(parallel.data_sharding(mesh), local)
    np.testing.assert_array_equal(np.asarray(arr), local)
    with pytest.raises(ValueError, match='num_shards'):
        rd.shard(base, 0, 0)
    with pytest.raises(ValueError, match='out of range'):
        rd.shard(base, 2, 2)


@gspmd
def test_remat_hook_counts_and_warns():
    """The MULTICHIP blind-spot fix: a compile whose captured stderr
    contains XLA's involuntary-rematerialization diagnostic becomes an
    executor.remat_detected event + counter + cache_stats entry + a
    Python warning — never a silently-lost C++ log line."""
    from paddle_tpu import obs
    from paddle_tpu.fluid import executor as executor_mod
    exe = fluid.Executor(fluid.CPUPlace())
    before = executor_mod._C_REMAT.value
    line = (b'2026-08-03 12:00:00 spmd_partitioner.cc:123] '
            b'Involuntary full rematerialization. The compiled was '
            b'%full and to be sharded!\n')
    with pytest.warns(RuntimeWarning, match='involuntary full'):
        exe._scan_remat([line * 2], 'key-under-test')
    assert exe.remat_detected == 2
    assert exe.cache_stats['remat_detected'] == 2
    assert executor_mod._C_REMAT.value == before + 2
    # clean captures never warn or count
    exe._scan_remat([b'ordinary diagnostic\n'], 'key-under-test')
    assert exe.remat_detected == 2


@gspmd
def test_pipeline_dp_composition_compiles_remat_free():
    """Acceptance drill: the pipeline-region + dp composition — the
    MULTICHIP_r05 class that used to log involuntary full
    rematerialization at the region boundary — now compiles clean (the
    executor pins the region output's batch layout, so the backward
    cotangent enters the region already in the partitioned layout)."""
    rng = np.random.RandomState(7)
    xs = rng.rand(8, 12).astype('float32')
    ys = rng.rand(8, 1).astype('float32')

    def build():
        x = fluid.layers.data(name='x', shape=[12], dtype='float32')
        y = fluid.layers.data(name='y', shape=[1], dtype='float32')
        h = fluid.layers.fc(input=x, size=12, act='tanh',
                            param_attr=fluid.ParamAttr(
                                initializer=fluid.initializer.Constant(0.05)))
        for k in range(2):
            with fluid.device_guard('pipe:%d' % k):
                f = fluid.layers.fc(
                    input=h, size=12, act='tanh', bias_attr=False,
                    param_attr=fluid.ParamAttr(
                        initializer=fluid.initializer.Constant(
                            0.01 * (k + 1))))
                h = fluid.layers.elementwise_add(f, h)
        pred = fluid.layers.fc(input=h, size=1)
        cost = fluid.layers.mean(
            fluid.layers.square_error_cost(input=pred, label=y))
        fluid.optimizer.SGD(learning_rate=0.05).minimize(cost)
        return cost

    def run(dist):
        with fresh_program() as (main, startup):
            cost = build()
            if dist:
                fluid.PipelineTranspiler(n_micro=2).transpile(main)
                fluid.DistributeTranspiler().transpile(trainer_id=0,
                                                       trainers=2)
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            losses = [float(exe.run(main, feed={'x': xs, 'y': ys},
                                    fetch_list=[cost])[0])
                      for _ in range(2)]
            return losses, exe

    base, _ = run(False)
    got, exe = run(True)
    assert exe.cache_stats['misses'] >= 1     # it really compiled
    assert exe.remat_detected == 0            # ...and stayed remat-free
    # no loss equality here: pipeline x dp numerics diverge under the
    # pre-0.6 shard_map compat shim (the xfailed
    # test_pipeline_composes_with_dp tracks that, pre-existing) — this
    # drill owns the COMPILE contract, and the losses must still be real
    assert all(np.isfinite(v) for v in base + got)


@gspmd
def test_three_way_composition_compiles_remat_free():
    """The verbatim MULTICHIP_r05 tail reproducer — transformer with a
    pipelined decoder under dp x pp x sp — whose SPMD partition used to
    log 'Involuntary full rematerialization' at the pipeline-region
    boundary. With the executor pinning the region output's dp/sp
    layout, the whole composition compiles remat-free. (Loss parity for
    this composition is tracked by test_sp_fluid under the shard_map
    shim caveat; this drill owns the remat contract. Slow tier.)"""
    from paddle_tpu.models import transformer as T
    rng = np.random.RandomState(61)
    vocab, seq, batch = 32, 16, 4
    feed_ids = {n: rng.randint(1, vocab, size=(batch, seq)).astype('int64')
                for n in ('src_word', 'trg_word', 'lbl_word')}
    with fresh_program() as (main, startup):
        avg_cost, _, _ = T.transformer(
            vocab, vocab, seq, n_layer=2, d_model=16, n_head=2,
            d_inner=32, dropout_rate=0.0, pp_decoder=True)
        fluid.optimizer.SGD(learning_rate=0.01).minimize(avg_cost)
        fluid.PipelineTranspiler(n_micro=2).transpile(main)
        fluid.SequenceParallelTranspiler(sp=2).transpile(main)
        fluid.DistributeTranspiler().transpile(trainer_id=0, trainers=2)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        loss = float(exe.run(main, feed=feed_ids,
                             fetch_list=[avg_cost])[0])
        assert np.isfinite(loss)
        assert exe.cache_stats['misses'] >= 1
        assert exe.remat_detected == 0
        assert exe.cache_stats['remat_detected'] == 0


@gspmd
def test_parallel_executor_deprecation_names_replacement():
    """The dp wrapper is a shim now: ONE DeprecationWarning naming the
    set_mesh/Executor.run replacement (docs/migration.md), once per
    process."""
    from paddle_tpu.fluid import parallel_executor as pe_mod
    pe_mod._warned[0] = False
    with fresh_program() as (main, startup):
        cost = _annotated_net()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        with pytest.warns(DeprecationWarning, match='set_mesh'):
            fluid.ParallelExecutor(use_cuda=False, loss_name=cost.name,
                                   main_program=main)
        import warnings as _w
        with _w.catch_warnings():
            _w.simplefilter('error', DeprecationWarning)
            fluid.ParallelExecutor(use_cuda=False, loss_name=cost.name,
                                   main_program=main)   # latched: silent


@gspmd
def test_annotate_tp_emits_program_annotations_and_matches():
    """The tp wrapper as an annotation emitter: parallel.annotate_tp
    stamps the Megatron layouts ONTO the Program, set_mesh declares the
    dp x tp mesh, and plain Executor.run lowers it — same losses as
    single-device, weight layouts as annotated (docs/parallel.md)."""
    import warnings as _w

    def net():
        x = fluid.layers.data(name='x', shape=[12], dtype='int64')
        y = fluid.layers.data(name='y', shape=[1], dtype='float32')
        emb = fluid.layers.embedding(x, size=[50, 16])
        h = fluid.layers.fc(input=emb, size=32, act='relu',
                            num_flatten_dims=2)
        pooled = fluid.layers.reduce_mean(h, dim=1)
        pred = fluid.layers.fc(input=pooled, size=2)
        cost = fluid.layers.mean(
            fluid.layers.square_error_cost(
                input=pred, label=fluid.layers.concat([y, y], axis=1)))
        fluid.optimizer.SGD(learning_rate=0.01).minimize(cost)
        return cost

    rng = np.random.RandomState(0)
    X = rng.randint(0, 50, size=(8, 12)).astype('int64')
    Y = rng.randn(8, 1).astype('float32')

    def run(tp):
        with fresh_program() as (main, startup):
            cost = net()
            if tp:
                annotated = parallel.annotate_tp(main, axis='tp')
                assert annotated['embedding_0.w_0'] == (None, 'tp')
                assert annotated['fc_0.w_0'] == ('tp', None)
                main.set_mesh({'dp': 4, 'tp': 2})
                from paddle_tpu.fluid import analysis
                assert analysis.analyze(main,
                                        fetches=[cost.name]) == []
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            losses = [float(np.asarray(
                exe.run(main, feed={'x': X, 'y': Y},
                        fetch_list=[cost])[0]).mean()) for _ in range(3)]
            from paddle_tpu.fluid.executor import global_scope
            w = global_scope().vars['embedding_0.w_0']
            return losses, getattr(w, 'sharding', None), exe

    single, _, _ = run(False)
    tp_l, emb_sh, exe = run(True)
    np.testing.assert_allclose(tp_l, single, rtol=2e-4)
    assert str(emb_sh.spec) == "PartitionSpec(None, 'tp')"
    assert exe.remat_detected == 0


@gspmd
def test_init_multihost_deprecation_names_init_distributed():
    """The env-compat multi-host entry is a shim now: one
    DeprecationWarning naming init_distributed (docs/migration.md)."""
    parallel._mh_warned[0] = False
    with pytest.warns(DeprecationWarning, match='init_distributed'):
        assert parallel.init_multihost() is False
    import warnings as _w
    with _w.catch_warnings():
        _w.simplefilter('error', DeprecationWarning)
        parallel.init_multihost()            # latched: silent
