"""Mesh / data-parallel tests on the 8-virtual-device CPU platform."""
import numpy as np

import jax
import paddle_tpu as paddle
import paddle_tpu.fluid as fluid
from paddle_tpu import parallel

from util import fresh_program


def test_eight_devices_available():
    assert len(jax.devices()) == 8


def test_parallel_executor_matches_single_device():
    """dp-sharded step must produce the same losses as single-device."""
    def build():
        x = fluid.layers.data(name='x', shape=[13], dtype='float32')
        y = fluid.layers.data(name='y', shape=[1], dtype='float32')
        pred = fluid.layers.fc(input=x, size=1,
                               param_attr=fluid.ParamAttr(
                                   initializer=fluid.initializer.Constant(0.05)))
        cost = fluid.layers.mean(
            fluid.layers.square_error_cost(input=pred, label=y))
        fluid.optimizer.SGD(learning_rate=0.05).minimize(cost)
        return cost

    rng = np.random.RandomState(0)
    xs = rng.rand(16, 13).astype('float32')
    ys = rng.rand(16, 1).astype('float32')

    with fresh_program() as (main, startup):
        cost = build()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        single = [float(exe.run(main, feed={'x': xs, 'y': ys},
                                fetch_list=[cost])[0]) for _ in range(4)]

    with fresh_program() as (main, startup):
        cost = build()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        pe = fluid.ParallelExecutor(use_cuda=False, loss_name=cost.name,
                                    main_program=main)
        par = [float(pe.run([cost.name], feed={'x': xs, 'y': ys})[0])
               for _ in range(4)]

    np.testing.assert_allclose(single, par, rtol=2e-4)


def test_parallel_executor_rejects_non_divisible_batch():
    """A batch not divisible by the mesh must raise, not silently pad
    (duplicated rows would double-weight examples in the loss)."""
    import pytest
    with fresh_program() as (main, startup):
        x = fluid.layers.data(name='x', shape=[13], dtype='float32')
        y = fluid.layers.data(name='y', shape=[1], dtype='float32')
        pred = fluid.layers.fc(input=x, size=1)
        cost = fluid.layers.mean(
            fluid.layers.square_error_cost(input=pred, label=y))
        fluid.optimizer.SGD(learning_rate=0.05).minimize(cost)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        pe = fluid.ParallelExecutor(use_cuda=False, loss_name=cost.name,
                                    main_program=main)
        xs = np.zeros((13, 13), 'float32')  # 13 % 8 != 0
        ys = np.zeros((13, 1), 'float32')
        with pytest.raises(ValueError, match='not divisible'):
            pe.run([cost.name], feed={'x': xs, 'y': ys})


def test_dryrun_multichip():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        '__graft_entry__', '__graft_entry__.py')
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod.dryrun_multichip(8)


def test_collectives_shard_map():
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P
    mesh = parallel.make_mesh({'dp': 8})
    x = np.arange(8 * 4, dtype=np.float32).reshape(8, 4)

    def f(x):
        return parallel.psum(x, 'dp')

    out = shard_map(f, mesh=mesh, in_specs=P('dp'), out_specs=P('dp'))(x)
    expect = np.broadcast_to(x.sum(0, keepdims=True), (8, 4)).reshape(8, 4)
    np.testing.assert_allclose(np.asarray(out)[0], x.sum(0))


def test_zero_sharded_optimizer_states():
    mesh = parallel.make_mesh({'dp': 8})
    vals = {'m': np.zeros((16, 4), np.float32), 's': np.zeros((3,), np.float32)}
    out = parallel.shard_optimizer_states(vals, mesh)
    assert out['m'].sharding.spec == parallel.P('dp', None)


class TestAutoTpRules:
    """parallel.auto_tp_rules: per-layer Megatron-style tp layouts derived
    from the Program graph (parallel/tp.py)."""

    @staticmethod
    def _layers():
        import paddle_tpu.fluid as fluid
        x = fluid.layers.data(name='x', shape=[12], dtype='int64')
        y = fluid.layers.data(name='y', shape=[1], dtype='float32')
        emb = fluid.layers.embedding(x, size=[50, 16])
        h = fluid.layers.fc(input=emb, size=32, act='relu',
                            num_flatten_dims=2)
        h2 = fluid.layers.fc(input=h, size=16, num_flatten_dims=2)
        pooled = fluid.layers.reduce_mean(h2, dim=1)
        pred = fluid.layers.fc(input=pooled, size=1)
        cost = fluid.layers.mean(
            fluid.layers.square_error_cost(input=pred, label=y))
        fluid.optimizer.SGD(learning_rate=0.01).minimize(cost)
        return cost

    def test_megatron_alternation(self):
        import re as _re
        from jax.sharding import PartitionSpec as P
        from paddle_tpu import parallel
        with fresh_program() as (main, startup):
            self._layers()
            rules = dict(parallel.auto_tp_rules(main, axis='tp'))
        # embedding: hidden-sharded; fc_0 consumes it -> row-parallel
        # (replicated bias); fc_1 takes the full output -> column-parallel
        # with tp-sharded bias. Patterns are exact-name anchored.
        by_name = {}
        for pat, spec in rules.items():
            for n in ('embedding_0.w_0', 'fc_0.w_0', 'fc_0.b_0',
                      'fc_1.w_0', 'fc_1.b_0'):
                if _re.search(pat, n):
                    by_name[n] = spec
        assert by_name['embedding_0.w_0'] == P(None, 'tp')
        assert by_name['fc_0.w_0'] == P('tp', None)
        assert 'fc_0.b_0' not in by_name
        assert by_name['fc_1.w_0'] == P(None, 'tp')
        assert by_name['fc_1.b_0'] == P('tp')
        # anchoring: a prefixed name must NOT match another param's rule
        assert not any(_re.search(p, 'pre_fc_0.w_0') for p in rules)

    def test_sharded_step_matches_single_device(self):
        import jax.numpy as jnp
        import paddle_tpu.fluid as fluid
        from paddle_tpu import parallel
        from paddle_tpu.fluid.executor import global_scope
        with fresh_program() as (main, startup):
            cost = self._layers()
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            scope = global_scope()
            snap = {k: np.asarray(v) for k, v in scope.vars.items()
                    if v is not None}
            rng = np.random.RandomState(0)
            X = rng.randint(0, 50, size=(8, 12)).astype('int64')
            Y = rng.randn(8, 1).astype('float32')
            single = [float(np.asarray(
                exe.run(main, feed={'x': X, 'y': Y}, fetch_list=[cost])[0]))
                for _ in range(3)]

            scope.vars.update({k: jnp.asarray(v) for k, v in snap.items()})
            mesh = parallel.make_mesh({'dp': 4, 'tp': 2})
            rules = parallel.auto_tp_rules(main, axis='tp')
            import warnings
            with warnings.catch_warnings():
                # the final [16,1] fc does not divide tp=2: replicated
                warnings.simplefilter('ignore')
                scope.vars.update(parallel.shard_params_by_rules(
                    dict(scope.vars), mesh, rules))
            feed = {'x': parallel.shard_batch(mesh, X),
                    'y': parallel.shard_batch(mesh, Y)}
            sharded = [float(np.asarray(
                exe.run(main, feed=feed, fetch_list=[cost])[0]).mean())
                for _ in range(3)]
            np.testing.assert_allclose(single, sharded, rtol=2e-4)


def test_fsdp_shard_params_matches_replicated():
    """parallel.fsdp_shard_params (ZeRO-3): params sharded over dp, GSPMD
    inserts gathers — identical training trajectory, params STAY sharded
    through the compiled step."""
    import jax.numpy as jnp
    from jax.sharding import NamedSharding
    import paddle_tpu.fluid as fluid
    from paddle_tpu import parallel
    from paddle_tpu.fluid.executor import global_scope
    from util import fresh_program

    rng = np.random.RandomState(0)
    X = rng.rand(16, 32).astype('float32')
    Y = rng.rand(16, 1).astype('float32')
    with fresh_program() as (main, startup):
        x = fluid.layers.data(name='x', shape=[32], dtype='float32')
        y = fluid.layers.data(name='y', shape=[1], dtype='float32')
        h = fluid.layers.fc(input=x, size=64, act='relu')
        pred = fluid.layers.fc(input=h, size=1)
        cost = fluid.layers.mean(
            fluid.layers.square_error_cost(input=pred, label=y))
        fluid.optimizer.Momentum(learning_rate=0.05,
                                 momentum=0.9).minimize(cost)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        scope = global_scope()
        snap = {k: np.asarray(v) for k, v in scope.vars.items()
                if v is not None}
        single = [float(np.asarray(
            exe.run(main, feed={'x': X, 'y': Y}, fetch_list=[cost])[0]))
            for _ in range(3)]

        scope.vars.update({k: jnp.asarray(v) for k, v in snap.items()})
        mesh = parallel.make_mesh({'dp': 8})
        scope.vars.update(parallel.fsdp_shard_params(
            dict(scope.vars), mesh, min_size=64))
        feed = {'x': parallel.shard_batch(mesh, X),
                'y': parallel.shard_batch(mesh, Y)}
        fsdp = [float(np.asarray(
            exe.run(main, feed=feed, fetch_list=[cost])[0]).mean())
            for _ in range(3)]
        np.testing.assert_allclose(single, fsdp, rtol=2e-4)

        # parameter is still dp-sharded after the jitted updates
        w = scope.vars['fc_0.w_0']
        assert isinstance(w.sharding, NamedSharding)
        assert 'dp' in str(w.sharding.spec)
        # small tensors (< min_size) stay replicated
        b = scope.vars['fc_1.b_0']
        assert str(getattr(b.sharding, 'spec', 'replicated')) \
            in ('PartitionSpec()', 'replicated')


def test_sharding_passes_compose():
    """fsdp_shard_params + shard_optimizer_states must not undo each
    other's placements (docs/distributed.md ZeRO-3 recipe)."""
    import jax.numpy as jnp
    mesh = parallel.make_mesh({'dp': 8})
    vals = {'w': jnp.zeros((30, 64)),      # dim0 not divisible: fsdp dim1
            'acc': jnp.zeros((64, 8))}
    a = parallel.fsdp_shard_params(vals, mesh, min_size=128)
    b = parallel.shard_optimizer_states(a, mesh)
    assert str(b['w'].sharding.spec) == "PartitionSpec(None, 'dp')"
    assert str(b['acc'].sharding.spec) == "PartitionSpec('dp',)"
    # reverse order: zero shards dim0, fsdp leaves it alone
    c = parallel.fsdp_shard_params(
        parallel.shard_optimizer_states(vals, mesh), mesh, min_size=128)
    assert str(c['w'].sharding.spec) == "PartitionSpec(None, 'dp')"
    assert str(c['acc'].sharding.spec) == "PartitionSpec('dp', None)"


def test_build_strategy_reduce_is_fsdp():
    """BuildStrategy.ReduceStrategy.Reduce (the reference's partitioned
    parameter updates) maps to ZeRO-3 parameter sharding: same losses as
    AllReduce, params dp-sharded."""
    from jax.sharding import NamedSharding

    def build():
        x = fluid.layers.data(name='x', shape=[32], dtype='float32')
        y = fluid.layers.data(name='y', shape=[1], dtype='float32')
        h = fluid.layers.fc(input=x, size=64, act='relu')
        pred = fluid.layers.fc(input=h, size=1)
        cost = fluid.layers.mean(
            fluid.layers.square_error_cost(input=pred, label=y))
        fluid.optimizer.SGD(learning_rate=0.05).minimize(cost)
        return cost

    rng = np.random.RandomState(0)
    X = rng.rand(16, 32).astype('float32')
    Y = rng.rand(16, 1).astype('float32')

    with fresh_program() as (main, startup):
        cost = build()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        pe = fluid.ParallelExecutor(use_cuda=False, loss_name=cost.name,
                                    main_program=main)
        allreduce = [float(np.asarray(pe.run([cost.name],
                                             feed={'x': X, 'y': Y})[0])
                           .mean()) for _ in range(3)]

    with fresh_program() as (main, startup):
        cost = build()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        bs = fluid.BuildStrategy()
        bs.reduce_strategy = fluid.BuildStrategy.ReduceStrategy.Reduce
        pe = fluid.ParallelExecutor(use_cuda=False, loss_name=cost.name,
                                    main_program=main, build_strategy=bs)
        reduced = [float(np.asarray(pe.run([cost.name],
                                           feed={'x': X, 'y': Y})[0])
                         .mean()) for _ in range(3)]
        from paddle_tpu.fluid.executor import global_scope
        w = global_scope().vars['fc_0.w_0']
        assert isinstance(w.sharding, NamedSharding)
        assert 'dp' in str(w.sharding.spec)
    np.testing.assert_allclose(allreduce, reduced, rtol=2e-4)
