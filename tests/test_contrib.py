"""fluid.contrib: memory_usage_calc + decoder library (parity: reference
contrib/memory_usage_calc.py and tests/test_beam_search_decoder.py)."""
import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import framework, layers
from paddle_tpu.fluid.contrib import memory_usage
from paddle_tpu.fluid.contrib.decoder.beam_search_decoder import (
    InitState, StateCell, TrainingDecoder, BeamSearchDecoder)
from paddle_tpu.fluid.executor import Scope, _switch_scope

DICT = 30
WORD_DIM = 8
HIDDEN = 8
BEAM = 2
MAX_LEN = 5


@pytest.fixture
def fresh():
    _switch_scope(Scope())
    main, startup = framework.Program(), framework.Program()
    with framework.program_guard(main, startup):
        yield main, startup


# ---------------------------------------------------------------------------
# memory_usage
# ---------------------------------------------------------------------------

def test_memory_usage_linear(fresh):
    main, startup = fresh
    x = layers.data(name='x', shape=[13], dtype='float32')
    y = layers.fc(input=x, size=1)
    lo, hi, unit = memory_usage(main, batch_size=10)
    assert lo > 0 and hi > lo and unit in ('B', 'KB', 'MB')


def test_memory_usage_scales_with_batch(fresh):
    main, startup = fresh
    x = layers.data(name='x', shape=[1024], dtype='float32')
    layers.fc(input=x, size=1024)

    def in_bytes(res):
        v, unit = res[1], res[2]
        return v * {'B': 1, 'KB': 1024, 'MB': 1024 ** 2}[unit]

    small = in_bytes(memory_usage(main, batch_size=1))
    big = in_bytes(memory_usage(main, batch_size=1024))
    # weights (1024x1024) are batch-invariant; activations scale ~3x here
    assert big > small * 2


def test_memory_usage_validates_args(fresh):
    main, _ = fresh
    with pytest.raises(TypeError):
        memory_usage("not a program", 1)
    with pytest.raises(ValueError):
        memory_usage(main, 0)


def test_memory_usage_within_2x_of_actual_resnet():
    """VERDICT item 10: estimate within 2x of actual for ResNet-50.
    'Actual' here = param+activation bytes implied by the program vars;
    the estimator must land within [0.5x, 2x] of the raw var sum."""
    _switch_scope(Scope())
    main, startup = framework.Program(), framework.Program()
    with framework.program_guard(main, startup):
        from paddle_tpu.models import resnet
        img = layers.data(name='img', shape=[3, 32, 32], dtype='float32')
        resnet.resnet_imagenet(img, class_dim=10, depth=50)
        raw = 0
        for var in main.global_block().vars.values():
            if var.shape is None:
                continue
            n = 1
            for d in var.shape:
                n *= 8 if d == -1 else d
            raw += n * 4
        lo, hi, unit = memory_usage(main, batch_size=8)
        est = {'B': 1, 'KB': 1024, 'MB': 1024 ** 2}[unit] * lo
        assert raw / 2 <= est <= raw * 2


# ---------------------------------------------------------------------------
# decoder library — reference tests/test_beam_search_decoder.py flow
# ---------------------------------------------------------------------------

def _encoder():
    src = layers.data(name='src_word', shape=[1], dtype='int64', lod_level=1)
    emb = layers.embedding(input=src, size=[DICT, WORD_DIM], dtype='float32')
    fc1 = layers.fc(input=emb, size=HIDDEN * 4, act='tanh')
    h, _ = layers.dynamic_lstm(input=fc1, size=HIDDEN * 4)
    return layers.sequence_last_step(input=h)


def _state_cell(context):
    h = InitState(init=context, need_reorder=True)
    cell = StateCell(inputs={'x': None}, states={'h': h}, out_state='h')

    @cell.state_updater
    def updater(cell):
        word = cell.get_input('x')
        prev_h = cell.get_state('h')
        cell.set_state('h', layers.fc(input=[prev_h, word], size=HIDDEN,
                                      act='tanh'))
    return cell


def test_training_decoder_converges(fresh):
    main, startup = fresh
    context = _encoder()
    cell = _state_cell(context)

    trg = layers.data(name='trg_word', shape=[1], dtype='int64', lod_level=1)
    trg_emb = layers.embedding(input=trg, size=[DICT, WORD_DIM],
                               dtype='float32')
    decoder = TrainingDecoder(cell)
    with decoder.block():
        word = decoder.step_input(trg_emb)
        decoder.state_cell.compute_state(inputs={'x': word})
        score = layers.fc(input=decoder.state_cell.get_state('h'),
                          size=DICT, act='softmax')
        decoder.state_cell.update_states()
        decoder.output(score)
    rnn_out = decoder()

    label = layers.data(name='next_word', shape=[1], dtype='int64',
                        lod_level=1)
    cost = layers.mean(layers.cross_entropy(input=rnn_out, label=label))
    fluid.optimizer.Adagrad(learning_rate=0.1).minimize(cost)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    feeder = fluid.DataFeeder(
        place=fluid.CPUPlace(),
        feed_list=[main.global_block().var('src_word'),
                   main.global_block().var('trg_word'),
                   main.global_block().var('next_word')])
    rng = np.random.RandomState(0)
    # tiny copy task: target = source sequence
    batch = []
    for _ in range(4):
        seq = rng.randint(2, DICT, size=(4, 1)).astype('int64')
        batch.append((seq, seq, seq))
    losses = []
    for _ in range(30):
        loss, = exe.run(main, feed=feeder.feed(batch), fetch_list=[cost])
        losses.append(float(np.asarray(loss).squeeze()))
    assert losses[-1] < losses[0] * 0.7, losses[::10]


def test_beam_search_decoder_decodes(fresh):
    main, startup = fresh
    context = _encoder()
    cell = _state_cell(context)

    init_ids = layers.data(name='init_ids', shape=[1], dtype='int64',
                           lod_level=2)
    init_scores = layers.data(name='init_scores', shape=[1], dtype='float32',
                              lod_level=2)
    decoder = BeamSearchDecoder(
        state_cell=cell, init_ids=init_ids, init_scores=init_scores,
        target_dict_dim=DICT, word_dim=WORD_DIM, input_var_dict={},
        topk_size=10, sparse_emb=False, max_len=MAX_LEN, beam_size=BEAM,
        end_id=1)
    decoder.decode()
    translation_ids, translation_scores = decoder()

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    B = 2
    rng = np.random.RandomState(1)
    feed = {
        'src_word': rng.randint(2, DICT, size=(B, 4, 1)).astype('int64'),
        'init_ids': np.zeros((B, 1), 'int64'),
        'init_scores': np.ones((B, 1), 'float32'),
    }
    ids, scores = exe.run(main, feed=feed,
                          fetch_list=[translation_ids, translation_scores])
    ids, scores = np.asarray(ids), np.asarray(scores)
    assert ids.shape == (B, BEAM, MAX_LEN)
    assert scores.shape == (B, BEAM)
    assert ((ids >= 0) & (ids < DICT)).all()
    # beams are sorted best-first by accumulated log-prob
    assert (scores[:, 0] >= scores[:, 1] - 1e-6).all()
    assert np.isfinite(scores).all()


def test_beam_search_decoder_respects_end_id(fresh):
    """With a vocab-2 model biased hard toward end_id, all beams should
    finish immediately and stay frozen at end_id."""
    main, startup = fresh
    context = _encoder()
    cell = _state_cell(context)
    init_ids = layers.data(name='init_ids', shape=[1], dtype='int64',
                           lod_level=2)
    init_scores = layers.data(name='init_scores', shape=[1], dtype='float32',
                              lod_level=2)
    decoder = BeamSearchDecoder(
        state_cell=cell, init_ids=init_ids, init_scores=init_scores,
        target_dict_dim=DICT, word_dim=WORD_DIM, input_var_dict={},
        topk_size=5, sparse_emb=False, max_len=MAX_LEN, beam_size=BEAM,
        end_id=1)
    decoder.decode()
    translation_ids, _ = decoder()

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    feed = {
        'src_word': np.full((1, 3, 1), 3, 'int64'),
        'init_ids': np.full((1, 1), 1, 'int64'),     # start == end_id
        'init_scores': np.ones((1, 1), 'float32'),
    }
    ids, = exe.run(main, feed=feed, fetch_list=[translation_ids])
    # a beam whose previous token is end_id must keep emitting end_id
    assert (np.asarray(ids) == 1).all()


def test_float16_transpiler_bf16_inference():
    """contrib.Float16Transpiler (reference paddle/contrib/float16/
    float16_transpiler.py): scope weights -> bf16, program dtypes patched,
    user keeps feeding/fetching float32."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid.executor import global_scope
    from util import fresh_program

    with fresh_program() as (main, startup):
        x = fluid.layers.data(name='x', shape=[8], dtype='float32')
        h = fluid.layers.fc(input=x, size=32, act='relu')
        pred = fluid.layers.fc(input=h, size=4, act='softmax')
        infer = main.clone(for_test=True)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        X = np.random.RandomState(0).randn(16, 8).astype('float32')
        ref, = exe.run(infer, feed={'x': X}, fetch_list=[pred.name])

        t = fluid.contrib.Float16Transpiler()
        converted = t.transpile(infer, fluid.CPUPlace())
        assert set(converted) == {'fc_0.w_0', 'fc_0.b_0',
                                  'fc_1.w_0', 'fc_1.b_0'}
        half, = exe.run(infer, feed={'x': X}, fetch_list=[pred.name])
        # fetch comes back float32 (reference appends fetch-side casts)
        assert half.dtype == np.float32
        np.testing.assert_allclose(ref, half, atol=0.02)
        # weights in the scope are genuinely half precision
        w = global_scope()._chain_get('fc_0.w_0')
        assert str(w.dtype) == 'bfloat16'
        # program var dtype patched like the reference's desc rewrite
        assert str(infer.global_block().vars['fc_0.w_0'].dtype) == 'bfloat16'

    import pytest
    with pytest.raises(TypeError):
        fluid.contrib.Float16Transpiler().transpile('not a program')


def test_float16_transpiled_program_survives_clone():
    import paddle_tpu.fluid as fluid
    from util import fresh_program
    with fresh_program() as (main, startup):
        x = fluid.layers.data(name='x', shape=[4], dtype='float32')
        pred = fluid.layers.fc(input=x, size=2)
        infer = main.clone(for_test=True)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        fluid.contrib.Float16Transpiler().transpile(infer)
        clone = infer.clone(for_test=True)
        out, = exe.run(clone,
                       feed={'x': np.ones((2, 4), 'float32')},
                       fetch_list=[pred.name])
        # the fetch-f32 contract and amp mode survive cloning
        assert out.dtype == np.float32
        assert getattr(clone, '_fetch_f32', False)
