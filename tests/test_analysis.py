"""Program verifier drills (docs/analysis.md): every pass, the executor's
PADDLE_TPU_VERIFY wiring, op provenance, strict inference, and the
zero-findings sweep over every book model."""
import json
import os

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import analysis, framework, layers, lowering
from paddle_tpu.fluid.analysis import donation
from paddle_tpu.fluid.analysis.findings import (
    DANGLING_INPUT, DEAD_OP, DONATION_UNSAFE, DTYPE_MISMATCH,
    EMBEDDING_UNTILEABLE, SCOPE_RACE, SHAPE_MISMATCH, SHARDING_INVALID,
    SHARDING_RESHARD, SHARDING_UNTILEABLE, UNREACHABLE_FETCH,
    USE_BEFORE_WRITE, WRITE_TO_FEED)

from util import fresh_program

pytestmark = pytest.mark.analysis


def _simple(depth=2):
    """x -> relu -> scale chain; returns the terminal var."""
    x = layers.data(name='x', shape=[8], dtype='float32')
    h = layers.relu(x)
    out = layers.scale(h, scale=2.0)
    return x, h, out


def _training():
    x = layers.data(name='x', shape=[8], dtype='float32')
    y = layers.data(name='y', shape=[1], dtype='float32')
    pred = layers.fc(input=x, size=1)
    cost = layers.mean(layers.square_error_cost(input=pred, label=y))
    fluid.optimizer.SGD(learning_rate=0.01).minimize(cost)
    return cost


def _kinds(findings):
    return [f.kind for f in findings]


# ---------------------------------------------------------------- dataflow

def test_clean_programs_have_zero_findings():
    with fresh_program() as (main, startup):
        _, _, out = _simple()
        assert analysis.analyze(main, startup=startup,
                                fetches=[out.name]) == []
        assert analysis.analyze(startup) == []


def test_training_program_clean_and_not_a_race_single_threaded():
    with fresh_program() as (main, startup):
        cost = _training()
        assert analysis.analyze(main, startup=startup,
                                fetches=[cost.name]) == []


def test_dangling_input_with_provenance():
    with fresh_program() as (main, _):
        _, _, out = _simple()
        blk = main.global_block()
        ghost = framework.Variable(blk, name='ghost', shape=[-1, 8],
                                   dtype='float32')
        blk.ops[1].inputs['X'] = [ghost]
        fs = analysis.analyze(main)
        assert _kinds(fs) == [DANGLING_INPUT]
        f = fs[0]
        assert f.severity == analysis.SEV_ERROR
        assert 'ghost' in f.var_names
        assert f.op_index == 1
        assert f.callsite and 'test_analysis.py' in f.callsite


def test_dropped_output_var_caught_downstream():
    with fresh_program() as (main, _):
        _, h, out = _simple()
        del main.global_block().ops[0].outputs['Out']
        fs = analysis.analyze(main)
        assert DANGLING_INPUT in _kinds(fs)
        assert any(h.name in f.var_names for f in fs)


def test_write_to_feed_flagged():
    with fresh_program() as (main, _):
        x, _, out = _simple()
        blk = main.global_block()
        # redirect the scale op's output onto the feed var
        blk.ops[1].outputs['Out'] = [x]
        fs = analysis.analyze(main)
        assert WRITE_TO_FEED in _kinds(fs)
        assert any(x.name in f.var_names for f in fs)
        # with an EXACT feed set that does not include x, the write is to
        # an ordinary intermediate — no finding (the executor passes the
        # real feed names, so an unfed data var must not false-positive)
        fs2 = analysis.analyze(main, feeds=['other'])
        assert WRITE_TO_FEED not in _kinds(fs2)


def test_unreachable_fetch_and_dead_op():
    with fresh_program() as (main, _):
        _, _, out = _simple()
        layers.sigmoid(out)   # unread, unfetched -> dead
        fs = analysis.analyze(main, fetches=['no_such_var'])
        kinds = _kinds(fs)
        assert UNREACHABLE_FETCH in kinds
        dead = [f for f in fs if f.kind == DEAD_OP]
        assert dead and all(f.severity == analysis.SEV_WARNING for f in dead)
        # with the real fetch only the sigmoid is dead
        fs2 = analysis.analyze(main, fetches=[out.name])
        assert _kinds(fs2) == [DEAD_OP]
        assert fs2[0].op_type == 'sigmoid'


def test_use_before_write_needs_startup_knowledge():
    with fresh_program() as (main, startup):
        x, _, out = _simple()
        blk = main.global_block()
        ctr = blk.create_var(name='ctr', shape=[1], dtype='float32',
                             persistable=True)
        layers.elementwise_add(out, ctr)
        # without the startup program the check cannot judge: quiet
        assert analysis.analyze(main) == []
        fs = analysis.analyze(main, startup=startup)
        assert _kinds(fs) == [USE_BEFORE_WRITE]
        assert 'ctr' in fs[0].var_names
        # a startup that initializes it silences the finding
        startup.global_block().create_var(name='ctr', shape=[1],
                                          dtype='float32', persistable=True)
        startup.global_block().append_op(
            type='fill_constant',
            outputs={'Out': [startup.global_block().var('ctr')]},
            attrs={'shape': [1], 'value': 0.0, 'dtype': 'float32'})
        assert analysis.analyze(main, startup=startup) == []


# ------------------------------------------------------------ shape/dtype

def test_dtype_corruption_caught_at_declaration():
    with fresh_program() as (main, _):
        _, _, out = _simple()
        main.global_block().var(out.name).dtype = 'int32'
        fs = analysis.analyze(main)
        assert _kinds(fs) == [DTYPE_MISMATCH]
        assert out.name in fs[0].var_names
        assert fs[0].callsite and 'test_analysis.py' in fs[0].callsite


def test_shape_corruption_caught():
    with fresh_program() as (main, _):
        _, _, out = _simple()
        main.global_block().var(out.name).shape = (4, 4)
        fs = analysis.analyze(main)
        assert _kinds(fs) == [SHAPE_MISMATCH]


def test_declared_int64_runs_as_int32_is_not_a_finding():
    with fresh_program() as (main, _):
        x = layers.data(name='ids', shape=[1], dtype='int64')
        layers.cast(x, 'int64')
        assert analysis.analyze(main) == []


def test_shape_pass_propagates_through_sub_blocks():
    with fresh_program() as (main, _):
        x = layers.data(name='x', shape=[8], dtype='float32')
        limit = layers.fill_constant(shape=[1], dtype='int32', value=3)
        i = layers.fill_constant(shape=[1], dtype='int32', value=0)
        acc = layers.fill_constant(shape=[1, 8], dtype='float32', value=0.0)
        cond = layers.less_than(i, limit)
        w = layers.While(cond=cond)
        with w.block():
            nxt = layers.elementwise_add(acc, acc)
            layers.assign(nxt, acc)
            layers.assign(layers.increment(i, in_place=False), i)
            layers.less_than(i, limit, cond=cond)
        # corrupt a declaration INSIDE the loop body
        sub = main.blocks[1]
        name = sub.ops[0].outputs['Out'][0].name
        sub.vars[name].dtype = 'int32'
        fs = analysis.analyze(main)
        assert DTYPE_MISMATCH in _kinds(fs)
        assert any(f.block == 1 for f in fs)


# --------------------------------------------------------- donation/races

def test_donation_unsafe_cross_check_pr3_class():
    """The PR-3 bug shape: a read-only inference step whose buffers the
    executor would donate. The analyzer recomputes the write-set and
    rejects the donation decision."""
    with fresh_program() as (main, _):
        _, _, out = _simple()
        fs = donation.run_pass(main, donates=True)
        assert _kinds(fs) == [DONATION_UNSAFE]
        # and the inverse: writes that would neither donate nor write back
        cost = None
    with fresh_program() as (main, _):
        _training()
        fs = donation.run_pass(main, donates=False)
        assert _kinds(fs) == [DONATION_UNSAFE]
        # the executor's real decision is consistent: no finding
        assert donation.run_pass(
            main, donates=analysis.executor_donates(main)) == []


def test_donation_subblock_only_write_flagged():
    """A persistable written ONLY inside a loop body (a stat var local to
    the sub-block, so it is not a While carry) never reaches the scope —
    the executor's donation scan reads top-level outputs only."""
    with fresh_program() as (main, _):
        x = layers.data(name='x', shape=[4], dtype='float32')
        limit = layers.fill_constant(shape=[1], dtype='int32', value=1)
        i = layers.fill_constant(shape=[1], dtype='int32', value=0)
        cond = layers.less_than(i, limit)
        w = layers.While(cond=cond)
        with w.block():
            sub = main.current_block()
            stat = sub.create_var(name='stat', shape=[-1, 4],
                                  dtype='float32', persistable=True)
            sub.append_op(type='assign', inputs={'X': [x]},
                          outputs={'Out': [stat]})
            layers.less_than(i, limit, cond=cond)
        fs = [f for f in donation.run_pass(main)
              if f.kind == DONATION_UNSAFE]
        assert fs and 'stat' in fs[0].var_names
        assert fs[0].op_type == 'assign' and fs[0].block == 1


def test_orphaned_sub_block_writes_do_not_count():
    """prune()/clone(for_test) drop ops but keep every Block, so a pruned
    inference artifact can carry a dead While body that wrote a
    persistable — an orphaned block never runs and must not trigger
    ScopeRace/DonationUnsafe (a valid read-only artifact would be
    rejected at Predictor load)."""
    with fresh_program() as (main, _):
        x = layers.data(name='x', shape=[4], dtype='float32')
        limit = layers.fill_constant(shape=[1], dtype='int32', value=1)
        i = layers.fill_constant(shape=[1], dtype='int32', value=0)
        cond = layers.less_than(i, limit)
        w = layers.While(cond=cond)
        with w.block():
            sub = main.current_block()
            stat = sub.create_var(name='stat', shape=[-1, 4],
                                  dtype='float32', persistable=True)
            sub.append_op(type='assign', inputs={'X': [x]},
                          outputs={'Out': [stat]})
            layers.less_than(i, limit, cond=cond)
        out = layers.relu(x)
        pruned = main.clone(for_test=True).prune([out.name])
        # the While op is gone but its body block remains, orphaned
        assert pruned.num_blocks > 1
        assert all(op.type != 'while'
                   for op in pruned.global_block().ops)
        assert donation.persistable_write_set(pruned) == set()
        assert analysis.analyze(pruned, concurrent=True) == []


def test_scope_race_only_when_concurrent():
    with fresh_program() as (main, startup):
        cost = _training()
        infer = main.clone(for_test=True)
        assert analysis.analyze(main) == []          # single-threaded: fine
        race = analysis.analyze(main, concurrent=True)
        assert SCOPE_RACE in _kinds(race)
        assert all(f.severity == analysis.SEV_ERROR
                   for f in race if f.kind == SCOPE_RACE)
        # the pruned inference clone is race-free
        assert analysis.analyze(infer, concurrent=True) == []


# --------------------------------------------------- verify surfaces/knob

def test_program_verify_levels():
    with fresh_program() as (main, _):
        _, _, out = _simple()
        del main.global_block().ops[0].outputs['Out']
        assert main.verify(level='off') == []
        with pytest.warns(UserWarning, match='DanglingInput'):
            fs = main.verify(level='warn')
        assert fs
        with pytest.raises(fluid.ProgramVerifyError) as ei:
            main.verify()
        assert any(f.kind == DANGLING_INPUT for f in ei.value.findings)
        with pytest.raises(ValueError):
            main.verify(level='loud')


def test_executor_verify_env_knob_and_once_per_key(monkeypatch):
    monkeypatch.setenv(analysis.ENV_VERIFY, 'error')
    analysis._seen.clear()
    from paddle_tpu import obs
    hist = obs.REGISTRY.histogram('analysis.verify.seconds')
    with fresh_program() as (main, startup):
        _, _, out = _simple()
        exe = fluid.Executor(fluid.CPUPlace())
        feed = {'x': np.ones((2, 8), 'float32')}
        before = hist.snapshot()['count']
        exe.run(main, feed=feed, fetch_list=[out])
        exe.run(main, feed=feed, fetch_list=[out])
        # ONE analysis.verify span for two runs of the same key
        assert hist.snapshot()['count'] == before + 1
        # break the program: the run dies as a typed verifier error with
        # provenance, not an XLA trace failure
        blk = main.global_block()
        ghost = framework.Variable(blk, name='ghost', shape=[-1, 8],
                                   dtype='float32')
        blk.ops[1].inputs['X'] = [ghost]
        main._bump_version()
        with pytest.raises(fluid.ProgramVerifyError) as ei:
            exe.run(main, feed=feed, fetch_list=[out])
        f = ei.value.findings[0]
        assert f.kind == DANGLING_INPUT and f.callsite


def test_executor_verify_rejects_on_every_retry(monkeypatch):
    """A rejected program stays rejected: the once-per-key memo records
    only PASSED verifications, so retrying the same broken step cannot
    slip past the verifier into the raw lowering failure."""
    monkeypatch.setenv(analysis.ENV_VERIFY, 'error')
    analysis._seen.clear()
    with fresh_program() as (main, _):
        _, _, out = _simple()
        blk = main.global_block()
        ghost = framework.Variable(blk, name='ghost', shape=[-1, 8],
                                   dtype='float32')
        blk.ops[1].inputs['X'] = [ghost]
        exe = fluid.Executor(fluid.CPUPlace())
        for _ in range(3):
            with pytest.raises(fluid.ProgramVerifyError):
                exe.run(main, feed={'x': np.ones((2, 8), 'float32')},
                        fetch_list=[out])


def test_analyze_survives_corrupt_sub_block_attrs():
    """program_lint feeds analyze() untrusted artifacts: cyclic or
    out-of-range sub_block indices must produce findings (or nothing),
    never a RecursionError/IndexError."""
    with fresh_program() as (main, _):
        _, _, out = _simple()
        op = main.global_block().ops[0]
        op.attrs['sub_block'] = 0          # claims its own block as body
        analysis.analyze(main, fetches=[out.name])
        op.attrs['sub_block'] = 99         # out of range
        analysis.analyze(main, fetches=[out.name])
        op.attrs['sub_blocks'] = [0, 99]   # both, plural form
        analysis.analyze(main, fetches=[out.name])
        op.attrs['sub_blocks'] = [None, 'x', 1.5]   # non-int corruption
        analysis.analyze(main, fetches=[out.name])


def test_provenance_survives_serialization_round_trip():
    """_from_dict must restore the serialized build site — never
    re-capture the deserializing frame, which would stamp every finding
    on a loaded artifact with the loader's file:line. Serialized form is
    basename:line (artifacts must not leak absolute build-machine
    paths)."""
    with fresh_program() as (main, _):
        _, _, out = _simple()
        orig = main.global_block().ops[0].callsite
        assert orig and 'test_analysis.py' in orig
        blob = main._to_dict()
        got = blob['blocks'][0]['ops'][0]['callsite']
        assert got == 'test_analysis.py:%s' % orig.rsplit(':', 1)[1]
        assert os.sep not in got
        clone = fluid.Program._from_dict(blob)
        assert clone.global_block().ops[0].callsite == got


def test_verify_mode_escalation_rejudges_seen_programs(monkeypatch):
    """The once-per-key memo is per (mode, key): flipping the knob from
    warn to error mid-process must re-judge an already-seen program."""
    monkeypatch.setenv(analysis.ENV_VERIFY, 'warn')
    analysis._seen.clear()
    with fresh_program() as (main, _):
        _, _, out = _simple()
        blk = main.global_block()
        ghost = framework.Variable(blk, name='ghost', shape=[-1, 8],
                                   dtype='float32')
        blk.ops[1].inputs['X'] = [ghost]
        exe = fluid.Executor(fluid.CPUPlace())
        feed = {'x': np.ones((2, 8), 'float32')}
        with pytest.warns(UserWarning, match='DanglingInput'):
            with pytest.raises(Exception):   # lowering still fails (warn)
                exe.run(main, feed=feed, fetch_list=[out])
        monkeypatch.setenv(analysis.ENV_VERIFY, 'error')
        with pytest.raises(fluid.ProgramVerifyError):
            exe.run(main, feed=feed, fetch_list=[out])


def test_executor_verify_off_by_default(monkeypatch):
    monkeypatch.delenv(analysis.ENV_VERIFY, raising=False)
    with fresh_program() as (main, _):
        _, _, out = _simple()
        blk = main.global_block()
        ghost = framework.Variable(blk, name='ghost', shape=[-1, 8],
                                   dtype='float32')
        blk.ops[1].inputs['X'] = [ghost]
        exe = fluid.Executor(fluid.CPUPlace())
        # without the knob the failure is the raw lowering KeyError
        with pytest.raises(Exception) as ei:
            exe.run(main, feed={'x': np.ones((2, 8), 'float32')},
                    fetch_list=[out])
        assert not isinstance(ei.value, fluid.ProgramVerifyError)


def test_run_bundle_carry_gap_is_a_verify_finding(monkeypatch):
    monkeypatch.setenv(analysis.ENV_VERIFY, 'error')
    analysis._seen.clear()
    with fresh_program() as (main, startup):
        cost = _training()
        exe = fluid.Executor(fluid.CPUPlace())
        feeds = [{'x': np.ones((2, 8), 'float32'),
                  'y': np.ones((2, 1), 'float32')} for _ in range(2)]
        # startup never ran: the scan carry has no persistable values
        with pytest.raises(fluid.ProgramVerifyError) as ei:
            exe.run_bundle(main, feeds=feeds, fetch_list=[cost], steps=2)
        assert USE_BEFORE_WRITE in _kinds(ei.value.findings)
        # initialized scope: verify is clean and the bundle runs
        exe.run(startup)
        out, = exe.run_bundle(main, feeds=feeds, fetch_list=[cost], steps=2)
        assert np.asarray(out).shape[0] == 2


def test_predictor_load_rejects_scope_race(tmp_path, monkeypatch):
    monkeypatch.setenv(analysis.ENV_VERIFY, 'error')
    analysis._seen.clear()
    from paddle_tpu.inference import Predictor
    with fresh_program() as (main, startup):
        cost = _training()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        # a GOOD artifact (pruned inference program) loads clean
        good = str(tmp_path / 'good')
        pred = main.global_block().ops[1].outputs['Out'][0]
        fluid.io.save_inference_model(good, ['x'], [pred], exe, main)
        Predictor(good)
        # a BAD artifact: the raw TRAINING program saved as if servable
        bad = str(tmp_path / 'bad')
        os.makedirs(bad, exist_ok=True)
        meta = {'program': main._to_dict(), 'feed_names': ['x', 'y'],
                'fetch_names': [cost.name]}
        with open(os.path.join(bad, '__model__.json'), 'w') as f:
            json.dump(meta, f)
        fluid.io.save_persistables(exe, bad, main)
        with pytest.raises(fluid.ProgramVerifyError) as ei:
            Predictor(bad)
        assert SCOPE_RACE in _kinds(ei.value.findings)


# ------------------------------------------------- provenance + strictness

def test_op_provenance_capture_and_flag(monkeypatch):
    with fresh_program() as (main, _):
        _, _, out = _simple()
        site = main.global_block().ops[0].callsite
        assert site and 'test_analysis.py' in site
    monkeypatch.setenv(framework.ENV_PROVENANCE, '0')
    with fresh_program() as (main, _):
        _, _, out = _simple()
        assert main.global_block().ops[0].callsite is None


def test_clone_preserves_provenance():
    with fresh_program() as (main, _):
        _, _, out = _simple()
        clone = main.clone(for_test=True)
        assert (clone.global_block().ops[0].callsite
                == main.global_block().ops[0].callsite)


def test_strict_infer_shape_raises_with_op_and_callsite():
    with fresh_program():
        a = layers.data(name='a', shape=[8], dtype='float32')
        b = layers.data(name='b', shape=[7], dtype='float32')
        with framework.strict_infer_shape():
            with pytest.raises(lowering.InferShapeError) as ei:
                layers.elementwise_add(a, b)
        msg = str(ei.value)
        assert 'elementwise_add' in msg
        assert 'test_analysis.py' in msg
        # outside the context the same build is best-effort again
        layers.elementwise_add(a, layers.relu(b))


def test_weight_norm_temps_get_inferred_shapes():
    with fresh_program() as (main, startup):
        x = layers.data(name='x', shape=[6], dtype='float32')
        layers.fc(input=x, size=4,
                  param_attr=fluid.WeightNormParamAttr(dim=1, name='wn_w'))
        for prog in (main, startup):
            wn = [v for v in prog.list_vars() if '.wn_' in v.name]
            assert wn, 'expected weight-norm temps in %r' % prog
            for v in wn:
                assert v.shape is not None, v.name
        assert analysis.analyze(main, startup=startup) == []


# ----------------------------------------------------------- model sweep

_SMALL = {
    'transformer': dict(batch_size=2, max_length=8, n_layer=1, d_model=32),
    'machine_translation': dict(batch_size=2, embedding_dim=16,
                                encoder_size=16),
    'stacked_dynamic_lstm': dict(batch_size=2, lstm_size=16, emb_dim=16),
    'se_resnext': dict(batch_size=2, class_dim=4),
    'resnet': dict(depth=8, batch_size=2),
    'vgg': dict(batch_size=2),
    'deepfm': dict(batch_size=4, embed_dim=4),
    'recommender_system': dict(batch_size=4, emb_dim=8, tower_dim=16),
}


def _model_names():
    from paddle_tpu import models
    return models.model_list


@pytest.mark.parametrize('name', _model_names())
def test_every_book_model_verifies_clean(name):
    """Acceptance: verify() reports zero findings on every book-example
    program (main AND startup), with full shape-pass coverage."""
    from paddle_tpu import models
    mod = models.get_model_module(name)
    with fresh_program() as (main, startup):
        mod.get_model(**_SMALL.get(name, {}))
        stats = {}
        fs = analysis.analyze(main, startup=startup, stats=stats)
        assert fs == [], '%s main program: %s' % (name, fs)
        assert analysis.analyze(startup) == [], '%s startup' % name
        assert stats['no_rule'] == 0, stats


# ------------------------------------------------------- sharding pass

class TestShardingPass:
    """fluid.analysis.sharding: GSPMD annotation consistency checked
    ahead of lowering, the same posture as donation safety
    (docs/parallel.md)."""

    @staticmethod
    def _annotated(spec=(None, 'model'), mesh={'dp': 2, 'model': 4}):
        x = layers.data(name='x', shape=[16], dtype='float32')
        h = layers.fc(input=x, size=32,
                      param_attr=fluid.ParamAttr(sharding=spec))
        prog = fluid.default_main_program()
        if mesh:
            prog.set_mesh(mesh)
        return h

    def test_clean_annotated_program_has_zero_findings(self):
        with fresh_program() as (main, _):
            out = self._annotated()
            assert analysis.analyze(main, fetches=[out.name]) == []

    def test_unknown_axis_is_error_with_annotation_provenance(self):
        with fresh_program() as (main, _):
            self._annotated(spec=(None, 'tp'))
            fs = [f for f in analysis.analyze(main)
                  if f.kind == SHARDING_INVALID]
            assert len(fs) == 1 and fs[0].severity == 'error'
            assert "'tp'" in fs[0].message
            # provenance: the layer call that declared the spec, not a
            # producer op (params have none in the main program)
            assert fs[0].callsite and 'test_analysis.py' in fs[0].callsite

    def test_axis_reuse_and_excess_entries_are_errors(self):
        with fresh_program() as (main, _):
            self._annotated(spec=('model', 'model'))
            assert [f.kind for f in analysis.analyze(main)] \
                == [SHARDING_INVALID]
        with fresh_program() as (main, _):
            self._annotated(spec=(None, 'model', None))   # 2-D var
            fs = analysis.analyze(main)
            assert [f.kind for f in fs] == [SHARDING_INVALID]
            assert '3 entries' in fs[0].message

    def test_untileable_dim_is_error(self):
        with fresh_program() as (main, _):
            # fc weight is [16, 32]; 'model' axis size 5 cannot tile 32
            self._annotated(spec=(None, 'model'),
                            mesh={'dp': 1, 'model': 5})
            fs = [f for f in analysis.analyze(main)
                  if f.kind == SHARDING_UNTILEABLE]
            assert len(fs) == 1
            assert 'not divisible' in fs[0].message

    def test_untileable_embedding_table_gets_specific_finding(self):
        """A row-sharded lookup_table weight whose VOCAB dim the axis
        cannot tile reports EmbeddingShardUntileable (not the generic
        untileable kind): the message names the lookup, the distributed
        flag, and the pad_vocab fix, with the annotating callsite as
        provenance (docs/embedding.md)."""
        with fresh_program() as (main, _):
            ids = layers.data(name='ids', shape=[1], dtype='int64')
            layers.embedding(
                ids, size=[50, 8], is_sparse=True, is_distributed=True,
                param_attr=fluid.ParamAttr(name='emb_w',
                                           sharding=('model', None)))
            main.set_mesh({'model': 8})
            fs = [f for f in analysis.analyze(main)
                  if f.kind == EMBEDDING_UNTILEABLE]
            assert len(fs) == 1 and fs[0].severity == 'error'
            assert 'emb_w' in fs[0].var_names
            assert 'pad_vocab' in fs[0].message
            assert 'is_distributed=True' in fs[0].message
            assert fs[0].callsite and 'test_analysis.py' in fs[0].callsite
            # the generic kind stays for non-table vars only
            assert not [f for f in analysis.analyze(main)
                        if f.kind == SHARDING_UNTILEABLE]

    def test_tileable_embedding_table_is_clean(self):
        with fresh_program() as (main, _):
            ids = layers.data(name='ids', shape=[1], dtype='int64')
            out = layers.embedding(
                ids, size=[48, 8], is_sparse=True, is_distributed=True,
                param_attr=fluid.ParamAttr(name='emb_w',
                                           sharding=('model', None)))
            main.set_mesh({'model': 8})
            assert [f for f in analysis.analyze(main,
                                                fetches=[out.name])
                    if f.kind in (EMBEDDING_UNTILEABLE,
                                  SHARDING_UNTILEABLE)] == []

    def test_embedding_untileable_via_mesh_override(self):
        """program_lint --mesh semantics: a table that tiles its OWN mesh
        can still fail a deployment mesh override (axis grown to 16)."""
        with fresh_program() as (main, _):
            ids = layers.data(name='ids', shape=[1], dtype='int64')
            layers.embedding(
                ids, size=[48, 8], is_sparse=True, is_distributed=True,
                param_attr=fluid.ParamAttr(name='emb_w',
                                           sharding=('model', None)))
            main.set_mesh({'model': 8})
            fs = analysis.analyze(main, mesh_axes=[('model', 32)])
            assert [f.kind for f in fs] == [EMBEDDING_UNTILEABLE]

    def test_annotation_without_mesh_is_inert_warning(self):
        with fresh_program() as (main, _):
            self._annotated(mesh=None)
            fs = [f for f in analysis.analyze(main)
                  if f.kind == SHARDING_INVALID]
            assert len(fs) == 1 and fs[0].severity == 'warning'
            assert 'declares no' in fs[0].message

    def test_mesh_axes_override_lints_deployment_mesh(self):
        """program_lint --mesh: the same annotated program is clean on
        its own mesh but fails against a deployment mesh without the
        'model' axis."""
        with fresh_program() as (main, _):
            out = self._annotated()
            assert analysis.analyze(main, fetches=[out.name]) == []
            fs = analysis.analyze(main, fetches=[out.name],
                                  mesh_axes=[('dp', 8)])
            assert [f.kind for f in fs] == [SHARDING_INVALID]

    def test_pipeline_stage_annotation_mismatch_is_reshard_warning(self):
        with fresh_program() as (main, _):
            x = layers.data(name='x', shape=[8], dtype='float32')
            a = layers.fc(input=x, size=8, bias_attr=False,
                          param_attr=fluid.ParamAttr(
                              name='stage0.w', sharding=('model',)))
            layers.fc(input=a, size=8, bias_attr=False,
                      param_attr=fluid.ParamAttr(name='stage1.w'))
            main.set_mesh({'model': 8})
            # the pipeline transpiler's stacked-parameter manifest
            main._pipeline_config = {
                'param_names': [['stage0.w'], ['stage1.w']]}
            fs = [f for f in analysis.analyze(main)
                  if f.kind == SHARDING_RESHARD]
            assert len(fs) == 1 and fs[0].severity == 'warning'
            assert 'stage-0 peer' in fs[0].message


def test_program_lint_mesh_flag_one_json_document(tmp_path):
    """tools/program_lint.py --mesh AXESxSIZES: lints a saved artifact's
    annotations against a deployment mesh; --json stays ONE parseable
    document carrying the mesh context."""
    import importlib.util
    import io as _io
    from contextlib import redirect_stdout

    with fresh_program() as (main, startup):
        x = layers.data(name='x', shape=[16], dtype='float32')
        pred = layers.fc(input=x, size=32,
                         param_attr=fluid.ParamAttr(sharding=(None, 'model')))
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        d = str(tmp_path / 'm')
        fluid.io.save_inference_model(d, ['x'], [pred], exe,
                                      main_program=main)

    spec = importlib.util.spec_from_file_location(
        'program_lint', os.path.join(os.path.dirname(__file__), '..',
                                     'tools', 'program_lint.py'))
    lint = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lint)

    def run(argv):
        buf = _io.StringIO()
        with redirect_stdout(buf):
            rc = lint.main(argv)
        return rc, buf.getvalue()

    # fits: dp x model mesh tiles the [16, 32] weight
    rc, out = run([d, '--mesh', 'dpx2,modelx4', '--json'])
    doc = json.loads(out)
    assert rc == 0
    assert doc['mesh'] == {'dp': 2, 'model': 4}
    assert doc['findings'] == []
    # deployment mesh without the axis: structured error finding
    rc, out = run([d, '--mesh', 'dpx8', '--json'])
    doc = json.loads(out)
    assert rc == 1
    assert [f['kind'] for f in doc['findings']] == [SHARDING_INVALID]
    # NAME=SIZE spelling accepted; malformed spec is usage error
    rc, _ = run([d, '--mesh', 'dp=2,model=4'])
    assert rc == 0
    rc, _ = run([d, '--mesh', 'dp-8'])
    assert rc == 2

    # embedding table artifact: the vocab-untileable deployment mesh
    # reports the embedding-specific kind through the CLI too
    # (docs/embedding.md)
    with fresh_program() as (main, startup):
        ids = layers.data(name='ids', shape=[1], dtype='int64')
        out_v = layers.embedding(
            ids, size=[48, 8], is_sparse=True, is_distributed=True,
            param_attr=fluid.ParamAttr(name='emb_w',
                                       sharding=('model', None)))
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        d2 = str(tmp_path / 'emb')
        fluid.io.save_inference_model(d2, ['ids'], [out_v], exe,
                                      main_program=main)
    rc, out = run([d2, '--mesh', 'modelx8', '--json'])
    assert rc == 0 and json.loads(out)['findings'] == []
    rc, out = run([d2, '--mesh', 'modelx32', '--json'])
    doc = json.loads(out)
    assert rc == 1
    assert [f['kind'] for f in doc['findings']] == [EMBEDDING_UNTILEABLE]
    assert 'pad_vocab' in doc['findings'][0]['message']


# ------------------------------------------------------ cost model (pass 6)
# The validation contract (docs/analysis.md#pass-6): static per-device
# residency agrees with XLA's own compiled_memory_stats() to within
# max(2 KiB, 5%) — argument bytes ARE persistables (shard-sized) + feeds.

def _feed_bytes(feed):
    """Feed bytes at EXECUTED width: x64 declarations narrow to 32-bit
    on device (the shapes-pass policy), so int64 ids upload as int32."""
    total = 0
    for a in feed.values():
        a = np.asarray(a)
        item = 4 if a.dtype.itemsize == 8 else a.dtype.itemsize
        total += a.size * item
    return total


def _residency_ab(main, feed, fetches, batch):
    """(estimated, measured) per-device residency for one program."""
    exe = fluid.Executor(fluid.CPUPlace())
    stats = exe.compiled_memory_stats(main, feed=feed, fetch_list=fetches)
    measured = stats.argument_size_in_bytes - _feed_bytes(feed)
    rep = analysis.cost_report(main, batch=batch, fetches=fetches)
    return rep.residency_per_device, measured


def _assert_tolerance(est, measured):
    assert abs(est - measured) <= max(2048, 0.05 * measured), \
        'estimate %d vs measured %d exceeds max(2KiB, 5%%)' % (est,
                                                               measured)


class TestCostModelResidencyAB:
    """cost_report residency vs Executor.compiled_memory_stats on real
    programs — the load-bearing-not-decorative acceptance drill."""

    def test_dense_fc_program(self):
        with fresh_program() as (main, startup):
            x = layers.data(name='x', shape=[16], dtype='float32')
            pred = layers.fc(input=x, size=32)
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            feed = {'x': np.zeros((4, 16), dtype='float32')}
            est, measured = _residency_ab(main, feed, [pred.name], 4)
            # W [16,32] + b [32] = 2176 bytes, exactly
            assert measured == 2176
            _assert_tolerance(est, measured)

    def test_sharded_embedding_program_counts_per_shard(self):
        with fresh_program() as (main, startup):
            ids = layers.data(name='ids', shape=[1], dtype='int64')
            emb = layers.embedding(
                input=ids, size=[64, 16], is_distributed=True,
                param_attr=fluid.ParamAttr(name='emb_w',
                                           sharding=('model', None)))
            pred = layers.fc(input=emb, size=8)
            main.set_mesh({'model': 8}, data_axis=False)
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            feed = {'ids': np.zeros((4, 1), dtype='int64')}
            est, measured = _residency_ab(main, feed, [pred.name], 4)
            # the [64,16] table counts PER SHARD (512B), not whole (4KiB)
            rep = analysis.cost_report(main, batch=4)
            assert rep.persistables['emb_w']['bytes_per_device'] == 512
            assert rep.tables['emb_w']['dist_axis'] == 'model'
            _assert_tolerance(est, measured)

    def test_offline_quantized_program_counts_int8_width(self):
        from paddle_tpu.fluid.passes import quant_pass
        with fresh_program() as (main, startup):
            x = layers.data(name='x', shape=[16], dtype='float32')
            pred = layers.fc(input=x, size=32, bias_attr=False,
                             param_attr=fluid.ParamAttr(name='qw'))
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            scope = fluid.executor.global_scope()
            assert quant_pass.quantize_weights(main, scope) == 1
            feed = {'x': np.zeros((4, 16), dtype='float32')}
            est, measured = _residency_ab(main, feed, [pred.name], 4)
            # int8 [16,32] = 512B + f32 per-channel scale [1,32] = 128B;
            # the f32 weight is DROPPED from both program and upload
            assert 'qw' not in {n for n in
                                analysis.cost_report(main).persistables}
            assert measured == 640
            _assert_tolerance(est, measured)

    def test_quant_marked_program_prices_quantized_width(self):
        """mark_quant (the fake-quant pass form): the cost model prices
        the weight at its DEPLOYMENT width — int8 + scale, not f32."""
        from paddle_tpu.fluid.passes import quant_pass
        with fresh_program() as (main, _):
            x = layers.data(name='x', shape=[16], dtype='float32')
            layers.fc(input=x, size=32, bias_attr=False,
                      param_attr=fluid.ParamAttr(name='qw'))
            plain = analysis.cost_report(main).residency_per_device
            quant_pass.mark_quant(main)
            marked = analysis.cost_report(main)
            assert marked.persistables['qw']['quant'] is True
            # 16*32 int8 + 32 f32 scales = 640 < 2048 f32
            assert marked.residency_per_device == 640 < plain == 2048


class TestCostModelFindings:

    def test_implicit_reshard_names_both_placements(self):
        with fresh_program() as (main, _):
            x = layers.data(name='x', shape=[8], dtype='float32')
            h = layers.relu(x)
            h.sharding = framework.normalize_sharding(('dp', None))
            y = layers.scale(h, scale=1.0)
            y.sharding = framework.normalize_sharding((None, 'dp'))
            main.set_mesh({'dp': 8})
            fs = [f for f in analysis.analyze(main, cost=True)
                  if f.kind == 'ImplicitReshard']
            assert len(fs) == 1 and fs[0].severity == 'warning'
            assert "('dp', None)" in fs[0].message
            assert "(None, 'dp')" in fs[0].message
            assert set(fs[0].var_names) == {h.name, y.name}
            # not armed -> the hotspot scan does not run
            assert not [f for f in analysis.analyze(main)
                        if f.kind == 'ImplicitReshard']

    def test_hbm_over_budget_is_error_finding(self):
        with fresh_program() as (main, _):
            x = layers.data(name='x', shape=[16], dtype='float32')
            layers.fc(input=x, size=32)
            fs = [f for f in analysis.analyze(main, hbm_budget=1024)
                  if f.kind == 'HbmOverBudget']
            assert len(fs) == 1 and fs[0].severity == 'error'
            assert not [f for f in analysis.analyze(main,
                                                    hbm_budget=1 << 20)
                        if f.kind == 'HbmOverBudget']

    def test_cost_report_collectives_and_span(self, tmp_path):
        from paddle_tpu import obs
        from paddle_tpu.obs import report as obs_report
        obs.enable(str(tmp_path / 'obs'))
        try:
            with fresh_program() as (main, _):
                ids = layers.data(name='ids', shape=[1], dtype='int64')
                emb = layers.embedding(
                    input=ids, size=[64, 16], is_distributed=True,
                    param_attr=fluid.ParamAttr(name='emb_w',
                                               sharding=('model', None)))
                layers.fc(input=emb, size=8)
                main.set_mesh({'model': 8}, data_axis=False)
                rep = analysis.cost_report(main, batch=4)
            # the all_to_all lookup wire: ids out + rows back
            assert [c['kind'] for c in rep.collectives] == \
                ['all_to_all', 'all_to_all']
            assert rep.comm_bytes_per_step == sum(
                c['bytes_per_device'] for c in rep.collectives) > 0
            events, errors = obs_report.load_events(obs.run_log_path())
            assert errors == []
            spans = [e for e in events if e.get('kind') == 'span'
                     and e['name'] == 'analysis.cost']
            assert spans and spans[0]['fields']['collectives'] == 2
            text = obs_report.summarize(events)
            assert '-- analysis --' in text and 'cost model:' in text
        finally:
            obs._reset()


# ----------------------------------------------- collective safety (pass 7)

def _dist_lookup_program(main):
    """The two-sharded-replica serving shape (test_pod_serving.py): a
    row-sharded is_distributed lookup + fc, feeds replicated."""
    ids = layers.data(name='ids', shape=[1], dtype='int64')
    emb = layers.embedding(
        input=ids, size=[64, 16], is_distributed=True,
        param_attr=fluid.ParamAttr(name='emb_w',
                                   sharding=('model', None)))
    pred = layers.fc(input=emb, size=8)
    main.set_mesh({'model': 8}, data_axis=False)
    return pred


class TestCollectiveSafety:

    def test_concurrent_collectives_points_at_pod_lock(self):
        with fresh_program() as (main, _):
            pred = _dist_lookup_program(main)
            fs = [f for f in analysis.analyze(main, feeds=['ids'],
                                              fetches=[pred.name],
                                              concurrent=True)
                  if f.kind == 'ConcurrentCollectives']
            assert len(fs) == 1
            # WARNING, not error: the pod lock DOES serialize, and
            # ShardedPredictor verifies with concurrent=True under
            # PADDLE_TPU_VERIFY=error — legitimate sharded replicas
            # must keep loading
            assert fs[0].severity == 'warning'
            assert '_MESH_DISPATCH_LOCK' in fs[0].message
            assert 'serving/pod.py' in fs[0].message
            assert 'emb_w' in fs[0].var_names
            analysis.report_findings(fs, mode='error')  # must not raise
            # not concurrent, or no mesh: no hazard
            assert not [f for f in analysis.analyze(
                main, feeds=['ids'], fetches=[pred.name])
                if f.kind == 'ConcurrentCollectives']

    def test_branch_only_collective_is_divergence_error(self):
        with fresh_program() as (main, _):
            blk = main.global_block()
            ids = layers.data(name='ids', shape=[1], dtype='int64')
            w = blk.create_var(name='div_w', shape=[64, 16],
                               dtype='float32', persistable=True)
            w.sharding = framework.normalize_sharding(('model', None))
            sub = main.create_block()
            emb = sub.create_var(name='div_emb', shape=[-1, 16],
                                 dtype='float32')
            sub.append_op(type='lookup_table',
                          inputs={'W': [w], 'Ids': [ids]},
                          outputs={'Out': [emb]},
                          attrs={'is_distributed': True,
                                 'dist_axis': 'model'},
                          infer_shape=False)
            main.rollback()
            out = blk.create_var(name='div_out', shape=[-1, 16],
                                 dtype='float32')
            blk.append_op(type='ifelse', inputs={},
                          outputs={'Out': [out]},
                          attrs={'sub_blocks': [sub.idx]},
                          infer_shape=False)
            main.set_mesh({'model': 8}, data_axis=False)
            fs = [f for f in analysis.analyze(main)
                  if f.kind == 'CollectiveDivergence']
            assert len(fs) == 1 and fs[0].severity == 'error'
            assert 'rendezvous' in fs[0].message
            assert fs[0].op_type == 'ifelse'

    def test_while_body_collective_is_divergence_warning(self):
        with fresh_program() as (main, _):
            blk = main.global_block()
            ids = layers.data(name='ids', shape=[1], dtype='int64')
            w = blk.create_var(name='loop_w', shape=[64, 16],
                               dtype='float32', persistable=True)
            w.sharding = framework.normalize_sharding(('model', None))
            sub = main.create_block()
            emb = sub.create_var(name='loop_emb', shape=[-1, 16],
                                 dtype='float32')
            sub.append_op(type='lookup_table',
                          inputs={'W': [w], 'Ids': [ids]},
                          outputs={'Out': [emb]},
                          attrs={'is_distributed': True,
                                 'dist_axis': 'model'},
                          infer_shape=False)
            main.rollback()
            blk.append_op(type='while', inputs={}, outputs={},
                          attrs={'sub_block': sub.idx},
                          infer_shape=False)
            main.set_mesh({'model': 8}, data_axis=False)
            fs = [f for f in analysis.analyze(main)
                  if f.kind == 'CollectiveDivergence']
            assert len(fs) == 1 and fs[0].severity == 'warning'
            assert 'trip count' in fs[0].message

    def test_no_mesh_means_no_collectives(self):
        with fresh_program() as (main, _):
            ids = layers.data(name='ids', shape=[1], dtype='int64')
            layers.embedding(
                input=ids, size=[64, 16], is_distributed=True,
                param_attr=fluid.ParamAttr(name='emb_w'))
            assert analysis.collective_sequence(main) == []
            assert not [f for f in analysis.analyze(main,
                                                    concurrent=True)
                        if f.kind == 'ConcurrentCollectives']


# --------------------------------------------- DimSharding (tiered tables)

class TestDimShardingStatic:

    def test_dim_sharded_tiered_table_is_static_error(self):
        with fresh_program() as (main, _):
            ids = layers.data(name='ids', shape=[1], dtype='int64')
            layers.embedding(
                input=ids, size=[64, 16],
                param_attr=fluid.ParamAttr(name='tt',
                                           sharding=(None, 'model')))
            tvar = main.global_block().vars['tt']
            tvar.tiered = True
            main.set_mesh({'model': 8})
            fs = [f for f in analysis.analyze(main)
                  if f.kind == 'DimSharding']
            assert len(fs) == 1 and fs[0].severity == 'error'
            assert 'ROADMAP item 3' in fs[0].message
            assert 'tt' in fs[0].var_names
            # the mark survives the artifact round-trip, so
            # program_lint --mesh catches it on a SAVED program too
            clone = fluid.Program._from_dict(main._to_dict())
            assert clone.global_block().vars['tt'].tiered is True
            assert [f.kind for f in analysis.analyze(
                clone, mesh_axes={'model': 8})
                if f.kind == 'DimSharding'] == ['DimSharding']
            # row sharding stays clean
            tvar.tiered = False
            tvar.sharding = framework.normalize_sharding(('model', None))
            tvar.tiered = True
            assert not [f for f in analysis.analyze(main)
                        if f.kind == 'DimSharding']

    def test_untiered_dim_sharded_table_not_flagged(self):
        with fresh_program() as (main, _):
            ids = layers.data(name='ids', shape=[1], dtype='int64')
            layers.embedding(
                input=ids, size=[64, 16],
                param_attr=fluid.ParamAttr(name='plain_t',
                                           sharding=(None, 'model')))
            main.set_mesh({'model': 8})
            assert not [f for f in analysis.analyze(main)
                        if f.kind == 'DimSharding']


# ------------------------------------------- fleet seam + CLI exit codes

def test_estimate_state_bytes_static_twin(tmp_path):
    """serving.estimate_state_bytes: the bin-packer's footprint of a
    model it never loaded (ROADMAP item 4) — program JSON only."""
    from paddle_tpu import serving
    with fresh_program() as (main, startup):
        ids = layers.data(name='ids', shape=[1], dtype='int64')
        emb = layers.embedding(
            input=ids, size=[64, 16], is_distributed=True,
            param_attr=fluid.ParamAttr(name='emb_w',
                                       sharding=('model', None)))
        pred = layers.fc(input=emb, size=8)
        main.set_mesh({'model': 8}, data_axis=False)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        d = str(tmp_path / 'm')
        fluid.io.save_inference_model(d, ['ids'], [pred], exe,
                                      main_program=main)
        est_prog = serving.estimate_state_bytes(main)
    # dir, __model__.json path, and Program all agree; weights untouched
    assert serving.estimate_state_bytes(d) == est_prog > 0
    assert serving.estimate_state_bytes(
        os.path.join(d, '__model__.json')) == est_prog
    # a deployment-mesh override re-prices: more shards, fewer bytes
    assert serving.estimate_state_bytes(d, mesh_axes={'model': 16}) \
        < est_prog


def test_program_lint_cost_budget_and_exit_rule(tmp_path):
    """program_lint --cost/--hbm-budget + the ONE exit-code rule:
    error-class problems (error findings, HbmOverBudget, ckpt/aot
    problems) exit 1 regardless of --strict; warnings need --strict."""
    import importlib.util
    import io as _io
    from contextlib import redirect_stdout

    with fresh_program() as (main, startup):
        ids = layers.data(name='ids', shape=[1], dtype='int64')
        emb = layers.embedding(
            input=ids, size=[64, 16], is_distributed=True,
            param_attr=fluid.ParamAttr(name='emb_w',
                                       sharding=('model', None)))
        pred = layers.fc(input=emb, size=8)
        dead = layers.scale(pred, scale=2.0)
        main.set_mesh({'model': 8}, data_axis=False)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        d = str(tmp_path / 'm')
        fluid.io.save_inference_model(d, ['ids'], [pred, dead], exe,
                                      main_program=main)

    spec = importlib.util.spec_from_file_location(
        'program_lint', os.path.join(os.path.dirname(__file__), '..',
                                     'tools', 'program_lint.py'))
    lint = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lint)

    def run(argv):
        buf = _io.StringIO()
        with redirect_stdout(buf):
            rc = lint.main(argv)
        return rc, buf.getvalue()

    # family 1 — analysis findings: a warning (DeadOp via a fetch
    # subset) passes without --strict, fails with it
    rc, out = run([d, '--fetch', pred.name, '--json'])
    doc = json.loads(out)
    assert rc == 0
    assert [f['kind'] for f in doc] == [DEAD_OP]
    rc, _ = run([d, '--fetch', pred.name, '--strict'])
    assert rc == 1

    # family 2 — cost: HbmOverBudget is ERROR-class, exits 1 with or
    # without --strict; the same artifact passes with the budget raised
    rc, out = run([d, '--cost', '--hbm-budget', '512', '--json'])
    doc = json.loads(out)
    assert rc == 1
    assert 'HbmOverBudget' in [f['kind'] for f in doc['findings']]
    assert doc['cost']['residency_per_device'] > 512
    assert doc['cost']['hbm_budget'] == 512
    rc, out = run([d, '--cost', '--hbm-budget', '1M', '--json'])
    doc = json.loads(out)
    assert rc == 0
    assert 'HbmOverBudget' not in [f['kind'] for f in doc['findings']]
    assert doc['cost']['hbm_budget'] == 1 << 20
    # the collectives the artifact implies ride the JSON doc
    assert [c['kind'] for c in doc['cost']['collectives']] == \
        ['all_to_all', 'all_to_all']
    # malformed budget is a usage error
    rc, _ = run([d, '--hbm-budget', '1.5X'])
    assert rc == 2

    # family 3 — AOT staleness: always error-class (exit 1, no --strict)
    # — drilled with a well-formed manifest recorded from a DIFFERENT
    # program (fingerprint mismatch is the staleness aot_check types)
    from paddle_tpu.fluid import step_artifact
    aot_dir = tmp_path / 'aot'
    aot_dir.mkdir()
    (aot_dir / step_artifact.AOT_MANIFEST).write_text(json.dumps({
        'format': step_artifact.AOT_FORMAT,
        'jax': __import__('jax').__version__,
        'platform': 'cpu',
        'signatures': [{'sig': 'stale', 'program': 'not-this-program',
                        'feeds': [], 'fetches': [], 'donates': []}],
    }))
    rc, out = run([str(aot_dir), '--json'])  # smoke: dir is not a model
    assert rc == 2
    rc, out = run([d, '--aot', str(aot_dir), '--json'])
    doc = json.loads(out)
    assert rc == 1
    assert doc['aot']['warm'] is False and doc['aot']['problems']
    # (family 3's checkpoint twin — --checkpoint problems exiting 1
    # without --strict — is drilled in test_elastic.py)
