"""MNIST conv net end-to-end (reference fluid/tests/book/test_recognize_digits.py)."""
import itertools

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid

from util import fresh_program


def _lenet(img, label):
    conv1 = fluid.nets.simple_img_conv_pool(input=img, filter_size=5,
                                            num_filters=8, pool_size=2,
                                            pool_stride=2, act="relu")
    conv1 = fluid.layers.batch_norm(conv1)
    conv2 = fluid.nets.simple_img_conv_pool(input=conv1, filter_size=5,
                                            num_filters=16, pool_size=2,
                                            pool_stride=2, act="relu")
    prediction = fluid.layers.fc(input=conv2, size=10, act='softmax')
    avg_cost = fluid.layers.mean(
        fluid.layers.cross_entropy(input=prediction, label=label))
    acc = fluid.layers.accuracy(input=prediction, label=label)
    return prediction, avg_cost, acc


def test_mnist_lenet_trains():
    with fresh_program() as (main, startup):
        img = fluid.layers.data(name='img', shape=[1, 28, 28], dtype='float32')
        label = fluid.layers.data(name='label', shape=[1], dtype='int64')
        _, avg_cost, acc = _lenet(img, label)
        fluid.optimizer.Adam(learning_rate=0.003).minimize(avg_cost)

        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        reader = paddle.batch(paddle.dataset.mnist.train(), batch_size=32)
        feeder = fluid.DataFeeder(place=fluid.CPUPlace(),
                                  feed_list=[img, label])
        acc_v = 0.0
        for epoch in range(2):
            for batch in itertools.islice(reader(), 30):
                rows = [(b[0].reshape(1, 28, 28), b[1]) for b in batch]
                loss_v, acc_v = exe.run(main, feed=feeder.feed(rows),
                                        fetch_list=[avg_cost, acc])
        assert float(acc_v) > 0.8, float(acc_v)


def test_mnist_mlp_momentum():
    with fresh_program() as (main, startup):
        img = fluid.layers.data(name='img', shape=[784], dtype='float32')
        label = fluid.layers.data(name='label', shape=[1], dtype='int64')
        h = fluid.layers.fc(input=img, size=64, act='relu')
        pred = fluid.layers.fc(input=h, size=10, act='softmax')
        avg_cost = fluid.layers.mean(
            fluid.layers.cross_entropy(input=pred, label=label))
        fluid.optimizer.Momentum(learning_rate=0.05, momentum=0.9) \
            .minimize(avg_cost)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        reader = paddle.batch(paddle.dataset.mnist.train(), batch_size=64)
        feeder = fluid.DataFeeder(place=fluid.CPUPlace(),
                                  feed_list=[img, label])
        losses = []
        for batch in itertools.islice(reader(), 60):
            loss_v, = exe.run(main, feed=feeder.feed(batch),
                              fetch_list=[avg_cost])
            losses.append(float(loss_v))
        assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])
