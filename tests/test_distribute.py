"""DistributeTranspiler -> executable mesh training.

Parity: reference transpiler/distribute_transpiler.py:167-300 (program
split across trainers/pservers). Here transpile() annotates the program and
the Executor consumes it: dp mesh, params replicated (or dp-sharded ZeRO-3
when shard_parameters is set), ZeRO-sharded optimizer accumulators — all
enforced inside the compiled step.
"""
import numpy as np
import pytest

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu.fluid as fluid
from paddle_tpu import parallel

from util import fresh_program


def _build(lr=0.05, optimizer='momentum'):
    x = fluid.layers.data(name='x', shape=[16], dtype='float32')
    y = fluid.layers.data(name='y', shape=[1], dtype='float32')
    pred = fluid.layers.fc(input=x, size=1,
                           param_attr=fluid.ParamAttr(
                               initializer=fluid.initializer.Constant(0.02)))
    cost = fluid.layers.mean(
        fluid.layers.square_error_cost(input=pred, label=y))
    if optimizer == 'momentum':
        fluid.optimizer.Momentum(learning_rate=lr, momentum=0.9).minimize(cost)
    else:
        fluid.optimizer.Adam(learning_rate=lr).minimize(cost)
    return cost


def _data(n=16):
    rng = np.random.RandomState(3)
    return (rng.rand(n, 16).astype('float32'),
            rng.rand(n, 1).astype('float32'))


def test_transpiled_training_matches_single_device():
    xs, ys = _data()
    with fresh_program() as (main, startup):
        cost = _build()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        single = [float(exe.run(main, feed={'x': xs, 'y': ys},
                                fetch_list=[cost])[0]) for _ in range(5)]

    with fresh_program() as (main, startup):
        cost = _build()
        t = fluid.DistributeTranspiler()
        t.transpile(trainer_id=0, trainers=8)
        train_prog = t.get_trainer_program()
        assert train_prog is main
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        dist = [float(exe.run(train_prog, feed={'x': xs, 'y': ys},
                              fetch_list=[cost])[0]) for _ in range(5)]
    np.testing.assert_allclose(single, dist, rtol=2e-4)


def test_zero_sharded_accumulators_stay_sharded_in_step():
    """slice_var_up=True: momentum/adam accumulators live dp-sharded and the
    compiled step keeps them sharded (ZeRO), while params stay replicated."""
    xs, ys = _data()
    with fresh_program() as (main, startup):
        cost = _build(optimizer='momentum')
        t = fluid.DistributeTranspiler()
        t.transpile(trainer_id=0, trainers=8, slice_var_up=True)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        from paddle_tpu.fluid.executor import global_scope
        scope = global_scope()
        for _ in range(3):
            exe.run(main, feed={'x': xs, 'y': ys}, fetch_list=[cost])
        acc_names = [v.name for v in main.list_vars()
                     if getattr(v, '_is_optimizer_accumulator', False)]
        assert acc_names, "momentum must create velocity accumulators"
        sharded = 0
        for n in acc_names:
            arr = scope.vars[n]
            assert isinstance(arr.sharding, NamedSharding), n
            if arr.sharding.spec and arr.sharding.spec[0] == 'dp':
                sharded += 1
                # each device holds 1/8 of the accumulator
                shard_rows = {s.data.shape[0] for s in arr.addressable_shards}
                assert shard_rows == {arr.shape[0] // 8}, n
        assert sharded >= 1, "fc weight velocity [16,1] must shard over dp"
        # parameters stay replicated
        w = [n for n in scope.vars if n.endswith('.w_0')][0]
        assert scope.vars[w].sharding.spec == P()


def test_zero_matches_unsharded_numerics():
    xs, ys = _data()

    def run_with(slice_var_up):
        with fresh_program() as (main, startup):
            cost = _build(optimizer='adam')
            t = fluid.DistributeTranspiler()
            t.transpile(trainer_id=0, trainers=8, slice_var_up=slice_var_up)
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            return [float(exe.run(main, feed={'x': xs, 'y': ys},
                                  fetch_list=[cost])[0]) for _ in range(5)]

    np.testing.assert_allclose(run_with(False), run_with(True), rtol=2e-4)


def test_non_divisible_distributed_feed_raises():
    with fresh_program() as (main, startup):
        cost = _build()
        fluid.DistributeTranspiler().transpile(trainer_id=0, trainers=8)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        xs, ys = _data(n=13)
        with pytest.raises(ValueError, match='not divisible'):
            exe.run(main, feed={'x': xs, 'y': ys}, fetch_list=[cost])


def test_pserver_compat_shims():
    """On TPU each 'pserver' endpoint is a mesh participant owning a ZeRO
    shard: get_pserver_program returns the SAME annotated program with the
    endpoint's shard coordinate recorded."""
    with fresh_program() as (main, startup):
        _build()
        t = fluid.DistributeTranspiler()
        t.transpile(trainer_id=0, trainers=4,
                    pservers='10.0.0.1:6174,10.0.0.2:6174')
        ps = t.get_pserver_program('10.0.0.2:6174')
        assert isinstance(ps, fluid.Program)
        # same ops as the trainer program; shard ownership annotated
        assert len(ps.global_block().ops) == len(main.global_block().ops)
        assert ps._dist_config['shard_owner'] == 1
        assert ps._dist_config['n_shard_owners'] == 2
        assert ps._dist_config['dp_size'] == 4
        with pytest.raises(ValueError, match='unknown pserver endpoint'):
            t.get_pserver_program('not-an-endpoint')
        sp = t.get_startup_program('10.0.0.2:6174')
        assert isinstance(sp, fluid.Program)


def test_init_multihost_noop_without_cluster_env(monkeypatch):
    for k in ('PADDLE_TRAINER_ENDPOINTS', 'PADDLE_TRAINERS',
              'PADDLE_TRAINER_ID'):
        monkeypatch.delenv(k, raising=False)
    assert parallel.init_multihost() is False


def test_transpile_shard_parameters_fsdp():
    """DistributeTranspilerConfig.shard_parameters=True: params shard over
    dp inside the executor's dist placement (ZeRO-3), same losses."""
    from paddle_tpu.fluid.executor import global_scope

    rng = np.random.RandomState(0)
    X = rng.rand(16, 32).astype('float32')
    Y = rng.rand(16, 1).astype('float32')

    def build():
        x = fluid.layers.data(name='x', shape=[32], dtype='float32')
        y = fluid.layers.data(name='y', shape=[1], dtype='float32')
        h = fluid.layers.fc(input=x, size=64, act='relu')
        pred = fluid.layers.fc(input=h, size=1)
        cost = fluid.layers.mean(
            fluid.layers.square_error_cost(input=pred, label=y))
        fluid.optimizer.Momentum(learning_rate=0.05,
                                 momentum=0.9).minimize(cost)
        return cost

    with fresh_program() as (main, startup):
        cost = build()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        single = [float(np.asarray(
            exe.run(main, feed={'x': X, 'y': Y}, fetch_list=[cost])[0]))
            for _ in range(3)]

    with fresh_program() as (main, startup):
        cost = build()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        cfg = fluid.DistributeTranspilerConfig()
        cfg.shard_parameters = True
        t = fluid.DistributeTranspiler(config=cfg)
        t.transpile(trainer_id=0, program=main, trainers=8,
                    startup_program=startup)
        sharded = [float(np.asarray(
            exe.run(main, feed={'x': X, 'y': Y}, fetch_list=[cost])[0]))
            for _ in range(3)]
        w = global_scope().vars['fc_0.w_0']
        assert isinstance(w.sharding, NamedSharding)
        assert 'dp' in str(w.sharding.spec)
    np.testing.assert_allclose(single, sharded, rtol=2e-4)


def test_shard_parameters_implies_sharded_optimizer_state():
    """ZeRO-3 subsumes ZeRO-1: shard_parameters=True shards accumulators
    even with slice_var_up=False (replicated Adam state would cost 2x the
    memory the user just sharded away)."""
    from jax.sharding import NamedSharding
    from paddle_tpu.fluid.executor import global_scope
    with fresh_program() as (main, startup):
        x = fluid.layers.data(name='x', shape=[32], dtype='float32')
        pred = fluid.layers.fc(input=x, size=64)
        cost = fluid.layers.mean(pred)
        fluid.optimizer.Adam(learning_rate=1e-3).minimize(cost)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        cfg = fluid.DistributeTranspilerConfig()
        cfg.shard_parameters = True
        cfg.slice_var_up = False
        fluid.DistributeTranspiler(config=cfg).transpile(
            trainer_id=0, program=main, trainers=8,
            startup_program=startup, slice_var_up=False)
        X = np.random.rand(8, 32).astype('float32')
        exe.run(main, feed={'x': X}, fetch_list=[cost])
        moments = [n for n in global_scope().vars
                   if 'moment' in n and 'fc_0.w_0' in n]
        assert moments, list(global_scope().vars)[:20]
        for n in moments:
            v = global_scope().vars[n]
            assert isinstance(v.sharding, NamedSharding) and \
                'dp' in str(v.sharding.spec), (n, v.sharding)


# ---------------------------------------------------------------------------
# async story: sync_mode=False (reference distribute_transpiler.py:185-206)


def test_sync_mode_false_warns_program_path_stays_synchronous():
    xs, ys = _data()
    with fresh_program() as (main, startup):
        cost = _build()
        t = fluid.DistributeTranspiler()
        t.transpile(trainer_id=0, trainers=8, sync_mode=False)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        with pytest.warns(UserWarning, match='LocalSGD'):
            exe.run(main, feed={'x': xs, 'y': ys}, fetch_list=[cost])
        # warn once, not per step
        import warnings as w
        with w.catch_warnings():
            w.simplefilter('error')
            exe.run(main, feed={'x': xs, 'y': ys}, fetch_list=[cost])


def test_local_sgd_matches_numpy_simulation():
    """parallel.LocalSGD: replicas diverge over local steps, one pmean
    mixes them — checked leaf-for-leaf against a numpy re-implementation."""
    n, bl, d, lr = 4, 4, 6, 0.1
    mesh = parallel.make_mesh({'dp': n})
    rng = np.random.RandomState(0)
    w0 = rng.rand(d).astype('float32')
    xs = rng.rand(3, n * bl, d).astype('float32')   # 3 steps of global batch
    ys = rng.rand(3, n * bl).astype('float32')

    def step_fn(params, batch):
        x, y = batch

        def loss(w):
            import jax.numpy as jnp
            return jnp.mean((x @ w - y) ** 2)

        g = jax.grad(loss)(params['w'])
        return {'w': params['w'] - lr * g}, loss(params['w'])

    ls = parallel.LocalSGD(step_fn, mesh, axis='dp', sync_steps=3)
    params = ls.replicate({'w': w0})
    for i in range(3):
        batch = ls.shard_batch((xs[i], ys[i]))
        params, aux = ls.step(params, batch)
        assert np.asarray(aux).shape == (n,)   # one local loss per replica
    # replicas have genuinely diverged before the sync
    pre = np.asarray(params['w'])
    assert pre.shape == (n, d)
    assert np.abs(pre - pre[0]).max() > 1e-6
    params = ls.sync(params)
    got = np.asarray(params['w'])[0]

    # numpy replica-by-replica simulation
    sim = np.tile(w0, (n, 1))
    for i in range(3):
        for r in range(n):
            x = xs[i, r * bl:(r + 1) * bl]
            y = ys[i, r * bl:(r + 1) * bl]
            g = 2.0 / bl * x.T @ (x @ sim[r] - y)
            sim[r] = sim[r] - lr * g
    np.testing.assert_allclose(got, sim.mean(axis=0), rtol=2e-5)
    np.testing.assert_allclose(np.asarray(params['w'])[1], sim.mean(axis=0),
                               rtol=2e-5)

    # sync_steps=1 (sync every step) == synchronous dp == full-batch SGD
    ls1 = parallel.LocalSGD(step_fn, mesh, axis='dp', sync_steps=1)
    p1 = ls1.replicate({'w': w0})
    for i in range(3):
        p1, _ = ls1.step(p1, ls1.shard_batch((xs[i], ys[i])))
        p1 = ls1.sync(p1)
    ref = w0.copy()
    for i in range(3):
        per = []
        for r in range(n):
            x = xs[i, r * bl:(r + 1) * bl]
            y = ys[i, r * bl:(r + 1) * bl]
            per.append(2.0 / bl * x.T @ (x @ ref - y))
        ref = ref - lr * np.mean(per, axis=0)
    np.testing.assert_allclose(np.asarray(p1['w'])[0], ref, rtol=2e-5)


def test_local_sgd_scalar_batch_leaf_replicates():
    """A 0-d batch leaf (scalar temperature/step) has no leading dim to
    split — it must replicate instead of producing an invalid spec."""
    n, bl, d = 4, 2, 3
    mesh = parallel.make_mesh({'dp': n})
    rng = np.random.RandomState(1)

    def step_fn(params, batch):
        x, temp = batch['x'], batch['temp']
        return {'w': params['w'] + temp * x.sum()}, temp

    ls = parallel.LocalSGD(step_fn, mesh, axis='dp', sync_steps=2)
    params = ls.replicate({'w': np.zeros(d, 'float32')})
    batch = ls.shard_batch({
        'x': rng.rand(n * bl, d).astype('float32'),
        'temp': np.float32(0.5),
    })
    params, aux = ls.step(params, batch)
    assert np.allclose(np.asarray(aux), 0.5)   # every replica saw it
    assert np.isfinite(np.asarray(params['w'])).all()
