"""Migration proof: the REFERENCE's own book test files run VERBATIM
against paddle_tpu through nothing but a sys.modules import alias.

The reference files are executed from /root/reference (read-only, never
copied into this repo); `import paddle` / `import paddle.fluid` inside
them resolve to paddle_tpu. This is the strongest form of the parity
claim — a reference user's training script works unchanged on TPU
(reference python/paddle/fluid/tests/book/*.py).

Each case runs in a subprocess: the alias must not leak into other tests,
and the scripts write model dirs into their cwd (a tmp dir here).

All FIFTEEN reference book files run verbatim, including
test_machine_translation.py's decode_main — the While-loop LoD beam
search whose per-iteration beam REGROUPING (dynamic per-step LoD) runs
here at fixed capacity: the While capacity-widening pass
(ops_impl/block_ops.py) + the capacity-form LoD beam ops
(ops_impl/lod_beam.py, A/B-tested against a numpy transcription of the
reference algorithm in tests/test_lod_beam.py). The dense fixed-trip
beam (layers.beam_search with explicit parents) remains the TPU-first
path for new code (examples/machine_translation.py).
"""
import os
import subprocess
import sys

import pytest

_REF_BOOK = '/root/reference/python/paddle/fluid/tests/book'

_RUNNER = r"""
import sys, types, os, json
import jax
jax.config.update('jax_platforms', 'cpu')

import paddle_tpu
# the alias: EVERY `paddle.*` import in the reference file — including
# deep ones like `from paddle.fluid.executor import Executor` — must
# resolve to the SAME module objects (a second copy loaded through the
# package __path__ breaks isinstance across the boundary)
paddle_tpu.install_as_paddle()

path, funcname, kwargs = sys.argv[1], sys.argv[2], json.loads(sys.argv[3])
import importlib.util
spec = importlib.util.spec_from_file_location('ref_book_case', path)
mod = importlib.util.module_from_spec(spec)
spec.loader.exec_module(mod)

fluid = paddle_tpu.fluid
with fluid.scope_guard(fluid.core.Scope()):
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        getattr(mod, funcname)(**kwargs)
print('REF-BOOK-COMPAT OK:', os.path.basename(path))
"""


# These tests execute the reference's OWN book files, which live in a
# read-only checkout OUTSIDE this repo. A container without that checkout
# cannot run them at all — that is an environment gap, not a parity
# regression, so the suite reads skipped-with-reason instead of failed
# (triage note, PR 6: all three "failures" at the seed were exactly this).
pytestmark = pytest.mark.skipif(
    not os.path.isdir(_REF_BOOK),
    reason='reference checkout not present at %s (the verbatim-book '
           'parity tier needs the read-only reference tree mounted)'
           % _REF_BOOK)


def _run_case(tmp_path, fname, kwargs=None, funcname='main', timeout=900):
    import json
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, '-c', _RUNNER, os.path.join(_REF_BOOK, fname),
         funcname, json.dumps(kwargs or {'use_cuda': False})],
        cwd=str(tmp_path), capture_output=True, text=True, timeout=timeout,
        env=dict(os.environ, PYTHONPATH=here, JAX_PLATFORMS='cpu'))
    assert r.returncode == 0, (r.stdout[-2000:], r.stderr[-2000:])
    assert 'REF-BOOK-COMPAT OK' in r.stdout


def test_reference_fit_a_line_runs_verbatim(tmp_path):
    """Linear regression: trains to loss<10, saves an inference model,
    reloads it and infers — all through the reference's own code."""
    _run_case(tmp_path, 'test_fit_a_line.py')


def test_reference_recognize_digits_mlp_runs_verbatim(tmp_path):
    """MNIST MLP from the reference book, verbatim: train to the
    reference's own acceptance threshold, save/load inference model,
    infer."""
    _run_case(tmp_path, 'test_recognize_digits.py',
              kwargs={'use_cuda': False, 'parallel': False,
                      'nn_type': 'mlp', 'combine': False},
              timeout=1200)


def test_reference_word2vec_runs_verbatim(tmp_path):
    """Skip-gram-style N-gram LM from the reference book (embedding
    lookups, concat, shared ParamAttrs, LoD feeds via
    create_lod_tensor in its infer()) — verbatim to cost < 5.0."""
    _run_case(tmp_path, 'test_word2vec.py',
              kwargs={'use_cuda': False, 'is_sparse': False,
                      'is_parallel': False},
              timeout=1200)


def test_reference_machine_translation_train_runs_verbatim(tmp_path):
    """Seq2seq attention trainer (DynamicRNN-style decoder over LoD
    feeds) from the reference book, verbatim — 4 batches, finite loss."""
    _run_case(tmp_path, 'test_machine_translation.py',
              funcname='train_main',
              kwargs={'use_cuda': False, 'is_sparse': False},
              timeout=1200)


def test_reference_machine_translation_decode_runs_verbatim(tmp_path):
    """The book's While-loop LoD beam-search decoder (decode_main:
    array_write/read + sequence_expand + lod_reset + beam_search +
    beam_search_decode over 2-level LoD), verbatim — the last of the 15
    reference book files. Runs at fixed capacity via the While
    capacity-widening pass and the lod_beam capacity-form ops; the step
    algorithm itself is A/B-tested against a numpy transcription of
    beam_search_op.cc in tests/test_lod_beam.py."""
    _run_case(tmp_path, 'test_machine_translation.py',
              funcname='decode_main',
              kwargs={'use_cuda': False, 'is_sparse': False},
              timeout=1500)


def test_reference_image_classification_vgg_runs_verbatim(tmp_path):
    """VGG on cifar from the reference book, verbatim — conv/bn/dropout
    tower, test-program clone + accuracy eval + inference round-trip."""
    _run_case(tmp_path, 'test_image_classification.py',
              kwargs={'use_cuda': False, 'net_type': 'vgg'},
              timeout=1200)


def test_reference_high_level_api_fit_a_line_runs_verbatim(tmp_path):
    """The reference's Trainer-based (high-level API) fit_a_line,
    verbatim: fluid.Trainer + EndStepEvent handler + trainer.stop() +
    params save/infer."""
    _run_case(tmp_path,
              'high-level-api/fit_a_line/test_fit_a_line.py',
              kwargs={'use_cuda': False}, timeout=1200)


def test_reference_image_classification_resnet_runs_verbatim(tmp_path):
    """The book's cifar ResNet (conv-residual basicblocks) variant of
    the same file, verbatim."""
    _run_case(tmp_path, 'test_image_classification.py',
              kwargs={'use_cuda': False, 'net_type': 'resnet'},
              timeout=1200)


def test_reference_hl_recognize_digits_conv_runs_verbatim(tmp_path):
    """Trainer-based LeNet (conv+pool tower) from the high-level-api
    book dir, verbatim — EndStepEvent accuracy gate + save + infer."""
    _run_case(tmp_path,
              'high-level-api/recognize_digits/test_recognize_digits_conv.py',
              kwargs={'use_cuda': False}, timeout=1200)


def test_reference_hl_sentiment_conv_runs_verbatim(tmp_path):
    """Trainer-based sentiment conv net (sequence_conv_pool x2 over an
    imdb lod feed), verbatim."""
    _run_case(
        tmp_path,
        'high-level-api/understand_sentiment/test_understand_sentiment_conv.py',
        kwargs={'use_cuda': False}, timeout=1200)


def test_reference_hl_sentiment_dynamic_rnn_runs_verbatim(tmp_path):
    """Trainer-based sentiment DynamicRNN (per-step rnn.step_input /
    memory update inside the dynamic rnn block), verbatim."""
    _run_case(
        tmp_path,
        'high-level-api/understand_sentiment/'
        'test_understand_sentiment_dynamic_rnn.py',
        kwargs={'use_cuda': False}, timeout=1200)


def test_reference_hl_sentiment_stacked_lstm_runs_verbatim(tmp_path):
    """Trainer-based sentiment stacked (3-layer) LSTM, verbatim."""
    _run_case(
        tmp_path,
        'high-level-api/understand_sentiment/'
        'test_understand_sentiment_stacked_lstm.py',
        kwargs={'use_cuda': False}, timeout=1200)


def test_reference_label_semantic_roles_runs_verbatim(tmp_path):
    """SRL with the 8-feature deep bidirectional LSTM mix + linear-chain
    CRF, verbatim: loads the pretrained embedding FILE via
    scope.find_var().get_tensor().set(), trains to the reference's
    cost<60 bar, saves + reloads the inference model."""
    _run_case(tmp_path, 'test_label_semantic_roles.py',
              kwargs={'use_cuda': False}, timeout=1200)


def test_reference_rnn_encoder_decoder_runs_verbatim(tmp_path):
    """The book's plain RNN encoder-decoder (DynamicRNN memories) —
    train + save/load inference model + infer, verbatim."""
    _run_case(tmp_path, 'test_rnn_encoder_decoder.py',
              kwargs={'use_cuda': False}, timeout=1200)


def test_reference_recommender_system_runs_verbatim(tmp_path):
    """The book's DSSM-style recommender (9 feeds incl. a sequence
    movie-title column, cos_sim head, test-program clone) — verbatim to
    the reference's own test-cost < 6.0 bar, then inference reload."""
    _run_case(tmp_path, 'test_recommender_system.py',
              kwargs={'use_cuda': False}, timeout=1200)
