"""Pipeline (pp) and expert (ep) parallelism utilities on the 8-device
virtual mesh: outputs must match the sequential / dense equivalents."""
import numpy as np

import jax
import jax.numpy as jnp

from paddle_tpu import parallel
from paddle_tpu.parallel.pipeline import pipeline_apply, stack_stage_params
from paddle_tpu.parallel.moe import moe_apply, stack_expert_params


def _mlp_stage(params, x):
    return jnp.tanh(x @ params['w'] + params['b'])


def test_pipeline_matches_sequential():
    mesh = parallel.make_mesh({'pp': 4})
    D, MB, NM = 6, 3, 5
    rng = np.random.RandomState(0)
    per_stage = [{'w': jnp.asarray(rng.randn(D, D).astype('float32') * 0.5),
                  'b': jnp.asarray(rng.randn(D).astype('float32') * 0.1)}
                 for _ in range(4)]
    stacked = stack_stage_params(per_stage)
    mbs = jnp.asarray(rng.randn(NM, MB, D).astype('float32'))

    got = pipeline_apply(_mlp_stage, stacked, mbs, mesh, axis='pp')
    want = mbs
    for p in per_stage:
        want = _mlp_stage(p, want)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_pipeline_single_microbatch():
    mesh = parallel.make_mesh({'pp': 8})
    D = 4
    rng = np.random.RandomState(1)
    per_stage = [{'w': jnp.asarray(rng.randn(D, D).astype('float32') * 0.3),
                  'b': jnp.zeros(D, jnp.float32)} for _ in range(8)]
    stacked = stack_stage_params(per_stage)
    mbs = jnp.asarray(rng.randn(1, 2, D).astype('float32'))
    got = pipeline_apply(_mlp_stage, stacked, mbs, mesh, axis='pp')
    want = mbs
    for p in per_stage:
        want = _mlp_stage(p, want)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_circular_schedule_matches_sequential():
    """n_virtual=2: 8 chunks on a pp=4 mesh (2 phases per device, each
    microbatch rides the ring twice) == sequential application, forward
    AND parameter gradients."""
    mesh = parallel.make_mesh({'pp': 4})
    D, MB, NM, V = 6, 3, 4, 2
    rng = np.random.RandomState(4)
    per_stage = [{'w': jnp.asarray(rng.randn(D, D).astype('float32') * 0.4),
                  'b': jnp.asarray(rng.randn(D).astype('float32') * 0.1)}
                 for _ in range(4 * V)]
    stacked = stack_stage_params(per_stage)
    mbs = jnp.asarray(rng.randn(NM, MB, D).astype('float32'))

    got = pipeline_apply(_mlp_stage, stacked, mbs, mesh, axis='pp',
                         n_virtual=V)
    want = mbs
    for p in per_stage:
        want = _mlp_stage(p, want)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)

    # gradients route through the circular schedule to the right chunks
    def loss_pipe(stk):
        return jnp.sum(pipeline_apply(_mlp_stage, stk, mbs, mesh,
                                      axis='pp', n_virtual=V) ** 2)

    def loss_seq(stk):
        x = mbs
        for s in range(4 * V):
            p = jax.tree_util.tree_map(lambda w: w[s], stk)
            x = _mlp_stage(p, x)
        return jnp.sum(x ** 2)

    g_pipe = jax.grad(loss_pipe)(stacked)
    g_seq = jax.grad(loss_seq)(stacked)
    for a, b in zip(jax.tree_util.tree_leaves(g_pipe),
                    jax.tree_util.tree_leaves(g_seq)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_circular_schedule_validation():
    import pytest
    mesh = parallel.make_mesh({'pp': 4})
    D = 4
    stages8 = [{'w': jnp.eye(D, dtype='float32')} for _ in range(8)]
    # n_micro=3 not a multiple of S=4 under the circular schedule
    with pytest.raises(ValueError, match='rounds of S'):
        pipeline_apply(_mlp_stage_w, stack_stage_params(stages8),
                       jnp.zeros((3, 2, D), jnp.float32), mesh, n_virtual=2)
    # 8 chunks with n_virtual=3 does not tile the pp=4 mesh
    with pytest.raises(ValueError, match='n_virtual'):
        pipeline_apply(_mlp_stage_w, stack_stage_params(stages8),
                       jnp.zeros((4, 2, D), jnp.float32), mesh, n_virtual=3)


def _mlp_stage_w(params, x):
    return x @ params['w']


def test_unit_count_must_match_axis():
    import pytest
    mesh = parallel.make_mesh({'pp': 4})
    D = 4
    # 8 stages on a pp=4 mesh would silently drop every other stage
    stages = [{'w': jnp.eye(D, dtype='float32')} for _ in range(8)]
    mbs = jnp.zeros((2, 2, D), jnp.float32)
    with pytest.raises(ValueError, match='must equal mesh axis'):
        pipeline_apply(_mlp_stage, stack_stage_params(stages), mbs, mesh)
    ep_mesh = parallel.make_mesh({'ep': 8})
    # 12 experts on an ep=8 mesh: not a multiple -> ragged shard rejected
    experts = [{'w': jnp.eye(D, dtype='float32')} for _ in range(12)]
    toks = jnp.zeros((16, D), jnp.float32)
    with pytest.raises(ValueError, match='must equal mesh axis'):
        moe_apply(_expert, stack_expert_params(experts), toks,
                  jnp.zeros((16, 12), jnp.float32), ep_mesh)
    # right expert count but wrong gate width
    experts8 = [{'w': jnp.eye(D, dtype='float32')} for _ in range(8)]
    with pytest.raises(ValueError, match='gate_logits'):
        moe_apply(_expert, stack_expert_params(experts8), toks,
                  jnp.zeros((16, 16), jnp.float32), ep_mesh)


def _expert(params, x):
    return x @ params['w']


def test_moe_matches_dense_with_headroom():
    mesh = parallel.make_mesh({'ep': 8})
    E, D, NT = 8, 4, 64          # NT tokens total, sharded 8 per device
    rng = np.random.RandomState(2)
    per_expert = [{'w': jnp.asarray(rng.randn(D, D).astype('float32') * 0.5)}
                  for _ in range(E)]
    stacked = stack_expert_params(per_expert)
    x = jnp.asarray(rng.randn(NT, D).astype('float32'))
    logits = jnp.asarray(rng.randn(NT, E).astype('float32'))

    # capacity 8 per expert per shard >= shard size: nothing dropped
    got = moe_apply(_expert, stacked, x, logits, mesh, axis='ep',
                    capacity_factor=8.0)

    expert = np.argmax(np.asarray(logits), axis=-1)
    gate = np.asarray(jax.nn.softmax(logits, axis=-1))[
        np.arange(NT), expert]
    want = np.stack([
        np.asarray(_expert(per_expert[e], x[i:i + 1]))[0] * gate[i]
        for i, e in enumerate(expert)])
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-4, atol=2e-5)


def test_moe_top2_matches_manual():
    """top_k=2 with capacity headroom == gate-renormalized two-expert sum
    computed by hand (GShard semantics)."""
    from paddle_tpu.parallel.moe import moe_apply
    mesh = parallel.make_mesh({'ep': 8})
    E, D, NT = 8, 4, 64
    rng = np.random.RandomState(7)
    per_expert = [{'w': jnp.asarray(rng.randn(D, D).astype('float32') * 0.5)}
                  for _ in range(E)]
    stacked = stack_expert_params(per_expert)
    x = jnp.asarray(rng.randn(NT, D).astype('float32'))
    logits = jnp.asarray(rng.randn(NT, E).astype('float32'))

    got = moe_apply(_expert, stacked, x, logits, mesh, axis='ep',
                    capacity_factor=8.0, top_k=2)

    probs = np.asarray(jax.nn.softmax(logits, axis=-1))
    order = np.argsort(-np.asarray(logits), axis=-1)[:, :2]   # [NT, 2]
    want = np.zeros((NT, D), np.float32)
    for i in range(NT):
        e1, e2 = order[i]
        g1, g2 = probs[i, e1], probs[i, e2]
        s = g1 + g2
        want[i] = (np.asarray(_expert(per_expert[e1], x[i:i + 1]))[0] * g1 / s
                   + np.asarray(_expert(per_expert[e2], x[i:i + 1]))[0] * g2 / s)
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-4, atol=2e-5)


def test_moe_experts_per_device():
    """16 experts on an ep=8 mesh (2 per device): the block-sharded
    all_to_all path == a dense vmap over all 16 experts."""
    from paddle_tpu.parallel.moe import moe_apply, pack_topk, combine_topk
    mesh = parallel.make_mesh({'ep': 8})
    E, D, DO, NT = 16, 4, 6, 64
    rng = np.random.RandomState(11)
    # d_out != d_in also exercises the output-width-agnostic return path
    per_expert = [{'w': jnp.asarray(rng.randn(D, DO).astype('float32') * 0.5)}
                  for _ in range(E)]
    stacked = stack_expert_params(per_expert)
    x = jnp.asarray(rng.randn(NT, D).astype('float32'))
    logits = jnp.asarray(rng.randn(NT, E).astype('float32'))

    got = moe_apply(_expert, stacked, x, logits, mesh, axis='ep',
                    capacity_factor=16.0, top_k=2)

    cap = int(16.0 * 2 * NT / E)
    send, route = pack_topk(x, logits, E, cap, 2)
    out = jax.vmap(_expert)(stacked, send)
    want = combine_topk(out, route, x.dtype)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


def test_load_balancing_loss():
    """Balanced router -> ~1.0; collapsed router -> ~E; uniform-probability
    router == exactly 1 regardless of assignments; differentiable."""
    from paddle_tpu.parallel.moe import load_balancing_loss
    E, NT = 8, 256
    rng = np.random.RandomState(13)
    # perfectly balanced: token i strongly prefers expert i % E
    bal = np.full((NT, E), -8.0, np.float32)
    bal[np.arange(NT), np.arange(NT) % E] = 8.0
    # collapsed: every token strongly prefers expert 0
    col = np.full((NT, E), -8.0, np.float32)
    col[:, 0] = 8.0
    l_bal = float(load_balancing_loss(jnp.asarray(bal)))
    l_col = float(load_balancing_loss(jnp.asarray(col)))
    assert abs(l_bal - 1.0) < 1e-2, l_bal
    assert l_col > 0.9 * E, (l_col, E)
    # exactly-uniform probabilities: E * sum_e f_e * (1/E) = 1 for any f
    uni = jnp.zeros((NT, E), jnp.float32)
    np.testing.assert_allclose(float(load_balancing_loss(uni)), 1.0,
                               rtol=1e-6)
    # top-2 accounting: balanced assignments still ~1
    l2 = float(load_balancing_loss(jnp.asarray(bal), top_k=2))
    assert np.isfinite(l2) and l2 < E
    # gradient flows (through P_e; f_e is argmax-blocked)
    g = jax.grad(lambda z: load_balancing_loss(z))(jnp.asarray(col))
    assert float(jnp.abs(g).sum()) > 0.0


def test_moe_capacity_drops_overflow():
    mesh = parallel.make_mesh({'ep': 8})
    E, D, NT = 8, 4, 64
    rng = np.random.RandomState(3)
    per_expert = [{'w': jnp.asarray(np.eye(D, dtype='float32'))}
                  for _ in range(E)]
    stacked = stack_expert_params(per_expert)
    x = jnp.asarray(rng.rand(NT, D).astype('float32') + 1.0)
    # every token picks expert 0 -> per-shard capacity binds
    logits = jnp.asarray(np.tile([10.] + [0.] * (E - 1), (NT, 1))
                         .astype('float32'))
    got = np.asarray(moe_apply(_expert, stacked, x, logits, mesh,
                               axis='ep', capacity_factor=1.0))
    # capacity = 1 token per expert per shard: exactly 1 token per shard
    # survives (8 total), the rest are zeroed
    kept = (np.abs(got).sum(-1) > 1e-6)
    assert kept.sum() == 8
    # survivors are gate-weighted identity of their inputs
    gate0 = float(np.asarray(jax.nn.softmax(logits[0]))[0])
    np.testing.assert_allclose(got[kept], np.asarray(x)[kept] * gate0,
                               rtol=1e-5)


class TestMoeMlpLayer:
    """fluid.layers.moe_mlp: the Fluid-level MoE surface (nn.py:moe_mlp,
    lowered by ops_impl/moe_ops.py)."""

    def _build(self, capacity_factor=8.0):
        import paddle_tpu.fluid as fluid
        from paddle_tpu.fluid import framework, unique_name
        from paddle_tpu.fluid.executor import Scope, _switch_scope
        _switch_scope(Scope())
        main, startup = framework.Program(), framework.Program()
        with unique_name.guard(), framework.program_guard(main, startup):
            x = fluid.layers.data(name='x', shape=[16], dtype='float32')
            y = fluid.layers.data(name='y', shape=[1], dtype='float32')
            h = fluid.layers.moe_mlp(x, num_experts=4, hidden_size=32,
                                     act='relu',
                                     capacity_factor=capacity_factor)
            pred = fluid.layers.fc(input=h, size=1)
            cost = fluid.layers.mean(
                fluid.layers.square_error_cost(input=pred, label=y))
        return main, startup, cost

    def test_trains_dense(self):
        import paddle_tpu.fluid as fluid
        from paddle_tpu.fluid import framework, unique_name
        from paddle_tpu.fluid.executor import Scope, _switch_scope
        _switch_scope(Scope())
        main, startup = framework.Program(), framework.Program()
        rng = np.random.RandomState(0)
        X = rng.randn(64, 16).astype('float32')
        Y = X @ rng.randn(16, 1).astype('float32')
        with unique_name.guard(), framework.program_guard(main, startup):
            x = fluid.layers.data(name='x', shape=[16], dtype='float32')
            y = fluid.layers.data(name='y', shape=[1], dtype='float32')
            h = fluid.layers.moe_mlp(x, num_experts=4, hidden_size=32)
            pred = fluid.layers.fc(input=h, size=1)
            cost = fluid.layers.mean(
                fluid.layers.square_error_cost(input=pred, label=y))
            fluid.optimizer.Adam(learning_rate=3e-3).minimize(cost)
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            first = last = None
            for _ in range(100):
                loss, = exe.run(main, feed={'x': X, 'y': Y},
                                fetch_list=[cost])
                first = first if first is not None else float(loss)
                last = float(loss)
        assert last < first * 0.2, (first, last)

    def test_mesh_path_matches_dense(self):
        """ParallelExecutor dp=4 == num_experts routes through moe_apply
        (all_to_all expert parallelism) and must match the single-device
        forward when capacity has headroom."""
        import paddle_tpu.fluid as fluid
        from paddle_tpu.fluid import ops_impl
        from paddle_tpu.fluid.executor import Scope, _switch_scope
        rng = np.random.RandomState(1)
        X = rng.randn(64, 16).astype('float32')
        Y = X @ rng.randn(16, 1).astype('float32')
        main, startup, cost = self._build()
        exe = fluid.Executor(fluid.CPUPlace())
        from paddle_tpu.fluid import framework
        import paddle_tpu.parallel.moe as moe_mod
        from paddle_tpu.fluid.ops_impl import moe_ops
        calls = {'mesh': 0}
        real = moe_mod.moe_apply

        def spy(*a, **kw):
            calls['mesh'] += 1
            return real(*a, **kw)

        with framework.program_guard(main, startup):
            exe.run(startup)
            single, = exe.run(main, feed={'x': X, 'y': Y},
                              fetch_list=[cost])
            assert calls['mesh'] == 0
            pe = fluid.ParallelExecutor(use_cuda=False, main_program=main,
                                        loss_name=cost.name, num_devices=4)
            moe_mod.moe_apply = spy
            try:
                par, = pe.run(fetch_list=[cost.name], feed={'x': X, 'y': Y})
            finally:
                moe_mod.moe_apply = real
        # the sharded all_to_all path must actually have been traced
        assert calls['mesh'] >= 1
        np.testing.assert_allclose(float(single),
                                   float(np.asarray(par).mean()), rtol=2e-4)
        # and the program is NOT left mesh-bound after the PE run: a later
        # plain Executor.run must not see a forced dp mesh (the scope's
        # mesh-REPLICATED params are a separate, documented GSPMD property)
        assert getattr(main, '_dist_mesh', None) is None

    def test_top2_aux_loss_in_loss_graph(self):
        """top_k=2 with the load-balancing aux loss ADDED TO THE PROGRAM'S
        OBJECTIVE: the combined loss trains, the aux term starts near its
        uniform-router value (~1.0) and stays bounded, and the gate weights
        receive gradient through the aux path."""
        import paddle_tpu.fluid as fluid
        from paddle_tpu.fluid import framework, unique_name
        from paddle_tpu.fluid.executor import Scope, _switch_scope
        _switch_scope(Scope())
        main, startup = framework.Program(), framework.Program()
        rng = np.random.RandomState(5)
        X = rng.randn(64, 16).astype('float32')
        Y = X @ rng.randn(16, 1).astype('float32')
        with unique_name.guard(), framework.program_guard(main, startup):
            x = fluid.layers.data(name='x', shape=[16], dtype='float32')
            y = fluid.layers.data(name='y', shape=[1], dtype='float32')
            h, aux = fluid.layers.moe_mlp(x, num_experts=4, hidden_size=32,
                                          top_k=2, return_aux_loss=True)
            pred = fluid.layers.fc(input=h, size=1)
            task = fluid.layers.mean(
                fluid.layers.square_error_cost(input=pred, label=y))
            cost = task + 0.01 * aux
            fluid.optimizer.Adam(learning_rate=3e-3).minimize(cost)
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            first = last = aux0 = None
            for _ in range(100):
                loss, a = exe.run(main, feed={'x': X, 'y': Y},
                                  fetch_list=[task, aux])
                first = first if first is not None else float(loss)
                aux0 = aux0 if aux0 is not None else float(a)
                last = float(loss)
        assert last < first * 0.2, (first, last)
        # aux is the Switch objective: 1.0 uniform .. E collapsed
        assert 0.9 <= aux0 <= 4.0, aux0
        assert 0.9 <= float(a) <= 4.0, float(a)

    def test_mesh_path_experts_per_device(self):
        """num_experts=8 on a dp=4 ParallelExecutor mesh (2 experts per
        device) routes through the block-sharded all_to_all path and
        matches the single-device forward."""
        import paddle_tpu.fluid as fluid
        from paddle_tpu.fluid import framework, unique_name
        from paddle_tpu.fluid.executor import Scope, _switch_scope
        import paddle_tpu.parallel.moe as moe_mod
        _switch_scope(Scope())
        main, startup = framework.Program(), framework.Program()
        rng = np.random.RandomState(9)
        X = rng.randn(64, 16).astype('float32')
        Y = X @ rng.randn(16, 1).astype('float32')
        with unique_name.guard(), framework.program_guard(main, startup):
            x = fluid.layers.data(name='x', shape=[16], dtype='float32')
            y = fluid.layers.data(name='y', shape=[1], dtype='float32')
            h = fluid.layers.moe_mlp(x, num_experts=8, hidden_size=8,
                                     top_k=2, capacity_factor=8.0)
            pred = fluid.layers.fc(input=h, size=1)
            cost = fluid.layers.mean(
                fluid.layers.square_error_cost(input=pred, label=y))
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            single, = exe.run(main, feed={'x': X, 'y': Y},
                              fetch_list=[cost])
            calls = {'mesh': 0}
            real = moe_mod.moe_apply

            def spy(*a, **kw):
                calls['mesh'] += 1
                return real(*a, **kw)

            pe = fluid.ParallelExecutor(use_cuda=False, main_program=main,
                                        loss_name=cost.name, num_devices=4)
            moe_mod.moe_apply = spy
            try:
                par, = pe.run(fetch_list=[cost.name], feed={'x': X, 'y': Y})
            finally:
                moe_mod.moe_apply = real
        assert calls['mesh'] >= 1
        np.testing.assert_allclose(float(single),
                                   float(np.asarray(par).mean()), rtol=2e-4)

    def test_bad_act_rejected_at_layer_time(self):
        import pytest
        import paddle_tpu.fluid as fluid
        from paddle_tpu.fluid import framework, unique_name
        from paddle_tpu.fluid.executor import Scope, _switch_scope
        _switch_scope(Scope())
        main, startup = framework.Program(), framework.Program()
        with unique_name.guard(), framework.program_guard(main, startup):
            x = fluid.layers.data(name='x', shape=[8], dtype='float32')
            with pytest.raises(ValueError, match='leaky_relu'):
                fluid.layers.moe_mlp(x, num_experts=2, hidden_size=4,
                                     act='leaky_relu')

    def test_3d_input(self):
        import paddle_tpu.fluid as fluid
        from paddle_tpu.fluid import framework, unique_name
        from paddle_tpu.fluid.executor import Scope, _switch_scope
        _switch_scope(Scope())
        main, startup = framework.Program(), framework.Program()
        with unique_name.guard(), framework.program_guard(main, startup):
            x = fluid.layers.data(name='x', shape=[6, 16], dtype='float32')
            h = fluid.layers.moe_mlp(x, num_experts=2, hidden_size=8,
                                     size=4)
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            out, = exe.run(main,
                           feed={'x': np.random.randn(3, 6, 16)
                                 .astype('float32')},
                           fetch_list=[h.name])
        assert np.asarray(out).shape == (3, 6, 4)
