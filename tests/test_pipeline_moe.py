"""Pipeline (pp) and expert (ep) parallelism utilities on the 8-device
virtual mesh: outputs must match the sequential / dense equivalents."""
import numpy as np

import jax
import jax.numpy as jnp

from paddle_tpu import parallel
from paddle_tpu.parallel.pipeline import pipeline_apply, stack_stage_params
from paddle_tpu.parallel.moe import moe_apply, stack_expert_params


def _mlp_stage(params, x):
    return jnp.tanh(x @ params['w'] + params['b'])


def test_pipeline_matches_sequential():
    mesh = parallel.make_mesh({'pp': 4})
    D, MB, NM = 6, 3, 5
    rng = np.random.RandomState(0)
    per_stage = [{'w': jnp.asarray(rng.randn(D, D).astype('float32') * 0.5),
                  'b': jnp.asarray(rng.randn(D).astype('float32') * 0.1)}
                 for _ in range(4)]
    stacked = stack_stage_params(per_stage)
    mbs = jnp.asarray(rng.randn(NM, MB, D).astype('float32'))

    got = pipeline_apply(_mlp_stage, stacked, mbs, mesh, axis='pp')
    want = mbs
    for p in per_stage:
        want = _mlp_stage(p, want)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_pipeline_single_microbatch():
    mesh = parallel.make_mesh({'pp': 8})
    D = 4
    rng = np.random.RandomState(1)
    per_stage = [{'w': jnp.asarray(rng.randn(D, D).astype('float32') * 0.3),
                  'b': jnp.zeros(D, jnp.float32)} for _ in range(8)]
    stacked = stack_stage_params(per_stage)
    mbs = jnp.asarray(rng.randn(1, 2, D).astype('float32'))
    got = pipeline_apply(_mlp_stage, stacked, mbs, mesh, axis='pp')
    want = mbs
    for p in per_stage:
        want = _mlp_stage(p, want)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_unit_count_must_match_axis():
    import pytest
    mesh = parallel.make_mesh({'pp': 4})
    D = 4
    # 8 stages on a pp=4 mesh would silently drop every other stage
    stages = [{'w': jnp.eye(D, dtype='float32')} for _ in range(8)]
    mbs = jnp.zeros((2, 2, D), jnp.float32)
    with pytest.raises(ValueError, match='must equal mesh axis'):
        pipeline_apply(_mlp_stage, stack_stage_params(stages), mbs, mesh)
    ep_mesh = parallel.make_mesh({'ep': 8})
    experts = [{'w': jnp.eye(D, dtype='float32')} for _ in range(16)]
    toks = jnp.zeros((16, D), jnp.float32)
    with pytest.raises(ValueError, match='must equal mesh axis'):
        moe_apply(_expert, stack_expert_params(experts), toks,
                  jnp.zeros((16, 16), jnp.float32), ep_mesh)
    # right expert count but wrong gate width
    experts8 = [{'w': jnp.eye(D, dtype='float32')} for _ in range(8)]
    with pytest.raises(ValueError, match='gate_logits'):
        moe_apply(_expert, stack_expert_params(experts8), toks,
                  jnp.zeros((16, 16), jnp.float32), ep_mesh)


def _expert(params, x):
    return x @ params['w']


def test_moe_matches_dense_with_headroom():
    mesh = parallel.make_mesh({'ep': 8})
    E, D, NT = 8, 4, 64          # NT tokens total, sharded 8 per device
    rng = np.random.RandomState(2)
    per_expert = [{'w': jnp.asarray(rng.randn(D, D).astype('float32') * 0.5)}
                  for _ in range(E)]
    stacked = stack_expert_params(per_expert)
    x = jnp.asarray(rng.randn(NT, D).astype('float32'))
    logits = jnp.asarray(rng.randn(NT, E).astype('float32'))

    # capacity 8 per expert per shard >= shard size: nothing dropped
    got = moe_apply(_expert, stacked, x, logits, mesh, axis='ep',
                    capacity_factor=8.0)

    expert = np.argmax(np.asarray(logits), axis=-1)
    gate = np.asarray(jax.nn.softmax(logits, axis=-1))[
        np.arange(NT), expert]
    want = np.stack([
        np.asarray(_expert(per_expert[e], x[i:i + 1]))[0] * gate[i]
        for i, e in enumerate(expert)])
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-4, atol=2e-5)


def test_moe_capacity_drops_overflow():
    mesh = parallel.make_mesh({'ep': 8})
    E, D, NT = 8, 4, 64
    rng = np.random.RandomState(3)
    per_expert = [{'w': jnp.asarray(np.eye(D, dtype='float32'))}
                  for _ in range(E)]
    stacked = stack_expert_params(per_expert)
    x = jnp.asarray(rng.rand(NT, D).astype('float32') + 1.0)
    # every token picks expert 0 -> per-shard capacity binds
    logits = jnp.asarray(np.tile([10.] + [0.] * (E - 1), (NT, 1))
                         .astype('float32'))
    got = np.asarray(moe_apply(_expert, stacked, x, logits, mesh,
                               axis='ep', capacity_factor=1.0))
    # capacity = 1 token per expert per shard: exactly 1 token per shard
    # survives (8 total), the rest are zeroed
    kept = (np.abs(got).sum(-1) > 1e-6)
    assert kept.sum() == 8
    # survivors are gate-weighted identity of their inputs
    gate0 = float(np.asarray(jax.nn.softmax(logits[0]))[0])
    np.testing.assert_allclose(got[kept], np.asarray(x)[kept] * gate0,
                               rtol=1e-5)


class TestMoeMlpLayer:
    """fluid.layers.moe_mlp: the Fluid-level MoE surface (nn.py:moe_mlp,
    lowered by ops_impl/moe_ops.py)."""

    def _build(self, capacity_factor=8.0):
        import paddle_tpu.fluid as fluid
        from paddle_tpu.fluid import framework, unique_name
        from paddle_tpu.fluid.executor import Scope, _switch_scope
        _switch_scope(Scope())
        main, startup = framework.Program(), framework.Program()
        with unique_name.guard(), framework.program_guard(main, startup):
            x = fluid.layers.data(name='x', shape=[16], dtype='float32')
            y = fluid.layers.data(name='y', shape=[1], dtype='float32')
            h = fluid.layers.moe_mlp(x, num_experts=4, hidden_size=32,
                                     act='relu',
                                     capacity_factor=capacity_factor)
            pred = fluid.layers.fc(input=h, size=1)
            cost = fluid.layers.mean(
                fluid.layers.square_error_cost(input=pred, label=y))
        return main, startup, cost

    def test_trains_dense(self):
        import paddle_tpu.fluid as fluid
        from paddle_tpu.fluid import framework, unique_name
        from paddle_tpu.fluid.executor import Scope, _switch_scope
        _switch_scope(Scope())
        main, startup = framework.Program(), framework.Program()
        rng = np.random.RandomState(0)
        X = rng.randn(64, 16).astype('float32')
        Y = X @ rng.randn(16, 1).astype('float32')
        with unique_name.guard(), framework.program_guard(main, startup):
            x = fluid.layers.data(name='x', shape=[16], dtype='float32')
            y = fluid.layers.data(name='y', shape=[1], dtype='float32')
            h = fluid.layers.moe_mlp(x, num_experts=4, hidden_size=32)
            pred = fluid.layers.fc(input=h, size=1)
            cost = fluid.layers.mean(
                fluid.layers.square_error_cost(input=pred, label=y))
            fluid.optimizer.Adam(learning_rate=3e-3).minimize(cost)
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            first = last = None
            for _ in range(100):
                loss, = exe.run(main, feed={'x': X, 'y': Y},
                                fetch_list=[cost])
                first = first if first is not None else float(loss)
                last = float(loss)
        assert last < first * 0.2, (first, last)

    def test_mesh_path_matches_dense(self):
        """ParallelExecutor dp=4 == num_experts routes through moe_apply
        (all_to_all expert parallelism) and must match the single-device
        forward when capacity has headroom."""
        import paddle_tpu.fluid as fluid
        from paddle_tpu.fluid import ops_impl
        from paddle_tpu.fluid.executor import Scope, _switch_scope
        rng = np.random.RandomState(1)
        X = rng.randn(64, 16).astype('float32')
        Y = X @ rng.randn(16, 1).astype('float32')
        main, startup, cost = self._build()
        exe = fluid.Executor(fluid.CPUPlace())
        from paddle_tpu.fluid import framework
        import paddle_tpu.parallel.moe as moe_mod
        from paddle_tpu.fluid.ops_impl import moe_ops
        calls = {'mesh': 0}
        real = moe_mod.moe_apply

        def spy(*a, **kw):
            calls['mesh'] += 1
            return real(*a, **kw)

        with framework.program_guard(main, startup):
            exe.run(startup)
            single, = exe.run(main, feed={'x': X, 'y': Y},
                              fetch_list=[cost])
            assert calls['mesh'] == 0
            pe = fluid.ParallelExecutor(use_cuda=False, main_program=main,
                                        loss_name=cost.name, num_devices=4)
            moe_mod.moe_apply = spy
            try:
                par, = pe.run(fetch_list=[cost.name], feed={'x': X, 'y': Y})
            finally:
                moe_mod.moe_apply = real
        # the sharded all_to_all path must actually have been traced
        assert calls['mesh'] >= 1
        np.testing.assert_allclose(float(single),
                                   float(np.asarray(par).mean()), rtol=2e-4)
        # and the program is NOT left mesh-bound after the PE run: a later
        # plain Executor.run must not see a forced dp mesh (the scope's
        # mesh-REPLICATED params are a separate, documented GSPMD property)
        assert getattr(main, '_dist_mesh', None) is None

    def test_bad_act_rejected_at_layer_time(self):
        import pytest
        import paddle_tpu.fluid as fluid
        from paddle_tpu.fluid import framework, unique_name
        from paddle_tpu.fluid.executor import Scope, _switch_scope
        _switch_scope(Scope())
        main, startup = framework.Program(), framework.Program()
        with unique_name.guard(), framework.program_guard(main, startup):
            x = fluid.layers.data(name='x', shape=[8], dtype='float32')
            with pytest.raises(ValueError, match='leaky_relu'):
                fluid.layers.moe_mlp(x, num_experts=2, hidden_size=4,
                                     act='leaky_relu')

    def test_3d_input(self):
        import paddle_tpu.fluid as fluid
        from paddle_tpu.fluid import framework, unique_name
        from paddle_tpu.fluid.executor import Scope, _switch_scope
        _switch_scope(Scope())
        main, startup = framework.Program(), framework.Program()
        with unique_name.guard(), framework.program_guard(main, startup):
            x = fluid.layers.data(name='x', shape=[6, 16], dtype='float32')
            h = fluid.layers.moe_mlp(x, num_experts=2, hidden_size=8,
                                     size=4)
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            out, = exe.run(main,
                           feed={'x': np.random.randn(3, 6, 16)
                                 .astype('float32')},
                           fetch_list=[h.name])
        assert np.asarray(out).shape == (3, 6, 4)
