"""Benchmark driver CLI (benchmark/fluid_benchmark.py — parity with
reference benchmark/fluid/fluid_benchmark.py + args.py)."""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..',
                                'benchmark'))

from fluid_benchmark import BENCHMARK_MODELS, parse_args, run_benchmark


def test_arg_surface_matches_reference():
    a = parse_args(['--model', 'mnist', '--gpus', '2', '--batch_size', '16',
                    '--update_method', 'pserver', '--no_random'])
    assert a.model == 'mnist' and a.chips == 2 and a.batch_size == 16
    assert a.update_method == 'pserver' and a.no_random
    # the reference set, plus the TPU-extension transformer model
    assert set(BENCHMARK_MODELS) == {
        'machine_translation', 'resnet', 'vgg', 'mnist',
        'stacked_dynamic_lstm', 'transformer'}


def test_mnist_local_runs_and_learns():
    a = parse_args(['--model', 'mnist', '--iterations', '8',
                    '--skip_batch_num', '1', '--batch_size', '32',
                    '--device', 'CPU', '--no_test', '--no_random'])
    loss = run_benchmark(a)
    assert np.isfinite(loss)


def test_mnist_parallel_chips():
    a = parse_args(['--model', 'mnist', '--iterations', '2',
                    '--skip_batch_num', '1', '--batch_size', '32',
                    '--device', 'CPU', '--no_test', '--chips', '2',
                    '--use_fake_data'])
    assert np.isfinite(run_benchmark(a))


def test_mnist_pserver_transpiled():
    a = parse_args(['--model', 'mnist', '--iterations', '2',
                    '--skip_batch_num', '1', '--batch_size', '32',
                    '--device', 'CPU', '--no_test', '--chips', '2',
                    '--update_method', 'pserver', '--use_fake_data'])
    assert np.isfinite(run_benchmark(a))


def test_recordio_converter_round_trip(tmp_path):
    import recordio_converter as rc
    from paddle_tpu.reader.recordio import RecordIOReader
    from paddle_tpu.fluid.recordio_writer import unpack_feed_record
    n = rc.prepare_mnist(str(tmp_path), 32)
    assert n > 0
    rec = next(iter(RecordIOReader(str(tmp_path / 'mnist.recordio'))))
    img, lbl = unpack_feed_record(rec)
    assert np.asarray(img.data).shape == (32, 784)
    assert np.asarray(lbl.data).shape == (32, 1)


def test_infer_only_without_infer_prog_rejected():
    import pytest
    a = parse_args(['--model', 'resnet', '--iterations', '1', '--device',
                    'CPU', '--infer_only', '--use_fake_data', '--no_test',
                    '--batch_size', '4'])
    with pytest.raises(ValueError, match='infer_only'):
        run_benchmark(a)


def test_converter_leaves_default_program_untouched(tmp_path):
    import paddle_tpu.fluid as fluid
    import recordio_converter as rc
    before = fluid.default_main_program()
    rc.prepare_mnist(str(tmp_path), 8)
    assert fluid.default_main_program() is before


def test_mnist_tensor_parallel_flag():
    a = parse_args(['--model', 'mnist', '--iterations', '2',
                    '--skip_batch_num', '1', '--batch_size', '32',
                    '--device', 'CPU', '--no_test', '--tp', '2',
                    '--use_fake_data'])
    assert np.isfinite(run_benchmark(a))


def test_transformer_model_with_sequence_parallel():
    a = parse_args(['--model', 'transformer', '--iterations', '1',
                    '--skip_batch_num', '0', '--batch_size', '4',
                    '--device', 'CPU', '--no_test', '--sp', '2',
                    '--use_fake_data'])
    assert np.isfinite(run_benchmark(a))


def test_tp_with_local_chips_rejected():
    import pytest
    a = parse_args(['--model', 'mnist', '--iterations', '1',
                    '--skip_batch_num', '0', '--batch_size', '32',
                    '--device', 'CPU', '--no_test', '--chips', '2',
                    '--tp', '2', '--use_fake_data'])
    with pytest.raises(ValueError, match='pserver'):
        run_benchmark(a)
